#!/bin/sh
# Tunnel liveness poller (VERDICT r3 item 1): append a probe record to
# TUNNEL_LOG.jsonl every ~20 min so "the tunnel was down all round" is a
# record, not an assumption. Run in the background for the whole session.
cd /root/repo || exit 1
while true; do
  python - <<'EOF'
import json, time
from daccord_tpu.utils.obs import probe_default_backend
t0 = time.time()
n = probe_default_backend(120)
rec = {"ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
       "devices": n, "alive": n > 0, "probe_s": round(time.time() - t0, 1),
       "round": 5}
with open("TUNNEL_LOG.jsonl", "a") as f:
    f.write(json.dumps(rec) + "\n")
print(rec)
EOF
  sleep 1080
done
