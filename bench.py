#!/usr/bin/env python
"""Benchmark: consensus bases/sec/chip of the batched window solver.

Prints ONE JSON line:
  {"metric": "consensus_bases_per_sec_per_chip", "value": N, "unit": "bases/s",
   "vs_baseline": R, ...}

The metric is BASELINE.json's "consensus bases/sec/chip". The reference
publishes no number (BASELINE.md: ``published: {}``) and the reference binary
is unavailable to measure, so ``vs_baseline`` is the ratio against the
framework's own single-core numpy oracle (the executable spec of the same
algorithm) measured in the same run — an honest, reproducible stand-in until
the C++ reference can be built (SURVEY.md §7.3 item 6).

The window set is a synthetic PacBio-like dataset (sim module); the tensorized
batches are cached under .bench_cache/ so reruns skip the host prep.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".bench_cache")
N_BENCH_WINDOWS = 32768
# 2048 measured ~2x the 1024-batch throughput on the tunneled v5e (batch-size
# sweep 2026-07-30: 1024 -> 330-459k bases/s, 2048 -> 652k): per-dispatch
# overhead dominates single-digit-ms compute, so bigger batches amortize it.
# DACCORD_BENCH_BATCH overrides for sweeps (must divide N_BENCH_WINDOWS).
BATCH = int(os.environ.get("DACCORD_BENCH_BATCH", "2048"))
if not (0 < BATCH <= N_BENCH_WINDOWS and N_BENCH_WINDOWS % BATCH == 0):
    raise SystemExit(   # not assert: stripped under python -O, and a
        # non-dividing batch silently drops the trailing partial batch
        f"DACCORD_BENCH_BATCH={BATCH} must divide N_BENCH_WINDOWS={N_BENCH_WINDOWS}")
# queued-hardware-experiment levers (ARCHITECTURE.md 2/3): override the
# escalation capacity (default: full batch) and the candidate count so the
# esc_cap=B/8 and --candidates 5 measurements are one env var each.
# Unset = the shipped config defaults (never pinned here, so a future
# default flip is what a plain run benches).
ESC_CAP = os.environ.get("DACCORD_BENCH_ESC_CAP")
ESC_CAP = int(ESC_CAP) if ESC_CAP else None
if ESC_CAP is not None and ESC_CAP <= 0:
    raise SystemExit(f"DACCORD_BENCH_ESC_CAP={ESC_CAP} must be positive "
                     "(0 would silently drop every escalated window)")
N_CANDIDATES = os.environ.get("DACCORD_BENCH_CANDIDATES")
N_CANDIDATES = int(N_CANDIDATES) if N_CANDIDATES else None
# queued experiment 7 (hp drain overlap): 1 = run the C++ hp rescue pass on
# every fetched batch inside the pipelined drain, exactly where the
# production pipeline runs it; the delta vs a plain run measures how much of
# the host-side hp cost hides behind dispatch/RTT overlap on real hardware
BENCH_HP = os.environ.get("DACCORD_BENCH_HP") == "1"
# warm-the-cache mode (ADVICE r5 #2): compile the ladder at BATCH into the
# persistent XLA cache, record the shape fingerprint, and exit — run this for
# B=2048/4096 BEFORE the batch sweep so no timed bench sits behind a silent
# multi-minute server-side compile
BENCH_PRECOMPILE = os.environ.get("DACCORD_BENCH_PRECOMPILE") == "1"
# self-staging batch ladder (VERDICT r5 next-round #1 — the fifth consecutive
# ask for an on-chip number): DACCORD_BENCH_LADDER=1 runs rungs
# B=64 -> 256 -> 1024 -> 2048 and commits one sidecar + one stdout line the
# MOMENT each rung completes (B=256 cold-compiles in ~35 s, so minute two of
# any live chip window already holds a fallback:false number), while the
# B=2048 compile warms in a background subprocess through the persistent
# cache. A comma list ("64,256") overrides the rungs; every rung must divide
# N_BENCH_WINDOWS. DACCORD_BENCH_LADDER_MAX_BATCHES caps batches per rung
# (local verification / CPU smoke).
_ladder_env = os.environ.get("DACCORD_BENCH_LADDER", "")
if _ladder_env and _ladder_env != "1":
    BENCH_LADDER: tuple | None = tuple(int(x) for x in _ladder_env.split(","))
elif _ladder_env == "1":
    BENCH_LADDER = (64, 256, 1024, 2048)
else:
    BENCH_LADDER = None
if BENCH_LADDER is not None:
    for _b in BENCH_LADDER:
        if not (0 < _b <= N_BENCH_WINDOWS and N_BENCH_WINDOWS % _b == 0):
            raise SystemExit(f"DACCORD_BENCH_LADDER rung {_b} must divide "
                             f"N_BENCH_WINDOWS={N_BENCH_WINDOWS}")
_lmb = os.environ.get("DACCORD_BENCH_LADDER_MAX_BATCHES")
LADDER_MAX_BATCHES = int(_lmb) if _lmb else None
# serving-plane bench (ISSUE 10): DACCORD_BENCH_SERVE=1 replays a recorded
# job-arrival trace against a real daccord-serve HTTP server and commits a
# sidecar with per-job p50/p99 latency + windows/sec — the latency axis
# landing next to the rung ladder's throughput axis on the first live
# window. DACCORD_BENCH_SERVE_TRACE names a jsonl of {"dt": seconds-since-
# previous-arrival} rows (default: a bursty 6-job trace);
# DACCORD_BENCH_SERVE_BACKEND overrides the engine (default: native when
# built, else cpu — the serving plane benches chip-free).
BENCH_SERVE = os.environ.get("DACCORD_BENCH_SERVE") == "1"
BENCH_SERVE_TRACE = os.environ.get("DACCORD_BENCH_SERVE_TRACE")
# chaos soak (ISSUE 15): DACCORD_BENCH_SERVE_SOAK=1 runs a sustained,
# seeded job-arrival trace against TWO daccord-serve processes sharing a
# peer-takeover dir, under a deterministic serve_crash + device_lost fault
# storm (dead processes are restarted), and asserts the crash-durability
# contract at the end: every admitted job reached COMMITTED or
# client-ABORTED exactly once, every committed FASTA is byte-identical to
# the solo control, and no quota charge or spool dir leaked. Commits
# BENCH_SERVE_SOAK.json. DACCORD_BENCH_SERVE_SOAK_JOBS overrides the job
# count (default 20).
BENCH_SERVE_SOAK = os.environ.get("DACCORD_BENCH_SERVE_SOAK") == "1"
# disk-chaos soak (ISSUE 17): DACCORD_BENCH_DISK=1 runs the same 2-peer
# serve fleet under an injected ENOSPC/EIO storage storm (io_enospc@journal
# bursts on one peer, transient io_eio@lease on the other — the full-disk
# matrix from runtime/faults.py) and asserts the graceful-degradation
# contract: NO process dies, submissions during the latch get structured
# 507 refusals, every completed FASTA is byte-identical to the solo
# control with exactly-once commits, transient lease EIO never demotes a
# healthy run, zero .tmp/spool litter remains, and the fleet recovers
# fully once the storm is spent. Commits BENCH_DISK.json (chaos-flagged so
# daccord-sentinel --strict exempts the deliberate pressure).
# DACCORD_BENCH_DISK_JOBS overrides the job count (default 8).
BENCH_DISK = os.environ.get("DACCORD_BENCH_DISK") == "1"
# network-chaos soak (ISSUE 18): DACCORD_BENCH_NET=1 runs a live
# daccord-router (in-process, so the injected net_* matrix from
# runtime/faults.py fires inside its serve/netio choke point) fronting TWO
# healthy daccord-serve subprocesses, and storms the NETWORK between them:
# a net_reset burst on the submit domain (absorbed by bounded idempotent
# retries), net_torn + net_hang + net_slow on the stream domain (torn
# proxied streams are detected via the byte-count trailer and retried,
# never committed short), then a full healthz partition of one peer
# (SIGSTOP: host answers TCP, process says nothing) whose announce lease
# stays fresh — the router must mark it PARTITIONED and route around it,
# the autoscaler must not drain/reap it, and job takeover must not fire.
# Asserts exactly-once commits fleet-wide, byte parity vs the solo
# control, breaker open AND re-close observed, and post-storm recovery.
# Commits BENCH_NET.json (chaos-flagged so daccord-sentinel --strict
# exempts the deliberate storm). DACCORD_BENCH_NET_JOBS overrides the job
# count (default 6).
BENCH_NET = os.environ.get("DACCORD_BENCH_NET") == "1"
# silent-data-corruption soak (ISSUE 20): DACCORD_BENCH_SDC=1 runs a
# mesh-8 correction three times over one seeded dataset — audit OFF
# (golden bytes + unaudited wall), an injected `sdc:*@3` storm (one mesh
# member silently flips consensus bases in every batch it touches), and a
# clean control at the DEFAULT 1/64 audit rate — and asserts the defense
# contract: the storm is detected by the sampled shadow audit, attributed
# to member 3 from the event stream alone, quarantined through the
# partial-mesh shrink rung with the verdict persisted in the trust
# registry, the final output is byte-identical to the golden run, and the
# control's steady-state audit cost is <=2% of wall. Chip-free: re-execs
# itself under the off-pod recipe (forced 8-device host platform), the
# same pattern as the mesh arm. Commits BENCH_SDC.json (chaos-flagged).
# DACCORD_BENCH_SDC_BATCH / _SEED override the window batch and the seed.
BENCH_SDC = os.environ.get("DACCORD_BENCH_SDC") == "1"
# front door (ISSUE 16): DACCORD_BENCH_ROUTER=1 commits BENCH_ROUTER.json
# with two arms: (a) cold-peer TTFR — time from fresh solve path to the
# first fetched batch result — WITH the fleet-shared AOT executable cache
# (deserialize) vs WITHOUT (cold jit compile), measured at the dispatcher
# under a fresh jax compilation-cache dir so the cold number is honest;
# (b) p99 job latency through a live daccord-router while the SLO-burn
# autoscaler scales the fleet out under a bursty arrival trace (spawned
# daccord-serve subprocesses join via announce leases + the shared AOT
# cache). Chip-free: both arms run on the CPU/native backends.
BENCH_ROUTER = os.environ.get("DACCORD_BENCH_ROUTER") == "1"
# multichip mesh arm (ISSUE 12): DACCORD_BENCH_MESH=1 measures mesh-N
# windows/sec scaling vs single-device ON THIS HOST through the sharded
# ladder (parallel/mesh.py) and commits the next MULTICHIP_r*.json sidecar —
# per-rung wall decomposed into dispatch vs fetch, per-device slice width,
# and the pad-to-mesh-multiple waste. With no live device the arm re-execs
# itself under the off-pod recipe (JAX_PLATFORMS=cpu + forced host platform
# device count), so the multichip trajectory resumes chip-free; on a live
# tunnel the same env var is the queued on-chip mesh rung.
# DACCORD_BENCH_MESH_N overrides the mesh width (default 8);
# DACCORD_BENCH_MESH_MAX_BATCHES caps batches per rung (CPU smoke).
BENCH_MESH = os.environ.get("DACCORD_BENCH_MESH") == "1"
BENCH_MESH_N = int(os.environ.get("DACCORD_BENCH_MESH_N", "8"))
_mmb = os.environ.get("DACCORD_BENCH_MESH_MAX_BATCHES")
BENCH_MESH_MAX_BATCHES = int(_mmb) if _mmb else None


def _bench_consensus_config():
    """ConsensusConfig for both throughput paths (pipelined AND compute
    ceiling must bench the SAME config or pipeline_efficiency mixes
    configs); env levers apply only when set."""
    from daccord_tpu.oracle.consensus import ConsensusConfig
    from daccord_tpu.oracle.dbg import DBGParams

    if N_CANDIDATES is None:
        return ConsensusConfig()
    return ConsensusConfig(dbg=DBGParams(n_candidates=N_CANDIDATES))
DEPTH, SEG_LEN, WLEN = 32, 64, 40


def build_windows() -> dict:
    os.makedirs(CACHE, exist_ok=True)
    npz = os.path.join(CACHE, "windows_v1.npz")
    if os.path.exists(npz):
        d = np.load(npz)
        return {k: d[k] for k in d.files}

    from daccord_tpu.kernels import BatchShape, tensorize_windows
    from daccord_tpu.oracle import (
        ConsensusConfig,
        cut_windows,
        estimate_profile_two_pass,
        refine_overlap,
    )
    from daccord_tpu.sim import SimConfig, simulate

    cfg = SimConfig(genome_len=20_000, coverage=20, read_len_mean=2_000, seed=42)
    res = simulate(cfg)
    ccfg = ConsensusConfig()
    shape = BatchShape(depth=DEPTH, seg_len=SEG_LEN, wlen=WLEN)
    items = []
    prof = None
    piles: dict[int, list] = {}
    for o in res.overlaps:
        piles.setdefault(o.aread, []).append(o)
    for aread, pile in piles.items():
        a = res.reads[aread].seq
        refined = [refine_overlap(o, a, res.reads[o.bread].seq, cfg.tspace) for o in pile]
        windows = cut_windows(a, refined, w=ccfg.w, adv=ccfg.adv)
        if prof is None:
            prof = estimate_profile_two_pass(refined, windows, ccfg, sample=24)
        items.extend((aread, ws) for ws in windows)
        if len(items) >= N_BENCH_WINDOWS:
            break
    batch = tensorize_windows(items[:N_BENCH_WINDOWS], shape)
    out = dict(seqs=batch.seqs, lens=batch.lens, nsegs=batch.nsegs,
               p_ins=np.float64(prof.p_ins), p_del=np.float64(prof.p_del),
               p_sub=np.float64(prof.p_sub))
    np.savez_compressed(npz, **out)
    return out


def _make_batch(data: dict, i: int, batch_size: int, shape):
    """Slice windows [i*batch_size, (i+1)*batch_size) into a WindowBatch —
    the one batch constructor shared by all three throughput paths."""
    from daccord_tpu.kernels.tensorize import WindowBatch

    sl = slice(i * batch_size, (i + 1) * batch_size)
    return WindowBatch(seqs=data["seqs"][sl], lens=data["lens"][sl],
                       nsegs=data["nsegs"][sl], shape=shape,
                       read_ids=np.zeros(batch_size, np.int64),
                       wstarts=np.zeros(batch_size, np.int64))


def oracle_baseline(data: dict, n: int = 48) -> float:
    """Single-core numpy oracle throughput (consensus bases/sec).

    Pins the PURE-python alignment path (native lib masked for the timing):
    r4 routed the oracle's rescore through the native exact DP, which would
    silently deflate every round's vs_baseline ratio — the baseline must
    stay the same numpy program it was in r1-r3 to remain comparable."""
    from daccord_tpu.oracle import align as _align
    from daccord_tpu.oracle.consensus import ConsensusConfig, make_offset_likely, solve_window
    from daccord_tpu.oracle.profile import ErrorProfile
    from daccord_tpu.oracle.windows import WindowSegments

    prof = ErrorProfile(float(data["p_ins"]), float(data["p_del"]), float(data["p_sub"]))
    ccfg = ConsensusConfig()
    ols = make_offset_likely(prof, ccfg)
    idx = np.linspace(0, len(data["nsegs"]) - 1, n).astype(int)
    orig_lib = _align._native_lib
    _align._native_lib = lambda: None
    try:
        t0 = time.perf_counter()
        bases = 0
        for i in idx:
            segs = [data["seqs"][i, d, : data["lens"][i, d]] for d in range(int(data["nsegs"][i]))]
            ws = WindowSegments(wstart=0, wlen=WLEN, segments=segs, breads=[0] * len(segs))
            r = solve_window(ws, ols, ccfg)
            if r.seq is not None:
                bases += len(r.seq)
        dt = time.perf_counter() - t0
    finally:
        _align._native_lib = orig_lib
    return bases / dt if dt > 0 else 0.0


def _ladder_fingerprint(batch: int = BATCH) -> str:
    import jax

    fp = f"{jax.default_backend()}:B{batch}xD{DEPTH}xL{SEG_LEN}"
    # esc_cap and n_candidates are STATIC jit args — a different value is a
    # different XLA program, so the esccap256/cand5 pounce steps must not be
    # announced as warm off the default program's fingerprint (the silent
    # cold compile that ambiguity caused killed two healthy r5 benches).
    # ESC_CAP == batch is the same program the default (None -> full batch)
    # compiles.
    if ESC_CAP is not None and ESC_CAP != batch:
        fp += f":esc{ESC_CAP}"
    if N_CANDIDATES is not None:
        fp += f":c{N_CANDIDATES}"
    return fp


def _announce_compile(ev, batch: int = BATCH) -> bool:
    """Echo the expected cold-compile wall BEFORE the warmup goes silent
    (ADVICE r5 #2: two healthy benches were killed because a multi-minute
    server-side compile is indistinguishable from a wedge). Returns whether
    the shape fingerprint was already in the persistent-cache registry."""
    import sys

    from daccord_tpu.utils.obs import expected_compile_wall_s, fingerprint_seen

    fp = _ladder_fingerprint(batch)
    cached = fingerprint_seen(fp)
    exp = 0.0 if cached else expected_compile_wall_s(batch)
    if ev:
        ev.log("bench_compile", batch=batch, cached=cached,
               expected_wall_s=round(exp, 1))
    if not cached:
        print(f"bench: cold ladder compile for B={batch} "
              f"(fingerprint {fp} not in cache registry) — expect up to "
              f"~{int(exp)}s of silence before the first batch; do NOT "
              "kill the run", file=sys.stderr)
    return cached


def precompile_ladder(data: dict, ev=None, batch: int = BATCH) -> dict:
    """Compile the ladder at ``batch`` into the persistent XLA cache and
    exit-style report (DACCORD_BENCH_PRECOMPILE=1): the pounce sequence runs
    this for B=2048/4096 first (and the rung ladder runs it in a background
    subprocess for its top rung) so the timed benches start solving in
    seconds."""
    import jax

    from daccord_tpu.kernels.tensorize import BatchShape
    from daccord_tpu.kernels.tiers import TierLadder, fetch, solve_ladder_async
    from daccord_tpu.oracle.profile import ErrorProfile
    from daccord_tpu.utils.obs import record_fingerprint

    prof = ErrorProfile(float(data["p_ins"]), float(data["p_del"]), float(data["p_sub"]))
    ladder = TierLadder.from_config(prof, _bench_consensus_config())
    shape = BatchShape(depth=DEPTH, seg_len=SEG_LEN, wlen=WLEN)
    cached = _announce_compile(ev, batch)
    t0 = time.perf_counter()
    b0 = _make_batch(data, 0, batch, shape)
    fetch(solve_ladder_async(b0, ladder, esc_cap=ESC_CAP))
    wall = time.perf_counter() - t0
    # compile-wall + HLO-cost telemetry into the fingerprint registry
    # (ISSUE 13): the AOT lower+compile after the warmup is a cache hit,
    # and the flops/bytes estimate rides the registry entry so the
    # host-local per-shape history holds program cost beside compile wall
    from daccord_tpu.kernels.tiers import ladder_cost

    cost = ladder_cost(b0, ladder, esc_cap=ESC_CAP)
    record_fingerprint(_ladder_fingerprint(batch), wall_s=wall, meta=cost)
    return {"precompile": True, "batch": batch,
            "compile_wall_s": round(wall, 3), "was_cached": cached,
            "hlo_cost": cost,
            "device": str(jax.devices()[0]).replace(" ", "")}


def device_throughput(data: dict, max_batches: int | None = None,
                      max_inflight: int = 8, ev=None,
                      batch: int = BATCH) -> tuple[float, dict]:
    """Pipelined-dispatch throughput (the pipeline's own dispatch discipline).

    A blocking fetch per batch would measure the axon tunnel's per-call
    latency (~60-300 ms), not the chip: batches are dispatched with a bounded
    in-flight window and results fetched as they complete, exactly like
    runtime/pipeline.py does in production.
    """
    from collections import deque

    import jax

    from daccord_tpu.kernels.tensorize import BatchShape
    from daccord_tpu.kernels.tiers import (TierLadder, fetch, fetch_many,
                                           solve_ladder_async)
    from daccord_tpu.oracle.consensus import ConsensusConfig
    from daccord_tpu.oracle.profile import ErrorProfile

    prof = ErrorProfile(float(data["p_ins"]), float(data["p_del"]), float(data["p_sub"]))
    ladder = TierLadder.from_config(prof, _bench_consensus_config())
    shape = BatchShape(depth=DEPTH, seg_len=SEG_LEN, wlen=WLEN)

    N = len(data["nsegs"])
    nb = N // batch
    if max_batches is not None:
        nb = min(nb, max_batches)

    def make_batch(i):
        return _make_batch(data, i, batch, shape)

    # warmup / compile all tier shapes (with the expected-wall echo so a
    # long-silent cold compile is not mistaken for a wedge)
    was_cached = _announce_compile(ev, batch)
    t_warm = time.perf_counter()
    fetch(solve_ladder_async(make_batch(0), ladder, esc_cap=ESC_CAP))
    from daccord_tpu.utils.obs import record_fingerprint

    # a cold warmup's wall IS the compile wall — fold it into the registry
    # (a cached one records no wall: it would understate the cold cost)
    record_fingerprint(_ladder_fingerprint(batch),
                       wall_s=None if was_cached
                       else time.perf_counter() - t_warm)

    # tunnel RTT estimate (sidecar provenance): median of 3 tiny blocking
    # fetches — the fixed per-device_get cost the pipelined dispatch amortizes
    tiny = jax.device_put(jax.numpy.zeros(8, jax.numpy.int32))
    jax.block_until_ready(tiny)
    rtts = []
    for _ in range(3):
        tr = time.perf_counter()
        jax.device_get(tiny)
        rtts.append(time.perf_counter() - tr)
    rtt_ms = round(sorted(rtts)[1] * 1e3, 1)

    nladder = None
    n_hp = 0
    if BENCH_HP:
        from daccord_tpu.native import available as _nat_avail
        from daccord_tpu.native.api import NativeLadder
        from daccord_tpu.oracle.consensus import make_offset_likely

        if not _nat_avail():
            raise SystemExit("DACCORD_BENCH_HP=1 needs the native library")
        _ccfg = _bench_consensus_config()
        import dataclasses as _dc

        _ccfg = _dc.replace(_ccfg, hp_rescue=True)
        nladder = NativeLadder(make_offset_likely(prof, _ccfg), _ccfg)

    t0 = time.perf_counter()
    bases = 0
    solved = 0
    inflight: deque = deque()
    # saturation accounting (ISSUE 14): device-occupancy integral +
    # fetch-blocked wall, so the committed rung carries the same
    # starvation gauges + verdict a pipeline run stamps
    sat = {"busy_s": 0.0, "t0": None, "fetch_s": 0.0, "dispatch_s": 0.0}

    def drain(to_depth: int):
        nonlocal bases, solved, n_hp
        n_pop = len(inflight) - to_depth
        if n_pop <= 0:
            return
        # ONE grouped fetch per drain: the tunnel charges its ~100 ms RTT per
        # device_get call, not per array (same discipline as the pipeline)
        entries = [inflight.popleft() for _ in range(n_pop)]
        if ev:
            # liveness heartbeat: a pounce watcher tailing the events file
            # can tell a progressing bench from a wedged one
            ev.log("bench_drain", fetched=n_pop, inflight=len(inflight))
        tf = time.perf_counter()
        outs = fetch_many([h for h, _ in entries])
        now = time.perf_counter()
        sat["fetch_s"] += now - tf
        if not inflight and sat["t0"] is not None:
            sat["busy_s"] += now - sat["t0"]
            sat["t0"] = None
        for (h, bi), out in zip(entries, outs):
            if nladder is not None:
                # the production drain's hp pass (runtime/pipeline.py
                # hp_pass C++ branch) on this batch's host-side tensors
                from types import SimpleNamespace

                sl = slice(bi * batch, (bi + 1) * batch)
                shim = SimpleNamespace(seqs=data["seqs"][sl],
                                       lens=data["lens"][sl],
                                       nsegs=data["nsegs"][sl])
                sub = {"cons": np.array(out["cons"][:batch], dtype=np.int8),
                       "cons_len": np.array(out["cons_len"][:batch],
                                            dtype=np.int32),
                       "err": np.array(out["err"][:batch], dtype=np.float32),
                       "tier": np.array(out["tier"][:batch], dtype=np.int32)}
                n_hp += nladder.hp_rescue(shim, sub, n_threads=1)
            bases += int(out["cons_len"].sum())
            solved += int(out["solved"].sum())

    for i in range(nb):
        td = time.perf_counter()
        if sat["t0"] is None:
            sat["t0"] = td
        inflight.append((solve_ladder_async(make_batch(i), ladder,
                                            esc_cap=ESC_CAP), i))
        sat["dispatch_s"] += time.perf_counter() - td
        if len(inflight) >= max_inflight:
            drain(max_inflight // 2)
    drain(0)
    dt = time.perf_counter() - t0
    from daccord_tpu.utils.obs import bottleneck_verdict, saturation_gauges

    gs = saturation_gauges(dt, sat["fetch_s"], sat["busy_s"])
    info = dict(windows=nb * batch, solved=solved, wall_s=round(dt, 3),
                device=str(jax.devices()[0]).replace(" ", ""),
                solve_rate=round(solved / (nb * batch), 4),
                batch=batch, rtt_ms=rtt_ms,
                # ISSUE 14: every committed rung carries the starvation
                # gauges + the automatic bottleneck verdict, so the device
                # bench trajectory is sentinel-guarded for feeder drift too
                saturation={**gs,
                            "dispatch_s": round(sat["dispatch_s"], 3),
                            "fetch_blocked_s": round(sat["fetch_s"], 3)},
                verdict=bottleneck_verdict(gs)["verdict"])
    if ESC_CAP is not None:
        info["esc_cap"] = ESC_CAP
    if N_CANDIDATES is not None:
        info["n_candidates"] = N_CANDIDATES
    if BENCH_HP:
        info["hp_drain"] = True
        info["hp_rescued"] = n_hp
    return bases / dt, info


def device_compute_throughput(data: dict, max_batches: int | None = None,
                              batch: int = BATCH) -> tuple[float, dict]:
    """Compute-bound ceiling: all batches pre-staged on device, every ladder
    program enqueued back-to-back, ONE terminal block — no per-batch fetch,
    no H2D inside the timed region. The gap between this number and the
    pipelined one is pure dispatch/tunnel overhead (VERDICT r1 weak #3: the
    chip was ~90% idle behind ~100 ms fetch RTTs and nobody had recorded the
    ceiling). Per-stage wall times (h2d, dispatch, compute, fetch) come back
    in the info dict so the overhead has a breakdown, not just a total.
    """
    import jax
    import jax.numpy as jnp

    from daccord_tpu.kernels.tiers import TierLadder, _ladder_packed_jit, unpack_result
    from daccord_tpu.oracle.consensus import ConsensusConfig
    from daccord_tpu.oracle.profile import ErrorProfile

    prof = ErrorProfile(float(data["p_ins"]), float(data["p_del"]), float(data["p_sub"]))
    ladder = TierLadder.from_config(prof, _bench_consensus_config())
    tables = tuple(ladder.tables[p.k] for p in ladder.params)
    params = tuple(ladder.params)
    cl = ladder.params[0].cons_len

    N = len(data["nsegs"])
    nb = N // batch
    if max_batches is not None:
        nb = min(nb, max_batches)

    def run(staged):
        return _ladder_packed_jit(*staged, tables, params,
                                  esc_cap=ESC_CAP if ESC_CAP is not None
                                  else batch)

    # H2D: stage every batch's inputs as committed device arrays
    t0 = time.perf_counter()
    staged = []
    for i in range(nb):
        sl = slice(i * batch, (i + 1) * batch)
        staged.append((jax.device_put(jnp.asarray(data["seqs"][sl])),
                       jax.device_put(jnp.asarray(data["lens"][sl])),
                       jax.device_put(jnp.asarray(data["nsegs"][sl]))))
    jax.block_until_ready(staged)
    t_h2d = time.perf_counter() - t0

    # warmup / compile (first staged batch), excluded from the timed region
    jax.block_until_ready(run(staged[0]))

    t0 = time.perf_counter()
    outs = [run(s) for s in staged]
    t_dispatch = time.perf_counter() - t0
    jax.block_until_ready(outs)
    t_total = time.perf_counter() - t0
    t_compute = t_total - t_dispatch

    t0 = time.perf_counter()
    arrs = jax.device_get(outs)   # one grouped transfer
    t_fetch = time.perf_counter() - t0

    bases = 0
    solved = 0
    for a in arrs:
        out = unpack_result(np.asarray(a), cl)
        bases += int(out["cons_len"].sum())
        solved += int(out["solved"].sum())
    info = dict(compute_windows=nb * batch, compute_solved=solved,
                compute_wall_s=round(t_total, 3),
                stage_h2d_s=round(t_h2d, 3),
                stage_dispatch_s=round(t_dispatch, 3),
                stage_compute_s=round(t_compute, 3),
                stage_fetch_s=round(t_fetch, 3),
                dispatch_ms_per_batch=round(1e3 * t_dispatch / nb, 2))
    return bases / t_total if t_total > 0 else 0.0, info


def cpu_fallback_throughput(data: dict, n_windows: int = 2048,
                            batch: int = 256) -> tuple[float, dict]:
    """Honest CPU number for tunnel-outage runs: the CPU-appropriate tiered
    path (small jitted batches + compacted rescue), not the TPU-shaped B=2048
    program that is pessimal on host (VERDICT r1 weak #2)."""
    import jax
    import jax.numpy as jnp

    from daccord_tpu.kernels.tensorize import BatchShape
    from daccord_tpu.kernels.tiers import TierLadder, solve_tiered
    from daccord_tpu.kernels.window_kernel import solve_window_batch
    from daccord_tpu.oracle.consensus import ConsensusConfig
    from daccord_tpu.oracle.profile import ErrorProfile

    prof = ErrorProfile(float(data["p_ins"]), float(data["p_del"]), float(data["p_sub"]))
    ladder = TierLadder.from_config(prof, ConsensusConfig())
    shape = BatchShape(depth=DEPTH, seg_len=SEG_LEN, wlen=WLEN)

    def make_batch(i):
        return _make_batch(data, i, batch, shape)

    nb = max(1, min(len(data["nsegs"]), n_windows) // batch)
    # warmup: tier 0 at the full batch shape via solve_tiered, PLUS every
    # rescue tier at its compact shape explicitly — solve_tiered stops at the
    # deepest tier batch 0 happens to need, and a first-time XLA compile of a
    # deeper tier inside the timed loop would deflate the reported number
    cs = 64
    solve_tiered(make_batch(0), ladder, compact_size=cs)
    zs = jnp.asarray(np.full((cs, DEPTH, SEG_LEN), 4, np.int8))
    zl = jnp.asarray(np.zeros((cs, DEPTH), np.int32))
    zn = jnp.asarray(np.zeros(cs, np.int32))
    for p in ladder.params[1:]:
        solve_window_batch(zs, zl, zn, ladder.tables[p.k], p)
    t0 = time.perf_counter()
    bases = 0
    solved = 0
    for i in range(nb):
        out = solve_tiered(make_batch(i), ladder, compact_size=cs)
        bases += int(out["cons_len"][out["solved"]].sum())
        solved += int(out["solved"].sum())
    dt = time.perf_counter() - t0
    from daccord_tpu.utils.obs import saturation_gauges

    info = dict(windows=nb * batch, solved=solved, wall_s=round(dt, 3),
                device=str(jax.devices()[0]).replace(" ", ""),
                solve_rate=round(solved / (nb * batch), 4),
                # ISSUE 14: the fallback loop is pure synchronous solve —
                # the host blocks on the engine for the whole timed region
                saturation=saturation_gauges(dt, dt, dt),
                verdict="device")

    # the native C++ full-graph engine is the framework's real degraded-mode
    # capability (4-7x the JAX-CPU ladder per core; --backend native): report
    # it next to the ladder number so a tunnel-outage round still carries an
    # honest best-CPU figure
    try:
        from daccord_tpu.native import available as _nat_avail
        from daccord_tpu.native.api import solve_windows_native

        if _nat_avail():
            full = _make_batch(data, 0, min(len(data["nsegs"]), n_windows),
                               shape)
            ccfg = ConsensusConfig()
            from daccord_tpu.oracle.consensus import make_offset_likely

            ols = make_offset_likely(prof, ccfg)
            solve_windows_native(_slice_batch(full, 64), ols, ccfg)  # warm
            t0 = time.perf_counter()
            out = solve_windows_native(full, ols, ccfg)
            ndt = time.perf_counter() - t0
            nbases = int(out["cons_len"][out["solved"]].sum())
            info["native_cpu_bases_per_sec"] = round(nbases / ndt, 1)
            info["native_cpu_windows"] = int(full.seqs.shape[0])
    except Exception as e:   # never let the extra figure sink the bench line
        info["native_cpu_error"] = repr(e)[:120]
    return bases / dt if dt > 0 else 0.0, info


def _slice_batch(batch, n: int):
    from daccord_tpu.tools.consensusbench import batch_slice

    return batch_slice(batch, n)


def _commit_sidecar(path: str, payload: dict) -> None:
    """Crash-durable rung sidecar via the repo's one durable-commit
    primitive (content fsync + rename + dir fsync): a tunnel or machine
    death mid-ladder can never tear — or un-publish — the evidence."""
    from daccord_tpu.utils.aio import durable_write

    durable_write(path, lambda fh: json.dump(payload, fh), mode="wt")


def _tunnel_staleness() -> dict:
    """Last-alive tunnel probe provenance (ISSUE 13 satellite: staleness
    blindness). Stamped into every BENCH_*/MULTICHIP_* sidecar as
    ``last_real_tpu_ts``/``last_real_tpu_age_h`` and echoed at bench start,
    so a ``fallback: true`` rung is attributable to a dated tunnel death
    from the sidecar alone — no TUNNEL_LOG spelunking."""
    from daccord_tpu.tools.trace import last_alive_info

    here = os.path.dirname(os.path.abspath(__file__))
    ts, age_h = last_alive_info(os.path.join(here, "TUNNEL_LOG.jsonl"))
    return {"last_real_tpu_ts": ts, "last_real_tpu_age_h": age_h}


def _echo_staleness() -> dict:
    import sys as _sys

    st = _tunnel_staleness()
    if st["last_real_tpu_ts"]:
        age = (f" ({st['last_real_tpu_age_h']}h ago)"
               if st["last_real_tpu_age_h"] is not None else "")
        print(f"bench: last real TPU probe alive {st['last_real_tpu_ts']}"
              f"{age}", file=_sys.stderr)
    else:
        print("bench: NO alive TPU probe on record (TUNNEL_LOG.jsonl) — "
              "any device number this run is suspect", file=_sys.stderr)
    return st


def _memory_telemetry() -> dict:
    """Peak-memory provenance for a bench sidecar (ISSUE 5): device
    ``memory_stats()`` peak bytes when the backend exposes it (TPU does;
    CPU returns None) and the host's peak RSS. Committed per rung, the
    B->HBM curve rides alongside the B->wall curve — the max-safe-B decision
    row then needs no second chip window."""
    out: dict = {"device_peak_bytes": None, "host_peak_rss_mb": None}
    try:
        import resource

        # ru_maxrss is KB on Linux (the only platform this repo targets)
        out["host_peak_rss_mb"] = round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0, 1)
    except Exception:
        pass
    try:
        import jax

        ms = jax.devices()[0].memory_stats()
        if ms:
            peak = ms.get("peak_bytes_in_use", ms.get("bytes_in_use"))
            # 0 is a real reading; only a missing stat means "unavailable"
            out["device_peak_bytes"] = int(peak) if peak is not None else None
    except Exception:
        pass
    return out


def _pad_waste_telemetry(data: dict, batch: int,
                         max_batches: int | None = None) -> dict:
    """Pad-waste provenance for a bench sidecar (ISSUE 7): the fraction of
    dispatched batch cells that are padding — a first-class BASELINE.md
    metric the rung sidecars previously omitted — plus per-depth-bucket
    occupancy of the measured window set (the pipeline's default bucket
    grid), so a paged-vs-dense comparison is attributable per rung without
    re-deriving the corpus histogram."""
    lens = data["lens"]
    nsegs = data["nsegs"]
    N = len(nsegs)
    nb = N // batch
    if max_batches is not None:
        nb = min(nb, max_batches)
    n = nb * batch
    total = n * lens.shape[1] * SEG_LEN
    used = int(lens[:n].sum())
    occ = {}
    d_buckets = (8, 16, 32)
    assign = np.searchsorted(np.asarray(d_buckets), nsegs[:n], side="left")
    for i, db_ in enumerate(d_buckets):
        sel = assign == i
        cnt = int(sel.sum())
        if cnt:
            occ[str(db_)] = round(
                float(lens[:n][sel].sum()) / (cnt * db_ * SEG_LEN), 4)
    return {"pad_waste": round(1.0 - used / max(total, 1), 4),
            "bucket_occupancy": occ}


def _measure_device(data: dict, ev, batch: int,
                    max_batches: int | None = None) -> tuple[float, dict]:
    """Pipelined throughput + compute ceiling + efficiency ratio at one
    batch size — the ONE metric-assembly block shared by the flagship bench
    line and every ladder rung, so their sidecar fields cannot drift."""
    dev_bps, info = device_throughput(data, max_batches=max_batches, ev=ev,
                                      batch=batch)
    comp_bps, comp_info = device_compute_throughput(data,
                                                    max_batches=max_batches,
                                                    batch=batch)
    info["device_compute_bases_per_sec"] = round(comp_bps, 1)
    info.update(comp_info)
    info["pipeline_efficiency"] = (round(dev_bps / comp_bps, 3)
                                   if comp_bps else None)
    info.update(_pad_waste_telemetry(data, batch, max_batches))
    # peak-memory telemetry AFTER both passes: the rung's sidecar commits
    # the B->HBM point next to its B->wall point
    info.update(_memory_telemetry())
    return dev_bps, info


def run_ladder(data: dict, ev, orc_bps: float) -> int:
    """Self-staging batch ladder (VERDICT r5 next-round #1): measure rungs
    small-to-large, COMMITTING one sidecar (BENCH_LADDER_B*.json, atomic)
    and printing one stdout line the moment each rung completes — so a chip
    window that dies after two minutes still leaves a fallback:false number
    on disk. The top rung's multi-minute server-side compile warms in a
    background subprocess (persistent XLA cache) while the small rungs
    measure; the ladder joins it before the top rung so the timed run loads
    the warm program instead of sitting silent. Returns the count of rungs
    that landed."""
    import subprocess
    import sys as _sys

    import jax

    from daccord_tpu.utils.obs import fingerprint_seen, probe_backend_status

    here = os.path.dirname(os.path.abspath(__file__))
    warm = None
    top = BENCH_LADDER[-1]
    if (len(BENCH_LADDER) > 1 and jax.default_backend() == "tpu"
            and not fingerprint_seen(_ladder_fingerprint(top))):
        # background warm of the top rung. Known trade (accepted by VERDICT
        # r5 #1's design): a second tunnel client runs concurrently with the
        # small-rung benches; the compile is server-side and the subprocess
        # commits only cache artifacts, so a conflict costs the warm, not
        # the measurement.
        env = dict(os.environ, DACCORD_BENCH_PRECOMPILE="1",
                   DACCORD_BENCH_BATCH=str(top),
                   DACCORD_BENCH_LADDER="")
        ev_path = os.path.join(here, f"BENCH_LADDER_B{top:04d}.warm.events.jsonl")
        env["DACCORD_BENCH_EVENTS"] = ev_path
        warm_log = open(os.path.join(here,
                                     f"BENCH_LADDER_B{top:04d}.warm.log"), "wt")
        warm = subprocess.Popen(
            [_sys.executable, os.path.abspath(__file__)],
            stdout=warm_log, stderr=subprocess.STDOUT, env=env)
        warm_log.close()   # the child holds its own descriptor
        print(f"bench: warming B={top} compile in background "
              f"(pid {warm.pid})", file=_sys.stderr)
    landed = 0
    try:
        for rung in BENCH_LADDER:
            mb = LADDER_MAX_BATCHES
            if mb is None and rung != top:
                # small rungs need a fast honest number, not the full window
                # set: ~16k windows bounds the B=64 rung to ~256 dispatches.
                # The TOP rung stays uncapped — it replaces the flagship
                # bench as the round's headline artifact, and truncating it
                # to one inflight-fill would bias it low vs every r1-r8
                # baseline
                mb = max(2, 16384 // rung)
            if warm is not None and rung == top:
                t_w = time.perf_counter()
                try:
                    # bounded: a warm child wedged on a dying tunnel must not
                    # hold the whole ladder hostage — the rung then announces
                    # and pays its own cold compile (or fails its probe)
                    warm.wait(timeout=2 * 3600)
                except subprocess.TimeoutExpired:
                    warm.kill()
                    warm.wait()   # reap: rc recorded for real, no zombie
                ev.log("bench_warm_join", batch=top, rc=warm.returncode,
                       waited_s=round(time.perf_counter() - t_w, 3))
                warm = None
            try:
                dev_bps, info = _measure_device(data, ev, rung, max_batches=mb)
            except Exception as e:
                if probe_backend_status()[0] > 0:
                    raise   # host-side bug, not a chip death — surface it
                reason = f"device_loss_mid_run:{type(e).__name__}"
                line = {"metric": "consensus_bases_per_sec_per_chip",
                        "rung": True, "batch": rung, "fallback": True,
                        "fallback_reason": reason, **_tunnel_staleness()}
                _commit_sidecar(os.path.join(here,
                                             f"BENCH_LADDER_B{rung:04d}.json"),
                                line)
                print(json.dumps(line), flush=True)
                ev.log("bench_rung", batch=rung, bases_per_sec=0.0,
                       fallback=True, pad_waste=0.0)
                break
            line = {"metric": "consensus_bases_per_sec_per_chip",
                    "value": round(dev_bps, 1), "unit": "bases/s", "rung": True,
                    "vs_baseline": round(dev_bps / orc_bps, 2) if orc_bps else None,
                    "oracle_bases_per_sec": round(orc_bps, 1),
                    "fallback": False, "fallback_reason": None,
                    "ts": round(time.time(), 1), **_tunnel_staleness(),
                    **info}
            _commit_sidecar(os.path.join(here, f"BENCH_LADDER_B{rung:04d}.json"),
                            line)
            print(json.dumps(line), flush=True)
            ev.log("bench_rung", batch=rung, bases_per_sec=round(dev_bps, 1),
                   fallback=False, pad_waste=info.get("pad_waste", 0.0))
            landed += 1
    finally:
        if warm is not None and warm.poll() is None:
            # ladder ended early (rung failure, dead chip, host bug): reap
            # the warm child so it neither zombies nor keeps an orphan
            # tunnel client racing the next pounce step
            warm.terminate()
            try:
                warm.wait(timeout=30)
            except subprocess.TimeoutExpired:
                warm.kill()
                warm.wait()
    return landed


def _next_multichip_path() -> str:
    """Next MULTICHIP_rNN.json index in the repo root (the committed
    multichip trajectory: r01-r05 are the graft dry runs, the bench arm
    resumes the series)."""
    import re

    here = os.path.dirname(os.path.abspath(__file__))
    idx = 0
    for f in os.listdir(here):
        m = re.fullmatch(r"MULTICHIP_r(\d+)\.json", f)
        if m:
            idx = max(idx, int(m.group(1)))
    return os.path.join(here, f"MULTICHIP_r{idx + 1:02d}.json")


def run_mesh_bench(data: dict, ev, fallback_reason=None) -> dict:
    """Mesh scaling rung (DACCORD_BENCH_MESH=1): pipelined throughput of the
    sharded ladder at mesh widths 1 and N over the same window set, with the
    pipeline's own dispatch discipline (bounded in-flight window + grouped
    fetch). Commits the next MULTICHIP_r*.json sidecar with per-rung wall
    decomposition (dispatch vs fetch-blocked), per-device slice width, and
    the pad-to-mesh-multiple waste rows."""
    from collections import deque

    import jax

    from daccord_tpu.kernels.tensorize import BatchShape
    from daccord_tpu.oracle.profile import ErrorProfile
    from daccord_tpu.kernels.tiers import TierLadder
    from daccord_tpu.parallel.mesh import make_mesh, make_sharded_solver

    nd = min(BENCH_MESH_N, len(jax.devices()))
    prof = ErrorProfile(float(data["p_ins"]), float(data["p_del"]),
                        float(data["p_sub"]))
    ladder = TierLadder.from_config(prof, _bench_consensus_config())
    shape = BatchShape(depth=DEPTH, seg_len=SEG_LEN, wlen=WLEN)
    nb = len(data["nsegs"]) // BATCH
    if BENCH_MESH_MAX_BATCHES is not None:
        nb = min(nb, BENCH_MESH_MAX_BATCHES)
    widths = [1, nd] if nd > 1 else [1]
    # staged double-buffered dispatch (ISSUE 19): batch i+1's pad/shard/H2D
    # staging runs on ONE background thread under batch i's solve, exactly
    # the pipeline's _Stager discipline; DACCORD_MESH_PIPELINE=0 reverts to
    # the monolithic dispatch (the parity/Amdahl control arm)
    pipelined = os.environ.get("DACCORD_MESH_PIPELINE", "1") != "0"
    rungs = []
    for mesh_w in widths:
        solver = make_sharded_solver(ladder, make_mesh(mesh_w), batch=BATCH)
        staged_ok = pipelined and hasattr(solver, "stage")
        # warmup / compile outside the timed region (the expected-wall echo
        # for cold mesh shapes rides the same bench_compile event)
        _announce_compile(ev, BATCH)
        solver(_make_batch(data, 0, BATCH, shape))
        dw0 = (solver.dispatch_walls()
               if hasattr(solver, "dispatch_walls") else None)
        t0 = time.perf_counter()
        t_disp = 0.0
        t_fetch = 0.0
        windows = 0
        solved = 0
        inflight: deque = deque()
        # device-occupancy integral (ISSUE 14): per-rung starvation gauges
        sat = {"busy_s": 0.0, "t0": None}

        def drain(to_depth: int):
            nonlocal t_fetch, windows, solved
            n_pop = len(inflight) - to_depth
            if n_pop <= 0:
                return
            entries = [inflight.popleft() for _ in range(n_pop)]
            tf = time.perf_counter()
            outs = solver.fetch_many(entries)
            now = time.perf_counter()
            t_fetch += now - tf
            if not inflight and sat["t0"] is not None:
                sat["busy_s"] += now - sat["t0"]
                sat["t0"] = None
            for out in outs:
                windows += len(out["solved"])
                solved += int(out["solved"].sum())

        if staged_ok and nb > 0:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=1,
                                    thread_name_prefix="bench-stager") as ex:
                fut = ex.submit(solver.stage, _make_batch(data, 0, BATCH,
                                                          shape))
                for i in range(nb):
                    staged = fut.result()
                    if i + 1 < nb:
                        fut = ex.submit(solver.stage,
                                        _make_batch(data, i + 1, BATCH,
                                                    shape))
                    # t_disp = host wall BLOCKED on the dispatch path (the
                    # acceptance number): with staging overlapped it is the
                    # cheap async jit launch, not the pad+transfer
                    td = time.perf_counter()
                    if sat["t0"] is None:
                        sat["t0"] = td
                    inflight.append(solver.dispatch(staged))
                    t_disp += time.perf_counter() - td
                    if len(inflight) >= 8:
                        drain(4)
        else:
            for i in range(nb):
                td = time.perf_counter()
                if sat["t0"] is None:
                    sat["t0"] = td
                inflight.append(solver.dispatch(_make_batch(data, i, BATCH,
                                                            shape)))
                t_disp += time.perf_counter() - td
                if len(inflight) >= 8:
                    drain(4)
        drain(0)
        wall = time.perf_counter() - t0
        wps = windows / wall if wall > 0 else 0.0
        from daccord_tpu.utils.obs import (bottleneck_verdict,
                                           saturation_gauges)

        rung_gs = saturation_gauges(wall, t_fetch, sat["busy_s"])
        rungs.append({
            # ISSUE 14: per-rung starvation gauges + verdict — a
            # host_feeder verdict on the mesh rung is the sentinel's
            # one-host-cannot-feed-this-mesh advisory
            "saturation": rung_gs,
            "verdict": bottleneck_verdict(rung_gs)["verdict"],
            "mesh": mesh_w, "batch": BATCH, "batches": nb,
            "windows": windows, "solved": solved,
            "wall_s": round(wall, 3),
            # wall decomposition: host time spent issuing sharded dispatches
            # vs blocked on the grouped fetch — the rest is overlap slack
            "dispatch_s": round(t_disp, 3), "fetch_s": round(t_fetch, 3),
            "windows_per_sec": round(wps, 1),
            "pipelined": bool(staged_ok),
            # per-device view: each device ran rows/mesh of every batch
            "per_device_rows": BATCH // mesh_w,
            "windows_per_sec_per_device": round(wps / mesh_w, 1),
            "pad_to_mesh_rows": int(solver.pad_rows),
            "pad_to_mesh_waste": round(
                solver.pad_rows / max(solver.pad_rows + solver.live_rows, 1),
                6),
        })
        if dw0 is not None:
            # dispatch sub-walls (ISSUE 19): this rung's pack/stage/launch
            # deltas — host work only, wherever the staging thread spent it
            dw1 = solver.dispatch_walls()
            rungs[-1].update(
                pack_s=round(dw1["pack_s"] - dw0["pack_s"], 3),
                stage_s=round(dw1["stage_s"] - dw0["stage_s"], 3),
                launch_s=round(dw1["launch_s"] - dw0["launch_s"], 3))
        if hasattr(solver, "health_map"):
            # per-member starvation + overlap gauges (ISSUE 19): the
            # sentinel's dispatch-share/idle-rise checks read these rows
            hm = solver.health_map()
            rungs[-1]["members"] = {
                str(i): {"device_idle_frac": row.get("idle_frac"),
                         "overlap_frac": row.get("overlap_frac")}
                for i, row in sorted(hm.get("devices", {}).items())}
        ev.log("bench_rung", batch=BATCH,
               bases_per_sec=0.0, fallback=bool(fallback_reason),
               pad_waste=rungs[-1]["pad_to_mesh_waste"])
    line = {
        "metric": "multichip_windows_per_sec",
        "mesh": nd, "batch": BATCH,
        "device": str(jax.devices()[0]).replace(" ", ""),
        "n_devices_visible": len(jax.devices()),
        "fallback": bool(fallback_reason),
        "fallback_reason": fallback_reason,
        "rungs": rungs,
        # headline saturation = the widest rung's (the mesh the sidecar is
        # named for); the sentinel's mesh>=4 host_feeder advisory keys on
        # this verdict next to the `mesh` field above
        "saturation": rungs[-1]["saturation"],
        "verdict": rungs[-1]["verdict"],
        "ts": round(time.time(), 1),
        **_tunnel_staleness(),
    }
    if len(rungs) == 2 and rungs[0]["windows_per_sec"]:
        # the headline: mesh-N throughput over single-device on this host.
        # On forced host devices this is bounded by host cores (the rung
        # exists for parity + plumbing provenance); the on-chip run of the
        # same arm is the real scaling number.
        line["scaling_vs_single"] = round(
            rungs[1]["windows_per_sec"] / rungs[0]["windows_per_sec"], 3)
    path = _next_multichip_path()
    _commit_sidecar(path, line)
    line["sidecar"] = os.path.basename(path)
    return line


def run_serve_bench(ev) -> dict:
    """Serving-plane stage (DACCORD_BENCH_SERVE=1): synth a toy corpus,
    start a REAL daccord-serve HTTP server in-process, replay a job-arrival
    trace against it over the wire, and commit a sidecar with per-job
    p50/p99 latency + aggregate windows/sec — ISSUE 10's acceptance metric.
    The arrival trace is deterministic (recorded or the default burst), so
    two rounds' serve sidecars compare like-for-like."""
    import tempfile
    import urllib.request

    from daccord_tpu.serve import AdmissionConfig, ConsensusService, ServeConfig
    from daccord_tpu.serve.http import start_server
    from daccord_tpu.sim.synth import SimConfig, make_dataset

    backend = os.environ.get("DACCORD_BENCH_SERVE_BACKEND")
    if not backend:
        try:
            from daccord_tpu.native import available as _nat

            backend = "native" if _nat() else "cpu"
        except Exception:
            backend = "cpu"
    if backend in ("cpu", "native"):
        import jax

        jax.config.update("jax_platforms", "cpu")
    arrivals = [0.0, 0.1, 0.2, 0.5, 0.8, 1.2]      # bursty default trace
    if BENCH_SERVE_TRACE:
        arrivals = []
        t = 0.0
        with open(BENCH_SERVE_TRACE) as fh:
            for line in fh:
                line = line.strip()
                if line:
                    t += float(json.loads(line).get("dt", 0.0))
                    arrivals.append(t)
    d = tempfile.mkdtemp(prefix="daccord-serve-bench-")
    data = make_dataset(d, SimConfig(genome_len=3000, coverage=12,
                                     read_len_mean=600, min_overlap=250,
                                     seed=11), name="sv")
    batch = 64 if backend != "native" else 256
    svc = ConsensusService(ServeConfig(
        workdir=os.path.join(d, "srv"), backend=backend,
        backend_explicit=True, batch=batch, workers=2, flush_lag_s=0.05,
        metrics_snapshot_s=0.0,
        admission=AdmissionConfig(max_queued_jobs=64, tenant_max_queued=64)))
    httpd, port, _t = start_server(svc, "127.0.0.1", 0)
    base = f"http://127.0.0.1:{port}"

    def req(method, path, body=None):
        r = urllib.request.Request(
            base + path, method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(r, timeout=600) as resp:
            return json.loads(resp.read())

    t0 = time.perf_counter()
    ids = []
    for i, at in enumerate(arrivals):
        dt = at - (time.perf_counter() - t0)
        if dt > 0:
            time.sleep(dt)
        st = req("POST", "/v1/jobs",
                 {"db": data["db"], "las": data["las"],
                  "tenant": f"t{i % 2}"})
        ids.append(st["job"])
    rows = []
    for j in ids:
        # result?wait=1 blocks to a terminal state; the status carries the
        # latency decomposition
        urllib.request.urlopen(
            urllib.request.Request(base + f"/v1/jobs/{j}/result?wait=1"),
            timeout=600).read()
        rows.append(req("GET", f"/v1/jobs/{j}"))
    wall = time.perf_counter() - t0
    metrics = req("GET", "/v1/metrics")
    req("POST", "/v1/shutdown")
    httpd.shutdown()
    lat = sorted(r["latency"]["total_s"] for r in rows)

    def q(v, p):
        return round(v[min(int(p * len(v)), len(v) - 1)], 4) if v else None

    windows = sum(r["windows"] for r in rows)
    mixed = sum(int(g.get("mixed_batches", 0))
                for g in metrics["warm"].get("groups", []))
    line = {
        "metric": "serve_job_latency_s",
        "backend": backend, "batch": batch, "jobs": len(rows),
        "arrivals_s": [round(a, 3) for a in arrivals],
        "p50_s": q(lat, 0.50), "p99_s": q(lat, 0.99),
        "max_s": q(lat, 1.0), "wall_s": round(wall, 3),
        "windows": windows,
        "windows_per_sec": round(windows / wall, 1) if wall else None,
        "mixed_batches": mixed,
        "per_job": [{"job": r["job"], "state": r["state"],
                     "windows": r["windows"], **r["latency"]}
                    for r in rows],
        "warm": {k: metrics["warm"][k] for k in ("hits", "misses")},
        # ISSUE 14: the service's demand-weighted saturation verdict +
        # gauges, read from the live /v1/metrics body the bench already
        # fetched — the serve sidecar's bottleneck attribution
        "verdict": metrics.get("verdict"),
        "saturation": {k: (metrics.get("metrics", {}).get("gauges", {})
                           .get(k))
                       for k in ("device_idle_frac", "host_blocked_frac",
                                 "overlap_frac")},
        **_tunnel_staleness(),
    }
    _commit_sidecar("BENCH_SERVE.json", line)
    ev.log("bench_done", wall_s=round(wall, 3))
    return line


def run_router_bench(ev) -> dict:
    """Front-door stage (DACCORD_BENCH_ROUTER=1, ISSUE 16). Two arms:

    **cold-peer TTFR** — the executable-acquisition latency a freshly
    spawned peer pays before its first solve result: WITHOUT the AOT cache
    that is the cold jit compile of the packed ladder program; WITH it, a
    deserialize of the fleet-published executable. Both timed at the
    dispatcher over the same real window batch, under a FRESH jax
    compilation-cache dir (otherwise a prior bench run's persistent XLA
    cache would silently deflate the cold number), with byte-identity of
    the fetched results asserted.

    **p99 during scale-out** — a bursty multi-tenant arrival trace through
    a live ``daccord-router`` fronting one warm peer with a deliberately
    tiny SLO target, so burn goes red and the autoscaler spawns a second
    ``daccord-serve`` subprocess mid-trace (announce-lease discovery +
    shared AOT cache). The sidecar records per-job latency, p99, spill and
    scale tallies — the latency cost of scaling out, measured."""
    import tempfile
    import urllib.request

    import jax

    jax.config.update("jax_platforms", "cpu")
    # -- arm (a): cold-peer TTFR, dispatcher-level -----------------------
    data = build_windows()
    from daccord_tpu.kernels import BatchShape
    from daccord_tpu.kernels.tiers import TierLadder, stream_dispatcher
    from daccord_tpu.kernels.tiers import fetch as t_fetch
    from daccord_tpu.oracle.consensus import ConsensusConfig
    from daccord_tpu.oracle.profile import ErrorProfile
    from daccord_tpu.serve.aotcache import AotCache

    cc_dir = tempfile.mkdtemp(prefix="daccord-router-bench-cc-")
    jax.config.update("jax_compilation_cache_dir", cc_dir)
    prof = ErrorProfile(float(data["p_ins"]), float(data["p_del"]),
                        float(data["p_sub"]))
    ladder = TierLadder.from_config(prof, _bench_consensus_config())
    shape = BatchShape(depth=DEPTH, seg_len=SEG_LEN, wlen=WLEN)
    batch = _make_batch(data, 0, 64, shape)
    # without: a fresh peer's first dispatch = cold jit compile + exec
    cold_fn = stream_dispatcher(ladder, use_pallas=False,
                                pallas_interpret=False)
    t0 = time.perf_counter()
    out_cold = t_fetch(cold_fn(batch))
    ttfr_cold = time.perf_counter() - t0
    # publish to a fresh fleet cache (untimed: the XLA cache above makes
    # this second compile cheap; only its serialized artifact matters)
    aot_dir = tempfile.mkdtemp(prefix="daccord-router-bench-aot-")
    AotCache(aot_dir).dispatcher(ladder, use_pallas=False,
                                 pallas_interpret=False,
                                 fp_prefix="cpu:")(batch)
    # with: a DIFFERENT fresh AotCache instance = the spawned peer's first
    # dispatch — disk load + deserialize + exec, no compile
    warm_fn = AotCache(aot_dir).dispatcher(ladder, use_pallas=False,
                                           pallas_interpret=False,
                                           fp_prefix="cpu:")
    t0 = time.perf_counter()
    out_warm = t_fetch(warm_fn(batch))
    ttfr_warm = time.perf_counter() - t0
    import numpy as _np

    aot_identical = all(
        _np.asarray(out_cold[k]).tobytes() == _np.asarray(out_warm[k]).tobytes()
        for k in ("cons", "cons_len", "solved"))
    ev.log("bench_compile", batch=64, cached=False,
           expected_wall_s=round(ttfr_cold, 3))

    # -- arm (b): p99 through the router during a live scale-out ---------
    from daccord_tpu.serve import (AdmissionConfig, AutoscaleConfig,
                                   Autoscaler, ConsensusService, RouterConfig,
                                   ServeConfig)
    from daccord_tpu.serve.http import start_server
    from daccord_tpu.serve.router import Router, start_router
    from daccord_tpu.sim.synth import SimConfig, make_dataset

    backend = os.environ.get("DACCORD_BENCH_SERVE_BACKEND")
    if not backend:
        try:
            from daccord_tpu.native import available as _nat

            backend = "native" if _nat() else "cpu"
        except Exception:
            backend = "cpu"
    d = tempfile.mkdtemp(prefix="daccord-router-bench-")
    ds = make_dataset(d, SimConfig(genome_len=3000, coverage=12,
                                   read_len_mean=600, min_overlap=250,
                                   seed=11), name="sv")
    peer_dir = os.path.join(d, "fleet")
    sbatch = 64 if backend != "native" else 256
    slo_s = 0.05        # deliberately tiny: every real job burns red
    svc = ConsensusService(ServeConfig(
        workdir=os.path.join(d, "peer0"), backend=backend,
        backend_explicit=True, batch=sbatch, workers=2, flush_lag_s=0.05,
        metrics_snapshot_s=0.0, slo_p99_s=slo_s, slo_window_s=60.0,
        peer_dir=peer_dir,
        admission=AdmissionConfig(max_queued_jobs=64, tenant_max_queued=64)))
    httpd, port, _t = start_server(svc, "127.0.0.1", 0)
    svc.announce(f"http://127.0.0.1:{port}")
    router = Router(RouterConfig(workdir=os.path.join(d, "router"),
                                 peer_dir=peer_dir, poll_s=0.2,
                                 spill_burn=1.0))
    router.autoscaler = Autoscaler(AutoscaleConfig(
        peer_dir=peer_dir, root=os.path.join(d, "autopeers"),
        max_peers=2, min_peers=1, spawn_burn=1.0, sustain_s=0.5,
        cooldown_s=3600.0, idle_ttl_s=0.0, backend=backend, batch=sbatch,
        workers=2, slo_p99_s=slo_s,
        spawn_env={"JAX_PLATFORMS": "cpu"}), router.log)
    rhttpd, rport, _rt = start_router(router)
    base = f"http://127.0.0.1:{rport}"

    def req(method, path, body=None):
        r = urllib.request.Request(
            base + path, method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(r, timeout=600) as resp:
            return json.loads(resp.read())

    deadline = time.time() + 30.0
    while time.time() < deadline:      # router must discover the warm peer
        if req("GET", "/v1/router").get("ready"):
            break
        time.sleep(0.1)
    arrivals = [0.0, 0.1, 0.2, 0.5, 0.8, 1.2, 2.0, 2.2, 2.5, 3.0, 3.5, 4.0]
    t0 = time.perf_counter()
    ids = []
    for i, at in enumerate(arrivals):
        dt = at - (time.perf_counter() - t0)
        if dt > 0:
            time.sleep(dt)
        st = req("POST", "/v1/jobs", {"db": ds["db"], "las": ds["las"],
                                      "tenant": f"t{i % 4}",
                                      "idempotency_key": f"rb{i}"})
        ids.append(st["job"])
    rows = []
    for j in ids:
        urllib.request.urlopen(
            urllib.request.Request(base + f"/v1/jobs/{j}/result?wait=1"),
            timeout=600).read()
        rows.append(req("GET", f"/v1/jobs/{j}"))
    wall = time.perf_counter() - t0
    rstats = req("GET", "/v1/router")
    router.shutdown()
    rhttpd.shutdown()
    svc.shutdown(drain=True)
    httpd.shutdown()
    lat = sorted(r["latency"]["total_s"] for r in rows)

    def q(v, p):
        return round(v[min(int(p * len(v)), len(v) - 1)], 4) if v else None

    line = {
        "metric": "router_scaleout_p99_s",
        "backend": backend, "batch": sbatch, "jobs": len(rows),
        "done": sum(1 for r in rows if r["state"] == "done"),
        "p50_s": q(lat, 0.50), "p99_s": q(lat, 0.99), "max_s": q(lat, 1.0),
        "wall_s": round(wall, 3),
        "routes": rstats["routes"], "spills": rstats["spills"],
        "proxy_errors": rstats["proxy_errors"],
        "peers_final": len(rstats["peers"]),
        "scale": rstats.get("autoscale"),
        # arm (a): the AOT acceptance metric (>= 5x is the ISSUE 16 bar)
        "aot": {"ttfr_cold_s": round(ttfr_cold, 3),
                "ttfr_warm_s": round(ttfr_warm, 3),
                "speedup": round(ttfr_cold / ttfr_warm, 1)
                if ttfr_warm > 0 else None,
                "byte_identical": aot_identical},
        **_tunnel_staleness(),
    }
    _commit_sidecar("BENCH_ROUTER.json", line)
    ev.log("bench_done", wall_s=round(time.perf_counter() - t0, 3))
    return line


def run_serve_soak(root: str | None = None, n_jobs: int = 20,
                   seed: int = 0x5E12, ev=None, backend: str | None = None,
                   timeout_s: float = 900.0,
                   commit_sidecar: bool = True) -> dict:
    """Chaos soak (ISSUE 15): a sustained seeded arrival trace against TWO
    ``daccord-serve`` subprocesses sharing a peer-takeover dir, under a
    deterministic ``serve_crash`` + ``device_lost`` fault storm. Dead
    processes are restarted (replaying their journals); in-flight jobs are
    recovered by replay or peer takeover — the driver only routes around
    dead listeners, it never resubmits work except through idempotency keys.

    Asserts the crash-durability contract at the end (AssertionError = the
    contract broke — the slow test and the soak bench both ride this):

    - every admitted job reached COMMITTED or client-ABORTED exactly once;
    - every committed FASTA is byte-identical to the solo control;
    - no quota charge leaked (all tenant balances zero at the end);
    - no spool dir leaked (every jobs/<id> dir maps to a journaled job).
    """
    import random as _random
    import shutil
    import socket
    import tempfile
    import urllib.error
    import urllib.request

    from daccord_tpu.serve.journal import replay as j_replay
    from daccord_tpu.sim.synth import SimConfig, make_dataset

    if backend is None:
        backend = os.environ.get("DACCORD_BENCH_SERVE_BACKEND")
    if not backend:
        try:
            from daccord_tpu.native import available as _nat

            backend = "native" if _nat() else "cpu"
        except Exception:
            backend = "cpu"
    rng = _random.Random(seed)
    owns_root = root is None
    root = root or tempfile.mkdtemp(prefix="daccord-serve-soak-")
    data = make_dataset(root, SimConfig(genome_len=1500, coverage=10,
                                        read_len_mean=500, min_overlap=200,
                                        seed=5), name="sv")
    # solo control through the same config builder the serve jobs use
    import dataclasses as _dc

    from daccord_tpu.runtime.pipeline import correct_to_fasta
    from daccord_tpu.serve.jobs import JobSpec, build_job_config

    spec = JobSpec.from_json({"db": data["db"], "las": data["las"]}, root)
    ccfg = build_job_config(spec, backend, True, 64, "fused", root, "solo")
    ccfg = _dc.replace(ccfg, native_solver=backend == "native",
                       supervise=True, events_path=None, ledger_path=None,
                       job_tag=None, quarantine_path=None)
    solo = os.path.join(root, "solo.fasta")
    correct_to_fasta(data["db"], data["las"], solo, ccfg)
    with open(solo, "rb") as fh:
        solo_bytes = fh.read()

    peer = os.path.join(root, "peer")
    pkg_root = os.path.dirname(os.path.abspath(
        __import__("daccord_tpu").__file__))
    pkg_root = os.path.dirname(pkg_root)

    # the seeded storm: each incarnation of each server gets its fault spec
    # here — deterministic, so two soak runs crash at the same journal
    # appends and the trajectory compares like-for-like
    storms = {
        "srvA": [f"serve_crash:{rng.randint(5, 12)}",
                 f"serve_crash:{rng.randint(18, 30)}", ""],
        "srvB": [f"device_lost:{rng.randint(2, 4)}"
                 f",serve_crash:{rng.randint(10, 20)}", ""],
    }
    servers = {name: {"workdir": os.path.join(root, name), "proc": None,
                      "port": None, "inc": 0, "crashes": 0}
               for name in ("srvA", "srvB")}

    def spawn(name: str) -> None:
        s = servers[name]
        fault = ""
        sched = storms[name]
        if s["inc"] < len(sched):
            fault = sched[s["inc"]]
        ready = os.path.join(root, f"{name}.ready.{s['inc']}.json")
        argv = [sys.executable, "-m", "daccord_tpu.tools.cli", "serve",
                "--workdir", s["workdir"], "--backend", backend, "-b", "64",
                "--workers", "2", "--port", "0", "--ready-file", ready,
                "--peer-dir", peer, "--lease-ttl-s", "6",
                "--heartbeat-s", "0.5", "--checkpoint-reads", "4",
                "--flush-lag-ms", "20", "--metrics-snapshot-s", "5",
                "--drain-deadline-s", "120"]
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = pkg_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        if fault:
            env["DACCORD_FAULT"] = fault
        else:
            env.pop("DACCORD_FAULT", None)
        log = open(os.path.join(root, f"{name}.{s['inc']}.log"), "wb")
        s["proc"] = subprocess.Popen(argv, env=env, stdout=log, stderr=log)
        s["inc"] += 1
        deadline = time.time() + 120
        while time.time() < deadline:
            if os.path.exists(ready):
                try:
                    s["port"] = json.load(open(ready))["port"]
                    return
                except (OSError, json.JSONDecodeError, ValueError):
                    pass
            if s["proc"].poll() is not None:
                # died during startup (an early serve_crash): restart with
                # the next incarnation's spec
                s["crashes"] += 1
                return spawn(name)
            time.sleep(0.05)
        raise RuntimeError(f"soak: {name} never wrote its ready file")

    def ensure_alive(name: str) -> None:
        s = servers[name]
        if s["proc"] is None or s["proc"].poll() is not None:
            if s["proc"] is not None:
                s["crashes"] += 1
            spawn(name)

    def req(name: str, method: str, path: str, body=None, timeout=60):
        s = servers[name]
        r = urllib.request.Request(
            f"http://127.0.0.1:{s['port']}{path}", method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(r, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read() or b"{}")

    t0 = time.time()
    for name in servers:
        spawn(name)
    # seeded arrival trace; idempotency keys make mid-crash submits safe to
    # retry (an admitted-but-unanswered submit dedupes on the retry)
    arrivals = []
    t = 0.0
    for i in range(n_jobs):
        t += rng.uniform(0.05, 0.35)
        arrivals.append(t)
    abort_idx = {2, n_jobs // 2} if n_jobs >= 6 else set()
    jobs = {}   # idem key -> {"home": name, "job": id, "abort": bool}
    for i, at in enumerate(arrivals):
        dt = at - (time.time() - t0)
        if dt > 0:
            time.sleep(dt)
        name = "srvA" if i % 2 == 0 else "srvB"
        idem = f"soak-{seed}-{i}"
        sub_deadline = time.time() + 180
        while True:
            ensure_alive(name)
            try:
                code, st = req(name, "POST", "/v1/jobs",
                               {"db": data["db"], "las": data["las"],
                                "tenant": f"t{i % 3}",
                                "idempotency_key": idem})
                if code in (200, 201):
                    jobs[idem] = {"home": name, "job": st["job"],
                                  "abort": i in abort_idx}
                    break
            except (urllib.error.URLError, ConnectionError, socket.timeout,
                    OSError):
                pass
            if time.time() > sub_deadline:
                raise RuntimeError(f"soak: submit {idem} never admitted")
            time.sleep(0.2)
        if i in abort_idx:
            try:
                req(name, "DELETE", f"/v1/jobs/{jobs[idem]['job']}")
            except (urllib.error.URLError, ConnectionError, socket.timeout,
                    OSError):
                pass   # the abort may race a crash; the contract check
                       # below accepts committed OR aborted for these

    def terminal(entry) -> str | None:
        """done|aborted|failed when the job is terminal, else None — via
        HTTP when the home server knows it, else the durable manifest (a
        peer may have finished it), else the journals."""
        name, jid = entry["home"], entry["job"]
        try:
            code, st = req(name, "GET", f"/v1/jobs/{jid}", timeout=20)
            if code == 200 and st.get("state") in ("done", "failed",
                                                   "aborted"):
                return st["state"]
            if code == 200:
                return None
        except (urllib.error.URLError, ConnectionError, socket.timeout,
                OSError):
            pass
        jdir = os.path.join(servers[name]["workdir"], "jobs", jid)
        if os.path.exists(os.path.join(jdir, "manifest.json")):
            return "done"
        ents, _ = j_replay(os.path.join(servers[name]["workdir"],
                                        "journal.jsonl"))
        e = ents.get(jid)
        if e is not None and e.terminal:
            return {"committed": "done"}.get(e.state, e.state)
        return None

    poll_deadline = time.time() + timeout_s
    states = {}
    while time.time() < poll_deadline:
        for name in servers:
            ensure_alive(name)
        states = {k: terminal(v) for k, v in jobs.items()}
        if all(states.values()):
            break
        time.sleep(0.5)
    assert all(states.values()), \
        f"soak: jobs never terminal: {[k for k, v in states.items() if not v]}"

    # quota balances BEFORE shutdown: nothing queued, nothing charged
    admissions = {}
    for name in servers:
        ensure_alive(name)
        _, m = req(name, "GET", "/v1/metrics", timeout=60)
        admissions[name] = m["admission"]
    for name, adm in admissions.items():
        for tname, tstat in adm.get("tenants", {}).items():
            assert tstat["queued"] == 0 and tstat["bytes"] == 0, \
                f"soak: leaked quota charge on {name}/{tname}: {tstat}"

    for name in servers:
        try:
            req(name, "POST", "/v1/shutdown", timeout=60)
        except (urllib.error.URLError, ConnectionError, socket.timeout,
                OSError):
            pass
        rc = servers[name]["proc"].wait(timeout=180)
        assert rc == 0, f"soak: {name} final incarnation exited {rc}"

    # ---- the contract ----------------------------------------------------
    # exactly-once: count serve.commit events per GLOBAL job key
    # (<origin-service>.<id>) across every incarnation of every server — a
    # local commit logs the short id (origin = the logging server), a
    # takeover commits under the global key. Real-run commits carry
    # fragments >= 0; recovery re-emissions (replay finalize / manifest
    # found) carry fragments == -1 — the exactly-once form is: AT MOST one
    # real run committed, AT LEAST one commit record total per done job.
    commits: dict[str, int] = {}
    commits_real: dict[str, int] = {}
    recoveries = {"replay_orphans": 0, "takeovers": 0, "replays": 0}
    for name in servers:
        evp = os.path.join(servers[name]["workdir"], "serve.events.jsonl")
        with open(evp) as fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                evk = rec.get("event")
                if evk == "serve.commit":
                    jid = str(rec.get("job", ""))
                    key = jid if "." in jid else f"{name}.{jid}"
                    commits[key] = commits.get(key, 0) + 1
                    if int(rec.get("fragments", 0)) >= 0:
                        commits_real[key] = commits_real.get(key, 0) + 1
                elif evk == "serve.takeover":
                    recoveries["takeovers"] += 1
                elif evk == "serve.replay":
                    recoveries["replays"] += 1
                    recoveries["replay_orphans"] += int(
                        rec.get("orphans", 0))
    n_done = n_aborted = 0
    for idem, entry in jobs.items():
        st = states[idem]
        jid = entry["job"]
        gkey = f"{entry['home']}.{jid}"
        jdir = os.path.join(servers[entry["home"]]["workdir"], "jobs", jid)
        assert st in ("done", "aborted"), \
            f"soak: job {gkey} terminal state {st!r} (never 'failed')"
        if st == "done":
            n_done += 1
            with open(os.path.join(jdir, "out.fasta"), "rb") as fh:
                got = fh.read()
            assert got == solo_bytes, \
                f"soak: job {gkey} FASTA diverged from the solo control"
            assert commits_real.get(gkey, 0) <= 1, \
                f"soak: job {gkey} committed by " \
                f"{commits_real[gkey]} distinct runs"
            assert commits.get(gkey, 0) >= 1, \
                f"soak: done job {gkey} has no commit record"
        else:
            n_aborted += 1
            assert commits.get(gkey, 0) == 0, \
                f"soak: aborted job {gkey} has {commits[gkey]} commits"
            assert not os.path.exists(os.path.join(jdir, "out.fasta")), \
                f"soak: aborted job {gkey} left a committed FASTA"
    # spool-dir leak check: every jobs/<id> dir maps to a journaled admit
    for name in servers:
        w = servers[name]["workdir"]
        ents, _ = j_replay(os.path.join(w, "journal.jsonl"))
        journaled = {jid.rsplit(".", 1)[-1] for jid in ents}
        # terminal entries without an idempotency key compact away, so the
        # event stream is the complete admit record
        with open(os.path.join(w, "serve.events.jsonl")) as fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if rec.get("event") == "serve.journal" \
                        and rec.get("rec") == "admitted":
                    journaled.add(str(rec.get("job", "")).rsplit(".", 1)[-1])
        dirs = set(os.listdir(os.path.join(w, "jobs")))
        strays = dirs - journaled
        assert not strays, f"soak: leaked spool dirs on {name}: {strays}"
        tmp_litter = [p for p in os.listdir(w) if ".tmp." in p]
        assert not tmp_litter, f"soak: tmp litter on {name}: {tmp_litter}"
    crashes = sum(s["crashes"] for s in servers.values())
    line = {
        "metric": "serve_soak", "backend": backend, "seed": seed,
        "jobs": n_jobs, "done": n_done, "aborted": n_aborted,
        "crashes": crashes,
        "incarnations": {n: s["inc"] for n, s in servers.items()},
        "storm": storms,
        **recoveries,
        "commit_events": sum(commits.values()),
        "wall_s": round(time.time() - t0, 3),
        "parity": True, "leaks": 0,
        **_tunnel_staleness(),
    }
    if ev is not None:
        ev.log("bench_done", wall_s=line["wall_s"])
    if commit_sidecar:
        _commit_sidecar("BENCH_SERVE_SOAK.json", line)
    if owns_root:
        shutil.rmtree(root, ignore_errors=True)
    return line


def run_disk_soak(root: str | None = None, n_jobs: int = 8,
                  seed: int = 0xD15C, ev=None, backend: str | None = None,
                  timeout_s: float = 900.0,
                  commit_sidecar: bool = True) -> dict:
    """Disk-chaos soak (ISSUE 17): the full-disk matrix against TWO live
    ``daccord-serve`` peers. One peer's journal domain eats a consecutive
    ``io_enospc@journal`` burst (every append in the window is refused —
    the disk-pressure governor must latch, 507 new work, and release once
    the volume proves writable); the other's lease domain eats scattered
    transient ``io_eio@lease`` (heartbeats must ride the bounded grace,
    never demote healthy runs). Unlike the crash soak, NOBODY dies — the
    whole point is that a disk saying no produces structured refusals and
    resumable state, not corpses.

    Asserts the graceful-degradation contract (AssertionError = broken):

    - no server process exits during the storm (rc 0 only at shutdown);
    - >= 1 structured 507 refusal with ``reason: disk_pressure``;
    - every admitted job completes DONE with a byte-identical FASTA and
      exactly-once commit semantics (events, not the refused journal);
    - zero lease demotions / takeovers (transient EIO stays transient);
    - the pressure latch is observed entering AND the fleet fully
      recovers: pressure clears, a post-storm submit admits and commits;
    - zero ``.tmp`` litter, zero stray spool dirs, zero leaked quota.
    """
    import random as _random
    import shutil
    import socket
    import tempfile
    import urllib.error
    import urllib.request

    from daccord_tpu.sim.synth import SimConfig, make_dataset

    if backend is None:
        backend = os.environ.get("DACCORD_BENCH_SERVE_BACKEND")
    if not backend:
        try:
            from daccord_tpu.native import available as _nat

            backend = "native" if _nat() else "cpu"
        except Exception:
            backend = "cpu"
    rng = _random.Random(seed)
    owns_root = root is None
    root = root or tempfile.mkdtemp(prefix="daccord-disk-soak-")
    data = make_dataset(root, SimConfig(genome_len=1500, coverage=10,
                                        read_len_mean=500, min_overlap=200,
                                        seed=5), name="sv")
    import dataclasses as _dc

    from daccord_tpu.runtime.pipeline import correct_to_fasta
    from daccord_tpu.serve.jobs import JobSpec, build_job_config

    spec = JobSpec.from_json({"db": data["db"], "las": data["las"]}, root)
    ccfg = build_job_config(spec, backend, True, 64, "fused", root, "solo")
    ccfg = _dc.replace(ccfg, native_solver=backend == "native",
                       supervise=True, events_path=None, ledger_path=None,
                       job_tag=None, quarantine_path=None)
    solo = os.path.join(root, "solo.fasta")
    correct_to_fasta(data["db"], data["las"], solo, ccfg)
    with open(solo, "rb") as fh:
        solo_bytes = fh.read()

    peer = os.path.join(root, "peer")
    pkg_root = os.path.dirname(os.path.abspath(
        __import__("daccord_tpu").__file__))
    pkg_root = os.path.dirname(pkg_root)

    # the storm: a CONSECUTIVE journal-refusal window on srvA (appends
    # 3..N all fail — the latch re-enters on every append until the burst
    # is spent), scattered transient lease EIO on srvB (hits land on
    # read/renew heartbeat ops; the grace must absorb them). Seed-jittered
    # burst width so two soak seeds stress different exhaustion points.
    burst_hi = 22 + rng.randint(0, 8)
    storms = {
        "srvA": ",".join(f"io_enospc:{i}@journal"
                         for i in range(3, burst_hi)) + ",io_slow:2@journal",
        "srvB": ",".join(f"io_eio:{i}@lease"
                         for i in (4, 5, 9, 10, 15)),
    }
    servers = {name: {"workdir": os.path.join(root, name), "proc": None,
                      "port": None}
               for name in ("srvA", "srvB")}

    def spawn(name: str) -> None:
        s = servers[name]
        ready = os.path.join(root, f"{name}.ready.json")
        argv = [sys.executable, "-m", "daccord_tpu.tools.cli", "serve",
                "--workdir", s["workdir"], "--backend", backend, "-b", "64",
                "--workers", "2", "--port", "0", "--ready-file", ready,
                "--peer-dir", peer, "--lease-ttl-s", "6",
                "--heartbeat-s", "0.5", "--checkpoint-reads", "4",
                "--flush-lag-ms", "20", "--metrics-snapshot-s", "5",
                "--drain-deadline-s", "120"]
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = pkg_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        env["DACCORD_FAULT"] = storms[name]
        log = open(os.path.join(root, f"{name}.log"), "wb")
        s["proc"] = subprocess.Popen(argv, env=env, stdout=log, stderr=log)
        deadline = time.time() + 120
        while time.time() < deadline:
            if os.path.exists(ready):
                try:
                    s["port"] = json.load(open(ready))["port"]
                    return
                except (OSError, json.JSONDecodeError, ValueError):
                    pass
            assert s["proc"].poll() is None, \
                f"disk soak: {name} died during startup " \
                f"(rc {s['proc'].poll()})"
            time.sleep(0.05)
        raise RuntimeError(f"disk soak: {name} never wrote its ready file")

    def assert_alive() -> None:
        for name, s in servers.items():
            rc = s["proc"].poll()
            assert rc is None, \
                f"disk soak: {name} DIED under the storage storm (rc {rc})" \
                f" — a full disk must degrade, never kill"

    def req(name: str, method: str, path: str, body=None, timeout=60):
        s = servers[name]
        r = urllib.request.Request(
            f"http://127.0.0.1:{s['port']}{path}", method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(r, timeout=timeout) as resp:
                return resp.status, json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read() or b"{}")
            except (json.JSONDecodeError, OSError, ValueError):
                payload = {}
            return e.code, payload

    t0 = time.time()
    for name in servers:
        spawn(name)

    refusals_507 = 0
    refusals_other = 0
    refusal_reasons: set[str] = set()
    jobs = {}   # idem -> {"home": name, "job": id}

    def submit(name: str, idem: str, patient: bool) -> bool:
        """One admission attempt (``patient`` retries through refusals);
        refusal codes are tallied, an admit lands in ``jobs``."""
        nonlocal refusals_507, refusals_other
        sub_deadline = time.time() + 180
        while True:
            assert_alive()
            try:
                code, st = req(name, "POST", "/v1/jobs",
                               {"db": data["db"], "las": data["las"],
                                "tenant": f"t{len(jobs) % 3}",
                                "idempotency_key": idem})
            except (urllib.error.URLError, ConnectionError, socket.timeout,
                    OSError):
                code, st = 0, {}
            if code in (200, 201):
                jobs[idem] = {"home": name, "job": st["job"]}
                return True
            if code == 507:
                refusals_507 += 1
                refusal_reasons.add(str(st.get("reason")))
            elif code in (429, 503):
                refusals_other += 1
            if not patient or time.time() > sub_deadline:
                return False
            time.sleep(0.2)

    # seeded arrival trace; each srvA admit is chased by one impatient
    # probe — the admit's journal append fails inside the burst window and
    # latches the governor, so a submit landing right behind it meets the
    # 507 while the latch is hot
    for i in range(n_jobs):
        time.sleep(rng.uniform(0.03, 0.25))
        name = "srvA" if i % 2 == 0 else "srvB"
        assert submit(name, f"disk-{seed}-{i}", patient=True), \
            f"disk soak: job {i} never admitted"
        if name == "srvA":
            submit("srvA", f"disk-{seed}-probe-{i}", patient=False)
    # the burst outlives the arrival trace: hammer until the 507 is seen
    # (every admitted probe burns more of the burst, so this terminates)
    probes = 0
    while refusals_507 == 0 and probes < 60:
        probes += 1
        submit("srvA", f"disk-{seed}-extra-{probes}", patient=False)
        time.sleep(0.05)
    assert refusals_507 >= 1, \
        "disk soak: the ENOSPC burst never produced a 507 refusal"
    assert "disk_pressure" in refusal_reasons, \
        f"disk soak: 507s lacked the disk_pressure reason: {refusal_reasons}"

    def poll_done() -> dict:
        states = {}
        for idem, entry in jobs.items():
            try:
                code, st = req(entry["home"], "GET",
                               f"/v1/jobs/{entry['job']}", timeout=20)
            except (urllib.error.URLError, ConnectionError, socket.timeout,
                    OSError):
                code, st = 0, {}
            states[idem] = st.get("state") if code == 200 else None
        return states

    poll_deadline = time.time() + timeout_s
    states = {}
    while time.time() < poll_deadline:
        assert_alive()
        states = poll_done()
        if all(s in ("done", "failed", "aborted") for s in states.values()):
            break
        time.sleep(0.5)
    bad = {k: v for k, v in states.items()
           if v not in ("done",)}
    assert not bad, f"disk soak: jobs not DONE under the storm: {bad}"

    # recovery: the latch must clear on its own (the probe writes to the
    # REAL, healthy disk; no appends are failing once the burst is spent)
    clear_deadline = time.time() + 60
    pressure = True
    while time.time() < clear_deadline:
        try:
            _, m = req("srvA", "GET", "/v1/metrics", timeout=20)
            pressure = bool(m["admission"].get("disk_pressure"))
        except (urllib.error.URLError, ConnectionError, socket.timeout,
                OSError, KeyError):
            pressure = True
        if not pressure:
            break
        time.sleep(0.5)
    assert not pressure, \
        "disk soak: disk_pressure never cleared after the storm"
    assert submit("srvA", f"disk-{seed}-recovery", patient=True), \
        "disk soak: post-storm recovery submit never admitted"
    rec_deadline = time.time() + 120
    while time.time() < rec_deadline:
        st = poll_done().get(f"disk-{seed}-recovery")
        if st == "done":
            break
        assert st in (None, "queued", "running", "done"), \
            f"disk soak: recovery job ended {st!r}"
        time.sleep(0.5)

    states = poll_done()
    assert all(v == "done" for v in states.values()), \
        f"disk soak: non-done terminal states: {states}"

    # quota balances: refusals and completions alike must leave no charge
    for name in servers:
        _, m = req(name, "GET", "/v1/metrics", timeout=60)
        for tname, tstat in m["admission"].get("tenants", {}).items():
            assert tstat["queued"] == 0 and tstat["bytes"] == 0, \
                f"disk soak: leaked quota on {name}/{tname}: {tstat}"

    assert_alive()
    for name in servers:
        try:
            req(name, "POST", "/v1/shutdown", timeout=60)
        except (urllib.error.URLError, ConnectionError, socket.timeout,
                OSError):
            pass
        rc = servers[name]["proc"].wait(timeout=180)
        assert rc == 0, f"disk soak: {name} exited {rc} at shutdown"

    # ---- the contract, from the durable record -------------------------
    commits: dict[str, int] = {}
    commits_real: dict[str, int] = {}
    counts = {"io_fault_journal": 0, "io_fault_lease": 0,
              "pressure_enter": 0, "pressure_clear": 0,
              "takeovers": 0, "demotions": 0, "interrupted": 0}
    for name in servers:
        evp = os.path.join(servers[name]["workdir"], "serve.events.jsonl")
        with open(evp) as fh:
            for raw in fh:
                try:
                    rec = json.loads(raw)
                except json.JSONDecodeError:
                    continue
                evk = rec.get("event")
                if evk == "serve.commit":
                    jid = str(rec.get("job", ""))
                    key = jid if "." in jid else f"{name}.{jid}"
                    commits[key] = commits.get(key, 0) + 1
                    if int(rec.get("fragments", 0)) >= 0:
                        commits_real[key] = commits_real.get(key, 0) + 1
                elif evk == "io.fault":
                    dom = rec.get("domain")
                    if dom == "journal":
                        counts["io_fault_journal"] += 1
                    elif dom == "lease":
                        counts["io_fault_lease"] += 1
                elif evk == "disk.pressure":
                    if rec.get("level") == "enter":
                        counts["pressure_enter"] += 1
                    elif rec.get("level") == "clear":
                        counts["pressure_clear"] += 1
                elif evk == "serve.takeover":
                    counts["takeovers"] += 1
                elif evk == "serve.journal":
                    if rec.get("rec") == "demoted":
                        counts["demotions"] += 1
                    elif rec.get("rec") == "interrupted":
                        counts["interrupted"] += 1
    assert counts["io_fault_journal"] >= 1, \
        "disk soak: no journal io.fault ever surfaced"
    assert counts["pressure_enter"] >= 1 and counts["pressure_clear"] >= 1, \
        f"disk soak: latch never cycled: {counts}"
    assert counts["io_fault_lease"] >= 1, \
        "disk soak: the lease EIO storm never landed"
    assert counts["takeovers"] == 0 and counts["demotions"] == 0, \
        f"disk soak: transient faults caused demotion/takeover: {counts}"
    for idem, entry in jobs.items():
        gkey = f"{entry['home']}.{entry['job']}"
        jdir = os.path.join(servers[entry["home"]]["workdir"], "jobs",
                            entry["job"])
        with open(os.path.join(jdir, "out.fasta"), "rb") as fh:
            got = fh.read()
        assert got == solo_bytes, \
            f"disk soak: job {gkey} FASTA diverged from the solo control"
        assert commits_real.get(gkey, 0) <= 1, \
            f"disk soak: job {gkey} committed by {commits_real[gkey]} runs"
        assert commits.get(gkey, 0) >= 1, \
            f"disk soak: done job {gkey} has no commit record"

    # litter: a refused disk must strand nothing — no .tmp anywhere under
    # the workdirs, no spool dir the driver didn't submit
    known = {e["job"] for e in jobs.values()}
    for name in servers:
        w = servers[name]["workdir"]
        tmp_litter = []
        for dirpath, _dirs, files in os.walk(w):
            tmp_litter += [os.path.join(dirpath, f) for f in files
                           if ".tmp." in f]
        assert not tmp_litter, f"disk soak: tmp litter on {name}: {tmp_litter}"
        strays = set(os.listdir(os.path.join(w, "jobs"))) - known
        assert not strays, f"disk soak: stray spool dirs on {name}: {strays}"

    line = {
        "metric": "disk_soak", "chaos": True, "backend": backend,
        "seed": seed, "jobs": len(jobs), "done": len(jobs),
        "refusals_507": refusals_507, "refusals_other": refusals_other,
        "storm": storms,
        **counts,
        "wall_s": round(time.time() - t0, 3),
        "parity": True, "leaks": 0, "recovered": True,
        **_tunnel_staleness(),
    }
    if ev is not None:
        ev.log("bench_done", wall_s=line["wall_s"])
    if commit_sidecar:
        _commit_sidecar("BENCH_DISK.json", line)
    if owns_root:
        shutil.rmtree(root, ignore_errors=True)
    return line


def run_net_soak(root: str | None = None, n_jobs: int = 6,
                 seed: int = 0x4E70, ev=None, backend: str | None = None,
                 timeout_s: float = 900.0,
                 commit_sidecar: bool = True) -> dict:
    """Network-chaos soak (ISSUE 18): a live ``daccord-router`` fronting
    TWO healthy ``daccord-serve`` subprocesses while the NETWORK between
    them misbehaves. The router runs in-process so the injected ``net_*``
    matrix (``runtime/faults.py``) fires inside its ``serve/netio`` choke
    point — the servers themselves are never faulted; the wire is.

    Three storms, in sequence:

    1. a ``net_reset`` burst on the submit domain — bounded idempotent
       retries (client keys) must absorb it with exactly-once admission;
    2. ``net_torn`` + ``net_hang`` + ``net_slow`` on the stream domain —
       a torn proxied stream is detected via the byte-count trailer and
       surfaces as a tear the client retries, never a short FASTA;
    3. a full healthz partition of srvB (SIGSTOP: the host answers TCP,
       the process says nothing) while its announce lease stays fresh —
       the router must mark it PARTITIONED (not dead), tenants spill to
       srvA, the autoscaler (which owns both peers here) must not drain
       or reap it, and job takeover must not fire.

    Asserts the network-resilience contract (AssertionError = broken):

    - every admitted job commits exactly once fleet-wide, with streamed
      bytes identical to the solo control;
    - the circuit breaker is observed OPEN and RE-CLOSED;
    - the partition window begins AND ends, with zero ``scale.drain`` /
      ``scale.reap`` inside any window and zero takeovers ever;
    - a post-storm submit + clean stream fetch completes (recovery);
    - both peers exit 0 at shutdown (the network was the only enemy).
    """
    import http.client as _http_client
    import random as _random
    import shutil
    import signal
    import socket
    import tempfile
    import urllib.error
    import urllib.request

    from daccord_tpu.runtime.faults import FaultPlan
    from daccord_tpu.serve import AutoscaleConfig, Autoscaler, RouterConfig
    from daccord_tpu.serve import netio
    from daccord_tpu.serve.router import Router, start_router
    from daccord_tpu.sim.synth import SimConfig, make_dataset

    if backend is None:
        backend = os.environ.get("DACCORD_BENCH_SERVE_BACKEND")
    if not backend:
        try:
            from daccord_tpu.native import available as _nat

            backend = "native" if _nat() else "cpu"
        except Exception:
            backend = "cpu"
    rng = _random.Random(seed)
    owns_root = root is None
    root = root or tempfile.mkdtemp(prefix="daccord-net-soak-")
    data = make_dataset(root, SimConfig(genome_len=1500, coverage=10,
                                        read_len_mean=500, min_overlap=200,
                                        seed=5), name="sv")
    import dataclasses as _dc

    from daccord_tpu.runtime.pipeline import correct_to_fasta
    from daccord_tpu.serve.jobs import JobSpec, build_job_config

    spec = JobSpec.from_json({"db": data["db"], "las": data["las"]}, root)
    ccfg = build_job_config(spec, backend, True, 64, "fused", root, "solo")
    ccfg = _dc.replace(ccfg, native_solver=backend == "native",
                       supervise=True, events_path=None, ledger_path=None,
                       job_tag=None, quarantine_path=None)
    solo = os.path.join(root, "solo.fasta")
    correct_to_fasta(data["db"], data["las"], solo, ccfg)
    with open(solo, "rb") as fh:
        solo_bytes = fh.read()

    peer = os.path.join(root, "peer")
    pkg_root = os.path.dirname(os.path.abspath(
        __import__("daccord_tpu").__file__))
    pkg_root = os.path.dirname(pkg_root)
    servers = {name: {"workdir": os.path.join(root, name), "proc": None,
                      "port": None}
               for name in ("srvA", "srvB")}

    def spawn(name: str) -> None:
        s = servers[name]
        ready = os.path.join(root, f"{name}.ready.json")
        argv = [sys.executable, "-m", "daccord_tpu.tools.cli", "serve",
                "--workdir", s["workdir"], "--backend", backend, "-b", "64",
                "--workers", "2", "--port", "0", "--ready-file", ready,
                "--peer-dir", peer, "--lease-ttl-s", "6",
                "--heartbeat-s", "0.5", "--flush-lag-ms", "20",
                "--metrics-snapshot-s", "5", "--drain-deadline-s", "120"]
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = pkg_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        # the servers are HEALTHY — only the wire (the in-process router's
        # netio layer) is stormed
        env.pop("DACCORD_FAULT", None)
        log = open(os.path.join(root, f"{name}.log"), "wb")
        s["proc"] = subprocess.Popen(argv, env=env, stdout=log, stderr=log)
        deadline = time.time() + 120
        while time.time() < deadline:
            if os.path.exists(ready):
                try:
                    s["port"] = json.load(open(ready))["port"]
                    return
                except (OSError, json.JSONDecodeError, ValueError):
                    pass
            assert s["proc"].poll() is None, \
                f"net soak: {name} died during startup " \
                f"(rc {s['proc'].poll()})"
            time.sleep(0.05)
        raise RuntimeError(f"net soak: {name} never wrote its ready file")

    t0 = time.time()
    for name in servers:
        spawn(name)

    # the front door: in-process, storm-injected. Tight healthz deadline +
    # short breaker cooldown keep the chaos phases brisk; the huge router
    # lease TTL keeps a SIGSTOPped peer's announce FRESH for the whole
    # partition window (the peer cannot renew while frozen).
    router = Router(RouterConfig(
        workdir=os.path.join(root, "router"), peer_dir=peer, poll_s=0.3,
        lease_ttl_s=600.0, healthz_timeout_s=1.0, probe_timeout_s=5.0,
        breaker_fails=3, breaker_open_s=2.0, net_retries=2))
    router.autoscaler = Autoscaler(AutoscaleConfig(
        peer_dir=peer, root=os.path.join(root, "autopeers"),
        max_peers=2, min_peers=2, idle_ttl_s=1.0, cooldown_s=3600.0,
        backend=backend, spawn_env={"JAX_PLATFORMS": "cpu"}), router.log)
    for name, s in servers.items():
        # the autoscaler OWNS both peers: its idle-drain sweep runs every
        # tick, so the partition-safety guard is exercised for real
        # (min_peers=2 blocks any legitimate drain)
        router.autoscaler.adopt(name, s["proc"], s["workdir"])
    rhttpd, rport, _rt = start_router(router)
    base = f"http://127.0.0.1:{rport}"

    def req(method: str, path: str, body=None, timeout=60,
            port: int | None = None):
        url = (f"http://127.0.0.1:{port}{path}" if port is not None
               else base + path)
        r = urllib.request.Request(
            url, method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(r, timeout=timeout) as resp:
                return resp.status, json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read() or b"{}")
            except (json.JSONDecodeError, OSError, ValueError):
                payload = {}
            return e.code, payload

    def assert_alive() -> None:
        for name, s in servers.items():
            rc = s["proc"].poll()
            assert rc is None, \
                f"net soak: {name} DIED (rc {rc}) — the network was the " \
                f"only thing being stormed"

    def peer_row(name: str) -> dict:
        try:
            _, st = req("GET", "/v1/router", timeout=20)
        except (urllib.error.URLError, ConnectionError, socket.timeout,
                OSError):
            return {}
        return {p["name"]: p for p in st.get("peers", [])}.get(name, {})

    deadline = time.time() + 60
    while time.time() < deadline:
        try:
            _, st = req("GET", "/v1/router", timeout=20)
        except (urllib.error.URLError, ConnectionError, socket.timeout,
                OSError):
            st = {}
        if st.get("ready") and \
                sum(1 for p in st.get("peers", []) if p.get("alive")) == 2:
            break
        time.sleep(0.1)
    else:
        raise RuntimeError("net soak: router never saw both peers alive")

    jobs = {}            # idem -> job id (router-assigned home)
    submit_retries = 0
    stream_retries = 0

    def submit(idem: str, deadline_s: float = 180.0) -> bool:
        """Patient admission through the router: retryable 502s (transport
        failure after the netio budget) and 503s are the client's to
        retry; the idempotency key carries exactly-once across them."""
        nonlocal submit_retries
        sub_deadline = time.time() + deadline_s
        while True:
            assert_alive()
            try:
                code, st = req("POST", "/v1/jobs",
                               {"db": data["db"], "las": data["las"],
                                "tenant": f"t{len(jobs) % 3}",
                                "idempotency_key": idem})
            except (urllib.error.URLError, ConnectionError, socket.timeout,
                    OSError):
                code, st = 0, {}
            if code in (200, 201):
                jobs[idem] = st["job"]
                return True
            submit_retries += 1
            if time.time() > sub_deadline:
                return False
            time.sleep(0.2)

    def wait_done(idems, deadline_s: float = None) -> None:
        poll_deadline = time.time() + (deadline_s or timeout_s)
        states = {}
        while time.time() < poll_deadline:
            assert_alive()
            states = {}
            for idem in idems:
                try:
                    code, st = req("GET", f"/v1/jobs/{jobs[idem]}",
                                   timeout=20)
                except (urllib.error.URLError, ConnectionError,
                        socket.timeout, OSError):
                    code, st = 0, {}
                states[idem] = st.get("state") if code == 200 else None
            if all(s in ("done", "failed", "aborted")
                   for s in states.values()):
                break
            time.sleep(0.3)
        bad = {k: v for k, v in states.items() if v != "done"}
        assert not bad, f"net soak: jobs not DONE: {bad}"

    def fetch_stream(idem: str, deadline_s: float = 120.0) -> bytes:
        """Streamed FASTA through the router's verified proxy. A torn
        stream surfaces to THIS client as a chunked-framing failure (the
        router never sends the terminal chunk past a tear) — detected and
        retried, never returned short."""
        nonlocal stream_retries
        f_deadline = time.time() + deadline_s
        while True:
            try:
                r = urllib.request.Request(
                    base + f"/v1/jobs/{jobs[idem]}/stream")
                with urllib.request.urlopen(r, timeout=60) as resp:
                    return resp.read()
            except (urllib.error.URLError, _http_client.HTTPException,
                    ConnectionError, socket.timeout, OSError):
                stream_retries += 1
                assert time.time() < f_deadline, \
                    f"net soak: stream fetch for {idem} never recovered"
                time.sleep(0.2)

    try:
        # ---- storm 1: reset burst on the submit domain -----------------
        storms = {
            "submit": ",".join(f"net_reset:{i}@submit" for i in
                               range(1, 6)),
            "stream": "net_torn:500@stream,net_hang:2@stream,"
                      "net_slow:120@stream",
        }
        netio.install_faults(FaultPlan.parse(storms["submit"]))
        for i in range(n_jobs):
            time.sleep(rng.uniform(0.02, 0.15))
            assert submit(f"net-{seed}-{i}"), \
                f"net soak: job {i} never admitted through the reset storm"
        wait_done([f"net-{seed}-{i}" for i in range(n_jobs)])

        # ---- storm 2: torn + hung + slow streams -----------------------
        netio.install_faults(FaultPlan.parse(storms["stream"]))
        for i in range(n_jobs):
            got = fetch_stream(f"net-{seed}-{i}")
            assert got == solo_bytes, \
                f"net soak: streamed FASTA for job {i} diverged from the " \
                f"solo control ({len(got)} vs {len(solo_bytes)} bytes)"
        assert stream_retries >= 1, \
            "net soak: the stream storm never forced a client retry"
        netio.install_faults(None)

        # ---- storm 3: asymmetric partition of srvB ---------------------
        os.kill(servers["srvB"]["proc"].pid, signal.SIGSTOP)
        t_part = time.time()
        part_deadline = time.time() + 30
        row = {}
        while time.time() < part_deadline:
            row = peer_row("srvB")
            if row.get("partitioned"):
                break
            time.sleep(0.2)
        assert row.get("partitioned"), \
            f"net soak: frozen srvB never marked PARTITIONED: {row}"
        assert row.get("lease_age_s", -1) >= 0, \
            "net soak: partitioned srvB lost its announce lease age"
        breaker_during = row.get("breaker")
        # the fleet must keep serving THROUGH the partition
        assert submit(f"net-{seed}-window"), \
            "net soak: submit during the partition window never admitted"
        wait_done([f"net-{seed}-window"], deadline_s=300)
        # hold the window until the breaker has provably opened (poll
        # cadence x breaker_fails bounds this to a few seconds)
        brk_deadline = time.time() + 30
        while time.time() < brk_deadline:
            breaker_during = peer_row("srvB").get("breaker")
            if breaker_during in ("open", "half-open"):
                break
            time.sleep(0.2)
        assert breaker_during in ("open", "half-open"), \
            f"net soak: srvB breaker never opened under the partition " \
            f"({breaker_during})"
        os.kill(servers["srvB"]["proc"].pid, signal.SIGCONT)
        heal_deadline = time.time() + 60
        while time.time() < heal_deadline:
            row = peer_row("srvB")
            if row.get("alive") and not row.get("partitioned") and \
                    row.get("breaker") == "closed":
                break
            time.sleep(0.2)
        assert row.get("alive") and not row.get("partitioned"), \
            f"net soak: srvB never healed after SIGCONT: {row}"
        assert row.get("breaker") == "closed", \
            f"net soak: srvB breaker never re-closed: {row}"
        window_s = time.time() - t_part

        # reap safety: the autoscaler owned an idle, partitioned,
        # TTL-expired peer the whole window and must have touched nothing
        ac = dict(router.autoscaler.counters)
        assert ac["drains"] == 0 and ac["reaps"] == 0, \
            f"net soak: autoscaler drained/reaped during the storm: {ac}"

        # ---- recovery: clean submit + clean verified stream ------------
        assert submit(f"net-{seed}-recovery"), \
            "net soak: post-storm recovery submit never admitted"
        wait_done([f"net-{seed}-recovery"], deadline_s=300)
        assert fetch_stream(f"net-{seed}-recovery") == solo_bytes, \
            "net soak: post-storm streamed FASTA diverged"
    finally:
        netio.install_faults(None)
        try:
            os.kill(servers["srvB"]["proc"].pid, signal.SIGCONT)
        except (OSError, ProcessLookupError):
            pass

    # teardown: hand the peers back (autoscaler.shutdown must not SIGTERM
    # what we stop gracefully), stop the poll loop BEFORE the peers die
    # (so their exit never reads as one more partition), then drain them
    try:
        _, rst = req("GET", "/v1/router", timeout=20)
    except (urllib.error.URLError, ConnectionError, socket.timeout, OSError):
        rst = {}
    jmap = rst.get("jobs", {})        # job id -> home peer (ids are
    for name in servers:              # per-peer, so commits key on both)
        router.autoscaler.disown(name)
    router.shutdown()
    rhttpd.shutdown()
    assert_alive()
    for name, s in servers.items():
        try:
            req("POST", "/v1/shutdown", body={}, port=s["port"])
        except (urllib.error.URLError, ConnectionError, socket.timeout,
                OSError):
            pass
        rc = s["proc"].wait(timeout=180)
        assert rc == 0, f"net soak: {name} exited {rc} at shutdown"

    # ---- the contract, from the durable record -------------------------
    counts = {"net_fault_reset": 0, "net_fault_torn": 0, "net_fault_hang": 0,
              "breaker_open": 0, "breaker_closed": 0,
              "partition_begin": 0, "partition_end": 0,
              "drain_or_reap_in_partition": 0}
    open_windows: set = set()
    with open(os.path.join(root, "router", "router.events.jsonl")) as fh:
        for raw in fh:
            try:
                rec = json.loads(raw)
            except json.JSONDecodeError:
                continue
            evk = rec.get("event")
            if evk == "net.fault":
                key = "net_fault_" + str(rec.get("kind", ""))[4:]
                if key in counts:
                    counts[key] += 1
            elif evk == "router.breaker":
                if rec.get("state") == "open":
                    counts["breaker_open"] += 1
                elif rec.get("state") == "closed":
                    counts["breaker_closed"] += 1
            elif evk == "router.partition":
                if rec.get("state") == "begin":
                    counts["partition_begin"] += 1
                    open_windows.add(rec.get("peer"))
                else:
                    counts["partition_end"] += 1
                    open_windows.discard(rec.get("peer"))
            elif evk in ("scale.drain", "scale.reap") and open_windows:
                counts["drain_or_reap_in_partition"] += 1
    assert counts["net_fault_reset"] >= 5, \
        f"net soak: the reset storm never fully landed: {counts}"
    assert counts["net_fault_torn"] >= 1 and counts["net_fault_hang"] >= 1, \
        f"net soak: the stream storm never fully landed: {counts}"
    assert counts["breaker_open"] >= 1 and counts["breaker_closed"] >= 1, \
        f"net soak: breaker open AND re-close not both observed: {counts}"
    assert counts["partition_begin"] >= 1 and \
        counts["partition_end"] >= 1 and not open_windows, \
        f"net soak: partition window never cycled: {counts} {open_windows}"
    assert counts["drain_or_reap_in_partition"] == 0, \
        f"net soak: the autoscaler killed cut-off hardware: {counts}"

    commits: dict[str, int] = {}
    takeovers = 0
    for name, s in servers.items():
        evp = os.path.join(s["workdir"], "serve.events.jsonl")
        with open(evp) as fh:
            for raw in fh:
                try:
                    rec = json.loads(raw)
                except json.JSONDecodeError:
                    continue
                evk = rec.get("event")
                if evk == "serve.commit":
                    jid = str(rec.get("job", ""))
                    key = jid if "." in jid else f"{name}.{jid}"
                    commits[key] = commits.get(key, 0) + 1
                elif evk == "serve.takeover":
                    takeovers += 1
    assert takeovers == 0, \
        f"net soak: a partition caused {takeovers} false takeover(s)"
    for idem, jid in jobs.items():
        home = jmap.get(jid)
        assert home in servers, \
            f"net soak: job {idem} ({jid}) has no router home: {jmap}"
        key = f"{home}.{jid}"
        assert commits.get(key, 0) == 1, \
            f"net soak: job {idem} ({key}) committed " \
            f"{commits.get(key, 0)} times — exactly-once broke"

    line = {
        "metric": "net_soak", "chaos": True, "backend": backend,
        "seed": seed, "jobs": len(jobs), "done": len(jobs),
        "storm": storms,
        "submit_retries": submit_retries, "stream_retries": stream_retries,
        **counts,
        "breaker_during_partition": breaker_during,
        "partition_window_s": round(window_s, 3),
        "takeovers": takeovers, "drains": 0, "reaps": 0,
        "wall_s": round(time.time() - t0, 3),
        "parity": True, "recovered": True,
        **_tunnel_staleness(),
    }
    if ev is not None:
        ev.log("bench_done", wall_s=line["wall_s"])
    if commit_sidecar:
        _commit_sidecar("BENCH_NET.json", line)
    if owns_root:
        shutil.rmtree(root, ignore_errors=True)
    return line


def run_sdc_soak(ev=None, root: str | None = None,
                 commit_sidecar: bool = True) -> dict:
    """Silent-data-corruption soak (ISSUE 20): one seeded dataset, four
    mesh-8 runs. (1) audit off — golden bytes and the unaudited wall;
    (2) an `sdc:*@K` storm — one member silently corrupts every batch,
    and the asserts ARE the stage: detection, culprit attribution from
    the durable event stream alone, trust quarantine through the
    partial-mesh shrink rung, registry persistence, and byte-parity of
    the final output against the golden run; (3)+(4) twin warm-cache
    controls, audit OFF then audit at the default 1/64 rate, whose
    marginal process-CPU must stay <=2%. CPU-marginal is the honest
    overhead on a single-core host: the audit's in-run wall (reported as
    ``audit_share_wall_pct``) double-counts device compute it merely
    overlaps with, while the twin-run CPU delta is exactly the extra
    compute auditing consumed. A contract break exits nonzero before
    any sidecar commits."""
    import shutil
    import tempfile

    from daccord_tpu.formats import LasFile, read_db
    from daccord_tpu.runtime import PipelineConfig, correct_shard
    from daccord_tpu.runtime.pipeline import estimate_profile_for_shard
    from daccord_tpu.sim import SimConfig, make_dataset
    from daccord_tpu.tools.eventcheck import validate_events
    from daccord_tpu.utils.obs import TRUST_QUARANTINED, trust_registry

    t0 = time.time()
    seed = int(os.environ.get("DACCORD_BENCH_SDC_SEED", "20"))
    batch = int(os.environ.get("DACCORD_BENCH_SDC_BATCH", "512"))
    mesh_n, culprit = 8, 3
    owns_root = root is None
    root = root or tempfile.mkdtemp(prefix="daccord-sdc-")
    # isolate the repo registries: the storm QUARANTINES a virtual member,
    # and that verdict must not leak into the real trust/compile registries
    prev_cc = os.environ.get("DACCORD_COMPCACHE")
    os.environ["DACCORD_COMPCACHE"] = os.path.join(root, "compcache")
    try:
        data = make_dataset(root, SimConfig(
            genome_len=4000, coverage=12, read_len_mean=700,
            min_overlap=300, seed=seed), name="sdc")
        db = read_db(data["db"])
        las = LasFile(data["las"])
        base = dict(batch_size=batch, depth_buckets=(16,))
        profile = estimate_profile_for_shard(db, las, PipelineConfig(**base))

        def run(tag: str, **kw):
            evp = os.path.join(root, f"{tag}.events.jsonl")
            cfg = PipelineConfig(**base, mesh=mesh_n, events_path=evp, **kw)
            w0, c0 = time.time(), time.process_time()
            got = [(rid, [f.tobytes() for f in frags])
                   for rid, frags, _ in correct_shard(db, las, cfg,
                                                      profile=profile)]
            return got, time.time() - w0, time.process_time() - c0, evp

        def events_of(evp: str):
            recs = []
            with open(evp) as fh:
                for raw in fh:
                    try:
                        recs.append(json.loads(raw))
                    except json.JSONDecodeError:
                        continue
            done = [r for r in recs if r.get("event") == "sup_done"]
            return recs, (done[-1] if done else {})

        # ---- golden: audit off = the pre-PR byte path -------------------
        golden, clean_wall, _, _ = run("clean", audit_rate=0.0)
        assert golden, "sdc soak: empty corrected output"

        # ---- storm: member `culprit` lies in every batch ----------------
        os.environ["DACCORD_FAULT"] = f"sdc:*@{culprit}"
        try:
            storm, storm_wall, _, storm_ev = run("storm")
        finally:
            os.environ.pop("DACCORD_FAULT", None)
        recs, sdone = events_of(storm_ev)
        sdc = [r for r in recs if r.get("event") == "sup_sdc"]
        attrib = [r for r in recs if r.get("event") == "audit.attrib"]
        trust = [r for r in recs if r.get("event") == "trust.state"]
        shrinks = [r for r in recs if r.get("event") == "mesh.shrink"]
        assert sdc, "sdc soak: the storm was never detected (no sup_sdc)"
        blamed = {int(r.get("culprit", -2)) for r in sdc + attrib}
        assert blamed == {culprit}, \
            f"sdc soak: events blame member(s) {blamed}, injected {culprit}"
        quar = [r for r in trust if r.get("state_to") == TRUST_QUARANTINED
                and int(r.get("device", -1)) == culprit]
        assert quar, \
            f"sdc soak: member {culprit} never reached QUARANTINED: {trust}"
        assert shrinks, \
            "sdc soak: quarantine never engaged the partial-mesh shrink rung"
        assert storm == golden, \
            "sdc soak: storm output diverged from the golden bytes — " \
            "a detected-too-late corruption reached the FASTA"
        reg = trust_registry()
        persisted = [k for k, v in reg.items()
                     if k.endswith(f"m{culprit}")
                     and v.get("state") == TRUST_QUARANTINED]
        assert persisted, \
            f"sdc soak: quarantine verdict not persisted in the registry: {reg}"
        lint = validate_events(storm_ev, strict=True)
        assert not lint, \
            f"sdc soak: eventcheck --strict rejects the storm stream: {lint[:5]}"

        # ---- twin controls: same warm caches (post-storm), audit off
        # then audit at the DEFAULT rate — the quarantined-registry mesh
        # both times, so the ONLY difference is the shadow audit ---------
        control0, ctl0_wall, ctl0_cpu, _ = run("control0", audit_rate=0.0)
        assert control0 == golden, \
            "sdc soak: the quarantine-shrunk mesh changed output bytes"
        control, ctl_wall, ctl_cpu, ctl_ev = run("control")
        _, cdone = events_of(ctl_ev)
        assert control == golden, \
            "sdc soak: audited control diverged from the golden bytes — " \
            "the audit rate changed output bytes"
        assert int(cdone.get("sdc_detected", 0)) == 0, \
            f"sdc soak: clean control false-positived: {cdone}"
        audits = int(cdone.get("audits", 0))
        assert audits > 0, "sdc soak: control never audited a batch"
        audit_s = float(cdone.get("audit_s", 0.0))
        overhead = max(0.0, ctl_cpu - ctl0_cpu) / max(ctl0_cpu, 1e-9)
        assert overhead <= 0.02, \
            f"sdc soak: default-rate audit cost {overhead:.1%} marginal " \
            f"CPU over the audit-off twin (>2%; audit_s {audit_s:.1f}s, " \
            f"cpu {ctl_cpu:.1f}s vs {ctl0_cpu:.1f}s)"

        line = {
            "metric": "sdc_soak", "chaos": True, "seed": seed,
            "batch": batch, "mesh": mesh_n, "fault": f"sdc:*@{culprit}",
            "windows": sum(len(f) for _, f in golden),
            "reads": len(golden),
            "detected": int(sdone.get("sdc_detected", 0)),
            "storm_audits": int(sdone.get("audits", 0)),
            "culprit": culprit, "culprit_from_events": sorted(blamed),
            "quarantined": True, "trust_persisted": True,
            "mesh_shrinks": len(shrinks),
            "parity": True, "false_positives": 0,
            "control_audits": audits,
            "audit_s": round(audit_s, 3),
            "audit_overhead_pct": round(100.0 * overhead, 3),
            "audit_share_wall_pct": round(100.0 * audit_s
                                          / max(ctl_wall, 1e-9), 3),
            "clean_wall_s": round(clean_wall, 3),
            "storm_wall_s": round(storm_wall, 3),
            "control0_wall_s": round(ctl0_wall, 3),
            "control_wall_s": round(ctl_wall, 3),
            "control0_cpu_s": round(ctl0_cpu, 3),
            "control_cpu_s": round(ctl_cpu, 3),
            "wall_s": round(time.time() - t0, 3),
            **_tunnel_staleness(),
        }
    finally:
        if prev_cc is None:
            os.environ.pop("DACCORD_COMPCACHE", None)
        else:
            os.environ["DACCORD_COMPCACHE"] = prev_cc
    if ev is not None:
        ev.log("bench_done", wall_s=line["wall_s"])
    if commit_sidecar:
        _commit_sidecar("BENCH_SDC.json", line)
    if owns_root:
        shutil.rmtree(root, ignore_errors=True)
    return line


def main() -> None:
    import argparse

    from daccord_tpu.utils.obs import (JsonlLogger, enable_compilation_cache,
                                       probe_backend_status)

    ap = argparse.ArgumentParser(description="consensus throughput bench")
    ap.add_argument("--events", default=os.environ.get("DACCORD_BENCH_EVENTS"),
                    metavar="PATH",
                    help="jsonl events sidecar (compile expectations, drain "
                         "heartbeats; schema: tools/eventcheck.py). Default: "
                         "$DACCORD_BENCH_EVENTS")
    args = ap.parse_args()
    ev = JsonlLogger(args.events)
    t_main0 = time.perf_counter()
    # staleness echo FIRST (ISSUE 13 satellite): every bench run dates the
    # tunnel's last real life sign before any measurement prints
    _echo_staleness()
    enable_compilation_cache()
    if BENCH_SERVE_SOAK:
        # chaos soak (ISSUE 15): 2 serve processes + seeded fault storm;
        # the asserts ARE the stage — a contract break exits nonzero
        ev.log("bench_start", batch=0, soak=True)
        n = int(os.environ.get("DACCORD_BENCH_SERVE_SOAK_JOBS", "20"))
        print(json.dumps(run_serve_soak(ev=ev, n_jobs=n)))
        return
    if BENCH_DISK:
        # disk-chaos soak (ISSUE 17): 2 serve peers under an injected
        # ENOSPC/EIO storage storm; the asserts ARE the stage — a broken
        # degradation contract exits nonzero
        ev.log("bench_start", batch=0, disk=True)
        n = int(os.environ.get("DACCORD_BENCH_DISK_JOBS", "8"))
        print(json.dumps(run_disk_soak(ev=ev, n_jobs=n)))
        return
    if BENCH_NET:
        # network-chaos soak (ISSUE 18): live router + 2 healthy serve
        # peers under an injected socket-fault storm and a SIGSTOP
        # partition; the asserts ARE the stage — a broken resilience
        # contract exits nonzero
        ev.log("bench_start", batch=0, net=True)
        n = int(os.environ.get("DACCORD_BENCH_NET_JOBS", "6"))
        print(json.dumps(run_net_soak(ev=ev, n_jobs=n)))
        return
    if BENCH_SDC:
        # silent-data-corruption soak (ISSUE 20): mesh-8 golden/storm/
        # control triple; the asserts ARE the stage — a broken defense
        # contract exits nonzero. Chip-free by the mesh arm's off-pod
        # recipe: re-exec under a forced 8-device host platform
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            import subprocess
            import sys as _sys

            env = dict(os.environ, JAX_PLATFORMS="cpu", XLA_FLAGS=(
                flags + " --xla_force_host_platform_device_count=8").strip())
            r = subprocess.run([_sys.executable, os.path.abspath(__file__)],
                               env=env)
            raise SystemExit(r.returncode)
        ev.log("bench_start", batch=0, sdc=True)
        print(json.dumps(run_sdc_soak(ev=ev)))
        return
    if BENCH_SERVE:
        # serving-plane stage: self-contained (synth corpus + real HTTP
        # server), chip-free by default — runs before any window build
        ev.log("bench_start", batch=0, serve=True)
        print(json.dumps(run_serve_bench(ev)))
        return
    if BENCH_ROUTER:
        # front-door stage (ISSUE 16): cold-peer TTFR with/without the AOT
        # cache + p99 through a live router during an autoscaler scale-out
        ev.log("bench_start", batch=0, router=True)
        print(json.dumps(run_router_bench(ev)))
        return
    data = build_windows()
    ev.log("bench_start", batch=BATCH, precompile=BENCH_PRECOMPILE)
    fallback = None
    # why the run fell back, machine-readably (ADVICE r5: a free-text device
    # string made degraded runs impossible to triage): probe_timeout |
    # init_error | no_devices | probe_error | device_loss_mid_run:<exc>
    fallback_reason = os.environ.get("DACCORD_BENCH_FALLBACK_REASON")
    if fallback_reason:
        # re-exec'd child of a mid-run device loss (see below); platform is
        # already pinned to cpu by the parent
        fallback = "cpu-fallback (device lost mid-bench)"
    else:
        ndev, reason = probe_backend_status()
        if ndev == 0:
            import jax

            jax.config.update("jax_platforms", "cpu")
            fallback = "cpu-fallback (device init unreachable at bench time)"
            fallback_reason = reason
    if BENCH_MESH:
        flags = os.environ.get("XLA_FLAGS", "")
        if fallback and "xla_force_host_platform_device_count" not in flags:
            # no live device and no forced host pool: re-exec under the
            # off-pod recipe so the mesh rung still lands chip-free (the
            # same pattern as the mid-run device-loss re-exec below)
            import subprocess
            import sys as _sys

            env = dict(os.environ, JAX_PLATFORMS="cpu", XLA_FLAGS=(
                flags + " --xla_force_host_platform_device_count="
                f"{BENCH_MESH_N}").strip())
            if args.events:
                env["DACCORD_BENCH_EVENTS"] = args.events + ".mesh"
            r = subprocess.run([_sys.executable, os.path.abspath(__file__)],
                               env=env)
            raise SystemExit(r.returncode)
        print(json.dumps(run_mesh_bench(data, ev, fallback_reason)))
        ev.log("bench_done", wall_s=round(time.perf_counter() - t_main0, 3))
        return
    if BENCH_PRECOMPILE:
        if fallback:
            line = {"precompile": True, "batch": BATCH, "skipped": True,
                    "fallback_reason": fallback_reason}
        else:
            line = precompile_ladder(data, ev)
        ev.log("bench_done", wall_s=round(time.perf_counter() - t_main0, 3))
        print(json.dumps(line))
        return
    if BENCH_LADDER is not None:
        # self-staging rung ladder: each rung commits its own sidecar the
        # moment it lands (see run_ladder); the final stdout line is only a
        # table of contents
        if fallback:
            # no chip: the rung ladder exists to capture a live window, and
            # a CPU run of B=1024/2048 rungs would wall for hours — record
            # the dated probe verdict instead and leave the evidence to
            # TUNNEL_LOG.jsonl
            line = {"ladder": True, "skipped": True, "fallback": True,
                    "fallback_reason": fallback_reason,
                    "rungs": list(BENCH_LADDER), **_tunnel_staleness()}
        else:
            orc_bps = oracle_baseline(data)
            landed = run_ladder(data, ev, orc_bps)
            line = {"ladder": True, "rungs": list(BENCH_LADDER),
                    "landed": landed, "fallback": False,
                    "fallback_reason": None}
        ev.log("bench_done", wall_s=round(time.perf_counter() - t_main0, 3))
        print(json.dumps(line))
        return
    if fallback:
        dev_bps, info = cpu_fallback_throughput(data)
        info["device"] = fallback
    else:
        try:
            # pipelined number + compute-bound ceiling + stage breakdown
            # (their ratio is the dispatch-overhead gap being attacked) —
            # one assembly block shared with the ladder rungs
            dev_bps, info = _measure_device(data, ev, BATCH)
        except Exception as e:
            # possibly the chip died mid-bench (the r5 failure mode) — but a
            # plain host-side bug raises here too, and relabeling THAT as
            # device loss would commit a degraded measurement blaming a
            # healthy chip. Re-probe: still alive -> it's a bug, re-raise.
            if probe_backend_status()[0] > 0:
                raise
            # dead chip confirmed. The TPU backend is already initialized in
            # this process and cannot be swapped for cpu, so re-exec a
            # cpu-pinned child to produce the honest degraded line — with
            # the loss recorded, not hidden in free text
            import subprocess
            import sys as _sys

            reason = f"device_loss_mid_run:{type(e).__name__}"
            ev.log("bench_done", wall_s=round(time.perf_counter() - t_main0, 3),
                   error=reason)
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       DACCORD_BENCH_FALLBACK_REASON=reason)
            if args.events:
                # a separate sidecar: appending the child's fresh-clock
                # stream to the parent's file would break eventcheck's
                # monotonic-t contract and blur the two attempts
                env["DACCORD_BENCH_EVENTS"] = args.events + ".degraded"
            r = subprocess.run([_sys.executable, os.path.abspath(__file__)],
                               env=env)
            raise SystemExit(r.returncode)
    info["fallback"] = bool(fallback)   # machine-detectable degraded run
    info["fallback_reason"] = fallback_reason
    info.update(_tunnel_staleness())
    orc_bps = oracle_baseline(data)
    line = {
        "metric": "consensus_bases_per_sec_per_chip",
        "value": round(dev_bps, 1),
        "unit": "bases/s",
        "vs_baseline": round(dev_bps / orc_bps, 2) if orc_bps > 0 else None,
        "baseline": "single-core numpy oracle (reference binary unavailable; BASELINE.md published:{})",
        "oracle_bases_per_sec": round(orc_bps, 1),
        **info,
    }
    # the axon tunnel dies for hours at a time; keep the last real-TPU
    # measurement next to a degraded run so the round artifact retains
    # context. Two sidecars: the machine-local cache copy, and a TRACKED
    # repo-root copy (BENCH_TPU_LAST.json) that survives fresh checkouts —
    # a fallback run on a machine that never saw the TPU still reports the
    # last real measurement. Payloads are timestamped and the NEWER of the
    # two wins, so a stale local cache can't shadow a fresher committed
    # measurement pulled from another machine (or vice versa).
    last_tpu = os.path.join(CACHE, "last_tpu.json")
    tracked = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_TPU_LAST.json")
    if not fallback:
        # provenance fields (VERDICT r3 weak #1 / item 7): a sidecar must be
        # recomputable — record the code SHA, batch size, and the measured
        # per-fetch tunnel RTT alongside the headline number
        try:
            import subprocess
            sha = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                                 capture_output=True, text=True, timeout=10,
                                 cwd=os.path.dirname(os.path.abspath(__file__))
                                 ).stdout.strip() or None
        except Exception:
            sha = None
        payload = {"value": line["value"], "wall_s": info["wall_s"],
                   "windows": info["windows"], "device": info["device"],
                   "git_sha": sha, "batch": info.get("batch"),
                   "rtt_ms": info.get("rtt_ms"),
                   "ts": round(time.time(), 1)}
        if "device_compute_bases_per_sec" in info:
            payload["device_compute_bases_per_sec"] = \
                info["device_compute_bases_per_sec"]
        for dst in (last_tpu, tracked):
            tmp = f"{dst}.tmp.{os.getpid()}"
            with open(tmp, "wt") as fh:  # atomic: a killed bench never corrupts it
                json.dump(payload, fh)
            os.replace(tmp, dst)
    else:
        best = None
        for src in (last_tpu, tracked):
            try:
                with open(src) as fh:
                    cand = json.load(fh)
            except (OSError, json.JSONDecodeError):
                continue  # a broken sidecar must never cost the round its bench line
            if best is None or cand.get("ts", 0) > best.get("ts", 0):
                best = cand
        if best is not None:
            line["last_tpu_measurement"] = best
    ev.log("bench_done", wall_s=round(time.perf_counter() - t_main0, 3))
    print(json.dumps(line))


if __name__ == "__main__":
    main()
