#!/usr/bin/env python
"""Benchmark: consensus bases/sec/chip of the batched window solver.

Prints ONE JSON line:
  {"metric": "consensus_bases_per_sec_per_chip", "value": N, "unit": "bases/s",
   "vs_baseline": R, ...}

The metric is BASELINE.json's "consensus bases/sec/chip". The reference
publishes no number (BASELINE.md: ``published: {}``) and the reference binary
is unavailable to measure, so ``vs_baseline`` is the ratio against the
framework's own single-core numpy oracle (the executable spec of the same
algorithm) measured in the same run — an honest, reproducible stand-in until
the C++ reference can be built (SURVEY.md §7.3 item 6).

The window set is a synthetic PacBio-like dataset (sim module); the tensorized
batches are cached under .bench_cache/ so reruns skip the host prep.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".bench_cache")
N_BENCH_WINDOWS = 32768
# 2048 measured ~2x the 1024-batch throughput on the tunneled v5e (batch-size
# sweep 2026-07-30: 1024 -> 330-459k bases/s, 2048 -> 652k): per-dispatch
# overhead dominates single-digit-ms compute, so bigger batches amortize it.
# DACCORD_BENCH_BATCH overrides for sweeps (must divide N_BENCH_WINDOWS).
BATCH = int(os.environ.get("DACCORD_BENCH_BATCH", "2048"))
assert 0 < BATCH <= N_BENCH_WINDOWS and N_BENCH_WINDOWS % BATCH == 0, \
    f"DACCORD_BENCH_BATCH={BATCH} must divide N_BENCH_WINDOWS={N_BENCH_WINDOWS}"
DEPTH, SEG_LEN, WLEN = 32, 64, 40


def build_windows() -> dict:
    os.makedirs(CACHE, exist_ok=True)
    npz = os.path.join(CACHE, "windows_v1.npz")
    if os.path.exists(npz):
        d = np.load(npz)
        return {k: d[k] for k in d.files}

    from daccord_tpu.kernels import BatchShape, tensorize_windows
    from daccord_tpu.oracle import (
        ConsensusConfig,
        cut_windows,
        estimate_profile_two_pass,
        refine_overlap,
    )
    from daccord_tpu.sim import SimConfig, simulate

    cfg = SimConfig(genome_len=20_000, coverage=20, read_len_mean=2_000, seed=42)
    res = simulate(cfg)
    ccfg = ConsensusConfig()
    shape = BatchShape(depth=DEPTH, seg_len=SEG_LEN, wlen=WLEN)
    items = []
    prof = None
    piles: dict[int, list] = {}
    for o in res.overlaps:
        piles.setdefault(o.aread, []).append(o)
    for aread, pile in piles.items():
        a = res.reads[aread].seq
        refined = [refine_overlap(o, a, res.reads[o.bread].seq, cfg.tspace) for o in pile]
        windows = cut_windows(a, refined, w=ccfg.w, adv=ccfg.adv)
        if prof is None:
            prof = estimate_profile_two_pass(refined, windows, ccfg, sample=24)
        items.extend((aread, ws) for ws in windows)
        if len(items) >= N_BENCH_WINDOWS:
            break
    batch = tensorize_windows(items[:N_BENCH_WINDOWS], shape)
    out = dict(seqs=batch.seqs, lens=batch.lens, nsegs=batch.nsegs,
               p_ins=np.float64(prof.p_ins), p_del=np.float64(prof.p_del),
               p_sub=np.float64(prof.p_sub))
    np.savez_compressed(npz, **out)
    return out


def oracle_baseline(data: dict, n: int = 48) -> float:
    """Single-core numpy oracle throughput (consensus bases/sec)."""
    from daccord_tpu.oracle.consensus import ConsensusConfig, make_offset_likely, solve_window
    from daccord_tpu.oracle.profile import ErrorProfile
    from daccord_tpu.oracle.windows import WindowSegments

    prof = ErrorProfile(float(data["p_ins"]), float(data["p_del"]), float(data["p_sub"]))
    ccfg = ConsensusConfig()
    ols = make_offset_likely(prof, ccfg)
    idx = np.linspace(0, len(data["nsegs"]) - 1, n).astype(int)
    t0 = time.perf_counter()
    bases = 0
    for i in idx:
        segs = [data["seqs"][i, d, : data["lens"][i, d]] for d in range(int(data["nsegs"][i]))]
        ws = WindowSegments(wstart=0, wlen=WLEN, segments=segs, breads=[0] * len(segs))
        r = solve_window(ws, ols, ccfg)
        if r.seq is not None:
            bases += len(r.seq)
    dt = time.perf_counter() - t0
    return bases / dt if dt > 0 else 0.0


def device_throughput(data: dict, max_batches: int | None = None,
                      max_inflight: int = 8) -> tuple[float, dict]:
    """Pipelined-dispatch throughput (the pipeline's own dispatch discipline).

    A blocking fetch per batch would measure the axon tunnel's per-call
    latency (~60-300 ms), not the chip: batches are dispatched with a bounded
    in-flight window and results fetched as they complete, exactly like
    runtime/pipeline.py does in production.
    """
    from collections import deque

    import jax

    from daccord_tpu.kernels.tensorize import BatchShape, WindowBatch
    from daccord_tpu.kernels.tiers import (TierLadder, fetch, fetch_many,
                                           solve_ladder_async)
    from daccord_tpu.oracle.consensus import ConsensusConfig
    from daccord_tpu.oracle.profile import ErrorProfile

    prof = ErrorProfile(float(data["p_ins"]), float(data["p_del"]), float(data["p_sub"]))
    ccfg = ConsensusConfig()
    ladder = TierLadder.from_config(prof, ccfg)
    shape = BatchShape(depth=DEPTH, seg_len=SEG_LEN, wlen=WLEN)

    N = len(data["nsegs"])
    nb = N // BATCH
    if max_batches is not None:
        nb = min(nb, max_batches)

    def make_batch(i):
        sl = slice(i * BATCH, (i + 1) * BATCH)
        return WindowBatch(seqs=data["seqs"][sl], lens=data["lens"][sl],
                           nsegs=data["nsegs"][sl], shape=shape,
                           read_ids=np.zeros(BATCH, np.int64),
                           wstarts=np.zeros(BATCH, np.int64))

    # warmup / compile all tier shapes
    fetch(solve_ladder_async(make_batch(0), ladder))

    t0 = time.perf_counter()
    bases = 0
    solved = 0
    inflight: deque = deque()

    def drain(to_depth: int):
        nonlocal bases, solved
        n_pop = len(inflight) - to_depth
        if n_pop <= 0:
            return
        # ONE grouped fetch per drain: the tunnel charges its ~100 ms RTT per
        # device_get call, not per array (same discipline as the pipeline)
        for out in fetch_many([inflight.popleft() for _ in range(n_pop)]):
            bases += int(out["cons_len"].sum())
            solved += int(out["solved"].sum())

    for i in range(nb):
        inflight.append(solve_ladder_async(make_batch(i), ladder))
        if len(inflight) >= max_inflight:
            drain(max_inflight // 2)
    drain(0)
    dt = time.perf_counter() - t0
    info = dict(windows=nb * BATCH, solved=solved, wall_s=round(dt, 3),
                device=str(jax.devices()[0]).replace(" ", ""),
                solve_rate=round(solved / (nb * BATCH), 4))
    return bases / dt, info


def _device_alive(timeout_s: int = 150) -> bool:
    from daccord_tpu.utils.obs import device_alive

    return device_alive(timeout_s)


def main() -> None:
    from daccord_tpu.utils.obs import enable_compilation_cache

    enable_compilation_cache()
    data = build_windows()
    fallback = None
    if not _device_alive():
        import jax

        jax.config.update("jax_platforms", "cpu")
        fallback = "cpu-fallback (device init unreachable at bench time)"
    dev_bps, info = device_throughput(data, max_batches=2 if fallback else None)
    info["fallback"] = bool(fallback)   # machine-detectable degraded run
    if fallback:
        info["device"] = fallback
    orc_bps = oracle_baseline(data)
    line = {
        "metric": "consensus_bases_per_sec_per_chip",
        "value": round(dev_bps, 1),
        "unit": "bases/s",
        "vs_baseline": round(dev_bps / orc_bps, 2) if orc_bps > 0 else None,
        "baseline": "single-core numpy oracle (reference binary unavailable; BASELINE.md published:{})",
        "oracle_bases_per_sec": round(orc_bps, 1),
        **info,
    }
    # the axon tunnel dies for hours at a time; keep the last real-TPU
    # measurement next to a degraded run so the round artifact retains
    # context. Two sidecars: the machine-local cache copy, and a TRACKED
    # repo-root copy (BENCH_TPU_LAST.json) that survives fresh checkouts —
    # a fallback run on a machine that never saw the TPU still reports the
    # last real measurement
    last_tpu = os.path.join(CACHE, "last_tpu.json")
    tracked = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_TPU_LAST.json")
    if not fallback:
        payload = {"value": line["value"], "wall_s": info["wall_s"],
                   "windows": info["windows"], "device": info["device"]}
        for dst in (last_tpu, tracked):
            tmp = f"{dst}.tmp.{os.getpid()}"
            with open(tmp, "wt") as fh:  # atomic: a killed bench never corrupts it
                json.dump(payload, fh)
            os.replace(tmp, dst)
    else:
        for src in (last_tpu, tracked):
            try:
                with open(src) as fh:
                    line["last_tpu_measurement"] = json.load(fh)
                break
            except (OSError, json.JSONDecodeError):
                continue  # a broken sidecar must never cost the round its bench line
    print(json.dumps(line))


if __name__ == "__main__":
    main()
