#!/bin/sh
# Chip-revival pounce script (VERDICT r4 "Next round" #1): the ordered run
# of every queued hardware experiment (ARCHITECTURE.md "Queued hardware
# experiments"), each sidecar committed IMMEDIATELY so a tunnel that dies
# mid-sequence still leaves evidence. Run the moment TUNNEL_LOG.jsonl
# records alive:true:   sh tools_pounce.sh
#
# EXCLUSIVITY (2026-08-02): stop tools_probe_loop.sh before running this.
# Each probe opens a fresh axon client; a concurrent client while a bench
# holds the device can leave the bench's RPC unanswered indefinitely.
# Probe manually between runs instead.
set -x
cd /root/repo || exit 1
stamp=$(date -u +%Y%m%dT%H%M%S)

run() {  # run <name> <cmd...>: capture one experiment, commit its sidecar
  name=$1; shift
  out="POUNCE_${stamp}_${name}.json"
  "$@" > "$out" 2> "POUNCE_${stamp}_${name}.log"
  git add "$out" "POUNCE_${stamp}_${name}.log"
  git commit -q -m "pounce: ${name} on live chip (${stamp})"
}

# 1. flagship bench first (pipelined + device_compute + stage breakdown)
run bench            python bench.py
# 2. batch sweep (experiment 1). 8192 dropped 2026-08-02: server-side XLA
# compile scales superlinearly with B (measured 256->35s, 1024->242s,
# 2048->925s; 8192 extrapolates to 2-4h) — precompile 2048/4096 via the
# persistent cache first, see BASELINE.md "r5 live-chip" notes.
run batch4096        env DACCORD_BENCH_BATCH=4096 python bench.py
# 3. esc_cap tail cost (experiment 3)
run esccap256        env DACCORD_BENCH_ESC_CAP=256 python bench.py
# 4. candidates=5 cost (experiment 2)
run cand5            env DACCORD_BENCH_CANDIDATES=5 python bench.py
# 5. fused Pallas vs scan decision row (experiment 6)
run ladder_pallas    python -m daccord_tpu.tools.kernelbench --backend auto \
                       --stages ladder_full,ladder_pallas
# 6. hp drain overlap on the real pipeline (experiment 7): hp on vs off
run hp_on            env DACCORD_BENCH_HP=1 python bench.py
echo "pounce complete: POUNCE_${stamp}_*"
