#!/bin/sh
# Chip-revival pounce script (VERDICT r4 "Next round" #1): the ordered run
# of every queued hardware experiment (ARCHITECTURE.md "Queued hardware
# experiments"), each sidecar committed IMMEDIATELY so a tunnel that dies
# mid-sequence still leaves evidence. Run the moment TUNNEL_LOG.jsonl
# records alive:true:   sh tools_pounce.sh
set -x
cd /root/repo || exit 1

# EXCLUSIVITY, enforced in code (ADVICE r5 #1: the comment-only rule let a
# concurrent probe client wedge a 30-min bench): each probe opens a fresh
# axon client, and a concurrent client while a bench holds the device can
# leave the bench's RPC unanswered indefinitely. Kill the probe loop; abort
# if it will not die. Probe manually between runs instead.
if pgrep -f tools_probe_loop >/dev/null 2>&1; then
  echo "tools_pounce: killing running tools_probe_loop (probe/bench exclusivity)" >&2
  pkill -f tools_probe_loop
  sleep 3
  if pgrep -f tools_probe_loop >/dev/null 2>&1; then
    echo "tools_pounce: probe loop still alive after pkill; aborting" >&2
    exit 1
  fi
fi

stamp=$(date -u +%Y%m%dT%H%M%S)

# Bounded committed probe (VERDICT r5 weak #2: the last round's fallback:true
# bench could not be attributed to a dated tunnel death because nothing
# bracketed when the chip died). Called after EVERY bench step, kill, and
# compile wait — exclusivity-safe: the bench process is gone by the time it
# runs, and 10 s bounds the cost. A probe_timeout under the 10 s cap on a
# warm-but-slow tunnel is still a dated, honest record (reason field says
# why), which is the point.
probe() {  # probe <label>
  python - "$1" <<'EOF'
import json, sys, time
from daccord_tpu.utils.obs import probe_backend_status
t0 = time.time()
n, reason = probe_backend_status(10)
rec = {"ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
       "devices": n, "alive": n > 0, "probe_s": round(time.time() - t0, 1),
       "reason": reason, "after": sys.argv[1]}
with open("TUNNEL_LOG.jsonl", "a") as f:
    f.write(json.dumps(rec) + "\n")
print(rec)
EOF
  git add TUNNEL_LOG.jsonl
  git commit -q -m "pounce: tunnel probe after $1 (${stamp})"
}

# the pkill above is itself a bench-adjacent action: date the chip's health
# before any chip time is spent
probe startup
# operator context: the probe pass/fail timeline + last-alive timestamp (with
# its age in hours — satellite: staleness blindness), so this window's
# benches are datable against the tunnel's recent history
python -m daccord_tpu.tools.cli trace --probe-history TUNNEL_LOG.jsonl || true
# regression sentinel, advisory pass (ISSUE 13): flag fallback rungs and
# throughput drift across the COMMITTED bench trajectory before adding to it.
# Advisory (|| true): the committed history already contains known-degraded
# rounds; the strict runs below gate the fresh smoke sidecars instead.
python -m daccord_tpu.tools.cli sentinel BENCH_r*.json MULTICHIP_r*.json || true

# corruption-fuzz smoke (ingest integrity layer, ISSUE 2): synthesize a toy
# DB/LAS, bit-flip a record and tear the file mid-record, then require a
# quarantine-mode completion with lint-clean ingest.* events — all CPU-side,
# BEFORE any chip time is spent. A failure here means the ingest layer
# regressed; abort the pounce rather than bench on top of it.
fuzzdir=$(mktemp -d)
python - "$fuzzdir" <<'EOF' || { echo "tools_pounce: fuzz synth failed" >&2; exit 1; }
import sys
from daccord_tpu.sim.synth import SimConfig, make_dataset
from daccord_tpu.runtime import faults
d = sys.argv[1]
out = make_dataset(d, SimConfig(genome_len=1500, coverage=10,
                                read_len_mean=500, min_overlap=200, seed=5),
                   name="fuzz")
print(faults.corrupt_las_bitflip(out["las"], 4))
print(faults.corrupt_las_truncate(out["las"], 300))
EOF
python -m daccord_tpu.tools.cli daccord "$fuzzdir/fuzz.db" "$fuzzdir/fuzz.las" \
    --backend native -b 64 --ingest-policy quarantine \
    -o "$fuzzdir/fuzz.fasta" --events "$fuzzdir/fuzz.events.jsonl" \
    --ledger "$fuzzdir/fuzz.ledger.jsonl" \
  || { echo "tools_pounce: corruption-fuzz run FAILED" >&2; exit 1; }
# strict schema lint + span-pairing/ledger lint (ISSUE 6): a drift in any
# record kind the telemetry spine emits fails HERE, before chip time
python -m daccord_tpu.tools.cli eventcheck --strict "$fuzzdir/fuzz.events.jsonl" \
  || { echo "tools_pounce: fuzz events failed schema lint" >&2; exit 1; }
python -m daccord_tpu.tools.cli trace --check --no-timeline \
    "$fuzzdir/fuzz.events.jsonl" "$fuzzdir/fuzz.ledger.jsonl" \
  || { echo "tools_pounce: fuzz sidecars failed daccord-trace lint" >&2; exit 1; }
# saturation-profiler reconciliation (ISSUE 14): stage sums must agree with
# the run's own feeder_s/host_s/device_s anchors within 5%/50 ms
python -m daccord_tpu.tools.cli prof --check "$fuzzdir/fuzz.events.jsonl" \
  || { echo "tools_pounce: fuzz sidecar failed daccord-prof reconciliation" >&2; exit 1; }
# regression sentinel, strict (ISSUE 13): a failover/degraded outcome in the
# fuzz smoke would otherwise land as a green exit code
python -m daccord_tpu.tools.cli sentinel --strict "$fuzzdir/fuzz.events.jsonl" \
  || { echo "tools_pounce: fuzz sidecar tripped the regression sentinel" >&2; exit 1; }
grep -q '"event": "ingest.quarantine"' "$fuzzdir/fuzz.events.jsonl" \
  || { echo "tools_pounce: fuzz run quarantined nothing" >&2; exit 1; }
echo "tools_pounce: corruption-fuzz smoke OK" >&2
rm -rf "$fuzzdir"

# fleet smoke (shard fleet orchestrator, ISSUE 3): synth a toy dataset, run a
# 4-shard supervised fleet with an injected worker crash, lint the fleet
# event sidecar, and require the merged FASTA to be byte-identical to an
# unfaulted fleet run — all CPU-side, before any chip time. A failure here
# means the orchestrator/requeue/merge-gate layer regressed; abort the
# pounce rather than bench on top of it.
fleetdir=$(mktemp -d)
python - "$fleetdir" <<'EOF' || { echo "tools_pounce: fleet synth failed" >&2; exit 1; }
import sys
from daccord_tpu.sim.synth import SimConfig, make_dataset
make_dataset(sys.argv[1], SimConfig(genome_len=1500, coverage=10,
                                    read_len_mean=500, min_overlap=200,
                                    seed=5), name="fleet")
EOF
python -m daccord_tpu.tools.cli fleet "$fleetdir/fleet.db" "$fleetdir/fleet.las" \
    "$fleetdir/ref" -n 4 --workers 2 --backend native --checkpoint-every 4 \
    --merge "$fleetdir/ref.fasta" \
  || { echo "tools_pounce: clean fleet run FAILED" >&2; exit 1; }
DACCORD_FAULT=worker_crash:1 python -m daccord_tpu.tools.cli fleet \
    "$fleetdir/fleet.db" "$fleetdir/fleet.las" \
    "$fleetdir/crash" -n 4 --workers 2 --backend native --checkpoint-every 4 \
    --merge "$fleetdir/crash.fasta" \
  || { echo "tools_pounce: crash-injected fleet run FAILED" >&2; exit 1; }
python -m daccord_tpu.tools.cli eventcheck --strict \
    "$fleetdir/ref/fleet.events.jsonl" "$fleetdir/crash/fleet.events.jsonl" \
    "$fleetdir"/ref/shard*.events.jsonl "$fleetdir"/crash/shard*.events.jsonl \
  || { echo "tools_pounce: fleet events failed schema lint" >&2; exit 1; }
# whole-directory trace lint (ISSUE 6): merges orchestrator + worker
# sidecars on absolute ts, enforces span pairing (the crashed attempt's
# unwind must have closed its spans) and ledger-vs-manifest window counts
python -m daccord_tpu.tools.cli trace --check --no-timeline \
    "$fleetdir/ref" "$fleetdir/crash" \
  || { echo "tools_pounce: fleet sidecars failed daccord-trace lint" >&2; exit 1; }
# per-worker saturation profiles must reconcile (ISSUE 14; directory sweep
# skips the orchestrator sidecar, which has no shard_done by design)
python -m daccord_tpu.tools.cli prof --check "$fleetdir/ref" "$fleetdir/crash" \
  || { echo "tools_pounce: fleet sidecars failed daccord-prof reconciliation" >&2; exit 1; }
grep -q '"event": "fleet.retry"' "$fleetdir/crash/fleet.events.jsonl" \
  || { echo "tools_pounce: injected worker crash was never requeued" >&2; exit 1; }
# sentinel strict over both fleet dirs: no shard may finish degraded, and the
# committed fleet.metrics.prom expositions must scrape-parse
python -m daccord_tpu.tools.cli sentinel --strict "$fleetdir/ref" "$fleetdir/crash" \
  || { echo "tools_pounce: fleet sidecars tripped the regression sentinel" >&2; exit 1; }
cmp -s "$fleetdir/ref.fasta" "$fleetdir/crash.fasta" \
  || { echo "tools_pounce: crash-requeued fleet FASTA diverged from clean run" >&2; exit 1; }
echo "tools_pounce: fleet smoke OK" >&2
rm -rf "$fleetdir"

# capacity-governor smoke (ISSUE 5): synth a toy dataset, then (a) an
# injected device OOM must complete HEALTHY through the bisect ladder with
# lint-clean governor.* events and a byte-identical FASTA, and (b) an
# injected monster pile must quarantine exactly its own read (emitted raw)
# with every other read byte-identical — all CPU-side, before any chip
# minute is spent. A failure here means the degradation path regressed;
# abort the pounce rather than OOM a live window. The injected runs get a
# throwaway compcache dir: the OOM ratchet they record must not land in the
# host's real registry (a real run would then dispatch at the shrunken
# width), and a persisted ratchet would short-circuit classification on the
# next pounce, failing the governor.classify check below.
govdir=$(mktemp -d)
govcc="DACCORD_COMPCACHE=$govdir/cc"
python - "$govdir" <<'EOF' || { echo "tools_pounce: governor synth failed" >&2; exit 1; }
import sys
from daccord_tpu.sim.synth import SimConfig, make_dataset
make_dataset(sys.argv[1], SimConfig(genome_len=1500, coverage=10,
                                    read_len_mean=500, min_overlap=200,
                                    seed=5), name="gov")
EOF
env "$govcc" python -m daccord_tpu.tools.cli daccord "$govdir/gov.db" "$govdir/gov.las" \
    --backend native -b 64 -o "$govdir/ref.fasta" \
  || { echo "tools_pounce: governor reference run FAILED" >&2; exit 1; }
env "$govcc" DACCORD_FAULT=device_oom:2 python -m daccord_tpu.tools.cli daccord \
    "$govdir/gov.db" "$govdir/gov.las" --backend native -b 64 \
    -o "$govdir/oom.fasta" --events "$govdir/oom.events.jsonl" \
  || { echo "tools_pounce: device_oom-injected run FAILED" >&2; exit 1; }
python -m daccord_tpu.tools.cli eventcheck --strict "$govdir/oom.events.jsonl" \
  || { echo "tools_pounce: governor events failed schema lint" >&2; exit 1; }
python -m daccord_tpu.tools.cli trace --check --no-timeline "$govdir/oom.events.jsonl" \
  || { echo "tools_pounce: governor sidecar failed daccord-trace lint" >&2; exit 1; }
python -m daccord_tpu.tools.cli prof --check "$govdir/oom.events.jsonl" \
  || { echo "tools_pounce: governor sidecar failed daccord-prof reconciliation" >&2; exit 1; }
grep -q '"event": "governor.classify"' "$govdir/oom.events.jsonl" \
  || { echo "tools_pounce: injected OOM was never classified" >&2; exit 1; }
python -m daccord_tpu.tools.cli sentinel --strict "$govdir/oom.events.jsonl" \
  || { echo "tools_pounce: governor sidecar tripped the regression sentinel" >&2; exit 1; }
grep -q '"event": "sup_failover"' "$govdir/oom.events.jsonl" \
  && { echo "tools_pounce: OOM run failed over instead of degrading" >&2; exit 1; }
cmp -s "$govdir/ref.fasta" "$govdir/oom.fasta" \
  || { echo "tools_pounce: OOM-degraded FASTA diverged from clean run" >&2; exit 1; }
env "$govcc" DACCORD_FAULT=monster_pile:2 python -m daccord_tpu.tools.cli daccord \
    "$govdir/gov.db" "$govdir/gov.las" --backend native -b 64 \
    -o "$govdir/mon.fasta" --events "$govdir/mon.events.jsonl" \
    --quarantine "$govdir/mon.quarantine.jsonl" \
  || { echo "tools_pounce: monster_pile-injected run FAILED" >&2; exit 1; }
python -m daccord_tpu.tools.cli eventcheck --strict "$govdir/mon.events.jsonl" \
  || { echo "tools_pounce: monster events failed schema lint" >&2; exit 1; }
python -m daccord_tpu.tools.cli trace --check --no-timeline "$govdir/mon.events.jsonl" \
  || { echo "tools_pounce: monster sidecar failed daccord-trace lint" >&2; exit 1; }
python -m daccord_tpu.tools.cli prof --check "$govdir/mon.events.jsonl" \
  || { echo "tools_pounce: monster sidecar failed daccord-prof reconciliation" >&2; exit 1; }
python - "$govdir" <<'EOF' || { echo "tools_pounce: monster quarantine parity FAILED" >&2; exit 1; }
import json, sys
from daccord_tpu.formats.fasta import read_fasta
d = sys.argv[1]
mon = [json.loads(x) for x in open(f"{d}/mon.events.jsonl")
       if '"governor.monster"' in x]
assert len(mon) == 1, mon
bad = f"read{mon[0]['aread']}"
q = [json.loads(x) for x in open(f"{d}/mon.quarantine.jsonl")]
assert q and q[0]["kind"] == "monster_pile", q
def by_read(p):
    m = {}
    for rec in read_fasta(p):
        m.setdefault(rec.name.split("/")[0], []).append(rec.seq)
    return m
r0, r1 = by_read(f"{d}/ref.fasta"), by_read(f"{d}/mon.fasta")
assert all(r0.get(k) == r1.get(k) for k in (set(r0) | set(r1)) - {bad}), \
    "a read outside the quarantined pile changed"
assert r0.get(bad) != r1.get(bad), "the monster pile's read was not contained"
print(f"governor smoke: {bad} contained, all other reads byte-identical")
EOF
echo "tools_pounce: capacity-governor smoke OK" >&2
rm -rf "$govdir"

# paged-batching smoke (ISSUE 7): synth a toy corpus, run the dense and the
# paged JAX-CPU ladder, and require byte-identical FASTA plus a >=2x
# pad-waste (dead cells per used cell) reduction with lint-clean
# paging.family/batch.paged events — all CPU-side, before any chip minute.
# A failure here means the paged wire format regressed; abort the pounce
# rather than spend chip time on it. Uses the REAL compcache (clean runs, no
# ratchets): the first pounce pays ~2 CPU ladder compiles per shape, later
# pounces run warm.
pagedir=$(mktemp -d)
python - "$pagedir" <<'EOF' || { echo "tools_pounce: paged synth failed" >&2; exit 1; }
import sys
from daccord_tpu.sim.synth import SimConfig, make_dataset
make_dataset(sys.argv[1], SimConfig(genome_len=1500, coverage=10,
                                    read_len_mean=500, min_overlap=200,
                                    seed=5), name="pg")
EOF
python -m daccord_tpu.tools.cli daccord "$pagedir/pg.db" "$pagedir/pg.las" \
    --backend cpu -b 32 -o "$pagedir/dense.fasta" \
    --stats "$pagedir/dense.stats.json" \
  || { echo "tools_pounce: paged-smoke dense run FAILED" >&2; exit 1; }
python -m daccord_tpu.tools.cli daccord "$pagedir/pg.db" "$pagedir/pg.las" \
    --backend cpu -b 32 --paged on -o "$pagedir/paged.fasta" \
    --stats "$pagedir/paged.stats.json" \
    --events "$pagedir/paged.events.jsonl" \
  || { echo "tools_pounce: paged-smoke paged run FAILED" >&2; exit 1; }
cmp -s "$pagedir/dense.fasta" "$pagedir/paged.fasta" \
  || { echo "tools_pounce: paged FASTA diverged from dense run" >&2; exit 1; }
python -m daccord_tpu.tools.cli eventcheck --strict "$pagedir/paged.events.jsonl" \
  || { echo "tools_pounce: paged events failed schema lint" >&2; exit 1; }
python -m daccord_tpu.tools.cli trace --check --no-timeline "$pagedir/paged.events.jsonl" \
  || { echo "tools_pounce: paged sidecar failed daccord-trace lint" >&2; exit 1; }
python -m daccord_tpu.tools.cli prof --check "$pagedir/paged.events.jsonl" \
  || { echo "tools_pounce: paged sidecar failed daccord-prof reconciliation" >&2; exit 1; }
grep -q '"event": "paging.family"' "$pagedir/paged.events.jsonl" \
  || { echo "tools_pounce: paged run derived no shape families" >&2; exit 1; }
python - "$pagedir" <<'EOF' || { echo "tools_pounce: paged pad-waste check FAILED" >&2; exit 1; }
import json, sys
d = sys.argv[1]
pw_d = json.load(open(f"{d}/dense.stats.json"))["pad_waste"]
pw_p = json.load(open(f"{d}/paged.stats.json"))["pad_waste"]
dead_d = pw_d / (1 - pw_d)      # dead cells per used cell
dead_p = pw_p / (1 - pw_p)
ratio = dead_d / max(dead_p, 1e-9)
print(f"pad waste: dense {pw_d} paged {pw_p}; dead/used reduction {ratio:.2f}x")
assert ratio >= 2.0, f"paged pad-waste reduction {ratio:.2f}x < 2x"
EOF
echo "tools_pounce: paged-batching smoke OK" >&2
rm -rf "$pagedir"

# mesh smoke (ISSUE 12): synth a toy corpus, run the mesh-8-on-CPU sharded
# ladder (forced host platform devices — the off-pod recipe) WITH paged
# batching on, and require byte-identical FASTA vs the single-device run
# plus lint-clean mesh.*/paging.* events — all CPU-side, before any chip
# minute. A failure here means the mesh solve path (supervisor :m keys,
# sharded paged gather, pad-to-mesh plumbing) regressed; abort the pounce
# rather than burn a pod slice on it.
meshdir=$(mktemp -d)
python - "$meshdir" <<'EOF' || { echo "tools_pounce: mesh synth failed" >&2; exit 1; }
import sys
from daccord_tpu.sim.synth import SimConfig, make_dataset
make_dataset(sys.argv[1], SimConfig(genome_len=1500, coverage=10,
                                    read_len_mean=500, min_overlap=200,
                                    seed=5), name="mx")
EOF
python -m daccord_tpu.tools.cli daccord "$meshdir/mx.db" "$meshdir/mx.las" \
    --backend cpu -b 64 -o "$meshdir/single.fasta" \
  || { echo "tools_pounce: mesh-smoke single-device run FAILED" >&2; exit 1; }
env JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m daccord_tpu.tools.cli daccord "$meshdir/mx.db" "$meshdir/mx.las" \
    --backend cpu -b 64 --mesh 8 --paged on -o "$meshdir/mesh.fasta" \
    --events "$meshdir/mesh.events.jsonl" \
  || { echo "tools_pounce: mesh-8-on-CPU run FAILED" >&2; exit 1; }
cmp -s "$meshdir/single.fasta" "$meshdir/mesh.fasta" \
  || { echo "tools_pounce: mesh-8 FASTA diverged from single-device run" >&2; exit 1; }
python -m daccord_tpu.tools.cli eventcheck --strict "$meshdir/mesh.events.jsonl" \
  || { echo "tools_pounce: mesh events failed schema lint" >&2; exit 1; }
python -m daccord_tpu.tools.cli trace --check --no-timeline "$meshdir/mesh.events.jsonl" \
  || { echo "tools_pounce: mesh sidecar failed daccord-trace lint" >&2; exit 1; }
python -m daccord_tpu.tools.cli prof --check "$meshdir/mesh.events.jsonl" \
  || { echo "tools_pounce: mesh sidecar failed daccord-prof reconciliation" >&2; exit 1; }
grep -q '"event": "mesh.init"' "$meshdir/mesh.events.jsonl" \
  || { echo "tools_pounce: mesh run never initialized a mesh" >&2; exit 1; }
# per-device flight recorder (ISSUE 13): the clean mesh smoke must emit the
# mesh health map (mesh.device rows ride the final metrics snapshot), and
# the sentinel must see no degradation in it
grep -q '"event": "mesh.device"' "$meshdir/mesh.events.jsonl" \
  || { echo "tools_pounce: mesh run emitted no per-device telemetry" >&2; exit 1; }
python -m daccord_tpu.tools.cli sentinel --strict "$meshdir/mesh.events.jsonl" \
  || { echo "tools_pounce: mesh sidecar tripped the regression sentinel" >&2; exit 1; }
# dispatch pipeline (ISSUE 19): the mesh run above is double-buffered by
# default — require its staged-dispatch telemetry (dispatch.stage/launch
# span pairs, the pack/stage/launch sub-walls prof --check reconciles above)
# and byte parity against the DACCORD_MESH_PIPELINE=0 fused control arm.
# A divergence means staging batch N+1 under batch N's solve changed bytes
# — the one thing the pipeline must never do.
grep -q '"event": "dispatch.pipeline"' "$meshdir/mesh.events.jsonl" \
  || { echo "tools_pounce: mesh run never engaged the dispatch pipeline" >&2; exit 1; }
grep -q '"event": "dispatch.stage"' "$meshdir/mesh.events.jsonl" \
  || { echo "tools_pounce: pipelined mesh run staged no batches" >&2; exit 1; }
grep -q '"event": "dispatch.launch"' "$meshdir/mesh.events.jsonl" \
  || { echo "tools_pounce: pipelined mesh run launched no staged batches" >&2; exit 1; }
grep -q '"pack_s"' "$meshdir/mesh.events.jsonl" \
  || { echo "tools_pounce: mesh shard_done carries no dispatch sub-walls" >&2; exit 1; }
env JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    DACCORD_MESH_PIPELINE=0 \
    python -m daccord_tpu.tools.cli daccord "$meshdir/mx.db" "$meshdir/mx.las" \
    --backend cpu -b 64 --mesh 8 --paged on -o "$meshdir/nopipe.fasta" \
    --events "$meshdir/nopipe.events.jsonl" \
  || { echo "tools_pounce: unpipelined mesh control run FAILED" >&2; exit 1; }
cmp -s "$meshdir/mesh.fasta" "$meshdir/nopipe.fasta" \
  || { echo "tools_pounce: pipelined FASTA diverged from unpipelined control" >&2; exit 1; }
grep -q '"event": "dispatch.pipeline"' "$meshdir/nopipe.events.jsonl" \
  && { echo "tools_pounce: DACCORD_MESH_PIPELINE=0 did not disable the pipeline" >&2; exit 1; }
python -m daccord_tpu.tools.cli eventcheck --strict "$meshdir/nopipe.events.jsonl" \
  || { echo "tools_pounce: unpipelined mesh events failed schema lint" >&2; exit 1; }
python -m daccord_tpu.tools.cli prof --check "$meshdir/nopipe.events.jsonl" \
  || { echo "tools_pounce: unpipelined sidecar failed daccord-prof reconciliation" >&2; exit 1; }
echo "tools_pounce: mesh + dispatch-pipeline smoke OK" >&2
rm -rf "$meshdir"

# serving-plane smoke (ISSUE 10): start a real daccord-serve HTTP server on
# the native engine, submit two overlapping jobs, and require each job's
# FASTA to be byte-identical to its solo `daccord` run, with lint-clean
# serve/group/job telemetry and a clean drain on shutdown — all CPU-side,
# before any chip time. A failure here means the cross-job batcher or the
# admission plane regressed; abort the pounce rather than serve on top of it.
servedir=$(mktemp -d)
python - "$servedir" <<'EOF' || { echo "tools_pounce: serve synth failed" >&2; exit 1; }
import sys
from daccord_tpu.sim.synth import SimConfig, make_dataset
make_dataset(sys.argv[1], SimConfig(genome_len=1500, coverage=10,
                                    read_len_mean=500, min_overlap=200,
                                    seed=5), name="sv")
EOF
python -m daccord_tpu.tools.cli daccord "$servedir/sv.db" "$servedir/sv.las" \
    --backend native -b 64 -o "$servedir/solo.fasta" \
  || { echo "tools_pounce: serve solo reference run FAILED" >&2; exit 1; }
python -m daccord_tpu.tools.cli serve --workdir "$servedir/srv" \
    --backend native -b 64 --port 0 --ready-file "$servedir/ready.json" \
    > "$servedir/serve.log" 2>&1 &
SERVE_PID=$!
python - "$servedir" <<'EOF' || { echo "tools_pounce: serve job round-trip FAILED" >&2; kill "$SERVE_PID" 2>/dev/null; exit 1; }
import json, os, sys, time, urllib.request
d = sys.argv[1]
for _ in range(300):
    if os.path.exists(f"{d}/ready.json"):
        break
    time.sleep(0.1)
else:
    raise SystemExit("serve never wrote its ready file")
port = json.load(open(f"{d}/ready.json"))["port"]
base = f"http://127.0.0.1:{port}"
def req(method, path, body=None):
    r = urllib.request.Request(base + path, method=method,
                               data=json.dumps(body).encode() if body is not None else None,
                               headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(r, timeout=300) as resp:
        return resp.read()
# two overlapping jobs, distinct tenants, same inputs (same solve
# fingerprint -> one warm group; cross-job batches whenever both have rows
# pooled inside the flush-lag window)
j1 = json.loads(req("POST", "/v1/jobs", {"db": f"{d}/sv.db", "las": f"{d}/sv.las", "tenant": "a"}))
j2 = json.loads(req("POST", "/v1/jobs", {"db": f"{d}/sv.db", "las": f"{d}/sv.las", "tenant": "b"}))
f1 = req("GET", f"/v1/jobs/{j1['job']}/result?wait=1")
f2 = req("GET", f"/v1/jobs/{j2['job']}/result?wait=1")
solo = open(f"{d}/solo.fasta", "rb").read()
assert f1 == solo, "job 1 FASTA diverged from the solo run"
assert f2 == solo, "job 2 FASTA diverged from the solo run"
m = json.loads(req("GET", "/v1/metrics"))
assert m["warm"]["misses"] == 1 and m["warm"]["hits"] >= 1, m["warm"]
hists = m["metrics"]["hists"]
assert "job_latency_s" in hists and hists["job_latency_s"]["p50"] is not None, \
    "latency quantiles missing from the metrics rollup"
# live prom scrape (ISSUE 13): the exposition the checker lints below is
# the one production actually serves, fetched over the wire
prom = req("GET", "/v1/metrics?format=prom")
assert b"daccord_serve_" in prom, "prom exposition empty"
# saturation profiler (ISSUE 14): the bottleneck verdict must be present
# in the LIVE exposition as a labeled gauge
assert b"daccord_serve_bottleneck_verdict" in prom, \
    "bottleneck verdict missing from the live prom exposition"
with open(f"{d}/metrics.prom", "wb") as fh:
    fh.write(prom)
# lock-free healthz now answers the on-call checklist
h = json.loads(req("GET", "/v1/healthz"))
assert "uptime_s" in h and "queue_depth" in h and "groups_busy" in h, h
# clean shutdown must drain in-flight work and exit 0
req("POST", "/v1/shutdown")
print("serve smoke: parity OK, latency p50 =", hists["job_latency_s"]["p50"])
EOF
wait "$SERVE_PID" \
  || { echo "tools_pounce: serve did not shut down cleanly" >&2; exit 1; }
python -m daccord_tpu.tools.cli eventcheck --strict \
    "$servedir/srv/serve.events.jsonl" "$servedir"/srv/g*.events.jsonl \
    "$servedir"/srv/jobs/*/events.jsonl \
  || { echo "tools_pounce: serve events failed schema lint" >&2; exit 1; }
python -m daccord_tpu.tools.cli trace --check --no-timeline \
    "$servedir/srv/serve.events.jsonl" "$servedir"/srv/g*.events.jsonl \
    "$servedir"/srv/jobs/*/events.jsonl "$servedir"/srv/jobs/*/ledger.jsonl \
  || { echo "tools_pounce: serve sidecars failed daccord-trace lint" >&2; exit 1; }
# every job's pipeline stage profile must reconcile (ISSUE 14)
python -m daccord_tpu.tools.cli prof --check "$servedir"/srv/jobs/*/events.jsonl \
  || { echo "tools_pounce: serve job sidecars failed daccord-prof reconciliation" >&2; exit 1; }
# scrape-parse the live prom exposition + the durable serve.metrics.prom,
# and run the sentinel strict over the whole serve workdir (ISSUE 13)
python -m daccord_tpu.tools.cli sentinel --strict "$servedir/srv" \
    --prom "$servedir/metrics.prom" \
  || { echo "tools_pounce: serve telemetry tripped the regression sentinel" >&2; exit 1; }
# one-shot operator snapshot must render from the same sidecars (CI form of
# the live `daccord-top srv/` screen)
python -m daccord_tpu.tools.cli top --once "$servedir/srv" \
  || { echo "tools_pounce: daccord-top failed over the serve workdir" >&2; exit 1; }
echo "tools_pounce: serving-plane smoke OK" >&2
rm -rf "$servedir"

# serve-crash smoke (ISSUE 15): kill -9 the server mid-job (deterministic
# serve_crash injection at a progress-checkpoint journal append), restart it
# on the same workdir, and require the journal replay to resume the job to a
# FASTA byte-identical to the solo run — with strict eventcheck + trace
# --check over the journal-bearing sidecars and a sentinel pass proving the
# recovery closed (a replayed-without-commit orphan trips it). This is the
# crash-durability contract gated before any chip time.
crashdir=$(mktemp -d)
python - "$crashdir" <<'EOF' || { echo "tools_pounce: crash-smoke synth failed" >&2; exit 1; }
import sys
from daccord_tpu.sim.synth import SimConfig, make_dataset
make_dataset(sys.argv[1], SimConfig(genome_len=1500, coverage=10,
                                    read_len_mean=500, min_overlap=200,
                                    seed=5), name="sv")
EOF
python -m daccord_tpu.tools.cli daccord "$crashdir/sv.db" "$crashdir/sv.las" \
    --backend native -b 64 -o "$crashdir/solo.fasta" \
  || { echo "tools_pounce: crash-smoke solo reference FAILED" >&2; exit 1; }
env DACCORD_FAULT=serve_crash:4 \
  python -m daccord_tpu.tools.cli serve --workdir "$crashdir/srv" \
    --backend native -b 64 --port 0 --ready-file "$crashdir/ready1.json" \
    --checkpoint-reads 2 \
    > "$crashdir/serve1.log" 2>&1 &
CRASH_PID=$!
python - "$crashdir" <<'EOF' || { echo "tools_pounce: crash-smoke submit FAILED" >&2; kill "$CRASH_PID" 2>/dev/null; exit 1; }
import json, os, sys, time, urllib.request
d = sys.argv[1]
for _ in range(300):
    if os.path.exists(f"{d}/ready1.json"):
        break
    time.sleep(0.1)
else:
    raise SystemExit("crash-smoke serve never wrote its ready file")
port = json.load(open(f"{d}/ready1.json"))["port"]
r = urllib.request.Request(f"http://127.0.0.1:{port}/v1/jobs", method="POST",
                           data=json.dumps({"db": f"{d}/sv.db",
                                            "las": f"{d}/sv.las",
                                            "idempotency_key": "crash-smoke"}).encode(),
                           headers={"Content-Type": "application/json"})
with urllib.request.urlopen(r, timeout=60) as resp:
    st = json.loads(resp.read())
open(f"{d}/job.txt", "w").write(st["job"])
EOF
wait "$CRASH_PID"; CRASH_RC=$?
[ "$CRASH_RC" -eq 137 ] \
  || { echo "tools_pounce: crash-smoke server exited $CRASH_RC (expected injected 137)" >&2; exit 1; }
python -m daccord_tpu.tools.cli serve --workdir "$crashdir/srv" \
    --backend native -b 64 --port 0 --ready-file "$crashdir/ready2.json" \
    --checkpoint-reads 2 \
    > "$crashdir/serve2.log" 2>&1 &
CRASH_PID=$!
python - "$crashdir" <<'EOF' || { echo "tools_pounce: crash-smoke resume/parity FAILED" >&2; kill "$CRASH_PID" 2>/dev/null; exit 1; }
import json, os, sys, time, urllib.request
d = sys.argv[1]
for _ in range(300):
    if os.path.exists(f"{d}/ready2.json"):
        break
    time.sleep(0.1)
else:
    raise SystemExit("crash-smoke restart never wrote its ready file")
port = json.load(open(f"{d}/ready2.json"))["port"]
job = open(f"{d}/job.txt").read().strip()
base = f"http://127.0.0.1:{port}"
# an idempotent resubmission must dedupe onto the replayed job, not rerun
r = urllib.request.Request(base + "/v1/jobs", method="POST",
                           data=json.dumps({"db": f"{d}/sv.db",
                                            "las": f"{d}/sv.las",
                                            "idempotency_key": "crash-smoke"}).encode(),
                           headers={"Content-Type": "application/json"})
with urllib.request.urlopen(r, timeout=60) as resp:
    dup = json.loads(resp.read())
    assert resp.status == 200 and dup["job"] == job, (resp.status, dup, job)
with urllib.request.urlopen(base + f"/v1/jobs/{job}/result?wait=1",
                            timeout=300) as resp:
    got = resp.read()
solo = open(f"{d}/solo.fasta", "rb").read()
assert got == solo, "resumed job FASTA diverged from the solo run"
urllib.request.urlopen(urllib.request.Request(base + "/v1/shutdown",
                                              method="POST"), timeout=60).read()
print("serve-crash smoke: resumed job byte-identical after kill -9")
EOF
wait "$CRASH_PID" \
  || { echo "tools_pounce: restarted serve did not shut down cleanly" >&2; exit 1; }
grep -q '"event": "serve.replay"' "$crashdir/srv/serve.events.jsonl" \
  || { echo "tools_pounce: restart emitted no serve.replay event" >&2; exit 1; }
python -m daccord_tpu.tools.cli eventcheck --strict \
    "$crashdir/srv/serve.events.jsonl" "$crashdir"/srv/g*.events.jsonl \
    "$crashdir"/srv/jobs/*/events.jsonl \
  || { echo "tools_pounce: crash-smoke events failed schema lint" >&2; exit 1; }
python -m daccord_tpu.tools.cli trace --check --no-timeline \
    "$crashdir/srv/serve.events.jsonl" "$crashdir"/srv/g*.events.jsonl \
    "$crashdir"/srv/jobs/*/events.jsonl "$crashdir"/srv/jobs/*/ledger.jsonl \
  || { echo "tools_pounce: crash-smoke sidecars failed daccord-trace lint" >&2; exit 1; }
python -m daccord_tpu.tools.cli sentinel --strict "$crashdir/srv" \
  || { echo "tools_pounce: crash-smoke tripped the regression sentinel (replay without commit?)" >&2; exit 1; }
echo "tools_pounce: serve-crash smoke OK" >&2
rm -rf "$crashdir"

# front-door smoke (ISSUE 16): two real daccord-serve peers share a peer-dir
# (announce leases) behind a real daccord-router. The tenant's rendezvous
# owner is computed up front and started with a deterministic SIGKILL at its
# first progress append; the client's retry with the SAME idempotency key
# must ride the router to the survivor and land exactly once, byte-identical
# to the solo run — the exactly-once contract THROUGH the front door, gated
# before any chip time. The router's own sidecar then passes the same strict
# eventcheck / trace / sentinel / top chain as every other plane.
routdir=$(mktemp -d)
python - "$routdir" <<'EOF' || { echo "tools_pounce: router-smoke synth failed" >&2; exit 1; }
import sys
from daccord_tpu.sim.synth import SimConfig, make_dataset
make_dataset(sys.argv[1], SimConfig(genome_len=1500, coverage=10,
                                    read_len_mean=500, min_overlap=200,
                                    seed=5), name="sv")
# the doomed peer is the tenant's rendezvous owner — computable before a
# single process starts, because the stickiness is pure hash (stateless)
from daccord_tpu.serve.router import Router
owner = max(["p1", "p2"], key=lambda n: Router._score("smoke", n))
open(f"{sys.argv[1]}/owner.txt", "w").write(owner)
EOF
python -m daccord_tpu.tools.cli daccord "$routdir/sv.db" "$routdir/sv.las" \
    --backend native -b 64 -o "$routdir/solo.fasta" \
  || { echo "tools_pounce: router-smoke solo reference FAILED" >&2; exit 1; }
OWNER=$(cat "$routdir/owner.txt")
if [ "$OWNER" = "p1" ]; then SURV=p2; else SURV=p1; fi
env DACCORD_FAULT=serve_crash:3 \
  python -m daccord_tpu.tools.cli serve --workdir "$routdir/$OWNER" \
    --backend native -b 64 --port 0 --ready-file "$routdir/ready-owner.json" \
    --checkpoint-reads 4 --peer-dir "$routdir/fleet" --lease-ttl-s 600 \
    > "$routdir/serve-owner.log" 2>&1 &
OWNER_PID=$!
python -m daccord_tpu.tools.cli serve --workdir "$routdir/$SURV" \
    --backend native -b 64 --port 0 --ready-file "$routdir/ready-surv.json" \
    --checkpoint-reads 4 --peer-dir "$routdir/fleet" --lease-ttl-s 600 \
    > "$routdir/serve-surv.log" 2>&1 &
SURV_PID=$!
python -m daccord_tpu.tools.cli router --workdir "$routdir/router" \
    --peer-dir "$routdir/fleet" --port 0 --poll-s 0.3 --lease-ttl-s 600 \
    --ready-file "$routdir/ready-router.json" \
    > "$routdir/router.log" 2>&1 &
ROUTER_PID=$!
python - "$routdir" <<'EOF' || { echo "tools_pounce: router-smoke submit FAILED" >&2; kill "$OWNER_PID" "$SURV_PID" "$ROUTER_PID" 2>/dev/null; exit 1; }
import json, os, sys, time, urllib.request
d = sys.argv[1]
for f in ("ready-owner.json", "ready-surv.json", "ready-router.json"):
    for _ in range(600):
        if os.path.exists(f"{d}/{f}"):
            break
        time.sleep(0.1)
    else:
        raise SystemExit(f"router smoke: {f} never appeared")
port = json.load(open(f"{d}/ready-router.json"))["port"]
base = f"http://127.0.0.1:{port}"
for _ in range(300):   # discovery: both peers announced AND polled ready
    with urllib.request.urlopen(base + "/v1/router", timeout=30) as resp:
        rs = json.loads(resp.read())
    if sum(1 for p in rs["peers"] if p["alive"] and p["ready"]) == 2:
        break
    time.sleep(0.1)
else:
    raise SystemExit(f"router smoke: fleet never turned ready: {rs['peers']}")
r = urllib.request.Request(base + "/v1/jobs", method="POST",
                           data=json.dumps({"db": f"{d}/sv.db",
                                            "las": f"{d}/sv.las",
                                            "tenant": "smoke",
                                            "idempotency_key": "fd-smoke"}).encode(),
                           headers={"Content-Type": "application/json"})
with urllib.request.urlopen(r, timeout=60) as resp:
    st = json.loads(resp.read())
with urllib.request.urlopen(base + "/v1/router", timeout=30) as resp:
    routed = json.loads(resp.read())["jobs"][st["job"]]
owner = open(f"{d}/owner.txt").read().strip()
assert routed == owner, f"stickiness broke: routed {routed}, owner {owner}"
EOF
wait "$OWNER_PID"; OWNER_RC=$?
[ "$OWNER_RC" -eq 137 ] \
  || { echo "tools_pounce: router-smoke owner exited $OWNER_RC (expected injected 137)" >&2; exit 1; }
python - "$routdir" <<'EOF' || { echo "tools_pounce: router-smoke retry/parity FAILED" >&2; kill "$SURV_PID" "$ROUTER_PID" 2>/dev/null; exit 1; }
import json, os, sys, time, urllib.error, urllib.request
d = sys.argv[1]
port = json.load(open(f"{d}/ready-router.json"))["port"]
base = f"http://127.0.0.1:{port}"
body = json.dumps({"db": f"{d}/sv.db", "las": f"{d}/sv.las",
                   "tenant": "smoke", "idempotency_key": "fd-smoke"}).encode()
def submit():
    r = urllib.request.Request(base + "/v1/jobs", method="POST", data=body,
                               headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(r, timeout=60) as resp:
        return resp.status, json.loads(resp.read())
job = None
deadline = time.time() + 60
while time.time() < deadline:   # early retries may see retryable 502/503
    try:
        stc, st = submit()
        if stc in (200, 201):
            job = st["job"]
            break
    except urllib.error.HTTPError as e:
        assert e.code in (502, 503), e.code
    except (urllib.error.URLError, OSError):
        pass
    time.sleep(0.3)
assert job, "retry never landed on the survivor"
with urllib.request.urlopen(base + f"/v1/jobs/{job}/result?wait=1",
                            timeout=300) as resp:
    got = resp.read()
solo = open(f"{d}/solo.fasta", "rb").read()
assert got == solo, "retried job FASTA diverged from the solo run"
stc, dup = submit()             # exactly once: the key dedupes, no rerun
assert stc == 200 and dup["job"] == job, (stc, dup, job)
for f in ("ready-owner.json", "ready-surv.json"):
    p = json.load(open(f"{d}/{f}"))["port"]
    try:
        urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{p}/v1/shutdown", method="POST"),
            timeout=60).read()
    except (urllib.error.URLError, OSError):
        pass                    # the dead owner: nothing to drain
urllib.request.urlopen(urllib.request.Request(base + "/v1/shutdown",
                                              method="POST"), timeout=60).read()
print("router smoke: retry landed exactly once, byte-identical")
EOF
wait "$SURV_PID" \
  || { echo "tools_pounce: surviving peer did not shut down cleanly" >&2; exit 1; }
wait "$ROUTER_PID" \
  || { echo "tools_pounce: router did not shut down cleanly" >&2; exit 1; }
grep -q '"event": "router.peer_down"' "$routdir/router/router.events.jsonl" \
  || { echo "tools_pounce: router never recorded the dead peer" >&2; exit 1; }
python -m daccord_tpu.tools.cli eventcheck --strict \
    "$routdir/router/router.events.jsonl" \
    "$routdir/$SURV/serve.events.jsonl" "$routdir/$SURV"/g*.events.jsonl \
    "$routdir/$SURV"/jobs/*/events.jsonl \
  || { echo "tools_pounce: router-smoke events failed schema lint" >&2; exit 1; }
python -m daccord_tpu.tools.cli trace --check --no-timeline \
    "$routdir/router/router.events.jsonl" \
    "$routdir/$SURV/serve.events.jsonl" "$routdir/$SURV"/g*.events.jsonl \
    "$routdir/$SURV"/jobs/*/events.jsonl \
  || { echo "tools_pounce: router-smoke sidecars failed daccord-trace lint" >&2; exit 1; }
python -m daccord_tpu.tools.cli sentinel --strict "$routdir/router" \
  || { echo "tools_pounce: router-smoke tripped the regression sentinel" >&2; exit 1; }
python -m daccord_tpu.tools.cli top --once "$routdir/router" \
  || { echo "tools_pounce: daccord-top failed over the router workdir" >&2; exit 1; }
echo "tools_pounce: front-door smoke OK" >&2
rm -rf "$routdir"

# disk-chaos smoke (ISSUE 17): the full storage fault matrix against two
# live serve peers — an io_enospc@journal burst on one, transient
# io_eio@lease on the other. The soak's own asserts ARE the contract (no
# process death, structured 507 refusals, byte parity, exactly-once
# commits, zero litter, full recovery); the tool belt then gates the
# artifacts: strict eventcheck + trace --check over the chaos sidecars,
# the sentinel MUST flag the deliberately-pressured workdirs (proving the
# disk red-flag wiring), and the committed chaos-flagged BENCH_DISK.json
# MUST pass the same sentinel (proving the chaos exemption).
diskdir=$(mktemp -d)
python - "$diskdir" <<'EOF' || { echo "tools_pounce: disk-chaos soak FAILED (degradation contract broke)" >&2; exit 1; }
import json, os, sys
sys.path.insert(0, os.getcwd())
import bench
line = bench.run_disk_soak(root=sys.argv[1], n_jobs=6)
print("disk-chaos smoke:", json.dumps({k: line[k] for k in (
    "jobs", "done", "refusals_507", "pressure_enter", "pressure_clear",
    "takeovers", "demotions")}))
EOF
python -m daccord_tpu.tools.cli eventcheck --strict \
    "$diskdir"/srv?/serve.events.jsonl "$diskdir"/srv?/g*.events.jsonl \
    "$diskdir"/srv?/jobs/*/events.jsonl \
  || { echo "tools_pounce: disk-chaos events failed schema lint" >&2; exit 1; }
python -m daccord_tpu.tools.cli trace --check --no-timeline \
    "$diskdir"/srv?/serve.events.jsonl "$diskdir"/srv?/g*.events.jsonl \
    "$diskdir"/srv?/jobs/*/events.jsonl \
  || { echo "tools_pounce: disk-chaos sidecars failed daccord-trace lint" >&2; exit 1; }
if python -m daccord_tpu.tools.cli sentinel --strict "$diskdir/srvA" \
    > "$diskdir/sentinel.out" 2>&1; then
  echo "tools_pounce: sentinel MISSED the injected disk pressure" >&2; exit 1
fi
grep -q "DISK PRESSURE" "$diskdir/sentinel.out" \
  || { echo "tools_pounce: sentinel flagged srvA for the wrong reason:" >&2; \
       cat "$diskdir/sentinel.out" >&2; exit 1; }
python -m daccord_tpu.tools.cli sentinel --strict BENCH_DISK.json \
  || { echo "tools_pounce: chaos-flagged BENCH_DISK.json tripped the sentinel (exemption broken)" >&2; exit 1; }
python -m daccord_tpu.tools.cli top --once "$diskdir/srvA" \
  || { echo "tools_pounce: daccord-top failed over the chaos workdir" >&2; exit 1; }
git add BENCH_DISK.json \
  && git commit -q -m "pounce: disk-chaos soak (${stamp})" \
  || echo "tools_pounce: BENCH_DISK.json unchanged (no commit)" >&2
echo "tools_pounce: disk-chaos smoke OK" >&2
rm -rf "$diskdir"

# net-chaos smoke (ISSUE 18): the network fault matrix against two live
# serve peers fronted by the resilient router — a reset storm on the
# submit domain, a torn/hung/grey-slow stream domain, and an asymmetric
# healthz partition (SIGSTOP) against a lease-fresh peer. The soak's own
# asserts ARE the contract (exactly-once commits under the reset storm,
# byte parity through torn/hung streams, zero drains/reaps/takeovers
# inside the partition window, breaker open AND re-close, full recovery);
# the tool belt then gates the artifacts: strict eventcheck + trace
# --check over the chaos sidecars, the sentinel MUST flag the partition
# window in the router workdir (proving the net red-flag wiring), and the
# committed chaos-flagged BENCH_NET.json MUST pass the same sentinel
# (proving the chaos exemption).
netdir=$(mktemp -d)
python - "$netdir" <<'EOF' || { echo "tools_pounce: net-chaos soak FAILED (resilience contract broke)" >&2; exit 1; }
import json, os, sys
sys.path.insert(0, os.getcwd())
import bench
line = bench.run_net_soak(root=sys.argv[1], n_jobs=6)
print("net-chaos smoke:", json.dumps({k: line[k] for k in (
    "jobs", "done", "net_fault_reset", "net_fault_torn", "net_fault_hang",
    "breaker_open", "breaker_closed", "partition_begin", "partition_end",
    "drain_or_reap_in_partition", "takeovers")}))
EOF
python -m daccord_tpu.tools.cli eventcheck --strict \
    "$netdir"/router/router.events.jsonl \
    "$netdir"/srv?/serve.events.jsonl "$netdir"/srv?/jobs/*/events.jsonl \
  || { echo "tools_pounce: net-chaos events failed schema lint" >&2; exit 1; }
python -m daccord_tpu.tools.cli trace --check --no-timeline \
    "$netdir"/router/router.events.jsonl \
    "$netdir"/srv?/serve.events.jsonl "$netdir"/srv?/jobs/*/events.jsonl \
  || { echo "tools_pounce: net-chaos sidecars failed daccord-trace lint" >&2; exit 1; }
if python -m daccord_tpu.tools.cli sentinel --strict "$netdir/router" \
    > "$netdir/sentinel.out" 2>&1; then
  echo "tools_pounce: sentinel MISSED the injected partition window" >&2; exit 1
fi
grep -q "ASYMMETRIC PARTITION" "$netdir/sentinel.out" \
  || { echo "tools_pounce: sentinel flagged the router for the wrong reason:" >&2; \
       cat "$netdir/sentinel.out" >&2; exit 1; }
python -m daccord_tpu.tools.cli sentinel --strict BENCH_NET.json \
  || { echo "tools_pounce: chaos-flagged BENCH_NET.json tripped the sentinel (exemption broken)" >&2; exit 1; }
python -m daccord_tpu.tools.cli top --once "$netdir/router" \
  || { echo "tools_pounce: daccord-top failed over the chaos router workdir" >&2; exit 1; }
git add BENCH_NET.json \
  && git commit -q -m "pounce: net-chaos soak (${stamp})" \
  || echo "tools_pounce: BENCH_NET.json unchanged (no commit)" >&2
echo "tools_pounce: net-chaos smoke OK" >&2
rm -rf "$netdir"

# SDC smoke (ISSUE 20): a chip that LIES — sdc:1@2 silently corrupts mesh
# member 2's result rows (no exception, valid alphabet) on the 1st fetch.
# The shadow audit (rate 1.0: every row sampled, detection deterministic)
# must catch it, attribute the culprit by replicated re-dispatch, and ship
# reference bytes — so the faulted FASTA is byte-identical to the clean
# control. Strict eventcheck covers the new sup_sdc/audit.*/trust.* kinds
# (including the trust-transition state machine). Throwaway compcache: the
# injected strike's trust verdict must not land in the host's real
# registry (a real run would then shrink the member out at sup_init).
sdcdir=$(mktemp -d)
sdccc="DACCORD_COMPCACHE=$sdcdir/cc"
python - "$sdcdir" <<'EOF' || { echo "tools_pounce: sdc synth failed" >&2; exit 1; }
import sys
from daccord_tpu.sim.synth import SimConfig, make_dataset
make_dataset(sys.argv[1], SimConfig(genome_len=1500, coverage=10,
                                    read_len_mean=500, min_overlap=200,
                                    seed=5), name="sdc")
EOF
env "$sdccc" JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m daccord_tpu.tools.cli daccord "$sdcdir/sdc.db" "$sdcdir/sdc.las" \
    --backend cpu -b 64 --mesh 8 --audit-rate 0 -o "$sdcdir/clean.fasta" \
  || { echo "tools_pounce: sdc clean control run FAILED" >&2; exit 1; }
env "$sdccc" JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    DACCORD_FAULT=sdc:1@2 DACCORD_TRUST_STRIKES=99 \
    python -m daccord_tpu.tools.cli daccord "$sdcdir/sdc.db" "$sdcdir/sdc.las" \
    --backend cpu -b 64 --mesh 8 --audit-rate 1.0 -o "$sdcdir/lie.fasta" \
    --events "$sdcdir/lie.events.jsonl" \
  || { echo "tools_pounce: sdc-injected mesh run FAILED" >&2; exit 1; }
cmp -s "$sdcdir/clean.fasta" "$sdcdir/lie.fasta" \
  || { echo "tools_pounce: the lie reached the FASTA (audit did not contain it)" >&2; exit 1; }
grep -q '"event": "sup_sdc"' "$sdcdir/lie.events.jsonl" \
  || { echo "tools_pounce: injected corruption was never detected" >&2; exit 1; }
grep -q '"event": "audit.attrib"' "$sdcdir/lie.events.jsonl" \
  || { echo "tools_pounce: detected corruption was never attributed" >&2; exit 1; }
python - "$sdcdir" <<'EOF' || { echo "tools_pounce: sdc culprit attribution FAILED" >&2; exit 1; }
import json, sys
d = sys.argv[1]
evs = [json.loads(x) for x in open(f"{d}/lie.events.jsonl")]
blamed = {e["culprit"] for e in evs
          if e.get("event") in ("sup_sdc", "audit.attrib")}
assert blamed == {2}, f"blamed {blamed}, injected liar was member 2"
trust = [e for e in evs if e.get("event") == "trust.state"]
assert trust and trust[0]["device"] == 2 \
    and trust[0]["state_to"] == "SUSPECT", trust
print("sdc smoke: member 2 caught lying, struck SUSPECT, bytes clean")
EOF
python -m daccord_tpu.tools.cli eventcheck --strict "$sdcdir/lie.events.jsonl" \
  || { echo "tools_pounce: sdc events failed schema lint" >&2; exit 1; }
python -m daccord_tpu.tools.cli trace --check --no-timeline "$sdcdir/lie.events.jsonl" \
  || { echo "tools_pounce: sdc sidecar failed daccord-trace lint" >&2; exit 1; }
# the contained lie is not a degraded outcome: no failover, no DEGRADED
# shard — the strict sentinel must stay green over the faulted sidecar
python -m daccord_tpu.tools.cli sentinel --strict "$sdcdir/lie.events.jsonl" \
  || { echo "tools_pounce: sdc sidecar tripped the regression sentinel" >&2; exit 1; }
echo "tools_pounce: sdc smoke OK" >&2
rm -rf "$sdcdir"

# front-door bench stage (ISSUE 16 satellite): cold-peer TTFR with/without
# the AOT cache + p99 through the router during a live scale-out
env DACCORD_BENCH_ROUTER=1 python bench.py > "BENCH_ROUTER_${stamp}.log" 2>&1 \
  && git add BENCH_ROUTER.json "BENCH_ROUTER_${stamp}.log" \
  && git commit -q -m "pounce: front-door router bench (${stamp})" \
  || echo "tools_pounce: router bench stage failed (non-fatal)" >&2

# serve bench stage (ISSUE 10 satellite): replay the default job-arrival
# trace against the server and commit the latency sidecar — the first
# serving number (p50/p99 + windows/sec) lands beside the rung ladder
env DACCORD_BENCH_SERVE=1 python bench.py > "BENCH_SERVE_${stamp}.log" 2>&1 \
  && git add BENCH_SERVE.json "BENCH_SERVE_${stamp}.log" \
  && git commit -q -m "pounce: serve latency bench (${stamp})" \
  || echo "tools_pounce: serve bench stage failed (non-fatal)" >&2

run() {  # run <name> <cmd...>: capture one experiment, commit its sidecar
  name=$1; shift
  out="POUNCE_${stamp}_${name}.json"
  ev="POUNCE_${stamp}_${name}.events.jsonl"
  # every bench emits its events sidecar (compile expectations, drain
  # heartbeats, supervisor transitions) — the machine-readable
  # compiling-vs-wedged-vs-dead signal whose absence killed two r5 benches
  DACCORD_BENCH_EVENTS="$ev" "$@" > "$out" 2> "POUNCE_${stamp}_${name}.log"
  if [ -s "$ev" ]; then
    # schema lint: a malformed events file is a bug worth catching now, but
    # never worth losing the measurement over
    python -m daccord_tpu.tools.cli eventcheck "$ev" \
      >> "POUNCE_${stamp}_${name}.log" 2>&1 || true
    git add "$ev"
  fi
  git add "$out" "POUNCE_${stamp}_${name}.log"
  git commit -q -m "pounce: ${name} on live chip (${stamp})"
}

# 1. SELF-STAGING BENCH LADDER FIRST (VERDICT r5 next-round #1, the fifth
# consecutive ask for an on-chip number): B=64 -> 256 -> 1024 -> 2048, each
# rung COMMITTED the moment it lands (B=256 cold-compiles in ~35 s, so a
# fallback:false sidecar exists inside minute two of any live window); the
# B=2048 compile warms in a background subprocess via the persistent XLA
# cache while the small rungs measure (bench.py announces every expected
# cold-compile wall — a long-silent rung is a compile, not a wedge; do NOT
# kill it)
run ladder           env DACCORD_BENCH_LADDER=1 python bench.py
# add each artifact individually: git add aborts the WHOLE command on one
# unmatched glob (e.g. no .warm.* files when the top rung was already
# cached), which would silently commit zero rung sidecars
for f in BENCH_LADDER_B*.json BENCH_LADDER_B*.warm.log BENCH_LADDER_B*.warm.events.jsonl; do
  [ -e "$f" ] && git add "$f"
done
git commit -q -m "pounce: bench ladder rung sidecars (${stamp})" || true
probe ladder
# 2. the open device decision rows, first minutes of the window
# (VERDICT r5 #4): fused-Pallas vs scan (open since r3), the fused-vs-split
# two-stream ladder row (ISSUE 4), the paged-vs-dense wire-format row
# (ISSUE 7: decision:paged — adopt --paged auto per the BASELINE.md rule),
# AND the mesh-vs-single decision row (ISSUE 12: decision:mesh over the
# visible device pool)
run ladder_rows      python -m daccord_tpu.tools.kernelbench --backend auto \
                       --stages ladder_full,ladder_pallas,ladder_paged,ladder_mesh,ladder_split
probe ladder_rows
# 2b. the on-chip mesh rung (ISSUE 12): mesh-N vs single-device pipelined
# throughput over the real device pool, committed as the next
# MULTICHIP_r*.json — the first measured point of the >=20x north star
run mesh_rung        env DACCORD_BENCH_MESH=1 python bench.py
for f in MULTICHIP_r*.json; do [ -e "$f" ] && git add "$f"; done
git commit -q -m "pounce: multichip mesh rung sidecar (${stamp})" || true
probe mesh_rung
# 3. esc_cap tail cost (experiment 3) — the fused-program comparator for
# the split ladder: B/8 rescue cap vs the split row above
run esccap256        env DACCORD_BENCH_ESC_CAP=256 python bench.py
probe esccap256
# 4. batch sweep 4096 (experiment 1), precompiled + announced (ADVICE r5
# #2: the server-side compile scales superlinearly with B — measured
# 256->35s, 1024->242s, 2048->925s — so warm the cache where the cold
# compile is expected and echoed instead of surfacing as a silent bench).
# 8192 dropped 2026-08-02: compile extrapolates to 2-4h even warm-cached.
run precompile4096   env DACCORD_BENCH_PRECOMPILE=1 DACCORD_BENCH_BATCH=4096 python bench.py
probe precompile4096
run batch4096        env DACCORD_BENCH_BATCH=4096 python bench.py
probe batch4096
# 5. candidates=5 cost (experiment 2)
run cand5            env DACCORD_BENCH_CANDIDATES=5 python bench.py
probe cand5
# 6. hp drain overlap on the real pipeline (experiment 7): hp on vs off
run hp_on            env DACCORD_BENCH_HP=1 python bench.py
probe hp_on
echo "pounce complete: POUNCE_${stamp}_*"
