#!/bin/sh
# Chip-revival pounce script (VERDICT r4 "Next round" #1): the ordered run
# of every queued hardware experiment (ARCHITECTURE.md "Queued hardware
# experiments"), each sidecar committed IMMEDIATELY so a tunnel that dies
# mid-sequence still leaves evidence. Run the moment TUNNEL_LOG.jsonl
# records alive:true:   sh tools_pounce.sh
set -x
cd /root/repo || exit 1

# EXCLUSIVITY, enforced in code (ADVICE r5 #1: the comment-only rule let a
# concurrent probe client wedge a 30-min bench): each probe opens a fresh
# axon client, and a concurrent client while a bench holds the device can
# leave the bench's RPC unanswered indefinitely. Kill the probe loop; abort
# if it will not die. Probe manually between runs instead.
if pgrep -f tools_probe_loop >/dev/null 2>&1; then
  echo "tools_pounce: killing running tools_probe_loop (probe/bench exclusivity)" >&2
  pkill -f tools_probe_loop
  sleep 3
  if pgrep -f tools_probe_loop >/dev/null 2>&1; then
    echo "tools_pounce: probe loop still alive after pkill; aborting" >&2
    exit 1
  fi
fi

stamp=$(date -u +%Y%m%dT%H%M%S)

# corruption-fuzz smoke (ingest integrity layer, ISSUE 2): synthesize a toy
# DB/LAS, bit-flip a record and tear the file mid-record, then require a
# quarantine-mode completion with lint-clean ingest.* events — all CPU-side,
# BEFORE any chip time is spent. A failure here means the ingest layer
# regressed; abort the pounce rather than bench on top of it.
fuzzdir=$(mktemp -d)
python - "$fuzzdir" <<'EOF' || { echo "tools_pounce: fuzz synth failed" >&2; exit 1; }
import sys
from daccord_tpu.sim.synth import SimConfig, make_dataset
from daccord_tpu.runtime import faults
d = sys.argv[1]
out = make_dataset(d, SimConfig(genome_len=1500, coverage=10,
                                read_len_mean=500, min_overlap=200, seed=5),
                   name="fuzz")
print(faults.corrupt_las_bitflip(out["las"], 4))
print(faults.corrupt_las_truncate(out["las"], 300))
EOF
python -m daccord_tpu.tools.cli daccord "$fuzzdir/fuzz.db" "$fuzzdir/fuzz.las" \
    --backend native -b 64 --ingest-policy quarantine \
    -o "$fuzzdir/fuzz.fasta" --events "$fuzzdir/fuzz.events.jsonl" \
  || { echo "tools_pounce: corruption-fuzz run FAILED" >&2; exit 1; }
python -m daccord_tpu.tools.cli eventcheck "$fuzzdir/fuzz.events.jsonl" \
  || { echo "tools_pounce: fuzz events failed schema lint" >&2; exit 1; }
grep -q '"event": "ingest.quarantine"' "$fuzzdir/fuzz.events.jsonl" \
  || { echo "tools_pounce: fuzz run quarantined nothing" >&2; exit 1; }
echo "tools_pounce: corruption-fuzz smoke OK" >&2
rm -rf "$fuzzdir"

# fleet smoke (shard fleet orchestrator, ISSUE 3): synth a toy dataset, run a
# 4-shard supervised fleet with an injected worker crash, lint the fleet
# event sidecar, and require the merged FASTA to be byte-identical to an
# unfaulted fleet run — all CPU-side, before any chip time. A failure here
# means the orchestrator/requeue/merge-gate layer regressed; abort the
# pounce rather than bench on top of it.
fleetdir=$(mktemp -d)
python - "$fleetdir" <<'EOF' || { echo "tools_pounce: fleet synth failed" >&2; exit 1; }
import sys
from daccord_tpu.sim.synth import SimConfig, make_dataset
make_dataset(sys.argv[1], SimConfig(genome_len=1500, coverage=10,
                                    read_len_mean=500, min_overlap=200,
                                    seed=5), name="fleet")
EOF
python -m daccord_tpu.tools.cli fleet "$fleetdir/fleet.db" "$fleetdir/fleet.las" \
    "$fleetdir/ref" -n 4 --workers 2 --backend native --checkpoint-every 4 \
    --merge "$fleetdir/ref.fasta" \
  || { echo "tools_pounce: clean fleet run FAILED" >&2; exit 1; }
DACCORD_FAULT=worker_crash:1 python -m daccord_tpu.tools.cli fleet \
    "$fleetdir/fleet.db" "$fleetdir/fleet.las" \
    "$fleetdir/crash" -n 4 --workers 2 --backend native --checkpoint-every 4 \
    --merge "$fleetdir/crash.fasta" \
  || { echo "tools_pounce: crash-injected fleet run FAILED" >&2; exit 1; }
python -m daccord_tpu.tools.cli eventcheck --strict \
    "$fleetdir/ref/fleet.events.jsonl" "$fleetdir/crash/fleet.events.jsonl" \
  || { echo "tools_pounce: fleet events failed schema lint" >&2; exit 1; }
grep -q '"event": "fleet.retry"' "$fleetdir/crash/fleet.events.jsonl" \
  || { echo "tools_pounce: injected worker crash was never requeued" >&2; exit 1; }
cmp -s "$fleetdir/ref.fasta" "$fleetdir/crash.fasta" \
  || { echo "tools_pounce: crash-requeued fleet FASTA diverged from clean run" >&2; exit 1; }
echo "tools_pounce: fleet smoke OK" >&2
rm -rf "$fleetdir"

run() {  # run <name> <cmd...>: capture one experiment, commit its sidecar
  name=$1; shift
  out="POUNCE_${stamp}_${name}.json"
  ev="POUNCE_${stamp}_${name}.events.jsonl"
  # every bench emits its events sidecar (compile expectations, drain
  # heartbeats, supervisor transitions) — the machine-readable
  # compiling-vs-wedged-vs-dead signal whose absence killed two r5 benches
  DACCORD_BENCH_EVENTS="$ev" "$@" > "$out" 2> "POUNCE_${stamp}_${name}.log"
  if [ -s "$ev" ]; then
    # schema lint: a malformed events file is a bug worth catching now, but
    # never worth losing the measurement over
    python -m daccord_tpu.tools.cli eventcheck "$ev" \
      >> "POUNCE_${stamp}_${name}.log" 2>&1 || true
    git add "$ev"
  fi
  git add "$out" "POUNCE_${stamp}_${name}.log"
  git commit -q -m "pounce: ${name} on live chip (${stamp})"
}

# 0. warm the persistent XLA cache for the sweep batch sizes FIRST
# (ADVICE r5 #2): the server-side compile scales superlinearly with B
# (measured 256->35s, 1024->242s, 2048->925s), so precompile 2048/4096 into
# the cache where a cold compile is expected and announced (bench echoes the
# expected wall) instead of surfacing as an unexplained silent bench
run precompile2048   env DACCORD_BENCH_PRECOMPILE=1 python bench.py
run precompile4096   env DACCORD_BENCH_PRECOMPILE=1 DACCORD_BENCH_BATCH=4096 python bench.py
# 1. flagship bench first (pipelined + device_compute + stage breakdown)
run bench            python bench.py
# 2. batch sweep (experiment 1). 8192 dropped 2026-08-02: compile
# extrapolates to 2-4h even warm-cached once; 4096 is precompiled above.
run batch4096        env DACCORD_BENCH_BATCH=4096 python bench.py
# 3. esc_cap tail cost (experiment 3)
run esccap256        env DACCORD_BENCH_ESC_CAP=256 python bench.py
# 4. candidates=5 cost (experiment 2)
run cand5            env DACCORD_BENCH_CANDIDATES=5 python bench.py
# 5. fused Pallas vs scan decision row (experiment 6)
run ladder_pallas    python -m daccord_tpu.tools.kernelbench --backend auto \
                       --stages ladder_full,ladder_pallas
# 6. hp drain overlap on the real pipeline (experiment 7): hp on vs off
run hp_on            env DACCORD_BENCH_HP=1 python bench.py
echo "pounce complete: POUNCE_${stamp}_*"
