"""Numpy-facing wrappers over the native library (ctypes marshalling)."""

from __future__ import annotations

import ctypes

import numpy as np

from . import load


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


class ColumnarLas:
    """Whole-file columnar LAS arrays (native parse)."""

    __slots__ = ("tspace", "novl", "aread", "bread", "abpos", "aepos", "bbpos",
                 "bepos", "comp", "diffs", "trace_off", "trace_flat", "pile_starts")

    def __init__(self, path: str, start: int | None = None, end: int | None = None):
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        b0 = 0 if start is None else int(start)
        b1 = 0 if end is None else int(end)
        novl = ctypes.c_int64()
        tspace = ctypes.c_int32()
        telems = ctypes.c_int64()
        rc = lib.las_scan(path.encode(), b0, b1, ctypes.byref(novl),
                          ctypes.byref(tspace), ctypes.byref(telems))
        if rc != 0:
            raise IOError(f"las_scan({path}) failed: {rc}")
        n, te = novl.value, telems.value
        self.novl, self.tspace = n, tspace.value
        self.aread = np.empty(n, np.int32)
        self.bread = np.empty(n, np.int32)
        self.abpos = np.empty(n, np.int32)
        self.aepos = np.empty(n, np.int32)
        self.bbpos = np.empty(n, np.int32)
        self.bepos = np.empty(n, np.int32)
        self.comp = np.empty(n, np.uint8)
        self.diffs = np.empty(n, np.int32)
        self.trace_off = np.empty(n + 1, np.int64)
        self.trace_flat = np.empty(te, np.int32)
        rc = lib.las_load(path.encode(), b0, b1, n, _ptr(self.aread), _ptr(self.bread),
                          _ptr(self.abpos), _ptr(self.aepos), _ptr(self.bbpos),
                          _ptr(self.bepos), _ptr(self.comp), _ptr(self.diffs),
                          _ptr(self.trace_off), _ptr(self.trace_flat))
        if rc != 0:
            raise IOError(f"las_load({path}) failed: {rc}")
        # pile boundaries (file sorted by aread)
        if n:
            change = np.nonzero(np.diff(self.aread))[0] + 1
            self.pile_starts = np.concatenate([[0], change, [n]]).astype(np.int64)
        else:
            self.pile_starts = np.zeros(1, np.int64)

    def piles(self):
        for p in range(len(self.pile_starts) - 1):
            s, e = int(self.pile_starts[p]), int(self.pile_starts[p + 1])
            yield int(self.aread[s]), s, e


def decode_reads_batch(bps: np.ndarray, boffs: np.ndarray,
                       rlens: np.ndarray) -> list[np.ndarray]:
    """Decode a batch of 2-bit packed reads into views over one buffer."""
    lib = load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    n = len(rlens)
    boffs = np.ascontiguousarray(boffs, dtype=np.int64)
    rlens = np.ascontiguousarray(rlens, dtype=np.int32)
    out_off = np.zeros(n + 1, np.int64)
    np.cumsum(rlens, out=out_off[1:])
    out = np.empty(int(out_off[-1]), np.int8)
    bps = np.ascontiguousarray(bps, dtype=np.uint8)
    rc = lib.decode_reads(_ptr(bps), _ptr(boffs), _ptr(rlens), n,
                          _ptr(out), _ptr(out_off))
    if rc != 0:
        raise RuntimeError(f"decode_reads failed: {rc}")
    return [out[out_off[i] : out_off[i + 1]] for i in range(n)]


def process_pile_native(a_bases: np.ndarray, col: ColumnarLas, s: int, e: int,
                        b_reads: list[np.ndarray],
                        w: int, adv: int, D: int, L: int,
                        include_a: bool = True,
                        order: np.ndarray | None = None):
    """Windows of one pile as batch tensors via the native hot path.

    ``b_reads``: decoded stored-orientation B bases per overlap, already in
    ``order`` if one is given. ``order`` permutes the pile (indices into
    [0, e-s)) — used for quality-ranked depth capping.
    Returns (seqs [nwin,D,L] int8, lens [nwin,D] i32, nsegs [nwin] i32).
    """
    lib = load()
    novl = e - s
    alen = len(a_bases)
    nwin = 0 if alen < w else (alen - w) // adv + 1
    seqs = np.full((nwin, D, L), 4, dtype=np.int8)
    lens = np.zeros((nwin, D), dtype=np.int32)
    nsegs = np.zeros(nwin, dtype=np.int32)
    if nwin == 0:
        return seqs, lens, nsegs

    b_off = np.zeros(novl + 1, np.int64)
    np.cumsum([len(b) for b in b_reads], out=b_off[1:])
    b_concat = (np.concatenate(b_reads) if b_reads else np.zeros(0, np.int8)).astype(np.int8, copy=False)
    b_len = np.asarray([len(b) for b in b_reads], dtype=np.int32)
    a_c = np.ascontiguousarray(a_bases, dtype=np.int8)

    if order is None:
        # rebase trace offsets for the contiguous pile slice
        toff = (col.trace_off[s : e + 1] - col.trace_off[s]).astype(np.int64)
        tflat = col.trace_flat[col.trace_off[s] : col.trace_off[e]]
        tflat = np.ascontiguousarray(tflat, dtype=np.int32)
        sel = slice(s, e)
        abpos = np.ascontiguousarray(col.abpos[sel])
        aepos = np.ascontiguousarray(col.aepos[sel])
        bbpos = np.ascontiguousarray(col.bbpos[sel])
        bepos = np.ascontiguousarray(col.bepos[sel])
        comp = np.ascontiguousarray(col.comp[sel])
    else:
        gi = s + np.asarray(order, dtype=np.int64)
        abpos = np.ascontiguousarray(col.abpos[gi])
        aepos = np.ascontiguousarray(col.aepos[gi])
        bbpos = np.ascontiguousarray(col.bbpos[gi])
        bepos = np.ascontiguousarray(col.bepos[gi])
        comp = np.ascontiguousarray(col.comp[gi])
        tlens = (col.trace_off[gi + 1] - col.trace_off[gi]).astype(np.int64)
        toff = np.zeros(novl + 1, np.int64)
        np.cumsum(tlens, out=toff[1:])
        tflat = np.empty(int(toff[-1]), np.int32)
        for j, g in enumerate(gi):
            tflat[toff[j] : toff[j + 1]] = col.trace_flat[col.trace_off[g] : col.trace_off[g + 1]]

    rc = lib.process_pile(_ptr(a_c), alen, novl,
                          _ptr(abpos), _ptr(aepos), _ptr(bbpos), _ptr(bepos),
                          _ptr(comp),
                          _ptr(b_concat), _ptr(b_off), _ptr(b_len),
                          _ptr(tflat), _ptr(toff),
                          col.tspace, w, adv, D, L, 1 if include_a else 0,
                          _ptr(seqs), _ptr(lens), _ptr(nsegs), nwin)
    if rc != 0:
        raise RuntimeError(f"process_pile failed: {rc}")
    return seqs, lens, nsegs


def las_sort_native(in_path: str, out_path: str, tmp_dir: str,
                    mem_records: int) -> int:
    """Native external LAS sort (LAsort role); returns the record count.

    Byte-identical to ``formats.extsort.sort_las_external``'s Python path for
    the same ``mem_records`` (same run partitioning, stable sort, earliest-
    run-wins merge — parity-tested)."""
    lib = load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    n = lib.las_sort(in_path.encode(), out_path.encode(), tmp_dir.encode(),
                     int(mem_records))
    if n < 0:
        raise IOError(f"las_sort({in_path}) failed: {n}")
    return int(n)


def las_merge_native(in_paths: list[str], out_path: str, tspace: int) -> int:
    """Native k-way merge of sorted headered LAS files (LAmerge role);
    returns the record count. Same ordering semantics as the Python
    heapq.merge path (parity-tested)."""
    lib = load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    blob = b"\x00".join(p.encode() for p in in_paths) + b"\x00\x00"
    n = lib.las_merge(blob, out_path.encode(), int(tspace))
    if n < 0:
        raise IOError(f"las_merge failed: {n}")
    return int(n)


class NativeLadder:
    """Pre-packed tier tables/params for the C++ consensus engine.

    ``solve_windows_native`` rebuilt the concatenated table arrays on every
    call; a pipeline run makes thousands of calls against the same tables,
    so the prep is hoisted here — build once per run, call ``solve`` per
    batch. Semantics identical to :func:`solve_windows_native`.
    """

    def __init__(self, ol_tables: dict, cfg, max_kmers: int = 0,
                 rescue_max_kmers: int = 256, _share=None):
        self.cfg = cfg
        # the hp posterior vote needs the error profile (every OL table
        # carries the same one)
        self.profile = (_share.profile if _share is not None else
                        (next(iter(ol_tables.values())).profile
                         if ol_tables else None))
        d = cfg.dbg
        tiers = list(cfg.tiers)
        if _share is not None:
            # caps-only variant: the packed tables (the heavy part) are
            # shared with the donor ladder — see with_caps
            for f in ("tables", "table_off", "tier_k", "tier_minc",
                      "tier_eminc", "tier_P", "tier_O"):
                setattr(self, f, getattr(_share, f))
        else:
            tabs = []
            offs = [0]
            for k, _, _ in tiers:
                t = np.ascontiguousarray(ol_tables[k].table, dtype=np.float32)
                tabs.append(t.reshape(-1))
                offs.append(offs[-1] + t.size)
            self.tables = np.concatenate(tabs)
            self.table_off = np.asarray(offs[:-1], dtype=np.int64)
            self.tier_k = np.asarray([t[0] for t in tiers], dtype=np.int32)
            self.tier_minc = np.asarray([t[1] for t in tiers], dtype=np.int32)
            self.tier_eminc = np.asarray([t[2] for t in tiers],
                                         dtype=np.int32)
            self.tier_P = np.asarray([ol_tables[t[0]].P for t in tiers],
                                     dtype=np.int32)
            self.tier_O = np.asarray([ol_tables[t[0]].O for t in tiers],
                                     dtype=np.int32)
        self.tier_M = np.asarray(
            [0 if max_kmers <= 0 else
             (rescue_max_kmers if t[1] <= 1 else max_kmers)
             for t in tiers], dtype=np.int32)
        self.n_tiers = len(tiers)
        self.CL = cfg.w + d.len_slack
        self._d = d

    def hp_rescue(self, batch, out: dict, n_threads: int = 1) -> int:
        """In-engine homopolymer rescue (oracle/hp.py semantics, C++ — see
        ``dazz_native.cpp hp_rescue_windows``): post-processes a ``solve``
        result in place. Rescued rows may exceed CL, so ``out['cons']`` is
        re-allocated at the hp width (2*w) with rescued rows overwritten;
        ``cons_len``/``err``/``tier`` update in place (tier 29 = HP_TIER).
        Returns the rescued count. Run AFTER any overflow-rescue pass so
        ordering matches the python host pass."""
        lib = load()
        import ctypes

        cfg = self.cfg
        d = self._d
        k0, minc0, eminc0 = cfg.tiers[0]
        seqs = np.ascontiguousarray(batch.seqs, dtype=np.int8)
        lens = np.ascontiguousarray(batch.lens, dtype=np.int32)
        nsegs = np.ascontiguousarray(batch.nsegs, dtype=np.int32)
        B, D, L = seqs.shape
        CLH = 2 * cfg.w
        hp_cons = np.full((B, CLH), 4, dtype=np.int8)
        cons_in = np.ascontiguousarray(out["cons"], dtype=np.int8)
        # calibrated posterior vote (r5): tables are built ONCE (cached on
        # self) by the same numpy code as the python host pass (bit-exact
        # likelihoods), one per quantized heat-grid multiplier (the shared
        # grid constants in oracle/hp.py); the C++ side only mirrors the
        # vote walk. Engages under the same slope gate as oracle/hp.py.
        from ..oracle.hp import (HP_HEAT_LO, HP_HEAT_N, HP_HEAT_STEP,
                                 hp_length_tables)

        prof = self.profile
        post_tabs = getattr(self, "_post_tabs", None)
        if (post_tabs is None
                and getattr(cfg, "hp_vote", "median") == "posterior"
                and prof is not None and prof.hp_slope >= 0.1):
            post_tabs = np.ascontiguousarray(
                np.stack([hp_length_tables(
                    prof, mult=HP_HEAT_LO + HP_HEAT_STEP * i)
                    for i in range(HP_HEAT_N)]), dtype=np.float64)
            self._post_tabs = post_tabs
        p_err = ((prof.p_ins + prof.p_del + prof.p_sub)
                 if prof is not None else 0.0)
        lib.hp_rescue_windows.restype = ctypes.c_int64
        n = int(lib.hp_rescue_windows(
            _ptr(seqs), _ptr(lens), _ptr(nsegs), B, D, L,
            _ptr(self.tables), int(self.tier_P[0]), int(self.tier_O[0]),
            int(k0), int(minc0), int(eminc0),
            cfg.w, d.anchor_slack, d.end_slack, d.len_slack,
            d.n_candidates, d.min_depth, ctypes.c_double(d.max_err),
            ctypes.c_float(d.count_frac),
            ctypes.c_double(cfg.hp_err), int(cfg.hp_min_run),
            ctypes.c_double(cfg.hp_margin), int(n_threads),
            _ptr(cons_in), int(cons_in.shape[1]),
            _ptr(hp_cons), CLH,
            _ptr(out["cons_len"]), _ptr(out["err"]), _ptr(out["tier"]),
            _ptr(post_tabs) if post_tabs is not None else None,
            HP_HEAT_N if post_tabs is not None else 0,
            int(post_tabs.shape[1] - 1) if post_tabs is not None else 0,
            int(post_tabs.shape[2] - 1) if post_tabs is not None else 0,
            ctypes.c_double(p_err),
            ctypes.c_double(HP_HEAT_LO), ctypes.c_double(HP_HEAT_STEP),
            int(getattr(cfg, "hp_accept", "rescore") == "likelihood"),
            ctypes.c_double(getattr(cfg, "hp_lambda_c", 3.0))))
        if n < 0:
            raise RuntimeError(f"hp_rescue_windows failed: {n}")
        if n:
            rescued = out["tier"] == 29
            merged = np.full((B, max(CLH, cons_in.shape[1])), 4, dtype=np.int8)
            merged[:, : cons_in.shape[1]] = cons_in
            merged[rescued, :CLH] = hp_cons[rescued]
            out["cons"] = merged
            out["solved"] = out["tier"] >= 0
        return n

    def with_caps(self, max_kmers: int, rescue_max_kmers: int = 256
                  ) -> "NativeLadder":
        """Caps-only variant sharing this ladder's packed tables (tier_M is
        the only per-cap array; everything heavy is reused)."""
        return NativeLadder(None, self.cfg, max_kmers, rescue_max_kmers,
                            _share=self)

    def solve(self, batch, n_threads: int = 1) -> dict:
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        import ctypes

        d = self._d
        seqs = np.ascontiguousarray(batch.seqs, dtype=np.int8)
        lens = np.ascontiguousarray(batch.lens, dtype=np.int32)
        nsegs = np.ascontiguousarray(batch.nsegs, dtype=np.int32)
        B, D, L = seqs.shape
        cons = np.empty((B, self.CL), dtype=np.int8)
        cons_len = np.empty(B, dtype=np.int32)
        errs = np.empty(B, dtype=np.float32)
        tiers_out = np.empty(B, dtype=np.int32)
        movf = np.empty(B, dtype=np.uint8)
        rc = lib.solve_windows(
            _ptr(seqs), _ptr(lens), _ptr(nsegs), B, D, L,
            _ptr(self.tables), _ptr(self.table_off), _ptr(self.tier_k),
            _ptr(self.tier_minc), _ptr(self.tier_eminc), _ptr(self.tier_P),
            _ptr(self.tier_O), _ptr(self.tier_M), self.n_tiers,
            self.cfg.w, d.anchor_slack, d.end_slack, d.len_slack,
            d.n_candidates, d.min_depth, ctypes.c_float(d.max_err),
            ctypes.c_float(d.count_frac), int(n_threads),
            _ptr(cons), _ptr(cons_len), _ptr(errs), _ptr(tiers_out),
            _ptr(movf))
        if rc != 0:
            raise RuntimeError(f"solve_windows failed: {rc}")
        return dict(cons=cons, cons_len=cons_len, err=errs,
                    solved=tiers_out >= 0, tier=tiers_out,
                    m_ovf=movf.astype(bool))


def solve_windows_native(batch, ol_tables: dict, cfg, n_threads: int = 1,
                         max_kmers: int = 0,
                         rescue_max_kmers: int = 256) -> dict:
    """Native tier-ladder consensus over a WindowBatch; the C++ replica of
    ``oracle.consensus.solve_window``. Returns the ``solve_tiered``-shaped
    dict. One-shot convenience over :class:`NativeLadder` (which callers
    making many calls against the same tables should hold instead).

    ``max_kmers=0`` (default) = full-graph oracle semantics, no truncation,
    ``m_ovf`` all False. ``max_kmers>0`` mirrors the device ladder's top-M
    compaction (count desc, smaller code wins ties; min_count<=1 rescue
    tiers get ``rescue_max_kmers``) — measured a beneficial noise filter on
    CLR regimes (BASELINE.md r3 top-M table); ``m_ovf`` flags truncated
    windows.

    ``ol_tables``: k -> OffsetLikely (oracle ``make_offset_likely`` output).
    ``cfg``: ConsensusConfig (tiers + dbg params + w).
    """
    return NativeLadder(ol_tables, cfg, max_kmers,
                        rescue_max_kmers).solve(batch, n_threads)
