// Native host layer: LAS columnar loader + pile -> window-tensor extraction.
//
// C++ equivalents of the reference's hot host-side components (SURVEY.md
// §2.2/§2.4): the libmaus2 dazzler/align streaming parser and the
// trace-point -> base-accurate window segmentation done with lcs::NP inside
// src/daccord.cpp (file:line citations pending backfill — reference mount
// empty, SURVEY.md §0). Exposed as a C ABI for ctypes; built by
// daccord_tpu/native/build.py with g++ -O3 (no pybind11 in this image).
//
// The tile realignment replicates oracle.align.align_path exactly (full
// unit-cost DP, backtrack preferring diagonal, then deletion, then insertion,
// a2b[0] = 0) so the native path is bit-identical to the Python oracle.

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <limits>
#include <queue>
#include <string>
#include <thread>
#include <vector>
#include <algorithm>

namespace {

constexpr int8_t PAD = 4;

constexpr int32_t DP_INF = 1 << 28;

// One banded DP fill (Ukkonen): only cells with lo_d <= j - i <= hi_d are
// computed; cells one past each band edge hold DP_INF sentinels so both the
// next row's reads and the backtrack see +inf outside the band. Returns the
// banded distance (>= the true distance; equal when the band held).
static int32_t fill_banded(const int8_t* a, int n, const int8_t* b, int m,
                           int32_t* D, int W, int lo_d, int hi_d) {
  static thread_local std::vector<int32_t> cbuf_v;
  cbuf_v.resize(W + 1);
  int32_t* cbuf = cbuf_v.data();
  {
    const int jhi = std::min(m, hi_d);
    for (int j = 0; j <= jhi; ++j) D[j] = j;
    if (jhi < m) D[jhi + 1] = DP_INF;
  }
  for (int i = 1; i <= n; ++i) {
    int32_t* row = D + (size_t)i * W;
    const int32_t* prev = row - W;
    const int jlo = std::max(0, i + lo_d);
    const int jhi = std::min(m, i + hi_d);
    if (jlo > jhi) return DP_INF;
    if (jlo > 0) row[jlo - 1] = DP_INF;
    if (jhi < m) row[jhi + 1] = DP_INF;
    const int8_t ai = a[i - 1];
    int j = jlo;
    if (j == 0) { row[0] = i; ++j; }
    // pass 1 (no loop-carried dependency -> SIMD): substitution/deletion
    // candidates from the previous row
    for (int j2 = j; j2 <= jhi; ++j2) {
      const int32_t sub = prev[j2 - 1] + (b[j2 - 1] != ai);
      const int32_t del = prev[j2] + 1;
      cbuf[j2] = del < sub ? del : sub;
    }
    // pass 2 (serial but 2 ops/cell): fold in the insertion chain
    int32_t run = row[j - 1];
    for (int j2 = j; j2 <= jhi; ++j2) {
      ++run;
      if (cbuf[j2] < run) run = cbuf[j2];
      row[j2] = run;
    }
  }
  return D[(size_t)n * W + m];
}

// full unit-cost edit DP with backtrack -> prefix map a2b (len n+1).
// Banded with verify-retry: when the returned distance d satisfies d < band
// slack B, every cell of every optimal path is interior to the band, those
// cells' banded values are exact, and the backtrack equalities decide
// identically to the full matrix — so the result is bit-identical to the
// full DP (the Python oracle's align_path) by construction, at ~half the
// cells for typical ~15%-error trace tiles. d >= B doubles the band.
// verify-retry driver: fill with a band of slack B, accept when d < B (every
// optimal path provably interior -> exact), else double. Leaves D filled for
// backtrack. The ONE copy of the exactness rule (align_path AND
// edit_distance_sum call it).
static int32_t fill_exact(const int8_t* a, int n, const int8_t* b, int m,
                          int32_t* D, int W, int32_t band_hint) {
  const int diff_lo = std::min(0, m - n), diff_hi = std::max(0, m - n);
  for (int32_t B = std::max(4, band_hint);; B *= 2) {
    if (diff_hi - diff_lo + 2 * B >= m)   // band no narrower than full width
      return fill_banded(a, n, b, m, D, W, -n, m);
    const int32_t d = fill_banded(a, n, b, m, D, W, diff_lo - B, diff_hi + B);
    if (d < B) return d;
  }
}

// ---------------------------------------------------------------------------
// Hyyro/Myers bit-parallel exact DP (r5 feeder lever, SURVEY.md §7.3 item 5)
// ---------------------------------------------------------------------------
// Unbanded and EXACT by construction (no verify-retry needed): the b side
// packs into K = ceil(m/64) words and each a row costs ~17 ops/word instead
// of 2-3 ops/cell. Per-row VP/VN (the deltas D[i][j]-D[i][j-1] along b) are
// stored — 16 bytes/row/word vs the int32 matrix's 4 bytes/cell — and the
// backtrack recovers the EXACT SAME decisions as the matrix walk from delta
// bits: with V = D[i][j]-D[i-1][j] (the step's HP/HN, recomputed per visited
// row from the stored previous-row VP/VN) and Hp = D[i-1][j]-D[i-1][j-1]
// (stored), the matrix conditions rewrite as
//     diagonal:  D[i][j] == D[i-1][j-1] + c   <=>  V + Hp == c
//     deletion:  D[i][j] == D[i-1][j] + 1     <=>  V == +1
// evaluated in the identical diagonal > deletion > insertion order, so a2b
// is bit-identical to the int32 backtrack (sealed by parity tests).
constexpr int MYERS_MAX_M = 256;   // 4 words; wider falls back to the matrix

struct MyersScratch {
  std::vector<uint64_t> peq;   // [5][K] match masks (incl. PAD=4: the
  //                              backtrack compares a!=b directly, so the
  //                              fill must also treat PAD==PAD as a match)
  std::vector<uint64_t> vp, vn;  // per-row stored deltas, (n+1)*K
  std::vector<uint64_t> hp, hn;  // K words, scratch for one step
  std::vector<uint64_t> t0, t1;  // discarded VP/VN outputs (backtrack
  //                                recompute wants HP/HN only; outputs must
  //                                NOT alias hp/hn — the step interleaves
  //                                HP/VP writes per word)
};

// one Myers step: from row i-1's VP/VN produce row i's, plus the step's
// HP/HN (= vertical deltas V(i, :) in matrix terms). Multi-word with carry.
static inline void myers_step(const uint64_t* peq_t, const uint64_t* VPp,
                              const uint64_t* VNp, uint64_t* HP, uint64_t* HN,
                              uint64_t* VP, uint64_t* VN, int K) {
  uint64_t carry = 0, hp_in = 1, hn_in = 0;   // hp_in=1: column 0 walks down
  for (int w = 0; w < K; ++w) {
    const uint64_t X = peq_t[w] | VNp[w];
    const uint64_t av = X & VPp[w];
    const uint64_t t = av + VPp[w];
    const uint64_t sum = t + carry;
    carry = (uint64_t)(t < av) | (uint64_t)(sum < t);
    const uint64_t D0 = (sum ^ VPp[w]) | X;
    const uint64_t hp = VNp[w] | ~(VPp[w] | D0);
    const uint64_t hn = VPp[w] & D0;
    HP[w] = hp; HN[w] = hn;
    const uint64_t hpw = (hp << 1) | hp_in; hp_in = hp >> 63;
    const uint64_t hnw = (hn << 1) | hn_in; hn_in = hn >> 63;
    VN[w] = hpw & D0;
    VP[w] = hnw | ~(hpw | D0);
  }
}

static inline void myers_build_peq(const int8_t* b, int m, int K,
                                   MyersScratch& S) {
  S.peq.assign((size_t)5 * K, 0);
  for (int j = 0; j < m; ++j) {
    const int8_t c = b[j];
    if (c >= 0 && c < 5)
      S.peq[(size_t)c * K + (j >> 6)] |= (uint64_t)1 << (j & 63);
  }
}

// distance-only variant (edit_distance_sum's path): no row storage.
static int32_t myers_dist(const int8_t* a, int n, const int8_t* b, int m,
                          MyersScratch& S) {
  const int K = (m + 63) >> 6;
  myers_build_peq(b, m, K, S);
  S.vp.assign(2 * K, ~(uint64_t)0);
  S.vn.assign(2 * K, 0);
  S.hp.resize(K); S.hn.resize(K);
  uint64_t* vp0 = S.vp.data(); uint64_t* vp1 = vp0 + K;
  uint64_t* vn0 = S.vn.data(); uint64_t* vn1 = vn0 + K;
  int32_t score = m;
  const int mw = (m - 1) >> 6;
  const uint64_t mb = (uint64_t)1 << ((m - 1) & 63);
  for (int i = 1; i <= n; ++i) {
    const int8_t c = a[i - 1];
    myers_step(S.peq.data() + (size_t)(c < 0 || c > 4 ? 4 : c) * K,
               vp0, vn0, S.hp.data(), S.hn.data(), vp1, vn1, K);
    score += (S.hp[mw] & mb) ? 1 : ((S.hn[mw] & mb) ? -1 : 0);
    std::swap(vp0, vp1); std::swap(vn0, vn1);
  }
  return score;
}

// full path variant: stores every row's VP/VN, walks the backtrack from
// delta bits. Returns the exact distance; writes the a2b prefix map.
static int32_t myers_path(const int8_t* a, int n, const int8_t* b, int m,
                          int64_t* a2b, MyersScratch& S) {
  const int K = (m + 63) >> 6;
  myers_build_peq(b, m, K, S);
  S.vp.resize((size_t)(n + 1) * K);
  S.vn.resize((size_t)(n + 1) * K);
  S.hp.resize(K); S.hn.resize(K);
  for (int w = 0; w < K; ++w) { S.vp[w] = ~(uint64_t)0; S.vn[w] = 0; }
  int32_t score = m;
  const int mw = (m - 1) >> 6;
  const uint64_t mb = (uint64_t)1 << ((m - 1) & 63);
  for (int i = 1; i <= n; ++i) {
    const int8_t c = a[i - 1];
    myers_step(S.peq.data() + (size_t)(c < 0 || c > 4 ? 4 : c) * K,
               S.vp.data() + (size_t)(i - 1) * K,
               S.vn.data() + (size_t)(i - 1) * K,
               S.hp.data(), S.hn.data(),
               S.vp.data() + (size_t)i * K, S.vn.data() + (size_t)i * K, K);
    score += (S.hp[mw] & mb) ? 1 : ((S.hn[mw] & mb) ? -1 : 0);
  }
  int i = n, j = m;
  a2b[n] = m;
  int hrow = -1;   // row whose HP/HN currently sit in S.hp/S.hn
  while (i > 0) {
    if (j == 0) {             // first column: deletion is the only move
      --i; a2b[i] = 0;
      continue;
    }
    if (hrow != i) {
      const int8_t c = a[i - 1];
      S.t0.resize(K); S.t1.resize(K);
      myers_step(S.peq.data() + (size_t)(c < 0 || c > 4 ? 4 : c) * K,
                 S.vp.data() + (size_t)(i - 1) * K,
                 S.vn.data() + (size_t)(i - 1) * K,
                 S.hp.data(), S.hn.data(), S.t0.data(), S.t1.data(), K);
      hrow = i;
    }
    const int w = (j - 1) >> 6;
    const uint64_t bit = (uint64_t)1 << ((j - 1) & 63);
    const int V = (S.hp[w] & bit) ? 1 : ((S.hn[w] & bit) ? -1 : 0);
    const uint64_t* VPp = S.vp.data() + (size_t)(i - 1) * K;
    const uint64_t* VNp = S.vn.data() + (size_t)(i - 1) * K;
    const int Hp = (VPp[w] & bit) ? 1 : ((VNp[w] & bit) ? -1 : 0);
    const int c = (a[i - 1] != b[j - 1]) ? 1 : 0;
    if (V + Hp == c) {
      --i; --j; a2b[i] = j;
    } else if (V == 1) {
      --i; a2b[i] = j;
    } else {
      --j;
    }
  }
  a2b[0] = 0;
  return score;
}

int32_t align_path(const int8_t* a, int n, const int8_t* b, int m,
                   std::vector<int32_t>& Dbuf, int64_t* a2b,
                   int32_t band_hint = 24) {
  if (m > 0 && m <= MYERS_MAX_M && n > 0) {
    static thread_local MyersScratch S;
    return myers_path(a, n, b, m, a2b, S);
  }
  const int W = m + 1;
  Dbuf.resize((size_t)(n + 1) * W);
  int32_t* D = Dbuf.data();
  const int32_t dist = fill_exact(a, n, b, m, D, W, band_hint);
  // backtrack (diagonal > deletion > insertion), matching oracle.align
  int i = n, j = m;
  a2b[n] = m;
  while (i > 0) {
    const int32_t* row = D + (size_t)i * W;
    const int32_t* prev = row - W;
    if (j > 0 && row[j] == prev[j - 1] + (a[i - 1] != b[j - 1])) {
      --i; --j;
      a2b[i] = j;
    } else if (row[j] == prev[j] + 1) {
      --i;
      a2b[i] = j;
    } else {
      --j;
    }
  }
  a2b[0] = 0;
  return dist;
}

}  // namespace

extern "C" {

// ---------------------------------------------------------------------------
// LAS columnar loader
// ---------------------------------------------------------------------------
// pass 1: header + totals so the caller can allocate numpy arrays.
// byte_start/byte_end restrict to an aread-aligned shard range (0,0 = whole
// file) — the multi-host data-plane unit (SURVEY.md §2.3 DP row).
int las_scan(const char* path, int64_t byte_start, int64_t byte_end,
             int64_t* novl, int32_t* tspace, int64_t* trace_elems) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  struct { int64_t novl; int32_t tspace; int32_t pad; } hdr;
  if (fread(&hdr, 16, 1, f) != 1) { fclose(f); return -2; }
  *tspace = hdr.tspace;
  const int tsize = hdr.tspace <= 125 ? 1 : 2;
  if (byte_start > 16 && fseek(f, (long)byte_start, SEEK_SET) != 0) { fclose(f); return -3; }
  int64_t total = 0, count = 0;
  struct Rec { int32_t tlen, diffs, abpos, bbpos, aepos, bepos; uint32_t flags; int32_t aread, bread, pad; } rec;
  static_assert(sizeof(Rec) == 40, "record layout");
  while ((byte_end <= 0 || ftell(f) < byte_end) && fread(&rec, sizeof(Rec), 1, f) == 1) {
    total += rec.tlen;
    ++count;
    if (fseek(f, (long)rec.tlen * tsize, SEEK_CUR) != 0) { fclose(f); return -3; }
  }
  *novl = count;
  *trace_elems = total;
  fclose(f);
  return 0;
}

// pass 2: fill caller-allocated columnar arrays
int las_load(const char* path, int64_t byte_start, int64_t byte_end, int64_t novl_expect,
             int32_t* aread, int32_t* bread,
             int32_t* abpos, int32_t* aepos,
             int32_t* bbpos, int32_t* bepos,
             uint8_t* comp, int32_t* diffs,
             int64_t* trace_off,          // [novl+1]
             int32_t* trace_flat) {       // [trace_elems] (d,b) interleaved
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  struct { int64_t novl; int32_t tspace; int32_t pad; } hdr;
  if (fread(&hdr, 16, 1, f) != 1) { fclose(f); return -2; }
  const int tsize = hdr.tspace <= 125 ? 1 : 2;
  if (byte_start > 16 && fseek(f, (long)byte_start, SEEK_SET) != 0) { fclose(f); return -3; }
  struct Rec { int32_t tlen, diffs, abpos, bbpos, aepos, bepos; uint32_t flags; int32_t aread, bread, pad; } rec;
  int64_t k = 0, off = 0;
  std::vector<uint8_t> tbuf;
  while ((byte_end <= 0 || ftell(f) < byte_end) && k < novl_expect
         && fread(&rec, sizeof(Rec), 1, f) == 1) {
    aread[k] = rec.aread; bread[k] = rec.bread;
    abpos[k] = rec.abpos; aepos[k] = rec.aepos;
    bbpos[k] = rec.bbpos; bepos[k] = rec.bepos;
    comp[k] = (uint8_t)(rec.flags & 1u);
    diffs[k] = rec.diffs;
    trace_off[k] = off;
    tbuf.resize((size_t)rec.tlen * tsize);
    if (rec.tlen && fread(tbuf.data(), tsize, rec.tlen, f) != (size_t)rec.tlen) { fclose(f); return -3; }
    if (tsize == 1) {
      for (int t = 0; t < rec.tlen; ++t) trace_flat[off + t] = tbuf[t];
    } else {
      const uint16_t* p = (const uint16_t*)tbuf.data();
      for (int t = 0; t < rec.tlen; ++t) trace_flat[off + t] = p[t];
    }
    off += rec.tlen;
    ++k;
  }
  trace_off[k] = off;
  fclose(f);
  return (int)(k == novl_expect ? 0 : -4);
}

// ---------------------------------------------------------------------------
// pile -> window tensors (the reference's L3 hot path, SURVEY.md §3.1)
// ---------------------------------------------------------------------------
// b_concat holds each overlap's B read bases in STORED orientation at
// b_off[i]..b_off[i]+b_len[i]; complementing happens here.
// out_seqs must be pre-filled with PAD by the caller ([nwin, D, L] int8);
// out_lens/out_nsegs are zero-filled by the caller.
int process_pile(const int8_t* a, int32_t alen,
                 int32_t novl,
                 const int32_t* abpos, const int32_t* aepos,
                 const int32_t* bbpos, const int32_t* bepos,
                 const uint8_t* comp,
                 const int8_t* b_concat, const int64_t* b_off, const int32_t* b_len,
                 const int32_t* trace_flat, const int64_t* trace_off,
                 int32_t tspace, int32_t w, int32_t adv,
                 int32_t D, int32_t L, int32_t include_a,
                 int8_t* out_seqs, int32_t* out_lens, int32_t* out_nsegs,
                 int32_t nwin) {
  // refine every overlap to a base-accurate prefix map. The scratch buffers
  // are thread_local flat arenas (the feeder pool calls this concurrently):
  // reusing their capacity across piles removes the per-pile allocation
  // churn of per-overlap vectors.
  static thread_local std::vector<int64_t> a2b_flat;
  static thread_local std::vector<int8_t> orient_flat;
  static thread_local std::vector<size_t> a2b_at, orient_at;
  static thread_local std::vector<int32_t> Dbuf;
  a2b_at.resize(novl);
  orient_at.resize(novl);
  {
    size_t at = 0, ot = 0;
    for (int i = 0; i < novl; ++i) {
      a2b_at[i] = at; orient_at[i] = ot;
      at += (size_t)(aepos[i] - abpos[i]) + 1;
      ot += (size_t)b_len[i];
    }
    a2b_flat.resize(at);
    orient_flat.resize(ot);
  }
  for (int i = 0; i < novl; ++i) {
    const int32_t ab = abpos[i], ae = aepos[i];
    const int32_t blen = b_len[i];
    const int8_t* bsrc = b_concat + b_off[i];
    int8_t* bo = orient_flat.data() + orient_at[i];
    if (comp[i]) {
      for (int32_t j = 0; j < blen; ++j) bo[j] = (int8_t)(3 - bsrc[blen - 1 - j]);
    } else {
      std::memcpy(bo, bsrc, blen);
    }
    int64_t* a2b = a2b_flat.data() + a2b_at[i];
    // tile bounds: [ab, next multiple of tspace, ..., ae]
    int64_t bpos = bbpos[i];
    const int32_t* tr = trace_flat + trace_off[i];
    int32_t t = 0;
    int32_t a0 = ab;
    while (a0 < ae) {
      int32_t a1 = std::min(((a0 / tspace) + 1) * tspace, ae);
      if (a1 <= a0) a1 = ae;
      const int32_t tb = tr[2 * t + 1];  // b bases in tile
      // the trace records the aligner's per-tile diff count; the optimal
      // distance is <= it, so diffs+2 is a valid exact band (the verify-
      // retry in align_path still protects against a lying trace)
      align_path(a + a0, a1 - a0, bo + bpos, tb, Dbuf, a2b + (a0 - ab),
                 tr[2 * t] + 2);
      // align_path wrote offsets relative to the tile; rebase to absolute
      for (int32_t x = a0 - ab; x <= a1 - ab; ++x) a2b[x] += bpos;
      bpos += tb;
      a0 = a1;
      ++t;
    }
    a2b[ae - ab] = bpos;
  }

  // cut windows
  const int32_t n_expected = alen < w ? 0 : (alen - w) / adv + 1;
  if (n_expected != nwin) return -5;
  for (int32_t j = 0; j < nwin; ++j) {
    const int32_t ws = j * adv, we = ws + w;
    int32_t d = 0;
    int8_t* wrow = out_seqs + (size_t)j * D * L;
    if (include_a && d < D) {
      const int32_t n = std::min(w, L);
      std::memcpy(wrow, a + ws, n);
      out_lens[(size_t)j * D] = n;
      ++d;
    }
    for (int i = 0; i < novl && d < D; ++i) {
      if (abpos[i] <= ws && aepos[i] >= we) {
        const int64_t* a2b = a2b_flat.data() + a2b_at[i];
        const int64_t b0 = a2b[ws - abpos[i]];
        const int64_t b1 = a2b[we - abpos[i]];
        if (b1 > b0) {
          const int32_t n = (int32_t)std::min<int64_t>(b1 - b0, L);
          std::memcpy(wrow + (size_t)d * L, orient_flat.data() + orient_at[i] + b0, n);
          out_lens[(size_t)j * D + d] = n;
          ++d;
        }
      }
    }
    out_nsegs[j] = d;
  }
  return 0;
}

// ---------------------------------------------------------------------------
// stitch splice: best suffix(a) x prefix(b) semi-global alignment
// ---------------------------------------------------------------------------
// Exact port of oracle.align.overlap_suffix_prefix (free start in a, free end
// in b, end chosen minimizing cost - len/2, ties to the lower index;
// backtrack tie order substitution > deletion > insertion).
int suffix_prefix(const int8_t* a, int32_t n, const int8_t* b, int32_t m,
                  int32_t* out_cost, int32_t* out_a_start, int32_t* out_b_end) {
  std::vector<int32_t> Dbuf((size_t)(n + 1) * (m + 1));
  int32_t* D = Dbuf.data();
  const int W = m + 1;
  for (int j = 0; j <= m; ++j) D[j] = j;
  for (int i = 1; i <= n; ++i) {
    int32_t* row = D + (size_t)i * W;
    const int32_t* prev = row - W;
    row[0] = 0;
    const int8_t ai = a[i - 1];
    for (int j = 1; j <= m; ++j) {
      int32_t best = prev[j - 1] + (b[j - 1] != ai);
      int32_t del = prev[j] + 1;
      if (del < best) best = del;
      int32_t ins = row[j - 1] + 1;
      if (ins < best) best = ins;
      row[j] = best;
    }
  }
  const int32_t* last = D + (size_t)n * W;
  int b_end = 0;
  int64_t bestc = 2LL * last[0];
  for (int j = 1; j <= m; ++j) {
    int64_t c = 2LL * last[j] - j;
    if (c < bestc) { bestc = c; b_end = j; }
  }
  int i = n, j = b_end;
  while (j > 0) {
    const int32_t* row = D + (size_t)i * W;
    const int32_t* prev = row - W;
    if (i > 0 && row[j] == prev[j - 1] + (b[j - 1] != a[i - 1])) {
      --i; --j;
    } else if (i > 0 && row[j] == prev[j] + 1) {
      --i;
    } else {
      --j;
    }
  }
  *out_cost = last[b_end];
  *out_a_start = i;
  *out_b_end = b_end;
  return 0;
}

// 2-bit .bps batch decode (SURVEY.md §2.4 native obligation: "2-bit decode
// straight into host buffers"). n reads decoded from the packed base store
// into one contiguous int8 buffer; layout per formats/dazzdb.py (4 bases per
// byte, first base in the two top bits — Dazzler order).
int decode_reads(const uint8_t* bps, const int64_t* boff, const int32_t* rlen,
                 int32_t n, int8_t* out, const int64_t* out_off) {
  for (int32_t i = 0; i < n; ++i) {
    const uint8_t* src = bps + boff[i];
    int8_t* dst = out + out_off[i];
    const int32_t len = rlen[i];
    const int32_t full = len / 4;
    for (int32_t j = 0; j < full; ++j) {
      const uint8_t b = src[j];
      dst[4 * j] = (b >> 6) & 3;
      dst[4 * j + 1] = (b >> 4) & 3;
      dst[4 * j + 2] = (b >> 2) & 3;
      dst[4 * j + 3] = b & 3;
    }
    for (int32_t k = 4 * full; k < len; ++k)
      dst[k] = (src[k / 4] >> (6 - 2 * (k % 4))) & 3;
  }
  return 0;
}

// exact unit-cost edit distance (verify-retry banded: a returned d < band
// slack proves every optimal path stayed interior, so the value equals the
// full DP's) of one candidate vs each of nsegs segments, summed — the
// oracle/hp rescore hot loop as ONE ctypes call (oracle.align
// edit_distance_sum; ~75 ms/window of Python row-DP replaced by ~100 us).
int64_t edit_distance_sum(const int8_t* cand, int32_t n, const int8_t* segs,
                          const int64_t* offs, const int32_t* lens,
                          int32_t nsegs) {
  static thread_local std::vector<int32_t> Dbuf;
  static thread_local MyersScratch S;
  int64_t tot = 0;
  for (int32_t s = 0; s < nsegs; ++s) {
    const int8_t* b = segs + offs[s];
    const int m = lens[s];
    if (n == 0) { tot += m; continue; }
    if (m == 0) { tot += n; continue; }
    // distance-only Myers has no row storage, so the gate is far wider
    // than the path variant's: n*K word-steps beat the banded fill well
    // past window widths (e.g. whole-read 4k x 4k rescores)
    if (m <= 8192) {
      tot += myers_dist(cand, n, b, m, S);
      continue;
    }
    const int W = m + 1;
    Dbuf.resize((size_t)(n + 1) * W);
    tot += fill_exact(cand, n, b, m, Dbuf.data(), W, 16);
  }
  return tot;
}

// exact a2b prefix map (oracle.align.align_path semantics, bit-identical
// backtrack tie order) — the hp run-length vote's per-segment alignment.
// Returns the exact edit distance (Myers score or the verify-retried
// banded fill's D[n][m]).
int64_t align_map(const int8_t* a, int32_t n, const int8_t* b, int32_t m,
                  int64_t* a2b) {
  static thread_local std::vector<int32_t> Dbuf;
  return align_path(a, n, b, m, Dbuf, a2b);
}

// best edit distance of needle a against ANY infix of haystack b
// (oracle.align.infix_distance semantics: free start/end gaps in the
// haystack). Myers' original approximate-search formulation: bits run along
// the NEEDLE (multi-word), text consumed with a free-start boundary (no
// carry-in on the HP shift), score tracked at the needle's last bit and
// minimized over text positions. Exact; the Q-score harness's hot loop.
int64_t infix_distance(const int8_t* a, int32_t n, const int8_t* b,
                       int32_t m) {
  if (n == 0) return 0;
  if (m == 0) return n;
  const int K = (n + 63) >> 6;
  static thread_local std::vector<uint64_t> peq_v, vp_v, vn_v;
  peq_v.assign((size_t)5 * K, 0);
  for (int j = 0; j < n; ++j) {
    const int8_t c = a[j];
    if (c >= 0 && c < 5)
      peq_v[(size_t)c * K + (j >> 6)] |= (uint64_t)1 << (j & 63);
  }
  vp_v.assign(K, ~(uint64_t)0);
  vn_v.assign(K, 0);
  uint64_t* VP = vp_v.data();
  uint64_t* VN = vn_v.data();
  const int nw = (n - 1) >> 6;
  const uint64_t nb = (uint64_t)1 << ((n - 1) & 63);
  int64_t score = n, best = n;
  for (int i = 0; i < m; ++i) {
    const int8_t c = b[i];
    const uint64_t* peq = peq_v.data() + (size_t)(c < 0 || c > 4 ? 4 : c) * K;
    uint64_t carry = 0, hp_in = 0, hn_in = 0;  // free text start: boundary
    //                                            delta 0, no carry-in
    for (int w = 0; w < K; ++w) {
      const uint64_t X = peq[w] | VN[w];
      const uint64_t av = X & VP[w];
      const uint64_t t = av + VP[w];
      const uint64_t sum = t + carry;
      carry = (uint64_t)(t < av) | (uint64_t)(sum < t);
      const uint64_t D0 = (sum ^ VP[w]) | X;
      const uint64_t hp = VN[w] | ~(VP[w] | D0);
      const uint64_t hn = VP[w] & D0;
      if (w == nw) score += (hp & nb) ? 1 : ((hn & nb) ? -1 : 0);
      const uint64_t hpw = (hp << 1) | hp_in; hp_in = hp >> 63;
      const uint64_t hnw = (hn << 1) | hn_in; hn_in = hn >> 63;
      VN[w] = hpw & D0;
      VP[w] = hnw | ~(hpw | D0);
    }
    if (score < best) best = score;
  }
  return best;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// external LAS sort (LAsort role: the reference's sorts are native and
// block-memory external; SURVEY.md §2.2 LAS row)
// ---------------------------------------------------------------------------
// Key (aread, bread, abpos), stable on input order. The run partitioning
// (chunks of mem_records in input order), the stable chunk sort, the
// earliest-run-wins tie break and the fan-in-64 multi-level merge replicate
// formats/extsort.py's semantics exactly, so for a given mem_records the
// native and Python sorts emit byte-identical files.

namespace {

struct SortKey {
  int32_t aread, bread, abpos;
  bool operator<(const SortKey& o) const {
    if (aread != o.aread) return aread < o.aread;
    if (bread != o.bread) return bread < o.bread;
    return abpos < o.abpos;
  }
};

struct LasRec40 {
  int32_t tlen, diffs, abpos, bbpos, aepos, bepos;
  uint32_t flags;
  int32_t aread, bread, pad;
};
static_assert(sizeof(LasRec40) == 40, "record layout");

// buffered reader over a run file of raw (Rec40 + trace) records.
// corrupt != exhausted: a truncated record or garbage tlen sets `err`
// (silently dropping a foreign file's tail would hand downstream consensus
// an incomplete overlap set while reporting success)
struct RunReader {
  FILE* f = nullptr;
  int tsize = 1;
  std::vector<uint8_t> rec;   // current raw record bytes
  SortKey key{};
  bool ok = false;
  bool err = false;

  bool next() {
    LasRec40 h;
    size_t got = fread(&h, 1, sizeof(h), f);
    if (got != sizeof(h)) {
      ok = false;
      err = got != 0;          // partial header = corruption, 0 = clean EOF
      return false;
    }
    if (h.tlen < 0 || h.tlen > (1 << 28)) { ok = false; err = true; return false; }
    h.pad = 0;   // normalize struct tail padding like the Python writer
    rec.resize(sizeof(h) + (size_t)h.tlen * tsize);
    std::memcpy(rec.data(), &h, sizeof(h));
    if (h.tlen &&
        fread(rec.data() + sizeof(h), tsize, h.tlen, f) != (size_t)h.tlen) {
      ok = false;
      err = true;              // truncated trace
      return false;
    }
    key = SortKey{h.aread, h.bread, h.abpos};
    ok = true;
    return true;
  }
};

// merge `paths` (already individually sorted) into `out`; `hdr16` non-null
// writes the 16-byte LAS header (novl patched at the end) for the final
// file. `in_hdr_tspace >= 0` means each input starts with a 16-byte LAS
// header that must carry that tspace (the las_merge foreign-input mode);
// -1 means headerless run files. `count_out` (optional) receives novl.
static int merge_runs(const std::vector<std::string>& paths, int tsize,
                      const char* out, const uint8_t* hdr16,
                      int32_t in_hdr_tspace = -1,
                      int64_t* count_out = nullptr) {
  std::vector<RunReader> rs(paths.size());
  auto close_runs = [&]() {
    for (auto& r : rs)
      if (r.f) { fclose(r.f); r.f = nullptr; }
  };
  for (size_t i = 0; i < paths.size(); ++i) {
    rs[i].f = fopen(paths[i].c_str(), "rb");
    if (!rs[i].f) { close_runs(); return -1; }
    if (in_hdr_tspace >= 0) {
      struct { int64_t novl; int32_t tspace; int32_t pad; } h;
      if (fread(&h, 16, 1, rs[i].f) != 1 || h.tspace != in_hdr_tspace) {
        close_runs();
        return -6;
      }
    }
    rs[i].tsize = tsize;
    rs[i].next();
  }
  FILE* fo = fopen(out, "wb");
  if (!fo) { close_runs(); return -1; }
  int64_t novl = 0;
  if (hdr16 && fwrite(hdr16, 16, 1, fo) != 1) { fclose(fo); close_runs(); return -2; }
  using HeapItem = std::pair<SortKey, size_t>;   // (key, run ordinal)
  auto gt = [](const HeapItem& a, const HeapItem& b) {
    if (b.first < a.first) return true;
    if (a.first < b.first) return false;
    return a.second > b.second;   // earliest run wins ties (stability)
  };
  std::priority_queue<HeapItem, std::vector<HeapItem>, decltype(gt)> heap(gt);
  for (size_t i = 0; i < rs.size(); ++i)
    if (rs[i].ok) heap.push({rs[i].key, i});
  while (!heap.empty()) {
    size_t i = heap.top().second;
    heap.pop();
    if (fwrite(rs[i].rec.data(), 1, rs[i].rec.size(), fo) != rs[i].rec.size()) {
      fclose(fo);
      close_runs();
      return -2;
    }
    ++novl;
    if (rs[i].next()) heap.push({rs[i].key, i});
  }
  for (const auto& r : rs)
    if (r.err) { fclose(fo); close_runs(); return -7; }   // corrupt input
  if (hdr16) {
    struct { int64_t novl; int32_t tspace; int32_t pad; } hdr;
    std::memcpy(&hdr, hdr16, 16);
    hdr.novl = novl;
    fseek(fo, 0, SEEK_SET);
    if (fwrite(&hdr, 16, 1, fo) != 1) { fclose(fo); close_runs(); return -2; }
  }
  close_runs();
  if (count_out) *count_out = novl;
  // fclose flushes the tail of the stdio buffer: a full disk surfaces HERE,
  // not at the buffered fwrites — an unchecked close would report a
  // truncated file as success
  return fclose(fo) == 0 ? 0 : -2;
}

}  // namespace

extern "C" {

// k-way merge of ALREADY-SORTED headered LAS files (LAmerge role; DALIGNER
// emits one LAS per block pair). Same key and earliest-input-wins tie break
// as las_sort / the Python heapq.merge path. in_paths is a
// NUL-separated, double-NUL-terminated list. Returns the record count or a
// negative error; all inputs must share out-file tspace `tspace_expect`.
int64_t las_merge(const char* in_paths, const char* out_path,
                  int32_t tspace_expect) {
  std::vector<std::string> paths;
  for (const char* p = in_paths; *p;) {
    paths.emplace_back(p);
    p += paths.back().size() + 1;
  }
  if (paths.empty()) return -1;
  const int tsize = tspace_expect <= 125 ? 1 : 2;
  struct { int64_t novl; int32_t tspace; int32_t pad; } oh{0, tspace_expect, 0};
  uint8_t hdr16[16];
  std::memcpy(hdr16, &oh, 16);
  int64_t novl = 0;
  const int rc = merge_runs(paths, tsize, out_path, hdr16,
                            /*in_hdr_tspace=*/tspace_expect, &novl);
  return rc == 0 ? novl : rc;
}

// sorts in_path -> out_path by (aread, bread, abpos) holding at most
// mem_records records in memory; temp runs live in tmp_dir. Returns the
// record count, or a negative error.
int64_t las_sort(const char* in_path, const char* out_path,
                 const char* tmp_dir, int64_t mem_records) {
  FILE* f = fopen(in_path, "rb");
  if (!f) return -1;
  struct { int64_t novl; int32_t tspace; int32_t pad; } hdr;
  if (fread(&hdr, 16, 1, f) != 1) { fclose(f); return -2; }
  const int tsize = hdr.tspace <= 125 ? 1 : 2;
  hdr.pad = 0;   // normalize header padding like the Python writer
  uint8_t hdr16[16];
  std::memcpy(hdr16, &hdr, 16);

  std::vector<uint8_t> arena;        // raw record bytes of the current chunk
  struct Ent { SortKey key; int64_t off; int32_t size; };
  std::vector<Ent> ents;
  std::vector<std::string> runs;
  int gen = 0;

  auto run_path = [&](int g) {
    return std::string(tmp_dir) + "/nrun" + std::to_string(g) + ".bin";
  };
  auto flush = [&]() -> int {
    if (ents.empty()) return 0;
    std::stable_sort(ents.begin(), ents.end(),
                     [](const Ent& a, const Ent& b) { return a.key < b.key; });
    std::string rp = run_path(gen++);
    FILE* fo = fopen(rp.c_str(), "wb");
    if (!fo) return -1;
    for (const auto& e : ents)
      if (fwrite(arena.data() + e.off, 1, e.size, fo) != (size_t)e.size) {
        fclose(fo);
        return -2;
      }
    fclose(fo);
    runs.push_back(rp);
    ents.clear();
    arena.clear();
    return 0;
  };

  LasRec40 rec;
  int64_t total = 0;
  size_t got;
  while ((got = fread(&rec, 1, sizeof(rec), f)) == sizeof(rec)) {
    if (rec.tlen < 0 || rec.tlen > (1 << 28)) { fclose(f); return -3; }
    rec.pad = 0;   // normalize struct tail padding like the Python writer
    const size_t sz = sizeof(rec) + (size_t)rec.tlen * tsize;
    const int64_t off = (int64_t)arena.size();
    arena.resize(arena.size() + sz);
    std::memcpy(arena.data() + off, &rec, sizeof(rec));
    if (rec.tlen && fread(arena.data() + off + sizeof(rec), tsize, rec.tlen, f)
                        != (size_t)rec.tlen) {
      fclose(f);
      return -3;
    }
    ents.push_back({SortKey{rec.aread, rec.bread, rec.abpos}, off, (int32_t)sz});
    ++total;
    if ((int64_t)ents.size() >= mem_records)
      if (flush() != 0) { fclose(f); return -4; }
  }
  if (got != 0) { fclose(f); return -3; }   // partial record = truncated input
  fclose(f);

  if (runs.empty()) {
    // whole input fit one chunk: sort and write directly (same fast path as
    // the Python implementation)
    std::stable_sort(ents.begin(), ents.end(),
                     [](const Ent& a, const Ent& b) { return a.key < b.key; });
    FILE* fo = fopen(out_path, "wb");
    if (!fo) return -1;
    if (fwrite(hdr16, 16, 1, fo) != 1) { fclose(fo); return -2; }
    for (const auto& e : ents)
      if (fwrite(arena.data() + e.off, 1, e.size, fo) != (size_t)e.size) {
        fclose(fo);
        return -2;
      }
    struct { int64_t novl; int32_t tspace; int32_t pad; } oh;
    std::memcpy(&oh, hdr16, 16);
    oh.novl = total;
    fseek(fo, 0, SEEK_SET);
    if (fwrite(&oh, 16, 1, fo) != 1) { fclose(fo); return -2; }
    if (fclose(fo) != 0) return -2;   // flush failure = truncated output
    return total;
  }
  if (flush() != 0) return -4;

  // multi-level merge, fan-in 64 (same grouping as extsort.py)
  const size_t FANIN = 64;
  while (runs.size() > FANIN) {
    std::vector<std::string> merged;
    for (size_t g0 = 0; g0 < runs.size(); g0 += FANIN) {
      std::vector<std::string> group(
          runs.begin() + g0,
          runs.begin() + std::min(runs.size(), g0 + FANIN));
      std::string rp = run_path(gen++);
      if (merge_runs(group, tsize, rp.c_str(), nullptr) != 0) return -5;
      for (const auto& p : group) std::remove(p.c_str());
      merged.push_back(rp);
    }
    runs = std::move(merged);
  }
  if (merge_runs(runs, tsize, out_path, hdr16) != 0) return -5;
  for (const auto& p : runs) std::remove(p.c_str());
  return total;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Native window-consensus engine: C++ replica of the oracle spec
// (oracle/dbg.py window_consensus + oracle/consensus.py solve_window tier
// ladder — the reference's handleWindow/DebruijnGraph<k> per SURVEY.md §3.3;
// reference file:line pending backfill, mount empty). Full-graph semantics
// (no top-M cap), same thresholds, same tie-breaks (candidate order = score
// desc then flat index asc, matching the oracle's stable argsort; DP argmax
// keeps the lowest u). Float accumulation is sequential f32, which can
// differ from numpy's blocked BLAS reductions in the last ulp — parity is
// asserted at the consensus-sequence level (tests/test_native.py).
//
// Consumes the pipeline's WindowBatch tensor layout directly:
// seqs [B, D, L] int8 (PAD-filled), lens [B, D] i32, nsegs [B] i32.

namespace dbgc {

constexpr float NEGF = -1e30f;

static int band_for(int n, int m) {  // the one band formula (spec + fast)
  int band = std::abs(n - m) + std::max(16, std::max(n, m) >> 2);
  return std::max(band, std::abs(n - m) + 1);
}

// oracle.align.edit_distance replica: banded unit-cost DP, int32, band
// derived exactly as the spec does (NOT verify-retried — the banded value IS
// the spec the kernel parity tests are calibrated against).
static int32_t edit_distance_spec(const int8_t* a, int n, const int8_t* b,
                                  int m) {
  if (n == 0) return m;
  if (m == 0) return n;
  const int band = band_for(n, m);
  static thread_local std::vector<int32_t> pv, cv;
  pv.resize(m + 1);
  cv.resize(m + 1);
  int32_t* prev = pv.data();
  int32_t* cur = cv.data();
  const int32_t BIG = 1 << 30;
  for (int j = 0; j <= m; ++j) prev[j] = j;
  for (int i = 1; i <= n; ++i) {
    const int lo = std::max(1, i - band);
    const int hi = std::min(m, i + band);
    cur[lo - 1] = (lo == 1) ? i : BIG;
    int32_t run = cur[lo - 1];
    const int8_t ai = a[i - 1];
    for (int j = lo; j <= hi; ++j) {
      const int32_t sub = prev[j - 1] + (b[j - 1] != ai);
      const int32_t del = prev[j] + 1;
      int32_t best = sub < del ? sub : del;
      ++run;
      if (best < run) run = best;
      cur[j] = run;
    }
    if (hi < m) cur[hi + 1] = BIG;  // next row reads prev[hi+1]
    std::swap(prev, cur);
  }
  return prev[m];
}

// Myers/Hyyrö bit-parallel exact edit distance for candidate rescoring,
// n <= 64 (cand_len <= wlen + len_slack = 48): one uint64 word of VP/VN,
// ~15 bitwise ops per segment char. Formulation mirrors the device kernel's
// _edit_distance_myers (window_kernel.py), which is bit-parity-tested
// against the exact anti-diagonal DP. The SPEC the oracle defines is the
// BANDED distance (edit_distance_spec above), which equals the exact
// distance whenever exact <= band — always true for real candidate/segment
// pairs at these lengths; rare junk pairs (and any out-of-alphabet bytes)
// fall back to the banded replica so native == oracle stays bit-exact.

struct MyersCand {   // per-candidate precompute, reused across all segments
  uint64_t peq[5];
  uint64_t vp_init, hb;
  int n;
  bool ok;
};

static void myers_prep(const int8_t* a, int n, MyersCand& mc) {
  mc.n = n;
  mc.ok = n > 0 && n <= 64;
  if (!mc.ok) return;
  for (int c = 0; c < 5; ++c) mc.peq[c] = 0;
  for (int i = 0; i < n; ++i) {
    const uint8_t c = (uint8_t)a[i];
    if (c > 4) { mc.ok = false; return; }   // out-of-alphabet: spec path
    mc.peq[c] |= 1ull << i;
  }
  mc.vp_init = (n == 64) ? ~0ull : ((1ull << n) - 1);
  mc.hb = 1ull << (n - 1);
}

static int32_t edit_distance_fast(const MyersCand& mc, const int8_t* a,
                                  const int8_t* b, int m, bool b_checked) {
  const int n = mc.n;
  if (!mc.ok || m == 0) return edit_distance_spec(a, n, b, m);
  if (!b_checked)   // callers that pre-validate their segments skip the scan
    for (int j = 0; j < m; ++j)
      if ((uint8_t)b[j] > 4) return edit_distance_spec(a, n, b, m);
  uint64_t vp = mc.vp_init;
  uint64_t vn = 0;
  int32_t score = n;
  const uint64_t hb = mc.hb;
  for (int j = 0; j < m; ++j) {
    const uint64_t eq = mc.peq[(uint8_t)b[j]];
    const uint64_t x = eq | vn;
    const uint64_t ad = x & vp;
    const uint64_t s = vp + ad;
    const uint64_t d0 = (s ^ vp) | x;
    const uint64_t hn = vp & d0;
    const uint64_t hp = vn | ~(vp | d0);
    score += (hp & hb) ? 1 : ((hn & hb) ? -1 : 0);
    const uint64_t x2 = (hp << 1) | 1ull;   // D[0,j] = j carry-in
    const uint64_t h2 = hn << 1;
    vn = x2 & d0;
    vp = h2 | ~(x2 | d0);
  }
  if (score <= band_for(n, m)) return score;  // banded spec == exact here
  return edit_distance_spec(a, n, b, m);
}

struct TierSpec {
  int32_t k, min_count, edge_min_count, P, O;
  int32_t max_kmers;   // 0 = unbounded (full graph); > 0 mirrors the device
                       // ladder's top-M compaction (count desc, smaller code
                       // wins ties — lax.top_k semantics), measured a
                       // beneficial noise filter (BASELINE.md r3 top-M table)
  const float* table;  // [P][O]
};

struct Scratch {
  std::vector<int64_t> codes, codes1, kept;
  std::vector<int32_t> offs, order;       // per-occurrence offset; sort order
  std::vector<uint8_t> flags;             // per-occurrence start/end bits
  std::vector<int32_t> kid_off, kid_cnt;  // per-kept-id slice into occ_*
  std::vector<int32_t> occ_o;             // dedup'd offsets, o-ascending
  std::vector<float> occ_c;               // counts at those offsets
  std::vector<uint8_t> src_ok, snk_ok;
  std::vector<int32_t> in_off, in_u;      // CSR incoming-edge lists
  std::vector<std::pair<int32_t, int32_t>> edges;
  std::vector<float> W, score;
  std::vector<int32_t> ptr;
  std::vector<std::pair<float, int32_t>> ends;
  std::vector<int32_t> path;
  std::vector<int8_t> cand, best;
  std::vector<int32_t> seen;
  // top-M compaction temporaries (swap targets; kept here so ALL per-thread
  // scratch lives in one audited struct)
  std::vector<int32_t> sel, off2, cnt2, occ_o2;
  std::vector<int64_t> kept2;
  std::vector<float> occ_c2;
  std::vector<uint8_t> src2, snk2;
  std::vector<int32_t> radix_i;    // LSD radix alternate buffers
  std::vector<int64_t> radix_v;
};

// LSD radix sort core, 8-bit digits over the low ``bits`` key bits.
// Grouping order is key-ascending and the sort is stable — the only
// properties the callers need (within-run order is irrelevant downstream:
// offsets re-sort per run, anchor flags OR). ~4x std::sort on the 16-bit
// k=8 codes that dominate (ARCHITECTURE.md "Native engine cost anatomy").
// One templated core; KeyFn maps an element to its int64 key.
template <class T, class KeyFn>
static void radix_sort_core(std::vector<T>& v, int bits, std::vector<T>& alt,
                            KeyFn key) {
  const int n = (int)v.size();
  alt.resize(n);
  T* src = v.data();
  T* dst = alt.data();
  const int passes = (bits + 7) / 8;
  for (int p = 0; p < passes; ++p) {
    int32_t hist[257] = {0};
    const int shift = 8 * p;
    for (int i = 0; i < n; ++i)
      ++hist[((key(src[i]) >> shift) & 0xFF) + 1];
    for (int b = 0; b < 256; ++b) hist[b + 1] += hist[b];
    for (int i = 0; i < n; ++i)
      dst[hist[(key(src[i]) >> shift) & 0xFF]++] = src[i];
    std::swap(src, dst);
  }
  if (src != v.data())
    std::memcpy(v.data(), src, (size_t)n * sizeof(T));
}

static void radix_sort_idx(std::vector<int32_t>& order,
                           const std::vector<int64_t>& keys, int bits,
                           std::vector<int32_t>& alt) {
  radix_sort_core(order, bits, alt,
                  [&keys](int32_t i) { return keys[i]; });
}

static void radix_sort_vals(std::vector<int64_t>& v, int bits,
                            std::vector<int64_t>& alt) {
  radix_sort_core(v, bits, alt, [](int64_t x) { return x; });
}

// one window, one tier. Returns 0 solved (cons/err written), else -1.
// *movf is set when the top-M cap truncated the surviving k-mer set.
static int try_tier(const int8_t* seqs, const int32_t* lens, int nseg, int L,
                    const TierSpec& ts, int wlen, int anchor_slack,
                    int end_slack, int len_slack, int n_candidates,
                    float max_err, float count_frac, Scratch& S,
                    int8_t* cons_out, int32_t* cons_len, float* err_out,
                    uint8_t* movf) {
  const int k = ts.k;
  const int O = ts.O;
  // ---- 1. per-occurrence k-mers/(k+1)-mers with offsets + anchor flags ----
  S.codes.clear();
  S.codes1.clear();
  S.offs.clear();
  S.flags.clear();
  int64_t seg_total = 0;
  for (int j = 0; j < nseg; ++j) {
    const int len = lens[j];
    seg_total += len;
    const int8_t* seg = seqs + (size_t)j * L;
    const int nk = len - k + 1;
    if (nk <= 0) continue;  // oracle: segments shorter than k skip entirely
    int64_t code = 0;
    for (int p = 0; p < k - 1; ++p) code = code * 4 + seg[p];
    const int64_t mask = ((int64_t)1 << (2 * k)) - 1;
    for (int o = 0; o < nk; ++o) {
      code = ((code << 2) | seg[o + k - 1]) & mask;
      S.codes.push_back(code);
      S.offs.push_back(o);
      S.flags.push_back((o <= anchor_slack ? 1 : 0) |
                        (o >= nk - 1 - end_slack ? 2 : 0));
    }
    const int nk1 = len - k;
    if (nk1 > 0) {
      const int64_t mask1 = ((int64_t)1 << (2 * (k + 1))) - 1;
      int64_t c1 = 0;
      for (int p = 0; p < k; ++p) c1 = c1 * 4 + seg[p];
      for (int o = 0; o < nk1; ++o) {
        c1 = ((c1 << 2) | seg[o + k]) & mask1;
        S.codes1.push_back(c1);
      }
    }
  }
  if (S.codes.empty()) return -1;  // "empty"

  // ---- 2. frequency filter -> kept ids (ascending code order) ------------
  const int novl_occ = (int)S.codes.size();
  S.order.resize(novl_occ);
  for (int i = 0; i < novl_occ; ++i) S.order[i] = i;
  radix_sort_idx(S.order, S.codes, 2 * k, S.radix_i);
  const int thresh =
      std::max(ts.min_count, (int)std::ceil(count_frac * nseg));
  S.kept.clear();
  S.kid_off.clear();
  S.kid_cnt.clear();
  S.occ_o.clear();
  S.occ_c.clear();
  S.src_ok.clear();
  S.snk_ok.clear();
  for (int i = 0; i < novl_occ;) {
    int e = i + 1;
    while (e < novl_occ && S.codes[S.order[e]] == S.codes[S.order[i]]) ++e;
    if (e - i >= thresh) {
      S.kept.push_back(S.codes[S.order[i]]);
      S.kid_off.push_back((int)S.occ_o.size());
      uint8_t s_ok = 0, e_ok = 0;
      // dedup occurrence offsets ascending (order within a code run is
      // occurrence order; offsets repeat across segments) — counts merge
      static thread_local std::vector<int32_t> tmp;
      tmp.clear();
      for (int q = i; q < e; ++q) {
        const int occ_idx = S.order[q];
        int o = S.offs[occ_idx];
        if (o < 0) o = 0;
        if (o > O - 1) o = O - 1;
        tmp.push_back(o);
        s_ok |= (S.flags[occ_idx] & 1);
        e_ok |= (S.flags[occ_idx] & 2) ? 1 : 0;
      }
      std::sort(tmp.begin(), tmp.end());
      for (size_t q = 0; q < tmp.size();) {
        size_t r = q + 1;
        while (r < tmp.size() && tmp[r] == tmp[q]) ++r;
        S.occ_o.push_back(tmp[q]);
        S.occ_c.push_back((float)(r - q));
        q = r;
      }
      S.src_ok.push_back(s_ok);
      S.snk_ok.push_back(e_ok);
      S.kid_cnt.push_back(e - i);
    }
    i = e;
  }
  if (S.kept.empty()) return -1;  // "allfiltered"
  S.kid_off.push_back((int)S.occ_o.size());

  // ---- 2a. top-M compaction (device-ladder semantics) --------------------
  if (ts.max_kmers > 0 && (int)S.kept.size() > ts.max_kmers) {
    const int nk0 = (int)S.kept.size();
    S.sel.resize(nk0);
    for (int i = 0; i < nk0; ++i) S.sel[i] = i;
    std::partial_sort(S.sel.begin(), S.sel.begin() + ts.max_kmers,
                      S.sel.end(),
                      [&](int a, int b) {
                        if (S.kid_cnt[a] != S.kid_cnt[b])
                          return S.kid_cnt[a] > S.kid_cnt[b];
                        return a < b;   // lax.top_k: lower index wins ties
                      });
    S.sel.resize(ts.max_kmers);
    std::sort(S.sel.begin(), S.sel.end());  // kept must stay code-ascending
    S.kept2.clear(); S.off2.clear(); S.cnt2.clear();
    S.occ_o2.clear(); S.occ_c2.clear(); S.src2.clear(); S.snk2.clear();
    for (int id : S.sel) {
      S.kept2.push_back(S.kept[id]);
      S.off2.push_back((int)S.occ_o2.size());
      for (int q = S.kid_off[id]; q < S.kid_off[id + 1]; ++q) {
        S.occ_o2.push_back(S.occ_o[q]);
        S.occ_c2.push_back(S.occ_c[q]);
      }
      S.cnt2.push_back(S.kid_cnt[id]);
      S.src2.push_back(S.src_ok[id]);
      S.snk2.push_back(S.snk_ok[id]);
    }
    S.off2.push_back((int)S.occ_o2.size());
    S.kept.swap(S.kept2); S.kid_off.swap(S.off2); S.kid_cnt.swap(S.cnt2);
    S.occ_o.swap(S.occ_o2); S.occ_c.swap(S.occ_c2);
    S.src_ok.swap(S.src2); S.snk_ok.swap(S.snk2);
    *movf = 1;
  }
  const int nk = (int)S.kept.size();

  // ---- 2b. edges from (k+1)-mer support ----------------------------------
  radix_sort_vals(S.codes1, 2 * (k + 1), S.radix_v);
  S.edges.clear();
  const int64_t mask_k = ((int64_t)1 << (2 * k)) - 1;
  const size_t n1 = S.codes1.size();
  for (size_t i = 0; i < n1;) {
    size_t e = i + 1;
    while (e < n1 && S.codes1[e] == S.codes1[i]) ++e;
    if ((int)(e - i) >= ts.edge_min_count) {
      const int64_t c1 = S.codes1[i];
      const int64_t pref = c1 >> 2;
      const int64_t suff = c1 & mask_k;
      auto pi = std::lower_bound(S.kept.begin(), S.kept.end(), pref);
      auto si = std::lower_bound(S.kept.begin(), S.kept.end(), suff);
      if (pi != S.kept.end() && *pi == pref && si != S.kept.end() &&
          *si == suff)
        S.edges.emplace_back((int32_t)(si - S.kept.begin()),
                             (int32_t)(pi - S.kept.begin()));  // (v, u)
    }
    i = e;
  }
  if (S.edges.empty()) return -1;  // "noedges"
  // CSR incoming lists, u ascending per v (argmax-first tie-break), dedup'd
  std::sort(S.edges.begin(), S.edges.end());
  S.edges.erase(std::unique(S.edges.begin(), S.edges.end()), S.edges.end());
  S.in_off.assign(nk + 1, 0);
  for (auto& vu : S.edges) S.in_off[vu.first + 1]++;
  for (int v = 0; v < nk; ++v) S.in_off[v + 1] += S.in_off[v];
  S.in_u.resize(S.edges.size());
  {
    static thread_local std::vector<int32_t> cursor;
    cursor.assign(nk, 0);
    for (auto& vu : S.edges)
      S.in_u[S.in_off[vu.first] + cursor[vu.first]++] = vu.second;
  }

  // ---- 3. position weights W[nk][P] (sparse occ x table) -----------------
  const int P = std::min(ts.P, wlen - k + 1 + len_slack);
  if (P <= 0) return -1;
  S.W.assign((size_t)nk * P, 0.0f);
  for (int id = 0; id < nk; ++id) {
    float* wrow = S.W.data() + (size_t)id * P;
    for (int p = 0; p < P; ++p) {
      const float* trow = ts.table + (size_t)p * O;
      float acc = 0.0f;
      for (int q = S.kid_off[id]; q < S.kid_off[id + 1]; ++q)
        acc += S.occ_c[q] * trow[S.occ_o[q]];
      wrow[p] = acc;
    }
  }

  // ---- 4. heaviest path DP ----------------------------------------------
  S.score.assign((size_t)P * nk, NEGF);
  S.ptr.assign((size_t)P * nk, -1);
  for (int v = 0; v < nk; ++v)
    if (S.src_ok[v]) S.score[v] = S.W[(size_t)v * P + 0];
  for (int t = 1; t < P; ++t) {
    const float* sp = S.score.data() + (size_t)(t - 1) * nk;
    float* st = S.score.data() + (size_t)t * nk;
    int32_t* pt = S.ptr.data() + (size_t)t * nk;
    for (int v = 0; v < nk; ++v) {
      float best = NEGF;
      int32_t bu = -1;
      for (int q = S.in_off[v]; q < S.in_off[v + 1]; ++q) {
        const int u = S.in_u[q];
        if (sp[u] > best) {
          best = sp[u];
          bu = u;
        }
      }
      if (best > NEGF / 2) {
        st[v] = best + S.W[(size_t)v * P + t];
        pt[v] = bu;
      }
    }
  }

  // ---- 5. candidates: sort (score desc, flat idx asc), rescore -----------
  bool segs_ok = true;   // alphabet check hoisted out of the rescore loop
  for (int j = 0; j < nseg && segs_ok; ++j) {
    const int8_t* sb = seqs + (size_t)j * L;
    for (int q = 0; q < lens[j]; ++q)
      if ((uint8_t)sb[q] > 4) { segs_ok = false; break; }
  }
  const int t_lo = std::max(0, wlen - k - len_slack);
  const int t_hi = std::min(P - 1, wlen - k + len_slack);
  if (t_hi < t_lo) return -1;
  S.ends.clear();
  for (int t = t_lo; t <= t_hi; ++t)
    for (int v = 0; v < nk; ++v) {
      const float s = S.snk_ok[v] ? S.score[(size_t)t * nk + v] : NEGF;
      S.ends.emplace_back(s, (t - t_lo) * nk + v);
    }
  const size_t topn = std::min(S.ends.size(), (size_t)(4 * n_candidates));
  std::partial_sort(S.ends.begin(), S.ends.begin() + topn, S.ends.end(),
                    [](const std::pair<float, int32_t>& a,
                       const std::pair<float, int32_t>& b) {
                      if (a.first != b.first) return a.first > b.first;
                      return a.second < b.second;
                    });
  double best_err = 1e300;
  int best_len = -1;
  S.seen.clear();
  int n_cand = 0;
  for (size_t ei = 0; ei < topn; ++ei) {
    const float s = S.ends[ei].first;
    if (s <= NEGF / 2 || n_cand >= n_candidates) break;
    const int t = t_lo + S.ends[ei].second / nk;
    const int v = S.ends[ei].second % nk;
    if (std::find(S.seen.begin(), S.seen.end(), v) != S.seen.end()) continue;
    S.seen.push_back(v);
    S.path.resize(t + 1);
    int cur = v;
    for (int tt = t; tt >= 0; --tt) {
      S.path[tt] = cur;
      if (tt > 0) cur = S.ptr[(size_t)tt * nk + cur];
    }
    S.cand.resize(k + t);
    const int64_t first = S.kept[S.path[0]];
    for (int j = 0; j < k; ++j)
      S.cand[j] = (int8_t)((first >> (2 * (k - 1 - j))) & 3);
    for (int tt = 1; tt <= t; ++tt)
      S.cand[k + tt - 1] = (int8_t)(S.kept[S.path[tt]] & 3);
    ++n_cand;
    MyersCand mc;
    myers_prep(S.cand.data(), (int)S.cand.size(), mc);
    int64_t tot = 0;
    for (int j = 0; j < nseg; ++j)
      tot += edit_distance_fast(mc, S.cand.data(),
                                seqs + (size_t)j * L, lens[j], segs_ok);
    const double err = (double)tot / (double)std::max<int64_t>(seg_total, 1);
    if (err < best_err) {
      best_err = err;
      best_len = (int)S.cand.size();
      S.best = S.cand;
    }
  }
  if (best_len < 0) return -1;           // "nopath"
  if (best_err > max_err) return -1;     // "badscore"
  // winner only, written once: cons_out keeps its PAD fill past best_len
  // even when an earlier tier or a longer losing candidate was evaluated
  std::memcpy(cons_out, S.best.data(), best_len);
  *cons_len = best_len;
  *err_out = (float)best_err;
  return 0;
}

}  // namespace dbgc

extern "C" {

// Batched tier-ladder consensus over the WindowBatch tensor layout.
// cons [B, CL] (CL = wlen + len_slack, PAD-filled), cons_lens/errs/tiers [B];
// tier = -1 unsolved (err left at +inf); movf_out [B] = 1 when any attempted
// tier's top-M cap truncated the k-mer set (tier_M[i] = 0 disables the cap
// for that tier -> full-graph oracle semantics). n_threads > 1 splits windows
// across std::threads (engine is stateless per window; scratch thread_local).
int solve_windows(const int8_t* seqs, const int32_t* lens,
                  const int32_t* nsegs, int32_t B, int32_t D, int32_t L,
                  const float* tables, const int64_t* table_off,
                  const int32_t* tier_k, const int32_t* tier_minc,
                  const int32_t* tier_eminc, const int32_t* tier_P,
                  const int32_t* tier_O, const int32_t* tier_M,
                  int32_t n_tiers, int32_t wlen,
                  int32_t anchor_slack, int32_t end_slack, int32_t len_slack,
                  int32_t n_candidates, int32_t min_depth, float max_err,
                  float count_frac, int32_t n_threads, int8_t* cons,
                  int32_t* cons_lens, float* errs, int32_t* tiers_out,
                  uint8_t* movf_out) {
  const int CL = wlen + len_slack;
  std::vector<dbgc::TierSpec> ts(n_tiers);
  for (int i = 0; i < n_tiers; ++i)
    ts[i] = {tier_k[i], tier_minc[i], tier_eminc[i], tier_P[i], tier_O[i],
             tier_M[i], tables + table_off[i]};
  std::atomic<int32_t> next(0);
  auto worker = [&]() {
    dbgc::Scratch S;
    for (;;) {
      const int b = next.fetch_add(1);
      if (b >= B) return;
      int8_t* c = cons + (size_t)b * CL;
      std::memset(c, PAD, CL);
      cons_lens[b] = 0;
      errs[b] = std::numeric_limits<float>::infinity();
      tiers_out[b] = -1;
      movf_out[b] = 0;
      if (nsegs[b] < min_depth) continue;  // oracle: "depth" for every tier
      for (int ti = 0; ti < n_tiers; ++ti) {
        if (dbgc::try_tier(seqs + (size_t)b * D * L, lens + (size_t)b * D,
                           nsegs[b], L, ts[ti], wlen, anchor_slack, end_slack,
                           len_slack, n_candidates, max_err, count_frac, S, c,
                           &cons_lens[b], &errs[b], &movf_out[b]) == 0) {
          tiers_out[b] = ti;
          break;
        }
      }
    }
  };
  if (n_threads <= 1) {
    worker();
  } else {
    std::vector<std::thread> pool;
    for (int i = 0; i < n_threads; ++i) pool.emplace_back(worker);
    for (auto& th : pool) th.join();
  }
  return 0;
}

// Homopolymer rescue post-pass over a solve_windows result (oracle/hp.py
// semantics, bit-identical by construction — see tests). Routing per window:
// failed or err > hp_err, with a run >= hp_min_run present (in the direct
// consensus if solved, else in any segment). Solve: run-length-compress the
// segments, run the FULL-GRAPH tier-0 DBG (M=0: the python path calls the
// oracle window_consensus) at wlen_c = int(median(compressed lens)), then
// re-expand each position's run length by the aligned MEDIAN vote
// (round-half-even, numpy/python parity) or — when post_tabs is non-NULL —
// the r5 CALIBRATED POSTERIOR vote (oracle/hp.py vote_runs_posterior
// parity; tables built python-side). Accept only when the expanded
// candidate's exact rescored error beats the direct result (hp_margin) or
// clears max_err where the direct solve failed. Rescued rows write their
// (possibly longer-than-CL) sequence into hp_cons[CLH] and update
// cons_lens/errs in place with tiers_io = 29 (HP_TIER). Returns count
// rescued.
namespace {
// log-likelihood of the compressed segments under one candidate sequence
// (oracle/hp.py hp_loglik parity): run-length-compress the candidate, then
// per segment add -lambda_c per compressed edit plus the posterior walk's
// per-position log P(o | L_i); float64, python's accumulation order.
double hp_loglik_c(const int8_t* cand, int cand_len, const int8_t* cseqs,
                   const int32_t* cruns_all, const int32_t* clens, int nseg,
                   int L_stride, const double* tab, int Lmax, int Omax,
                   double lam_c, std::vector<int8_t>& cc_buf,
                   std::vector<int32_t>& cr_buf, std::vector<int64_t>& a2b,
                   std::vector<int32_t>& Dbuf_v) {
  cc_buf.clear();
  cr_buf.clear();
  for (int i = 0; i < cand_len; ++i) {
    if (!cc_buf.empty() && cand[i] == cc_buf.back()) {
      ++cr_buf.back();
    } else {
      cc_buf.push_back(cand[i]);
      cr_buf.push_back(1);
    }
  }
  const int n = (int)cc_buf.size();
  if (n == 0) return -std::numeric_limits<double>::infinity();
  const int TO = Omax + 1;
  double J = 0.0;
  a2b.resize(n + 1);
  for (int j = 0; j < nseg; ++j) {
    const int m = clens[j];
    if (m == 0) continue;
    const int8_t* cs = cseqs + (size_t)j * L_stride;
    const int32_t* cr = cruns_all + (size_t)j * L_stride;
    const int32_t d_c =
        align_path(cc_buf.data(), n, cs, m, Dbuf_v, a2b.data());
    J -= lam_c * (double)d_c;
    int claimed[4] = {0, 0, 0, 0};
    for (int i = 0; i < n; ++i) {
      const int c = cc_buf[i];
      if (c < 0 || c > 3) continue;
      int lo = (int)a2b[i];
      if (claimed[c] > lo) lo = claimed[c];
      int hi = (int)a2b[i + 1];
      if (hi < lo) hi = lo;
      if (hi < m && cs[hi] == c) ++hi;
      if (lo > claimed[c] && cs[lo - 1] == c) --lo;
      if (hi <= lo) continue;
      claimed[c] = hi;
      int64_t o = 0;
      for (int q = lo; q < hi; ++q)
        if (cs[q] == c) o += cr[q];
      int Li = cr_buf[i];
      if (Li < 1) Li = 1;
      if (Li > Lmax) Li = Lmax;
      const double v = tab[(size_t)Li * TO + (o > Omax ? Omax : (int)o)];
      if (std::isfinite(v)) {
        J += v;
      } else {
        J -= 60.0;   // impossible-under-model observation: crushing but
        //              finite, one outlier cannot veto via -inf
      }
    }
  }
  return J;
}
}  // namespace

int64_t hp_rescue_windows(
    const int8_t* seqs, const int32_t* lens, const int32_t* nsegs,
    int32_t B, int32_t D, int32_t L,
    const float* table0, int32_t P0, int32_t O0,
    int32_t k0, int32_t minc0, int32_t eminc0,
    int32_t wlen, int32_t anchor_slack, int32_t end_slack, int32_t len_slack,
    int32_t n_candidates, int32_t min_depth, double max_err,
    float count_frac,
    double hp_err, int32_t hp_min_run, double hp_margin, int32_t n_threads,
    const int8_t* cons_in, int32_t CL,
    int8_t* hp_cons, int32_t CLH,
    int32_t* cons_lens, float* errs, int32_t* tiers_io,
    // calibrated posterior vote (oracle/hp.py vote_runs_posterior), r5:
    // post_tabs = [n_mult, Lmax+1, Omax+1] float64 log P(o|L) tables built
    // by the PYTHON hp_length_tables (bit-exact likelihoods; C++ only
    // mirrors the vote walk and same-order float64 accumulation), one per
    // quantized heat multiplier 1.0,1.25,..; NULL = median vote (r4).
    const double* post_tabs, int32_t n_mult, int32_t Lmax, int32_t Omax,
    double p_err_prof, double mult_lo, double mult_step,
    // likelihood-ratio acceptance (oracle/hp.py hp_loglik; r5): 1 = accept
    // the candidate that better explains the segments under the model
    // (only meaningful with post_tabs; solved windows only), 0 = raw
    // rescore bar. lambda_c = compressed-space edit penalty (log units).
    int32_t accept_likelihood, double lambda_c) {
  const dbgc::TierSpec ts_hp = {k0, minc0, eminc0, P0, O0, 0, table0};
  std::atomic<int32_t> next(0);
  std::atomic<int64_t> rescued(0);
  auto max_run_of = [](const int8_t* s, int n) {
    int best = 0, run = 0;
    for (int i = 0; i < n; ++i) {
      run = (i > 0 && s[i] == s[i - 1]) ? run + 1 : 1;
      if (run > best) best = run;
    }
    return best;
  };
  auto worker = [&]() {
    dbgc::Scratch S;
    std::vector<int8_t> cseqs((size_t)D * L);
    std::vector<int32_t> clens(D), cruns((size_t)D * L), med_buf;
    std::vector<int32_t> runs_out;
    std::vector<int8_t> hcons, expanded;
    std::vector<int64_t> a2b;
    std::vector<int32_t> Dbuf_v;   // align_path / rescore DP matrix
    std::vector<std::vector<int32_t>> pos_votes;
    std::vector<double> ll_buf;    // posterior log-likelihood accumulator
    std::vector<int32_t> nv_buf;
    std::vector<int8_t> cc_buf;    // hp_loglik_c candidate compression
    std::vector<int32_t> cr_buf;
    for (;;) {
      const int b = next.fetch_add(1);
      if (b >= B) return;
      const int nseg = nsegs[b];
      if (nseg < min_depth) continue;
      const bool solved = tiers_io[b] >= 0;
      // thresholds stay double end to end: the python host pass compares
      // float64 config values, and a float32-narrowed 0.12 differs from
      // float64 0.12 by enough to flip borderline routing decisions
      const double derr = solved ? (double)errs[b]
                                 : std::numeric_limits<double>::infinity();
      if (solved && derr <= hp_err) continue;
      const int8_t* wseqs = seqs + (size_t)b * D * L;
      const int32_t* wlens = lens + (size_t)b * D;
      // routing probe: a long run must exist for a vote to fix anything
      int mrun = 0;
      if (solved) {
        mrun = max_run_of(cons_in + (size_t)b * CL, cons_lens[b]);
      } else {
        for (int j = 0; j < nseg && mrun < hp_min_run; ++j)
          mrun = std::max(mrun, max_run_of(wseqs + (size_t)j * L, wlens[j]));
      }
      if (mrun < hp_min_run) continue;
      // ---- run-length compress into the same [D, L] layout --------------
      int64_t seg_total = 0;
      for (int j = 0; j < nseg; ++j) {
        const int8_t* s = wseqs + (size_t)j * L;
        const int n = wlens[j];
        seg_total += n;
        int8_t* cs = cseqs.data() + (size_t)j * L;
        int32_t* cr = cruns.data() + (size_t)j * L;
        int m = 0;
        for (int i = 0; i < n; ++i) {
          if (m > 0 && s[i] == cs[m - 1]) {
            ++cr[m - 1];
          } else {
            cs[m] = s[i];
            cr[m] = 1;
            ++m;
          }
        }
        clens[j] = m;
      }
      // wlen_c = int(np.median(clens)): sorted middle, even -> mean then
      // int() truncation toward zero
      med_buf.assign(clens.begin(), clens.begin() + nseg);
      std::sort(med_buf.begin(), med_buf.end());
      const int mid = nseg / 2;
      const int wlen_c =
          (nseg & 1) ? med_buf[mid]
                     : (int)((med_buf[mid - 1] + med_buf[mid]) / 2.0);
      if (wlen_c < k0 + 4) continue;
      // ---- full-graph DBG on the compressed subproblem -------------------
      hcons.assign((size_t)wlen_c + len_slack, PAD);
      int32_t hlen = 0;
      float herr = 0.0f;
      uint8_t hm = 0;
      if (dbgc::try_tier(cseqs.data(), clens.data(), nseg, L, ts_hp, wlen_c,
                         anchor_slack, end_slack, len_slack, n_candidates,
                         (float)max_err, count_frac, S, hcons.data(), &hlen,
                         &herr, &hm) != 0)
        continue;
      // ---- aligned per-position run-length vote --------------------------
      a2b.resize(hlen + 1);
      runs_out.assign(hlen, 1);
      int64_t out_len = 0;
      const double* tab_sel = nullptr;   // heat-selected posterior table
      if (post_tabs != nullptr) {
        // calibrated posterior (vote_runs_posterior parity): per segment,
        // per-base claim cursors keep same-base counted spans disjoint;
        // the observation is the summed same-base run length over the
        // (one-position-extended) span; argmax_L of the summed log
        // likelihood, first-max tie-break like np.argmax.
        // heat grid comes from oracle/hp.py's shared constants (mult_lo,
        // mult_step, n_mult) — the ONE definition; hp_heat() parity:
        // round to the step grid (nearbyint = python round ties-even on
        // the same exact power-of-two arithmetic), then clip
        const int TL = Lmax + 1, TO = Omax + 1;
        const double mult_hi = mult_lo + mult_step * (n_mult - 1);
        const double m_raw = std::isfinite(derr)
            ? derr / std::max(p_err_prof, 1e-3) : 1.5;
        double mq = std::nearbyint(m_raw / mult_step) * mult_step;
        if (mq < mult_lo) mq = mult_lo;
        if (mq > mult_hi) mq = mult_hi;
        int mi = (int)std::nearbyint((mq - mult_lo) / mult_step);
        if (mi < 0) mi = 0;
        if (mi >= n_mult) mi = n_mult - 1;
        const double* tab = post_tabs + (size_t)mi * TL * TO;
        tab_sel = tab;
        ll_buf.assign((size_t)hlen * TL, 0.0);
        nv_buf.assign(hlen, 0);
        for (int j = 0; j < nseg; ++j) {
          const int m = clens[j];
          if (m == 0) continue;
          align_path(hcons.data(), hlen, cseqs.data() + (size_t)j * L, m,
                     Dbuf_v, a2b.data());
          const int32_t* cr = cruns.data() + (size_t)j * L;
          const int8_t* cs = cseqs.data() + (size_t)j * L;
          int claimed[4] = {0, 0, 0, 0};
          for (int i = 0; i < hlen; ++i) {
            const int c = hcons[i];
            if (c < 0 || c > 3) continue;
            int lo = (int)a2b[i];
            if (claimed[c] > lo) lo = claimed[c];
            int hi = (int)a2b[i + 1];
            if (hi < lo) hi = lo;
            if (hi < m && cs[hi] == c) ++hi;
            if (lo > claimed[c] && cs[lo - 1] == c) --lo;
            if (hi <= lo) continue;
            int64_t o = 0;
            for (int q = lo; q < hi; ++q)
              if (cs[q] == c) o += cr[q];
            const int oc = o > Omax ? Omax : (int)o;
            double* row = ll_buf.data() + (size_t)i * TL;
            for (int Lv = 0; Lv < TL; ++Lv)
              row[Lv] += tab[(size_t)Lv * TO + oc];
            nv_buf[i] += 1;
            claimed[c] = hi;
          }
        }
        for (int i = 0; i < hlen; ++i) {
          if (nv_buf[i]) {
            const double* row = ll_buf.data() + (size_t)i * TL;
            int bestL = 1;
            double bestv = row[1];
            for (int Lv = 2; Lv < TL; ++Lv)
              if (row[Lv] > bestv) { bestv = row[Lv]; bestL = Lv; }
            runs_out[i] = bestL;
          }
          out_len += runs_out[i];
        }
      } else {
      pos_votes.assign(hlen, {});
      for (int j = 0; j < nseg; ++j) {
        const int m = clens[j];
        if (m == 0) continue;
        align_path(hcons.data(), hlen, cseqs.data() + (size_t)j * L, m,
                   Dbuf_v, a2b.data());
        const int32_t* cr = cruns.data() + (size_t)j * L;
        const int8_t* cs = cseqs.data() + (size_t)j * L;
        for (int i = 0; i < hlen; ++i)
          for (int64_t q = a2b[i]; q < a2b[i + 1]; ++q)
            if (cs[q] == hcons[i]) pos_votes[i].push_back(cr[q]);
      }
      for (int i = 0; i < hlen; ++i) {
        auto& v = pos_votes[i];   // sort in place: no per-position copies
        if (!v.empty()) {
          std::sort(v.begin(), v.end());
          const int vm = (int)v.size() / 2;
          const double med = (v.size() & 1) ? (double)v[vm]
                                            : (v[vm - 1] + v[vm]) / 2.0;
          // int(round(med)): python round() is half-to-even; nearbyint
          // honors the default FE_TONEAREST (ties-to-even) mode
          runs_out[i] = std::max(1, (int)std::nearbyint(med));
        }
        out_len += runs_out[i];
      }
      }
      if (out_len < wlen / 2 || out_len > 2 * wlen || out_len > CLH)
        continue;
      expanded.resize(out_len);
      {
        int64_t w = 0;
        for (int i = 0; i < hlen; ++i)
          for (int r = 0; r < runs_out[i]; ++r) expanded[w++] = hcons[i];
      }
      // ---- exact rescore vs the ORIGINAL segments ------------------------
      int64_t tot = 0;
      for (int j = 0; j < nseg; ++j) {
        const int m = wlens[j];
        const int n = (int)out_len;
        if (n == 0) { tot += m; continue; }
        if (m == 0) { tot += n; continue; }
        Dbuf_v.resize((size_t)(n + 1) * (m + 1));
        tot += fill_exact(expanded.data(), n, wseqs + (size_t)j * L, m,
                          Dbuf_v.data(), m + 1, 16);
      }
      const double err_hp =
          (double)tot / (double)std::max<int64_t>(seg_total, 1);
      if (accept_likelihood && tab_sel != nullptr && solved) {
        // likelihood-ratio acceptance (hp_loglik parity): the expanded
        // candidate must EXPLAIN the segments better than the direct one,
        // with a loose raw-error sanity bound (oracle/hp.py hp_candidate)
        const double j_exp = hp_loglik_c(
            expanded.data(), (int)out_len, cseqs.data(), cruns.data(),
            clens.data(), nseg, L, tab_sel, Lmax, Omax, lambda_c,
            cc_buf, cr_buf, a2b, Dbuf_v);
        const double j_dir = hp_loglik_c(
            cons_in + (size_t)b * CL, cons_lens[b], cseqs.data(),
            cruns.data(), clens.data(), nseg, L, tab_sel, Lmax, Omax,
            lambda_c, cc_buf, cr_buf, a2b, Dbuf_v);
        if (!(j_exp > j_dir) || err_hp > derr + 0.10) continue;
      } else {
        const double bar = solved ? derr - hp_margin : max_err;
        if (err_hp >= bar) continue;
      }
      int8_t* out_row = hp_cons + (size_t)b * CLH;
      std::memset(out_row, PAD, CLH);
      std::memcpy(out_row, expanded.data(), out_len);
      cons_lens[b] = (int32_t)out_len;
      errs[b] = (float)err_hp;
      tiers_io[b] = 29;  // HP_TIER (oracle/hp.py)
      rescued.fetch_add(1);
    }
  };
  if (n_threads <= 1) {
    worker();
  } else {
    std::vector<std::thread> pool;
    for (int i = 0; i < n_threads; ++i) pool.emplace_back(worker);
    for (auto& th : pool) th.join();
  }
  return rescued.load();
}

}  // extern "C"
