"""Native host library loader: builds dazz_native.cpp with g++ on first use.

No pybind11 in this image (SURVEY environment constraints), so the library is
a plain C ABI loaded through ctypes; ``available()`` gates every caller and
the pure-Python paths remain as fallback (and as the executable spec).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "dazz_native.cpp")
_lock = threading.Lock()
_lib = None
_tried = False


def _tsan() -> bool:
    return bool(os.environ.get("DACCORD_NATIVE_TSAN"))


def _so_path() -> str:
    # the TSAN build gets its own artifact so a race-detection run never
    # shadows the optimized library for later normal runs
    name = "libdazz_native_tsan.so" if _tsan() else "libdazz_native.so"
    return os.path.join(_DIR, name)


def _build(so: str) -> bool:
    if _tsan():
        # race-detection build (SURVEY.md §5 race row): the library is called
        # concurrently by the feeder thread pool
        cmd = ["g++", "-O1", "-g", "-fsanitize=thread", "-shared", "-fPIC",
               "-std=c++17", _SRC, "-o", so]
    else:
        cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
               _SRC, "-o", so]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=300)
        return True
    except Exception:
        return False


def load():
    """Return the ctypes library, building it if needed; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        so = _so_path()
        # <= not <: a fresh checkout stamps .so and .cpp with identical mtimes,
        # and a stale -march=native build from another host can SIGILL at call
        # time even though CDLL load succeeds — rebuild on any tie
        if not os.path.exists(so) or os.path.getmtime(so) <= os.path.getmtime(_SRC):
            if not _build(so):
                return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            return None
        c = ctypes
        lib.las_scan.restype = c.c_int
        lib.las_scan.argtypes = [c.c_char_p, c.c_int64, c.c_int64,
                                 c.POINTER(c.c_int64), c.POINTER(c.c_int32),
                                 c.POINTER(c.c_int64)]
        lib.las_load.restype = c.c_int
        lib.las_load.argtypes = [c.c_char_p, c.c_int64, c.c_int64, c.c_int64] + [c.c_void_p] * 10
        lib.las_sort.restype = c.c_int64
        lib.las_sort.argtypes = [c.c_char_p, c.c_char_p, c.c_char_p, c.c_int64]
        lib.las_merge.restype = c.c_int64
        lib.las_merge.argtypes = [c.c_char_p, c.c_char_p, c.c_int32]
        lib.suffix_prefix.restype = c.c_int
        lib.suffix_prefix.argtypes = [c.c_void_p, c.c_int32, c.c_void_p, c.c_int32,
                                      c.POINTER(c.c_int32), c.POINTER(c.c_int32),
                                      c.POINTER(c.c_int32)]
        lib.decode_reads.restype = c.c_int
        lib.decode_reads.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p,
                                     c.c_int32, c.c_void_p, c.c_void_p]
        lib.solve_windows.restype = c.c_int
        lib.solve_windows.argtypes = (
            [c.c_void_p] * 3 + [c.c_int32] * 3     # seqs/lens/nsegs, B D L
            + [c.c_void_p] * 8 + [c.c_int32]       # tables, off, tier arrays (k/minc/eminc/P/O/M), n_tiers
            + [c.c_int32] * 6                      # wlen..min_depth
            + [c.c_float] * 2 + [c.c_int32]        # max_err, count_frac, n_threads
            + [c.c_void_p] * 5)                    # cons, lens, errs, tiers, movf
        lib.process_pile.restype = c.c_int
        lib.process_pile.argtypes = (
            [c.c_void_p, c.c_int32, c.c_int32]        # a, alen, novl
            + [c.c_void_p] * 5                        # abpos..comp
            + [c.c_void_p] * 3                        # b_concat, b_off, b_len
            + [c.c_void_p] * 2                        # trace_flat, trace_off
            + [c.c_int32] * 6                         # tspace, w, adv, D, L, include_a
            + [c.c_void_p] * 3 + [c.c_int32])         # outputs + nwin
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None
