"""HTTP/JSON front-end for the consensus service (stdlib only).

``ThreadingHTTPServer`` + JSON bodies — no new dependencies, per the repo
doctrine. The API surface:

    POST   /v1/jobs             submit a job (JSON: db/las paths or
                                base64 ``files`` upload + config knobs);
                                201 {job, state} | 400 bad spec/ingest |
                                429 quota | 503 pressure/draining |
                                507 disk_pressure (the volume is — or is
                                about to be — full; retryable).
                                ``idempotency_key`` (ISSUE 15): a seen key
                                answers 200 with the EXISTING job — the
                                retry path for clients whose 201 was lost
                                to a server crash (keys ride the journal,
                                so dedupe survives restarts)
    GET    /v1/jobs             all jobs' status
    GET    /v1/jobs/<id>        one job's status (404 unknown)
    GET    /v1/jobs/<id>/result the committed FASTA; ``?wait=1`` blocks to
                                a terminal state first (409 if not done)
    GET    /v1/jobs/<id>/stream chunked live FASTA as fragments commit; a
                                client disconnect mid-stream ABORTS the job
                                (the poison-free abort path the batcher
                                guarantees — cohabiting jobs unaffected)
    DELETE /v1/jobs/<id>        abort
    GET    /v1/healthz          liveness + uptime + queue depth + per-group
                                busy flags + RSS (lock-free: never queues
                                behind a group solve)
    GET    /v1/metrics          registry rollup (latency quantiles),
                                admission + warm-state + batcher stats;
                                ``?format=prom`` = Prometheus text
                                exposition of the same registry
    POST   /v1/shutdown         graceful drain + stop

Streaming reads the job's ``out.fasta.part`` as it grows — the runner
flushes after every emitted read, so the stream tracks pipeline progress at
read granularity with no extra buffering layer.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .admission import AdmissionReject
from .jobs import ABORTED, DONE, FAILED
from .netio import BODY_BYTES_HEADER, STREAM_BYTES_TRAILER


def _json_bytes(obj) -> bytes:
    return (json.dumps(obj) + "\n").encode()


class ServeHandler(BaseHTTPRequestHandler):
    # the service is attached to the server object by serve()
    protocol_version = "HTTP/1.1"

    @property
    def svc(self):
        return self.server.service  # type: ignore[attr-defined]

    def log_message(self, fmt, *args):  # stdlib default spams stderr
        pass

    # -- helpers ---------------------------------------------------------

    def _send(self, code: int, obj=None, body: bytes | None = None,
              ctype: str = "application/json") -> None:
        payload = body if body is not None else _json_bytes(obj)
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(payload)))
        # end-to-end integrity (ISSUE 18): unlike Content-Length, this
        # survives proxies that re-frame the body — netio verifies it and
        # turns a torn response into a retryable error, not a short commit
        self.send_header(BODY_BYTES_HEADER, str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _body_json(self) -> dict:
        n = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(n) if n else b"{}"
        obj = json.loads(raw.decode() or "{}")
        if not isinstance(obj, dict):
            raise ValueError("body must be a JSON object")
        return obj

    def _job_route(self):
        parts = [p for p in self.path.split("?")[0].split("/") if p]
        # ['v1', 'jobs', '<id>', maybe 'result'|'stream']
        if len(parts) >= 3 and parts[0] == "v1" and parts[1] == "jobs":
            return parts[2], (parts[3] if len(parts) > 3 else None)
        return None, None

    def _query(self) -> dict:
        if "?" not in self.path:
            return {}
        out = {}
        for kv in self.path.split("?", 1)[1].split("&"):
            k, _, v = kv.partition("=")
            out[k] = v
        return out

    # -- routes ----------------------------------------------------------

    def do_POST(self) -> None:  # noqa: N802 (stdlib casing)
        path = self.path.split("?")[0]
        if path == "/v1/jobs":
            try:
                body = self._body_json()
            except (ValueError, json.JSONDecodeError) as e:
                return self._send(400, {"error": f"bad body: {e}"})
            try:
                st = self.svc.submit(body)
            except AdmissionReject as e:
                # 507 Insufficient Storage for the disk-pressure governor
                # (ISSUE 17): machine-readable, retryable — clients back
                # off until the volume recovers
                if e.reason == "disk_pressure":
                    code = 507
                elif e.reason in ("pressure", "draining"):
                    code = 503
                else:
                    code = 429
                return self._send(code, {"error": str(e), "reason": e.reason,
                                         "retryable": e.retryable})
            except (ValueError, TypeError) as e:
                # TypeError covers wrong-typed spec fields (e.g. "k" sent
                # as a JSON string): a malformed request must get a 400,
                # never a dropped connection
                return self._send(400, {"error": str(e)})
            # an idempotency_key replay answers with the EXISTING job
            # (200, not 201 — nothing was created); see service.submit
            return self._send(200 if st.get("idempotent") else 201, st)
        if path == "/v1/shutdown":
            # drain in a side thread: the response must make it out before
            # the listener stops accepting
            threading.Thread(target=self._shutdown_later,
                             daemon=True).start()
            return self._send(200, {"state": "draining"})
        self._send(404, {"error": "unknown route"})

    def _shutdown_later(self) -> None:
        self.svc.shutdown(drain=True)
        self.server.shutdown()  # type: ignore[attr-defined]

    def do_GET(self) -> None:  # noqa: N802
        path = self.path.split("?")[0]
        if path == "/v1/healthz":
            # lock-free-ish liveness: must never queue behind a group's
            # solve lock (a jit compile holds it for minutes)
            if self.headers.get("X-Daccord-Router"):
                # a front-door router is polling us (ISSUE 16): arm the
                # evict-vs-route grace so the idle sweep defers evicting
                # groups the router's stickiness still points at
                self.svc.warm.note_router_heartbeat()
            return self._send(200, self.svc.health())
        if path == "/v1/metrics":
            if self._query().get("format") == "prom":
                # Prometheus text exposition (ISSUE 13): the scrapeable
                # health plane — registry + health/admission gauges through
                # obs.render_prom, no group solve lock taken
                return self._send(200, body=self.svc.stats_prom().encode(),
                                  ctype="text/plain; version=0.0.4")
            return self._send(200, self.svc.stats())
        if path == "/v1/jobs":
            with self.svc._jobs_lock:
                out = [j.status() for j in self.svc.jobs.values()]
            return self._send(200, out)
        job_id, sub = self._job_route()
        if job_id is None:
            return self._send(404, {"error": "unknown route"})
        st = self.svc.status(job_id)
        if st is None:
            return self._send(404, {"error": f"unknown job {job_id!r}"})
        if sub is None:
            return self._send(200, st)
        if sub == "result":
            q = self._query()
            if q.get("wait"):
                try:
                    timeout_s = float(q.get("timeout", 300))
                except ValueError:
                    return self._send(400,
                                      {"error": "timeout must be a number"})
                st = self.svc.wait(job_id, timeout_s=timeout_s)
            if st["state"] != DONE:
                code = 409 if st["state"] not in (FAILED, ABORTED) else 410
                return self._send(code, st)
            with self.svc._jobs_lock:
                job = self.svc.jobs[job_id]
            with open(job.fasta, "rb") as fh:
                data = fh.read()
            return self._send(200, body=data, ctype="text/x-fasta")
        if sub == "stream":
            return self._stream(job_id)
        self._send(404, {"error": f"unknown subresource {sub!r}"})

    def _stream(self, job_id: str) -> None:
        """Chunked live FASTA; client disconnect aborts the job (the
        mid-job-disconnect contract: the batcher drops its pooled rows,
        cohabiting batches finish untouched)."""
        with self.svc._jobs_lock:
            job = self.svc.jobs[job_id]
        self.send_response(200)
        self.send_header("Content-Type", "text/x-fasta")
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header("Trailer", STREAM_BYTES_TRAILER)
        self.end_headers()

        def chunk(data: bytes) -> None:
            self.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")

        pos = 0
        try:
            while True:
                src = job.fasta if os.path.exists(job.fasta) else \
                    job.fasta_part
                if os.path.exists(src):
                    with open(src, "rb") as fh:
                        fh.seek(pos)
                        data = fh.read(1 << 20)
                    if data:
                        chunk(data)
                        pos += len(data)
                        continue
                if job.state in (DONE, FAILED, ABORTED):
                    break
                time.sleep(0.05)
            # terminal chunk + byte-count trailer: a consumer (the router's
            # verified proxy, netio.stream) that got fewer bytes knows the
            # stream tore — a short FASTA must never look complete
            self.wfile.write(b"0\r\n" + STREAM_BYTES_TRAILER.encode()
                             + b": %d\r\n\r\n" % pos)
        except (BrokenPipeError, ConnectionResetError):
            self.svc.abort(job_id, reason="disconnect")
            self.close_connection = True

    def do_DELETE(self) -> None:  # noqa: N802
        job_id, sub = self._job_route()
        if job_id is None or sub is not None:
            return self._send(404, {"error": "unknown route"})
        ok = self.svc.abort(job_id, reason="delete")
        st = self.svc.status(job_id)
        if st is None:
            return self._send(404, {"error": f"unknown job {job_id!r}"})
        return self._send(200 if ok else 409, st)


def start_server(service, host: str = "127.0.0.1", port: int = 0):
    """Bind + start the HTTP front-end on a daemon thread; returns
    ``(httpd, bound_port, thread)``. ``port=0`` binds an ephemeral port —
    pair with a ready-file so scripts can discover it."""
    httpd = ThreadingHTTPServer((host, port), ServeHandler)
    httpd.daemon_threads = True
    httpd.service = service  # type: ignore[attr-defined]
    t = threading.Thread(target=httpd.serve_forever, daemon=True,
                         name="daccord-serve-http")
    t.start()
    return httpd, httpd.server_address[1], t
