"""Admission control: per-tenant quotas + RSS watermarks (ISSUE 10 (c)).

Admission is the OUTER pressure valve, deliberately ahead of the two the
pipeline already has: the serve watermarks should sit below the governor's
feeder watermarks (``DACCORD_GOV_RSS_*``), so a loaded server stops taking
NEW work before any running job's feeder has to pause, and the OS OOM killer
never gets a vote. Per-tenant quotas (queued jobs, queued input bytes) keep
one tenant from monopolizing the queue; the shed path (the service halving
group batch widths under sustained pressure — the capacity governor's batch
ladder as overload policy) degrades throughput, never correctness.

Every decision is counted and logged (``serve.admit`` / ``serve.reject``)
so a capacity report can reconstruct exactly what was shed and why.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from ..runtime.governor import host_rss_mb


class AdmissionReject(Exception):
    """Admission refused: ``reason`` is machine-readable (quota_jobs,
    quota_bytes, queue_full, pressure, disk_pressure, draining);
    ``retryable`` hints the HTTP layer between 429/503/507 (back off and
    retry) and 400-class refusals."""

    def __init__(self, reason: str, detail: str = "", retryable: bool = True):
        super().__init__(detail or reason)
        self.reason = reason
        self.retryable = retryable


@dataclass
class AdmissionConfig:
    max_queued_jobs: int = 32        # service-wide queue depth
    tenant_max_queued: int = 8       # queued+running jobs per tenant
    tenant_max_bytes: int = 1 << 30  # queued input bytes per tenant
    rss_soft_mb: float = 0.0         # pause admission at this host RSS
    rss_hard_mb: float = 0.0         # reject + shed at this host RSS
                                     # (0 = watermark off)
    # free-bytes watermarks (ISSUE 17), mirroring the RSS pair: admission
    # pauses when the watched volume's free space sinks to soft, and the
    # service's disk-pressure governor engages at hard. 0 = off.
    disk_soft_mb: float = 0.0
    disk_hard_mb: float = 0.0
    watch_dir: str = ""              # the volume the watermarks read
                                     # (the serve workdir; "" = off)


@dataclass
class _Tenant:
    queued: int = 0
    bytes: int = 0
    admitted: int = 0
    rejected: int = 0
    extra: dict = field(default_factory=dict)


class AdmissionController:
    def __init__(self, cfg: AdmissionConfig | None = None, log=None,
                 faults=None):
        from ..utils.obs import NullLogger

        self.cfg = cfg or AdmissionConfig()
        self.log = log if log is not None else NullLogger()
        self.faults = faults
        self._lock = threading.Lock()
        self._tenants: dict[str, _Tenant] = {}
        self._queued = 0
        self._draining = False
        self.counters = {"admitted": 0, "rejected": 0, "shed": 0}
        # disk-pressure latch (ISSUE 17): the service sets this to a detail
        # string when the journal's own appends start failing (the watermark
        # may not have seen it coming — ENOSPC can arrive first) and clears
        # it once the volume recovers; any non-None value refuses admission
        # with the 507-style ``disk_pressure`` reason
        self.disk_pressure: str | None = None

    def drain(self) -> None:
        """Stop admitting (graceful shutdown); running jobs finish."""
        self._draining = True

    def disk_level(self) -> tuple[str | None, float]:
        """(level, free_mb) of the watched volume against the free-bytes
        watermarks, mirroring :meth:`pressure_level`; (None, -1.0) when the
        watermarks are off or the volume is unreadable."""
        from ..utils.obs import disk_free_mb

        cfg = self.cfg
        if not cfg.watch_dir or not (cfg.disk_soft_mb or cfg.disk_hard_mb):
            return None, -1.0
        free = disk_free_mb(cfg.watch_dir)
        if free < 0:
            return None, free
        if cfg.disk_hard_mb and free <= cfg.disk_hard_mb:
            return "hard", free
        if cfg.disk_soft_mb and free <= cfg.disk_soft_mb:
            return "soft", free
        return None, free

    def pressure_level(self) -> tuple[str | None, float]:
        """(level, rss_mb) against the ADMISSION watermarks. The injected
        ``host_rss`` fault reports hard pressure deterministically (same
        counter domain the pipeline's feeder watermark consumes — in a serve
        process the admission check runs first, so the injection lands
        here)."""
        if self.faults is not None and self.faults.host_rss_check():
            return "hard", host_rss_mb()
        cfg = self.cfg
        if not (cfg.rss_soft_mb or cfg.rss_hard_mb):
            return None, 0.0
        rss = host_rss_mb()
        if cfg.rss_hard_mb and rss >= cfg.rss_hard_mb:
            return "hard", rss
        if cfg.rss_soft_mb and rss >= cfg.rss_soft_mb:
            return "soft", rss
        return None, rss

    def admit(self, tenant: str, nbytes: int, job: str = "") -> None:
        """Charge ``tenant`` for one queued job of ``nbytes`` input, or
        raise :class:`AdmissionReject`. Pair with :meth:`release`."""
        with self._lock:
            t = self._tenants.setdefault(tenant, _Tenant())
            reason = None
            if self._draining:
                reason = "draining"
            elif self.disk_pressure is not None \
                    or self.disk_level()[0] is not None:
                # the volume is (or is about to be) full: both the journal-
                # failure latch and the free-bytes watermarks refuse new
                # work with the machine-readable 507-style reason — running
                # jobs keep their already-charged quota and finish
                reason = "disk_pressure"
            else:
                level, rss = self.pressure_level()
                if level is not None:
                    # admission pauses BEFORE the feeder watermarks engage:
                    # both levels refuse new work; hard additionally drives
                    # the service's shed ladder (service ticker)
                    reason = "pressure"
                    self.counters["shed"] += 1
                elif self._queued >= self.cfg.max_queued_jobs:
                    reason = "queue_full"
                elif t.queued >= self.cfg.tenant_max_queued:
                    reason = "quota_jobs"
                elif t.bytes + nbytes > self.cfg.tenant_max_bytes:
                    reason = "quota_bytes"
            if reason is not None:
                t.rejected += 1
                self.counters["rejected"] += 1
                self.log.log("serve.reject", tenant=tenant, reason=reason,
                             job=job, bytes=int(nbytes))
                detail = f"tenant {tenant!r}: {reason}"
                if reason == "disk_pressure" and self.disk_pressure:
                    detail += f" ({self.disk_pressure})"
                raise AdmissionReject(
                    reason, detail,
                    retryable=reason in ("pressure", "disk_pressure",
                                         "queue_full", "quota_jobs",
                                         "quota_bytes"))
            t.queued += 1
            t.bytes += int(nbytes)
            t.admitted += 1
            self._queued += 1
            self.counters["admitted"] += 1
            self.log.log("serve.admit", tenant=tenant, job=job,
                         bytes=int(nbytes), queued=self._queued)

    def release(self, tenant: str, nbytes: int) -> None:
        with self._lock:
            t = self._tenants.get(tenant)
            if t is None:
                return
            t.queued = max(0, t.queued - 1)
            t.bytes = max(0, t.bytes - int(nbytes))
            self._queued = max(0, self._queued - 1)

    def stats(self) -> dict:
        with self._lock:
            return {**self.counters, "queued": self._queued,
                    "draining": self._draining,
                    "disk_pressure": bool(self.disk_pressure),
                    "tenants": {k: {"queued": t.queued, "bytes": t.bytes,
                                    "admitted": t.admitted,
                                    "rejected": t.rejected}
                                for k, t in sorted(self._tenants.items())}}
