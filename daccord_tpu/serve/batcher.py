"""Cross-job batcher: shared device batches over concurrent jobs' windows.

The serving plane's core claim (ISSUE 10): window streams from concurrent
jobs may share device batches, because every window solves independently —
the same per-window-independence argument behind the split ladder, the
governor's bisect, and the paged router. A job's pipeline runs exactly as a
solo run does (its own feeder, profile, scatter, stitch, commit); only its
dispatch seam changes: instead of solving its own (possibly partial) batches,
it hands row blocks to a :class:`SolveGroup`, which pools rows from every
cohabiting job per (depth, seg-len, stream) bucket, flushes MERGED batches
padded to the service width through ONE shared supervised solve path, and
scatters each merged result back to the per-job handles. The shared path is
a full production stack — DeviceSupervisor watchdog/retries/failover plus
the capacity governor's bisect/clamp ladder — so a device_lost replays a
mixed-job batch whole and a device_oom bisects it, with every job's bytes
unchanged (tests/test_serve.py).

Warmth is the point: the group owns the TierLadder (and therefore the jitted
programs' cache identity), the supervisor's compile-fingerprint state, and
the governor's capacity ratchets, so the Nth job pays none of the cold-start
a fresh ``daccord`` invocation would. Groups are keyed by solve fingerprint
(profile + consensus config + backend — see ``jobs.solve_fingerprint``):
jobs whose solve semantics differ can never share a batch, because their
results would differ; they still share the process, the admission plane, and
the warm cache.

Optional group modes mirror the pipeline's dispatch strategies:

- ``ladder_mode='split'``: job pipelines run the two-stream machinery
  (``PipelineConfig.ladder_mode='split'`` with the solver's
  ``routes_streams`` opt-in); tier0 and rescue rows pool separately here and
  each merged batch routes via ``kernels.tiers.stream_dispatcher`` — the
  SAME routing rule the pipeline uses.
- ``paged=True``: merged batches pack into the ragged paged wire format
  (``kernels/paging.py``); shape families derive lazily from the first
  pooled rows per bucket, so the family router reflects the live mix of
  workloads rather than any one job's sample.

Locking: one RLock per group serializes pool mutation AND the device solve.
Jobs therefore take turns driving the device — correct (one device is one
resource) and simple; the in-flight deque still overlaps each job's host
windowing with device work exactly as the solo pipeline does.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from ..kernels.tensorize import BatchShape, WindowBatch, pad_batch, slice_batch
from ..runtime.governor import GovernorConfig, merge_results
from ..utils.obs import NullLogger, Tracer


class JobAborted(RuntimeError):
    """Raised by :meth:`SolveGroup.fetch` on a handle whose job was
    released mid-flight (client disconnect / DELETE). The job's own
    pipeline unwinds on it; cohabiting jobs never see it."""


class JobHandle:
    """One job-side dispatch: ``n`` rows whose results arrive as ordered
    parts (a handle's rows may split across consecutive merged batches).
    ``result()`` materializes via the governor's ``merge_results`` — the
    same row-exact merge the bisect rung trusts."""

    __slots__ = ("job", "n", "parts", "filled", "event", "aborted")

    def __init__(self, job: str, n: int):
        self.job = job
        self.n = int(n)
        self.parts: list[tuple[int, dict]] = []
        self.filled = 0
        self.event = threading.Event()
        self.aborted = False

    def add_part(self, n: int, out: dict) -> None:
        self.parts.append((n, out))
        self.filled += n
        if self.filled >= self.n:
            self.event.set()

    def abort(self) -> None:
        self.aborted = True
        self.event.set()

    def result(self) -> dict:
        return merge_results(self.parts)


class _Block:
    """A contiguous run of one handle's rows sitting in a pool."""

    __slots__ = ("handle", "batch", "pages")

    def __init__(self, handle: JobHandle, batch: WindowBatch, pages):
        self.handle = handle
        self.batch = batch
        self.pages = pages          # int64 [rows] (paged groups) or None


class _Pool:
    """FIFO of row blocks for one (depth, seg-len, stream[, family]) bucket."""

    __slots__ = ("blocks", "rows", "pages", "oldest_ts", "shape", "stream")

    def __init__(self, shape: BatchShape, stream: str):
        self.blocks: deque[_Block] = deque()
        self.rows = 0
        self.pages = 0
        self.oldest_ts: float | None = None
        self.shape = shape
        self.stream = stream

    def append(self, blk: _Block) -> None:
        self.blocks.append(blk)
        self.rows += blk.batch.size
        if blk.pages is not None:
            self.pages += int(blk.pages.sum())
        if self.oldest_ts is None:
            self.oldest_ts = time.time()


@dataclass
class GroupConfig:
    backend: str = "native"      # native | cpu | device (any jax platform)
    batch: int = 512             # merged dispatch width (service batch)
    ladder_mode: str = "fused"   # fused | split (group-level routing)
    paged: bool = False          # pack merged batches as the paged wire format
    page_len: int = 16
    paged_families: int = 4
    mesh: int = 0                # mesh-backed group (parallel/mesh.py):
                                 # merged cross-job batches shard over the
                                 # first N local devices through ONE warm
                                 # supervised solve path — N x the
                                 # continuous-batching width per compile;
                                 # 0/1 = single device (JAX backends only)
    use_pallas: bool = False
    max_inflight: int = 8        # merged batches in flight before a drain
    min_width: int = 8           # shed floor for the width ladder
    shed_levels: int = 0         # current load-shed level: merged batches
                                 # dispatch at batch >> shed_levels — the
                                 # batch ladder as the overload policy
                                 # (ISSUE 10 (c)); mutated via set_shed
    aot_dir: str | None = None   # fleet-shared AOT executable cache dir
                                 # (ISSUE 16, serve/aotcache.py): jitted
                                 # stream dispatches load/publish serialized
                                 # executables here so a fresh peer's first
                                 # solve skips the cold compile; None = off
    audit_rate: float | None = None  # sampled shadow verification rate for
                                 # the group's supervisor (ISSUE 20); None =
                                 # env DACCORD_AUDIT_RATE (1/64), 0 disables.
                                 # Native groups never audit: the reference
                                 # engine IS the primary there
    governor: GovernorConfig = field(default_factory=GovernorConfig.from_env)


class SolveGroup:
    """Shared solve path + cross-job row pools for one solve fingerprint.

    Construction mirrors the pipeline's solver resolution exactly (same
    helpers): ``native`` → the C++ ladder (inline supervisor, fallback =
    itself); ``cpu`` fused dense → host-routed ``solve_tiered``; anything
    else (device platforms, or cpu forced onto the jitted path by split/
    paged modes) → the async ladder via ``stream_dispatcher`` with the
    esc-cap clamp rung wired for the governor.
    """

    def __init__(self, key: str, profile, cfg, gcfg: GroupConfig,
                 log=None, name: str = "g0"):
        self.key = key
        self.name = name
        self.cfg = cfg                      # canonical PipelineConfig
        self.gcfg = gcfg
        self.log = log if log is not None else NullLogger()
        self.tracer = Tracer(self.log)
        self._lock = threading.RLock()
        self._pools: dict[tuple, _Pool] = {}
        self._inflight: deque = deque()
        self._families: dict[tuple, list] = {}   # (D, L) -> ShapeFamily list
        self.counters = {"dispatches": 0, "rows": 0, "batches": 0,
                         "mixed_batches": 0, "demand_flushes": 0,
                         "lag_flushes": 0, "shed_flushes": 0}
        # saturation accounting (ISSUE 14): dispatch wall, fetch-blocked
        # wall, and the device-busy occupancy integral over this group's
        # lifetime — the per-serve-group twin of the pipeline's gauges. A
        # native group solves INSIDE the dispatch call (sync), so its busy
        # time IS its dispatch wall; JAX groups are async and busy is the
        # in-flight occupancy window. All flush/drain runs under _lock.
        self.sat = {"dispatch_s": 0.0, "fetch_blocked_s": 0.0,
                    "busy_s": 0.0, "t0": None}
        self._sync_engine = gcfg.backend == "native"
        self.ladder = None
        self.mesh_solver = None      # set when gcfg.mesh > 1 (JAX backends)
        self.aot = None              # AotCache when gcfg.aot_dir (ISSUE 16)
        self._profile = profile
        self._hp_ols = None          # lazy; native groups set it at build
        self._build_solver(profile, cfg)
        # refcount/idle bookkeeping owned by WarmState
        self.refs = 0
        self.last_used = time.time()
        self.created = time.time()

    # ------------------------------------------------------------------
    # solve-path construction (the pipeline's resolution, reused)
    # ------------------------------------------------------------------

    def _build_solver(self, profile, cfg) -> None:
        from ..runtime.faults import FaultPlan
        from ..runtime.pipeline import _build_native_fallback, _make_clamp_solve
        from ..runtime.supervisor import DeviceSupervisor, SupervisorConfig

        g = self.gcfg
        clamp = None
        fetch_many = None
        rtt_s = None
        if g.backend == "native":
            if g.paged or g.ladder_mode == "split":
                raise ValueError("native serve groups run fused dense: the "
                                 "C++ engine escalates per window on host")
            base = _build_native_fallback(profile, cfg)
            dispatch, fetch = base, (lambda h: h)
            inline, prefix, desc = True, "native:", "serve-native-ladder"
            fallback_factory = (lambda: base)
            # the engine already built the OffsetLikely tables; share them
            # with every job's hp pass (read-only)
            self._hp_ols = base.ols
        else:
            import jax

            from ..kernels.tiers import TierLadder

            self.ladder = TierLadder.from_config(
                profile, cfg.consensus, max_kmers=cfg.max_kmers,
                rescue_max_kmers=cfg.rescue_max_kmers,
                overflow_rescue=cfg.overflow_rescue)
            is_cpu = jax.default_backend() == "cpu"
            prefix = jax.default_backend() + ":"
            ladder = self.ladder
            if g.mesh and g.mesh > 1:
                # mesh-backed group: merged cross-job batches shard over the
                # device mesh, through the same supervisor (with the
                # partial-mesh rung) and governor (per-device bisect) the
                # pipeline wraps a --mesh run in
                from ..kernels.window_kernel import pallas_needs_interpret
                from ..parallel.mesh import (check_mesh_devices, make_mesh,
                                             make_sharded_solver)
                from ..runtime.pipeline import _make_clamp_solve as _mk_clamp

                check_mesh_devices(g.mesh)
                interp = g.use_pallas and pallas_needs_interpret()
                self.mesh_solver = make_sharded_solver(
                    ladder, make_mesh(g.mesh), use_pallas=g.use_pallas,
                    pallas_interpret=interp, batch=g.batch)
                dispatch = self.mesh_solver.dispatch
                fetch = self.mesh_solver.fetch
                fetch_many = self.mesh_solver.fetch_many
                clamp = _mk_clamp(ladder, g.use_pallas, interp,
                                  g.governor.esc_clamp)
                inline = self.mesh_solver.host_local
                desc = f"serve-{self.mesh_solver.describe()}"
                if not inline:
                    from ..utils.obs import measure_rtt_s

                    rtt_s = measure_rtt_s()
                self.log.log("mesh.init", nd=int(self.mesh_solver.nd),
                             devices=self.mesh_solver.describe(),
                             esc_cap=int(
                                 self.mesh_solver._esc_cap_for(g.batch)))
            elif is_cpu and g.ladder_mode != "split" and not g.paged \
                    and not g.aot_dir:
                # with an AOT cache configured the CPU group falls through
                # to the packed-jit dispatcher below instead: solve_tiered
                # solves eagerly per tier and has no whole-program
                # executable to serialize (same ladder numerics either way)
                from ..kernels.tiers import solve_tiered

                dispatch = (lambda b: solve_tiered(b, ladder))
                fetch = (lambda h: h)
                inline, desc = True, "serve-cpu-ladder"
            else:
                from ..kernels.tiers import fetch as _fetch
                from ..kernels.tiers import fetch_many as _fetch_many
                from ..kernels.tiers import stream_dispatcher
                from ..kernels.window_kernel import pallas_needs_interpret

                interp = g.use_pallas and pallas_needs_interpret()
                if g.aot_dir:
                    # fleet-shared AOT executable cache (ISSUE 16): the
                    # same routing as stream_dispatcher, but each shape's
                    # program loads from / publishes to the shared cache —
                    # a freshly spawned peer's first dispatch deserializes
                    # in <1 s instead of paying the cold jit compile
                    from .aotcache import AotCache

                    self.aot = AotCache(g.aot_dir, log=self.log)
                    dispatch = self.aot.dispatcher(
                        ladder, use_pallas=g.use_pallas,
                        pallas_interpret=interp, fp_prefix=prefix)
                else:
                    dispatch = stream_dispatcher(
                        ladder, use_pallas=g.use_pallas,
                        pallas_interpret=interp)
                fetch = _fetch
                fetch_many = _fetch_many
                clamp = _make_clamp_solve(ladder, g.use_pallas, interp,
                                          g.governor.esc_clamp)
                inline = is_cpu
                desc = "serve-device-ladder" if not is_cpu else \
                    "serve-cpu-ladder-async"
                if not is_cpu:
                    from ..utils.obs import measure_rtt_s

                    rtt_s = measure_rtt_s()

            def fallback_factory():
                if is_cpu:
                    # exact-ladder host fallback (byte-exact vs the primary)
                    from ..kernels.tiers import solve_tiered as _st

                    def _cpu_fb(b):
                        if hasattr(b, "to_dense"):
                            b = b.to_dense()
                        return _st(b, ladder)

                    _cpu_fb.__name__ = "cpu-ladder"
                    return _cpu_fb
                return _build_native_fallback(profile, cfg)

            def audit_factory():
                # audit reference: byte-identical to the failover engine,
                # but k-row samples ride the fused single-dispatch ladder
                # (one XLA call per audit, not one per rescue tier)
                eng = fallback_factory()
                if getattr(eng, "__name__", "") == "cpu-ladder":
                    from ..kernels.tiers import audit_reference

                    return audit_reference(ladder)
                return eng

        self.sup = DeviceSupervisor(
            dispatch, fetch, fetch_many, fallback_factory=fallback_factory,
            log=self.log, cfg=SupervisorConfig.from_env(),
            faults=FaultPlan.from_env(), rtt_s=rtt_s, describe=desc,
            fingerprint_prefix=prefix, inline=inline, clamp_solve=clamp,
            governor_cfg=g.governor, tracer=self.tracer,
            mesh=self.mesh_solver,
            # sampled shadow verification (ISSUE 20): the group's own
            # supervisor audits merged cross-job batches — the per-job
            # pipeline never sees the device, so this is the only seam.
            # Native groups skip it: the reference IS the primary
            audit_ref_factory=(None if g.backend == "native"
                               else audit_factory),
            audit_rate=g.audit_rate)

    # ------------------------------------------------------------------
    # job-side API
    # ------------------------------------------------------------------

    def job_solver(self, job: str) -> "JobSolver":
        return JobSolver(self, job)

    @property
    def hp_ols(self):
        """The group's shared OffsetLikely tables for the hp-rescue pass
        (built once, read-only across job threads) — rebuilding them per
        job was most of the warm path's residual cold start. Double-checked
        read: once built, a new job must NOT queue behind a cohabitant's
        in-flight solve (which holds the group lock) just to read the
        reference."""
        ols = self._hp_ols
        if ols is not None:
            return ols
        with self._lock:
            if self._hp_ols is None:
                from ..oracle.consensus import make_offset_likely

                self._hp_ols = make_offset_likely(self._profile,
                                                  self.cfg.consensus)
            return self._hp_ols

    def set_shed(self, levels: int) -> None:
        """Load-shed rung: merged batches dispatch at ``batch >> levels``
        (floored) until pressure clears — the capacity governor's batch
        ladder promoted to the service's overload policy."""
        with self._lock:
            self.gcfg.shed_levels = max(0, int(levels))

    def _width(self) -> int:
        w = self.gcfg.batch >> self.gcfg.shed_levels
        return max(self.gcfg.min_width, w)

    def _pool_key(self, batch: WindowBatch) -> tuple:
        return (batch.shape.depth, batch.shape.seg_len, batch.shape.wlen,
                getattr(batch, "stream", "full"))

    def dispatch(self, job: str, batch: WindowBatch) -> JobHandle:
        """Pool one job batch's rows; flush merged batches when a bucket
        holds a dispatch width. Returns the job-side handle."""
        h = JobHandle(job, batch.size)
        if batch.size == 0:
            h.event.set()
            return h
        with self._lock:
            pk = self._pool_key(batch)
            pool = self._pools.get(pk)
            if pool is None:
                pool = self._pools[pk] = _Pool(batch.shape, pk[3])
            pages = None
            if self.gcfg.paged:
                from ..kernels import paging

                pages = paging.window_pages(batch.lens, self.gcfg.page_len)
            pool.append(_Block(h, batch, pages))
            self.counters["rows"] += batch.size
            while pool.rows >= self._width():
                self._flush(pk, reason="full")
            if len(self._inflight) >= self.gcfg.max_inflight:
                self._drain(self.gcfg.max_inflight // 2)
        return h

    def fetch(self, handle: JobHandle) -> dict:
        """Block until ``handle``'s rows are solved; the calling job thread
        drives the shared flush/drain machinery itself (no dedicated device
        thread), so a lone job proceeds at full speed and cohabiting jobs
        complete each other's handles as a side effect of their own."""
        if not handle.event.is_set():
            with self._lock:
                while not handle.event.is_set():
                    pk = self._pool_of(handle)
                    if pk is not None:
                        self.counters["demand_flushes"] += 1
                        self._flush(pk, reason="demand")
                    elif self._inflight:
                        self._drain(0)
                    else:
                        raise RuntimeError(
                            f"handle for job {handle.job!r} has rows neither "
                            "pooled nor in flight (batcher bookkeeping bug)")
        if handle.aborted:
            raise JobAborted(f"job {handle.job!r} aborted")
        return handle.result()

    def fetch_many(self, handles: list) -> list[dict]:
        return [self.fetch(h) for h in handles]

    def flush_stale(self, max_age_s: float) -> None:
        """Service-ticker hook: flush pools whose oldest rows have waited
        longer than ``max_age_s`` — bounds the extra latency one job's rows
        can pay waiting for cohabitants (the cross-job form of the
        pipeline's bucket_flush_reads rule). NON-BLOCKING on the group
        lock: the lock is held across real device solves (minutes during a
        jit compile), and the single ticker thread must not stall behind
        one group's solve — a busy group's pools are being drained by the
        very solve that holds the lock."""
        if not self._lock.acquire(blocking=False):
            return
        try:
            now = time.time()
            for pk, pool in list(self._pools.items()):
                if (pool.rows and pool.oldest_ts is not None
                        and now - pool.oldest_ts >= max_age_s):
                    self.counters["lag_flushes"] += 1
                    self._flush(pk, reason="lag")
            if self._inflight:
                self._drain(0)
        finally:
            self._lock.release()

    def release_job(self, job: str) -> None:
        """Drop a released (aborted/finished) job's rows from every pool so
        they never waste a device slot; handles left incomplete abort. Rows
        already in a merged in-flight batch stay — their results scatter
        into dead handles harmlessly; cohabiting rows are untouched (the
        abort-must-not-poison contract)."""
        with self._lock:
            for pool in self._pools.values():
                kept: deque[_Block] = deque()
                for blk in pool.blocks:
                    if blk.handle.job == job:
                        pool.rows -= blk.batch.size
                        if blk.pages is not None:
                            pool.pages -= int(blk.pages.sum())
                        blk.handle.abort()
                    else:
                        kept.append(blk)
                pool.blocks = kept
                if not pool.rows:
                    pool.oldest_ts = None

    def drain_all(self) -> None:
        """Flush every pool and drain every in-flight batch (shutdown)."""
        with self._lock:
            for pk, pool in list(self._pools.items()):
                while pool.rows:
                    self._flush(pk, reason="final")
            if self._inflight:
                self._drain(0)

    def busy(self) -> bool:
        """True while the solve lock is held (a dispatch/flush/solve is in
        flight). Pure try-lock — the lock-free healthz contract: a liveness
        probe must never queue behind a minutes-long jit compile."""
        locked = self._lock.acquire(blocking=False)
        if locked:
            self._lock.release()
        return not locked

    def saturation(self) -> dict:
        """Starvation/overlap gauges over this group's lifetime (ISSUE 14):
        obs.saturation_gauges plus the raw walls (``busy_s``/``blocked_s``)
        the service aggregates into its demand-weighted verdict. Lock-free
        like :meth:`stats` — momentarily-stale floats beat stalling behind
        a solve."""
        from ..utils.obs import saturation_gauges

        now = time.time()
        el = max(now - self.created, 1e-9)
        busy = self.sat["busy_s"]
        if self.sat["t0"] is not None:
            busy += now - self.sat["t0"]
        blocked = self.sat["fetch_blocked_s"]
        if self._sync_engine:
            blocked += self.sat["dispatch_s"]
            busy += self.sat["fetch_blocked_s"]
        return {**saturation_gauges(el, blocked, busy),
                "dispatch_s": round(self.sat["dispatch_s"], 4),
                "blocked_s": round(blocked, 4),
                "busy_s": round(busy, 4), "lifetime_s": round(el, 3)}

    def stats(self) -> dict:
        """Group stats. NON-BLOCKING on the solve lock (same reasoning as
        :meth:`flush_stale`): during an in-flight solve the counters are
        read without the lock — dict reads are atomic under the GIL, and a
        momentarily-stale gauge beats stalling the ticker (pressure shed,
        eviction, other groups' flushes) behind a minutes-long compile."""
        locked = self._lock.acquire(blocking=False)
        try:
            pooled = sum(p.rows for p in self._pools.values())
            return {"key": self.key, "name": self.name, **self.counters,
                    "pooled_rows": pooled, "inflight": len(self._inflight),
                    "width": self._width(), "refs": self.refs,
                    "busy": not locked,
                    "saturation": self.saturation(),
                    "degraded": self.sup.failed_over,
                    "governor": self.sup.governor.counters.copy(),
                    **({"aot": self.aot.stats()} if self.aot else {})}
        finally:
            if locked:
                self._lock.release()

    def close(self) -> None:
        self.tracer.unwind()
        if self.log is not None:
            self.log.close()

    # ------------------------------------------------------------------
    # merged-batch assembly
    # ------------------------------------------------------------------

    def _pool_of(self, handle: JobHandle) -> tuple | None:
        for pk, pool in self._pools.items():
            for blk in pool.blocks:
                if blk.handle is handle:
                    return pk
        return None

    def _family_for(self, pool: _Pool, nsegs: np.ndarray,
                    pages: np.ndarray):
        """Lazily-derived shape families for this bucket's (D, L): the
        corpus sample is the pooled rows themselves, so the family grid
        reflects the live cross-job mix. The mandatory full-coverage family
        guarantees any later window routes somewhere."""
        from ..kernels import paging

        fk = (pool.shape.depth, pool.shape.seg_len)
        fams = self._families.get(fk)
        if fams is None:
            D, L = fk
            PL = self.gcfg.page_len
            fams = paging.derive_families(
                np.asarray(nsegs, np.int64), np.asarray(pages, np.int64),
                max_depth=D, max_pages=-(-D * L // PL),
                budget=self.gcfg.paged_families, page_len=PL)
            # a width-wide pool must fit at least one worst-case window
            fams = [f if self._width() * f.budget >= f.pages else
                    paging.ShapeFamily(depth=f.depth, pages=f.pages,
                                       page_len=f.page_len,
                                       pool_pages=-(-f.pages // self._width()))
                    for f in fams]
            self._families[fk] = fams
            for fi, f in enumerate(fams):
                self.log.log("paging.family", family=f.describe(), bucket=fi,
                             depth=int(f.depth), pages=int(f.pages),
                             page_len=int(f.page_len), pool_pages=int(f.budget))
        # smallest family covering every row of this merged batch
        mxd = int(np.max(nsegs)) if len(nsegs) else 0
        mxp = int(np.max(pages)) if len(pages) else 0
        for f in fams:
            if f.depth >= mxd and f.pages >= mxp:
                return f
        return fams[-1]

    def _flush(self, pk: tuple, reason: str) -> None:
        pool = self._pools.get(pk)
        if pool is None or not pool.rows:
            return
        width = self._width()
        take = min(width, pool.rows)
        # pop a `take`-row prefix, splitting the last block if needed
        taken: list[tuple[JobHandle, WindowBatch, np.ndarray | None]] = []
        need = take
        while need > 0:
            blk = pool.blocks[0]
            if blk.batch.size <= need:
                pool.blocks.popleft()
                taken.append((blk.handle, blk.batch, blk.pages))
                need -= blk.batch.size
            else:
                head = slice_batch(blk.batch, 0, need)
                tail = slice_batch(blk.batch, need, blk.batch.size)
                taken.append((blk.handle, head,
                              None if blk.pages is None else blk.pages[:need]))
                blk.batch = tail
                if blk.pages is not None:
                    blk.pages = blk.pages[need:]
                need = 0
        pool.rows -= take
        pool.oldest_ts = time.time() if pool.rows else None
        if self.gcfg.paged:
            pool.pages = sum(int(b.pages.sum()) for b in pool.blocks
                             if b.pages is not None)

        jobs: list[str] = []
        for h, _, _ in taken:
            if h.job not in jobs:
                jobs.append(h.job)

        def _cat(get):
            arrs = [get(b) for _, b, _ in taken]
            return np.concatenate(arrs) if len(arrs) > 1 else arrs[0]

        merged = WindowBatch(
            seqs=_cat(lambda b: b.seqs), lens=_cat(lambda b: b.lens),
            nsegs=_cat(lambda b: b.nsegs), shape=pool.shape,
            read_ids=_cat(lambda b: b.read_ids),
            wstarts=_cat(lambda b: b.wstarts), stream=pool.stream,
            job="+".join(jobs))
        if self.gcfg.paged:
            from ..kernels import paging

            pages = np.concatenate([p for _, _, p in taken]) \
                if len(taken) > 1 else taken[0][2]
            fam = self._family_for(pool, merged.nsegs, pages)
            # the dispatch width must hold this family's worst-case window
            # even after a shed rung shrank _width() below the derivation-
            # time width the family fixup assumed — otherwise the forced
            # fit>=1 row below could bust pack_paged's pool assertion
            width = max(width, -(-fam.pages // fam.budget))
            # respect the family's pool budget: requeue rows past it (front
            # of the pool, original order — the router-side guarantee
            # behind pack_paged's overflow assertion)
            budget = fam.pool_rows(width) - 1
            fit = int(np.searchsorted(np.cumsum(pages), budget,
                                      side="right"))
            fit = max(min(fit, take), 1)
            if fit < take:
                self._requeue(pool, taken, fit)
                taken, merged, pages = self._retake(taken, merged, pages, fit)
                jobs = [j for j in jobs
                        if any(h.job == j for h, _, _ in taken)]
            merged = paging.pack_paged(merged, fam, target_rows=width)
        elif self.gcfg.backend != "native":
            merged = pad_batch(merged, width)
        rows = sum(b.size for _, b, _ in taken)
        self.counters["batches"] += 1
        self.counters["dispatches"] += 1
        if len(jobs) > 1:
            self.counters["mixed_batches"] += 1
        if self.gcfg.shed_levels:
            self.counters["shed_flushes"] += 1
        self.log.log("serve.batch", windows=rows, jobs=len(jobs),
                     stream=pool.stream, width=int(merged.size),
                     reason=reason, job="+".join(jobs))
        t_d = time.time()
        if not self._sync_engine and self.sat["t0"] is None:
            self.sat["t0"] = t_d
        if (self.mesh_solver is not None
                and hasattr(self.mesh_solver, "stage")
                and os.environ.get("DACCORD_MESH_PIPELINE", "1") != "0"):
            # merged cross-job batches ride the staged dispatch path
            # (ISSUE 19): pre-built per-device shard buffers consumed by the
            # launch; with earlier flushes still in flight the staging books
            # as overlapped, and every supervisor replay path (failover,
            # shrink, capacity bisect) operates on the retained HOST batch
            # the StagedBatch carries
            merged = self.mesh_solver.stage(merged)
        dh = self.sup.dispatch(merged)
        dt = time.time() - t_d
        self.sat["dispatch_s"] += dt
        if self._sync_engine:
            self.sat["busy_s"] += dt
        rowmap = [(h, b.size) for h, b, _ in taken]
        self._inflight.append((dh, rowmap, rows))

    def _requeue(self, pool: _Pool, taken, fit: int) -> None:
        """Push rows past ``fit`` back to the FRONT of the pool (paged
        budget cut), preserving block order and handle row order."""
        off = 0
        tail_blocks: list[_Block] = []
        for h, b, p in taken:
            if off + b.size <= fit:
                off += b.size
                continue
            lo = max(fit - off, 0)
            tb = slice_batch(b, lo, b.size)
            tail_blocks.append(_Block(h, tb, None if p is None else p[lo:]))
            off += b.size
        for tb in reversed(tail_blocks):
            pool.blocks.appendleft(tb)
            pool.rows += tb.batch.size
            if tb.pages is not None:
                pool.pages += int(tb.pages.sum())
        if pool.rows and pool.oldest_ts is None:
            pool.oldest_ts = time.time()

    @staticmethod
    def _retake(taken, merged, pages, fit):
        """Trim the taken list / merged batch / page vector to ``fit`` rows."""
        new_taken = []
        off = 0
        for h, b, p in taken:
            if off >= fit:
                break
            n = min(b.size, fit - off)
            new_taken.append((h, slice_batch(b, 0, n),
                              None if p is None else p[:n]))
            off += n
        return new_taken, slice_batch(merged, 0, fit), pages[:fit]

    # ------------------------------------------------------------------
    # drain + scatter
    # ------------------------------------------------------------------

    def _drain(self, to_depth: int) -> None:
        n_pop = len(self._inflight) - to_depth
        if n_pop <= 0:
            return
        entries = [self._inflight.popleft() for _ in range(n_pop)]
        t_f = time.time()
        try:
            outs = self.sup.fetch_many([e[0] for e in entries])
            now = time.time()
            self.sat["fetch_blocked_s"] += now - t_f
            if not self._inflight and self.sat["t0"] is not None:
                self.sat["busy_s"] += now - self.sat["t0"]
                self.sat["t0"] = None
        except BaseException:
            # the popped entries' handles would otherwise be stranded
            # (neither pooled nor in flight): abort them so cohabiting
            # jobs' fetch() raises JobAborted with the truth — the solve
            # path died — instead of a misleading bookkeeping error. The
            # original exception still propagates to whoever drove this
            # drain (their job fails with the real reason).
            for _, rowmap, _ in entries:
                for handle, _n in rowmap:
                    handle.abort()
            raise
        for (dh, rowmap, rows), out in zip(entries, outs):
            lo = 0
            for handle, n in rowmap:
                part = self._slice_out(out, lo, lo + n, rows)
                lo += n
                handle.add_part(n, part)

    @staticmethod
    def _slice_out(out: dict, lo: int, hi: int, live: int) -> dict:
        """Rows [lo, hi) of a merged result, per field. Row-shaped arrays
        slice; numeric scalars (esc_overflow) zero for EVERY part — a
        batch-level scalar cannot be attributed to one cohabitant's rows,
        and crediting it to the first job would book another job's overflow
        in the wrong telemetry stream. (Structurally moot today: the group
        dispatches at default esc_cap = full width, so esc_overflow is
        always 0, and the clamp rung zeroes it after host completion.)"""
        part: dict = {}
        for k, v in out.items():
            if isinstance(v, np.ndarray) and v.ndim >= 1 and len(v) >= live:
                part[k] = v[lo:hi]
            elif isinstance(v, (int, float, np.integer, np.floating)):
                part[k] = type(v)(0)
            elif isinstance(v, np.ndarray) and v.ndim == 0:
                part[k] = np.zeros_like(v)
            else:
                part[k] = v
        return part


class JobSolver:
    """Per-job facade over a :class:`SolveGroup` — the async-solver duck
    type ``correct_shard`` injects (``dispatch``/``fetch``/``fetch_many``).
    ``accepts_partial`` tells the pipeline to skip its own padding (the
    group pads MERGED batches); ``routes_streams`` opts the pipeline's
    split-ladder machinery in (the group routes the stream tags)."""

    accepts_partial = True
    routes_streams = True

    def __init__(self, group: SolveGroup, job: str):
        self.group = group
        self.job = job

    @property
    def ladder(self):
        """The group's warm TierLadder (None for native groups) — the
        pipeline reuses it instead of rebuilding OffsetLikely tables per
        job, which is most of the cold start the warm cache amortizes."""
        return self.group.ladder

    @property
    def hp_ols(self):
        """The group's shared hp-rescue OffsetLikely tables (read-only)."""
        return self.group.hp_ols

    @property
    def mesh(self) -> int:
        """The group's mesh width (0 = single-device) — the pipeline stamps
        it into ledger rows (ISSUE 13 satellite: the ROADMAP-4 router
        training set segments by mesh configuration), so a job solved
        through a mesh-backed group records which topology solved it."""
        return int(getattr(self.group.gcfg, "mesh", 0) or 0)

    def describe(self) -> str:
        return f"serve-batcher:{self.group.name}"

    def dispatch(self, batch: WindowBatch) -> JobHandle:
        return self.group.dispatch(self.job, batch)

    def fetch(self, handle: JobHandle) -> dict:
        return self.group.fetch(handle)

    def fetch_many(self, handles: list) -> list[dict]:
        return self.group.fetch_many(handles)
