"""Job lifecycle: spec parsing, CLI-default config parity, the runner.

Parity doctrine: a serve job's bytes must equal a solo ``daccord`` run with
the same inputs and flags, so :func:`build_job_config` constructs the
pipeline config EXACTLY the way ``tools/cli.py daccord_main`` does (tier
ladder from ``k``, DBG params from ``candidates``/``max_err``, hp defaults
keyed by backend) — any drift here is a byte-parity bug, and
tests/test_serve.py compares against the real solo path to catch it.

Jobs arrive as JSON: server-local ``db``/``las`` paths, or uploaded files
(``files``: name → base64) spooled into the job's work directory. The
PR-2 ingest layer validates at ADMISSION (``scan_with_db``): a strict-policy
job with integrity violations is rejected with the structured report before
it costs a queue slot, and the scan report is handed to ``correct_shard`` so
the validation is never paid twice.

The runner streams fragments to ``out.fasta.part`` as they emit (the HTTP
layer live-streams that file), then commits durably: fsync → rename →
manifest via ``aio.durable_write`` — the PR-2 crash-durability doctrine
applied per job.
"""

from __future__ import annotations

import base64
import json
import os
import threading
import time
from dataclasses import dataclass, field

# job states
QUEUED = "queued"
RUNNING = "running"
DONE = "done"
FAILED = "failed"
ABORTED = "aborted"


@dataclass
class JobSpec:
    db: str
    las: str
    tenant: str = "default"
    # solve-semantics knobs (CLI flag parity; defaults == daccord defaults)
    w: int = 40
    adv: int = 10
    k: int = 8
    depth: int = 32
    seg_len: int = 64
    max_kmers: int = 64
    candidates: int = 3
    max_err: float = 0.3
    mode: str = "split"
    overflow_rescue: bool = False
    hp_rescue: bool | None = None    # None = backend-keyed default (CLI rule)
    hp_vote: str = "median"
    hp_accept: str = "rescore"
    end_trim: bool = True
    qv_track: str | None = "inqual"
    ingest_policy: str = "strict"
    profile_sample_piles: int = 4
    nbytes: int = 0                  # admission accounting (db + las bytes)
    uploaded: bool = False

    @classmethod
    def from_json(cls, body: dict, jobdir: str) -> "JobSpec":
        """Parse a submission body; uploaded files spool into ``jobdir``.
        Raises ValueError on a malformed spec (HTTP 400)."""
        body = dict(body)
        files = body.pop("files", None)
        uploaded = False
        if files:
            from ..utils import aio

            os.makedirs(jobdir, exist_ok=True)
            for name, b64 in files.items():
                name = os.path.basename(str(name))
                if not name:
                    raise ValueError("files: empty file name")
                # spool through the aio fault hook (``@spool`` domain): an
                # ENOSPC here raises out of admission, which releases the
                # tenant's quota charge and rmtree's the spool dir — a
                # refused upload leaves no disk residue (see _submit_new)
                with aio.open_output(os.path.join(jobdir, name), "wb",
                                     domain="spool") as fh:
                    fh.write(base64.b64decode(b64))
            uploaded = True
            for key in ("db", "las"):
                if key not in body:
                    raise ValueError(f"upload job needs {key!r} naming the "
                                     "uploaded entry")
                body[key] = os.path.join(jobdir,
                                         os.path.basename(str(body[key])))
        for key in ("db", "las"):
            if key not in body:
                raise ValueError(f"job spec missing {key!r}")
        known = set(cls.__dataclass_fields__) - {"nbytes", "uploaded"}
        unknown = set(body) - known
        if unknown:
            raise ValueError(f"unknown job fields: {sorted(unknown)}")
        # type-check the simple fields at the boundary: dataclasses don't,
        # and a wrong-typed knob accepted here would surface later as an
        # opaque FAILED job instead of a 400 (bool is an int subclass —
        # reject it for numeric fields explicitly)
        _types = {"db": str, "las": str, "tenant": str, "w": int, "adv": int,
                  "k": int, "depth": int, "seg_len": int, "max_kmers": int,
                  "candidates": int, "max_err": (int, float), "mode": str,
                  "overflow_rescue": bool, "hp_rescue": (bool, type(None)),
                  "hp_vote": str, "hp_accept": str, "end_trim": bool,
                  "qv_track": (str, type(None)), "ingest_policy": str,
                  "profile_sample_piles": int}
        for name, want in _types.items():
            if name not in body:
                continue
            v = body[name]
            ok = isinstance(v, want)
            if ok and want in (int, (int, float)) and isinstance(v, bool):
                ok = False
            if not ok:
                raise ValueError(f"job field {name!r}: expected "
                                 f"{getattr(want, '__name__', want)}, got "
                                 f"{type(v).__name__}")
        spec = cls(**body)
        spec.uploaded = uploaded
        if spec.ingest_policy not in ("strict", "quarantine", "off"):
            raise ValueError(f"bad ingest_policy {spec.ingest_policy!r}")
        if not (4 <= spec.k <= 11):
            raise ValueError(f"k {spec.k}: supported range is 4..11")
        for p in (spec.db, spec.las):
            if not (os.path.exists(p) or os.path.exists(p + ".db")):
                raise ValueError(f"input not found: {p}")
        spec.nbytes = sum(os.path.getsize(p) for p in (spec.db, spec.las)
                          if os.path.exists(p))
        return spec


def build_job_config(spec: JobSpec, backend: str, backend_explicit: bool,
                     batch: int, ladder_mode: str, jobdir: str,
                     job_id: str):
    """The job's PipelineConfig, CLI-parity by construction (see module
    docstring). The injected cross-job solver supersedes per-job
    supervision — the SolveGroup's shared supervisor owns faults, retries,
    failover, and the capacity ladder for every cohabiting job."""
    from ..oracle.consensus import ConsensusConfig
    from ..oracle.dbg import DBGParams
    from ..runtime.pipeline import PipelineConfig

    k = spec.k
    tiers = ((k, 2, 2), (k + 2, 2, 2), (k + 4, 2, 2), (k, 1, 1))
    hp = spec.hp_rescue
    if hp is None:
        # the CLI rule verbatim: host engines default hp ON only when the
        # backend was EXPLICIT (an auto-resolved engine must not flip
        # defaults with tunnel health)
        hp = backend in ("native", "cpu") and backend_explicit
    ccfg = ConsensusConfig(w=spec.w, adv=spec.adv, mode=spec.mode,
                           tiers=tiers,
                           dbg=DBGParams(n_candidates=spec.candidates,
                                         max_err=spec.max_err),
                           hp_rescue=hp, hp_vote=spec.hp_vote,
                           hp_accept=spec.hp_accept)
    return PipelineConfig(
        consensus=ccfg, batch_size=batch, depth=spec.depth,
        seg_len=spec.seg_len, max_kmers=spec.max_kmers,
        overflow_rescue=spec.overflow_rescue,
        end_trim=spec.end_trim, qv_track=spec.qv_track or None,
        profile_sample_piles=spec.profile_sample_piles,
        ingest_policy=spec.ingest_policy,
        quarantine_path=os.path.join(jobdir, "quarantine.jsonl"),
        events_path=os.path.join(jobdir, "events.jsonl"),
        ledger_path=os.path.join(jobdir, "ledger.jsonl"),
        job_tag=job_id,
        # the group's supervisor is the device authority for every
        # cohabiting job; a per-job supervisor would double-consume fault
        # injections and double-wrap the dispatch seam
        supervise=False,
        ladder_mode=ladder_mode)


def solve_fingerprint(profile, cfg, backend: str, mesh: int = 0) -> str:
    """Key under which jobs may share device batches: everything that can
    change a window's BYTES (profile floats, consensus/ladder semantics,
    engine family) — and nothing that cannot (batch width, shapes, telemetry
    paths, job identity). Full-precision float reprs: two jobs share a group
    only when their solve semantics are bit-identical.

    ``mesh`` (the group's device-mesh width) joins the key even though it
    cannot change bytes: a mesh group owns mesh-width-specific jitted
    programs and per-:m<N> capacity ratchets, so a mesh and a single-device
    group must never share warm state (0 = single device, and the key is
    unchanged from pre-mesh builds)."""
    import hashlib

    c = cfg.consensus
    payload = {
        "backend": "native" if backend == "native" else "jax",
        "profile": [repr(float(profile.p_ins)), repr(float(profile.p_del)),
                    repr(float(profile.p_sub)), repr(float(profile.hp_slope)),
                    repr(float(profile.hp_base)), int(profile.hp_cap)],
        "w": c.w, "adv": c.adv, "tiers": list(map(list, c.tiers)),
        "mode": c.mode, "min_fragment": c.min_fragment,
        "dbg": [c.dbg.n_candidates, repr(float(c.dbg.max_err)),
                c.dbg.min_depth],
        "hp": [c.hp_rescue, repr(float(c.hp_err)), c.hp_min_run,
               repr(float(c.hp_margin)), c.hp_vote, c.hp_accept,
               repr(float(c.hp_lambda_c))],
        "max_kmers": cfg.max_kmers, "rescue_max_kmers": cfg.rescue_max_kmers,
        "overflow_rescue": cfg.overflow_rescue,
    }
    if mesh and mesh > 1:
        payload["mesh"] = int(mesh)
    return hashlib.sha256(
        json.dumps(payload, sort_keys=True).encode()).hexdigest()[:24]


@dataclass
class Job:
    id: str
    tenant: str
    spec: JobSpec
    dir: str
    state: str = QUEUED
    submitted_ts: float = field(default_factory=time.time)
    started_ts: float | None = None
    first_emit_ts: float | None = None
    done_ts: float | None = None
    error: str | None = None
    reads: int = 0
    windows: int = 0
    fragments: int = 0
    bases_out: int = 0
    group: str | None = None
    # crash-durable tier (ISSUE 15): a watch job is one a live PEER holds
    # (its lease is fresh) — registered so clients polling this process see
    # it, never queued or quota-charged here; the ticker flips it DONE when
    # the peer's manifest lands, or re-admits it if the peer's lease goes
    # stale
    watch: bool = False
    # True while a local run_job thread is executing this job: the takeover
    # scan must never re-queue a job whose demoted straggler is still
    # unwinding (it will exit at its next abort_event check; the reclaim
    # waits for that — two threads on one job would race the commit)
    running_local: bool = False
    # the CURRENT attempt's private part file (set by run_job): every
    # attempt writes its own file, so a demoted straggler's O_APPEND
    # writes can never splice into a taker's (or a reclaimer's) stream —
    # the resume path COPIES the checkpointed prefix instead of sharing
    # the inode. None = the pre-run default name (streaming falls back).
    part_path: str | None = None
    abort_event: threading.Event = field(default_factory=threading.Event)

    @property
    def fasta_part(self) -> str:
        return self.part_path or os.path.join(self.dir, "out.fasta.part")

    @property
    def fasta(self) -> str:
        return os.path.join(self.dir, "out.fasta")

    @property
    def progress_path(self) -> str:
        """Per-job pipeline checkpoint (ISSUE 15): emitted-read count + the
        durable ``out.fasta.part`` byte size at that point — the resume
        point a journal replay (or peer takeover) restarts the run from."""
        return os.path.join(self.dir, "progress.json")

    def status(self) -> dict:
        now = time.time()
        lat = {
            "queue_s": round((self.started_ts or now) - self.submitted_ts, 4),
            "first_result_s": (round(self.first_emit_ts - self.submitted_ts, 4)
                               if self.first_emit_ts else None),
            "total_s": (round(self.done_ts - self.submitted_ts, 4)
                        if self.done_ts else None),
        }
        return {"job": self.id, "tenant": self.tenant, "state": self.state,
                "reads": self.reads, "windows": self.windows,
                "fragments": self.fragments, "bases_out": self.bases_out,
                "group": self.group, "error": self.error, "latency": lat}


def run_job(job: Job, service) -> None:
    """Execute one admitted job end to end (worker thread). ``service`` is
    the owning :class:`~.service.ConsensusService` (warm state, events,
    metrics). State transitions and the durable commit happen here; the
    byte-producing pipeline is the stock ``correct_shard``."""
    from ..formats.dazzdb import read_db
    from ..formats.fasta import write_fasta
    from ..formats.ingest import scan_with_db
    from ..formats.las import LasFile
    from ..runtime.pipeline import correct_shard, estimate_profile_for_shard
    from ..utils.aio import durable_write
    from ..utils.bases import ints_to_seq

    scfg = service.cfg
    if os.path.exists(os.path.join(job.dir, "manifest.json")):
        # a peer (or a prior incarnation) already committed this job
        # durably — the exactly-once contract says never run it again
        # (reachable when a takeover claim raced the committer's last
        # milliseconds: the claim won, the manifest still landed)
        job.state = DONE
        job.done_ts = time.time()
        service.journal_mark("committed", job.id, by="manifest")
        service.log_event("serve.job", job=job.id, state=DONE,
                          tenant=job.tenant)
        service.admission.release(job.tenant, job.spec.nbytes)
        service.release_job_lease(job.id)
        return
    job.state = RUNNING
    job.started_ts = time.time()
    job.running_local = True
    service.journal_mark("running", job.id)
    service.log_event("serve.job", job=job.id, state=RUNNING,
                      tenant=job.tenant)
    if service.faults is not None and service.faults.serve_hang_check():
        # injected wedge (ISSUE 15): the stand-in for a group thread stuck
        # in a solve — ignores aborts and shutdown, exactly like the real
        # thing. The bounded drain deadline (journal INTERRUPTED + nonzero
        # exit) and the peer lease takeover are what recover from this.
        service.log_event("serve.job", job=job.id, state="hang",
                          tenant=job.tenant)
        while True:
            time.sleep(0.25)
    key = None
    group = None
    gen = None
    try:
        cfg = build_job_config(job.spec, scfg.backend, scfg.backend_explicit,
                               scfg.batch, scfg.group_ladder_mode(), job.dir,
                               job.id)
        db = read_db(job.spec.db, strict=cfg.ingest_policy == "strict")
        las = LasFile(job.spec.las)
        report = None
        if cfg.ingest_policy != "off":
            # PR-2 ingest gate at the job boundary; strict violations were
            # already rejected at admission — this is the (cheap) re-scan
            # guard for TOCTOU on server-local paths, reused by the pipeline
            report = scan_with_db(db, las, None, None)
            if report.issues and cfg.ingest_policy == "strict":
                raise report.error()
        kw = (dict(pile_ranges=report.pile_ranges)
              if report is not None and report.issues else {})
        profile = estimate_profile_for_shard(db, las, cfg, **kw)
        key = solve_fingerprint(profile, cfg, scfg.backend,
                                mesh=scfg.group_mesh())
        group = service.warm.acquire(
            key, lambda: service.build_group(key, profile, cfg))
        service.note_tenant_key(job.tenant, key)
        job.group = group.name
        solver = group.job_solver(job.id)
        t_first = None
        # per-job checkpoint resume (ISSUE 15): a replayed (or taken-over)
        # job resumes from its progress manifest. Every attempt writes its
        # OWN part file (pid+tid-named) and the resume COPIES the
        # checkpointed prefix into it — sharing the inode would let a
        # demoted straggler's O_APPEND writes splice into this attempt's
        # stream. The first `skip` reads re-solve without re-writing
        # (emission order is deterministic, so the committed bytes are
        # identical to an uninterrupted run; torn progress JSON reads as
        # absent, like every manifest in the repo).
        skip = part_pos = 0
        ck_every = int(getattr(scfg, "checkpoint_reads", 0) or 0)
        prior_part = None
        try:
            with open(job.progress_path) as ph:
                prog = json.load(ph)
            emitted = int(prog.get("emitted", 0))
            pb = int(prog.get("part_bytes", 0))
            pp = os.path.join(job.dir, os.path.basename(
                str(prog.get("part", "out.fasta.part"))))
            if emitted > 0 and os.path.exists(pp) \
                    and os.path.getsize(pp) >= pb:
                skip, part_pos, prior_part = emitted, pb, pp
        except (OSError, json.JSONDecodeError, ValueError, TypeError):
            pass
        my_part = os.path.join(
            job.dir,
            f"out.fasta.part.{os.getpid()}.{threading.get_ident()}")
        if prior_part is not None:
            with open(prior_part, "rb") as src, open(my_part, "wb") as dst:
                dst.write(src.read(part_pos))
        job.part_path = my_part
        with open(my_part, "at" if part_pos else "wt") as fh:
            fh.seek(0, os.SEEK_END)
            gen = correct_shard(db, las, cfg, profile=profile, solver=solver,
                                ingest_report=report)
            n_seen = 0
            for rid, frags, st in gen:
                if job.abort_event.is_set():
                    # checked BEFORE writing: a demoted straggler must not
                    # emit one more read after losing ownership
                    raise JobAbortRequested()
                n_seen += 1
                if n_seen > skip:
                    if t_first is None and frags:
                        t_first = time.time()
                        job.first_emit_ts = t_first
                    write_fasta(fh, [(f"read{rid}/{fi}", ints_to_seq(f))
                                     for fi, f in enumerate(frags)])
                    fh.flush()
                job.reads = st.n_reads
                job.windows = st.n_windows
                job.fragments = st.n_fragments
                job.bases_out = st.bases_out
                if ck_every and n_seen > skip and n_seen % ck_every == 0:
                    # checkpoint ordering contract (PR 2): the part bytes
                    # fsync FIRST, then the manifest that points at them
                    # commits durably — a checkpoint never points past the
                    # durable bytes
                    os.fsync(fh.fileno())
                    part_sz = fh.tell()
                    try:
                        durable_write(
                            job.progress_path,
                            lambda mh, n=n_seen, b=part_sz: json.dump(
                                {"emitted": n, "part_bytes": b,
                                 "part": os.path.basename(my_part)}, mh),
                            mode="wt", domain="manifest")
                    except OSError as ce:
                        # a refused CHECKPOINT must not fail a healthy run:
                        # it only widens the resume window (the prior
                        # checkpoint — or read zero — still bounds the
                        # recompute). The run itself keeps going; the
                        # commit path is where a full disk becomes fatal.
                        service.log_event(
                            "io.fault", domain="manifest", op="checkpoint",
                            error=f"{type(ce).__name__}: {ce}"[:200])
                    else:
                        service.journal_mark("progress", job.id,
                                             emitted=n_seen, bytes=part_sz,
                                             part=os.path.basename(my_part))
            fh.flush()
            os.fsync(fh.fileno())
            if not service.still_owns(job.id):
                # our lease was taken over while we solved (heartbeat
                # stalled past the TTL under load): the taker owns the
                # commit — stand down and watch its manifest instead of
                # double-committing
                job.watch = True
                job.abort_event.set()
                raise JobAbortRequested()
            # the WAL commit point: after this record the bytes are durable
            # and replay finishes the rename/manifest WITHOUT re-running —
            # the mid-commit crash window (fsync'd FASTA, un-renamed part)
            # recovers to the identical committed output
            # the committing record carries the content digest of the
            # fsync'd bytes (ISSUE 20): a replay/takeover finalize verifies
            # it before the publishing rename, so a part file silently
            # corrupted between crash and recovery re-solves instead of
            # publishing wrong bytes
            from ..utils.obs import sha256_file

            service.journal_mark("committing", job.id, bytes=fh.tell(),
                                 part=os.path.basename(my_part),
                                 sha=sha256_file(my_part, limit=fh.tell()))
        os.replace(my_part, job.fasta)
        job.done_ts = time.time()
        job.state = DONE
        durable_write(os.path.join(job.dir, "manifest.json"),
                      lambda mh: json.dump(
                          {**job.status(),
                           "fasta": job.fasta,
                           "fasta_bytes": os.path.getsize(job.fasta),
                           "fasta_sha256": sha256_file(job.fasta)}, mh),
                      mode="wt", domain="manifest")
        import glob as _glob

        for leftover in (job.progress_path,
                         *_glob.glob(os.path.join(job.dir,
                                                  "out.fasta.part*"))):
            # prior attempts' private part files are orphans now (deleting
            # an open file is safe — a straggler's fd stays valid until it
            # stands down)
            try:
                os.remove(leftover)
            except OSError:
                pass
        # commit EVENT before the terminal journal record: a crash between
        # the two leaves a committing+manifest orphan whose replay re-emits
        # a recovery commit (fragments=-1) — so every done job has >= 1
        # commit event and <= 1 REAL-run one, the soak's exactly-once form.
        # (serve.commit is a DURABLE_EVENTS flush-through, so once logged
        # it survives the very next crash.)
        service.log_event("serve.commit", job=job.id,
                          fragments=job.fragments,
                          bytes=os.path.getsize(job.fasta))
        service.journal_mark("committed", job.id)
        service.observe_latency(job)
    except JobAbortRequested:
        if job.watch:
            # lease ownership lost mid-run (serve._lease_tick demoted us):
            # the taker owns the job now — this run stands down and the
            # registry entry reverts to watching the taker's manifest (the
            # journal already holds the demoted record, never an abort)
            job.state = RUNNING
        else:
            job.state = ABORTED
            job.done_ts = time.time()
            service.journal_mark("aborted", job.id, reason="client")
            service.log_event("serve.abort", job=job.id, reason="client")
    except BaseException as e:  # noqa: BLE001 — job isolation boundary
        # ABORTED only when the CLIENT asked (abort event): a JobAborted
        # surfacing without it means the shared solve path died under this
        # job's rows (drain failure) — that is a FAILURE with a reason,
        # not an abort
        if job.watch and job.abort_event.is_set():
            job.state = RUNNING    # demoted (see JobAbortRequested above)
        elif job.abort_event.is_set():
            job.state = ABORTED
            service.journal_mark("aborted", job.id, reason="client")
            service.log_event("serve.abort", job=job.id,
                              reason="client")
        else:
            job.state = FAILED
            job.error = f"{type(e).__name__}: {e}"[:500]
            service.journal_mark("failed", job.id, error=job.error[:200])
            service.log_event("serve.job", job=job.id, state=FAILED,
                              tenant=job.tenant, error=job.error)
        job.done_ts = time.time()
        if not isinstance(e, Exception):
            raise   # KeyboardInterrupt/SystemExit must still unwind
    finally:
        job.running_local = False
        if gen is not None:
            gen.close()     # unwinds the pipeline's telemetry bundle
        if group is not None:
            group.release_job(job.id)
            service.warm.release(key)
        service.admission.release(job.tenant, job.spec.nbytes)
        service.release_job_lease(job.id)
        if job.state == DONE:
            service.log_event("serve.job", job=job.id, state=DONE,
                              tenant=job.tenant)


class JobAbortRequested(Exception):
    """Internal: the runner noticed the job's abort event between
    emissions."""
