"""Resilient HTTP choke point for the serve fleet (ISSUE 18).

PR 16's front door talks to its peers through raw ``urllib`` calls with
ad-hoc timeouts, and PR 17 proved the value of funnelling every durable
disk op through one fault-aware layer (``utils/aio.py``). This module is
the same move one layer up: every router / autoscaler / client HTTP call
goes through here, so the whole fleet shares

- **per-domain deadlines**: each RPC class (``healthz`` | ``submit`` |
  ``result`` | ``stream`` | ``abort``) carries an explicit timeout — a
  wedged peer socket costs one bounded deadline, never a stalled poll or
  scale loop;
- **bounded retries** with exponential backoff + full jitter, absorbing
  ONLY the transient class (connection reset / refused — the peer never
  processed, or never finished receiving, the request). Non-idempotent
  calls (a submit without an ``idempotency_key``) are never retried: a
  reset after the request left the socket is ambiguous, and only the
  journal-backed key makes the retry exactly-once;
- a per-peer **circuit breaker** (consecutive-failure open → half-open
  probe → close — the lease-grace-beats pattern applied to sockets), so a
  peer in a reset storm stops eating deadlines from every caller;
- **hedged reads** for idempotent domains (``result`` / ``healthz``): when
  a peer exceeds its own p99-derived latency budget, a second identical
  request races the first and the earliest answer wins (``net.hedge``) —
  the grey-slow-peer countermeasure;
- **response integrity**: full-body responses carry an end-to-end
  ``X-Daccord-Body-Bytes`` header and chunked streams a
  ``X-Daccord-Stream-Bytes`` trailer, so a torn body — a proxy that died
  mid-copy, an injected ``net_torn`` — is detected (:class:`TornBody`)
  and retried instead of committed short.

Injected network faults (ISSUE 18 ``net_*`` kinds, ``runtime/faults.py``)
are consulted before every attempt exactly like the aio hook: installed
explicitly by tests via :func:`install_faults` or resolved lazily from
``DACCORD_FAULT``, so a router under a ``net_reset@submit`` storm needs no
extra wiring. Injected errors are real ``OSError`` instances with real
errnos (ECONNREFUSED / ECONNRESET) or a real ``TimeoutError``, so callers'
handling of the injected matrix IS their handling of the real thing.
"""

from __future__ import annotations

import errno
import http.client
import json
import os
import random
import socket
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from collections import deque

#: end-to-end integrity headers (survive proxies that re-frame the body,
#: which Content-Length does not)
BODY_BYTES_HEADER = "X-Daccord-Body-Bytes"
STREAM_BYTES_TRAILER = "X-Daccord-Stream-Bytes"

#: default per-domain deadlines (seconds). ``result``/``stream`` are long
#: because ``result?wait=1`` legitimately blocks while a job solves;
#: ``healthz`` is short because the poll loop's cadence rides on it.
DEADLINES = {"healthz": 5.0, "submit": 30.0, "result": 600.0,
             "stream": 600.0, "abort": 10.0}

#: an injected ``net_hang`` spends min(deadline, this) of real wall-clock
#: before surfacing as the deadline timeout — enough to prove the caller
#: bounded the call, without making a chaos soak wait out a production
#: result deadline
_HANG_SLEEP_CAP_S = 2.0


def deadline_for(domain: str) -> float:
    return DEADLINES.get(domain, 30.0)


# ---------------------------------------------------------------------------
# Injected-network-fault hook — the aio plan-resolution pattern verbatim:
# an explicitly installed plan wins, else DACCORD_FAULT is parsed lazily
# and cached per env-string so counters persist across ops.
# ---------------------------------------------------------------------------

_FAULTS = None                     # explicitly installed plan (wins)
_ENV_FAULTS: tuple = (None, None)  # (env text, parsed plan) lazy cache


class InjectedNetFault(OSError):
    """A ``net_*``-injected transport failure; ``fault_kind`` names the
    spec so tests and event logs match the grammar despite the instance
    wearing a real errno."""

    def __init__(self, err: int, msg: str, fault_kind: str):
        super().__init__(err, msg)
        self.fault_kind = fault_kind


class TornBody(OSError):
    """Response-integrity failure: the body ended short of the byte count
    the peer declared (header or stream trailer). Idempotent callers
    retry; nobody commits a short result."""

    def __init__(self, expected: int, got: int, url: str = ""):
        super().__init__(f"torn body: got {got} of {expected} bytes"
                         + (f" from {url}" if url else ""))
        self.expected = expected
        self.got = got


class BreakerOpen(ConnectionError):
    """The peer's circuit breaker is open: fail fast, spend no deadline."""


def install_faults(plan) -> None:
    """Install (or with None, clear) the FaultPlan whose ``net_*`` kinds
    every request consults — counters and one-shot state live on the plan,
    exactly like ``aio.install_faults``."""
    global _FAULTS, _ENV_FAULTS
    _FAULTS = plan
    _ENV_FAULTS = (None, None)


def _net_plan():
    if _FAULTS is not None:
        return _FAULTS if _FAULTS.has_net_faults() else None
    text = os.environ.get("DACCORD_FAULT")
    global _ENV_FAULTS
    if _ENV_FAULTS[0] != text:
        plan = None
        if text:
            try:
                from ..runtime.faults import FaultPlan
                p = FaultPlan.parse(text)
                plan = p if p.has_net_faults() else None
            except ValueError:
                plan = None  # the CLI entry point already rejected it loudly
        _ENV_FAULTS = (text, plan)
    plan = _ENV_FAULTS[1]
    return plan if plan is not None and plan.has_net_faults() else None


def _prelude(domain: str, timeout: float, log_event=None, peer: str = ""):
    """One HTTP attempt: apply any ``net_slow`` delay, then fire and raise
    refused/reset/hang, or return the byte offset of a fired ``net_torn``
    (None = attempt runs clean)."""
    plan = _net_plan()
    if plan is None:
        return None
    ms = plan.net_slow_ms(domain)
    if ms > 0:
        time.sleep(ms / 1000.0)
    spec = plan.net_check(domain)
    if spec is None:
        return None
    if log_event is not None:
        log_event("net.fault", kind=spec.kind, domain=domain, peer=peer)
    if spec.kind == "net_refused":
        raise InjectedNetFault(errno.ECONNREFUSED,
                               f"injected net_refused@{domain}", spec.kind)
    if spec.kind == "net_reset":
        raise InjectedNetFault(errno.ECONNRESET,
                               f"injected net_reset@{domain}", spec.kind)
    if spec.kind == "net_hang":
        time.sleep(min(timeout, _HANG_SLEEP_CAP_S))
        raise TimeoutError(f"injected net_hang@{domain}: deadline "
                           f"{timeout:.1f}s expired")
    return int(spec.at)  # net_torn: truncate the body here


def _is_transient(exc: BaseException) -> bool:
    """The retry-safe class: the connection was refused (nothing sent) or
    reset (the peer tore the conversation down). Deadline timeouts and
    torn bodies are NOT transient-by-default — retrying them is the
    caller's idempotency decision, made via ``request(idempotent=...)``."""
    if isinstance(exc, InjectedNetFault):
        return exc.fault_kind in ("net_refused", "net_reset")
    if isinstance(exc, (ConnectionRefusedError, ConnectionResetError)):
        return True
    if isinstance(exc, urllib.error.URLError):
        return isinstance(getattr(exc, "reason", None),
                          (ConnectionRefusedError, ConnectionResetError))
    return False


def _is_timeout(exc: BaseException) -> bool:
    if isinstance(exc, (TimeoutError, socket.timeout)):
        return True
    if isinstance(exc, urllib.error.URLError):
        return isinstance(getattr(exc, "reason", None),
                          (TimeoutError, socket.timeout))
    return False


# ---------------------------------------------------------------------------
# one bounded attempt
# ---------------------------------------------------------------------------

def _attempt(url: str, domain: str, method: str, body, headers: dict,
             timeout: float, log_event=None, peer: str = ""):
    """One fault-gated HTTP attempt → (status, body, headers). An
    HTTP-level error status (429/503/404...) is a VALID ANSWER — returned,
    never raised: the peer is alive and talking. Only transport failures
    raise."""
    torn_at = _prelude(domain, timeout, log_event, peer)
    req = urllib.request.Request(url, method=method, data=body,
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            data = resp.read()
            status, rhead = resp.status, dict(resp.headers)
    except urllib.error.HTTPError as e:
        data = e.read()
        status, rhead = e.code, dict(e.headers)
    if torn_at is not None:
        data = data[:torn_at]
    declared = rhead.get(BODY_BYTES_HEADER)
    if declared is not None and int(declared) != len(data):
        raise TornBody(int(declared), len(data), url)
    return status, data, rhead


# ---------------------------------------------------------------------------
# module-level request: deadline + faults + integrity, no breaker/hedging
# (the autoscaler's drain call, tests, simple clients)
# ---------------------------------------------------------------------------

def request(url: str, domain: str, method: str = "GET",
            body: bytes | None = None, headers: dict | None = None,
            timeout: float | None = None, retries: int = 0,
            idempotent: bool = True, backoff_s: float = 0.05,
            log_event=None, peer: str = ""):
    """One resilient call → (status, body, headers). ``retries`` bounds
    EXTRA attempts, spent only on the transient class and only when
    ``idempotent`` (a submit without an idempotency key must pass
    ``idempotent=False`` — its reset is ambiguous and stays surfaced)."""
    timeout = deadline_for(domain) if timeout is None else timeout
    attempts = 1 + (retries if idempotent else 0)
    last: BaseException | None = None
    for i in range(attempts):
        try:
            return _attempt(url, domain, method, body, dict(headers or {}),
                            timeout, log_event, peer)
        except (TornBody, OSError, urllib.error.URLError,
                http.client.HTTPException) as e:
            last = e
            retryable = _is_transient(e) or (isinstance(e, TornBody)
                                             and idempotent)
            if not retryable or i + 1 >= attempts:
                raise
            # full jitter: a fleet of callers must not retry in lockstep
            time.sleep(random.uniform(0, backoff_s * (2 ** i)))
    raise last  # pragma: no cover — loop always returns or raises


# ---------------------------------------------------------------------------
# streamed reads with trailer verification
# ---------------------------------------------------------------------------

def stream(url: str, domain: str = "stream", headers: dict | None = None,
           timeout: float | None = None, log_event=None, peer: str = ""):
    """Open a chunked response and return ``(status, headers, chunks)``
    where ``chunks`` is a generator of body byte-chunks. The generator
    parses the chunk framing itself (stdlib clients discard trailers) and
    raises :class:`TornBody` at exhaustion when the peer's
    ``X-Daccord-Stream-Bytes`` trailer disagrees with the bytes received —
    a torn stream is an error, never a silently short result. Non-chunked
    responses degrade to one verified read."""
    timeout = deadline_for(domain) if timeout is None else timeout
    torn_at = _prelude(domain, timeout, log_event, peer)
    u = urllib.parse.urlsplit(url)
    conn = http.client.HTTPConnection(u.hostname, u.port, timeout=timeout)
    path = u.path + (f"?{u.query}" if u.query else "")
    conn.request("GET", path, headers=dict(headers or {}))
    resp = conn.getresponse()
    rhead = dict(resp.headers)
    chunked = (rhead.get("Transfer-Encoding", "").lower() == "chunked")

    def _gen():
        got = 0
        try:
            if not chunked:
                data = resp.read()
                if torn_at is not None:
                    data = data[:torn_at]
                declared = rhead.get(BODY_BYTES_HEADER)
                if declared is not None and int(declared) != len(data):
                    raise TornBody(int(declared), len(data), url)
                if data:
                    yield data
                return
            # manual chunk framing straight off the socket file: the only
            # way to see the trailer (http.client reads and discards it)
            fp = resp.fp
            while True:
                line = fp.readline(65536)
                if not line:
                    raise TornBody(-1, got, url)  # died before terminator
                size = int(line.split(b";")[0].strip() or b"0", 16)
                if size == 0:
                    break
                data = fp.read(size)
                if len(data) != size:
                    raise TornBody(got + size, got + len(data), url)
                fp.read(2)  # chunk CRLF
                if torn_at is not None and got + len(data) >= torn_at:
                    # injected tear: the proxy died mid-copy — bytes stop
                    # and the terminator/trailer never arrives
                    yield data[:max(0, torn_at - got)]
                    raise TornBody(-1, torn_at, url)
                got += len(data)
                yield data
            declared = None
            while True:  # trailer block: header lines until a blank
                line = fp.readline(65536)
                if line in (b"\r\n", b"\n", b""):
                    break
                k, _, v = line.decode("latin-1").partition(":")
                if k.strip().lower() == STREAM_BYTES_TRAILER.lower():
                    declared = int(v.strip())
            if declared is not None and declared != got:
                raise TornBody(declared, got, url)
        finally:
            conn.close()

    return resp.status, rhead, _gen()


def json_of(body: bytes):
    """The fleet's JSON-body convention in one place."""
    return json.loads(body.decode() or "{}")


# ---------------------------------------------------------------------------
# circuit breaker (per peer)
# ---------------------------------------------------------------------------

class CircuitBreaker:
    """Consecutive-failure breaker: ``fails`` transport failures in a row
    open it; after ``open_s`` it half-opens (ONE trial request passes);
    the trial's outcome closes or re-opens it. State probes are pure —
    only :meth:`allow` / :meth:`ok` / :meth:`fail` transition."""

    def __init__(self, fails: int = 3, open_s: float = 5.0,
                 clock=time.monotonic):
        self.fail_threshold = max(1, int(fails))
        self.open_s = float(open_s)
        self._clock = clock
        self._fails = 0
        self._opened_ts: float | None = None
        self._probing = False
        self._lock = threading.Lock()

    def state(self) -> str:
        with self._lock:
            if self._opened_ts is None:
                return "closed"
            if self._clock() - self._opened_ts >= self.open_s:
                return "half-open"
            return "open"

    def allow(self) -> bool:
        """May a request go out now? Open = no; half-open = yes for ONE
        in-flight probe (concurrent callers keep failing fast until the
        probe resolves)."""
        with self._lock:
            if self._opened_ts is None:
                return True
            if self._clock() - self._opened_ts < self.open_s:
                return False
            if self._probing:
                return False
            self._probing = True
            return True

    def ok(self) -> str | None:
        """Record a success; returns the new state when it transitioned
        (for ``router.breaker`` event logging), else None."""
        with self._lock:
            self._fails = 0
            self._probing = False
            if self._opened_ts is not None:
                self._opened_ts = None
                return "closed"
            return None

    def fail(self) -> str | None:
        with self._lock:
            self._fails += 1
            self._probing = False
            if self._opened_ts is None and \
                    self._fails >= self.fail_threshold:
                self._opened_ts = self._clock()
                return "open"
            if self._opened_ts is not None:
                # a failed half-open probe re-arms the full cooldown
                self._opened_ts = self._clock()
            return None


# ---------------------------------------------------------------------------
# NetClient: breakers + hedging + latency memory, per calling process
# ---------------------------------------------------------------------------

#: domains whose reads are side-effect-free on the peer — safe to hedge
HEDGE_DOMAINS = ("result", "healthz")


class NetClient:
    """The router's (or any long-lived caller's) stateful view of the
    fleet's sockets: one :class:`CircuitBreaker` and a recent-latency
    window per peer. ``log_event(kind, **fields)`` receives ``net.fault``
    / ``net.hedge`` / ``router.breaker`` events."""

    def __init__(self, log_event=None, retries: int = 2,
                 backoff_s: float = 0.05, breaker_fails: int = 3,
                 breaker_open_s: float = 5.0, hedge_floor_s: float = 0.25,
                 hedge_min_samples: int = 8):
        self.log_event = log_event
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self.breaker_fails = int(breaker_fails)
        self.breaker_open_s = float(breaker_open_s)
        self.hedge_floor_s = float(hedge_floor_s)
        self.hedge_min_samples = int(hedge_min_samples)
        self._breakers: dict[str, CircuitBreaker] = {}
        self._lat: dict[tuple, deque] = {}
        self._lock = threading.Lock()
        self.counters = {"hedges": 0, "hedge_wins": 0, "breaker_opens": 0}

    # -- state accessors ---------------------------------------------------

    def breaker(self, peer: str) -> CircuitBreaker:
        with self._lock:
            b = self._breakers.get(peer)
            if b is None:
                b = self._breakers[peer] = CircuitBreaker(
                    self.breaker_fails, self.breaker_open_s)
            return b

    def breaker_state(self, peer: str) -> str:
        with self._lock:
            b = self._breakers.get(peer)
        return b.state() if b is not None else "closed"

    def _note_latency(self, peer: str, domain: str, dt: float) -> None:
        with self._lock:
            q = self._lat.setdefault((peer, domain), deque(maxlen=64))
            q.append(dt)

    def latency_budget(self, peer: str, domain: str) -> float | None:
        """The hedge trigger: ~p99 of this peer+domain's recent latencies,
        floored so cold stats never hedge-storm. None = not enough
        samples to judge the peer slow."""
        with self._lock:
            q = self._lat.get((peer, domain))
            if q is None or len(q) < self.hedge_min_samples:
                return None
            lat = sorted(q)
        p99 = lat[min(len(lat) - 1, int(len(lat) * 0.99))]
        return max(self.hedge_floor_s, 2.0 * p99)

    def _emit(self, event: str, **fields) -> None:
        # param named ``event``, not ``kind``: net.fault carries a field
        # literally called ``kind`` and must not collide with it
        if self.log_event is not None:
            try:
                self.log_event(event, **fields)
            except Exception:  # noqa: BLE001 — telemetry never breaks I/O
                pass

    def _transition(self, peer: str, state: str | None) -> None:
        if state is None:
            return
        if state == "open":
            self.counters["breaker_opens"] += 1
        self._emit("router.breaker", peer=peer, state=state)

    def record_ok(self, peer: str) -> None:
        """Feed the breaker an out-of-band success (e.g. a streamed proxy
        that this client opened through :func:`stream`, which has no
        breaker loop of its own)."""
        self._transition(peer, self.breaker(peer).ok())

    def record_fail(self, peer: str) -> None:
        """Feed the breaker an out-of-band transport failure."""
        self._transition(peer, self.breaker(peer).fail())

    # -- the resilient request ---------------------------------------------

    def request(self, peer: str, url: str, domain: str,
                method: str = "GET", body: bytes | None = None,
                headers: dict | None = None, timeout: float | None = None,
                idempotent: bool = True):
        """(status, body, headers) with the full discipline: breaker gate,
        bounded transient retries, hedged reads on slow idempotent
        domains, integrity verification. Transport failure raises after
        the retry budget; :class:`BreakerOpen` raises immediately while
        the peer's breaker holds."""
        timeout = deadline_for(domain) if timeout is None else timeout
        br = self.breaker(peer)
        attempts = 1 + (self.retries if idempotent else 0)
        last: BaseException | None = None
        for i in range(attempts):
            if not br.allow():
                raise BreakerOpen(f"breaker open for peer {peer}")
            t0 = time.monotonic()
            try:
                out = self._hedged_attempt(peer, url, domain, method, body,
                                           headers, timeout, idempotent)
            except (TornBody, OSError, urllib.error.URLError,
                    http.client.HTTPException) as e:
                self._transition(peer, br.fail())
                last = e
                retryable = _is_transient(e) or (isinstance(e, TornBody)
                                                 and idempotent)
                if not retryable or i + 1 >= attempts:
                    raise
                time.sleep(random.uniform(0, self.backoff_s * (2 ** i)))
                continue
            self._note_latency(peer, domain, time.monotonic() - t0)
            self._transition(peer, br.ok())
            return out
        raise last  # pragma: no cover

    def _hedged_attempt(self, peer, url, domain, method, body, headers,
                        timeout, idempotent):
        """One attempt, hedged when the domain is read-only and the peer
        has a latency history: if the primary outlives the p99-derived
        budget, a second identical request races it."""
        budget = self.latency_budget(peer, domain) \
            if idempotent and domain in HEDGE_DOMAINS else None
        if budget is None or budget >= timeout:
            return _attempt(url, domain, method, body, dict(headers or {}),
                            timeout, self._emit, peer)

        box: list = []
        done = threading.Event()

        def _run(which: str):
            try:
                r = _attempt(url, domain, method, body, dict(headers or {}),
                             timeout, self._emit, peer)
                box.append(("ok", which, r))
            except BaseException as e:  # noqa: BLE001 — relayed to caller
                box.append(("err", which, e))
            done.set()

        t1 = threading.Thread(target=_run, args=("primary",), daemon=True)
        t1.start()
        if not done.wait(budget):
            self.counters["hedges"] += 1
            self._emit("net.hedge", peer=peer, domain=domain,
                       budget_s=round(budget, 4))
            t2 = threading.Thread(target=_run, args=("hedge",), daemon=True)
            t2.start()
        # first completion wins; a straggler's late append is ignored
        while not box:
            done.wait(timeout)
            if not box:  # both wedged past the deadline
                raise TimeoutError(f"hedged {domain} to {peer}: no answer "
                                   f"within {timeout:.1f}s")
        status, which, payload = box[0]
        if status == "err":
            raise payload
        if which == "hedge":
            self.counters["hedge_wins"] += 1
        return payload
