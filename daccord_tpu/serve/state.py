"""Warm-state manager: solve groups resident across jobs, idle-evicted.

The whole reason a server beats per-invocation `daccord` at serving scale is
cold-start amortization: the ladder tables, the jitted programs (cache
identity = the TierLadder object a :class:`~.batcher.SolveGroup` owns), the
supervisor's compile-fingerprint state, and the governor's capacity ratchets
all survive from job to job here. Groups are keyed by solve fingerprint
(``jobs.solve_fingerprint``); a hit means the Nth job starts solving
immediately. Idle groups (refcount zero past the TTL) evict so a long-lived
server's memory tracks its live workload mix, not its history.

Front-door interplay (ISSUE 16): the router's rendezvous stickiness exists
to keep a tenant's jobs landing on the peer whose groups are already warm —
which the idle TTL can defeat by evicting the exact group the router is
about to route to (the tenant paused just past the TTL; the router still
owns them). When a router heartbeat is live (``note_router_heartbeat``, set
by the proxied ``/v1/healthz`` poll), eviction therefore consults the
last-routed timestamps the service records at submit time (``note_route``):
a group whose key was routed within the grace window survives the sweep.
Without a router (solo peer), behavior is exactly the pre-16 TTL.
"""

from __future__ import annotations

import threading
import time


class WarmState:
    # a router poll within this window counts as "a router is alive" (the
    # default healthz cadence is ~1 s; 10 s tolerates a slow poll loop
    # without keeping grace armed long after the router died)
    ROUTER_FRESH_S = 10.0

    def __init__(self, idle_evict_s: float = 600.0, log=None,
                 route_grace_s: float = 30.0):
        from ..utils.obs import NullLogger

        self.idle_evict_s = float(idle_evict_s)
        self.log = log if log is not None else NullLogger()
        self._lock = threading.Lock()
        self._groups: dict[str, object] = {}
        self.counters = {"hits": 0, "misses": 0, "evicted": 0,
                         "evict_deferred": 0}
        # evict-vs-route race guard (ISSUE 16): last router heartbeat +
        # per-key last-routed stamps; grace = how long a routed-to key
        # outlives its idle TTL while a router is alive
        self.route_grace_s = float(route_grace_s)
        self._router_seen_ts = 0.0
        self._last_routed: dict[str, float] = {}

    def acquire(self, key: str, factory):
        """The group for ``key`` (built via ``factory()`` on miss), with its
        refcount taken — callers MUST pair with :meth:`release`.

        The build runs OUTSIDE the cache lock (per-key once-guard): a cold
        group build is seconds of ladder/table construction, and holding
        the lock through it would stall the ticker's ``groups()`` sweep —
        freezing stale-pool flushes for every already-warm group — plus
        every other job's acquire, warm or not. Concurrent acquirers of
        the SAME key wait on the build event; a failed build clears the
        placeholder so the next acquirer retries."""
        while True:
            with self._lock:
                entry = self._groups.get(key)
                if entry is None:
                    self.counters["misses"] += 1
                    building = threading.Event()
                    self._groups[key] = ("building", building)
                    break
                if isinstance(entry, tuple):
                    building = entry[1]
                else:
                    self.counters["hits"] += 1
                    entry.refs += 1
                    entry.last_used = time.time()
                    return entry
            building.wait()
        try:
            g = factory()
        except BaseException:
            with self._lock:
                self._groups.pop(key, None)
            building.set()
            raise
        with self._lock:
            self._groups[key] = g
            g.refs += 1
            g.last_used = time.time()
        building.set()
        return g

    @staticmethod
    def _built(entry) -> bool:
        # in-progress builds sit in the cache as ("building", Event)
        # placeholders so concurrent acquirers of the same key can wait
        return not isinstance(entry, tuple)

    def release(self, key: str) -> None:
        with self._lock:
            g = self._groups.get(key)
            if g is not None and self._built(g):
                g.refs = max(0, g.refs - 1)
                g.last_used = time.time()

    def note_router_heartbeat(self, now: float | None = None) -> None:
        """A front-door router just polled this peer (the healthz handler
        calls this on the ``X-Daccord-Router`` header) — arm the
        evict-vs-route grace window."""
        self._router_seen_ts = time.time() if now is None else now

    def note_route(self, key: str, now: float | None = None) -> None:
        """A job routed here was admitted for ``key`` — stamp it so the
        idle sweep knows the router's stickiness still points at this
        group even if no solve has touched it yet."""
        with self._lock:
            self._last_routed[key] = time.time() if now is None else now

    def router_live(self, now: float | None = None) -> bool:
        now = time.time() if now is None else now
        return (now - self._router_seen_ts) < self.ROUTER_FRESH_S

    def evict_idle(self, now: float | None = None) -> int:
        """Close and drop groups idle (refcount 0) past the TTL; returns the
        eviction count. A TTL of 0 evicts every idle group (tests/shutdown).

        The evict-vs-route race (ISSUE 16): between the router choosing this
        peer for a tenant (stickiness = this group is warm HERE) and that
        tenant's next submit arriving, the TTL can expire and this sweep
        would evict the exact group the router is routing to — the next job
        then pays a cold build the whole front door exists to avoid. While a
        router heartbeat is fresh, a key routed within ``route_grace_s``
        therefore survives the sweep (deferred, not exempted: once the
        router dies or the grace lapses, the TTL wins again)."""
        now = time.time() if now is None else now
        n = 0
        router = self.router_live(now)
        with self._lock:
            for key, g in list(self._groups.items()):
                if not self._built(g):
                    continue
                if g.refs == 0 and now - g.last_used >= self.idle_evict_s:
                    routed = self._last_routed.get(key)
                    if (router and routed is not None
                            and now - routed < self.route_grace_s):
                        self.counters["evict_deferred"] += 1
                        self.log.log("serve.evict_defer", group=g.name,
                                     key=key[:16],
                                     routed_s=round(now - routed, 3))
                        continue
                    del self._groups[key]
                    self._last_routed.pop(key, None)
                    self.counters["evicted"] += 1
                    n += 1
                    idle = now - g.last_used
                    self.log.log("serve.evict", group=g.name, key=key[:16],
                                 idle_s=round(idle, 3))
                    g.close()
        return n

    def building(self) -> int:
        """In-progress group builds (the ``ready`` denominator: a peer with
        a build in flight is up but not warm — the router should not
        rendezvous new tenants onto it)."""
        with self._lock:
            return sum(1 for g in self._groups.values()
                       if not self._built(g))

    def groups(self) -> list:
        with self._lock:
            return [g for g in self._groups.values() if self._built(g)]

    def stats(self) -> dict:
        with self._lock:
            built = [g for g in self._groups.values() if self._built(g)]
            return {**self.counters, "resident": len(built),
                    "groups": [g.stats() for g in built]}

    def close(self) -> None:
        with self._lock:
            for g in self._groups.values():
                if self._built(g):
                    g.close()
            self._groups.clear()
