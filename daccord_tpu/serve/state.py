"""Warm-state manager: solve groups resident across jobs, idle-evicted.

The whole reason a server beats per-invocation `daccord` at serving scale is
cold-start amortization: the ladder tables, the jitted programs (cache
identity = the TierLadder object a :class:`~.batcher.SolveGroup` owns), the
supervisor's compile-fingerprint state, and the governor's capacity ratchets
all survive from job to job here. Groups are keyed by solve fingerprint
(``jobs.solve_fingerprint``); a hit means the Nth job starts solving
immediately. Idle groups (refcount zero past the TTL) evict so a long-lived
server's memory tracks its live workload mix, not its history.
"""

from __future__ import annotations

import threading
import time


class WarmState:
    def __init__(self, idle_evict_s: float = 600.0, log=None):
        from ..utils.obs import NullLogger

        self.idle_evict_s = float(idle_evict_s)
        self.log = log if log is not None else NullLogger()
        self._lock = threading.Lock()
        self._groups: dict[str, object] = {}
        self.counters = {"hits": 0, "misses": 0, "evicted": 0}

    def acquire(self, key: str, factory):
        """The group for ``key`` (built via ``factory()`` on miss), with its
        refcount taken — callers MUST pair with :meth:`release`.

        The build runs OUTSIDE the cache lock (per-key once-guard): a cold
        group build is seconds of ladder/table construction, and holding
        the lock through it would stall the ticker's ``groups()`` sweep —
        freezing stale-pool flushes for every already-warm group — plus
        every other job's acquire, warm or not. Concurrent acquirers of
        the SAME key wait on the build event; a failed build clears the
        placeholder so the next acquirer retries."""
        while True:
            with self._lock:
                entry = self._groups.get(key)
                if entry is None:
                    self.counters["misses"] += 1
                    building = threading.Event()
                    self._groups[key] = ("building", building)
                    break
                if isinstance(entry, tuple):
                    building = entry[1]
                else:
                    self.counters["hits"] += 1
                    entry.refs += 1
                    entry.last_used = time.time()
                    return entry
            building.wait()
        try:
            g = factory()
        except BaseException:
            with self._lock:
                self._groups.pop(key, None)
            building.set()
            raise
        with self._lock:
            self._groups[key] = g
            g.refs += 1
            g.last_used = time.time()
        building.set()
        return g

    @staticmethod
    def _built(entry) -> bool:
        # in-progress builds sit in the cache as ("building", Event)
        # placeholders so concurrent acquirers of the same key can wait
        return not isinstance(entry, tuple)

    def release(self, key: str) -> None:
        with self._lock:
            g = self._groups.get(key)
            if g is not None and self._built(g):
                g.refs = max(0, g.refs - 1)
                g.last_used = time.time()

    def evict_idle(self, now: float | None = None) -> int:
        """Close and drop groups idle (refcount 0) past the TTL; returns the
        eviction count. A TTL of 0 evicts every idle group (tests/shutdown)."""
        now = time.time() if now is None else now
        n = 0
        with self._lock:
            for key, g in list(self._groups.items()):
                if not self._built(g):
                    continue
                if g.refs == 0 and now - g.last_used >= self.idle_evict_s:
                    del self._groups[key]
                    self.counters["evicted"] += 1
                    n += 1
                    idle = now - g.last_used
                    self.log.log("serve.evict", group=g.name, key=key[:16],
                                 idle_s=round(idle, 3))
                    g.close()
        return n

    def groups(self) -> list:
        with self._lock:
            return [g for g in self._groups.values() if self._built(g)]

    def stats(self) -> dict:
        with self._lock:
            built = [g for g in self._groups.values() if self._built(g)]
            return {**self.counters, "resident": len(built),
                    "groups": [g.stats() for g in built]}

    def close(self) -> None:
        with self._lock:
            for g in self._groups.values():
                if self._built(g):
                    g.close()
            self._groups.clear()
