"""Stateless tenant router: the serve fleet's front door (ISSUE 16).

PR 15 made a single peer crash-durable and PR 13 gave it an SLO burn
signal, but clients still picked peers by hand and a saturated peer shed
instead of spilling. The router is the thin stateless tier in front
(ParaFold's split of routing/admission from stateful solve, applied at the
fleet boundary):

- **discovery**: peers are read from the takeover group's shared lease dir
  — every ``daccord-serve --peer-dir`` process announces its URL at
  ``<peer_dir>/peers/<service_id>.lease`` (``ConsensusService.announce``)
  and renews it on the job-lease heartbeat, so a dead peer's announce goes
  stale on the same clock as its job leases. A lock-free ``/v1/healthz``
  poll (with the ``X-Daccord-Router`` header that arms the peers'
  evict-vs-route grace) layers liveness + the ``ready`` flag on top:
  ``ready`` distinguishes warm from mid-compile, because a peer minutes
  into a cold jit is alive and yet a terrible routing target.
- **stickiness**: rendezvous (highest-random-weight) hashing of tenant →
  ready peer. Warmth — compiled programs, governor ratchets, shape
  families — lives per peer, so a tenant bouncing between peers pays N
  cold builds for N peers; rendezvous keeps the map stable under peer
  arrival/departure with no coordination and no state to lose (a restarted
  router computes the identical map, which is what "stateless" buys).
- **spill**: when the owner's admission is paused (shed level > 0), it is
  not ready, or its SLO burn band is red (>= ``spill_burn``), the job
  spills to the least-loaded ready peer instead of queuing behind the
  burn. Stickiness is a preference, not a cage.
- **proxying**: submit/result/stream/abort forward verbatim — including
  the client's ``idempotency_key``, which is what makes a mid-proxy router
  or peer crash already-exactly-once: the client retries the SAME key and
  the fleet dedupes (journal-backed), whether the retry lands on the same
  peer or, after a takeover, on its successor. The router holds no job
  state a crash could lose; its job→peer map is a cache rebuilt by
  fan-out on miss.
- **network discipline** (ISSUE 18): every peer call goes through the
  ``serve/netio.py`` choke point — per-domain deadlines (a wedged socket
  can no longer stall the poll loop), bounded transient retries, a
  per-peer circuit breaker (``router.breaker`` events; an open breaker
  spills the owner's tenants like a shed does), hedged healthz/result
  reads against grey-slow peers, and byte-count verification that turns a
  torn proxied stream into a retryable error instead of a short commit.
- **partition asymmetry**: an HTTP-unreachable peer whose announce lease
  is still fresh is *partitioned*, not dead (``router.partition``): its
  tenants spill, but its jobs keep their leases (no takeover fires — the
  job-lease clock is the peer's own, still beating) and the autoscaler
  must neither reap nor drain it. Only a stale lease — the shared-FS
  ground truth — declares a peer gone.

The router's own telemetry (``router.events.jsonl``: ``router.*`` routing
milestones + ``scale.*`` from the optional autoscaler + ``net.*`` from
the choke point) rides the same eventcheck/trace/sentinel chain as every
other sidecar in the repo.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..utils import lease
from . import netio
from .service import _LockedLogger

# hop-by-hop headers a proxy must not forward (RFC 9110 §7.6.1)
_HOP_HEADERS = {"connection", "keep-alive", "proxy-authenticate",
                "proxy-authorization", "te", "trailer",
                "transfer-encoding", "upgrade", "host", "content-length"}


class _ClientGone(Exception):
    """The DOWNSTREAM client disconnected mid-proxy — a failure of the
    tenant's connection, not of the peer being proxied to. Kept distinct
    so the error paths never blame (mark_dead / breaker-strike) a healthy
    peer for it."""


@dataclass
class RouterConfig:
    workdir: str = "daccord-router"
    peer_dir: str = ""               # the takeover group's shared lease root
    poll_s: float = 1.0              # healthz poll cadence
    lease_ttl_s: float = 15.0        # announce lease older than this = down
    spill_burn: float = 1.0          # owner burn >= this (red band) → spill
    proxy_timeout_s: float = 600.0   # per proxied request (result?wait=1
                                     # legitimately blocks for minutes)
    healthz_timeout_s: float = 5.0   # per poll — the poll loop's cadence
                                     # rides on this being bounded
    probe_timeout_s: float = 5.0     # per fan-out job probe
    breaker_fails: int = 3           # consecutive failures → breaker opens
    breaker_open_s: float = 5.0      # open cooldown before half-open probe
    net_retries: int = 2             # transient-class retry budget
    events_path: str | None = None   # default <workdir>/router.events.jsonl


@dataclass
class Peer:
    name: str                        # service_id (announce lease basename)
    url: str
    alive: bool = False              # lease fresh + healthz answering
    ready: bool = False              # healthz.ready (warm, replay done)
    partitioned: bool = False        # healthz unreachable, lease FRESH —
                                     # alive-but-unroutable, never reaped
    lease_age: float = -1.0          # announce lease age at last scan
    shed_level: int = 0
    queue_depth: int = 0
    burn: float = 0.0
    jobs_active: int = 0             # queued+running (healthz.jobs)
    last_ok_ts: float = 0.0
    health: dict = field(default_factory=dict)

    def load(self) -> tuple:
        """Least-loaded ordering key for spill targets."""
        return (self.jobs_active + self.queue_depth, self.burn)


class Router:
    """Peer table + routing policy + the proxy core. The HTTP tier
    (:func:`start_router`) is a thin shell over :meth:`proxy` /
    :meth:`route`; everything testable lives here."""

    def __init__(self, cfg: RouterConfig, log=None):
        if not cfg.peer_dir:
            raise ValueError("router needs a peer_dir (the takeover "
                             "group's shared lease root) to discover peers")
        self.cfg = cfg
        os.makedirs(cfg.workdir, exist_ok=True)
        ev = cfg.events_path or os.path.join(cfg.workdir,
                                             "router.events.jsonl")
        self.log = log if log is not None else \
            _LockedLogger(ev, buffer_lines=16, flush_s=1.0)
        self._lock = threading.Lock()
        self.peers: dict[str, Peer] = {}
        self._job_map: dict[str, str] = {}    # job id -> peer name (cache)
        self.counters = {"routes": 0, "spills": 0, "proxied": 0,
                         "proxy_errors": 0, "fanouts": 0}
        self.autoscaler = None                # attached by start_router
        self.net = netio.NetClient(log_event=self._net_event,
                                   retries=cfg.net_retries,
                                   breaker_fails=cfg.breaker_fails,
                                   breaker_open_s=cfg.breaker_open_s)
        self._stop = threading.Event()
        self.started_ts = time.time()
        self.log.log("router.start", workdir=cfg.workdir,
                     peer_dir=cfg.peer_dir, pid=os.getpid())
        self._poller = threading.Thread(target=self._poll_loop, daemon=True,
                                        name="daccord-router-poll")
        self._poller.start()

    # ------------------------------------------------------------------
    # discovery: announce leases + healthz polls
    # ------------------------------------------------------------------

    def _net_event(self, event: str, **fields) -> None:
        """netio's event sink: the choke point's net.fault / net.hedge /
        router.breaker milestones land in the router's own sidecar. The
        positional is named ``event`` on purpose — ``net.fault`` carries a
        FIELD named ``kind``, which would collide with a ``kind`` param."""
        try:
            self.log.log(event, **fields)
        except Exception:  # noqa: BLE001 — telemetry never breaks routing
            pass

    def _scan_announces(self) -> dict[str, tuple]:
        """name -> (url, lease_age_s) from fresh announce leases (stale =
        peer presumed dead; its job leases are going stale on the same
        clock and the takeover path owns recovery — the router only stops
        routing there). The age rides along so an HTTP-unreachable peer
        can be reconciled against the shared-FS ground truth: fresh lease
        + dead healthz = partitioned, not dead."""
        import glob as _glob

        out: dict[str, tuple] = {}
        for path in _glob.glob(os.path.join(self.cfg.peer_dir, "peers",
                                            "*.lease")):
            age = lease.stale_s(path)
            if age is None or age > self.cfg.lease_ttl_s:
                continue
            info = lease.read(path)
            if info and info.get("url"):
                name = os.path.basename(path).rsplit(".lease", 1)[0]
                out[name] = (str(info["url"]), float(age))
        return out

    def _poll_one(self, peer: Peer) -> None:
        """One lock-free healthz poll through the choke point — bounded by
        the healthz deadline (a hung peer socket costs one deadline, never
        a stalled poll loop), breaker-gated, hedged once the peer has a
        latency history. The X-Daccord-Router header arms the peer's
        evict-vs-route grace window."""
        try:
            status, body, _h = self.net.request(
                peer.name, peer.url + "/v1/healthz", "healthz",
                headers={"X-Daccord-Router": "1"},
                timeout=self.cfg.healthz_timeout_s)
            if status != 200:
                raise OSError(f"healthz status {status}")
            h = json.loads(body)
        except Exception:
            peer.alive = False
            peer.ready = False
            return
        peer.alive = bool(h.get("ok"))
        peer.ready = bool(h.get("ready"))
        peer.shed_level = int(h.get("shed_level", 0) or 0)
        peer.queue_depth = int(h.get("queue_depth", 0) or 0)
        peer.burn = float(h.get("burn", 0.0) or 0.0)
        jobs = h.get("jobs") or {}
        peer.jobs_active = int(jobs.get("queued", 0)) + \
            int(jobs.get("running", 0))
        peer.last_ok_ts = time.time()
        peer.health = h

    def refresh(self) -> None:
        """One discovery+poll sweep (the poll loop's body; tests call it
        directly for determinism)."""
        announced = self._scan_announces()
        with self._lock:
            known = dict(self.peers)
        for name, (url, age) in announced.items():
            p = known.get(name)
            if p is None:
                p = Peer(name=name, url=url)
                with self._lock:
                    self.peers[name] = p
            p.url = url
            p.lease_age = age
        for name, p in list(known.items()):
            if name not in announced:
                # stale/released announce: the peer is gone — the shared-FS
                # ground truth, strictly stronger than an HTTP verdict
                if p.alive or p.partitioned:
                    self.log.log("router.peer_down", peer=name,
                                 reason="lease_stale")
                with self._lock:
                    self.peers.pop(name, None)
        with self._lock:
            peers = list(self.peers.values())
        for p in peers:
            was = p.alive
            self._poll_one(p)
            if p.alive and not was:
                self.log.log("router.peer_up", peer=p.name, url=p.url,
                             ready=p.ready)
            elif was and not p.alive:
                self.log.log("router.peer_down", peer=p.name,
                             reason="healthz")
            # partition reconciliation: healthz says dead, the announce
            # lease says the peer's heart is beating. Believe the lease —
            # the peer is cut off from US, not from the world: its tenants
            # spill (it is unroutable) but its jobs keep their fresh
            # leases (takeover must not fire) and the autoscaler must not
            # reap or drain it (tick() checks this flag).
            part = not p.alive and p.name in announced
            if part and not p.partitioned:
                self.log.log("router.partition", peer=p.name, state="begin",
                             lease_age_s=round(p.lease_age, 3))
            elif p.partitioned and not part:
                self.log.log("router.partition", peer=p.name, state="end",
                             lease_age_s=round(p.lease_age, 3))
            p.partitioned = part

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.cfg.poll_s):
            try:
                self.refresh()
                if self.autoscaler is not None:
                    self.autoscaler.tick(self.snapshot_peers())
            except Exception as e:  # noqa: BLE001 — the poller must survive
                try:
                    self.log.log("router.proxy_error", peer="-",
                                 error=f"poll:{type(e).__name__}"[:200])
                except Exception:
                    pass

    def snapshot_peers(self) -> list[Peer]:
        with self._lock:
            return list(self.peers.values())

    # ------------------------------------------------------------------
    # routing policy
    # ------------------------------------------------------------------

    @staticmethod
    def _score(tenant: str, peer_name: str) -> int:
        """Rendezvous weight: every router instance (and every restart)
        ranks peers identically from the hash alone — the stateless
        stickiness that keeps a tenant on the peer whose groups are warm."""
        h = hashlib.sha256(f"{tenant}|{peer_name}".encode()).digest()
        return int.from_bytes(h[:8], "big")

    def owner_of(self, tenant: str, peers: list[Peer] | None = None) -> Peer | None:
        """The rendezvous owner among ALIVE peers. Readiness deliberately
        does NOT move ownership (the map must stay stable while a peer is
        briefly mid-compile) — :meth:`route` spills off a not-ready owner
        instead, and comes back when it warms."""
        peers = self.snapshot_peers() if peers is None else peers
        pool = [p for p in peers if p.alive]
        if not pool:
            return None
        return max(pool, key=lambda p: self._score(tenant, p.name))

    def route(self, tenant: str, job: str | None = None) -> Peer | None:
        """The peer ``tenant``'s next job should land on: the rendezvous
        owner unless its admission is pausing (shed), it lost readiness, or
        its burn band is red — then the least-loaded OTHER ready peer
        (spill). Returns None when the fleet is empty/unreachable."""
        peers = self.snapshot_peers()
        owner = self.owner_of(tenant, peers)
        if owner is None:
            return None
        chosen, spilled, reason = owner, False, None
        if not owner.ready:
            reason = "not_ready"
        elif owner.shed_level > 0:
            reason = "shed"
        elif self.cfg.spill_burn and owner.burn >= self.cfg.spill_burn:
            reason = "burn"
        elif self.net.breaker_state(owner.name) == "open":
            # the owner's sockets are in a failure storm: spill past it
            # while the breaker cools (half-open probes re-admit it)
            reason = "breaker"
        if reason is not None:
            others = [p for p in peers if p.ready and p.name != owner.name]
            if others:
                chosen = min(others, key=Peer.load)
                spilled = True
            # nobody to spill to: the owner (alive, maybe shedding) still
            # beats a refusal — its admission plane is the backstop
        self.counters["routes"] += 1
        if spilled:
            self.counters["spills"] += 1
            self.log.log("router.spill", tenant=tenant, owner=owner.name,
                         to=chosen.name, reason=reason)
        self.log.log("router.route", tenant=tenant, peer=chosen.name,
                     spilled=spilled, **({"job": job} if job else {}))
        return chosen

    # ------------------------------------------------------------------
    # proxy core
    # ------------------------------------------------------------------

    def mark_dead(self, peer: Peer, reason: str = "proxy_error") -> None:
        """A proxy just failed against ``peer``: stop routing there NOW
        (the next healthz poll re-checks). Logging the transition here —
        not in the poll loop — keeps ``router.peer_down`` exact when the
        proxy error is what discovered the death."""
        if peer.alive:
            self.log.log("router.peer_down", peer=peer.name, reason=reason)
        peer.alive = False
        peer.ready = False

    def note_job(self, job_id: str, peer_name: str) -> None:
        with self._lock:
            self._job_map[job_id] = peer_name

    def peer_for_job(self, job_id: str) -> Peer | None:
        """The peer owning ``job_id``: the cached mapping when fresh, else
        a fan-out probe of every live peer (the cache is just a cache — a
        restarted router, or a job that moved by takeover, rebuilds it)."""
        with self._lock:
            name = self._job_map.get(job_id)
            p = self.peers.get(name) if name else None
        if p is not None and p.alive:
            return p
        self.counters["fanouts"] += 1
        for p in self.snapshot_peers():
            if not p.alive:
                continue
            try:
                status, _b, _h = self.net.request(
                    p.name, p.url + f"/v1/jobs/{job_id}", "result",
                    timeout=self.cfg.probe_timeout_s)
            except Exception:
                continue
            if status == 200:
                self.note_job(job_id, p.name)
                return p
        return None

    @staticmethod
    def _domain_for(method: str, path: str) -> str:
        """RPC class of a proxied request — the netio deadline/fault key."""
        p = path.split("?")[0]
        if method == "DELETE" or p.endswith("/shutdown"):
            return "abort"
        if method == "POST":
            return "submit"
        if p.endswith("/stream"):
            return "stream"
        return "result"

    def proxy(self, peer: Peer, method: str, path: str,
              body: bytes | None = None, headers: dict | None = None,
              idempotent: bool | None = None) -> tuple[int, bytes, str]:
        """Forward one request through the choke point; returns (status,
        body, content_type). An HTTP-level refusal (429/503/404...) is a
        valid answer and forwards verbatim; transport failure raises (the
        caller maps that to 502 + retryable, and the client's idempotency
        key makes the retry exactly-once). ``idempotent`` gates the
        transient-retry budget: a submit is only retry-safe when the
        client sent an idempotency key — everything else (GET status,
        result, DELETE abort) is safe by construction."""
        domain = self._domain_for(method, path)
        if idempotent is None:
            idempotent = domain != "submit"
        status, data, rhead = self.net.request(
            peer.name, peer.url + path, domain, method=method, body=body,
            headers={k: v for k, v in (headers or {}).items()
                     if k.lower() not in _HOP_HEADERS},
            timeout=min(self.cfg.proxy_timeout_s,
                        netio.deadline_for(domain)),
            idempotent=idempotent)
        self.counters["proxied"] += 1
        return (status, data,
                rhead.get("Content-Type", "application/json"))

    def stats(self) -> dict:
        peers = self.snapshot_peers()
        with self._lock:
            jmap = dict(self._job_map)
        out = {"ok": True, "ready": any(p.ready for p in peers),
               "uptime_s": round(time.time() - self.started_ts, 3),
               "peers": [{"name": p.name, "url": p.url, "alive": p.alive,
                          "ready": p.ready, "shed": p.shed_level,
                          "queue_depth": p.queue_depth, "burn": p.burn,
                          "jobs_active": p.jobs_active,
                          "partitioned": p.partitioned,
                          "lease_age_s": round(p.lease_age, 3),
                          "breaker": self.net.breaker_state(p.name)}
                         for p in sorted(peers, key=lambda p: p.name)],
               "jobs": jmap, **self.counters}
        if self.autoscaler is not None:
            out["autoscale"] = self.autoscaler.stats()
        return out

    def shutdown(self) -> None:
        self._stop.set()
        self._poller.join(timeout=5.0)
        if self.autoscaler is not None:
            self.autoscaler.shutdown()
        self.log.log("router.done",
                     wall_s=round(time.time() - self.started_ts, 3),
                     **self.counters)
        self.log.close()


class RouterHandler(BaseHTTPRequestHandler):
    """The proxy shell: tenant-routed submits, job-mapped result/stream/
    abort forwards, the router's own healthz/stats. HTTP/1.1 with explicit
    Content-Length (keep-alive safe), like the serve handler it fronts."""

    protocol_version = "HTTP/1.1"
    server_version = "daccord-router/0.1"

    @property
    def rt(self) -> Router:
        return self.server.router  # type: ignore[attr-defined]

    def log_message(self, fmt, *args):  # noqa: A002
        pass

    def _send(self, code: int, obj=None, body: bytes | None = None,
              ctype: str = "application/json") -> None:
        if body is None:
            body = (json.dumps(obj) + "\n").encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        # end-to-end integrity: recomputed here (not forwarded) because
        # the router re-frames the body it proxies
        self.send_header(netio.BODY_BYTES_HEADER, str(len(body)))
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            self.close_connection = True

    def _read_body(self) -> bytes:
        n = int(self.headers.get("Content-Length", 0) or 0)
        return self.rfile.read(n) if n > 0 else b""

    def _peer_fail(self, peer, e: BaseException):
        """Transport failure talking to the PEER: a retryable 502, plus
        the peer-table verdict. An open breaker is NOT evidence of death
        (the breaker is the evidence-gatherer; healthz decides), so only
        genuine transport failures de-route the peer."""
        self.rt.counters["proxy_errors"] += 1
        self.rt.log.log("router.proxy_error", peer=peer.name,
                        error=f"{type(e).__name__}: {e}"[:200])
        if not isinstance(e, netio.BreakerOpen):
            self.rt.mark_dead(peer)
        return self._send(502, {"error": f"peer {peer.name} unreachable",
                                "peer": peer.name, "retryable": True})

    def _forward(self, peer, method: str, body: bytes | None = None):
        """Proxy + map transport failure to a retryable 502 (the client's
        idempotency key carries exactly-once across the retry)."""
        try:
            code, data, ctype = self.rt.proxy(peer, method, self.path, body,
                                              dict(self.headers))
        except Exception as e:
            return self._peer_fail(peer, e)
        return self._send(code, body=data, ctype=ctype)

    def _job_route(self):
        parts = [p for p in self.path.split("?")[0].split("/") if p]
        if len(parts) >= 3 and parts[0] == "v1" and parts[1] == "jobs":
            return parts[2], (parts[3] if len(parts) > 3 else None)
        return None, None

    def do_POST(self) -> None:  # noqa: N802
        path = self.path.split("?")[0]
        if path == "/v1/jobs":
            raw = self._read_body()
            try:
                body = json.loads(raw) if raw else {}
                if not isinstance(body, dict):
                    raise ValueError("body must be a JSON object")
            except (ValueError, json.JSONDecodeError) as e:
                return self._send(400, {"error": f"bad body: {e}"})
            tenant = str(body.get("tenant", "default"))
            peer = self.rt.route(tenant)
            if peer is None:
                return self._send(503, {"error": "no ready peers",
                                        "retryable": True})
            try:
                # a keyed submit is retry-safe (the fleet dedupes on the
                # journal-backed key); a bare one must surface its reset
                code, data, ctype = self.rt.proxy(
                    peer, "POST", self.path, raw, dict(self.headers),
                    idempotent=bool(body.get("idempotency_key")))
            except Exception as e:
                return self._peer_fail(peer, e)
            if code in (200, 201):
                try:
                    jid = json.loads(data).get("job")
                    if jid:
                        self.rt.note_job(str(jid), peer.name)
                except (ValueError, json.JSONDecodeError):
                    pass
            return self._send(code, body=data, ctype=ctype)
        if path == "/v1/shutdown":
            threading.Thread(target=self._shutdown_later,
                             daemon=True).start()
            return self._send(200, {"state": "draining"})
        self._send(404, {"error": "unknown route"})

    def _shutdown_later(self) -> None:
        self.rt.shutdown()
        self.server.shutdown()  # type: ignore[attr-defined]

    def do_GET(self) -> None:  # noqa: N802
        path = self.path.split("?")[0]
        if path == "/v1/healthz" or path == "/v1/router":
            # the router's own state (daccord-top's ROUTER panel): peer
            # table + ownership cache + spill/scale counters
            return self._send(200, self.rt.stats())
        job_id, sub = self._job_route()
        if job_id is None:
            return self._send(404, {"error": "unknown route"})
        peer = self.rt.peer_for_job(job_id)
        if peer is None:
            return self._send(404, {"error": f"unknown job {job_id!r}"})
        if sub == "stream":
            return self._proxy_stream(peer)
        return self._forward(peer, "GET")

    def do_DELETE(self) -> None:  # noqa: N802
        job_id, _sub = self._job_route()
        if job_id is None:
            return self._send(404, {"error": "unknown route"})
        peer = self.rt.peer_for_job(job_id)
        if peer is None:
            return self._send(404, {"error": f"unknown job {job_id!r}"})
        return self._forward(peer, "DELETE")

    def _proxy_stream(self, peer) -> None:
        """Chunked passthrough of a live FASTA stream, byte-verified. The
        peer's ``X-Daccord-Stream-Bytes`` trailer is checked by the netio
        reader: a torn upstream (peer died mid-copy, injected ``net_torn``)
        means the terminal chunk is NEVER sent to the client — the client
        sees a torn stream and re-fetches, instead of committing a short
        result. A CLIENT disconnect mid-proxy is classified separately
        (``router.client_gone``): the peer is healthy and keeps its
        routability — a tenant's flaky connection must not de-ready a
        peer for everyone else."""
        try:
            status, rhead, chunks = netio.stream(
                peer.url + self.path, "stream",
                timeout=self.rt.cfg.proxy_timeout_s,
                log_event=self.rt._net_event, peer=peer.name)
        except Exception as e:
            self.rt.net.record_fail(peer.name)
            return self._peer_fail(peer, e)
        self.send_response(status)
        self.send_header("Content-Type",
                         rhead.get("Content-Type", "text/x-fasta"))
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header("Trailer", netio.STREAM_BYTES_TRAILER)
        self.end_headers()
        sent = 0
        client_gone = False
        try:
            for data in chunks:
                try:
                    self.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
                except (BrokenPipeError, ConnectionResetError) as e:
                    client_gone = True
                    raise _ClientGone() from e
                sent += len(data)
            # clean end: terminal chunk + end-to-end byte-count trailer,
            # so the CLIENT can verify the full proxied path too
            self.wfile.write(b"0\r\n" + netio.STREAM_BYTES_TRAILER.encode()
                             + b": %d\r\n\r\n" % sent)
            self.rt.net.record_ok(peer.name)
        except _ClientGone:
            # the CLIENT hung up mid-proxy: log it as such and leave the
            # peer's verdict alone — no mark_dead, no breaker strike
            self.rt.log.log("router.client_gone", peer=peer.name,
                            path=self.path.split("?")[0], bytes=sent)
            self.close_connection = True
        except Exception as e:  # noqa: BLE001 — peer-side tear
            self.rt.counters["proxy_errors"] += 1
            self.rt.net.record_fail(peer.name)
            self.rt.log.log("router.proxy_error", peer=peer.name,
                            error=f"{type(e).__name__}: {e}"[:200])
            if not client_gone:
                self.rt.mark_dead(peer, reason="torn_stream")
            # no terminal chunk was written: the client sees a torn
            # stream, never a silently short result
            self.close_connection = True


def start_router(router: Router, host: str = "127.0.0.1", port: int = 0):
    """Bind + start the router front-end on a daemon thread; returns
    ``(httpd, bound_port, thread)`` — the serve tier's start_server shape."""
    httpd = ThreadingHTTPServer((host, port), RouterHandler)
    httpd.daemon_threads = True
    httpd.router = router  # type: ignore[attr-defined]
    t = threading.Thread(target=httpd.serve_forever, daemon=True,
                         name="daccord-router-http")
    t.start()
    return httpd, httpd.server_address[1], t
