"""daccord-serve: the always-on consensus service (serving plane, ISSUE 10).

Everything else in the repo is batch-job shaped; this package is the
long-lived server the ROADMAP north star ("serve heavy traffic from millions
of users") needs: a `daccord-serve` HTTP/JSON front-end accepting concurrent
correction jobs, a **cross-job batcher** multiplexing their window streams
into shared device batches (legal by per-window independence — the same
property the split ladder and the paged router exploit), admission control
and load shedding built on the capacity governor's watermarks, and a warm
state manager keeping compiled programs, ratchet registries, and shape
families resident across jobs.

Layering (ParaFold's CPU-pre / device-compute / CPU-post split, applied at
serving scale):

    http.py       stdlib HTTP/JSON front-end (upload-or-path jobs,
                  streaming results, metrics, graceful shutdown)
    service.py    ConsensusService: job registry, worker pool, ticker
                  (stale-pool flush, pressure shed, idle eviction)
    admission.py  per-tenant quotas + RSS watermarks (admission pauses
                  BEFORE the pipeline's feeder watermarks engage)
    jobs.py       job spec/config (CLI-default parity), the per-job
                  pipeline runner with durable streaming commit
    batcher.py    SolveGroup (shared supervised solve path per solve
                  fingerprint) + the cross-job row pools
    state.py      WarmState: solve-group cache with idle eviction
    journal.py    write-ahead job journal (ISSUE 15): fsync'd lifecycle
                  records, torn-tail-tolerant replay, idempotency-key
                  memory, startup/shutdown compaction — the crash-
                  durability spine behind restart replay and the
                  per-job-lease peer takeover (utils/lease.py)
    router.py     stateless front door (ISSUE 16): rendezvous tenant →
                  peer stickiness over announce-lease discovery + healthz
                  ``ready`` polls, burn/shed spill, verbatim proxying
                  (idempotency keys pass through = exactly-once retries)
    autoscale.py  SLO-burn autoscaler riding the router's poll loop:
                  sustained red burn spawns daccord-serve peers (bounded,
                  cooled-down), idle spawned peers drain gracefully
    aotcache.py   fleet-shared AOT executable cache: serialized compiled
                  programs keyed by registry shape keys + static digest +
                  jax/jaxlib/backend versions — a fresh peer's cold TTFR
                  becomes a deserialize, not a jit compile

Byte contract: every job's FASTA is byte-identical to a solo ``daccord``
run over the same inputs and config — enforced by tests/test_serve.py under
the fault/capacity matrix (device_lost, device_oom bisect of mixed-job
batches, mid-job aborts) and by tests/test_serve_durability.py under the
crash matrix (SIGKILL at every lifecycle point, journal replay, peer
takeover, the 2-process chaos soak).
"""

from .admission import AdmissionConfig, AdmissionController, AdmissionReject
from .aotcache import AotCache
from .autoscale import AutoscaleConfig, Autoscaler
from .batcher import JobAborted, JobSolver, SolveGroup
from .jobs import Job, JobSpec, build_job_config, solve_fingerprint
from .journal import JobJournal, JournalEntry
from .router import Router, RouterConfig
from .service import ConsensusService, ServeConfig
from .state import WarmState

__all__ = [
    "AdmissionConfig", "AdmissionController", "AdmissionReject",
    "AotCache", "AutoscaleConfig", "Autoscaler",
    "ConsensusService", "Job", "JobAborted", "JobJournal", "JobSolver",
    "JobSpec", "JournalEntry", "Router", "RouterConfig", "ServeConfig",
    "SolveGroup", "WarmState", "build_job_config", "solve_fingerprint",
]
