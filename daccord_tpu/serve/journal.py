"""Write-ahead job journal: the serve tier's crash-durability spine (ISSUE 15).

Every job the service admits is recorded here BEFORE any other effect, in an
append-only, per-record-fsync'd jsonl file (``<workdir>/journal.jsonl``).
The lifecycle a job's records trace::

    admitted    spec + tenant charge + optional client idempotency key
    running     a worker claimed it
    progress    per-job pipeline checkpoint landed (emitted reads + durable
                ``out.fasta.part`` bytes — the resume point)
    committing  the FASTA bytes are fsync'd; the publishing rename is next
    committed   out.fasta + manifest durably published
    aborted     client abort (terminal)
    failed      run failed / replay re-admission refused (terminal)
    interrupted bounded-drain shutdown gave up waiting (resumable)
    replayed    a restart re-admitted this orphan through the quota path
    demoted     lease ownership lost mid-run (a peer took the job over)

On restart the service replays the journal (:func:`replay`): terminal jobs
contribute only their idempotency keys; a job with a ``committing`` record
whose part file matches the recorded byte count is FINISHED in place (the
rename + manifest the crash interrupted — no recompute); every other
non-terminal job is an *orphan*, re-admitted through the normal quota path
and re-run — resuming from its per-job checkpoint where one landed.

Torn tails are tolerated exactly like torn manifests (PR 2): a crash can
land mid-append, so an unparseable trailing line is skipped, never fatal —
what was fsync'd before it is the truth. (Mid-file garbage is skipped too,
counted, and surfaced; only the records that parse are trusted.)

The journal COMPACTS at startup (after replay) and shutdown: live jobs keep
their full record chain, terminal jobs collapse to one ``admitted`` +
terminal pair — kept only while they carry an idempotency key, so duplicate
submissions keep answering with the committed job without the file growing
with lifetime job count.

``serve_crash:N`` fault injection lives here by design: the Nth fsync'd
append returns, THEN the process dies hard (``os._exit(137)``) — the
injected crash can never claim durability it doesn't have.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass

from ..utils import aio

#: journal record kinds that end a job's lifecycle
TERMINAL_RECS = ("committed", "aborted", "failed")


@dataclass
class JournalEntry:
    """Replayed per-job state: the last-record-wins fold of one job's chain."""

    job: str
    state: str = "admitted"           # last lifecycle record kind
    tenant: str = "default"
    nbytes: int = 0                   # the admission charge to restore
    spec: dict | None = None          # JobSpec fields (asdict form)
    dir: str | None = None            # jobdir (absolute; foreign on takeover)
    idem: str | None = None           # client idempotency key
    takeover: bool = False            # admitted via peer takeover
    part_bytes: int = 0               # committing: fsync'd part-file bytes
    part_name: str | None = None      # the attempt-private part file those
                                      # bytes live in (basename)
    part_sha: str | None = None       # committing: sha256 of those fsync'd
                                      # bytes — replay/takeover finalize
                                      # refuses a part whose content belies
                                      # the journaled digest (ISSUE 20)

    @property
    def terminal(self) -> bool:
        return self.state in TERMINAL_RECS


class JobJournal:
    """Append-side handle. One per service process; thread-safe (HTTP
    threads, workers, and the ticker all append)."""

    def __init__(self, path: str, faults=None):
        self.path = path
        self.faults = faults
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                           0o644)
        self.appended = 0
        # disk-says-no accounting (ISSUE 17): appends that failed to reach
        # durability (ENOSPC/EIO, real or injected) and the last error text —
        # the service's disk-pressure governor reads both
        self.append_failures = 0
        self.last_error: str | None = None

    def append(self, rec: str, job: str, **fields) -> bool:
        """Durably append one record: the write and fsync complete before
        this returns True — the WRITE-AHEAD contract every state transition
        in the service leans on. The ``serve_crash`` fault fires here, AFTER
        durability, so an injected death never loses a record it claims.

        Returns False when the record did NOT become durable: the closed-fd
        shutdown-drain window (the durable manifest is already the truth),
        or a disk refusal (ENOSPC/EIO — real, or injected via the
        ``@journal`` fault domain). A refusal never raises — the appenders
        are HTTP threads, workers, and the ticker, none of which may die
        for a full volume; the service reads False and enters its
        ``disk_pressure`` state instead."""
        line = json.dumps({"rec": rec, "job": job, "ts": time.time(),
                           **fields}) + "\n"
        with self._lock:
            if self._fd is None:
                return False
            try:
                aio.io_gate("journal", op="append")
                os.write(self._fd, line.encode())
                os.fsync(self._fd)
            except OSError as e:
                # a partial write may have torn the tail; replay tolerates
                # torn lines, so the journal stays replayable either way
                self.append_failures += 1
                self.last_error = f"{type(e).__name__}: {e}"
                return False
            self.appended += 1
        if self.faults is not None and self.faults.serve_crash_check():
            # test-only hard death (see runtime/faults.py serve_crash): the
            # record above is durable; nothing after it is — exactly a
            # SIGKILL landing between syscalls
            os._exit(137)
        return True

    def size_bytes(self) -> int:
        """Current on-disk journal size (0 when unreadable) — the online
        compaction watermark's input."""
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    def compact_online(self) -> dict | None:
        """Compact the LIVE journal in place — the restart-only compaction
        (replay → :func:`compact`) without the restart, triggered by the
        service at a size/free-space watermark so an ENOSPC'd volume can be
        relieved by the journal's own garbage (terminal chains without
        idempotency keys) instead of waiting for an operator bounce.

        Replays from disk under the append lock (disk state IS the truth —
        records that failed to append were never durable), durably rewrites,
        then swaps the append fd to the new file. Returns a summary dict
        (``before``/``after`` bytes, ``kept`` jobs, ``torn`` lines) or None
        when the rewrite itself was refused — the old fd keeps appending,
        nothing is lost, and the caller may retry at the next watermark."""
        with self._lock:
            if self._fd is None:
                return None
            before = self.size_bytes()
            entries, torn = replay(self.path)
            try:
                compact(self.path, entries)
                fd = os.open(self.path,
                             os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
            except OSError:
                return None  # disk still refusing; keep the old fd
            os.close(self._fd)
            self._fd = fd
            return {"before": before, "after": self.size_bytes(),
                    "kept": sum(1 for e in entries.values()
                                if not e.terminal or e.idem),
                    "torn": torn}

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None


def replay(path: str) -> tuple[dict[str, JournalEntry], int]:
    """Fold the journal into per-job :class:`JournalEntry` state.

    Returns ``(entries, torn)``: ``entries`` keyed by job id in first-seen
    order, ``torn`` the count of unparseable lines tolerated (a crash mid-
    append tears at most the tail; anything else is surfaced for the
    sentinel, not trusted)."""
    entries: dict[str, JournalEntry] = {}
    torn = 0
    try:
        with open(path, "rb") as fh:
            raw = fh.read()
    except OSError:
        return entries, 0
    for line in raw.split(b"\n"):
        if not line.strip():
            continue
        try:
            rec = json.loads(line.decode())
        except (json.JSONDecodeError, UnicodeDecodeError):
            torn += 1
            continue
        if not isinstance(rec, dict) or "rec" not in rec or "job" not in rec:
            torn += 1
            continue
        job = str(rec["job"])
        e = entries.get(job)
        if e is None:
            e = entries[job] = JournalEntry(job=job)
        kind = str(rec["rec"])
        b = rec.get("bytes")
        if isinstance(b, (int, float)) and not isinstance(b, bool):
            # any record may carry the durable part-file byte count (the
            # compaction tail does for non-committing states too)
            e.part_bytes = int(b)
        if isinstance(rec.get("part"), str):
            e.part_name = os.path.basename(rec["part"])
        if isinstance(rec.get("sha"), str):
            e.part_sha = rec["sha"]
        if kind == "admitted":
            e.tenant = str(rec.get("tenant", e.tenant))
            e.nbytes = int(rec.get("nbytes", e.nbytes) or 0)
            e.spec = rec.get("spec") if isinstance(rec.get("spec"), dict) \
                else e.spec
            e.dir = rec.get("dir") or e.dir
            e.idem = rec.get("idem") or e.idem
            e.takeover = bool(rec.get("takeover", e.takeover))
            e.state = "admitted"
        elif kind == "progress":
            pass   # refines the resume point; not a state change
        elif kind == "committing":
            e.state = "committing"
        elif kind in ("running", "replayed", "interrupted", "demoted",
                      *TERMINAL_RECS):
            e.state = kind
        # unknown record kinds: forward-compat, folded as a no-op
    return entries, torn


def compact(path: str, entries: dict[str, JournalEntry]) -> None:
    """Durably rewrite the journal from replayed state: live jobs keep an
    ``admitted`` record (plus their resume state), terminal jobs collapse to
    an ``admitted``+terminal pair kept ONLY while they carry an idempotency
    key (the dedupe memory). Without compaction an always-on server's
    journal — and every restart's replay — grows with lifetime job count."""

    def _write(fh) -> None:
        now = time.time()
        for e in entries.values():
            if e.terminal and not e.idem:
                continue
            admitted = {"rec": "admitted", "job": e.job, "ts": now,
                        "tenant": e.tenant, "nbytes": e.nbytes,
                        "spec": e.spec, "dir": e.dir, "idem": e.idem,
                        "takeover": e.takeover}
            fh.write((json.dumps(admitted) + "\n").encode())
            if e.state != "admitted":
                tail = {"rec": e.state, "job": e.job, "ts": now}
                if e.state == "committing" or e.part_bytes:
                    tail["bytes"] = e.part_bytes
                if e.part_name:
                    tail["part"] = e.part_name
                if e.part_sha:
                    tail["sha"] = e.part_sha
                fh.write((json.dumps(tail) + "\n").encode())

    aio.durable_write(path, _write, mode="wb", domain="journal")
