"""ConsensusService: the always-on serving core behind ``daccord-serve``.

Owns the job registry, a bounded worker pool draining the admission queue,
the warm solve-group cache, and a ticker thread doing the housekeeping a
long-lived server needs: stale cross-job pools flush (latency bound), RSS
pressure drives the shed ladder (group batch widths halve under sustained
pressure, restore when it clears), idle groups evict, and the metrics
registry snapshots into the service events sidecar at a bounded cadence.

Telemetry layout (one file per concern, so the strict eventcheck state
machines never interleave):

    <workdir>/serve.events.jsonl      serve.* lifecycle + metrics snapshots
    <workdir>/g<N>.events.jsonl       each solve group's supervisor/governor
                                      stream (sup_*, governor.*, serve.batch)
    <workdir>/jobs/<id>/events.jsonl  the job's own pipeline telemetry
                                      (shard_start, spans, shard_done)
    <workdir>/jobs/<id>/ledger.jsonl  per-window outcome ledger, job-tagged
    <workdir>/journal.jsonl           write-ahead job journal (ISSUE 15):
                                      NOT an events file — fsync'd
                                      lifecycle records replayed at
                                      restart (serve/journal.py); mirrored
                                      into serve.events as serve.journal
    <workdir>/jobs/<id>/progress.json per-job pipeline checkpoint (the
                                      replay/takeover resume point)

All of it passes ``eventcheck --strict`` and ``daccord-trace --check`` — the
serve smoke in tools_pounce.sh enforces that before any chip time.

Latency is a first-class metric here (the axis ISSUE 10 opens): per-job
queue/first-result/total latencies feed histograms whose p50/p95/p99 ride
every metrics snapshot and the durable rollup committed at shutdown.
"""

from __future__ import annotations

import itertools
import json
import os
import queue
import threading
import time
from dataclasses import dataclass, field

from ..utils.obs import JsonlLogger, MetricsRegistry
from .admission import AdmissionConfig, AdmissionController
from .batcher import GroupConfig, SolveGroup
from .jobs import ABORTED, DONE, FAILED, QUEUED, RUNNING, Job, JobSpec, run_job
from .state import WarmState


class _LockedLogger(JsonlLogger):
    """JsonlLogger safe for concurrent writers (HTTP threads, workers, the
    ticker): the timestamp is taken and the line buffered under one lock, so
    ``t`` stays monotonic per file — the strict eventcheck contract."""

    def __init__(self, path: str | None = None, **kw):
        super().__init__(path, **kw)
        self._wlock = threading.Lock()

    def log(self, event: str, **fields) -> None:
        with self._wlock:
            super().log(event, **fields)

    def close(self) -> None:
        with self._wlock:
            super().close()


@dataclass
class ServeConfig:
    workdir: str = "daccord-serve"
    backend: str = "native"          # resolved engine (native|cpu|tpu)
    backend_explicit: bool = True    # the operator named it (hp default rule)
    batch: int = 512                 # merged dispatch width
    workers: int = 2                 # concurrent job threads
    ladder_mode: str = "fused"       # fused | split (JAX groups only)
    paged: bool = False              # paged wire format for merged batches
    page_len: int = 16
    mesh: int = 0                    # mesh-backed solve groups (JAX groups
                                     # only): merged cross-job batches shard
                                     # over the first N local devices — N x
                                     # the continuous-batching width per
                                     # warm compile; the solve fingerprint
                                     # includes N so mesh and single-device
                                     # groups never share warm state
    use_pallas: bool = False
    flush_lag_s: float = 0.05        # stale cross-job pool flush deadline
    idle_evict_s: float = 600.0      # warm-group TTL
    job_retention_s: float = 3600.0  # terminal jobs leave the in-memory
                                     # registry (and GET /v1/jobs) this long
                                     # after finishing; durable results stay
                                     # on disk under jobs/<id>/. 0 = keep
                                     # forever (tests); an always-on server
                                     # must bound registry growth
    metrics_snapshot_s: float = 30.0
    shed_max_levels: int = 3         # batch-ladder floor under pressure
    # SLO burn tracking (ISSUE 13): rolling p99 job latency over
    # slo_window_s compared against the p99 target. burn = p99/target;
    # crossing slo_shed_burn drives the batch-width shed ladder BEFORE the
    # target is breached (burn >= 1 is the breach the sentinel flags), and
    # dropping below slo_clear_burn releases the slo-held shed rung.
    # 0 = tracking off.
    slo_p99_s: float = 0.0
    slo_window_s: float = 60.0
    slo_shed_burn: float = 0.8
    slo_clear_burn: float = 0.5
    # crash-durable tier (ISSUE 15): the write-ahead job journal + per-job
    # pipeline checkpoints + (optional) peer lease takeover
    journal: bool = True             # fsync'd WAL under <workdir>/journal.jsonl
    checkpoint_reads: int = 16       # per-job progress checkpoint stride
                                     # (emitted reads between durable
                                     # progress manifests; 0 = off — a
                                     # replayed job then re-runs from its
                                     # first read, still byte-identical)
    peer_dir: str | None = None      # shared-FS root for per-job lease files
                                     # (leases/ beneath it): serve processes
                                     # pointing at the SAME peer_dir form a
                                     # takeover group — any of them finishes
                                     # a dead peer's journaled jobs. None =
                                     # solo durability (journal replay only).
                                     # Peers' WORKDIR BASENAMES must be
                                     # unique within a group (the stable
                                     # lease namespace); a live collision is
                                     # refused at submit (lease_conflict)
    peer_name: str = ""              # lease holder identity; default
                                     # <workdir-basename>:<pid>
    lease_ttl_s: float = 15.0        # older per-job lease is stale (takeover)
    heartbeat_s: float = 1.0         # lease renewal + takeover-scan cadence
    aot_dir: str | None = None       # fleet-shared AOT executable cache
                                     # (ISSUE 16, serve/aotcache.py): jitted
                                     # solve groups load serialized
                                     # executables from / publish them to
                                     # this shared-FS dir, so a freshly
                                     # spawned peer answers its first job
                                     # warm. Conventionally
                                     # <peer_dir>/aotcache (the serve CLI
                                     # defaults it there). None = off
    audit_rate: float | None = None  # sampled shadow verification for the
                                     # solve groups (ISSUE 20); None = env
                                     # DACCORD_AUDIT_RATE (1/64), 0 = off
    drain_deadline_s: float = 0.0    # bounded graceful shutdown: >0 means a
                                     # drain that outlives this many seconds
                                     # journal-marks in-flight jobs
                                     # INTERRUPTED (resumable on restart)
                                     # and shutdown reports unclean (the
                                     # serve CLI exits nonzero). 0 = legacy
                                     # unbounded-ish drain (timeout_s)
    # disk-pressure governor (ISSUE 17). The free-bytes admission
    # watermarks live on AdmissionConfig (disk_soft_mb / disk_hard_mb);
    # watch_dir defaults to the serve workdir at construction.
    journal_compact_mb: float = 64.0 # ONLINE journal compaction triggers
                                     # when journal.jsonl reaches this size
                                     # (or the hard free-space watermark
                                     # fires): the restart-only compaction
                                     # without the restart, so a filling
                                     # volume is relieved by the journal's
                                     # own garbage. 0 = size trigger off
    lease_grace_beats: int = 3       # consecutive failed lease renewals
                                     # (EIO-class, real or injected)
                                     # tolerated before a holder self-
                                     # demotes: one shared-FS hiccup must
                                     # not abort healthy in-flight work,
                                     # but a holder that cannot prove
                                     # liveness for this many heartbeats
                                     # stands down before the TTL lets a
                                     # peer steal the lease mid-commit
    admission: AdmissionConfig = field(default_factory=AdmissionConfig)
    events_path: str | None = None   # default: <workdir>/serve.events.jsonl

    def group_ladder_mode(self) -> str:
        # the native engine escalates per window on host: stream routing
        # (and paging) are JAX-ladder concepts
        return "fused" if self.backend == "native" else self.ladder_mode

    def group_mesh(self) -> int:
        # same rule for the mesh: a device-mesh group is a JAX-ladder concept
        return 0 if self.backend == "native" else (self.mesh or 0)


class ConsensusService:
    def __init__(self, cfg: ServeConfig):
        from ..runtime.faults import FaultPlan

        self.cfg = cfg
        os.makedirs(cfg.workdir, exist_ok=True)
        os.makedirs(os.path.join(cfg.workdir, "jobs"), exist_ok=True)
        if not cfg.admission.watch_dir:
            # the free-bytes watermarks read the serve volume by default
            # (they stay off until disk_soft_mb/disk_hard_mb are set)
            cfg.admission.watch_dir = cfg.workdir
        ev = cfg.events_path or os.path.join(cfg.workdir,
                                             "serve.events.jsonl")
        self.events = _LockedLogger(ev, buffer_lines=16, flush_s=1.0)
        self.metrics = MetricsRegistry()
        self.faults = FaultPlan.from_env()
        self.admission = AdmissionController(cfg.admission, log=self.events,
                                             faults=self.faults)
        self.warm = WarmState(cfg.idle_evict_s, log=self.events)
        self.jobs: dict[str, Job] = {}
        self._jobs_lock = threading.Lock()
        # crash-durable tier (ISSUE 15): stable service identity (lease file
        # namespace + foreign job keys), the per-job lease registry, and the
        # client idempotency-key map (rebuilt from the journal at replay)
        import socket

        self.service_id = os.path.basename(
            os.path.abspath(cfg.workdir)) or "serve"
        # holder identity includes the hostname (fleet convention): two
        # hosts' processes must never read each other's leases as their own
        # — `still_owns` is the double-commit gate and keys on this string
        self.peer = cfg.peer_name or \
            f"{self.service_id}@{socket.gethostname()}:{os.getpid()}"
        self._lease_lock = threading.Lock()
        self._owned_leases: dict[str, str] = {}   # job id -> lease path
        self._lease_grace: dict[str, int] = {}    # job id -> consecutive
                                                  # failed renew beats
        self._idem: dict[str, str | None] = {}    # idem key -> job id
        # front door (ISSUE 16): the announce lease (peer discovery for the
        # router — <peer_dir>/peers/<service_id>.lease carrying our URL),
        # readiness (journal replay finished AND no group build in flight),
        # and the tenant -> group-key map behind the evict-vs-route guard
        self._announce_url: str | None = None
        self._announce_path: str | None = None
        self._replay_done = not cfg.journal
        self._tenant_keys: dict[str, set] = {}
        self.clean = True                         # last shutdown's verdict
        # resume the id sequence past any job dirs already in the (durable)
        # workdir — or named by the journal (a post-admit crash can journal
        # an id whose spool dir never landed): a restarted server must never
        # reuse jNNNNN — the old run's committed out.fasta would be served
        # as (or clobbered by) the new job's
        last = 0
        for name in os.listdir(os.path.join(cfg.workdir, "jobs")):
            if name.startswith("j") and name[1:].isdigit():
                last = max(last, int(name[1:]))
        self._journal_path = os.path.join(cfg.workdir, "journal.jsonl")
        replayed = {}
        torn = 0
        if cfg.journal:
            from .journal import replay as journal_replay

            replayed, torn = journal_replay(self._journal_path)
            for jid in replayed:
                short = jid.rsplit(".", 1)[-1]
                if short.startswith("j") and short[1:].isdigit():
                    last = max(last, int(short[1:]))
        self._job_ids = itertools.count(last + 1)
        self._group_ids = itertools.count(0)
        self._queue: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        self._shed = 0
        # SLO burn state (ISSUE 13): finished-job latencies inside the
        # rolling window, the slo-held shed rung, and the last emitted burn
        # band (serve.slo emits on band changes, not every tick)
        from collections import deque

        self._lat_window: deque = deque()
        # guards window iteration in _slo_tick against concurrent worker
        # appends (deque append is atomic; iterating one mid-append raises)
        self._lat_lock = threading.Lock()
        self._slo_shed = 0
        self._slo_band: int | None = None
        self._slo_burn_last = 0.0    # last computed burn (healthz: the
                                     # router's spill + autoscaler signal)
        # lifetime peaks (ISSUE 13 satellite): the rollup must answer "how
        # bad did it GET", not just "how bad is it now"
        self._peak_rss_mb = 0.0
        self._peak_queue_depth = 0
        # disk-pressure governor state (ISSUE 17): what latched the 507
        # state (journal refusal vs free-bytes watermark) and the online-
        # compaction rate limiter
        self._disk_latch_src: str | None = None
        self._last_compact = 0.0
        # saturation profiler (ISSUE 14): the serve-plane verdict denominator
        # is DEMAND wall (ticker-sampled time with >= 1 job queued/running),
        # not uptime — an always-on server that simply has no traffic is
        # balanced, not host_feeder-starved
        self._demand_s = 0.0
        self._last_demand_tick = time.time()
        self._verdict = "balanced"
        self.started_ts = time.time()
        self.log_event("serve.start", workdir=cfg.workdir,
                       backend=cfg.backend, batch=int(cfg.batch),
                       workers=int(cfg.workers), pid=os.getpid())
        # the write-ahead journal opens AFTER replay folded (and compacted)
        # the previous incarnation's records — compaction rewrites the file
        # via rename, so it must finish before the append fd is taken
        self.journal = None
        if cfg.journal:
            from .journal import JobJournal, compact

            compact(self._journal_path, replayed)
            self.journal = JobJournal(self._journal_path, faults=self.faults)
            self._replay(replayed, torn)
        self._replay_done = True
        self._workers = [
            threading.Thread(target=self._worker_loop, daemon=True,
                             name=f"daccord-serve-worker-{i}")
            for i in range(max(1, cfg.workers))]
        for t in self._workers:
            t.start()
        self._ticker = threading.Thread(target=self._tick_loop, daemon=True,
                                        name="daccord-serve-ticker")
        self._ticker.start()

    # ------------------------------------------------------------------
    # plumbing used by jobs.run_job
    # ------------------------------------------------------------------

    def log_event(self, event: str, **fields) -> None:
        self.events.log(event, **fields)

    def build_group(self, key: str, profile, cfg) -> SolveGroup:
        """Factory handed to WarmState.acquire: one solve group with its
        own events sidecar (the strict state-machine lint needs one
        supervisor stream per file)."""
        scfg = self.cfg
        name = f"g{next(self._group_ids)}"
        glog = _LockedLogger(os.path.join(scfg.workdir,
                                          f"{name}.events.jsonl"),
                             buffer_lines=16, flush_s=1.0)
        gcfg = GroupConfig(backend=scfg.backend, batch=scfg.batch,
                           ladder_mode=scfg.group_ladder_mode(),
                           paged=scfg.paged and scfg.backend != "native",
                           page_len=scfg.page_len,
                           mesh=scfg.group_mesh(),
                           use_pallas=scfg.use_pallas,
                           shed_levels=self._shed,
                           aot_dir=scfg.aot_dir,
                           audit_rate=scfg.audit_rate)
        g = SolveGroup(key, profile, cfg, gcfg, log=glog, name=name)
        self.log_event("serve.group", group=name, key=key[:16],
                       backend=scfg.backend, batch=int(scfg.batch))
        return g

    def note_tenant_key(self, tenant: str, key: str) -> None:
        """Record that ``tenant``'s jobs solve on group ``key`` (called by
        run_job at acquire time) and stamp the route — the tenant→warmth
        map behind the evict-vs-route guard (ISSUE 16)."""
        with self._jobs_lock:
            self._tenant_keys.setdefault(tenant, set()).add(key)
        self.warm.note_route(key)

    def observe_latency(self, job: Job) -> None:
        """Per-job latency histograms (p50/p95/p99 ride the snapshots)."""
        h = self.metrics.histogram
        if job.started_ts:
            h("job_queue_s").observe(job.started_ts - job.submitted_ts)
        if job.first_emit_ts:
            h("job_first_result_s").observe(
                job.first_emit_ts - job.submitted_ts)
        if job.done_ts:
            h("job_latency_s").observe(job.done_ts - job.submitted_ts)
            if self.cfg.slo_p99_s:
                # rolling SLO window (pruned by the ticker's slo pass)
                with self._lat_lock:
                    self._lat_window.append(
                        (job.done_ts, job.done_ts - job.submitted_ts))
        if job.done_ts and job.windows and job.started_ts:
            run_s = max(job.done_ts - job.started_ts, 1e-9)
            self.metrics.gauge("last_job_windows_per_sec").set(
                job.windows / run_s)

    # ------------------------------------------------------------------
    # crash durability (ISSUE 15): journal, replay, per-job leases
    # ------------------------------------------------------------------

    def journal_mark(self, rec: str, job_id: str, **fields) -> None:
        """Durably append one lifecycle record (no-op with the journal off)
        and mirror it into the events stream (``serve.journal``) + the
        ``journal_records`` counter, so recovery is observable without
        reading the journal file itself.

        A disk refusal (ENOSPC/EIO, real or injected) never raises — the
        appenders are HTTP threads, workers, and the ticker. It is counted,
        surfaced as an ``io.fault`` event, and latches the admission
        ``disk_pressure`` state (507-style refusals) until the volume
        proves writable again (``_disk_tick``'s probe)."""
        j = self.journal   # racing shutdown's None-swap: read once
        if j is None:
            return
        before = j.append_failures
        if not j.append(rec, job_id, **fields):
            if j.append_failures > before:
                # a disk refusal, not the closed-fd shutdown-drain window
                self.log_event("io.fault", domain="journal", op="append",
                               error=str(j.last_error or "?")[:200])
                self.metrics.counter("journal_append_failures").inc()
                self._enter_disk_pressure(
                    "journal", j.last_error or "append refused")
            return
        self.metrics.counter("journal_records").inc()
        self.log_event("serve.journal", rec=rec, job=job_id)

    def _lease_file(self, job_id: str) -> str | None:
        """The per-job lease path under the peer dir (None with takeover
        off). Local ids (jNNNNN) are namespaced by this service's identity;
        a foreign key (``<service>.<jobid>``, from a takeover) already is."""
        if not self.cfg.peer_dir:
            return None
        key = job_id if "." in job_id else f"{self.service_id}.{job_id}"
        return os.path.join(self.cfg.peer_dir, "leases", f"{key}.lease")

    def _claim_job_lease(self, job, nbytes: int,
                         idem: str | None = None) -> bool:
        """Claim (or re-claim) the job's lease with the full job descriptor
        as payload, so a peer takeover is self-contained — the taker needs
        nothing from this process but the lease file and the shared-FS
        jobdir. Returns False ONLY when a live claim race was lost (a peer
        owns the job now); True with takeover off (no lease to lose)."""
        import dataclasses

        from ..utils import lease

        path = self._lease_file(job.id)
        if path is None:
            return True
        short = job.id.rsplit(".", 1)[-1]
        svc = job.id.rsplit(".", 1)[0] if "." in job.id else self.service_id
        extra = {"service": svc, "job": short,
                 "jobdir": os.path.abspath(job.dir),
                 "tenant": job.tenant, "nbytes": int(nbytes),
                 "spec": dataclasses.asdict(job.spec), "idem": idem}
        ok, _ = lease.claim(path, self.peer, self.cfg.lease_ttl_s,
                            extra=extra)
        if ok:
            with self._lease_lock:
                self._owned_leases[job.id] = path
        return ok

    def still_owns(self, job_id: str) -> bool:
        """Pre-commit ownership re-check (the fencing-free protocol's last
        gate): True when this process still holds the job's lease — or
        takeover is off entirely. A long GIL-bound solve can stall the
        heartbeat past the TTL; if a peer claimed the lease meanwhile, the
        PEER owns the commit and the runner must stand down rather than
        double-commit (the sub-heartbeat window that remains is the
        protocol's documented inherent race, now read-to-rename instead of
        solve-length)."""
        if not self.cfg.peer_dir:
            return True
        from ..utils import lease

        path = self._lease_file(job_id)
        info, lstat = lease.read_result(path)
        for i in range(3):
            # an EIO-class read hiccup here is NOT ownership loss — failing
            # the gate on it would strand a finished solve (stand down with
            # no taker to finish the job). Bounded re-read, like the
            # heartbeat's renewal grace; absent/torn/foreign stay decisive.
            if lstat != "error":
                break
            time.sleep(0.01 * (2 ** i))
            info, lstat = lease.read_result(path)
        return info is not None and info.get("host") == self.peer

    def release_job_lease(self, job_id: str) -> None:
        """Holder-checked release of a finished job's lease (no-op when we
        hold none — e.g. solo mode, or ownership already lost to a taker)."""
        from ..utils import lease

        with self._lease_lock:
            path = self._owned_leases.pop(job_id, None)
        self._lease_grace.pop(job_id, None)
        if path is not None:
            lease.release(path, host=self.peer)

    def _durable_status(self, job_id: str) -> dict | None:
        """A committed job's status straight from its durable manifest —
        how an idempotent resubmission is answered after the in-memory
        registry pruned (or never held, across a restart) the job."""
        p = os.path.join(self.cfg.workdir, "jobs", job_id, "manifest.json")
        try:
            with open(p) as fh:
                st = json.load(fh)
        except (OSError, json.JSONDecodeError):
            return None
        return st if isinstance(st, dict) else None

    def _replay(self, entries: dict, torn: int) -> None:
        """Fold the previous incarnation's journal back into live state
        (called once, before the workers start):

        - terminal jobs contribute their idempotency keys only;
        - orphans whose jobdir already holds a committed manifest (a peer
          — or the pre-crash rename — finished them) are journal-marked
          committed, never re-run;
        - a ``committing`` orphan whose part file matches the recorded
          byte count is FINISHED in place (rename + manifest), no recompute;
        - an orphan whose lease a live peer holds becomes a *watch* job
          (the peer is running it; the ticker flips it DONE when the
          manifest lands, or re-admits it if the lease goes stale);
        - every other orphan is re-admitted through the NORMAL quota path
          (an admission refusal journals ``failed``) and re-queued,
          resuming from its per-job checkpoint.
        """
        from ..utils import lease
        from ..utils.aio import durable_write
        from .jobs import JobSpec

        n_orphan = n_finished = n_watch = n_failed = 0
        for e in entries.values():
            if e.terminal:
                if e.idem:
                    self._idem[e.idem] = e.job
                continue
            if e.idem:
                self._idem[e.idem] = e.job
            jobdir = e.dir or os.path.join(self.cfg.workdir, "jobs", e.job)
            manifest = os.path.join(jobdir, "manifest.json")
            part = os.path.join(jobdir, "out.fasta.part")
            def _register_done(entry, jdir):
                # recovered-to-done jobs join the registry so clients keep
                # GETting status/result across the restart (pruned on the
                # normal retention schedule)
                if entry.spec is None:
                    return
                sp = JobSpec(**entry.spec)
                sp.nbytes = entry.nbytes
                jb = Job(id=entry.job, tenant=entry.tenant, spec=sp,
                         dir=jdir, state=DONE)
                jb.done_ts = time.time()
                with self._jobs_lock:
                    self.jobs.setdefault(entry.job, jb)

            if os.path.exists(manifest):
                # finished by a peer (or this process, pre-crash): the
                # durable commit is the truth — record it, never re-run.
                # A NON-terminal entry means the committer died between its
                # serve.commit flush-through and the committed journal
                # append (or a peer committed): re-emit the recovery form
                # (fragments=-1) so every done job keeps >= 1 commit event
                # — terminal entries already logged theirs (event-before-
                # journal ordering in run_job), so re-emitting would double
                self.journal_mark("committed", e.job, by="manifest")
                try:
                    fb = int(json.load(open(manifest)).get("fasta_bytes", 0))
                except (OSError, json.JSONDecodeError, ValueError,
                        TypeError):
                    fb = 0
                self.log_event("serve.commit", job=e.job, fragments=-1,
                               bytes=fb)
                _register_done(e, jobdir)
                n_finished += 1
                continue
            if e.spec is None:
                self.journal_mark("failed", e.job, error="replay: no spec")
                n_failed += 1
                continue
            spec = JobSpec(**e.spec)
            spec.nbytes = e.nbytes
            job = Job(id=e.job, tenant=e.tenant, spec=spec, dir=jobdir)
            lp = self._lease_file(e.job)
            if lp is not None:
                info = lease.read(lp)
                age = lease.stale_s(lp)
                fresh_foreign = (info is not None
                                 and info.get("host") != self.peer
                                 and age is not None
                                 and age <= self.cfg.lease_ttl_s)
                # exactly-once gate — BEFORE any recovery action, including
                # the mid-commit finalize below: the lease CLAIM decides who
                # recovers the orphan. A fresh foreign lease, or losing the
                # claim race on a stale one to a peer mid-takeover, means
                # the job is someone else's now: watch their manifest,
                # never run (or finalize) it ourselves.
                if fresh_foreign or not self._claim_job_lease(
                        job, e.nbytes, idem=e.idem):
                    job.state, job.watch = RUNNING, True
                    with self._jobs_lock:
                        self.jobs[e.job] = job
                    n_watch += 1
                    continue
            if e.part_name:
                # attempts write private part files; the committing record
                # names the one whose bytes are fsync'd
                part = os.path.join(jobdir, e.part_name)
            part_ok = (e.state == "committing" and os.path.exists(part)
                       and os.path.getsize(part) >= e.part_bytes
                       and e.part_bytes > 0)
            if part_ok and e.part_sha:
                # content verification (ISSUE 20): the journaled committing
                # digest must match the fsync'd prefix on disk — a part file
                # silently corrupted between crash and recovery falls
                # through to orphan re-admission (re-solve), never to a
                # publishing rename of wrong bytes
                from ..utils.obs import sha256_file

                if sha256_file(part, limit=e.part_bytes) != e.part_sha:
                    self.log_event(
                        "io.fault", domain="manifest", op="finalize",
                        error=f"job {e.job}: part digest mismatches the "
                              "journaled committing record"[:200])
                    part_ok = False
            if part_ok:
                # the crash landed between the FASTA fsync and the
                # publishing rename: every byte is durable — finish the
                # commit in place, byte-identical, zero recompute
                os.truncate(part, e.part_bytes)
                fasta = os.path.join(jobdir, "out.fasta")
                os.replace(part, fasta)
                durable_write(manifest,
                              lambda mh, j=e.job, f=fasta: json.dump(
                                  {"job": j, "state": "done", "fasta": f,
                                   "fasta_bytes": os.path.getsize(f),
                                   "recovered": True}, mh),
                              mode="wt", domain="manifest")
                self.journal_mark("committed", e.job, by="replay")
                self.log_event("serve.commit", job=e.job, fragments=-1,
                               bytes=os.path.getsize(fasta))
                self.release_job_lease(e.job)
                _register_done(e, jobdir)
                n_finished += 1
                continue
            try:
                self.admission.admit(e.tenant, e.nbytes, job=e.job)
            except Exception as exc:
                if lp is not None:
                    # same rule as the takeover scan: no headroom here
                    # means hand the lease back for a peer WITH headroom —
                    # a quota-tight restart must not convert recoverable
                    # orphans into permanent failures
                    self.release_job_lease(e.job)
                    job.state, job.watch = RUNNING, True
                    with self._jobs_lock:
                        self.jobs[e.job] = job
                    n_watch += 1
                    continue
                self.journal_mark("failed", e.job,
                                  error=f"replay admission: {exc}"[:200])
                n_failed += 1
                continue
            with self._jobs_lock:
                self.jobs[e.job] = job
            self.journal_mark("replayed", e.job)
            self.metrics.counter("replay_orphans").inc()
            n_orphan += 1
            self._queue.put(e.job)
        if entries or torn:
            self.log_event("serve.replay", jobs=len(entries),
                           orphans=n_orphan, finished=n_finished,
                           watch=n_watch, failed=n_failed, torn=torn)

    # ------------------------------------------------------------------
    # front-end API (HTTP layer calls these)
    # ------------------------------------------------------------------

    @staticmethod
    def _estimate_bytes(body: dict) -> int:
        """Admission charge for a submission BEFORE anything is spooled or
        scanned: path inputs by on-disk size, uploads by decoded base64
        size. Admission must run on this estimate first — spooling or
        scanning an over-quota tenant's input would let rejected requests
        burn disk and CPU the quota exists to protect. A Dazzler ``.db``
        is a tiny stub whose real payload lives in the hidden
        ``.<name>.idx``/``.bps`` siblings — charge those too, or a
        multi-GB DB would bill as a few hundred bytes and the byte quota
        would be toothless."""
        files = body.get("files")
        if isinstance(files, dict):
            return sum(len(v) * 3 // 4 for v in files.values()
                       if isinstance(v, str))
        n = 0
        for key in ("db", "las"):
            p = body.get(key)
            if not isinstance(p, str):
                continue
            if not os.path.exists(p) and os.path.exists(p + ".db"):
                p = p + ".db"
            if os.path.exists(p):
                n += os.path.getsize(p)
            if key == "db":
                from ..formats.dazzdb import _db_stems

                try:
                    d, stem = _db_stems(p)
                except Exception:
                    continue
                for ext in (".idx", ".bps", ".names"):
                    h = os.path.join(d, f".{stem}{ext}")
                    if os.path.exists(h):
                        n += os.path.getsize(h)
        return n

    def submit(self, body: dict) -> dict:
        """Admit + enqueue one job; returns its status dict. Raises
        ValueError (bad spec / failed ingest validation → 400) or
        AdmissionReject (→ 429/503). Admission is charged FIRST, on the
        pre-spool byte estimate; any later refusal releases the charge and
        removes the job's spool directory, so rejected requests leave no
        disk residue.

        ``idempotency_key`` (ISSUE 15): a client that lost its connection
        mid-submit (the server crashed after journaling ADMITTED but before
        answering) retries with the same key and gets the EXISTING job —
        whatever state it reached, including done — instead of a second
        run. The key rides the journal, so dedupe survives restarts."""
        if not isinstance(body, dict):
            raise ValueError("body must be a JSON object")
        body = dict(body)
        idem = body.pop("idempotency_key", None)
        if idem is not None and (not isinstance(idem, str) or not idem):
            raise ValueError("idempotency_key must be a non-empty string")
        if idem is not None:
            from .admission import AdmissionReject

            with self._jobs_lock:
                seen = self._idem.get(idem, "")
                if seen is None:
                    # a concurrent submit with the same key is mid-admission
                    raise AdmissionReject("idempotent_in_flight",
                                          f"key {idem!r} is being admitted",
                                          retryable=True)
                if not seen:
                    self._idem[idem] = None   # reserve
            if seen:
                # outside the jobs lock: status() takes it too
                st = self.status(seen) or self._durable_status(seen)
                if st is not None:
                    self.metrics.counter("idempotent_hits").inc()
                    return {**st, "idempotent": True}
                # journaled key whose job left no trace (failed replay):
                # run it fresh under the same key. Compare-and-set the
                # reservation — two concurrent traceless retries must not
                # both win (the loser gets the retryable 429)
                with self._jobs_lock:
                    if self._idem.get(idem) != seen:
                        raise AdmissionReject(
                            "idempotent_in_flight",
                            f"key {idem!r} is being admitted",
                            retryable=True)
                    self._idem[idem] = None
        try:
            return self._submit_new(body, idem)
        except BaseException:
            if idem is not None:
                with self._jobs_lock:
                    if self._idem.get(idem) is None:
                        del self._idem[idem]
            raise

    def _submit_new(self, body: dict, idem: str | None) -> dict:
        job_id = f"j{next(self._job_ids):05d}"
        jobdir = os.path.join(self.cfg.workdir, "jobs", job_id)
        tenant = str(body.get("tenant", "default"))
        charged = self._estimate_bytes(body)
        self.admission.admit(tenant, charged, job=job_id)
        try:
            spec = JobSpec.from_json(body, jobdir)
            # release() must mirror the admitted charge exactly
            spec.nbytes = charged
            # PR-2 ingest gate AT ADMISSION: a strict-policy job with
            # integrity violations is refused here with the structured
            # report — it never reaches a worker
            if spec.ingest_policy == "strict":
                from ..formats.dazzdb import read_db
                from ..formats.ingest import IngestError, scan_with_db
                from ..formats.las import LasFile

                try:
                    rep = scan_with_db(read_db(spec.db, strict=True),
                                       LasFile(spec.las), None, None)
                except (IngestError, ValueError, OSError) as e:
                    raise ValueError(f"ingest validation failed: {e}")
                if rep.issues:
                    first = rep.issues[0]
                    raise ValueError(
                        f"ingest validation: {len(rep.issues)} issue(s); "
                        f"first: {first.kind} at byte {first.offset}")
        except Exception:
            import shutil

            self.admission.release(tenant, charged)
            shutil.rmtree(jobdir, ignore_errors=True)
            raise
        os.makedirs(jobdir, exist_ok=True)
        job = Job(id=job_id, tenant=tenant, spec=spec, dir=jobdir)
        with self._jobs_lock:
            self.jobs[job_id] = job
            if idem is not None:
                self._idem[idem] = job_id
        # WRITE-AHEAD: the admitted record (spec + charge + idempotency
        # key) is durable before the job is queued or the client answered —
        # a crash from here on is recoverable by replay
        import dataclasses

        self.journal_mark("admitted", job_id, tenant=tenant,
                          nbytes=int(spec.nbytes),
                          spec=dataclasses.asdict(spec),
                          dir=os.path.abspath(jobdir), idem=idem)
        if not self._claim_job_lease(job, spec.nbytes, idem=idem):
            # a FRESH job's lease already exists and is live: another
            # service in the peer group shares our workdir basename (the
            # lease namespace) and minted the same id. Running unleased
            # would dodge every exactly-once gate — refuse loudly instead;
            # the operator must give peers distinct workdir basenames.
            from .admission import AdmissionReject

            import shutil

            with self._jobs_lock:
                self.jobs.pop(job_id, None)
                if idem is not None and self._idem.get(idem) == job_id:
                    del self._idem[idem]
            self.admission.release(tenant, spec.nbytes)
            # the refused job's spool is OURS (the holder has its own
            # workdir) — keeping it would strand tenant bytes forever
            shutil.rmtree(jobdir, ignore_errors=True)
            self.journal_mark("failed", job_id, error="lease conflict")
            raise AdmissionReject(
                "lease_conflict",
                f"lease for {self.service_id}.{job_id} is held by another "
                "service — peer-group workdir basenames must be unique",
                retryable=False)
        self.metrics.counter("jobs_submitted").inc()
        # evict-vs-route guard (ISSUE 16): a submit IS a route landing —
        # stamp every group key this tenant has solved on, so the idle
        # sweep cannot evict the group the router's stickiness sent this
        # job to while its profile/fingerprint is still being computed
        with self._jobs_lock:
            keys = list(self._tenant_keys.get(tenant, ()))
        for k in keys:
            self.warm.note_route(k)
        self.log_event("serve.job", job=job_id, state=QUEUED,
                       tenant=spec.tenant)
        self._queue.put(job_id)
        return job.status()

    def status(self, job_id: str) -> dict | None:
        with self._jobs_lock:
            job = self.jobs.get(job_id)
        return None if job is None else job.status()

    def abort(self, job_id: str, reason: str = "client") -> bool:
        with self._jobs_lock:
            job = self.jobs.get(job_id)
        if job is None or job.state in (DONE, FAILED, ABORTED):
            return False
        if job.watch:
            # a peer owns and runs this job: an abort here could not stop
            # it (and setting the local abort_event would be silently
            # dropped on a takeover reclaim) — refuse honestly (409)
            # rather than claim an abort nothing will honor
            return False
        job.abort_event.set()
        # a QUEUED job aborts synchronously: its quota charge releases NOW
        # (a tenant cancelling its backlog must get its slots back without
        # waiting for a worker to churn to each cancelled job) — the
        # worker loop skips already-terminal jobs when it dequeues them
        with self._jobs_lock:
            was_queued = job.state == QUEUED
            if was_queued:
                job.state = ABORTED
                job.done_ts = time.time()
        if was_queued:
            self.admission.release(job.tenant, job.spec.nbytes)
            self.metrics.counter("jobs_aborted").inc()
            self.journal_mark("aborted", job_id, reason=reason)
            self.release_job_lease(job_id)
        # otherwise outcome counting happens ONCE in the worker loop
        # (jobs_<state>); counting the request here too would double-bill
        self.log_event("serve.abort", job=job_id, reason=reason)
        return True

    def wait(self, job_id: str, timeout_s: float = 300.0) -> dict | None:
        """Poll a job to a terminal state (HTTP ?wait=1)."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            st = self.status(job_id)
            if st is None or st["state"] in (DONE, FAILED, ABORTED):
                return st
            time.sleep(0.02)
        return self.status(job_id)

    def health(self) -> dict:
        """Liveness snapshot that takes NO SolveGroup lock: the group lock
        is held across real device solves (a first-batch jit compile runs
        minutes on TPU), and a liveness probe that queued behind it would
        time out and get a perfectly healthy server killed by its
        orchestrator. Only the (briefly-held) jobs lock is touched; the
        per-group busy flags come from a try-lock (``SolveGroup.busy`` —
        never a blocking acquire), and queue depth is a lock-free qsize.
        The on-call triage fields (ISSUE 13): uptime, queue depth, and
        WHICH group is mid-solve when latency spikes."""
        from ..runtime.governor import host_rss_mb
        from ..utils.obs import disk_free_mb

        with self._jobs_lock:
            states: dict[str, int] = {}
            for j in self.jobs.values():
                states[j.state] = states.get(j.state, 0) + 1
        with self._lease_lock:
            held = sorted(self._owned_leases)
        return {"ok": True,
                # ready != ok (ISSUE 16): up-but-mid-compile is alive yet a
                # terrible routing target — the journal has replayed AND no
                # group build (minutes of jit on a real chip) is in flight.
                # WarmState.building() is a brief map scan, never a group
                # lock, so the no-blocking contract above holds
                "ready": bool(self._replay_done
                              and self.warm.building() == 0),
                "uptime_s": round(time.time() - self.started_ts, 3),
                "jobs": states, "shed_level": self._shed,
                "queue_depth": self._queue.qsize(),
                # the router's spill/least-loaded signal (0.0 = no SLO
                # tracking or an empty window)
                "burn": self._slo_burn_last,
                "groups_busy": {g.name: g.busy()
                                for g in self.warm.groups()},
                # crash-durable tier (ISSUE 15): this process's lease
                # identity + the jobs it currently owns — the per-process
                # ownership state daccord-top renders
                "peer": self.peer,
                "leases": held,
                "rss_mb": round(host_rss_mb(), 1),
                # disk-pressure governor (ISSUE 17): the on-call "is the
                # volume the problem" pair — daccord-top's DISK column
                "disk_free_mb": round(disk_free_mb(self.cfg.workdir), 1),
                "disk_pressure": bool(self.admission.disk_pressure)}

    def stats(self) -> dict:
        """Full stats (the /v1/metrics body). NOTE: group stats take each
        group's solve lock, so this can block behind an in-flight device
        solve — liveness probes must use :meth:`health` instead."""
        return {**self.health(),
                "admission": self.admission.stats(),
                "warm": self.warm.stats(),
                # saturation verdict (ISSUE 14): last computed by
                # _refresh_gauges over the demand wall
                "verdict": self._verdict,
                "metrics": self.metrics.rollup()}

    def stats_prom(self) -> str:
        """Prometheus text exposition of the live registry (ISSUE 13: the
        scrapeable health plane behind ``GET /v1/metrics?format=prom``).
        Health/admission scalars fold in as extra gauges so one scrape
        answers the whole on-call checklist; renders through the shared
        ``obs.render_prom`` so the pounce scrape checker lints exactly what
        production serves."""
        from ..utils.obs import render_prom

        self._refresh_gauges()
        roll = self.metrics.rollup()
        g = roll["gauges"]
        g["uptime_s"] = round(time.time() - self.started_ts, 3)
        g["queue_depth"] = self._queue.qsize()
        adm = self.admission.stats()
        for k in ("admitted", "rejected", "shed"):
            roll["counters"][f"admission_{k}"] = int(adm.get(k, 0))
        for grp in self.warm.groups():
            g[f"group_busy_{grp.name}"] = float(grp.busy())
        # the bottleneck verdict rides the rollup so render_prom exposes
        # daccord_serve_bottleneck_verdict{verdict="..."} — the field the
        # serve smoke asserts is present in the live exposition (ISSUE 14)
        roll["verdict"] = self._verdict
        return render_prom(roll, prefix="daccord_serve")

    def shutdown(self, drain: bool = True, timeout_s: float = 300.0) -> bool:
        """Graceful stop: admission closes, queued+running jobs finish
        (``drain``), pools drain, telemetry commits durably.

        Bounded drain (ISSUE 15 satellite): with ``drain_deadline_s`` set, a
        drain that outlives it — a group thread wedged in a solve — stops
        waiting: every in-flight job is journal-marked INTERRUPTED (an
        orphan the next restart replays and resumes) and the method returns
        False (the serve CLI exits nonzero). Returns True on a clean drain;
        the verdict also lands on ``self.clean``."""
        self.admission.drain()
        clean = True
        if drain:
            bound = self.cfg.drain_deadline_s or 0.0
            deadline = time.time() + (bound if bound > 0 else timeout_s)
            while True:
                with self._jobs_lock:
                    busy = any(j.state in (QUEUED, RUNNING) and not j.watch
                               for j in self.jobs.values())
                if not busy and self._queue.empty():
                    break
                if time.time() >= deadline:
                    if bound > 0:
                        clean = False
                        with self._jobs_lock:
                            stuck = [j for j in self.jobs.values()
                                     if j.state in (QUEUED, RUNNING)
                                     and not j.watch]
                        for j in stuck:
                            # resumable on restart: the journal keeps the
                            # job live, the per-job checkpoint bounds the
                            # recompute; the lease is deliberately NOT
                            # released — a peer takes it over once stale
                            self.journal_mark("interrupted", j.id)
                            self.log_event("serve.job", job=j.id,
                                           state="interrupted",
                                           tenant=j.tenant)
                    break
                time.sleep(0.05)
        self._stop.set()
        for _ in self._workers:
            self._queue.put(None)
        for t in self._workers:
            t.join(timeout=10.0)
        if any(t.is_alive() for t in self._workers):
            clean = False   # a wedged worker thread cannot be drained
        self._ticker.join(timeout=10.0)
        if clean:
            # a wedged solve could hold a group lock forever — only a clean
            # drain flushes residual pools (the unclean path is exiting: the
            # journal already holds everything a restart needs)
            for g in self.warm.groups():
                g.drain_all()
        self._refresh_gauges()
        self.metrics.snapshot(self.events, final=True)
        from ..utils.aio import durable_write
        from ..utils.obs import _note_dropped

        try:
            durable_write(
                os.path.join(self.cfg.workdir, "serve.metrics.json"),
                lambda fh: json.dump(self.stats(), fh), mode="wt",
                domain="sidecar")
            # the scrapeable twin (ISSUE 13): the same registry as a prom
            # text exposition, durably beside the JSON rollup — post-mortem
            # tooling and the pounce scrape checker read one format
            prom = self.stats_prom()
            durable_write(
                os.path.join(self.cfg.workdir, "serve.metrics.prom"),
                lambda fh: fh.write(prom), mode="wt", domain="sidecar")
        except OSError:
            # telemetry never raises into shutdown: a full volume costs the
            # rollup sidecars, not the drain verdict (counted like any
            # other dropped telemetry)
            _note_dropped(1)
        with self._jobs_lock:
            n_done = sum(j.state == DONE for j in self.jobs.values())
        self.log_event("serve.done", jobs=len(self.jobs), done=n_done,
                       wall_s=round(time.time() - self.started_ts, 3))
        self.warm.close()
        if self.journal is not None:
            # close the append fd, then compact: terminal jobs collapse to
            # their idempotency memory, so a long-lived service's journal
            # (and the next restart's replay) stays bounded
            from .journal import compact, replay as journal_replay

            self.journal.close()
            self.journal = None
            entries, _ = journal_replay(self._journal_path)
            compact(self._journal_path, entries)
        # release still-held leases ONLY on a clean exit: an unclean one
        # leaves them for peer takeover / our own restart (holder-checked,
        # so a taker that already claimed is never disturbed)
        if clean:
            with self._lease_lock:
                held = list(self._owned_leases)
            for jid in held:
                self.release_job_lease(jid)
        # the announce lease drops on ANY shutdown verdict: a draining-but-
        # unclean peer is equally gone from the router's point of view (an
        # unreleased announce would cost the router a TTL of proxy errors)
        if self._announce_path is not None:
            from ..utils import lease

            lease.release(self._announce_path, host=self.peer)
            self._announce_path = None
        self.events.close()
        self.clean = clean
        return clean

    # ------------------------------------------------------------------
    # background threads
    # ------------------------------------------------------------------

    def _worker_loop(self) -> None:
        while not self._stop.is_set():
            job_id = self._queue.get()
            if job_id is None:
                return
            # claim atomically: abort() releases a QUEUED job's quota
            # synchronously under this same lock, so exactly one of the two
            # (claim here, or queued-abort there) wins — never both
            with self._jobs_lock:
                job = self.jobs.get(job_id)
                if job is None or job.state != QUEUED:
                    # pre-dequeue abort already released the charge/counted
                    continue
                aborted_now = job.abort_event.is_set()
                if aborted_now:
                    job.state = ABORTED
                    job.done_ts = time.time()
                else:
                    job.state = RUNNING
            if aborted_now:
                self.admission.release(job.tenant, job.spec.nbytes)
                self.metrics.counter("jobs_aborted").inc()
                continue
            with self._jobs_lock:
                running = sum(1 for j in self.jobs.values()
                              if j.state == RUNNING)
            self.metrics.gauge("active_jobs").set(running + 1)
            try:
                run_job(job, self)
            except Exception as e:  # noqa: BLE001 — a worker must survive
                # run_job already isolates job failures; anything escaping
                # here is a harness bug, and losing the worker thread would
                # silently shrink service capacity AND strand queued jobs
                job.state = FAILED
                job.error = job.error or f"{type(e).__name__}: {e}"[:500]
                job.done_ts = job.done_ts or time.time()
                self.log_event("serve.job", job=job.id, state=FAILED,
                               tenant=job.tenant, error=job.error)
            if not job.watch:
                # a demoted run returns non-terminal (RUNNING-watch): its
                # outcome is the TAKER's to count — the watch resolution
                # counts jobs_done when the peer's manifest lands
                self.metrics.counter(f"jobs_{job.state}").inc()
            with self._jobs_lock:
                running = sum(1 for j in self.jobs.values()
                              if j.state == RUNNING)
            self.metrics.gauge("active_jobs").set(float(running))

    def _tick_loop(self) -> None:
        last_snap = time.time()
        last_pressure = 0.0
        last_beat = 0.0
        while not self._stop.wait(self.cfg.flush_lag_s):
            # EVERY housekeeping step is guarded: the single ticker thread
            # dying (full disk on the events file, a group close raising)
            # would silently stop pressure shedding, stale flushes, job
            # pruning, and eviction for the rest of the server's life
            try:
                # latency bound: stale cross-job pools flush even when
                # every cohabitant is busy windowing
                for g in self.warm.groups():
                    g.flush_stale(self.cfg.flush_lag_s)
                now = time.time()
                # demand-wall sampling (ISSUE 14): accrue wall while any
                # job is queued/running — the saturation verdict's
                # denominator (see _refresh_gauges)
                dt = now - self._last_demand_tick
                self._last_demand_tick = now
                with self._jobs_lock:
                    active = any(j.state in (QUEUED, RUNNING)
                                 for j in self.jobs.values())
                if active:
                    self._demand_s += dt
                if now - last_pressure >= 1.0:
                    last_pressure = now
                    self._pressure_tick()
                    self._disk_tick(now)
                    self._prune_jobs(now)
                if self.cfg.peer_dir \
                        and now - last_beat >= self.cfg.heartbeat_s:
                    # watch jobs only exist when peer_dir is set, so the
                    # lease tick (and its O(jobs) scans) stays off entirely
                    # for solo deployments
                    last_beat = now
                    self._lease_tick()
                self.warm.evict_idle()
                if (self.cfg.metrics_snapshot_s
                        and now - last_snap >= self.cfg.metrics_snapshot_s):
                    last_snap = now
                    self._refresh_gauges()
                    self.metrics.snapshot(self.events)
            except Exception as e:  # noqa: BLE001 — ticker must survive
                try:
                    self.log_event("serve.job", job="-", state="tick_error",
                                   tenant="-", error=str(e)[:200])
                except Exception:
                    pass

    def _prune_jobs(self, now: float) -> None:
        """Bound the in-memory registry: terminal jobs drop out
        ``job_retention_s`` after finishing (status turns 404; the durable
        commit under jobs/<id>/ is untouched). Without this an always-on
        server's registry — and every loop that iterates it — grows with
        lifetime job count."""
        ttl = self.cfg.job_retention_s
        if not ttl:
            return
        with self._jobs_lock:
            for jid, j in list(self.jobs.items()):
                if (j.state in (DONE, FAILED, ABORTED) and j.done_ts
                        and now - j.done_ts >= ttl):
                    del self.jobs[jid]

    def announce(self, url: str) -> None:
        """Publish this peer's HTTP address for front-door discovery
        (ISSUE 16): an announce lease at
        ``<peer_dir>/peers/<service_id>.lease`` carrying the URL, renewed
        every ``_lease_tick`` — the job-lease protocol reused verbatim, so
        a dead peer's announce goes stale on exactly the same clock as its
        job leases and the router needs no second liveness protocol. No-op
        without a peer_dir (solo deployments have no router)."""
        if not self.cfg.peer_dir:
            return
        from ..utils import lease

        path = os.path.join(self.cfg.peer_dir, "peers",
                            f"{self.service_id}.lease")
        # our service_id namespace (unique-basename rule): a previous
        # incarnation's leftover announce is ours to replace
        lease.release(path)
        lease.claim(path, self.peer, self.cfg.lease_ttl_s,
                    extra={"url": url, "service": self.service_id})
        self._announce_url = url
        self._announce_path = path
        self.log_event("serve.announce", url=url, peer=self.peer)

    def _demote_job(self, job, jid: str, to: str) -> None:
        """Stand down from a job whose lease we can no longer prove we hold
        (a taker owns it, or the renew grace ran out): our run aborts at its
        next check and the job becomes a watch (a committed peer manifest
        flips it DONE). A still-QUEUED job flips to RUNNING-watch under the
        lock so the worker's dequeue skips it (state != QUEUED) instead of
        misreading the demotion abort_event as a client abort — and its
        quota charge releases NOW (the taker charged its own)."""
        with self._lease_lock:
            self._owned_leases.pop(jid, None)
        with self._jobs_lock:
            was_queued = job.state == QUEUED
            if was_queued:
                job.state = RUNNING
            job.watch = True
        job.abort_event.set()
        if was_queued:
            self.admission.release(job.tenant, job.spec.nbytes)
        self.journal_mark("demoted", jid, to=to)

    def _lease_tick(self) -> None:
        """The peer-takeover heartbeat (ISSUE 15), at ``heartbeat_s``
        cadence so a serve fleet never storms the shared FS:

        1. renew every lease we hold — with the fleet's re-read-before-
           renew ownership check: if a taker claimed our stale lease during
           a pause, renewing would keep THE TAKER'S lease fresh while two
           processes run one job. We stand down (abort our run, watch the
           taker) instead.
        2. resolve watch jobs: a peer-held job whose manifest landed is
           DONE here too; one whose lease went stale re-enters the takeover
           scan below.
        3. scan the shared lease dir for stale leases of dead peers, claim
           them (race-safe), and re-admit their journaled jobs through the
           normal quota path — the byte contract is unchanged because the
           job runs through the same pipeline against the same shared-FS
           inputs, resuming from the dead peer's per-job checkpoint.
        """
        import glob as _glob

        from ..utils import lease
        from .jobs import JobSpec

        ttl = self.cfg.lease_ttl_s
        # 0. renew the announce lease (router discovery, ISSUE 16) — same
        # re-read-before-renew discipline as job leases; a vanished file
        # (an operator rm) is simply re-announced
        if self._announce_path is not None:
            info = lease.read(self._announce_path)
            if info is None:
                lease.claim(self._announce_path, self.peer, ttl,
                            extra={"url": self._announce_url,
                                   "service": self.service_id})
            elif info.get("host") == self.peer:
                lease.renew(self._announce_path)
        # 1. renew (ownership-checked)
        with self._lease_lock:
            held = list(self._owned_leases.items())
        for jid, path in held:
            with self._jobs_lock:
                job = self.jobs.get(jid)
            if job is None or job.state in (DONE, FAILED, ABORTED):
                self.release_job_lease(jid)
                continue
            info, lstat = lease.read_result(path)
            if info is not None and info.get("host") != self.peer:
                # ownership lost: never renew the taker's lease
                self._lease_grace.pop(jid, None)
                self._demote_job(job, jid, str(info.get("host", "?")))
                continue
            # ``lstat`` != ok (absent / torn / EIO-class read error) leaves
            # ownership unproven this beat; still attempt the bump — utime
            # can succeed where the read hiccupped — and count a failed
            # beat against the bounded grace. One shared-FS hiccup must not
            # abort healthy in-flight work, but a holder that cannot prove
            # liveness for lease_grace_beats heartbeats stands down BEFORE
            # the TTL lets a peer steal the lease out from under a commit.
            if lease.renew(path):
                self._lease_grace.pop(jid, None)
                continue
            n = self._lease_grace.get(jid, 0) + 1
            self._lease_grace[jid] = n
            grace = max(1, int(self.cfg.lease_grace_beats))
            self.log_event("io.fault", domain="lease", op="renew",
                           error=f"beat {n}/{grace} ({lstat})")
            if n >= grace:
                self._lease_grace.pop(jid, None)
                self._demote_job(job, jid, "(renew grace exhausted)")
        # 2. watch jobs: peer finished, or peer died
        with self._jobs_lock:
            watches = [j for j in self.jobs.values()
                       if j.watch and j.state not in (DONE, FAILED, ABORTED)]
        for job in watches:
            if os.path.exists(os.path.join(job.dir, "manifest.json")):
                with self._jobs_lock:
                    job.state = DONE
                    job.done_ts = job.done_ts or time.time()
                    job.watch = False
                self.metrics.counter("jobs_done").inc()
                self.journal_mark("committed", job.id, by="peer")
                self.log_event("serve.job", job=job.id, state=DONE,
                               tenant=job.tenant)
        # 3. takeover scan
        if not self.cfg.peer_dir:
            return
        with self._lease_lock:
            mine = set(self._owned_leases.values())
        for path in _glob.glob(os.path.join(self.cfg.peer_dir, "leases",
                                            "*.lease")):
            if path in mine:
                continue
            age = lease.stale_s(path)
            if age is None or age <= ttl:
                continue
            info = lease.read(path)
            if not info or not info.get("jobdir") or not info.get("spec"):
                # torn lease from a killed claimer: clear it once stale so
                # the dir doesn't accrete litter (the job itself is in the
                # dead process's journal; its restart replays it)
                lease.release(path)
                continue
            jobdir = info["jobdir"]
            if os.path.exists(os.path.join(jobdir, "manifest.json")):
                # committed, then the committer died before releasing
                lease.release(path)
                continue
            key = (info["job"] if info.get("service") == self.service_id
                   else f"{info.get('service', '?')}.{info['job']}")
            with self._jobs_lock:
                existing = self.jobs.get(key)
                if existing is not None and not existing.watch \
                        and existing.state in (QUEUED, RUNNING):
                    continue   # already ours (replay got here first)
                if existing is not None and existing.running_local:
                    # a demoted straggler thread is still unwinding this
                    # job: re-queueing now would put two local threads on
                    # one job. It exits at its next abort check; until
                    # then the lease stays stale — a later tick (or a
                    # peer) reclaims
                    continue
            ok, tk = lease.claim(path, self.peer, ttl,
                                 extra={k: info.get(k) for k in
                                        ("service", "job", "jobdir",
                                         "tenant", "nbytes", "spec",
                                         "idem")})
            if not ok:
                continue   # another peer won the race
            tenant = str(info.get("tenant", "default"))
            nbytes = int(info.get("nbytes", 0) or 0)
            try:
                # the NORMAL quota path: a loaded peer refuses the orphan
                # and hands the lease back for someone with headroom
                self.admission.admit(tenant, nbytes, job=key)
            except Exception:
                lease.release(path, host=self.peer)
                continue
            try:
                spec = JobSpec(**info["spec"])
            except TypeError:
                self.admission.release(tenant, nbytes)
                lease.release(path, host=self.peer)
                continue
            spec.nbytes = nbytes
            with self._jobs_lock:
                job = self.jobs.get(key)
                if job is not None:
                    # our own watch job whose peer died: reclaim it
                    job.state = QUEUED
                    job.watch = False
                    job.abort_event = threading.Event()
                else:
                    job = Job(id=key, tenant=tenant, spec=spec, dir=jobdir)
                    self.jobs[key] = job
            with self._lease_lock:
                self._owned_leases[key] = path
            idem = info.get("idem")
            if idem:
                with self._jobs_lock:
                    self._idem[idem] = key
            self.journal_mark("admitted", key, tenant=tenant, nbytes=nbytes,
                              spec=info["spec"], dir=jobdir, idem=idem,
                              takeover=True)
            self.metrics.counter("takeovers").inc()
            self.log_event(
                "serve.takeover", job=key,
                prev_host=str((tk or {}).get("prev_host", "?")),
                stale_s=float((tk or {}).get("stale_s", round(age, 3))))
            self._queue.put(key)

    def _slo_tick(self) -> None:
        """SLO burn tracking (ISSUE 13): rolling p99 job latency over the
        window vs the target. ``burn = p99/target``; crossing the shed
        fraction raises the slo-held shed rung so the batch ladder engages
        BEFORE the target is breached (burn >= 1 — the breach the sentinel
        flags), and a cleared window releases it one rung per tick.
        ``serve.slo`` emits on burn-band changes, not every tick."""
        cfg = self.cfg
        if not cfg.slo_p99_s:
            return
        now = time.time()
        win = self._lat_window
        with self._lat_lock:
            while win and now - win[0][0] > cfg.slo_window_s:
                win.popleft()
            lats = sorted(v for _, v in win)
        n = len(lats)
        p99 = lats[min(int(0.99 * n), n - 1)] if n else None
        if p99 is None:
            # an empty window (traffic stopped) must still release a held
            # rung per tick, or a past burst pins the shed ladder forever
            self._slo_burn_last = 0.0
            if self._slo_shed:
                self._slo_shed -= 1
            return
        burn = round(p99 / cfg.slo_p99_s, 3)
        self._slo_burn_last = burn
        self.metrics.gauge("slo_burn").set(burn)
        self.metrics.gauge("slo_p99_s").set(round(p99, 4))
        if burn >= cfg.slo_shed_burn:
            self._slo_shed = min(self._slo_shed + 1, cfg.shed_max_levels)
        elif burn < cfg.slo_clear_burn and self._slo_shed:
            self._slo_shed -= 1
        band = int(burn * 10)
        if band != self._slo_band:
            self._slo_band = band
            self.log_event("serve.slo", target_s=cfg.slo_p99_s,
                           p99_s=round(p99, 4), burn=burn, n=n,
                           window_s=cfg.slo_window_s, shed=self._slo_shed)

    def _pressure_tick(self) -> None:
        """The shed ladder (ISSUE 10 (c)): hard pressure halves every
        group's merged-batch width one rung per second of sustained
        pressure (bounded); clear pressure restores one rung per second.
        The SLO burn tracker holds its own rung (``_slo_tick``) — the
        effective level is the max of the two, so latency pressure sheds
        before an SLO breach even when RSS is fine. Degrades throughput,
        never bytes — it is the capacity governor's batch-bisect argument
        applied service-wide."""
        level, rss = self.admission.pressure_level()
        self._peak_rss_mb = max(self._peak_rss_mb, rss)
        qd = self._queue.qsize()
        self._peak_queue_depth = max(self._peak_queue_depth, qd)
        self._slo_tick()
        want = self._shed
        if level == "hard":
            want = min(self._shed + 1, self.cfg.shed_max_levels)
        elif level is None and self._shed:
            want = self._shed - 1
        want = max(want, self._slo_shed)
        if want != self._shed:
            self._shed = want
            self.log_event("serve.shed", level=int(want),
                           rss_mb=round(rss, 1))
            for g in self.warm.groups():
                g.set_shed(want)

    def _enter_disk_pressure(self, src: str, detail: str) -> None:
        """Latch the admission ``disk_pressure`` state (idempotent):
        submissions answer machine-readable 507-style refusals until the
        volume proves writable again. Every in-flight job is journal-marked
        INTERRUPTED — a resumable record, NOT an abort: the jobs keep
        running (compute needs no disk until commit), but if the full
        volume kills the process first, replay resumes them from their
        checkpoints instead of losing them. The marks themselves may be
        refused by the same full disk — tolerated (append returns False);
        a never-marked orphan replays identically."""
        if self.admission.disk_pressure is not None:
            return
        self.admission.disk_pressure = f"{src}: {detail}"[:200]
        self._disk_latch_src = src
        _, free = self.admission.disk_level()
        self.log_event("disk.pressure", level="enter", src=src,
                       free_mb=round(free, 1), detail=str(detail)[:200])
        self.metrics.counter("disk_pressure_events").inc()
        with self._jobs_lock:
            inflight = [j.id for j in self.jobs.values()
                        if j.state in (QUEUED, RUNNING) and not j.watch]
        for jid in inflight:
            self.journal_mark("interrupted", jid)

    def _clear_disk_pressure(self, free: float) -> None:
        detail = self.admission.disk_pressure
        self.admission.disk_pressure = None
        self._disk_latch_src = None
        self.log_event("disk.pressure", level="clear", src="probe",
                       free_mb=round(free, 1), detail=str(detail or "")[:200])

    def _disk_probe_ok(self) -> bool:
        """One raw write+fsync on the serve volume — deliberately NOT
        through the aio fault hook (the probe asks the REAL disk, and must
        not consume injected-fault counters): the latch clears only when
        bytes demonstrably reach durability again."""
        p = os.path.join(self.cfg.workdir, ".disk.probe")
        try:
            fd = os.open(p, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
            try:
                os.write(fd, b"ok\n")
                os.fsync(fd)
            finally:
                os.close(fd)
            os.remove(p)
            return True
        except OSError:
            return False

    def _disk_tick(self, now: float) -> None:
        """The disk-pressure governor (ISSUE 17), mirroring the RSS ladder
        at the same 1 Hz cadence: free-bytes watermarks latch the admission
        507 state at hard, a successful probe (plus a clear watermark)
        releases it, and the journal compacts ONLINE at a size or hard
        free-space watermark — a filling volume is relieved by the
        journal's own garbage before an operator has to bounce the
        server."""
        level, free = self.admission.disk_level()
        if free >= 0:
            self.metrics.gauge("disk_free_mb").set(round(free, 1))
        self.metrics.gauge("disk_pressure").set(
            1.0 if self.admission.disk_pressure else 0.0)
        if level == "hard" and self.admission.disk_pressure is None:
            self._enter_disk_pressure(
                "watermark",
                f"free {free:.0f} MiB <= hard "
                f"{self.admission.cfg.disk_hard_mb:.0f} MiB")
        elif self.admission.disk_pressure is not None and level is None \
                and self._disk_probe_ok():
            self._clear_disk_pressure(free)
        j = self.journal
        if j is None:
            return
        size_mb = j.size_bytes() / float(1 << 20)
        want = bool(self.cfg.journal_compact_mb
                    and size_mb >= self.cfg.journal_compact_mb) \
            or level == "hard"
        if want and now - self._last_compact >= 5.0:
            # rate-limited: a journal that compacts to >= the watermark
            # (nothing terminal to collapse) must not rewrite every tick
            self._last_compact = now
            res = j.compact_online()
            if res is not None:
                self.log_event("journal.compact", **res)
                self.metrics.counter("journal_compactions").inc()

    def _refresh_gauges(self) -> None:
        from ..runtime.governor import host_rss_mb

        g = self.metrics.gauge
        with self._jobs_lock:
            g("jobs_total").set(float(len(self.jobs)))
            g("jobs_running").set(float(sum(
                1 for j in self.jobs.values() if j.state == RUNNING)))
        rss = host_rss_mb()
        self._peak_rss_mb = max(self._peak_rss_mb, rss)
        qd = self._queue.qsize()
        self._peak_queue_depth = max(self._peak_queue_depth, qd)
        g("rss_mb").set(rss)
        # peaks, not just the last sample (ISSUE 13 satellite): the durable
        # rollup must answer "how bad did it get", and a drained shutdown
        # always reads 0 at the last tick
        g("rss_mb_peak").set(self._peak_rss_mb)
        g("queue_depth").set(float(qd))
        g("queue_depth_peak").set(float(self._peak_queue_depth))
        g("shed_level").set(float(self._shed))
        from ..utils.obs import disk_free_mb

        free = disk_free_mb(self.cfg.workdir)
        if free >= 0:
            g("disk_free_mb").set(round(free, 1))
        g("disk_pressure").set(
            1.0 if self.admission.disk_pressure else 0.0)
        with self._lease_lock:
            g("leases_held").set(float(len(self._owned_leases)))
        mixed = rows = 0
        busy_s = blocked_s = 0.0
        for grp in self.warm.groups():
            s = grp.stats()
            mixed += s["mixed_batches"]
            rows += s["rows"]
            # per-group starvation gauges (ISSUE 14): each warm group's
            # device-idle / host-blocked fractions over its own lifetime
            sat = s.get("saturation") or {}
            g(f"group_device_idle_frac_{grp.name}").set(
                float(sat.get("device_idle_frac", 1.0)))
            g(f"group_host_blocked_frac_{grp.name}").set(
                float(sat.get("host_blocked_frac", 0.0)))
            busy_s += float(sat.get("busy_s", 0.0))
            blocked_s += float(sat.get("blocked_s", 0.0))
        g("batcher_rows").set(float(rows))
        g("batcher_mixed_batches").set(float(mixed))
        # service-level saturation + verdict over the DEMAND wall: device
        # gaps while jobs were live mean the feeders (job windowing) starve
        # the warm groups — the serve-plane form of host_feeder
        from ..utils.obs import bottleneck_verdict, saturation_gauges

        if self._demand_s > 1e-6:
            sat = saturation_gauges(self._demand_s, blocked_s, busy_s)
            self._verdict = bottleneck_verdict(sat)["verdict"]
        else:
            sat = saturation_gauges(1.0, 0.0, 1.0)   # no traffic: balanced
            self._verdict = "balanced"
        for k, v in sat.items():
            g(k).set(v)
        g("demand_s").set(round(self._demand_s, 3))
