"""SLO-burn autoscaler for the serve fleet (ISSUE 16).

Runs inside the router process (the only place with a fleet-wide view) and
is driven by the router's poll loop: every sweep hands it the fresh peer
table and it decides spawn / drain / nothing.

Policy, deliberately simple and bounded:

- **signal**: fleet burn = max burn over READY peers (the router already
  spills around a hot owner, so the scale trigger is "even the spill
  targets are hot"). Band changes emit ``scale.burn`` — the audit trail
  that lets the sentinel correlate scale-outs with their p99 outcome.
- **scale-out**: burn >= ``spawn_burn`` sustained for ``sustain_s``
  (instantaneous spikes don't buy hardware) AND past ``cooldown_s`` since
  the last spawn (a cold peer takes a while to turn ready — spawning again
  before the first one warms is the spawn-storm failure mode the cooldown
  exists to prevent) AND live < ``max_peers``. Spawns one
  ``daccord-serve`` subprocess with the same peer-dir (so it announces
  itself and joins the takeover group) and the fleet-shared AOT cache dir
  (so its cold TTFR is a deserialize, not a compile).
- **scale-in**: a peer this autoscaler spawned (never a peer someone else
  owns) that has been idle — no queued/running jobs — past ``idle_ttl_s``
  while the fleet holds more than ``min_peers`` gets a graceful
  ``POST /v1/shutdown`` (``scale.drain``). The drain path releases its job
  leases; if it dies unclean instead, the PR 15 takeover path re-homes its
  jobs — reaping is safe either way. Process exit emits ``scale.reap``.
- **partition safety** (ISSUE 18): a peer whose healthz is unreachable but
  whose announce lease is fresh (``Peer.partitioned``) is alive-but-cut-off
  — it is NEVER drained (its idle clock resets: the autoscaler cannot see
  its queue, so it must not claim the peer is idle), it still occupies
  spawn capacity (the partition healing must not land the fleet over
  ``max_peers``), and a drain call that times out journal-marks nothing —
  the peer's own journal owns its recovery, the autoscaler only ever asks
  politely. The drain call itself goes through the ``serve/netio`` choke
  point with the bounded ``abort`` deadline, so a wedged peer socket can
  no longer stall the scale loop.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from dataclasses import dataclass, field

from . import netio


@dataclass
class AutoscaleConfig:
    peer_dir: str                     # shared lease root (joins the group)
    root: str                         # new peers live at <root>/peer<N>/
    max_peers: int = 4
    min_peers: int = 1
    spawn_burn: float = 1.0           # fleet burn >= this arms the trigger
    sustain_s: float = 5.0            # ... for this long
    cooldown_s: float = 30.0          # min gap between spawns
    idle_ttl_s: float = 120.0         # idle spawned peer older than this
                                      # drains (0 = never scale in)
    drain_timeout_s: float = 10.0     # bound on the graceful-shutdown call
    backend: str = "native"
    batch: int = 64
    workers: int = 2
    slo_p99_s: float = 0.0            # forwarded so new peers burn-report
    extra_args: tuple = field(default_factory=tuple)
    spawn_env: dict = field(default_factory=dict)


class Autoscaler:
    """Owns the peers it spawned (pid + workdir); everything else in the
    fleet is read-only to it."""

    def __init__(self, cfg: AutoscaleConfig, log):
        self.cfg = cfg
        self.log = log
        os.makedirs(cfg.root, exist_ok=True)
        self._spawned: dict[str, dict] = {}   # peer name -> {proc, ...}
        self._seq = 0
        self._burn_since: float | None = None
        self._last_spawn_ts = 0.0
        self._band = -1
        self._idle_since: dict[str, float] = {}
        self.counters = {"spawns": 0, "drains": 0, "reaps": 0}

    # -- helpers -----------------------------------------------------------

    def _peer_name(self, workdir: str) -> str:
        # must match ConsensusService.service_id (announce lease basename)
        return os.path.basename(os.path.abspath(workdir))

    def _spawn(self) -> None:
        self._seq += 1
        workdir = os.path.join(self.cfg.root, f"autopeer{self._seq}")
        ready = os.path.join(workdir, "ready.port")
        os.makedirs(workdir, exist_ok=True)
        cmd = [sys.executable, "-m", "daccord_tpu.tools.cli", "serve",
               "--workdir", workdir,
               "--backend", self.cfg.backend,
               "-b", str(self.cfg.batch),
               "--workers", str(self.cfg.workers),
               "--port", "0",
               "--ready-file", ready,
               "--peer-dir", self.cfg.peer_dir]
        if self.cfg.slo_p99_s:
            cmd += ["--slo-p99-s", str(self.cfg.slo_p99_s)]
        cmd += list(self.cfg.extra_args)
        env = dict(os.environ, **self.cfg.spawn_env)
        proc = subprocess.Popen(
            cmd, env=env,
            stdout=open(os.path.join(workdir, "serve.out"), "wb"),
            stderr=subprocess.STDOUT)
        name = self._peer_name(workdir)
        self._spawned[name] = {"proc": proc, "workdir": workdir,
                               "spawn_ts": time.time()}
        self._last_spawn_ts = time.time()
        self.counters["spawns"] += 1
        self.log.log("scale.spawn", peer=name, pid=proc.pid,
                     workdir=workdir, n_spawned=len(self._spawned))

    def adopt(self, name: str, proc, workdir: str) -> None:
        """Take ownership of an externally spawned peer (bench / chaos
        harness escape hatch): it joins the idle-drain and reap sweeps
        exactly as if this autoscaler had spawned it."""
        self._spawned[name] = {"proc": proc, "workdir": workdir,
                               "spawn_ts": time.time()}

    def disown(self, name: str) -> None:
        """Release an adopted peer without draining or reaping it —
        :meth:`shutdown` must not terminate a process the harness intends
        to stop gracefully itself."""
        self._spawned.pop(name, None)
        self._idle_since.pop(name, None)

    def _net_event(self, event: str, **fields) -> None:
        # ``event``, not ``kind``: net.fault carries a field named kind
        try:
            self.log.log(event, **fields)
        except Exception:  # noqa: BLE001 — telemetry never breaks scaling
            pass

    def _drain(self, name: str, url: str) -> None:
        try:
            netio.request(url + "/v1/shutdown", "abort", method="POST",
                          body=b"{}", timeout=self.cfg.drain_timeout_s,
                          log_event=self._net_event, peer=name)
        except Exception:
            # unreachable or timed out: journal-mark NOTHING — the peer's
            # own journal owns its recovery (graceful exit releases its
            # leases; an unclean death goes stale and takeover re-homes
            # the jobs). The reap sweep collects the process if it exits.
            pass
        self.counters["drains"] += 1
        self.log.log("scale.drain", peer=name, reason="idle_ttl")

    def _reap(self) -> None:
        for name, info in list(self._spawned.items()):
            rc = info["proc"].poll()
            if rc is None:
                continue
            del self._spawned[name]
            self._idle_since.pop(name, None)
            self.counters["reaps"] += 1
            self.log.log("scale.reap", peer=name, rc=int(rc),
                         life_s=round(time.time() - info["spawn_ts"], 3))

    # -- the per-sweep decision -------------------------------------------

    def tick(self, peers: list) -> None:
        """One decision pass over the router's freshly-polled peer table
        (``peers`` are router.Peer objects)."""
        now = time.time()
        self._reap()
        ready = [p for p in peers if p.ready]
        live = [p for p in peers if p.alive]
        # a partitioned peer (healthz dead, announce lease fresh) is alive
        # hardware we merely cannot see: it occupies capacity
        present = [p for p in peers
                   if p.alive or getattr(p, "partitioned", False)]

        # burn signal + band audit trail
        burn = max((p.burn for p in ready), default=0.0)
        band = int(min(burn, 5.0) * 10)
        if band != self._band:
            self._band = band
            self.log.log("scale.burn", burn=round(burn, 4), band=band,
                         n_ready=len(ready), n_live=len(live))

        # scale-out: sustained burn, cooled down, under the cap
        if burn >= self.cfg.spawn_burn and ready:
            if self._burn_since is None:
                self._burn_since = now
            sustained = now - self._burn_since >= self.cfg.sustain_s
            cooled = now - self._last_spawn_ts >= self.cfg.cooldown_s
            capacity = len(present) + self._n_pending() < self.cfg.max_peers
            if sustained and cooled and capacity:
                self._spawn()
        else:
            self._burn_since = None

        # scale-in: OUR idle peers past TTL, keeping min_peers alive
        if self.cfg.idle_ttl_s <= 0:
            return
        by_name = {p.name: p for p in peers}
        for name in list(self._spawned):
            p = by_name.get(name)
            if p is None or not p.alive:
                if p is not None and getattr(p, "partitioned", False):
                    # we cannot see a partitioned peer's queue, so we
                    # cannot call it idle — reset its clock, never drain
                    self._idle_since.pop(name, None)
                continue
            idle = p.jobs_active == 0 and p.queue_depth == 0
            if not idle:
                self._idle_since.pop(name, None)
                continue
            first = self._idle_since.setdefault(name, now)
            if now - first >= self.cfg.idle_ttl_s and \
                    len(live) > self.cfg.min_peers:
                self._idle_since.pop(name, None)
                self._drain(name, p.url)

    def _n_pending(self) -> int:
        """Spawned processes that haven't announced/turned ready yet still
        count against max_peers — that's the spawn-storm guard."""
        return sum(1 for i in self._spawned.values()
                   if i["proc"].poll() is None)

    def stats(self) -> dict:
        return {"spawned": sorted(self._spawned),
                "burn_band": self._band, **self.counters}

    def shutdown(self) -> None:
        """Drain every peer we own (router shutdown): graceful stop, then
        a bounded wait; a peer that won't die is left for takeover."""
        for name, info in list(self._spawned.items()):
            proc = info["proc"]
            if proc.poll() is None:
                proc.terminate()
        deadline = time.time() + 15.0
        for info in self._spawned.values():
            proc = info["proc"]
            try:
                proc.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                proc.kill()
        self._reap()
