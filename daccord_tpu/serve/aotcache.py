"""Fleet-shared AOT executable cache (ISSUE 16).

The registry records 900 s-class cold jit walls for the fused ladder; a
freshly spawned serve peer paying that before its first job makes autoscale
nominal rather than real. This module closes the gap: the FIRST peer to
compile a (shape, program) pair serializes the compiled executable via JAX
AOT export (``jitted.lower(*args).compile()`` + ``serialize_executable``)
into a cache directory on the shared filesystem beside the lease dir, and
every later peer — including one the autoscaler spawned seconds ago —
deserializes it in well under a second instead of recompiling.

Entries are keyed by the SAME shape key the compile-fingerprint registry
uses (``runtime.supervisor.shape_key``: ``B..xD..xL..`` with the ``:t0`` /
``:pg`` stream/wire suffixes), so the observability chain lines up: a
``aot.miss`` on a key the registry already holds means a peer recompiled
something the fleet had — exactly the regression ``daccord-sentinel``
flags. Because two ladders can share a batch shape while lowering different
programs (different tier params, table widths, pallas mode), the on-disk
entry name also folds in a static-config digest; the registry key stays the
human-readable identity, the digest keeps colliding programs in separate
files.

Wire format of an entry (single file, atomic tmp+fsync+rename publish):

    DACAOT01 <sha256 of body> <pickle body>

where the body is ``{"key", "meta", "payload", "in_tree", "out_tree"}``
and ``meta`` pins jax/jaxlib versions + backend. A torn or bit-flipped
entry fails the checksum and is *rejected* (``aot.reject`` reason=corrupt),
never trusted; a version-mismatched entry is rejected with reason=version.
Both fall back to the cold jit path — the cache can only ever cost a
rejected read, never correctness (byte parity vs the cold compile is
asserted by tests/test_router.py).

Scope: single-device JAX groups only. Mesh groups (``shard_map`` closures)
and the native/C++ and host-routed ``solve_tiered`` paths never reach the
jitted stream dispatcher, so :meth:`AotCache.dispatcher` is wired only on
the ``stream_dispatcher`` branch of ``SolveGroup._build_solver``.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import threading
import time

from ..utils.obs import NullLogger

_MAGIC = b"DACAOT01"
_SHA_LEN = 32


def _versions() -> dict:
    import jax
    import jaxlib

    return {"jax": jax.__version__, "jaxlib": jaxlib.__version__,
            "backend": jax.default_backend()}


def static_digest(ladder, stream: str, use_pallas: bool,
                  pallas_interpret: bool) -> str:
    """Digest of everything that changes the lowered program at a fixed
    batch shape: tier params, wide-p0 rescue config, pallas mode, and the
    k-mer table shapes/dtypes. Two processes with the same profile produce
    the same digest (dataclass reprs are deterministic); two different
    ladders at the same batch shape get different entry files."""
    tabs = tuple((int(k),) + tuple(ladder.tables[k].shape)
                 + (str(ladder.tables[k].dtype),)
                 for k in sorted(ladder.tables))
    sig = repr((stream, tuple(ladder.params), ladder.wide_p0,
                bool(use_pallas), bool(pallas_interpret), tabs))
    return hashlib.sha256(sig.encode()).hexdigest()[:16]


class AotCache:
    """Load/publish serialized executables in a fleet-shared directory.

    Thread-safe: the in-memory map is lock-guarded; disk publishes go
    through tmp+fsync+rename so concurrent peers racing to publish the same
    entry both succeed (last rename wins, both bodies identical-in-meaning).
    """

    def __init__(self, cache_dir: str, log=None, cap_mb: float | None = None):
        self.dir = cache_dir
        self.log = log if log is not None else NullLogger()
        self._mem: dict[tuple[str, str], object] = {}
        self._lock = threading.Lock()
        self.counters = {"hits": 0, "mem_hits": 0, "misses": 0,
                         "publishes": 0, "rejects": 0, "swept": 0}
        # size cap on the SHARED dir (ISSUE 17): an always-on fleet keeps
        # publishing new (shape, program) entries forever; without a sweep
        # the cache itself becomes the thing that fills the volume. LRU by
        # mtime (a hit re-reads but does not bump mtime — good enough: the
        # hot entries are the recently published ones). 0 = uncapped.
        if cap_mb is None:
            try:
                cap_mb = float(os.environ.get("DACCORD_AOT_CAP_MB", 512))
            except ValueError:
                cap_mb = 512.0
        self.cap_mb = cap_mb
        os.makedirs(cache_dir, exist_ok=True)

    # ------------------------------------------------------------------
    # entry IO
    # ------------------------------------------------------------------

    def _path(self, key: str, digest: str) -> str:
        name = hashlib.sha256(f"{key}|{digest}".encode()).hexdigest()[:32]
        return os.path.join(self.dir, name + ".aot")

    def load(self, key: str, digest: str):
        """The cached executable for ``(key, digest)``, or None.

        Memory first, then disk. A disk hit is deserialized and memoized;
        corrupt/torn entries and version mismatches are rejected with an
        ``aot.reject`` event and left in place (another peer's re-publish
        heals them — removal would race the publisher's rename)."""
        with self._lock:
            exe = self._mem.get((key, digest))
        if exe is not None:
            self.counters["mem_hits"] += 1
            return exe
        path = self._path(key, digest)
        try:
            with open(path, "rb") as fh:
                raw = fh.read()
        except OSError:
            return None
        t0 = time.perf_counter()
        if (len(raw) < len(_MAGIC) + _SHA_LEN
                or raw[:len(_MAGIC)] != _MAGIC):
            return self._reject(key, "corrupt")
        body = raw[len(_MAGIC) + _SHA_LEN:]
        if hashlib.sha256(body).digest() != \
                raw[len(_MAGIC):len(_MAGIC) + _SHA_LEN]:
            return self._reject(key, "corrupt")
        try:
            ent = pickle.loads(body)
        except Exception:
            return self._reject(key, "corrupt")
        if ent.get("meta") != _versions():
            return self._reject(key, "version")
        try:
            from jax.experimental import serialize_executable as se

            exe = se.deserialize_and_load(ent["payload"], ent["in_tree"],
                                          ent["out_tree"])
        except Exception as e:
            return self._reject(key, f"load:{type(e).__name__}")
        with self._lock:
            self._mem[(key, digest)] = exe
        self.counters["hits"] += 1
        self.log.log("aot.hit", key=key,
                     wall_s=round(time.perf_counter() - t0, 3))
        return exe

    def _reject(self, key: str, reason: str):
        self.counters["rejects"] += 1
        self.log.log("aot.reject", key=key, reason=reason)
        return None

    def publish(self, key: str, digest: str, compiled, wall_s: float) -> None:
        """Serialize ``compiled`` and install it durably; failures only log
        (a peer that cannot publish — serialization refusal, or a full
        shared volume, ENOSPC real or injected via the ``@aot`` fault
        domain — still serves from memory: skip-and-continue)."""
        with self._lock:
            self._mem[(key, digest)] = compiled
        try:
            from jax.experimental import serialize_executable as se

            from ..utils.aio import durable_write

            payload, in_tree, out_tree = se.serialize(compiled)
            body = pickle.dumps({"key": key, "meta": _versions(),
                                 "payload": payload, "in_tree": in_tree,
                                 "out_tree": out_tree})
            blob = _MAGIC + hashlib.sha256(body).digest() + body
            durable_write(self._path(key, digest),
                          lambda fh: fh.write(blob), domain="aot")
        except Exception as e:
            self._reject(key, f"publish:{type(e).__name__}")
            return
        self.counters["publishes"] += 1
        self.log.log("aot.publish", key=key, bytes=len(blob),
                     wall_s=round(wall_s, 3))
        self.sweep(keep=self._path(key, digest))

    def sweep(self, keep: str | None = None) -> int:
        """Size-capped LRU sweep of the shared dir: oldest-mtime ``.aot``
        entries go until the total is back under ``cap_mb``. Wholly
        OSError-tolerant — peers sweep concurrently, entries vanish under
        us, and a full disk must never make the sweep (the relief valve)
        the thing that raises. Returns the number of entries removed."""
        if not self.cap_mb:
            return 0
        try:
            names = [n for n in os.listdir(self.dir) if n.endswith(".aot")]
        except OSError:
            return 0
        ents = []
        for n in names:
            p = os.path.join(self.dir, n)
            try:
                st = os.stat(p)
            except OSError:
                continue
            ents.append((st.st_mtime, st.st_size, p))
        total = sum(sz for _, sz, _ in ents)
        cap = self.cap_mb * (1 << 20)
        if total <= cap:
            return 0
        removed = freed = 0
        for _, sz, p in sorted(ents):
            if total - freed <= cap:
                break
            if keep is not None and os.path.abspath(p) == \
                    os.path.abspath(keep):
                continue   # never evict the entry we just published
            try:
                os.remove(p)
            except OSError:
                continue
            removed += 1
            freed += sz
        if removed:
            self.counters["swept"] += removed
            self.log.log("aot.sweep", removed=removed, freed=freed,
                         total=total, cap_mb=self.cap_mb)
        return removed

    def stats(self) -> dict:
        return dict(self.counters)

    # ------------------------------------------------------------------
    # the dispatch wrap (stream_dispatcher's AOT twin)
    # ------------------------------------------------------------------

    def dispatcher(self, ladder, use_pallas: bool = False,
                   pallas_interpret: bool = False, fp_prefix: str = ""):
        """A drop-in for ``kernels.tiers.stream_dispatcher`` that routes
        each batch shape through the cache: disk hit → deserialize once and
        run warm; miss → ONE ``lower().compile()`` (the same compile the
        jit path would have paid) that is then both executed and published.
        Cache machinery failures fall back to the plain jit dispatch; real
        device errors from the executable call propagate untouched so the
        supervisor's fault classification still sees them."""
        import jax.numpy as jnp

        from ..kernels import tiers as T
        from ..runtime.supervisor import shape_key

        inner = T.stream_dispatcher(ladder, use_pallas=use_pallas,
                                    pallas_interpret=pallas_interpret)
        digests = {
            "full": static_digest(ladder, "full", use_pallas,
                                  pallas_interpret),
            "tier0": static_digest(ladder, "tier0", use_pallas,
                                   pallas_interpret),
        }

        def _assemble(batch):
            """(jit_fn, dynamic args, static args, cons_len) for this
            batch — the exact assembly of ``solve_ladder_async`` /
            ``solve_tier0_async``, shared so the two can't diverge."""
            stream = getattr(batch, "stream", "full")
            tier0 = stream == "tier0"
            p0 = ladder.params[0]
            cl = p0.cons_len
            if getattr(batch, "pool", None) is not None:
                dyn = (jnp.asarray(batch.pool), jnp.asarray(batch.table),
                       jnp.asarray(batch.lens), jnp.asarray(batch.nsegs))
                if tier0:
                    return (T._tier0_packed_paged_jit,
                            dyn + (ladder.tables[p0.k],),
                            (p0, batch.family.page_len, batch.shape.seg_len,
                             use_pallas, pallas_interpret), cl)
                tables = tuple(ladder.tables[p.k] for p in ladder.params)
                return (T._ladder_packed_paged_jit, dyn + (tables,),
                        (tuple(ladder.params), int(batch.size),
                         batch.family.page_len, batch.shape.seg_len,
                         use_pallas, pallas_interpret, ladder.wide_p0), cl)
            dyn = (jnp.asarray(batch.seqs), jnp.asarray(batch.lens),
                   jnp.asarray(batch.nsegs))
            if tier0:
                return (T._tier0_packed_jit, dyn + (ladder.tables[p0.k],),
                        (p0, use_pallas, pallas_interpret), cl)
            tables = tuple(ladder.tables[p.k] for p in ladder.params)
            return (T._ladder_packed_jit, dyn + (tables,),
                    (tuple(ladder.params), int(batch.size), use_pallas,
                     pallas_interpret, ladder.wide_p0), cl)

        def dispatch(batch):
            stream = getattr(batch, "stream", "full")
            digest = digests["tier0" if stream == "tier0" else "full"]
            try:
                key = shape_key(batch, fp_prefix)
                fn, dyn, statics, cl = _assemble(batch)
                exe = self.load(key, digest)
            except Exception as e:
                self._reject("?", f"keying:{type(e).__name__}")
                return inner(batch)
            if exe is None:
                self.counters["misses"] += 1
                self.log.log("aot.miss", key=key)
                try:
                    t0 = time.perf_counter()
                    exe = fn.lower(*dyn, *statics).compile()
                    self.publish(key, digest, exe,
                                 time.perf_counter() - t0)
                except Exception as e:
                    # a failed AOT lower/compile (e.g. an executable that
                    # refuses serialization on this backend) must not take
                    # the solve down with it: the jit path is the answer
                    self._reject(key, f"compile:{type(e).__name__}")
                    return inner(batch)
            # device faults from here MUST propagate: the supervisor owns
            # retry/failover classification, not the cache
            return T._PackedHandle(exe(*dyn), cl)

        return dispatch
