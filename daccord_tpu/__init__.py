"""daccord_tpu — a TPU-native long-read consensus / error-correction framework.

A ground-up re-design of the capabilities of gt1/daccord (non-hybrid PacBio/ONT
consensus by per-window local de Bruijn graph assembly over DALIGNER alignment
piles) for TPU hardware:

- ``formats``  : Dazzler DB / LAS / FASTA / track I/O (readers AND writers).
- ``sim``      : synthetic genome/read/alignment generator (test + bench data).
- ``oracle``   : pure numpy executable spec of the consensus algorithm.
- ``kernels``  : batched, fixed-shape JAX/Pallas implementation of the
                 per-window consensus (the reference's ``handleWindow`` seam).
- ``runtime``  : host pipeline streaming LAS piles -> window batches -> device.
- ``parallel`` : jax.sharding Mesh / shard_map scale-out of window batches.
- ``tools``    : CLI tools mirroring the reference tool suite.

Reference provenance: the upstream tree at /root/reference was empty when this
framework was designed (see SURVEY.md §0); behavior follows the daccord paper
(Tischler & Myers, bioRxiv 106252) and the driver-pinned seam description in
BASELINE.json. File:line citations must be backfilled per SURVEY.md §8 once the
reference mount is populated.
"""

__version__ = "0.1.0"
