"""Base-level encoding helpers.

Conventions (shared by every layer of the framework):

- Bases are encoded A=0, C=1, G=2, T=3 (the Dazzler 2-bit numbering; reference:
  DAZZ_DB ``DB.h`` Compress_Read / libmaus2 ``dazzler/db`` decode tables —
  file:line to backfill per SURVEY.md §8).
- In-memory sequences are numpy ``int8`` arrays of 0..3; the value 4 is the
  universal PAD sentinel in batched tensors.
- On-disk ``.bps`` packing is 4 bases/byte, first base in the two *highest*
  bits of the byte (Dazzler order).
"""

from __future__ import annotations

import numpy as np

BASES = "ACGT"
PAD = 4

# ASCII -> 0..3 lookup (uppercase + lowercase); everything else maps to 0 (A),
# matching the Dazzler convention of arbitrary-coding unknown characters.
_ASCII_LUT = np.zeros(256, dtype=np.int8)
for _i, _c in enumerate(BASES):
    _ASCII_LUT[ord(_c)] = _i
    _ASCII_LUT[ord(_c.lower())] = _i

_INT_TO_CHAR = np.frombuffer(b"ACGT", dtype=np.uint8)


def seq_to_ints(seq: str | bytes) -> np.ndarray:
    """ASCII sequence -> int8 array of 0..3."""
    if isinstance(seq, str):
        seq = seq.encode("ascii")
    raw = np.frombuffer(seq, dtype=np.uint8)
    return _ASCII_LUT[raw]


def ints_to_seq(arr: np.ndarray) -> str:
    """int8 array of 0..3 -> ASCII string."""
    arr = np.asarray(arr)
    return _INT_TO_CHAR[arr.astype(np.intp)].tobytes().decode("ascii")


def revcomp_ints(arr: np.ndarray) -> np.ndarray:
    """Reverse complement in integer space: complement is 3 - b."""
    return (3 - np.asarray(arr))[::-1].astype(np.int8)


def revcomp_seq(seq: str) -> str:
    return ints_to_seq(revcomp_ints(seq_to_ints(seq)))


def pack_2bit(arr: np.ndarray) -> bytes:
    """Pack 0..3 ints into Dazzler .bps bytes (4 bases/byte, MSB-first).

    Length is padded up with base 0 (A); callers must remember the true length.
    """
    arr = np.asarray(arr, dtype=np.uint8)
    n = len(arr)
    padded = np.zeros(((n + 3) // 4) * 4, dtype=np.uint8)
    padded[:n] = arr
    quads = padded.reshape(-1, 4)
    packed = (quads[:, 0] << 6) | (quads[:, 1] << 4) | (quads[:, 2] << 2) | quads[:, 3]
    return packed.astype(np.uint8).tobytes()


def unpack_2bit(buf: bytes | np.ndarray, length: int) -> np.ndarray:
    """Unpack Dazzler .bps bytes into an int8 array of ``length`` bases."""
    raw = np.frombuffer(buf, dtype=np.uint8) if isinstance(buf, (bytes, bytearray, memoryview)) else np.asarray(buf, dtype=np.uint8)
    out = np.empty(len(raw) * 4, dtype=np.int8)
    out[0::4] = (raw >> 6) & 3
    out[1::4] = (raw >> 4) & 3
    out[2::4] = (raw >> 2) & 3
    out[3::4] = raw & 3
    return out[:length]
