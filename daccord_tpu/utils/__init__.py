from .bases import (
    BASES,
    seq_to_ints,
    ints_to_seq,
    revcomp_ints,
    revcomp_seq,
    pack_2bit,
    unpack_2bit,
)

__all__ = [
    "BASES",
    "seq_to_ints",
    "ints_to_seq",
    "revcomp_ints",
    "revcomp_seq",
    "pack_2bit",
    "unpack_2bit",
]
