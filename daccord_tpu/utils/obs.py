"""Observability: structured jsonl event log + counters.

The reference's observability is unstructured stderr prints plus
``util::Histogram`` dumps (SURVEY.md §5); here every pipeline event is a JSON
line so runs are machine-checkable: windows/sec, bases/sec/chip, per-tier
solve counts, pad-waste ratio — the metrics BASELINE.json tracks.
"""

from __future__ import annotations

import json
import sys
import time


class JsonlLogger:
    def __init__(self, path: str | None = None, stream=None):
        self._fh = None
        if path == "-":
            self._fh = stream or sys.stderr
        elif path:
            self._fh = open(path, "at")
        self._t0 = time.time()

    def log(self, event: str, **fields) -> None:
        if self._fh is None:
            return
        rec = {"t": round(time.time() - self._t0, 3), "event": event, **fields}
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()

    def close(self) -> None:
        if self._fh is not None and self._fh is not sys.stderr:
            self._fh.close()

    # context manager: the short-lived open/log/close triplets (checkpoint
    # commits, fault events) must not leak the fd when an abort path unwinds
    # between open and close
    def __enter__(self) -> "JsonlLogger":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


class NullLogger(JsonlLogger):
    def __init__(self):
        super().__init__(None)


def probe_backend_status(timeout_s: int | None = None) -> tuple[int, str]:
    """(device count, reason) of the default backend, probed from a
    throwaway subprocess: a dead axon tunnel HANGS forever inside
    make_c_api_client (it does not error), which would wedge any process
    that touches the default backend — the subprocess bounds the hang to
    ``timeout_s``. Count 0 means dead/unreachable; the reason string says
    *why* (``probe_timeout`` | ``init_error`` | ``no_devices`` |
    ``probe_error``), the classification bench.py's ``fallback_reason``
    sidecar field records instead of free text. The one probe (and one
    timeout policy) shared by bench.py, ladderbench, __graft_entry__ and the
    CLI's ``--backend auto``; the default 150 s can be overridden
    process-wide via ``DACCORD_PROBE_TIMEOUT_S`` (malformed values fall
    back to 150)."""
    import os
    import subprocess
    import sys

    if timeout_s is None:
        try:
            timeout_s = int(os.environ.get("DACCORD_PROBE_TIMEOUT_S", "150"))
        except ValueError:
            timeout_s = 150

    code = ("import jax, jax.numpy as jnp;"
            "jax.block_until_ready(jnp.ones((8,8)) @ jnp.ones((8,8)));"
            "print('ndev=%d' % len(jax.devices()))")
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return 0, "probe_timeout"
    except Exception:
        return 0, "probe_error"
    for line in r.stdout.decode(errors="replace").splitlines():
        if line.startswith("ndev="):
            try:
                n = int(line.split("=", 1)[1])
            except ValueError:
                # partial write from a killed probe: dead, not a crash
                return 0, "init_error"
            return n, ("ok" if n > 0 else "no_devices")
    return 0, "init_error"


def probe_default_backend(timeout_s: int | None = None) -> int:
    """Device count of the default backend (see probe_backend_status)."""
    return probe_backend_status(timeout_s)[0]


def device_alive(timeout_s: int = 150) -> bool:
    """True iff default-backend init + one matmul succeeds (see probe)."""
    return probe_default_backend(timeout_s) > 0


def resolve_auto_backend(prefer_native: bool = True) -> str:
    """Resolve ``--backend auto`` without ever wedging on a dead tunnel.

    ``jax.default_backend()`` on this image hangs FOREVER when the axon
    tunnel is down (no error, no timeout — see probe_default_backend), so
    "auto" must decide from a bounded subprocess probe BEFORE any in-process
    backend init. Dead tunnel → the native C++ engine when built (fastest
    host path), else the CPU device ladder; either way the process pins
    ``jax_platforms='cpu'`` so no later jax touch can wedge. Probe timeout
    via ``DACCORD_PROBE_TIMEOUT_S`` (see probe_default_backend).
    """
    if probe_default_backend() > 0:
        return "tpu"
    import sys

    import jax

    jax.config.update("jax_platforms", "cpu")
    if prefer_native:
        try:
            from ..native import available as _nat_avail

            if _nat_avail():
                print("daccord: device backend unreachable (probe timed out); "
                      "using the native host engine", file=sys.stderr)
                return "native"
        except Exception:
            pass
    print("daccord: device backend unreachable (probe timed out); "
          "using the CPU device ladder", file=sys.stderr)
    return "cpu"


def auto_batch_size(native: bool, jax_backend: str | None = None) -> int:
    """Batch auto-selection when ``-b`` is not given: the native C++ engine
    pays no shape-scaled compile cost so bigger is strictly better (4096);
    the JAX ladder runs 2048 on TPU, 512 elsewhere. The single source for
    this mapping — ``correct_shard`` sizes its batches with it and the
    fleet's capacity requeue halves it, so the two can never disagree on
    what a worker's effective batch was."""
    if native:
        return 4096
    return 2048 if jax_backend == "tpu" else 512


def env_float(name: str, default: float) -> float:
    """Float env knob with a silent fall-back on unparseable values (the
    runtime config pattern shared by the supervisor and the governor)."""
    import os

    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _host_cpu_fingerprint() -> str:
    """Short stable hash of this host's CPU feature flags.

    XLA:CPU AOT-compiled cache entries embed the build host's CPU features;
    loading them on a host with fewer features can SIGILL (warning observed
    in BENCH_r03 and again in r4: "Machine type used for XLA:CPU compilation
    doesn't match the machine type for execution"). Keying the persistent
    cache directory by CPU flags gives identical hosts a shared cache and a
    differing future host a fresh one — the same hazard rule the native
    ``.so`` rebuild guard applies (native/__init__.py).

    Note (r4 finding): the warning itself fires even for SAME-host cache
    entries, because XLA appends tuning pseudo-features (+prefer-no-scatter,
    +prefer-no-gather) to the compile-time feature string that never appear
    in the parsed host feature list — the named "unsupported" features in a
    same-host load are exactly those two. Treat the warning as noise unless
    a genuine ISA feature is named; this keying removes the genuine case."""
    import hashlib

    flags = ""
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.startswith("flags"):
                    flags = " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except OSError:
        import platform

        flags = platform.machine() + platform.processor()
    return hashlib.sha256(flags.encode()).hexdigest()[:10]


def compcache_dir() -> str | None:
    """The persistent-compile-cache directory this host would use (None when
    opted out via DACCORD_NO_COMPCACHE) — shared by enable_compilation_cache
    and the compile-fingerprint registry below."""
    import os

    if os.environ.get("DACCORD_NO_COMPCACHE"):
        return None
    return os.environ.get("DACCORD_COMPCACHE") or os.path.expanduser(
        "~/.cache/daccord_tpu/xla-" + _host_cpu_fingerprint())


def enable_compilation_cache() -> str | None:
    """Turn on JAX's persistent compilation cache (opt out:
    DACCORD_NO_COMPCACHE=1; relocate: DACCORD_COMPCACHE=dir).

    The ladder compiles one program per (depth, seg-len) bucket shape at
    ~20-40s each on the tunneled TPU; caching them makes repeat CLI runs
    start solving in seconds. Must run before the first jit compilation.
    """
    import os

    path = compcache_dir()
    if path is None:
        return None
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        return path
    except Exception:
        return None


def _fingerprint_path() -> str | None:
    import os

    d = compcache_dir()
    return os.path.join(d, "daccord_shapes.json") if d else None


def fingerprint_seen(key: str) -> bool:
    """True when ``key`` (a ladder shape fingerprint like ``tpu:B2048xD32xL64``)
    was recorded compiled on this host's persistent cache. The supervisor uses
    this for COMPILING-vs-wedged deadline classification; bench.py uses it to
    echo the expected cold-compile wall BEFORE going silent, so a long-quiet
    warmup is not killed as wedged (the r5 failure mode). With the compile
    cache disabled every shape is cold — always False."""
    import json
    import os

    p = _fingerprint_path()
    if p is None or not os.path.exists(p):
        return False
    try:
        with open(p) as fh:
            return key in json.load(fh)
    except (OSError, json.JSONDecodeError):
        return False


def record_fingerprint(key: str) -> None:
    """Record ``key`` as compiled-and-cached (atomic rewrite; best-effort —
    a read-only cache dir must never sink a run)."""
    import json
    import os

    p = _fingerprint_path()
    if p is None:
        return
    try:
        seen: list = []
        if os.path.exists(p):
            with open(p) as fh:
                seen = json.load(fh)
        if key in seen:
            return
        seen.append(key)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = f"{p}.tmp.{os.getpid()}"
        with open(tmp, "wt") as fh:
            json.dump(seen, fh)
        os.replace(tmp, p)
    except (OSError, json.JSONDecodeError):
        pass


def expected_compile_wall_s(batch_rows: int) -> float:
    """Expected COLD server-side XLA compile wall for a ladder program of
    ``batch_rows`` windows, from the measured superlinear scaling on the
    tunneled v5e (2026-08-02: B=256 -> 35 s, 1024 -> 242 s, 2048 -> 925 s;
    the 8192 point was abandoned after extrapolating to hours). Power-law
    anchored at the 1024/2048 pair; a patience estimate for humans and
    deadline classification, not a promise."""
    if batch_rows <= 0:
        return 120.0
    est = 242.0 * (batch_rows / 1024.0) ** 1.93
    return float(min(max(est, 20.0), 4 * 3600.0))


def measure_rtt_s(n: int = 3, timeout_s: float = 30.0) -> float | None:
    """Median round-trip of a tiny blocking device fetch (the fixed
    per-device_get cost the pipeline amortizes; ~60-300 ms through the axon
    tunnel, microseconds locally). None on error OR when the measurement
    itself exceeds ``timeout_s`` — a tunnel that wedges between backend init
    and this call must not hang the caller (it runs on a daemon thread; the
    abandoned thread dies with the process). Only call once a backend is
    already initialized — this is NOT a liveness probe (see
    probe_backend_status for that)."""
    import threading
    import time as _time

    box: list = []

    def work() -> None:
        try:
            import jax
            import jax.numpy as jnp

            tiny = jax.device_put(jnp.zeros(8, jnp.int32))
            jax.block_until_ready(tiny)
            rtts = []
            for _ in range(n):
                t0 = _time.perf_counter()
                jax.device_get(tiny)
                rtts.append(_time.perf_counter() - t0)
            box.append(sorted(rtts)[len(rtts) // 2])
        except Exception:
            pass

    t = threading.Thread(target=work, daemon=True, name="daccord-rtt-probe")
    t.start()
    t.join(timeout_s)
    return box[0] if box else None
