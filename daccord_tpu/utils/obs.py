"""Observability: structured jsonl event log, trace spans, metrics, ledger.

The reference's observability is unstructured stderr prints plus
``util::Histogram`` dumps (SURVEY.md §5); here every pipeline event is a JSON
line so runs are machine-checkable: windows/sec, bases/sec/chip, per-tier
solve counts, pad-waste ratio — the metrics BASELINE.json tracks.

The telemetry spine (ISSUE 6) lives here:

- :class:`JsonlLogger` — every record carries BOTH a process-relative ``t``
  and an absolute wall-clock ``ts`` (epoch seconds), so per-worker event
  files from different processes merge onto one fleet timeline
  (``daccord-trace``). Buffered mode bounds the hot-path cost to one
  syscall per ``buffer_lines`` records (or ``flush_s`` seconds), while
  fault/commit-class events (:data:`DURABLE_EVENTS`) keep line-granularity
  durability by flushing through immediately.
- :class:`Tracer` — hierarchical trace spans (``span_open``/``span_close``
  with ids chaining run → pile → batch → dispatch/fetch/flush/governor-rung)
  over any :class:`JsonlLogger`; span ids are process-unique so merged
  multi-worker files cannot collide.
- :class:`MetricsRegistry` — typed counters/gauges/histograms with periodic
  ``metrics`` snapshot events and an end-of-run rollup dict (committed
  durably beside the shard manifest by ``launch.run_shard``).
- :class:`WindowLedger` — the per-window outcome ledger (window identity,
  length, depth, tier reached, rescue membership, batch solve wall) as a
  jsonl sidecar: the training set ROADMAP item 5's learned window router
  needs, written through the buffered logger so it stays off the hot path.
"""

from __future__ import annotations

import itertools
import json
import os
import sys
import time

#: events that keep line-granularity durability even under a buffered
#: logger: anything a post-mortem needs the instant it happened (faults,
#: checkpoint commits, state machine transitions, quarantine/poison
#: decisions). All of them are off the hot path, so flushing through costs
#: nothing in steady state.
DURABLE_EVENTS = frozenset({
    "sup_fault", "sup_failover", "sup_failback", "sup_state",
    "ingest.fault", "ingest.commit", "ingest.quarantine",
    "fleet.fault", "fleet.poison", "fleet.capacity", "fleet.takeover",
    "governor.classify", "governor.monster",
    # crash-durable serve tier (ISSUE 15): recovery milestones must hit
    # disk at line granularity — they are exactly the records a post-crash
    # investigation reads (the journal itself fsyncs per record; these are
    # its event-stream mirrors)
    "serve.replay", "serve.takeover", "serve.commit", "serve.abort",
    # front door (ISSUE 16): discovery transitions, spills, scale
    # lifecycle, and AOT publish/reject are exactly what a fleet
    # post-mortem replays — all low-rate control-plane rows
    "router.spill", "router.proxy_error", "router.peer_up",
    "router.peer_down", "scale.spawn", "scale.drain", "scale.reap",
    "aot.publish", "aot.reject",
    # storage fault matrix (ISSUE 17): injected/observed I/O failures and
    # disk-pressure transitions are the post-mortem spine of the disk soak
    "io.fault", "disk.pressure", "journal.compact",
    # silent-data-corruption defense plane (ISSUE 20): a detected shadow-
    # verification divergence, the per-member attribution probe, and every
    # device-trust ratchet transition are exactly what the post-mortem (and
    # the BENCH_SDC attribution assert) read from events alone
    "sup_sdc", "audit.attrib", "trust.state", "trust.load",
})


# ---------------------------------------------------------------------------
# Telemetry drop accounting (ISSUE 17). The rule: telemetry writers NEVER
# raise into the data path. A full or failing volume under an events /
# ledger / metrics sidecar drops the buffered lines and counts them here —
# process-wide, because any number of loggers may share the fate of one
# volume — and the count surfaces in every metrics snapshot/rollup so
# ``daccord-sentinel --strict`` can flag a run that flew blind.
# ---------------------------------------------------------------------------

_TEL_DROPPED = 0


def _note_dropped(n: int) -> None:
    global _TEL_DROPPED
    _TEL_DROPPED += int(n)


def telemetry_dropped_total() -> int:
    """Lines dropped by telemetry writers process-wide (0 = none)."""
    return _TEL_DROPPED


def reset_telemetry_dropped() -> None:
    """Test hook: zero the process-wide drop counter."""
    global _TEL_DROPPED
    _TEL_DROPPED = 0


def disk_free_mb(path: str) -> float:
    """Free MiB on the filesystem holding ``path`` (walking up to the
    nearest existing ancestor — a watched dir may not exist yet); -1.0 when
    even statvfs fails. The free-bytes gauge feeding the disk-pressure
    watermark machinery (admission pause, shed ladder, fleet spawn floor),
    mirroring the RSS governor's ``host_rss_mb``."""
    p = os.path.abspath(path or ".")
    while p and not os.path.exists(p):
        parent = os.path.dirname(p)
        if parent == p:
            break
        p = parent
    try:
        st = os.statvfs(p)
    except (OSError, AttributeError):
        return -1.0
    return st.f_bavail * st.f_frsize / float(1 << 20)


class JsonlLogger:
    def __init__(self, path: str | None = None, stream=None,
                 buffer_lines: int = 1, flush_s: float = 0.0):
        """``buffer_lines=1`` (default) flushes after every record — the
        historical behavior, right for low-rate loggers whose readers poll
        mid-run. Hot-path writers (the pipeline's event/ledger streams) pass
        ``buffer_lines``>1 plus a ``flush_s`` cadence bound; records in
        :data:`DURABLE_EVENTS` always flush through, and ``close()``
        flushes the tail."""
        self._fh = None
        if path == "-":
            self._fh = stream or sys.stderr
        elif path:
            self._fh = open(path, "at")
        self._t0 = time.time()
        self._buf: list[str] = []
        self._buffer_lines = max(1, int(buffer_lines))
        self._flush_s = flush_s
        self._last_flush = self._t0

    def log(self, event: str, **fields) -> None:
        if self._fh is None:
            return
        now = time.time()
        # t = process-relative (human-scale deltas within one run); ts =
        # absolute epoch (the cross-process merge key — every fleet worker's
        # t0 differs, so t alone cannot order a multi-host timeline)
        rec = {"t": round(now - self._t0, 3), "ts": round(now, 6),
               "event": event, **fields}
        self._buf.append(json.dumps(rec) + "\n")
        if (len(self._buf) >= self._buffer_lines
                or event in DURABLE_EVENTS
                or (self._flush_s and now - self._last_flush >= self._flush_s)):
            self.flush()

    def flush(self) -> None:
        if self._fh is None or not self._buf:
            return
        try:
            from . import aio

            aio.io_gate("sidecar", op="events")
            # one write call for the whole buffer: complete lines only, so
            # concurrent appenders (launch.py's checkpoint logger shares the
            # worker's events file) interleave at line granularity
            self._fh.write("".join(self._buf))
            self._fh.flush()
        except (OSError, ValueError):
            # telemetry NEVER raises into the data path (ISSUE 17): a full
            # or failing volume under a sidecar drops the buffered lines and
            # counts them — the serve ticker and fleet heartbeat threads
            # writing through here must not die for an events file.
            # ValueError is the racing-close case ("I/O operation on closed
            # file"), tolerated since the serve drain window existed.
            _note_dropped(len(self._buf))
        self._buf.clear()
        self._last_flush = time.time()

    def close(self) -> None:
        self.flush()
        if self._fh is not None and self._fh is not sys.stderr:
            try:
                self._fh.close()
            except OSError:
                _note_dropped(0)  # OS-buffer tail lost; nothing countable
        # a closed logger silently drops later records instead of raising
        # "I/O operation on closed file": long-lived writers (the serve
        # plane's shutdown drain window) may race a final log against close
        self._fh = None

    # context manager: the short-lived open/log/close triplets (checkpoint
    # commits, fault events) must not leak the fd when an abort path unwinds
    # between open and close
    def __enter__(self) -> "JsonlLogger":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


class NullLogger(JsonlLogger):
    def __init__(self):
        super().__init__(None)


#: process-wide span id counter: several Tracer instances may share one
#: events file (pipeline + supervisor default), so uniqueness must not
#: depend on which instance minted the id
_SPAN_IDS = itertools.count(1)


class Tracer:
    """Hierarchical trace spans over a :class:`JsonlLogger`.

    ``open`` emits ``span_open`` (id, parent, name) and pushes the span on
    the parent stack; ``close`` emits ``span_close`` with the measured wall.
    Ids are ``<pid-hex>-<n>`` so files merged across fleet workers cannot
    collide. Non-nested spans (a batch open at dispatch, closed at fetch
    several piles later) pass ``attach=False`` with an explicit ``parent``
    so the stack stays well-formed. ``unwind`` closes every span still open
    (status=abort) — called from the owners' ``finally`` blocks so abort
    and failover paths keep the every-open-has-a-close invariant that
    ``daccord-trace --check`` enforces.
    """

    def __init__(self, log: JsonlLogger | None):
        self.log = log if log is not None else NullLogger()
        self.enabled = self.log._fh is not None
        self._pid = "%x" % os.getpid()
        self._stack: list[str] = []
        self._open: dict[str, tuple[str, float]] = {}

    def open(self, name: str, parent: str | None = None, attach: bool = True,
             **fields) -> str | None:
        if not self.enabled:
            return None
        sid = f"{self._pid}-{next(_SPAN_IDS)}"
        if parent is None:
            parent = self._stack[-1] if self._stack else ""
        self._open[sid] = (name, time.time())
        if attach:
            self._stack.append(sid)
        self.log.log("span_open", span=sid, parent=parent, name=name, **fields)
        return sid

    def close(self, sid: str | None, **fields) -> None:
        if sid is None:
            return
        name, t0 = self._open.pop(sid, (None, 0.0))
        if name is None:
            return   # unknown/already closed: keep close idempotent
        if sid in self._stack:
            # normally the top; an out-of-order close (abort unwind) must
            # not strand descendants' parent pointers
            self._stack.remove(sid)
        self.log.log("span_close", span=sid, name=name,
                     wall_s=round(time.time() - t0, 6), **fields)

    def span(self, name: str, **fields):
        """Context manager form; closes with ``status=error`` on exception."""
        return _SpanCtx(self, name, fields)

    def unwind(self, status: str = "abort") -> None:
        """Close every span still open, innermost first."""
        for sid in sorted(self._open,
                          key=lambda s: self._open[s][1], reverse=True):
            self.close(sid, status=status)


class _SpanCtx:
    __slots__ = ("_tr", "_name", "_fields", "sid")

    def __init__(self, tracer: Tracer, name: str, fields: dict):
        self._tr, self._name, self._fields = tracer, name, fields
        self.sid = None

    def __enter__(self):
        self.sid = self._tr.open(self._name, **self._fields)
        return self.sid

    def __exit__(self, et, ev, tb) -> bool:
        if et is None:
            self._tr.close(self.sid)
        else:
            self._tr.close(self.sid, status="error")
        return False


# ---------------------------------------------------------------------------
# Saturation profiler (ISSUE 14): per-stage host-feeder accounting, device
# starvation gauges, and the automatic bottleneck verdict. The StageProfile
# is the one registry the pipeline's feeder call sites, the tensorize/paging
# kernels, feederbench, and daccord-prof all speak.
# ---------------------------------------------------------------------------

#: canonical feeder sub-stage names, in pipeline order. ``decode`` = LAS/DB
#: byte decode (ColumnarLas parse, read_bases), ``rank`` = depth-ranking
#: score+sort, ``realign`` = trace-point refinement / the native pile
#: processor (which fuses realign + window cut + tensorize in C++ — its wall
#: books here, so python-path ``kmer``/``tensorize`` read 0 on native runs),
#: ``kmer`` = cut_windows k-mer extraction (python path), ``tensorize`` =
#: tensorize_windows packing, ``pack`` = pad_batch / pack_paged at dispatch
#: assembly, ``stall`` = injected feeder_stall fault delay (faults.py).
FEEDER_STAGES = ("decode", "rank", "realign", "kmer", "tensorize", "pack",
                 "stall")

#: verdict thresholds. A run is ``device``-bound when the host spends at
#: least this fraction of wall blocked on the device (dispatch for inline
#: engines, fetch for async ones); it is starved (``host_feeder``/``io``)
#: when the device sits idle at least this fraction of wall. Between the
#: two: ``balanced``.
VERDICT_BLOCKED_FRAC = 0.40
VERDICT_IDLE_FRAC = 0.40


class StageProfile:
    """Per-stage wall-clock accounting of the host feeder.

    Always-on and deliberately tiny: one ``perf_counter`` pair per timed
    region (per pile / per batch, never per window) folded into a dict under
    a lock — measured well under the 2% hot-path budget. ``threads`` records
    the feeder pool width: with N windowing threads the per-stage walls sum
    ACROSS threads (CPU-time-like), so reconciliation against the pipeline's
    blocked-on-feeder wall must scale by it (``daccord-prof --check``).
    """

    __slots__ = ("_lock", "walls", "calls", "threads")

    def __init__(self, threads: int = 1):
        import threading

        self._lock = threading.Lock()
        self.walls: dict[str, float] = {}
        self.calls: dict[str, int] = {}
        self.threads = max(1, int(threads))

    def add(self, stage: str, wall_s: float, calls: int = 1) -> None:
        with self._lock:
            self.walls[stage] = self.walls.get(stage, 0.0) + float(wall_s)
            self.calls[stage] = self.calls.get(stage, 0) + calls

    def timed(self, stage: str):
        """Context manager form (perf_counter pair around the block)."""
        return _StageTimer(self, stage)

    def wall(self, stage: str) -> float:
        return self.walls.get(stage, 0.0)

    def total(self) -> float:
        """Summed wall over every stage (thread-summed, see class doc)."""
        return sum(self.walls.values())

    def dominant(self) -> tuple[str | None, float]:
        """(stage, wall) of the heaviest stage; (None, 0.0) when empty."""
        if not self.walls:
            return None, 0.0
        name = max(self.walls, key=lambda k: self.walls[k])
        return name, self.walls[name]

    def summary(self) -> dict:
        """The committed form: ``{"threads": n, "stages": {name: {"wall_s",
        "calls"}}}`` — what ``stage.profile`` events, ``shard_done.stages``
        readers, and the FEEDER_r* sidecars carry."""
        with self._lock:
            return {"threads": self.threads,
                    "stages": {k: {"wall_s": round(self.walls[k], 6),
                                   "calls": self.calls.get(k, 0)}
                               for k in sorted(self.walls)}}


class _StageTimer:
    __slots__ = ("_prof", "_stage", "_t0")

    def __init__(self, prof: StageProfile, stage: str):
        self._prof, self._stage = prof, stage

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        self._prof.add(self._stage, time.perf_counter() - self._t0)
        return False


def saturation_gauges(wall_s: float, blocked_s: float,
                      busy_s: float) -> dict:
    """Device starvation/overlap gauges from three measured walls.

    ``blocked_s`` = host wall spent WAITING on the device (fetch for async
    engines, plus dispatch for inline/synchronous ones — the feeder can do
    nothing else then); ``busy_s`` = wall during which the device (or inline
    solve engine) had work. Derived:

    - ``device_idle_frac`` — device gaps while the host was busy feeding
      (the starvation signal device-side ingest must close);
    - ``host_blocked_frac`` — feeder waiting on the device (the signal a
      bigger batch / deeper in-flight window closes);
    - ``overlap_frac`` — both sides productive at once (the pipelining win).
    """
    w = max(float(wall_s), 1e-9)
    blocked = min(max(float(blocked_s), 0.0), w)
    busy = min(max(float(busy_s), 0.0), w)
    return {"device_idle_frac": round(max(w - busy, 0.0) / w, 4),
            "host_blocked_frac": round(blocked / w, 4),
            "overlap_frac": round(max(busy - blocked, 0.0) / w, 4)}


def bottleneck_verdict(gauges: dict, stages: dict | None = None) -> dict:
    """The automatic per-run bottleneck attribution (ISSUE 14).

    ``gauges`` is a :func:`saturation_gauges` dict; ``stages`` the
    ``StageProfile.summary()['stages']`` table (optional — gauge-only
    callers like the serve plane pass None). Returns ``{"verdict":
    'host_feeder'|'device'|'io'|'balanced', "stage": <dominant feeder
    sub-stage or None>, **gauges}``. Rules, in precedence order:

    - host blocked on the device >= :data:`VERDICT_BLOCKED_FRAC` of wall:
      the DEVICE is the bottleneck;
    - device idle >= :data:`VERDICT_IDLE_FRAC` of wall: the host side is —
      ``io`` when the dominant feeder sub-stage is byte decode (the disk /
      decompression path), else ``host_feeder`` (compute: realign, k-mer,
      tensorize, pack, or an injected stall);
    - otherwise ``balanced``.
    """
    dom = None
    if stages:
        dom = max(stages, key=lambda k: stages[k].get("wall_s", 0.0))
    if gauges.get("host_blocked_frac", 0.0) >= VERDICT_BLOCKED_FRAC:
        verdict = "device"
    elif gauges.get("device_idle_frac", 0.0) >= VERDICT_IDLE_FRAC:
        verdict = "io" if dom == "decode" else "host_feeder"
    else:
        verdict = "balanced"
    return {"verdict": verdict, "stage": dom, **gauges}


class _Counter:
    __slots__ = ("n",)

    def __init__(self):
        self.n = 0

    def inc(self, n: int = 1) -> None:
        self.n += n


class _Gauge:
    __slots__ = ("v",)

    def __init__(self):
        self.v = 0.0

    def set(self, v: float) -> None:
        self.v = float(v)


#: bounded per-histogram sample (reservoir) backing the quantile estimates;
#: 512 doubles per histogram is noise memory-wise and keeps p99 exact for
#: any run under ~50k observations' worth of tail resolution
_HIST_RESERVOIR = 512


class _Histogram:
    """Count/sum/min/max, coarse log2 buckets, and p50/p95/p99 quantiles
    from a bounded reservoir sample (latency is a quantile metric — a serving
    decision made on count/sum alone hides exactly the tail it is about).
    The reservoir uses a per-instance seeded RNG, so a run's quantile
    estimates are deterministic given its observation sequence."""

    __slots__ = ("count", "total", "vmin", "vmax", "buckets", "samples",
                 "_rng")

    def __init__(self):
        import random

        self.count = 0
        self.total = 0.0
        self.vmin = None
        self.vmax = None
        self.buckets: dict[int, int] = {}
        self.samples: list[float] = []
        self._rng = random.Random(0xDACC)

    def observe(self, v: float) -> None:
        v = float(v)
        self.count += 1
        self.total += v
        self.vmin = v if self.vmin is None else min(self.vmin, v)
        self.vmax = v if self.vmax is None else max(self.vmax, v)
        b = max(-30, min(30, int(v).bit_length() if v >= 1
                         else -int(1.0 / max(v, 1e-9)).bit_length()))
        self.buckets[b] = self.buckets.get(b, 0) + 1
        # Vitter reservoir: every observation has an equal chance of being
        # in the sample once count > capacity
        if len(self.samples) < _HIST_RESERVOIR:
            self.samples.append(v)
        else:
            j = self._rng.randrange(self.count)
            if j < _HIST_RESERVOIR:
                self.samples[j] = v

    def quantile(self, q: float) -> float | None:
        """Nearest-rank quantile over the reservoir (exact while count <=
        reservoir capacity; an unbiased estimate beyond)."""
        if not self.samples:
            return None
        s = sorted(self.samples)
        return s[min(int(q * len(s)), len(s) - 1)]

    def summary(self) -> dict:
        return {"count": self.count, "sum": round(self.total, 6),
                "min": self.vmin, "max": self.vmax,
                "mean": round(self.total / self.count, 6) if self.count else None,
                # the satellite contract (ISSUE 10): quantiles ride every
                # periodic `metrics` snapshot AND the durable rollup
                "p50": self.quantile(0.50), "p95": self.quantile(0.95),
                "p99": self.quantile(0.99)}


class MetricsRegistry:
    """Typed metrics registry: counters, gauges, histograms.

    ``snapshot(log)`` emits one ``metrics`` event with every current value
    (periodic — the pipeline calls it at a bounded cadence from the pile
    loop); ``rollup()`` returns the end-of-run dict that ``launch.run_shard``
    commits durably beside the shard manifest."""

    def __init__(self):
        self._counters: dict[str, _Counter] = {}
        self._gauges: dict[str, _Gauge] = {}
        self._hists: dict[str, _Histogram] = {}

    def counter(self, name: str) -> _Counter:
        return self._counters.setdefault(name, _Counter())

    def gauge(self, name: str) -> _Gauge:
        return self._gauges.setdefault(name, _Gauge())

    def histogram(self, name: str) -> _Histogram:
        return self._hists.setdefault(name, _Histogram())

    def _counter_view(self) -> dict:
        out = {k: c.n for k, c in sorted(self._counters.items())}
        # the process-wide telemetry drop count rides every snapshot/rollup
        # — but only once nonzero, so committed baselines predating ISSUE 17
        # don't see a phantom new counter on clean runs
        if _TEL_DROPPED and "telemetry_dropped_total" not in out:
            out["telemetry_dropped_total"] = _TEL_DROPPED
        return out

    def snapshot(self, log: JsonlLogger, **extra) -> None:
        log.log("metrics",
                counters=self._counter_view(),
                gauges={k: round(g.v, 6)
                        for k, g in sorted(self._gauges.items())},
                hists={k: h.summary() for k, h in sorted(self._hists.items())},
                **extra)

    def rollup(self) -> dict:
        return {"counters": self._counter_view(),
                "gauges": {k: round(g.v, 6)
                           for k, g in sorted(self._gauges.items())},
                "hists": {k: h.summary()
                          for k, h in sorted(self._hists.items())}}


# ---------------------------------------------------------------------------
# Prometheus text exposition (the live health plane, ISSUE 13): the one
# render/parse pair shared by the serve HTTP endpoint
# (GET /v1/metrics?format=prom), the durable shard/fleet .prom dumps, and
# the pounce scrape checker — producer and lint can never drift apart.
# ---------------------------------------------------------------------------

def _prom_name(name: str) -> str:
    """A registry metric name as a legal Prometheus metric name."""
    import re

    n = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    return n if not n[:1].isdigit() else "_" + n


def _prom_labels(labels: dict | None) -> str:
    if not labels:
        return ""
    import json as _json

    return "{" + ",".join(
        f"{_prom_name(str(k))}={_json.dumps(str(v))}"
        for k, v in sorted(labels.items())) + "}"


def render_prom(rollup: dict, prefix: str = "daccord",
                labels: dict | None = None) -> str:
    """Prometheus text exposition (format 0.0.4) of a registry rollup dict
    (:meth:`MetricsRegistry.rollup`, or the ``metrics`` key of a committed
    ``*.metrics.json``). Counters render as ``<prefix>_<name>_total``,
    gauges as ``<prefix>_<name>``, histograms as summaries (``_count``,
    ``_sum``, and ``quantile`` series from the reservoir p50/p95/p99).
    ``labels`` (e.g. ``{"shard": 3}``) ride every sample, so fleet-merged
    scrapes keep per-shard attribution. A rollup carrying a ``verdict``
    string (the ISSUE 14 bottleneck attribution) renders it as
    ``<prefix>_bottleneck_verdict{verdict="..."} 1`` — the field the serve
    smoke asserts is present in the live exposition."""
    lab = _prom_labels(labels)
    lines: list[str] = []
    verdict = rollup.get("verdict")
    if isinstance(verdict, str) and verdict:
        mn = f"{_prom_name(prefix)}_bottleneck_verdict"
        vl = _prom_labels(dict(labels or {}, verdict=verdict))
        lines.append(f"# TYPE {mn} gauge")
        lines.append(f"{mn}{vl} 1")
    for name, v in (rollup.get("counters") or {}).items():
        mn = f"{_prom_name(prefix)}_{_prom_name(name)}_total"
        lines.append(f"# TYPE {mn} counter")
        lines.append(f"{mn}{lab} {int(v)}")
    for name, v in (rollup.get("gauges") or {}).items():
        mn = f"{_prom_name(prefix)}_{_prom_name(name)}"
        lines.append(f"# TYPE {mn} gauge")
        lines.append(f"{mn}{lab} {float(v):g}")
    for name, h in (rollup.get("hists") or {}).items():
        mn = f"{_prom_name(prefix)}_{_prom_name(name)}"
        lines.append(f"# TYPE {mn} summary")
        for q, key in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
            val = h.get(key)
            if val is None:
                continue
            ql = dict(labels or {}, quantile=q)
            lines.append(f"{mn}{_prom_labels(ql)} {float(val):g}")
        lines.append(f"{mn}_count{lab} {int(h.get('count') or 0)}")
        lines.append(f"{mn}_sum{lab} {float(h.get('sum') or 0.0):g}")
    return "\n".join(lines) + "\n"


def parse_prom(text: str) -> tuple[dict, list[str]]:
    """Parse/lint a Prometheus text exposition: returns
    ``({metric_name: [(labels_str, value)]}, errors)``. The checker the
    pounce scrape gate runs — every sample line must be
    ``name[{labels}] value`` with a finite float value, every ``# TYPE``
    must name a known type, and a typed metric must have >= 1 sample."""
    import math
    import re

    samples: dict[str, list] = {}
    errs: list[str] = []
    typed: dict[str, str] = {}
    line_re = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)$")
    for ln, line in enumerate(text.splitlines(), start=1):
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 2 and parts[1] == "TYPE":
                if len(parts) != 4 or parts[3] not in (
                        "counter", "gauge", "summary", "histogram",
                        "untyped"):
                    errs.append(f"line {ln}: malformed TYPE comment")
                else:
                    typed[parts[2]] = parts[3]
            continue
        m = line_re.match(line)
        if m is None:
            errs.append(f"line {ln}: not a sample line: {line[:80]!r}")
            continue
        name, _labels, val = m.groups()
        try:
            fv = float(val)
        except ValueError:
            errs.append(f"line {ln}: {name}: non-numeric value {val!r}")
            continue
        if math.isnan(fv) or math.isinf(fv):
            errs.append(f"line {ln}: {name}: non-finite value {val!r}")
            continue
        samples.setdefault(name, []).append((_labels or "", fv))
    for name, kind in typed.items():
        base = [k for k in samples
                if k == name or (kind in ("summary", "histogram")
                                 and k.startswith(name))]
        if not base:
            errs.append(f"TYPE {name} declared but no samples follow")
    return samples, errs


class WindowLedger:
    """Per-window outcome ledger: one ``window`` jsonl row per window the
    pipeline accounted — the exact training set the learned window router
    (ROADMAP item 5) needs. Rows are written through a buffered
    :class:`JsonlLogger` (appending: a checkpointed resume continues the
    sidecar; fresh runs remove the file first, the quarantine-sidecar rule).

    ``wall_s`` is the window's batch turnaround (dispatch → scatter): windows
    solve batched, so per-window wall is attributable only at batch
    granularity. Rows record the outcome at solve time — a later end-trim
    (rescue-tier read ends) does not rewrite them."""

    def __init__(self, path: str):
        self.log = JsonlLogger(path, buffer_lines=256, flush_s=5.0)
        self.rows = 0

    def record(self, aread: int, widx: int, length: int, depth: int,
               tier: int, k: int, solved: bool, stream: str, rescued: bool,
               wall_s: float, job: str | None = None, mesh: int = 0) -> None:
        self.rows += 1
        log = self.log
        if log._fh is None:
            return
        # hand-built line (fixed schema, scalar fields only): one ledger row
        # per window is the highest-volume telemetry record, and skipping
        # json.dumps keeps it ~3x cheaper — the hot-path budget (<=2% on the
        # native engine) is spent mostly here. `job` (ISSUE 10 satellite:
        # the serving plane's per-workload tag) and `mesh` (the solve path's
        # mesh width — lets the ROADMAP-4 router training set segment by
        # mesh configuration) are optional so non-serve / non-mesh ledgers
        # stay byte-for-byte what they were
        now = time.time()
        # json.dumps, not raw interpolation: job_tag is a public config
        # field, and a quote/backslash in it would corrupt every row
        jf = ', "job": %s' % json.dumps(job) if job else ""
        if mesh:
            jf += ', "mesh": %d' % mesh
        log._buf.append(
            '{"t": %.3f, "ts": %.6f, "event": "window", "aread": %d, '
            '"widx": %d, "len": %d, "depth": %d, "tier": %d, "k": %d, '
            '"solved": %s, "stream": "%s", "rescued": %s, "wall_s": %.6f%s}\n'
            % (now - log._t0, now, aread, widx, length, depth, tier, k,
               "true" if solved else "false", stream,
               "true" if rescued else "false", wall_s, jf))
        if (len(log._buf) >= log._buffer_lines
                or (log._flush_s and now - log._last_flush >= log._flush_s)):
            log.flush()

    def close(self) -> None:
        self.log.close()


def device_peak_bytes() -> int | None:
    """Peak device memory of device 0 via ``memory_stats()`` (None when the
    backend does not report it — CPU usually, or jax untouched). Callers
    gate on a device path: this initializes the default backend if nothing
    has yet."""
    if "jax" not in sys.modules:
        return None
    try:
        import jax

        ms = jax.devices()[0].memory_stats()
        if not ms or "peak_bytes_in_use" not in ms:
            return None
        return int(ms["peak_bytes_in_use"])
    except Exception:
        return None


def probe_backend_status(timeout_s: int | None = None) -> tuple[int, str]:
    """(device count, reason) of the default backend, probed from a
    throwaway subprocess: a dead axon tunnel HANGS forever inside
    make_c_api_client (it does not error), which would wedge any process
    that touches the default backend — the subprocess bounds the hang to
    ``timeout_s``. Count 0 means dead/unreachable; the reason string says
    *why* (``probe_timeout`` | ``init_error`` | ``no_devices`` |
    ``probe_error``), the classification bench.py's ``fallback_reason``
    sidecar field records instead of free text. The one probe (and one
    timeout policy) shared by bench.py, ladderbench, __graft_entry__ and the
    CLI's ``--backend auto``; the default 150 s can be overridden
    process-wide via ``DACCORD_PROBE_TIMEOUT_S`` (malformed values fall
    back to 150)."""
    import os
    import subprocess
    import sys

    if timeout_s is None:
        try:
            timeout_s = int(os.environ.get("DACCORD_PROBE_TIMEOUT_S", "150"))
        except ValueError:
            timeout_s = 150

    code = ("import jax, jax.numpy as jnp;"
            "jax.block_until_ready(jnp.ones((8,8)) @ jnp.ones((8,8)));"
            "print('ndev=%d' % len(jax.devices()))")
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return 0, "probe_timeout"
    except Exception:
        return 0, "probe_error"
    for line in r.stdout.decode(errors="replace").splitlines():
        if line.startswith("ndev="):
            try:
                n = int(line.split("=", 1)[1])
            except ValueError:
                # partial write from a killed probe: dead, not a crash
                return 0, "init_error"
            return n, ("ok" if n > 0 else "no_devices")
    return 0, "init_error"


def probe_default_backend(timeout_s: int | None = None) -> int:
    """Device count of the default backend (see probe_backend_status)."""
    return probe_backend_status(timeout_s)[0]


def device_alive(timeout_s: int = 150) -> bool:
    """True iff default-backend init + one matmul succeeds (see probe)."""
    return probe_default_backend(timeout_s) > 0


def resolve_auto_backend(prefer_native: bool = True) -> str:
    """Resolve ``--backend auto`` without ever wedging on a dead tunnel.

    ``jax.default_backend()`` on this image hangs FOREVER when the axon
    tunnel is down (no error, no timeout — see probe_default_backend), so
    "auto" must decide from a bounded subprocess probe BEFORE any in-process
    backend init. Dead tunnel → the native C++ engine when built (fastest
    host path), else the CPU device ladder; either way the process pins
    ``jax_platforms='cpu'`` so no later jax touch can wedge. Probe timeout
    via ``DACCORD_PROBE_TIMEOUT_S`` (see probe_default_backend).
    """
    if probe_default_backend() > 0:
        return "tpu"
    import sys

    import jax

    jax.config.update("jax_platforms", "cpu")
    if prefer_native:
        try:
            from ..native import available as _nat_avail

            if _nat_avail():
                print("daccord: device backend unreachable (probe timed out); "
                      "using the native host engine", file=sys.stderr)
                return "native"
        except Exception:
            pass
    print("daccord: device backend unreachable (probe timed out); "
          "using the CPU device ladder", file=sys.stderr)
    return "cpu"


def auto_batch_size(native: bool, jax_backend: str | None = None,
                    mesh: int = 0) -> int:
    """Batch auto-selection when ``-b`` is not given: the native C++ engine
    pays no shape-scaled compile cost so bigger is strictly better (4096);
    the JAX ladder runs 2048 on TPU, 512 elsewhere — times the mesh width
    when batches shard over a device mesh (one host, N chips is ONE worker:
    each device's slice keeps the single-device width). The single source
    for this mapping — ``correct_shard`` sizes its batches with it and the
    fleet's capacity requeue halves it, so the two can never disagree on
    what a worker's effective batch was."""
    if native:
        return 4096
    base = 2048 if jax_backend == "tpu" else 512
    return base * max(int(mesh or 0), 1)


def env_float(name: str, default: float) -> float:
    """Float env knob with a silent fall-back on unparseable values (the
    runtime config pattern shared by the supervisor and the governor)."""
    import os

    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _host_cpu_fingerprint() -> str:
    """Short stable hash of this host's CPU feature flags.

    XLA:CPU AOT-compiled cache entries embed the build host's CPU features;
    loading them on a host with fewer features can SIGILL (warning observed
    in BENCH_r03 and again in r4: "Machine type used for XLA:CPU compilation
    doesn't match the machine type for execution"). Keying the persistent
    cache directory by CPU flags gives identical hosts a shared cache and a
    differing future host a fresh one — the same hazard rule the native
    ``.so`` rebuild guard applies (native/__init__.py).

    Note (r4 finding): the warning itself fires even for SAME-host cache
    entries, because XLA appends tuning pseudo-features (+prefer-no-scatter,
    +prefer-no-gather) to the compile-time feature string that never appear
    in the parsed host feature list — the named "unsupported" features in a
    same-host load are exactly those two. Treat the warning as noise unless
    a genuine ISA feature is named; this keying removes the genuine case."""
    import hashlib

    flags = ""
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.startswith("flags"):
                    flags = " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except OSError:
        import platform

        flags = platform.machine() + platform.processor()
    return hashlib.sha256(flags.encode()).hexdigest()[:10]


def compcache_dir() -> str | None:
    """The persistent-compile-cache directory this host would use (None when
    opted out via DACCORD_NO_COMPCACHE) — shared by enable_compilation_cache
    and the compile-fingerprint registry below."""
    import os

    if os.environ.get("DACCORD_NO_COMPCACHE"):
        return None
    return os.environ.get("DACCORD_COMPCACHE") or os.path.expanduser(
        "~/.cache/daccord_tpu/xla-" + _host_cpu_fingerprint())


def enable_compilation_cache() -> str | None:
    """Turn on JAX's persistent compilation cache (opt out:
    DACCORD_NO_COMPCACHE=1; relocate: DACCORD_COMPCACHE=dir).

    The ladder compiles one program per (depth, seg-len) bucket shape at
    ~20-40s each on the tunneled TPU; caching them makes repeat CLI runs
    start solving in seconds. Must run before the first jit compilation.
    """
    import os

    path = compcache_dir()
    if path is None:
        return None
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        return path
    except Exception:
        return None


def _fingerprint_path() -> str | None:
    import os

    d = compcache_dir()
    return os.path.join(d, "daccord_shapes.json") if d else None


def fingerprint_registry() -> dict:
    """The compile-fingerprint registry as a dict ``{key: meta}`` where meta
    carries whatever compile telemetry was recorded (``wall_s``, ``ts``,
    HLO cost fields). Reads BOTH formats: the pre-ISSUE-13 registry was a
    bare list of keys (meta then ``{}``). Empty dict when the compile cache
    is disabled or the registry is unreadable."""
    import json
    import os

    p = _fingerprint_path()
    if p is None or not os.path.exists(p):
        return {}
    try:
        with open(p) as fh:
            d = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return {}
    if isinstance(d, dict):
        return {str(k): (v if isinstance(v, dict) else {}) for k, v in d.items()}
    if isinstance(d, list):
        return {str(k): {} for k in d}
    return {}


def fingerprint_seen(key: str) -> bool:
    """True when ``key`` (a ladder shape fingerprint like ``tpu:B2048xD32xL64``)
    was recorded compiled on this host's persistent cache. The supervisor uses
    this for COMPILING-vs-wedged deadline classification; bench.py uses it to
    echo the expected cold-compile wall BEFORE going silent, so a long-quiet
    warmup is not killed as wedged (the r5 failure mode). With the compile
    cache disabled every shape is cold — always False."""
    return key in fingerprint_registry()


def record_fingerprint(key: str, wall_s: float | None = None,
                       meta: dict | None = None) -> None:
    """Record ``key`` as compiled-and-cached (atomic rewrite; best-effort —
    a read-only cache dir must never sink a run). ``wall_s`` is the measured
    cold-compile wall (the supervisor times its fresh guarded dispatches),
    ``meta`` any extra compile telemetry (HLO flops/bytes from an AOT
    lower+compile) — both fold into the registry entry, accumulating a
    host-local per-shape compile-cost history for offline drift analysis
    (``daccord-sentinel`` gates committed sidecars, not this registry).
    Re-recording a known key only ever ADDS telemetry (first recorded wall
    wins: that is the cold one)."""
    import json
    import os
    import time as _time

    p = _fingerprint_path()
    if p is None:
        return
    try:
        reg = fingerprint_registry()
        entry = reg.get(key)
        fresh_info = {}
        if wall_s is not None:
            fresh_info["wall_s"] = round(float(wall_s), 3)
        if meta:
            fresh_info.update(meta)
        if entry is None:
            entry = {"ts": round(_time.time(), 1), **fresh_info}
        else:
            added = {k: v for k, v in fresh_info.items() if k not in entry}
            if not added:
                return
            entry = {**entry, **added}
        reg[key] = entry
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = f"{p}.tmp.{os.getpid()}"
        with open(tmp, "wt") as fh:
            json.dump(reg, fh)
        os.replace(tmp, p)
    except (OSError, json.JSONDecodeError):
        pass


# ---------------------------------------------------------------------------
# Content digests (ISSUE 20). ONE implementation feeds every durable
# boundary of the integrity chain: shard-manifest stamping, the merge gate's
# content verify, the serve journal's committing record, and daccord-audit.
# ---------------------------------------------------------------------------


def sha256_file(path: str, limit: int | None = None,
                chunk: int = 1 << 20) -> str:
    """Streaming sha256 hex digest of a file's content (first ``limit``
    bytes when given — the journal records the fsync'd prefix length, so a
    finalize verifies exactly the bytes it is about to publish). Speaks aio
    URLs like every durable reader (mem: fixtures hash too)."""
    import hashlib

    from . import aio

    h = hashlib.sha256()
    remaining = limit
    with aio.open_input(path, "rb") as fh:
        while remaining is None or remaining > 0:
            n = chunk if remaining is None else min(chunk, remaining)
            b = fh.read(n)
            if not b:
                break
            h.update(b)
            if remaining is not None:
                remaining -= len(b)
    return h.hexdigest()


def result_digest(out: dict, rows=None) -> str:
    """Canonical sha256 of a (packed-wire) solver result dict — the
    per-window bytes the FASTA is assembled from: ``solved`` flag,
    ``cons_len``, and the live consensus bytes per row. Deliberately
    EXCLUDES err/tier/m_ovf: those steer routing, never output bytes, so
    two engines at byte parity digest equal even where float err differs in
    the last ulp. ``rows`` restricts to a row subset (the shadow audit
    digests its sample)."""
    import hashlib

    import numpy as np

    h = hashlib.sha256()
    cons = np.asarray(out["cons"])
    cons_len = np.asarray(out["cons_len"])
    solved = np.asarray(out["solved"])
    idx = range(len(cons)) if rows is None else rows
    for i in idx:
        ok = bool(solved[i])
        h.update(b"\x01" if ok else b"\x00")
        if ok:
            cl = int(cons_len[i])
            h.update(cl.to_bytes(4, "little"))
            h.update(np.ascontiguousarray(cons[i, :cl]).tobytes())
    return h.hexdigest()


def row_digests(batch) -> list:
    """Per-window sha256 digests of a :class:`WindowBatch`'s live content
    (identity + ragged segment bytes; pad cells excluded). The anchor of the
    window→batch→shard composition property: ``pack_paged``/``unpack_paged``/
    ``to_dense`` round-trips and ``slice_batch`` row slices must preserve
    these exactly — re-batching can never change a window's bytes."""
    import hashlib

    import numpy as np

    if getattr(batch, "pool", None) is not None:
        batch = batch.to_dense()
    out = []
    for i in range(batch.size):
        h = hashlib.sha256()
        h.update(int(batch.read_ids[i]).to_bytes(8, "little", signed=True))
        h.update(int(batch.wstarts[i]).to_bytes(8, "little", signed=True))
        d = int(batch.nsegs[i])
        h.update(d.to_bytes(4, "little"))
        for di in range(d):
            ln = int(batch.lens[i, di])
            h.update(ln.to_bytes(4, "little"))
            h.update(np.ascontiguousarray(
                batch.seqs[i, di, :ln]).tobytes())
        out.append(h.hexdigest())
    return out


def batch_digest(batch) -> str:
    """One sha256 over a batch's :func:`row_digests` — digest-stable under
    every re-batching transform that preserves row identity and order."""
    import hashlib

    return hashlib.sha256(
        "".join(row_digests(batch)).encode()).hexdigest()


# ---------------------------------------------------------------------------
# Device-trust registry (ISSUE 20): the ratcheted TRUSTED -> SUSPECT ->
# QUARANTINED state machine's persistent home, beside the compile-fingerprint
# and capacity-ratchet registries. Same contract: best-effort atomic rewrite,
# a read-only cache dir never sinks a run.
# ---------------------------------------------------------------------------

#: trust states a device ratchets through (strings: they go straight into
#: JSON events and the registry file)
TRUST_TRUSTED = "TRUSTED"
TRUST_SUSPECT = "SUSPECT"
TRUST_QUARANTINED = "QUARANTINED"


def _trust_path() -> str | None:
    import os

    d = compcache_dir()
    return os.path.join(d, "daccord_trust.json") if d else None


def trust_registry() -> dict:
    """The device-trust registry as ``{key: {"state", "strikes", "ts"}}``
    — key is the supervisor's device identity string (e.g. ``cpu:m3``).
    Empty when the cache dir is disabled or the file is unreadable."""
    import json
    import os

    p = _trust_path()
    if p is None or not os.path.exists(p):
        return {}
    try:
        with open(p) as fh:
            d = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return {}
    if not isinstance(d, dict):
        return {}
    return {str(k): v for k, v in d.items() if isinstance(v, dict)}


def record_trust(key: str, state: str, strikes: int) -> None:
    """Persist one device's trust state (atomic rewrite, best-effort).
    Unlike the fingerprint registry this OVERWRITES the entry — trust is a
    current-state machine, not an append-only telemetry fold."""
    import json
    import os
    import time as _time

    p = _trust_path()
    if p is None:
        return
    try:
        reg = trust_registry()
        reg[key] = {"state": state, "strikes": int(strikes),
                    "ts": round(_time.time(), 1)}
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = f"{p}.tmp.{os.getpid()}"
        with open(tmp, "wt") as fh:
            json.dump(reg, fh)
        os.replace(tmp, p)
    except (OSError, json.JSONDecodeError):
        pass


def hlo_cost(fn, *args, **kwargs) -> dict | None:
    """HLO cost estimate (flops, bytes accessed) of a jitted callable at
    the given args, via the AOT ``lower().compile()`` path — the compile
    hits the in-process jit cache (and the persistent XLA cache) when the
    shape was already traced, so harvesting cost after a warmup is cheap.
    None when the backend/jax version does not expose cost_analysis; this
    is telemetry, it must never sink a caller."""
    try:
        ca = fn.lower(*args, **kwargs).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if not isinstance(ca, dict):
            return None
        out = {}
        for key in ("flops", "bytes accessed"):
            v = ca.get(key)
            if isinstance(v, (int, float)):
                out[key.replace(" ", "_")] = float(v)
        return out or None
    except Exception:
        return None


def expected_compile_wall_s(batch_rows: int) -> float:
    """Expected COLD server-side XLA compile wall for a ladder program of
    ``batch_rows`` windows, from the measured superlinear scaling on the
    tunneled v5e (2026-08-02: B=256 -> 35 s, 1024 -> 242 s, 2048 -> 925 s;
    the 8192 point was abandoned after extrapolating to hours). Power-law
    anchored at the 1024/2048 pair; a patience estimate for humans and
    deadline classification, not a promise."""
    if batch_rows <= 0:
        return 120.0
    est = 242.0 * (batch_rows / 1024.0) ** 1.93
    return float(min(max(est, 20.0), 4 * 3600.0))


def measure_rtt_s(n: int = 3, timeout_s: float = 30.0) -> float | None:
    """Median round-trip of a tiny blocking device fetch (the fixed
    per-device_get cost the pipeline amortizes; ~60-300 ms through the axon
    tunnel, microseconds locally). None on error OR when the measurement
    itself exceeds ``timeout_s`` — a tunnel that wedges between backend init
    and this call must not hang the caller (it runs on a daemon thread; the
    abandoned thread dies with the process). Only call once a backend is
    already initialized — this is NOT a liveness probe (see
    probe_backend_status for that)."""
    import threading
    import time as _time

    box: list = []

    def work() -> None:
        try:
            import jax
            import jax.numpy as jnp

            tiny = jax.device_put(jnp.zeros(8, jnp.int32))
            jax.block_until_ready(tiny)
            rtts = []
            for _ in range(n):
                t0 = _time.perf_counter()
                jax.device_get(tiny)
                rtts.append(_time.perf_counter() - t0)
            box.append(sorted(rtts)[len(rtts) // 2])
        except Exception:
            pass

    t = threading.Thread(target=work, daemon=True, name="daccord-rtt-probe")
    t.start()
    t.join(timeout_s)
    return box[0] if box else None
