"""Observability: structured jsonl event log + counters.

The reference's observability is unstructured stderr prints plus
``util::Histogram`` dumps (SURVEY.md §5); here every pipeline event is a JSON
line so runs are machine-checkable: windows/sec, bases/sec/chip, per-tier
solve counts, pad-waste ratio — the metrics BASELINE.json tracks.
"""

from __future__ import annotations

import json
import sys
import time


class JsonlLogger:
    def __init__(self, path: str | None = None, stream=None):
        self._fh = None
        if path == "-":
            self._fh = stream or sys.stderr
        elif path:
            self._fh = open(path, "at")
        self._t0 = time.time()

    def log(self, event: str, **fields) -> None:
        if self._fh is None:
            return
        rec = {"t": round(time.time() - self._t0, 3), "event": event, **fields}
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()

    def close(self) -> None:
        if self._fh is not None and self._fh is not sys.stderr:
            self._fh.close()


class NullLogger(JsonlLogger):
    def __init__(self):
        super().__init__(None)
