"""URL-scheme stream factory (libmaus2 ``aio`` role, SURVEY.md §2.2).

The reference's abstract I/O layer opens streams by URL, and its ``mem:``
scheme — process-local in-memory files — is the closest thing it has to a
test-fixture infrastructure (SURVEY.md §4). This is the TPU framework's
equivalent:

- plain paths and ``file:PATH`` map to the filesystem;
- ``mem:NAME`` maps to a process-local byte store: writes become visible at
  close (atomic, like the repo's tmp+rename discipline on disk), reads get
  an independent seekable view.

Binary formats (DB/LAS) open their inputs through :func:`open_input` /
:func:`getsize`, so tests can parse in-memory files without touching disk;
multi-file stores (the DB's .idx/.bps/track sidecars) and the persistent
LAS index sidecar stay file-backed by design — they are the durable
resume/data plane of the shard model, not stream consumers.
"""

from __future__ import annotations

import io
import os
import threading

_MEM: dict[str, bytes] = {}
_LOCK = threading.Lock()

MEM_SCHEME = "mem:"
FILE_SCHEME = "file:"


def is_mem(url: str) -> bool:
    return isinstance(url, str) and url.startswith(MEM_SCHEME)


def local_path(url: str) -> str:
    """Filesystem path of a non-mem URL (strips a ``file:`` scheme)."""
    return url[len(FILE_SCHEME):] if isinstance(url, str) and \
        url.startswith(FILE_SCHEME) else url


_path = local_path


def _is_text(mode: str) -> bool:
    # builtin open() treats modes without 'b' as text ('r', 'rt', 'w', ...);
    # the mem: branch must agree or the same code yields str on disk and
    # bytes in memory
    return "b" not in mode


class _MemWriter(io.BytesIO):
    """Seekable write buffer committed to the store on close."""

    def __init__(self, name: str):
        super().__init__()
        self._name = name

    def close(self) -> None:
        if not self.closed:
            with _LOCK:
                _MEM[self._name] = self.getvalue()
        super().close()


def open_input(url: str, mode: str = "rb"):
    """Readable stream for a URL (text unless mode contains 'b', exactly
    like builtin ``open``)."""
    if is_mem(url):
        with _LOCK:
            if url not in _MEM:
                raise FileNotFoundError(url)
            data = _MEM[url]
        buf = io.BytesIO(data)
        return io.TextIOWrapper(buf) if _is_text(mode) else buf
    return open(local_path(url), mode)


def open_output(url: str, mode: str = "wb"):
    """Writable stream for a URL (text unless mode contains 'b'). mem:
    content becomes visible at close."""
    if is_mem(url):
        buf = _MemWriter(url)
        return io.TextIOWrapper(buf) if _is_text(mode) else buf
    return open(local_path(url), mode)


def exists(url: str) -> bool:
    if is_mem(url):
        with _LOCK:
            return url in _MEM
    return os.path.exists(local_path(url))


def getsize(url: str) -> int:
    if is_mem(url):
        with _LOCK:
            if url not in _MEM:
                raise FileNotFoundError(url)
            return len(_MEM[url])
    return os.path.getsize(local_path(url))


def fsync_dir(path: str) -> None:
    """Best-effort fsync of the directory holding ``path`` — makes a rename
    itself durable, not just the renamed bytes. Filesystems that cannot
    fsync a directory fd are silently tolerated."""
    d = os.path.dirname(os.path.abspath(local_path(path))) or "."
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def durable_replace(tmp: str, dst: str) -> None:
    """``os.replace`` + directory fsync: the crash-durable commit primitive.

    The caller must have fsynced ``tmp``'s CONTENT already; this makes the
    rename that publishes it survive power loss too. The ordering contract
    of the ingest layer (ISSUE 2): data bytes fsync first, then the pointer
    that references them commits through here — a checkpoint manifest must
    never point past the durable bytes."""
    os.replace(local_path(tmp), local_path(dst))
    fsync_dir(dst)


def durable_write(dst: str, write_fn, mode: str = "wb"):
    """The one crash-durable file-commit sequence: write to a pid-suffixed
    tmp via ``write_fn(fh)``, fsync its content, publish with
    :func:`durable_replace` (rename + dir fsync). The tmp is removed on any
    failure so aborted commits never strand ``.tmp`` litter. Returns
    ``write_fn``'s return value."""
    real = local_path(dst)
    tmp = f"{real}.tmp.{os.getpid()}"
    try:
        with open(tmp, mode) as fh:
            out = write_fn(fh)
            fh.flush()
            os.fsync(fh.fileno())
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    durable_replace(tmp, real)
    return out


def exclusive_create(url: str, data: bytes) -> bool:
    """Atomically create ``url`` with ``data`` iff it does not exist —
    the ``O_CREAT|O_EXCL`` claim primitive of the shared-FS lease protocol
    (``parallel/fleet.py``): of N hosts racing to claim a shard, exactly one
    sees True. Content and the containing directory are fsynced so a claim
    survives power loss (a lost claim file would let two hosts run the same
    shard after a crash+restart). False when the file already exists."""
    if is_mem(url):
        with _LOCK:
            if url in _MEM:
                return False
            _MEM[url] = data
        return True
    try:
        fd = os.open(local_path(url), os.O_WRONLY | os.O_CREAT | os.O_EXCL,
                     0o644)
    except FileExistsError:
        return False
    try:
        os.write(fd, data)
        os.fsync(fd)
    finally:
        os.close(fd)
    fsync_dir(url)
    return True


def remove(url: str) -> None:
    """Delete a URL; raises FileNotFoundError when absent (both schemes —
    callers' double-delete handling must not depend on the backend)."""
    if is_mem(url):
        with _LOCK:
            if url not in _MEM:
                raise FileNotFoundError(url)
            del _MEM[url]
        return
    os.remove(local_path(url))
