"""URL-scheme stream factory (libmaus2 ``aio`` role, SURVEY.md §2.2).

The reference's abstract I/O layer opens streams by URL, and its ``mem:``
scheme — process-local in-memory files — is the closest thing it has to a
test-fixture infrastructure (SURVEY.md §4). This is the TPU framework's
equivalent:

- plain paths and ``file:PATH`` map to the filesystem;
- ``mem:NAME`` maps to a process-local byte store: writes become visible at
  close (atomic, like the repo's tmp+rename discipline on disk), reads get
  an independent seekable view.

Binary formats (DB/LAS) open their inputs through :func:`open_input` /
:func:`getsize`, so tests can parse in-memory files without touching disk;
multi-file stores (the DB's .idx/.bps/track sidecars) and the persistent
LAS index sidecar stay file-backed by design — they are the durable
resume/data plane of the shard model, not stream consumers.

Storage fault hook (ISSUE 17): every durable primitive here —
:func:`durable_write`, :func:`durable_replace`, :func:`exclusive_create`,
:func:`open_output`, :func:`fsync_dir` — consults the process
``DACCORD_FAULT`` plan's ``io_*`` kinds (``runtime/faults.py``) before
touching the disk, keyed by an optional path-class ``domain``
(``journal`` | ``lease`` | ``manifest`` | ``spool`` | ``sidecar`` |
``aot``). Injected failures are real :class:`OSError` instances with real
errnos (ENOSPC / EIO) so callers' handling of the injected matrix IS their
handling of the real thing; :func:`retrying` is the bounded-backoff
wrapper for the transient class (EIO), and code with its own fds (the
journal's O_APPEND fd, lease renewal's utime) consults :func:`io_gate`
directly. Tests install a plan with :func:`install_faults`; subprocess
tiers pick the plan up lazily from the env, so a serve peer under an
``io_enospc@journal`` storm needs no extra wiring.
"""

from __future__ import annotations

import errno
import io
import os
import threading
import time

_MEM: dict[str, bytes] = {}
_LOCK = threading.Lock()

MEM_SCHEME = "mem:"
FILE_SCHEME = "file:"


def is_mem(url: str) -> bool:
    return isinstance(url, str) and url.startswith(MEM_SCHEME)


def local_path(url: str) -> str:
    """Filesystem path of a non-mem URL (strips a ``file:`` scheme)."""
    return url[len(FILE_SCHEME):] if isinstance(url, str) and \
        url.startswith(FILE_SCHEME) else url


_path = local_path


# ---------------------------------------------------------------------------
# Injected-storage-fault hook (ISSUE 17). The plan is either installed
# explicitly (tests, in-process services) or resolved lazily from
# DACCORD_FAULT — cached per env-string so counters persist across ops
# within one setting but a test changing the var gets a fresh plan.
# ---------------------------------------------------------------------------

_FAULTS = None                     # explicitly installed plan (wins)
_ENV_FAULTS: tuple = (None, None)  # (env text, parsed plan) lazy cache


class InjectedIOFault(OSError):
    """An ``io_*``-injected failure; ``fault_kind`` names the spec so the
    retry policy can distinguish an injected fsync failure (never retried)
    from an injected transient EIO (retried) despite both wearing real
    errnos."""

    def __init__(self, err: int, msg: str, fault_kind: str):
        super().__init__(err, msg)
        self.fault_kind = fault_kind


def install_faults(plan) -> None:
    """Install (or with None, clear) the FaultPlan whose ``io_*`` kinds the
    primitives consult — counters and one-shot state live on the plan, so
    installing the same object a service already consumes keeps the two
    views coherent."""
    global _FAULTS, _ENV_FAULTS
    _FAULTS = plan
    _ENV_FAULTS = (None, None)


def _io_plan():
    if _FAULTS is not None:
        return _FAULTS if _FAULTS.has_io_faults() else None
    text = os.environ.get("DACCORD_FAULT")
    global _ENV_FAULTS
    if _ENV_FAULTS[0] != text:
        plan = None
        if text:
            try:
                from ..runtime.faults import FaultPlan
                p = FaultPlan.parse(text)
                plan = p if p.has_io_faults() else None
            except ValueError:
                plan = None  # the CLI entry point already rejected it loudly
        _ENV_FAULTS = (text, plan)
    plan = _ENV_FAULTS[1]
    return plan if plan is not None and plan.has_io_faults() else None


#: re-entrancy guard: a primitive composed from other primitives (e.g.
#: durable_write publishing through durable_replace) is ONE logical storage
#: op — the inner call must not advance fault counters a second time
_NESTED = threading.local()


def _io_prelude(domain: str):
    """One logical storage op: apply any ``io_slow`` delay and return the
    fired error spec (or None)."""
    if getattr(_NESTED, "depth", 0):
        return None
    plan = _io_plan()
    if plan is None:
        return None
    ms = plan.io_slow_ms(domain)
    if ms > 0:
        time.sleep(ms / 1000.0)
    return plan.io_check(domain)


def _io_raise(spec, op: str, domain: str):
    err = errno.ENOSPC if spec.kind in ("io_enospc", "io_short_write") \
        else errno.EIO
    raise InjectedIOFault(
        err, f"injected {spec.kind}"
             + (f"@{domain}" if domain else "")
             + f" at {op} #{spec.at}", spec.kind)


def io_gate(domain: str, op: str = "write") -> None:
    """Consult the storage-fault hook for one logical op performed OUTSIDE
    the aio primitives (the journal's own ``O_APPEND`` fd, lease renewal's
    ``os.utime``): applies any ``io_slow`` delay and raises the injected
    OSError when a spec fires. No-op without a plan."""
    spec = _io_prelude(domain)
    if spec is not None:
        _io_raise(spec, op, domain)


#: errnos the bounded-retry wrapper treats as transient on REAL errors
_TRANSIENT_ERRNOS = (errno.EIO, errno.EAGAIN, errno.EINTR)


def _retryable(e: OSError) -> bool:
    kind = getattr(e, "fault_kind", None)
    if kind is not None:
        # injected faults declare their class: only io_eio is transient —
        # ENOSPC won't clear in milliseconds, a torn write already damaged
        # the artifact, and a failed fsync leaves page state undefined
        return kind == "io_eio"
    return e.errno in _TRANSIENT_ERRNOS


def retrying(fn, attempts: int = 3, base_s: float = 0.01):
    """Run ``fn()`` with bounded retries + exponential backoff on transient
    OSErrors (EIO / EAGAIN / EINTR). Persistent classes — ENOSPC, injected
    fsync/short-write faults — propagate immediately: retrying them burns
    the caller's latency budget against a disk that will keep saying no.
    The caller's ``fn`` must be safe to re-run from scratch (every aio
    primitive is: each attempt rewrites its tmp/claim file whole)."""
    i = 0
    while True:
        try:
            return fn()
        except OSError as e:
            if not _retryable(e) or i >= attempts - 1:
                raise
            time.sleep(base_s * (2 ** i))
            i += 1


def _is_text(mode: str) -> bool:
    # builtin open() treats modes without 'b' as text ('r', 'rt', 'w', ...);
    # the mem: branch must agree or the same code yields str on disk and
    # bytes in memory
    return "b" not in mode


class _MemWriter(io.BytesIO):
    """Seekable write buffer committed to the store on close."""

    def __init__(self, name: str):
        super().__init__()
        self._name = name

    def close(self) -> None:
        if not self.closed:
            with _LOCK:
                _MEM[self._name] = self.getvalue()
        super().close()


def open_input(url: str, mode: str = "rb"):
    """Readable stream for a URL (text unless mode contains 'b', exactly
    like builtin ``open``)."""
    if is_mem(url):
        with _LOCK:
            if url not in _MEM:
                raise FileNotFoundError(url)
            data = _MEM[url]
        buf = io.BytesIO(data)
        return io.TextIOWrapper(buf) if _is_text(mode) else buf
    return open(local_path(url), mode)


def open_output(url: str, mode: str = "wb", domain: str = ""):
    """Writable stream for a URL (text unless mode contains 'b'). mem:
    content becomes visible at close. A fired storage fault raises at open
    (``io_short_write`` additionally leaves the zero-byte file behind — the
    torn-artifact litter the caller's cleanup discipline must handle)."""
    if is_mem(url):
        buf = _MemWriter(url)
        return io.TextIOWrapper(buf) if _is_text(mode) else buf

    def attempt():
        spec = _io_prelude(domain)
        if spec is not None:
            if spec.kind == "io_short_write":
                open(local_path(url), mode).close()
            _io_raise(spec, "open_output", domain)
        return open(local_path(url), mode)

    return retrying(attempt)


def exists(url: str) -> bool:
    if is_mem(url):
        with _LOCK:
            return url in _MEM
    return os.path.exists(local_path(url))


def getsize(url: str) -> int:
    if is_mem(url):
        with _LOCK:
            if url not in _MEM:
                raise FileNotFoundError(url)
            return len(_MEM[url])
    return os.path.getsize(local_path(url))


def _fsync_dir_raw(path: str) -> None:
    d = os.path.dirname(os.path.abspath(local_path(path))) or "."
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def fsync_dir(path: str, domain: str = "") -> None:
    """Best-effort fsync of the directory holding ``path`` — makes a rename
    itself durable, not just the renamed bytes. Filesystems that cannot
    fsync a directory fd are silently tolerated, and an injected storage
    fault is absorbed the same way (the real failure mode it simulates)."""
    spec = _io_prelude(domain)
    if spec is not None:
        return  # a failed dir fsync is tolerated — same as the real branch
    _fsync_dir_raw(path)


def durable_replace(tmp: str, dst: str, domain: str = "") -> None:
    """``os.replace`` + directory fsync: the crash-durable commit primitive.

    The caller must have fsynced ``tmp``'s CONTENT already; this makes the
    rename that publishes it survive power loss too. The ordering contract
    of the ingest layer (ISSUE 2): data bytes fsync first, then the pointer
    that references them commits through here — a checkpoint manifest must
    never point past the durable bytes. One logical storage op: an injected
    fault fires before the rename, so a refused publish never half-lands."""
    def attempt():
        spec = _io_prelude(domain)
        if spec is not None:
            _io_raise(spec, "durable_replace", domain)
        os.replace(local_path(tmp), local_path(dst))
        _fsync_dir_raw(dst)

    retrying(attempt)


def durable_write(dst: str, write_fn, mode: str = "wb", domain: str = ""):
    """The one crash-durable file-commit sequence: write to a pid-suffixed
    tmp via ``write_fn(fh)``, fsync its content, publish with
    :func:`durable_replace` (rename + dir fsync). The tmp is removed on any
    failure so aborted commits never strand ``.tmp`` litter. Returns
    ``write_fn``'s return value.

    One logical storage op per attempt: a fired fault lands after
    ``write_fn`` has populated the tmp (``io_short_write`` first truncates
    it to half, putting genuinely torn bytes on disk; ``io_fsync_fail``
    replaces the content fsync), so the cleanup-on-failure path — not just
    the happy path — is what the matrix exercises. Transient EIO is
    absorbed by :func:`retrying` (each attempt rewrites the tmp whole)."""
    real = local_path(dst)
    tmp = f"{real}.tmp.{os.getpid()}"

    def attempt():
        spec = _io_prelude(domain)
        try:
            with open(tmp, mode) as fh:
                out = write_fn(fh)
                fh.flush()
                if spec is not None:
                    if spec.kind == "io_short_write":
                        fh.truncate(max(0, fh.tell() // 2))
                    _io_raise(spec, "durable_write", domain)
                os.fsync(fh.fileno())
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise
        # publish through the module-level durable_replace so crash-injection
        # harnesses can interpose on the rename; the nesting guard keeps the
        # whole sequence ONE logical storage op for the fault counters
        _NESTED.depth = getattr(_NESTED, "depth", 0) + 1
        try:
            durable_replace(tmp, real)
        finally:
            _NESTED.depth -= 1
        return out

    return retrying(attempt)


def exclusive_create(url: str, data: bytes, domain: str = "") -> bool:
    """Atomically create ``url`` with ``data`` iff it does not exist —
    the ``O_CREAT|O_EXCL`` claim primitive of the shared-FS lease protocol
    (``parallel/fleet.py``): of N hosts racing to claim a shard, exactly one
    sees True. Content and the containing directory are fsynced so a claim
    survives power loss (a lost claim file would let two hosts run the same
    shard after a crash+restart). False when the file already exists.

    A write/fsync failure AFTER the O_EXCL open unlinks the claim before
    re-raising: a stranded zero-byte/torn claim file would otherwise block
    every future claimant of that slot until the stale-TTL takeover — and
    the unlink is also what makes a transient-EIO retry attempt's O_EXCL
    succeed instead of colliding with our own wreckage."""
    if is_mem(url):
        with _LOCK:
            if url in _MEM:
                return False
            _MEM[url] = data
        return True

    def attempt():
        spec = _io_prelude(domain)
        try:
            fd = os.open(local_path(url),
                         os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o644)
        except FileExistsError:
            return False
        try:
            try:
                if spec is not None:
                    if spec.kind == "io_short_write":
                        os.write(fd, data[: len(data) // 2])
                    _io_raise(spec, "exclusive_create", domain)
                os.write(fd, data)
                os.fsync(fd)
            finally:
                os.close(fd)
        except BaseException:
            try:
                os.remove(local_path(url))
            except OSError:
                pass
            raise
        _fsync_dir_raw(url)
        return True

    return retrying(attempt)


def remove(url: str) -> None:
    """Delete a URL; raises FileNotFoundError when absent (both schemes —
    callers' double-delete handling must not depend on the backend)."""
    if is_mem(url):
        with _LOCK:
            if url not in _MEM:
                raise FileNotFoundError(url)
            del _MEM[url]
        return
    os.remove(local_path(url))
