"""Shared-filesystem lease protocol (extracted from ``parallel/fleet.py``).

One file = one lease. A claimant atomically creates the lease file
(``O_CREAT|O_EXCL``, :func:`aio.exclusive_create`) with a JSON payload
naming the holder; of N processes racing, exactly one wins. The holder
renews by bumping the file's mtime every heartbeat; a lease whose mtime is
older than the TTL is *stale* — its holder died or wedged — and any process
may take it over by removing the stale file and re-claiming. No coordinator,
no network protocol: the shared filesystem IS the control plane.

The four protocol rules, hardened by the fleet's production history and now
shared verbatim by the serve tier's per-job leases (ISSUE 15):

- **claim**: ``O_EXCL`` create arbitrates every race; takeover of a stale
  lease goes through ``os.replace`` to a grave name, which succeeds for
  exactly one taker (the loser's replace raises ``FileNotFoundError``).
- **heartbeat re-read-before-renew**: a holder must re-read the payload
  before renewing — if its lease went stale during a host pause and another
  process took over, renewing would keep THE TAKER'S lease fresh while two
  processes run the same work. Ownership loss means stand down, never renew.
  (:func:`read` is the primitive; the stand-down policy lives with each
  caller — the fleet kills its worker, the serve tier aborts its run.)
- **holder-checked release**: a releasing holder that was taken over must
  not delete the taker's live lease; :func:`release` with ``host`` given
  only removes while the payload still names that host.
- **stale takeover**: :func:`claim` on a stale lease reports the previous
  holder's identity and staleness, so the takeover is attributable in the
  event log.

The TTL must exceed a few heartbeats plus worst-case shared-FS mtime
propagation and host clock skew. :func:`backdate` is the deterministic test
hook (and fault-injection lever) that makes a lease stale without burning
TTL wall-clock.
"""

from __future__ import annotations

import json
import os
import time

from . import aio


def claim(path: str, host: str, ttl_s: float,
          extra: dict | None = None) -> tuple[bool, dict | None]:
    """Try to claim the lease at ``path`` for ``host``.

    Returns ``(claimed, takeover)``: ``takeover`` carries the previous
    holder's identity and the lease's staleness when the claim displaced a
    stale lease. A fresh (live) lease loses the race: ``(False, None)``.
    ``extra`` fields join the payload (the serve tier stores the full job
    descriptor there, so a takeover is self-contained). Takeover is
    race-safe on a POSIX shared FS: ``os.replace`` of the stale file
    succeeds for exactly one taker (the loser's replace raises), and the
    subsequent ``O_EXCL`` create arbitrates any claim/claim race.
    """
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    payload = json.dumps({"host": host, "pid": os.getpid(),
                          "claimed_t": time.time(),
                          **(extra or {})}).encode()

    def _create() -> bool:
        # a disk that says no (ENOSPC/EIO — real or injected via the
        # ``@lease`` fault domain) is indistinguishable from losing the
        # race, and exactly as retryable: never let an OSError escape a
        # claim attempt into a heartbeat/submit thread. A torn claim file
        # cannot be ours — exclusive_create unlinks its wreckage on failure.
        try:
            return aio.exclusive_create(path, payload, domain="lease")
        except OSError:
            return False

    if _create():
        return True, None
    try:
        stale_s = time.time() - os.path.getmtime(path)
    except OSError:
        # holder released between our create and stat: claim the vacancy
        return _create(), None
    if stale_s <= ttl_s:
        return False, None
    prev = read(path) or {}
    grave = f"{path}.stale.{os.getpid()}"
    try:
        os.replace(path, grave)
    except FileNotFoundError:
        return False, None  # another taker won the replace race
    except OSError:
        return False, None  # disk refused the takeover rename: stand down
    try:
        os.remove(grave)
    except OSError:
        pass
    if not _create():
        return False, None
    return True, {"prev_host": str(prev.get("host", "?")),
                  "stale_s": round(stale_s, 3)}


def read_result(path: str) -> tuple[dict | None, str]:
    """The lease's payload plus WHY it is missing when it is:
    ``(info, "ok")`` | ``(None, "absent" | "torn" | "error")``.

    ``absent`` = no file (released / taken over); ``torn`` = the file
    exists but its payload doesn't parse (zero-byte or partial write from
    a claimer killed mid-create — stale-TTL takeover-eligible, never a
    crash); ``error`` = the read itself failed (EIO-class — the holder's
    bounded heartbeat grace applies, see the serve tier's ``_lease_tick``).
    The distinction exists because demoting on a transient read error would
    abort healthy in-flight work every time a shared FS hiccups."""
    try:
        aio.io_gate("lease", op="read")
        with open(path) as fh:
            info = json.load(fh)
    except FileNotFoundError:
        return None, "absent"
    except OSError:
        return None, "error"
    except json.JSONDecodeError:
        return None, "torn"
    if not isinstance(info, dict):
        return None, "torn"
    return info, "ok"


def read(path: str) -> dict | None:
    """The lease's payload, or None when absent/torn (a torn lease from a
    killed claimer is still takeover-able once stale)."""
    return read_result(path)[0]


def renew(path: str) -> bool:
    """Heartbeat: bump the lease mtime (the staleness clock other processes
    read). Callers must :func:`read`-check ownership first (see module doc);
    a vanished lease is tolerated — the owner's reaper notices soon enough.
    Returns False when the bump failed (vanished OR an EIO-class refusal,
    real or injected): the caller's bounded grace counts these before
    self-demoting — one hiccup must not abort healthy work, but a holder
    that cannot prove liveness for several heartbeats must stand down
    before the TTL lets a peer steal the lease out from under it."""
    try:
        aio.io_gate("lease", op="renew")
        os.utime(path, None)
        return True
    except OSError:
        return False


def release(path: str, host: str | None = None) -> None:
    """Remove the lease; with ``host`` given, only while the payload still
    names that host — a holder that was taken over must not delete the
    taker's live lease (the read/remove race that remains is the
    fencing-free protocol's inherent window, bounded by the heartbeat
    ownership re-check)."""
    if host is not None:
        prev = read(path)
        if prev is not None and prev.get("host") != host:
            return
    try:
        os.remove(path)
    except OSError:
        pass


def backdate(path: str, age_s: float) -> None:
    """Set the lease's mtime ``age_s`` into the past — how fault injection
    (``lease_stall``, the serve kill matrix) makes a wedged holder's lease
    stale deterministically instead of burning TTL wall-clock."""
    t = time.time() - age_s
    try:
        os.utime(path, (t, t))
    except OSError:
        pass


def stale_s(path: str) -> float | None:
    """Seconds since the lease's last heartbeat, or None when absent."""
    try:
        return time.time() - os.path.getmtime(path)
    except OSError:
        return None
