"""Shared-filesystem lease protocol (extracted from ``parallel/fleet.py``).

One file = one lease. A claimant atomically creates the lease file
(``O_CREAT|O_EXCL``, :func:`aio.exclusive_create`) with a JSON payload
naming the holder; of N processes racing, exactly one wins. The holder
renews by bumping the file's mtime every heartbeat; a lease whose mtime is
older than the TTL is *stale* — its holder died or wedged — and any process
may take it over by removing the stale file and re-claiming. No coordinator,
no network protocol: the shared filesystem IS the control plane.

The four protocol rules, hardened by the fleet's production history and now
shared verbatim by the serve tier's per-job leases (ISSUE 15):

- **claim**: ``O_EXCL`` create arbitrates every race; takeover of a stale
  lease goes through ``os.replace`` to a grave name, which succeeds for
  exactly one taker (the loser's replace raises ``FileNotFoundError``).
- **heartbeat re-read-before-renew**: a holder must re-read the payload
  before renewing — if its lease went stale during a host pause and another
  process took over, renewing would keep THE TAKER'S lease fresh while two
  processes run the same work. Ownership loss means stand down, never renew.
  (:func:`read` is the primitive; the stand-down policy lives with each
  caller — the fleet kills its worker, the serve tier aborts its run.)
- **holder-checked release**: a releasing holder that was taken over must
  not delete the taker's live lease; :func:`release` with ``host`` given
  only removes while the payload still names that host.
- **stale takeover**: :func:`claim` on a stale lease reports the previous
  holder's identity and staleness, so the takeover is attributable in the
  event log.

The TTL must exceed a few heartbeats plus worst-case shared-FS mtime
propagation and host clock skew. :func:`backdate` is the deterministic test
hook (and fault-injection lever) that makes a lease stale without burning
TTL wall-clock.
"""

from __future__ import annotations

import json
import os
import time

from . import aio


def claim(path: str, host: str, ttl_s: float,
          extra: dict | None = None) -> tuple[bool, dict | None]:
    """Try to claim the lease at ``path`` for ``host``.

    Returns ``(claimed, takeover)``: ``takeover`` carries the previous
    holder's identity and the lease's staleness when the claim displaced a
    stale lease. A fresh (live) lease loses the race: ``(False, None)``.
    ``extra`` fields join the payload (the serve tier stores the full job
    descriptor there, so a takeover is self-contained). Takeover is
    race-safe on a POSIX shared FS: ``os.replace`` of the stale file
    succeeds for exactly one taker (the loser's replace raises), and the
    subsequent ``O_EXCL`` create arbitrates any claim/claim race.
    """
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    payload = json.dumps({"host": host, "pid": os.getpid(),
                          "claimed_t": time.time(),
                          **(extra or {})}).encode()
    if aio.exclusive_create(path, payload):
        return True, None
    try:
        stale_s = time.time() - os.path.getmtime(path)
    except OSError:
        # holder released between our create and stat: claim the vacancy
        return aio.exclusive_create(path, payload), None
    if stale_s <= ttl_s:
        return False, None
    prev = read(path) or {}
    grave = f"{path}.stale.{os.getpid()}"
    try:
        os.replace(path, grave)
    except FileNotFoundError:
        return False, None  # another taker won the replace race
    try:
        os.remove(grave)
    except OSError:
        pass
    if not aio.exclusive_create(path, payload):
        return False, None
    return True, {"prev_host": str(prev.get("host", "?")),
                  "stale_s": round(stale_s, 3)}


def read(path: str) -> dict | None:
    """The lease's payload, or None when absent/torn (a torn lease from a
    killed claimer is still takeover-able once stale)."""
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None


def renew(path: str) -> None:
    """Heartbeat: bump the lease mtime (the staleness clock other processes
    read). Callers must :func:`read`-check ownership first (see module doc);
    a vanished lease is tolerated — the owner's reaper notices soon enough."""
    try:
        os.utime(path, None)
    except OSError:
        pass


def release(path: str, host: str | None = None) -> None:
    """Remove the lease; with ``host`` given, only while the payload still
    names that host — a holder that was taken over must not delete the
    taker's live lease (the read/remove race that remains is the
    fencing-free protocol's inherent window, bounded by the heartbeat
    ownership re-check)."""
    if host is not None:
        prev = read(path)
        if prev is not None and prev.get("host") != host:
            return
    try:
        os.remove(path)
    except OSError:
        pass


def backdate(path: str, age_s: float) -> None:
    """Set the lease's mtime ``age_s`` into the past — how fault injection
    (``lease_stall``, the serve kill matrix) makes a wedged holder's lease
    stale deterministically instead of burning TTL wall-clock."""
    t = time.time() - age_s
    try:
        os.utime(path, (t, t))
    except OSError:
        pass


def stale_s(path: str) -> float | None:
    """Seconds since the lease's last heartbeat, or None when absent."""
    try:
        return time.time() - os.path.getmtime(path)
    except OSError:
        return None
