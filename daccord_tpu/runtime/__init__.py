from .pipeline import PipelineConfig, PipelineStats, correct_shard, correct_to_fasta, estimate_profile_for_shard
from .supervisor import DeviceSupervisor, SupervisorConfig

__all__ = ["PipelineConfig", "PipelineStats", "correct_shard", "correct_to_fasta",
           "estimate_profile_for_shard", "DeviceSupervisor", "SupervisorConfig"]
