"""Device supervisor: fault-tolerant dispatch/fetch with watchdog
classification, native failover, and optional failback.

Three straight rounds lost TPU wall-clock to the same failure class: the
tunneled chip died mid-round with no brackets on when, healthy benches were
killed because a silent server-side compile is indistinguishable from a dead
socket, and a wedged fetch had no deadline at all (VERDICT/BASELINE/ADVICE
r5). This module composes the ingredients that already existed in isolation —
the bounded subprocess probe (``utils/obs.py``), the native C++ engine at
oracle parity, and the pipeline's batch-granular dispatch/fetch seam — into a
state machine that keeps a run alive across all of it:

    HEALTHY ──fresh shape──▶ COMPILING ──done──▶ HEALTHY
       │                        │ deadline
       │ timeout/error          ▼
       └──────────────────▶ SUSPECT ──probe alive──▶ RETRYING ──ok──▶ HEALTHY
                                │ probe dead /            │ fail
                                │ retries exhausted ◀─────┘
                                ▼
                              LOST ──fallback built──▶ DEGRADED
                                                          │ re-probe alive
                                                          ▼
                               HEALTHY ◀──primary ok── FAILBACK

*Deadline classification*: the first dispatch of a bucket shape whose
fingerprint is not in the persistent-compile-cache registry is COMPILING —
it gets the long compile deadline and emits heartbeat events instead of being
declared wedged. A warm-shape op gets an RTT-scaled deadline; expiry makes
the device SUSPECT, and a bounded subprocess probe decides between RETRYING
(exponential backoff + deterministic jitter, the op re-dispatched from its
retained batch) and LOST.

*Failover*: on LOST the supervisor builds the degraded engine once (native
C++ ladder in production — oracle parity; or the same CPU-routed JAX ladder
for exact-byte arms) and re-solves every in-flight batch on it. Dispatch
handles retain their ``WindowBatch`` precisely so this replay is possible —
no window is dropped or duplicated. Under the two-stream ladder
(``--ladder split``) BOTH streams' in-flight batches replay this way: a
Stream B rescue batch replays to its exact result (the fallback IS a full
ladder), and a Stream A tier0 batch replays to full-ladder results — which
composes byte-identically, because the pipeline's pool rule
(``kernels.tiers.rescue_candidates``) re-solves every still-pooled window
to the same per-window bytes while already-final windows scatter directly. With ``failback`` enabled a background
re-probe can route new dispatches back to the revived primary.

Every transition emits a structured event through ``utils.obs.JsonlLogger``
(schema: ``tools/eventcheck.py``), giving pounce/bench scripts the
machine-readable "compiling vs wedged vs dead" signal whose absence killed
two benches in r5. Fault injection (``runtime/faults.py``,
``DACCORD_FAULT=...``) makes every path here deterministically testable on
CPU.

Retries re-run the primary solver on the same batch; engines whose solve
mutates host-side counters (the native hp-rescue stat) may over-count by the
retried batch — output bytes are unaffected.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from dataclasses import dataclass

from .faults import (FaultCompileStall, FaultDeviceLost, FaultDeviceOOM,
                     FaultDispatchError, FaultHang, FaultPlan)
from .governor import (CapacityError, CapacityGovernor, GovernorConfig,
                       is_capacity_error)

# states (strings, not an enum: they go straight into JSON events)
HEALTHY = "HEALTHY"
COMPILING = "COMPILING"
SUSPECT = "SUSPECT"
RETRYING = "RETRYING"
LOST = "LOST"
DEGRADED = "DEGRADED"
FAILBACK = "FAILBACK"

#: legal state transitions (also enforced by ``eventcheck --strict``)
TRANSITIONS = {
    HEALTHY: {COMPILING, SUSPECT},
    COMPILING: {HEALTHY, SUSPECT},
    SUSPECT: {RETRYING, LOST, HEALTHY},
    # a partial-mesh retry (mesh N -> N/2, runtime/supervisor.py
    # _mesh_degrade) re-dispatches at a fresh :m<N/2> shape key — a cold
    # compile — so COMPILING is reachable from RETRYING like from FAILBACK
    RETRYING: {HEALTHY, COMPILING, SUSPECT, LOST},
    LOST: {DEGRADED},
    DEGRADED: {FAILBACK},
    # a failback re-compiles every bucket shape (the revived device has no
    # warm programs), so COMPILING is reachable from FAILBACK too
    FAILBACK: {HEALTHY, COMPILING, SUSPECT, LOST},
}


class DeviceLostError(RuntimeError):
    """The supervisor declared the primary engine dead."""


class WatchdogTimeout(RuntimeError):
    """A guarded op exceeded its deadline."""


from ..utils.obs import env_float as _env_float


@dataclass
class SupervisorConfig:
    op_deadline_s: float = 300.0      # warm-shape deadline (no RTT estimate)
    rtt_mult: float = 300.0           # RTT-scaled deadline = rtt_s * this
    min_op_deadline_s: float = 30.0   # floor under the RTT scaling
    compile_deadline_s: float = 3600.0  # cold-shape deadline (server-side XLA
                                      # compile measured 925 s at B=2048 and
                                      # superlinear — see obs.expected_compile_wall_s)
    heartbeat_s: float = 30.0         # COMPILING heartbeat cadence
    max_retries: int = 3
    backoff_base_s: float = 0.5
    backoff_cap_s: float = 30.0
    jitter: float = 0.25              # +[0, jitter) fraction, deterministic RNG
    probe_timeout_s: int = 150
    failback: bool = False
    failback_probe_s: float = 300.0   # min seconds between failback re-probes
    seed: int = 0

    @classmethod
    def from_env(cls, **overrides) -> "SupervisorConfig":
        """Env-tunable knobs (``DACCORD_SUP_*``); keyword overrides win."""
        cfg = cls(
            op_deadline_s=_env_float("DACCORD_SUP_OP_DEADLINE_S", 300.0),
            compile_deadline_s=_env_float("DACCORD_SUP_COMPILE_DEADLINE_S",
                                          3600.0),
            heartbeat_s=_env_float("DACCORD_SUP_HEARTBEAT_S", 30.0),
            max_retries=int(_env_float("DACCORD_SUP_RETRIES", 3)),
            backoff_base_s=_env_float("DACCORD_SUP_BACKOFF_S", 0.5),
            probe_timeout_s=int(_env_float("DACCORD_PROBE_TIMEOUT_S", 150)),
            failback=_env_float("DACCORD_SUP_FAILBACK", 0.0) > 0,
        )
        for k, v in overrides.items():
            setattr(cfg, k, v)
        return cfg


class _Watchdog:
    """One daemon worker thread running guarded ops with a deadline.

    An abandoned (hung) op leaves its worker stuck inside the call; the
    watchdog then spawns a fresh worker+queue so later ops never queue behind
    the corpse. Worker threads are daemonic: a genuinely hung tunnel RPC must
    not block interpreter exit.
    """

    def __init__(self):
        self._spawn()

    def _spawn(self) -> None:
        self._q: queue.Queue = queue.Queue()
        self._thread = threading.Thread(target=self._loop, args=(self._q,),
                                        daemon=True,
                                        name="daccord-supervisor-watchdog")
        self._thread.start()

    @staticmethod
    def _loop(q: queue.Queue) -> None:
        while True:
            fn, args, box, done = q.get()
            try:
                box[0] = fn(*args)
            except BaseException as e:  # noqa: BLE001 - relayed to caller
                box[1] = e
            finally:
                done.set()

    def run(self, fn, args, deadline_s: float, slice_s: float | None = None,
            on_wait=None):
        """Run ``fn(*args)`` on the worker; raise :class:`WatchdogTimeout`
        after ``deadline_s``. ``slice_s`` splits the wait so ``on_wait(t)``
        can emit heartbeats while a long (compiling) op is legitimately
        silent."""
        box: list = [None, None]
        done = threading.Event()
        self._q.put((fn, args, box, done))
        waited = 0.0
        while True:
            step = deadline_s - waited
            if slice_s is not None:
                step = min(step, slice_s)
            if done.wait(step):
                break
            waited += step
            if waited >= deadline_s:
                # abandon: the worker may be hung inside fn forever
                self._spawn()
                raise WatchdogTimeout(
                    f"op exceeded {deadline_s:.0f}s deadline")
            if on_wait is not None:
                on_wait(waited)
        if box[1] is not None:
            raise box[1]
        return box[0]


class _SupHandle:
    """In-flight op handle: retains the dispatched batch so a retry can
    re-dispatch it and a failover can replay it on the degraded engine.
    ``result`` is set when the batch was already solved synchronously (the
    governor's degradation ladder solves at dispatch time) — fetch then
    returns it directly, even after a later failover."""

    __slots__ = ("inner", "batch", "key", "degraded", "result")

    def __init__(self, inner, batch, key: str, degraded: bool = False,
                 result=None):
        self.inner = inner
        self.batch = batch
        self.key = key
        self.degraded = degraded
        self.result = result


def shape_key(batch, fp_prefix: str, mesh_suffix: str = "") -> str:
    """The compile-shape identity of a batch: the registry/ratchet/AOT key.

    Module-level (ISSUE 16) so the supervisor's fingerprint registry and the
    serve tier's fleet-shared AOT executable cache can never disagree about
    which program a batch dispatches."""
    if getattr(batch, "pool", None) is not None:
        # paged wire format (kernels/paging.py): pool rows + table width
        # + lens depth are the jit shape dims; the :pg suffix keeps
        # paged and dense programs of the same batch width classifying
        # (and fingerprinting) separately — a warm dense shape must not
        # rob the paged cold compile of its long deadline
        b, ppw = batch.table.shape
        key = (f"{fp_prefix}B{b}xD{batch.lens.shape[1]}"
               f"xL{batch.shape.seg_len}"
               f"xP{ppw}x{batch.family.page_len}"
               f"xN{batch.pool.shape[0]}:pg")
        if getattr(batch, "stream", "full") == "tier0":
            key += ":t0"
        return key + mesh_suffix
    seqs = getattr(batch, "seqs", None)
    if seqs is None:
        return fp_prefix + "opaque" + mesh_suffix
    b, d, l = seqs.shape
    key = f"{fp_prefix}B{b}xD{d}xL{l}"
    # the two-stream ladder dispatches TWO distinct programs at the same
    # batch shape: tier0-only (Stream A, cheap compile) and the full
    # rescue ladder (Stream B — same program as a fused dispatch, so
    # "rescue"/"full" share a fingerprint). Without the suffix the first
    # program's warm fingerprint would rob the second cold compile of
    # its long deadline and heartbeats.
    if getattr(batch, "stream", "full") == "tier0":
        key += ":t0"
    return key + mesh_suffix


class DeviceSupervisor:
    """Wraps a solver's ``dispatch``/``fetch``(/``fetch_many``) callables in
    the watchdog + classification + failover state machine. Exposes the same
    async-solver interface the pipeline already speaks, so it drops into
    ``correct_shard`` transparently.
    """

    def __init__(self, dispatch_fn, fetch_fn, fetch_many_fn=None, *,
                 fallback_factory=None, log=None, cfg: SupervisorConfig | None = None,
                 faults: FaultPlan | None = None, probe_fn=None,
                 rtt_s: float | None = None, describe: str = "",
                 fingerprint_prefix: str = "", inline: bool = False,
                 clamp_solve=None, governor_cfg: GovernorConfig | None = None,
                 tracer=None, mesh=None, audit_ref_factory=None,
                 audit_rate: float | None = None):
        import random

        from ..utils.obs import NullLogger, Tracer

        self._dispatch_fn = dispatch_fn
        self._fetch_fn = fetch_fn
        self._fetch_many_fn = fetch_many_fn
        self._fallback_factory = fallback_factory
        self._fallback = None
        self.cfg = cfg or SupervisorConfig.from_env()
        self.log = log if log is not None else NullLogger()
        self.faults = faults if faults is not None else FaultPlan.from_env()
        self._probe_fn = probe_fn
        self._fp_prefix = fingerprint_prefix
        self._rng = random.Random(self.cfg.seed)
        # inline mode skips the watchdog thread entirely: right for
        # host-local engines (native C++, local CPU ladder), where a hang is
        # a host bug rather than a tunnel failure and the per-op thread
        # hand-off (~0.1-0.8 ms under GIL contention) would be pure tax.
        # Error/fault classification, retries, and failover work identically;
        # only deadline enforcement needs the thread.
        self._inline = inline
        self._wd = None if inline else _Watchdog()
        self._seen_shapes: set[str] = set()
        self._ignore_fp_registry = False   # set on failback: the registry
                                 # records CLIENT-side caching, which a
                                 # replaced chip or evicted cache can betray
        self._last_failback_probe = 0.0
        self.state = HEALTHY
        self.failed_over = False
        self.fail_reason: str | None = None
        self.counters = {"dispatch": 0, "fetch": 0, "retries": 0,
                         "timeouts": 0, "probes": 0, "degraded_solves": 0,
                         "heartbeats": 0, "mesh_shrinks": 0,
                         "audits": 0, "sdc_detected": 0}
        # host-blocking wall spent inside governor ladder solves (they run
        # synchronously at dispatch time, so the pipeline's fetch timer
        # never sees them) — folded into stats.device_s at shard end
        self.gov_device_s = 0.0
        # capacity governor (runtime/governor.py): memory faults walk a
        # byte-identical degradation ladder instead of the transient retry
        # ladder; native failover is demoted to its last rung
        self._clamp_solve = clamp_solve
        # trace spans (ISSUE 6): the pipeline passes its tracer so probe /
        # governor-rung spans parent into the run's span chain; standalone
        # supervisors get their own over the same log (span ids are
        # process-unique, so mixing tracers on one file is safe)
        self.tracer = tracer if tracer is not None else Tracer(self.log)
        # mesh-native solve path (parallel/mesh.py): ``mesh`` is the sharded
        # solver itself (``nd``/``shrink``/``restore``). It gives mesh
        # programs real supervisor identity — a dynamic ``:m<N>`` shape-key
        # suffix so mesh compiles classify/fingerprint/ratchet separately —
        # and a partial-mesh degradation rung: on declared device loss the
        # mesh shrinks N -> N/2 -> ... -> 1 (re-pad + re-dispatch the
        # retained batch, byte-identical by per-window independence) BEFORE
        # whole-program native/CPU failover.
        self._mesh = mesh
        self.governor = CapacityGovernor(
            self._gov_solve_width, log=self.log,
            cfg=governor_cfg or GovernorConfig.from_env(),
            clamp_solve_fn=self._gov_clamp if clamp_solve is not None else None,
            tracer=self.tracer,
            # capacity bisect operates on the PER-DEVICE slice: widths stay
            # mesh multiples and the floor scales by mesh size, so one
            # chip's HBM ceiling shrinks every device's slice in lockstep
            # instead of collapsing the whole mesh to the scalar floor
            quantum_fn=(lambda: self._mesh.nd) if mesh is not None else None)
        if rtt_s:
            self.op_deadline_s = max(self.cfg.min_op_deadline_s,
                                     rtt_s * self.cfg.rtt_mult)
        else:
            self.op_deadline_s = self.cfg.op_deadline_s
        # ---- silent-data-corruption defense (ISSUE 20) ----------------
        # Sampled shadow verification: a deterministic seeded sample of
        # windows per fetched batch is re-solved on the trusted reference
        # engine (the host ladder that is already the byte-exact oracle)
        # and compared byte-for-byte. audit_ref_factory is the lazy builder
        # for that engine; no factory => audit disabled. Rate 0 disables.
        self._audit_ref_factory = audit_ref_factory
        if audit_rate is None:
            audit_rate = _env_float("DACCORD_AUDIT_RATE", 1.0 / 64.0)
        self._audit_rate = max(0.0, float(audit_rate)) \
            if audit_ref_factory is not None else 0.0
        self._audit_ref = None
        self._n_audit = 0          # audited-batch ordinal, seeds the sampler
        self.audit_s = 0.0         # host wall spent in shadow solves
                                   # (steady-state: first-shape XLA compile
                                   # books under the audit.warm span instead)
        self._audit_warmed: set[tuple] = set()
        # Device trust is a per-member ratchet TRUSTED -> SUSPECT ->
        # QUARANTINED, persisted in a registry beside the compile/capacity
        # registries so a lying chip stays quarantined across runs (and is
        # re-verified under DACCORD_TRUST_PROBATION like the governor).
        self._trust: dict[int, dict] = {}
        self._trust_strikes_max = max(1, int(os.environ.get(
            "DACCORD_TRUST_STRIKES", "2") or "2"))
        self._trust_probation = os.environ.get(
            "DACCORD_TRUST_PROBATION", "") == "1"
        self.log.log("sup_init", primary=describe or "solver",
                     op_deadline_s=round(self.op_deadline_s, 1),
                     compile_deadline_s=self.cfg.compile_deadline_s,
                     rtt_s=rtt_s, faults=bool(self.faults),
                     failback=self.cfg.failback, inline=inline,
                     audit_rate=self._audit_rate)
        if self._mesh is not None:
            self._trust_load()

    # ---- state machine -------------------------------------------------

    def _transition(self, to: str, reason: str = "") -> None:
        if to == self.state:
            return
        # no explicit ts: JsonlLogger stamps every record with the absolute
        # clock now (an explicit kwarg would clobber the base field)
        self.log.log("sup_state", state_from=self.state, state_to=to,
                     reason=reason)
        self.state = to

    def _probe(self) -> bool:
        self.counters["probes"] += 1
        t0 = time.time()
        if self.faults is not None:
            ov = self.faults.probe_override()
            if ov is not None:
                self.log.log("sup_probe", alive=ov, wall_s=0.0, injected=True)
                return ov
        with self.tracer.span("probe"):
            if self._probe_fn is not None:
                alive = bool(self._probe_fn())
            else:
                from ..utils.obs import device_alive

                alive = device_alive(self.cfg.probe_timeout_s)
        self.log.log("sup_probe", alive=alive,
                     wall_s=round(time.time() - t0, 3))
        return alive

    def _mesh_suffix(self) -> str:
        """Dynamic ``:m<N>`` compile-key suffix for mesh dispatches: a mesh
        program is a different XLA program from the single-device one at the
        same batch shape (and from the same mesh at a different width), so
        it must classify/fingerprint/ratchet separately — composes with
        ``:t0`` and ``:pg``. Dynamic because the partial-mesh rung changes N
        mid-run; post-shrink shapes are cold again."""
        return f":m{self._mesh.nd}" if self._mesh is not None else ""

    def _shape_key(self, batch) -> str:
        return shape_key(batch, self._fp_prefix, self._mesh_suffix())

    def _is_fresh(self, key: str) -> bool:
        """Cold-compile classification: not yet dispatched this process AND
        not in the persistent compile-fingerprint registry. After a failback
        the registry is ignored: cold classification only costs a longer
        deadline, while trusting a stale registry against a replaced chip or
        evicted cache would declare a real 900s recompile wedged."""
        if key in self._seen_shapes:
            return False
        if self._ignore_fp_registry:
            return True
        from ..utils.obs import fingerprint_seen

        return not fingerprint_seen(key)

    # ---- guarded op core -----------------------------------------------

    def _guarded(self, op: str, fn, make_args, key: str, fresh: bool,
                 width: int | None = None):
        """Run one logical op with deadline classification + retry/probe.
        ``make_args(attempt)`` builds the argument tuple per attempt — a
        retried fetch re-dispatches its retained batch rather than trusting
        an abandoned/broken handle. ``width`` is the op's batch width,
        consulted by capacity fault injection and carried on classified
        capacity errors. Raises :class:`DeviceLostError` when the op cannot
        be salvaged, :class:`CapacityError` when it is memory-classified
        (deterministic — the caller routes it to the governor, never the
        transient retry ladder)."""
        cfg = self.cfg
        injected: BaseException | None = None
        if self.faults is not None:
            try:
                self.faults.op(op, compiling=fresh, width=width)
            except FaultDeviceLost as e:
                self.log.log("sup_fault", kind=e.kind, op=op, n=e.n)
                self._transition(SUSPECT, reason=str(e))
                raise DeviceLostError(str(e)) from e
            except (FaultHang, FaultDispatchError, FaultCompileStall,
                    FaultDeviceOOM) as e:
                self.log.log("sup_fault", kind=e.kind, op=op, n=e.n)
                injected = e
        if fresh:
            from ..utils.obs import expected_compile_wall_s

            b = int(key.rsplit("B", 1)[-1].split("x")[0]) if "B" in key else 0
            self._transition(COMPILING, reason=f"cold shape {key}")
            self.log.log("sup_compile", key=key,
                         expected_wall_s=round(expected_compile_wall_s(b), 1))

        def heartbeat(waited: float) -> None:
            self.counters["heartbeats"] += 1
            self.log.log("sup_heartbeat", op=op, key=key,
                         waited_s=round(waited, 1),
                         deadline_s=cfg.compile_deadline_s,
                         state=self.state)

        attempt = 0
        # retry budget applies PER CLASS (ISSUE 5 satellite): a run that eats
        # two timeouts must still have its transient-error budget intact, and
        # a deterministic class (capacity; the compile-stall misfire already
        # short-circuits below) must never consume either ladder
        n_retry = {"timeout": 0, "transient": 0}
        while True:
            attempt += 1
            err: BaseException | None = None
            try:
                if injected is not None:
                    e, injected = injected, None
                    raise e
                if self._inline:
                    out = fn(*make_args(attempt))
                else:
                    deadline = (cfg.compile_deadline_s if fresh
                                else self.op_deadline_s)
                    # make_args runs INSIDE the worker: a retry's re-dispatch
                    # is itself a device call that can hang, so it must sit
                    # under the same deadline as the op proper
                    a = attempt
                    out = self._wd.run(lambda: fn(*make_args(a)), (), deadline,
                                       slice_s=cfg.heartbeat_s if fresh else None,
                                       on_wait=heartbeat if fresh else None)
                if self.state in (COMPILING, RETRYING, FAILBACK, SUSPECT):
                    self._transition(HEALTHY, reason=f"{op} ok")
                return out
            except FaultCompileStall:
                # simulate one silent heartbeat slice, then proceed: the
                # deterministic CPU stand-in for a long server-side compile
                heartbeat(cfg.heartbeat_s)
                continue
            except (WatchdogTimeout, FaultHang) as e:
                self.counters["timeouts"] += 1
                err = e
                cls = "timeout"
                reason = f"{op} timeout: {e}"
            except DeviceLostError:
                raise
            except CapacityError:
                raise
            except FaultDeviceLost as e:
                self._transition(SUSPECT, reason=str(e))
                raise DeviceLostError(str(e)) from e
            except Exception as e:  # dead-tunnel RPC errors, XLA aborts, ...
                if is_capacity_error(e):
                    # deterministic class: re-dispatching the identical shape
                    # would OOM identically — no backoff, no probe, no retry
                    # budget spent; the governor's ladder is the remedy (and
                    # the chip stays HEALTHY: it is full, not dead)
                    if self.state in (COMPILING, RETRYING, FAILBACK, SUSPECT):
                        self._transition(HEALTHY, reason="capacity classified")
                    raise CapacityError(f"{op}: {e}",
                                        width=int(width or 0)) from e
                err = e
                cls = "transient"
                reason = f"{op} error: {type(e).__name__}: {e}"
            self._transition(SUSPECT, reason=reason[:200])
            if not self._probe():
                raise DeviceLostError(reason) from err
            n_retry[cls] += 1
            if n_retry[cls] > cfg.max_retries:
                raise DeviceLostError(
                    f"{op}: {cfg.max_retries} {cls} retries exhausted") from err
            delay = min(cfg.backoff_cap_s,
                        cfg.backoff_base_s * (2 ** (n_retry[cls] - 1)))
            delay *= 1.0 + cfg.jitter * self._rng.random()
            self.counters["retries"] += 1
            self.log.log("sup_retry", op=op, attempt=attempt, cls=cls,
                         delay_s=round(delay, 3), reason=reason[:200])
            time.sleep(delay)
            self._transition(RETRYING, reason=f"{op} attempt {attempt + 1}")
            fresh = False   # a retry is never a cold compile

    # ---- failover / failback -------------------------------------------

    def _engage_fallback(self, reason: str):
        if self._fallback is None:
            if self._fallback_factory is None:
                raise DeviceLostError(
                    f"device lost ({reason}) and no fallback engine "
                    "configured")
            self._transition(LOST, reason=reason[:200])
            self.failed_over = True
            self.fail_reason = reason[:200]
            try:
                self._fallback = self._fallback_factory()
            except Exception as e:
                # a missing/broken fallback engine must surface as the
                # classified loss it is, not as an escaped RuntimeError
                raise DeviceLostError(
                    f"device lost ({reason}) and the fallback engine "
                    f"could not be built: {e}") from e
            self._transition(DEGRADED, reason="fallback engine ready")
            self.log.log("sup_failover", reason=reason[:200],
                         fallback=getattr(self._fallback, "__name__",
                                          type(self._fallback).__name__))
        elif self.state != DEGRADED:
            # the chip died AGAIN after a failback: the fallback engine is
            # already built, but the state must re-enter DEGRADED or every
            # later dispatch would keep retrying the dead primary at full
            # deadline + probe cost
            self._transition(LOST, reason=reason[:200])
            self._transition(DEGRADED, reason="fallback engine re-engaged")
            self.log.log("sup_failover", reason=reason[:200],
                         fallback=getattr(self._fallback, "__name__",
                                          type(self._fallback).__name__))
        return self._fallback

    def _degraded_solve(self, batch, op: str):
        fb = self._engage_fallback("degraded op")
        if self.faults is not None:
            self.faults.op(op, degraded=True)   # only `crash` can fire here
        self.counters["degraded_solves"] += 1
        if hasattr(batch, "to_dense"):
            # degraded engines (native C++ ladder, host-routed solve_tiered)
            # iterate dense rows: unpack the retained paged batch first —
            # byte-identical by the pack/unpack round-trip property
            batch = batch.to_dense()
        return fb(batch)

    def _maybe_failback(self) -> bool:
        """In DEGRADED state with failback enabled: re-probe (rate-limited)
        and, when the chip answers, route the next dispatches back to the
        primary. Shapes are treated as cold again — a revived device has no
        warm programs."""
        if self.state != DEGRADED or not self.cfg.failback:
            return False
        now = time.time()
        if now - self._last_failback_probe < self.cfg.failback_probe_s:
            return False
        self._last_failback_probe = now
        if self.faults is not None and self.faults.device_dead:
            return False
        if not self._probe():
            return False
        self._transition(FAILBACK, reason="re-probe alive")
        self._seen_shapes.clear()
        self._ignore_fp_registry = True
        if self._mesh is not None and self._mesh.nd < len(
                getattr(self._mesh, "_devices0", [])):
            # the revived device pool re-enters whole: the shrunken mesh
            # rebuilds at full width (every shape recompiles under the
            # original :m<N> key — _seen_shapes was just cleared)
            nd_from = self._mesh.nd
            self._mesh.restore()
            self.log.log("mesh.restore", nd_from=nd_from, nd_to=self._mesh.nd)
        self.log.log("sup_failback")
        return True

    # ---- partial-mesh degradation rung ----------------------------------

    def _mesh_degrade(self, reason: str, culprit: int = -1) -> bool:
        """On declared device loss with a mesh primary: shrink the mesh
        N -> N/2 and keep the run on the (smaller) primary — the retained
        batch re-pads and re-dispatches, byte-identical by per-window
        independence — instead of failing over whole-program. Returns False
        when no smaller mesh exists (width 1): the caller then engages the
        native/CPU fallback as before. Walks SUSPECT -> RETRYING, the same
        legal chain a transient retry uses.

        Culprit attribution (ISSUE 13): an injected loss names its member
        (``device_lost:N@K``); a real loss on a non-host-local mesh runs a
        bounded per-device probe. The attributed index rides the
        ``mesh.shrink`` event and flips that member's ``mesh.device`` state
        row to ``lost`` — so a partial-mesh degradation is attributable to
        the single chip that caused it, not just to "the mesh"."""
        m = self._mesh
        if m is None:
            return False
        if m.nd <= 1:
            self.log.log("mesh.degrade", nd=int(m.nd), reason=reason[:200])
            return False
        nd_from = m.nd
        if culprit < 0:
            if self.faults is not None and self.faults.dead_device >= 0:
                culprit = self.faults.dead_device
            elif self.faults is None and not getattr(m, "host_local", True):
                with self.tracer.span("probe"):
                    dead = m.probe_devices()
                if len(dead) == 1:
                    culprit = dead[0]
        prev_state = {i: row.get("state")
                      for i, row in getattr(m, "device_stats", {}).items()}
        m.shrink(culprit=culprit)
        if self.faults is not None:
            # an injected device_lost marks the whole (virtual) backend dead;
            # in mesh terms the loss was ONE member, and the shrink just
            # removed it — the surviving sub-mesh is a fresh primary, so the
            # plan's dead latch clears (a second device_lost spec kills
            # another member and shrinks again)
            self.faults.device_dead = False
            self.faults.dead_device = -1
        self.counters["mesh_shrinks"] += 1
        self.log.log("mesh.shrink", nd_from=int(nd_from), nd_to=int(m.nd),
                     culprit=int(culprit), reason=reason[:200])
        # one mesh.device state row per member THIS shrink removed (earlier
        # casualties already have theirs): the flight-recorder record
        # `daccord-top` keys its device table on
        for i, row in getattr(m, "device_stats", {}).items():
            if row.get("state") != prev_state.get(i):
                self.log.log("mesh.device", device=int(i),
                             state=row["state"],
                             platform=row.get("platform", "?"),
                             dispatches=int(row.get("dispatches", 0)))
        self._transition(RETRYING,
                         reason=f"partial mesh {nd_from}->{m.nd}")
        return True

    # ---- capacity governor hooks ---------------------------------------

    @staticmethod
    def _width_of(batch) -> int | None:
        w = getattr(batch, "size", None)
        return int(w) if w is not None else None

    def _gov_solve_width(self, batch):
        """One guarded dispatch+fetch of ``batch`` at its own (reduced)
        width — the governor's ladder rung executor. Shapes are keyed
        normally, so a shrunken width gets real cold-compile classification
        and records its fingerprint; transient faults still retry; a
        capacity fault propagates as CapacityError for the governor to
        shrink further."""
        key = self._shape_key(batch)
        w = self._width_of(batch)
        fresh = self._is_fresh(key)
        self.counters["dispatch"] += 1
        t_d = time.time()
        inner = self._guarded("dispatch", self._dispatch_fn,
                              lambda attempt: (batch,), key, fresh, width=w)
        self._seen_shapes.add(key)
        if fresh:
            self._record_compile(key, time.time() - t_d)
        h = _SupHandle(inner, batch, key)
        self.counters["fetch"] += 1
        return self._guarded("fetch", self._fetch_fn,
                             lambda attempt: self._refetch_args(h, attempt),
                             key, fresh=False, width=w)

    def _gov_clamp(self, batch):
        """The esc-cap-clamp rung: solve on the clamped ladder program. Its
        effective width for capacity purposes is the clamp itself — the
        M=256 quadratic rescue DP over the esc_cap lanes dominates the
        program's memory, not the tier-0 rows."""
        eff = min(int(self.governor.cfg.esc_clamp),
                  self._width_of(batch) or self.governor.cfg.esc_clamp)
        key = self._shape_key(batch) + ":clamp"
        fresh = self._is_fresh(key)
        self.counters["dispatch"] += 1
        t_d = time.time()
        out = self._guarded("dispatch", self._clamp_solve,
                            lambda attempt: (batch,), key, fresh, width=eff)
        self._seen_shapes.add(key)
        if fresh:
            self._record_compile(key, time.time() - t_d)
        return out

    def _gov_dispatch(self, batch, key: str, reason: str | None) -> _SupHandle:
        """Route ``batch`` through the governor's degradation ladder;
        returns a handle carrying the solved result. A ladder exhausted all
        the way down demotes to native failover (the last rung); a device
        loss mid-walk shrinks a mesh primary first (the partial-mesh rung —
        ratchets then re-key under the new :m<N>), else fails over."""
        t0 = time.time()
        try:
            while True:
                try:
                    out = self.governor.solve(batch, key, reason=reason)
                    break
                except DeviceLostError as e:
                    if self._mesh_degrade(str(e)):
                        key = self._shape_key(batch)
                        continue
                    self._engage_fallback(str(e))
                    return _SupHandle(None, batch, key, degraded=True)
        except CapacityError as e:
            # last rung: native failover. Walk the legal state chain — the
            # device is declared unusable (for this workload), not merely
            # busy, so SUSPECT precedes LOST exactly like a probe-dead path
            self._transition(SUSPECT, reason=f"capacity: {e}"[:200])
            self._engage_fallback(f"capacity ladder exhausted: {e}")
            return _SupHandle(None, batch, key, degraded=True)
        finally:
            if not self._inline:
                # host-local (inline) engines are host time everywhere —
                # only a real device/tunnel solve belongs in device_s
                self.gov_device_s += time.time() - t0
        return _SupHandle(None, batch, key, result=out)

    # ---- solver interface ----------------------------------------------

    def dispatch(self, batch) -> _SupHandle:
        # Staged-dispatch unwrap (ISSUE 19): a mesh ``StagedBatch`` carries
        # pre-transferred per-device shards PLUS the host-side batch they
        # came from. Everything the supervisor might ever replay — retries,
        # governor bisect, partial-mesh shrink, failover, crash/resume —
        # operates on the retained HOST batch; only the first dispatch
        # attempt consumes the staged device buffers (and the mesh solver
        # re-stages a stale one itself when the mesh changed under it).
        staged = batch if hasattr(batch, "replay_batch") else None
        rb = staged.replay_batch if staged is not None else batch
        key = self._shape_key(rb)
        if self.state == DEGRADED:
            self._maybe_failback()
        if self.state in (LOST, DEGRADED):
            # degraded dispatch is lazy: the batch solves at fetch time, so
            # the pipeline's dispatch/drain cadence is preserved
            self.counters["dispatch"] += 1
            if self.faults is not None:
                self.faults.op("dispatch", degraded=True)
            return _SupHandle(None, rb, key, degraded=True)
        w = self._width_of(rb)
        if w is not None:
            planned = self.governor.planned_width(key, w)
            if planned is not None:
                # ratcheted shape: dispatch at the known-good width directly
                # — never re-try the full width (that is the retry-storm this
                # module exists to kill); opt-in probation restores it. Not
                # counted here: no op runs at this width — the governor's
                # own guarded ops count themselves
                return self._gov_dispatch(rb, key, reason=None)
        self.counters["dispatch"] += 1
        while True:
            fresh = self._is_fresh(key)
            t_d = time.time()
            try:
                arg = staged if staged is not None else rb
                inner = self._guarded("dispatch", self._dispatch_fn,
                                      lambda attempt: (arg if attempt == 1
                                                       else rb,),
                                      key, fresh, width=w)
                break
            except CapacityError as e:
                return self._gov_dispatch(rb, key, reason=str(e))
            except DeviceLostError as e:
                # partial-mesh degradation rung: a shrunken mesh is a new
                # primary at a new :m<N> key (cold-classified), so the
                # re-dispatch below gets real compile deadlines. The staged
                # device buffers are discarded with it — the retained host
                # batch re-stages at the new width (byte-identical).
                if self._mesh_degrade(str(e)):
                    staged = None
                    key = self._shape_key(rb)
                    continue
                self._engage_fallback(str(e))
                return _SupHandle(None, rb, key, degraded=True)
        self._seen_shapes.add(key)
        if fresh:
            self._record_compile(key, time.time() - t_d)
        return _SupHandle(inner, rb, key)

    def _record_compile(self, key: str, wall_s: float) -> None:
        """Fold a fresh shape's measured dispatch wall into the fingerprint
        registry (ISSUE 13): jit compilation is synchronous at call time,
        so a cold dispatch's wall IS the compile wall to within the launch
        cost. The registry entry keeps the FIRST (cold) wall; the
        ``sup_compile_done`` event gives live consumers the same number."""
        from ..utils.obs import record_fingerprint

        record_fingerprint(key, wall_s=wall_s)
        self.log.log("sup_compile_done", key=key, wall_s=round(wall_s, 3))

    def _refetch_args(self, h: _SupHandle, attempt: int):
        """Arg builder for a guarded fetch: attempt 1 uses the live handle;
        a retry re-dispatches the retained batch first — the abandoned/
        broken in-flight result is discarded, so exactly one result per
        batch reaches the caller (no duplicate, no drop)."""
        if attempt > 1 or h.inner is None:
            h.inner = self._dispatch_fn(h.batch)
        return (h.inner,)

    def fetch(self, handle: _SupHandle):
        h = handle
        if h.result is not None:
            # governor-solved at dispatch time: the result is already host-
            # side and final — valid even after a later failover (replay
            # must not re-solve it on the degraded engine). Not counted: the
            # governor's own guarded ops already were.
            return h.result
        self.counters["fetch"] += 1
        if h.degraded or self.state in (LOST, DEGRADED):
            return self._degraded_solve(h.batch, "fetch")
        try:
            out = self._guarded("fetch", self._fetch_fn,
                                lambda attempt: self._refetch_args(h, attempt),
                                h.key, fresh=False, width=self._width_of(h.batch))
            return self._postfetch(h, out)
        except CapacityError as e:
            # the OOM surfaced at materialization (async dispatch): the
            # retained batch re-solves down the ladder, never verbatim
            gh = self._gov_dispatch(h.batch, h.key, reason=str(e))
            if gh.result is not None:
                return gh.result
            return self._degraded_solve(h.batch, "fetch")
        except DeviceLostError as e:
            if self._mesh_degrade(str(e)):
                # re-dispatch the retained batch on the shrunken mesh and
                # fetch THAT: dispatch/fetch recursion absorbs any further
                # loss (another shrink, or failover at mesh width 1)
                return self.fetch(self.dispatch(h.batch))
            self._engage_fallback(str(e))
            return self._degraded_solve(h.batch, "fetch")

    def fetch_many(self, handles: list) -> list:
        """Grouped fetch (one tunnel RTT for the whole drain). Counts as ONE
        logical fetch op; on declared loss every batch in the group replays
        on the degraded engine."""
        if self._fetch_many_fn is None or len(handles) == 1 or \
                any(h.degraded or h.result is not None for h in handles) or \
                self.state in (LOST, DEGRADED):
            return [self.fetch(h) for h in handles]
        self.counters["fetch"] += 1
        widths = [self._width_of(h.batch) for h in handles]
        width = max((w for w in widths if w is not None), default=None)

        def make_args(attempt):
            # a retried group re-dispatches every batch (see _refetch_args)
            inners = []
            for h in handles:
                if attempt > 1 or h.inner is None:
                    h.inner = self._dispatch_fn(h.batch)
                inners.append(h.inner)
            return (inners,)

        try:
            outs = self._guarded("fetch", self._fetch_many_fn, make_args,
                                 handles[0].key, fresh=False, width=width)
            return [self._postfetch(h, o) for h, o in zip(handles, outs)]
        except CapacityError:
            # per-handle fallback: each batch classifies (and degrades)
            # against its OWN width — a group is not a capacity unit. The
            # per-handle fetches count themselves; un-count the abandoned
            # group op so ratios stay one-count-per-result.
            self.counters["fetch"] -= 1
            return [self.fetch(h) for h in handles]
        except DeviceLostError as e:
            if self._mesh_degrade(str(e)):
                # every batch in the drained group replays on the shrunken
                # mesh (dispatch/fetch recursion absorbs further losses)
                self.counters["fetch"] -= 1
                return [self.fetch(self.dispatch(h.batch)) for h in handles]
            self._engage_fallback(str(e))
            return [self._degraded_solve(h.batch, "fetch") for h in handles]

    # ---- silent-data-corruption defense plane (ISSUE 20) -----------------

    def _postfetch(self, h, out):
        """Runs on every SUCCESSFUL primary fetch: (1) inject any pending
        ``sdc`` fault — silent corruption of the packed consensus rows, no
        exception raised, exactly what a lying chip looks like; (2) sampled
        shadow verification against the trusted reference engine. Degraded
        solves and governor-solved results never pass through here: the
        reference IS (or shares bytes with) the degraded engine, so
        auditing those would be a tautology."""
        if not isinstance(out, dict) or "cons" not in out:
            return out
        if self.faults is not None and self.faults.has_sdc_faults():
            spec = self.faults.sdc_check()
            if spec is not None:
                self._sdc_corrupt(out, spec.device)
        if self._audit_rate > 0.0 and self._audit_ref_factory is not None:
            out = self._audit(h, out)
        return out

    def _sdc_corrupt(self, out: dict, device: int) -> None:
        """Silently corrupt the result rows owned by mesh member ``device``
        (every row when unpinned or no mesh). Corruption bumps live
        consensus bases in place — valid alphabet, valid lengths, no flag
        touched — so nothing downstream can notice without comparing bytes
        against the reference."""
        import numpy as np

        B = int(np.asarray(out["cons"]).shape[0])
        rows = range(B)
        if device >= 0 and self._mesh is not None and self._mesh.nd > 1:
            members = self._mesh.member_ids()
            if device not in members:
                return      # pinned member already shrunk out of the mesh
            per = -(-B // len(members))
            j = members.index(device)
            lo, hi = j * per, min((j + 1) * per, B)
            if lo >= hi:
                return      # trimmed tail: this member got only pad rows
            rows = range(lo, hi)
        self._corrupt_rows(out, rows)

    @staticmethod
    def _corrupt_rows(out: dict, rows) -> None:
        import numpy as np

        cons = np.asarray(out["cons"])
        if not cons.flags.writeable:
            cons = cons.copy()
            out["cons"] = cons
        cl = np.asarray(out["cons_len"])
        solved = np.asarray(out["solved"])
        for i in rows:
            if not bool(solved[i]):
                continue
            n = int(cl[i])
            if n <= 0:
                continue
            seg = cons[i, :n]
            live = seg < 4
            seg[live] = (seg[live] + 1) % 4

    # ---- sampled shadow verification -------------------------------------

    def _audit_engine(self):
        """Lazy build of the trusted reference engine (the same factory the
        failover rung uses — byte-exact host ladder). A build failure
        disables auditing for the run rather than killing it: the audit is
        a defense plane, not a dependency."""
        if self._audit_ref is None and self._audit_ref_factory is not None:
            try:
                with self.tracer.span("audit.build"):
                    self._audit_ref = self._audit_ref_factory()
            except Exception as e:
                self.log.log("audit.disabled", error=str(e)[:200])
                self._audit_rate = 0.0
                self._audit_ref_factory = None
                return None
        return self._audit_ref

    def _audit_sample(self, B: int) -> list[int]:
        """Deterministic seeded row sample for one audited batch, budgeted
        at ``k = max(1, round(B*rate))`` rows. On a mesh the sample is
        member-aware — a lying member must not hide in the unsampled rows:
        when the budget covers the mesh (``k >= nd``) every member slice
        contributes a row EVERY batch (deterministic per-batch detection,
        what BENCH_SDC asserts at B=512/nd=8/rate=1/64); under that, member
        slices rotate round-robin across audited batches, so every member
        is still audited once per ``nd`` batches at the configured cost.
        Seeded by (cfg.seed, audit ordinal) so a re-run samples identically
        — the chaos soak depends on that determinism."""
        import random

        rng = random.Random((self.cfg.seed << 16) ^ self._n_audit)
        k = min(max(1, round(B * self._audit_rate)), B)
        rows: set[int] = set()
        if self._mesh is not None and self._mesh.nd > 1:
            nd = self._mesh.nd
            per = -(-B // nd)
            slices = range(nd) if k >= nd else [self._n_audit % nd]
            for j in slices:
                lo, hi = j * per, min((j + 1) * per, B)
                if lo < hi:
                    rows.add(rng.randrange(lo, hi))
        while len(rows) < k:
            rows.add(rng.randrange(B))
        return sorted(rows)

    @staticmethod
    def _take_rows(batch, rows):
        """Row-subset copy of a batch (dense first — the reference ladder
        iterates dense rows, same contract as ``_degraded_solve``)."""
        import dataclasses

        import numpy as np

        if hasattr(batch, "to_dense"):
            batch = batch.to_dense()
        idx = np.asarray(rows, dtype=np.int64)
        return dataclasses.replace(
            batch, seqs=batch.seqs[idx], lens=batch.lens[idx],
            nsegs=batch.nsegs[idx], read_ids=batch.read_ids[idx],
            wstarts=batch.wstarts[idx])

    @staticmethod
    def _rows_equal(dev: dict, ref: dict, i: int, j: int, tier0: bool):
        """Byte comparison of device row ``i`` against reference row ``j``.
        Returns None to SKIP a row the comparison cannot judge: on a
        tier0-stream batch the reference (a full ladder) legitimately
        solves rows the tier0 program pools for rescue, so only rows the
        device claims final (solved & !m_ovf) are comparable. err/tier are
        deliberately excluded — they never reach the FASTA."""
        import numpy as np

        if tier0 and (not bool(dev["solved"][i]) or bool(dev["m_ovf"][i])):
            return None
        if bool(dev["solved"][i]) != bool(ref["solved"][j]):
            return False
        if not bool(dev["solved"][i]):
            return True
        nd_, nr_ = int(dev["cons_len"][i]), int(ref["cons_len"][j])
        if nd_ != nr_:
            return False
        return bool(np.array_equal(np.asarray(dev["cons"])[i, :nd_],
                                   np.asarray(ref["cons"])[j, :nr_]))

    def _audit(self, h, out: dict):
        """Shadow-verify a seeded sample of ``out`` rows byte-for-byte
        against the reference engine. On divergence: emit ``sup_sdc``,
        attribute the culprit member (mesh), strike its trust ratchet, and
        re-solve the WHOLE batch on the reference — so a detected
        corruption never reaches the caller and output bytes are identical
        to a clean run (a tier0 batch re-solves to full-ladder rows, which
        composes byte-identically by the pipeline's pool rule — the same
        argument the failover replay rests on)."""
        import numpy as np

        eng = self._audit_engine()
        if eng is None:
            return out
        batch = h.batch
        B = int(np.asarray(out["cons"]).shape[0])
        if B <= 0:
            return out
        rows = self._audit_sample(B)
        self._n_audit += 1
        self.counters["audits"] += 1
        sample = self._take_rows(batch, rows)
        shape = tuple(np.asarray(sample.seqs).shape)
        if shape not in self._audit_warmed:
            # first audit at this shape pays the reference ladder's XLA
            # compile — a one-time cost like the engine build, booked under
            # its own span and NOT under audit_s: the audit RATE controls
            # the per-audit steady-state cost, which is what the ≤2%
            # overhead contract (BENCH_SDC) is about
            self._audit_warmed.add(shape)
            with self.tracer.span("audit.warm", rows=len(rows)):
                eng(sample)
        t0 = time.time()
        tier0 = getattr(batch, "stream", "full") == "tier0"
        with self.tracer.span("audit", rows=len(rows)):
            ref = eng(sample)
        divergent = [i for j, i in enumerate(rows)
                     if self._rows_equal(out, ref, i, j, tier0) is False]
        if not divergent:
            self.audit_s += time.time() - t0
            return out
        self.counters["sdc_detected"] += 1
        culprit = self._sdc_attribute(batch, divergent[0])
        self.log.log("sup_sdc", key=h.key, rows=int(B), sampled=len(rows),
                     divergent=len(divergent), row=int(divergent[0]),
                     culprit=int(culprit))
        dense = batch.to_dense() if hasattr(batch, "to_dense") else batch
        with self.tracer.span("audit.resolve", rows=int(B)):
            out = eng(dense)
        self.audit_s += time.time() - t0
        self._trust_strike(culprit, "shadow audit divergence")
        return out

    def _sdc_attribute(self, batch, row: int) -> int:
        """Per-member re-dispatch of ONE divergent window: the row is
        replicated mesh-width times so each member solves its own copy
        (slice width 1), and whichever member's copy diverges from the
        reference is the culprit. Rides the raw mesh (not the supervised
        path — a recursive audit would be circular); the fault plan's
        persistent liar set re-applies the injected corruption here, which
        is what makes attribution verifiable chip-free on CPU."""
        m = self._mesh
        if m is None or m.nd <= 1:
            return -1
        eng = self._audit_engine()
        if eng is None:
            return -1
        import dataclasses

        import numpy as np

        dense = batch.to_dense() if hasattr(batch, "to_dense") else batch
        nd = int(m.nd)
        rep = lambda a: np.repeat(a[row:row + 1], nd, axis=0)
        probe = dataclasses.replace(
            dense, seqs=rep(dense.seqs), lens=rep(dense.lens),
            nsegs=rep(dense.nsegs), read_ids=rep(dense.read_ids),
            wstarts=rep(dense.wstarts), stream="full")
        members = m.member_ids()
        try:
            pout = m.fetch(m.dispatch(probe))
        except Exception as e:
            self.log.log("audit.attrib", row=int(row), culprit=-1,
                         nd=nd, error=str(e)[:200])
            return -1
        if self.faults is not None:
            liars = self.faults.sdc_liars()
            for j, orig in enumerate(members):
                if orig in liars:
                    self._corrupt_rows(pout, [j])
        ref1 = eng(self._take_rows(dense, [row]))
        culprits = [members[j] for j in range(len(members))
                    if self._rows_equal(pout, ref1, j, 0, False) is False]
        culprit = int(culprits[0]) if culprits else -1
        self.log.log("audit.attrib", row=int(row), culprit=culprit, nd=nd)
        return culprit

    # ---- device trust ratchet --------------------------------------------

    def _trust_key(self, orig: int) -> str:
        return f"{self._fp_prefix}m{int(orig)}"

    def _trust_strike(self, orig: int, reason: str) -> None:
        """Ratchet TRUSTED -> SUSPECT -> QUARANTINED (never loosens within
        a run). Quarantine drives the EXISTING degradation rungs — the
        partial-mesh shrink for an attributed member, whole-program
        failover otherwise — and persists to the trust registry so the
        next run starts with the chip already out (or on probation under
        ``DACCORD_TRUST_PROBATION=1``)."""
        from ..utils.obs import (TRUST_QUARANTINED, TRUST_SUSPECT,
                                 TRUST_TRUSTED, record_trust)

        orig = int(orig)
        ent = self._trust.setdefault(orig, {"state": TRUST_TRUSTED,
                                            "strikes": 0})
        ent["strikes"] += 1
        frm = ent["state"]
        to = TRUST_QUARANTINED \
            if ent["strikes"] >= self._trust_strikes_max else TRUST_SUSPECT
        if frm == TRUST_QUARANTINED:
            to = TRUST_QUARANTINED
        ent["state"] = to
        self.log.log("trust.state", device=orig, state_from=frm,
                     state_to=to, strikes=int(ent["strikes"]))
        record_trust(self._trust_key(orig), to, ent["strikes"])
        if to != TRUST_QUARANTINED or frm == TRUST_QUARANTINED:
            return
        # SUSPECT first: the state machine has no HEALTHY->RETRYING edge,
        # and a trust quarantine IS a suspicion resolved against the device
        if self._mesh is not None and self._mesh.nd > 1 and \
                orig in self._mesh.member_ids():
            if self.state in (HEALTHY, COMPILING):
                self._transition(SUSPECT, reason=reason)
            self._mesh_degrade(f"trust quarantined: {reason}", culprit=orig)
        elif self._fallback_factory is not None:
            if self.state in (HEALTHY, COMPILING, RETRYING):
                self._transition(SUSPECT, reason=reason)
            try:
                self._engage_fallback(f"trust quarantined: {reason}")
            except DeviceLostError:
                pass        # no fallback buildable: keep running, keep auditing

    def _trust_load(self) -> None:
        """Load persisted trust state for the active mesh members (called
        once, right after ``sup_init``). A registry-quarantined member is
        shrunk out before it solves a single window — unless
        ``DACCORD_TRUST_PROBATION=1`` demotes it to SUSPECT for a
        re-verify, mirroring the governor's probation lever."""
        from ..utils.obs import (TRUST_QUARANTINED, TRUST_SUSPECT,
                                 record_trust, trust_registry)

        reg = trust_registry()
        if not reg:
            return
        m = self._mesh
        for orig in list(m.member_ids()):
            ent = reg.get(self._trust_key(orig))
            if not ent:
                continue
            state = ent.get("state")
            strikes = int(ent.get("strikes", 0))
            self._trust[int(orig)] = {"state": state, "strikes": strikes}
            self.log.log("trust.load", device=int(orig), state=state,
                         strikes=strikes)
            if state != TRUST_QUARANTINED:
                continue
            if self._trust_probation:
                demoted = max(0, strikes - 1)
                self._trust[int(orig)] = {"state": TRUST_SUSPECT,
                                          "strikes": demoted}
                self.log.log("trust.state", device=int(orig),
                             state_from=state, state_to=TRUST_SUSPECT,
                             strikes=demoted)
                record_trust(self._trust_key(orig), TRUST_SUSPECT, demoted)
                continue
            while m.nd > 1 and orig in m.member_ids():
                nd_from = m.nd
                prev_state = {i: row.get("state") for i, row in
                              getattr(m, "device_stats", {}).items()}
                m.shrink(culprit=int(orig))
                self.counters["mesh_shrinks"] += 1
                self.log.log("mesh.shrink", nd_from=int(nd_from),
                             nd_to=int(m.nd), culprit=int(orig),
                             reason="trust quarantined (registry)")
                for i, row in getattr(m, "device_stats", {}).items():
                    if row.get("state") != prev_state.get(i):
                        self.log.log("mesh.device", device=int(i),
                                     state=row["state"],
                                     platform=row.get("platform", "?"),
                                     dispatches=int(row.get("dispatches", 0)))
