"""Deterministic fault injection for the device supervisor.

Every failure mode the supervisor handles (``runtime/supervisor.py``) can be
reproduced on a CPU-only host from one env var, so the whole
dispatch/fetch/failover state machine is testable without a TPU and without
wall-clock waits::

    DACCORD_FAULT=fetch_hang:3            # 3rd fetch times out once
    DACCORD_FAULT=dispatch_error:5        # 5th dispatch raises once
    DACCORD_FAULT=device_lost:7           # 7th device op: chip declared dead
    DACCORD_FAULT=compile_stall           # first cold-shape op stalls once
    DACCORD_FAULT=device_lost:2,crash:9   # comma-joins compose

Grammar: ``kind[:N]`` with N the 1-based index of the triggering operation in
that kind's counter domain (default 1). Counters advance once per *logical*
operation (retries of the same op do not re-count), so a given spec fires at
exactly one reproducible point in a run. All faults are one-shot except the
state they leave behind: ``device_lost`` additionally marks the (virtual)
device dead, which the supervisor's probe consults before any real probe —
so the probe-declares-loss path runs deterministically too.

``crash`` is a test-only kind: it raises :class:`InjectedCrash`, a
``BaseException`` the supervisor deliberately does NOT catch, simulating a
hard process death (SIGKILL-ish) for checkpoint/resume composition tests.

Counter domains: ``fetch_hang`` counts fetches, ``dispatch_error`` counts
dispatches, ``device_lost``/``crash`` count device ops (dispatch + fetch,
interleaved in pipeline order), ``compile_stall`` counts cold-shape ops.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


class FaultInjected(Exception):
    """Base class of injected (recoverable) faults. Instances carry the
    spec's ``kind`` and the 1-based index ``n`` in that kind's own counter
    domain, so event logs match the ``DACCORD_FAULT`` grammar exactly."""

    kind = "fault"
    n = 0


class FaultHang(FaultInjected):
    """Injected hang: the supervisor treats it exactly like a watchdog
    deadline expiry (no real wall-clock is spent)."""


class FaultDispatchError(FaultInjected):
    """Injected transient dispatch failure (retry succeeds)."""


class FaultDeviceLost(FaultInjected):
    """Injected terminal device loss (probe reports dead afterwards)."""


class FaultCompileStall(FaultInjected):
    """Injected first-compile stall (exercises the COMPILING/heartbeat
    path; the op then proceeds normally)."""


class InjectedCrash(BaseException):
    """Test-only hard crash: BaseException so no supervisor/pipeline
    ``except Exception`` can swallow it — it must unwind like a kill."""


_KINDS = ("fetch_hang", "dispatch_error", "device_lost", "compile_stall",
          "crash")


@dataclass
class FaultSpec:
    kind: str
    at: int = 1        # 1-based index in the kind's counter domain
    fired: bool = False


@dataclass
class FaultPlan:
    specs: list = field(default_factory=list)
    device_dead: bool = False
    # logical-operation counters (advance once per op, not per retry)
    n_dispatch: int = 0
    n_fetch: int = 0
    n_device: int = 0
    n_compile: int = 0

    @classmethod
    def parse(cls, text: str) -> "FaultPlan":
        specs = []
        for part in text.split(","):
            part = part.strip()
            if not part:
                continue
            kind, _, at = part.partition(":")
            if kind not in _KINDS:
                raise ValueError(
                    f"DACCORD_FAULT: unknown kind {kind!r} (known: "
                    f"{', '.join(_KINDS)})")
            try:
                n = int(at) if at else 1
            except ValueError:
                raise ValueError(f"DACCORD_FAULT: bad count in {part!r}")
            if n < 1:
                raise ValueError(f"DACCORD_FAULT: count must be >= 1 in {part!r}")
            specs.append(FaultSpec(kind, n))
        return cls(specs=specs)

    @classmethod
    def from_env(cls, env=None) -> "FaultPlan | None":
        """The process-wide plan, or None when ``DACCORD_FAULT`` is unset.
        Read at supervisor construction (once per shard), so a test can set
        the env var per run."""
        text = (env if env is not None else os.environ).get("DACCORD_FAULT")
        return cls.parse(text) if text else None

    def _take(self, kind: str, count: int) -> FaultSpec | None:
        for s in self.specs:
            if s.kind == kind and not s.fired and count >= s.at:
                s.fired = True
                return s
        return None

    def op(self, domain: str, compiling: bool = False,
           degraded: bool = False) -> None:
        """Advance counters for one logical ``dispatch``/``fetch`` op and
        raise the matching injected fault, if any. ``degraded`` ops (already
        failed over; no device involved) only ever raise ``crash`` — the
        device-fault kinds describe the primary engine."""
        if domain == "dispatch":
            self.n_dispatch += 1
        elif domain == "fetch":
            self.n_fetch += 1
        else:
            raise ValueError(f"unknown op domain {domain!r}")
        self.n_device += 1
        if compiling:
            self.n_compile += 1
        def _raise(exc_cls, kind: str, n: int, msg: str):
            e = exc_cls(msg)
            e.kind, e.n = kind, n
            raise e

        if self._take("crash", self.n_device) is not None:
            raise InjectedCrash(f"injected crash at {domain} #{self.n_device}")
        if degraded:
            return
        if self.device_dead:
            # a lost device stays lost for every later primary op
            _raise(FaultDeviceLost, "device_lost", self.n_device,
                   f"device dead (injected) at {domain}")
        if self._take("device_lost", self.n_device) is not None:
            self.device_dead = True
            _raise(FaultDeviceLost, "device_lost", self.n_device,
                   f"injected device_lost at {domain} #{self.n_device}")
        if domain == "fetch" and self._take("fetch_hang",
                                            self.n_fetch) is not None:
            _raise(FaultHang, "fetch_hang", self.n_fetch,
                   f"injected fetch_hang at fetch #{self.n_fetch}")
        if domain == "dispatch" and self._take(
                "dispatch_error", self.n_dispatch) is not None:
            _raise(FaultDispatchError, "dispatch_error", self.n_dispatch,
                   f"injected dispatch_error at dispatch #{self.n_dispatch}")
        if compiling and self._take("compile_stall",
                                    self.n_compile) is not None:
            _raise(FaultCompileStall, "compile_stall", self.n_compile,
                   f"injected compile_stall at cold-shape op "
                   f"#{self.n_compile}")

    def probe_override(self) -> bool | None:
        """False once device_lost fired (probe must agree the chip is dead);
        None = no opinion, run the real probe."""
        return False if self.device_dead else None
