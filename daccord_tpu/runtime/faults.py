"""Deterministic fault injection for the device supervisor.

Every failure mode the supervisor handles (``runtime/supervisor.py``) can be
reproduced on a CPU-only host from one env var, so the whole
dispatch/fetch/failover state machine is testable without a TPU and without
wall-clock waits::

    DACCORD_FAULT=fetch_hang:3            # 3rd fetch times out once
    DACCORD_FAULT=dispatch_error:5        # 5th dispatch raises once
    DACCORD_FAULT=device_lost:7           # 7th device op: chip declared dead
    DACCORD_FAULT=compile_stall           # first cold-shape op stalls once
    DACCORD_FAULT=device_lost:2,crash:9   # comma-joins compose

Grammar: ``kind[:N]`` with N the 1-based index of the triggering operation in
that kind's counter domain (default 1). Counters advance once per *logical*
operation (retries of the same op do not re-count), so a given spec fires at
exactly one reproducible point in a run. All faults are one-shot except the
state they leave behind: ``device_lost`` additionally marks the (virtual)
device dead, which the supervisor's probe consults before any real probe —
so the probe-declares-loss path runs deterministically too.

``device_lost`` accepts an optional mesh-member index: ``device_lost:2@3``
marks device 3 as the member that died. On a mesh primary the supervisor
attributes the partial-mesh shrink to that device index (``mesh.shrink``
``culprit`` + a ``mesh.device`` state row) — the per-chip attribution the
flight recorder (ISSUE 13) exists for. Without ``@K`` the culprit is
unknown (-1), matching a real whole-program abort.

``crash`` is a test-only kind: it raises :class:`InjectedCrash`, a
``BaseException`` the supervisor deliberately does NOT catch, simulating a
hard process death (SIGKILL-ish) for checkpoint/resume composition tests.

Counter domains: ``fetch_hang`` counts fetches, ``dispatch_error`` counts
dispatches, ``device_lost``/``crash`` count device ops (dispatch + fetch,
interleaved in pipeline order), ``compile_stall`` counts cold-shape ops.

Data-corruption kinds (the ingest-layer twins, ISSUE 2) corrupt input
artifacts instead of raising at ops — N indexes the corrupted record::

    DACCORD_FAULT=las_bitflip:4           # flip abpos MSB of LAS record 4
    DACCORD_FAULT=las_truncate:30         # cut the LAS mid-record 30
    DACCORD_FAULT=db_garbage:2            # 0xFF over DB .idx read record 2

They are applied once by the pipeline entry points via
:func:`maybe_apply_data_faults` (or directly by tests / the pounce
corruption-fuzz step via the ``corrupt_*`` helpers).

Fleet kinds (the orchestrator-level twins, ``parallel/fleet.py``) sabotage
worker processes / lease renewal instead of device ops or artifacts::

    DACCORD_FAULT=worker_crash:2          # 2nd spawned worker dies mid-shard
    DACCORD_FAULT=worker_hang:3           # 3rd spawned worker wedges (no progress)
    DACCORD_FAULT=lease_stall             # 1st claimed lease stops heartbeating
    DACCORD_FAULT=worker_oom:2            # 2nd spawned worker exits like an
                                          # OOM-killed process (status 137)

Counter domains: ``worker_crash``/``worker_hang``/``worker_oom`` count
worker spawns (fleet-wide, in spawn order), ``lease_stall`` counts
successful lease claims. The orchestrator consumes them via
:meth:`FaultPlan.fleet_spawn` / :meth:`FaultPlan.fleet_claim_stall`; worker
subprocesses never see the fleet kinds (the fleet strips them from the
inherited ``DACCORD_FAULT``), so a composed spec like
``worker_crash:1,las_bitflip:3`` sends only the data kind down to the
workers.

Capacity kinds (the memory-exhaustion twins, ISSUE 5) make the capacity
governor (``runtime/governor.py``) deterministically testable on CPU::

    DACCORD_FAULT=device_oom:3            # 3rd device op: allocator OOM, and
                                          # a virtual HBM ceiling is set to
                                          # HALF that op's batch width — every
                                          # later primary op wider than the
                                          # ceiling OOMs too, so the governor's
                                          # bisect walk terminates exactly when
                                          # the shape genuinely fits
    DACCORD_FAULT=host_rss:2              # 2nd host-watermark check reports
                                          # hard memory pressure once
    DACCORD_FAULT=monster_pile:4          # 4th pile inspected by the monster
                                          # guard busts the budget once

Counter domains: ``device_oom`` counts device ops (dispatch + fetch, like
``device_lost``); ``host_rss`` counts watermark checks (one per pile block,
:meth:`FaultPlan.host_rss_check`); ``monster_pile`` counts piles inspected
before tensorization (:meth:`FaultPlan.monster_check`). The ceiling left by
``device_oom`` is deliberately NOT one-shot: re-dispatching the identical
doomed shape must keep failing (that is the failure mode under test), while
a bisected one fits.

Serve-tier kinds (the crash-durability twins, ISSUE 15) sabotage a
``daccord-serve`` process the way the fleet kinds sabotage worker
subprocesses — from inside, deterministically, so the whole journal-replay
and peer-takeover machinery runs on CPU in CI::

    DACCORD_FAULT=serve_crash:3           # the process dies HARD (exit 137,
                                          # no cleanup) right after its 3rd
                                          # journal append becomes durable
    DACCORD_FAULT=serve_hang:1            # the 1st job run wedges forever
                                          # (a group thread stuck in a solve)

Counter domains: ``serve_crash`` counts fsync'd journal appends
(:meth:`FaultPlan.serve_crash_check`, consumed by ``serve/journal.py`` —
the append is durable FIRST, then the process dies, so every record the
journal claims to hold survives the injected crash exactly like a real
SIGKILL between syscalls); ``serve_hang`` counts job runs
(:meth:`FaultPlan.serve_hang_check`, consumed by ``serve/jobs.run_job``).
Because the journal appends in lifecycle order (admitted, running,
progress..., committing, committed), ``serve_crash:N`` lands the death at
an exact lifecycle point: N=1 dies post-admit pre-queue, N=3 with a small
checkpoint stride dies running mid-batch, N=3 with checkpoints off dies
mid-commit — after the FASTA fsync, before the publishing rename. The kill
matrix in tests/test_serve_durability.py and the chaos soak
(``DACCORD_BENCH_SERVE_SOAK``) are built on exactly this determinism.
Like the fleet kinds, serve kinds never reach the per-job pipeline — the
pipeline's own FaultPlan parses the same spec, so the kinds are known
everywhere but consumed only by the serve layer.

The saturation-profiler kind (ISSUE 14) deliberately breaks the index
grammar: ``feeder_stall:N`` reads N as MILLISECONDS of artificial delay
injected into EVERY feeder pile block (booked under the profiler's
``stall`` stage), not a 1-based trigger index — flipping a bottleneck
verdict requires sustained slowdown, not a one-shot event. It is the A/B
lever the acceptance run uses: the same corpus with ``feeder_stall:50``
must flip the committed verdict to ``host_feeder`` with ``stall`` named as
the dominant sub-stage, while the FASTA stays byte-identical (a slow feeder
changes wall-clock, never bytes).

Storage kinds (the I/O twins, ISSUE 17) make the disk say no — every
durable path (journal appends, lease claims/renewals, manifest commits,
spool uploads, telemetry sidecars, AOT-cache publishes) consults the plan
through ``utils/aio.py``'s fault hook, so the full-disk matrix runs
chip-free like every prior one::

    DACCORD_FAULT=io_enospc:3             # 3rd I/O primitive op: ENOSPC
    DACCORD_FAULT=io_eio:2                # 2nd op: transient EIO (the aio
                                          # bounded-retry wrapper absorbs it)
    DACCORD_FAULT=io_fsync_fail:1         # 1st op: the fsync step fails
    DACCORD_FAULT=io_short_write:2        # 2nd op: torn bytes hit the disk,
                                          # then the write errors (ENOSPC)
    DACCORD_FAULT=io_slow:50              # EVERY op delayed 50 ms (duration
                                          # grammar, like feeder_stall)
    DACCORD_FAULT=io_enospc:3@journal     # 3rd JOURNAL-domain op only

The optional ``@domain`` suffix scopes a storage spec to one path class —
``journal`` | ``lease`` | ``manifest`` | ``spool`` | ``sidecar`` | ``aot``
— with a per-domain counter, so ``io_enospc:3@journal`` means "the 3rd
journal write fails" regardless of how much lease/sidecar traffic
interleaves. Without a domain, N indexes the process-wide I/O-op counter.
Counter domains: every :meth:`FaultPlan.io_check` call (one per logical
aio primitive invocation — retries of the same op re-count, because each
retry genuinely re-runs the syscalls) advances both the global and the
per-domain counter. ``io_slow`` reads N as milliseconds and is continuous
(never fired-out), mirroring ``feeder_stall``; an ``@domain`` scopes the
delay. ``io_eio`` is the only *transient* class: ``aio.retrying`` retries
it with bounded backoff, while ``io_enospc`` / ``io_fsync_fail`` /
``io_short_write`` are persistent-for-this-op and surface to the caller
(a failed fsync in particular must never be silently retried — the page
state after it is undefined).

Network kinds (the socket twins, ISSUE 18) make the router → peer HTTP
fabric say no — every router/autoscaler/client call goes through the
``serve/netio.py`` choke point, which consults the plan before (and, for
``net_torn``, while) each request, so grey network failures run chip-free
and socket-free like every prior matrix::

    DACCORD_FAULT=net_refused:3           # 3rd HTTP op: connection refused
    DACCORD_FAULT=net_reset:2             # 2nd op: connection reset mid-flight
    DACCORD_FAULT=net_hang:1              # 1st op: the socket wedges until
                                          # the per-domain deadline expires
    DACCORD_FAULT=net_torn:512            # next response body truncated
                                          # after 512 bytes (N is BYTES, not
                                          # an op index — it tears the FIRST
                                          # matching op's stream)
    DACCORD_FAULT=net_slow:80             # EVERY op delayed 80 ms (duration
                                          # grammar, like io_slow)
    DACCORD_FAULT=net_reset:3@submit      # 3rd SUBMIT-domain op only

The optional ``@domain`` suffix scopes a net spec to one RPC class —
``healthz`` | ``submit`` | ``result`` | ``stream`` | ``abort`` — with a
per-domain counter, exactly the ``io_*@domain`` design one layer up.
Counter domains: every :meth:`FaultPlan.net_check` call (one per HTTP
*attempt* — retries re-count, each retry genuinely re-opens a socket)
advances both the global and the per-domain counter. ``net_slow`` reads N
as milliseconds and is continuous; ``net_torn`` reads N as a BYTE offset
and fires one-shot on the first matching op. ``net_reset`` and
``net_refused`` are the *transient* class: ``netio.request`` retries them
with bounded backoff+jitter (idempotent domains only — a submit without an
idempotency key is never retried); ``net_hang`` surfaces as a deadline
timeout and ``net_torn`` as a short-read integrity error, both feeding the
per-peer circuit breaker rather than the retry loop.

The silent-data-corruption kind (ISSUE 20) is the one fault nothing in the
loud matrices can see: the device op SUCCEEDS, but the bytes are wrong ——
no exception, no timeout, no event at injection time (detection is the
shadow audit's job, runtime/supervisor.py)::

    DACCORD_FAULT=sdc:3                   # 3rd fetched device result:
                                          # consensus rows silently perturbed
    DACCORD_FAULT=sdc:1@2                 # 1st result: only mesh member 2's
                                          # row slice lies
    DACCORD_FAULT=sdc:*@3                 # EVERY result: member 3 lies
                                          # continuously (the chaos-storm
                                          # grammar; '*' = never fired-out)

Counter domain: ``sdc`` counts successfully fetched primary results
(:meth:`FaultPlan.sdc_check`, consumed by the supervisor AFTER unpack,
BEFORE the shadow audit sees the dict). The ``@K`` suffix reuses the
``device_lost`` ``@device`` grammar: member K's contiguous row slice of the
fetched batch is the only part perturbed — and K joins the plan's
persistent liar set, so the supervisor's per-member attribution probe
(which re-solves the divergent window on every member) deterministically
re-corrupts K's copy. That persistence is the point: a real lying chip
lies to the probe too, and without it culprit attribution of a one-shot
lie would be impossible.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


class FaultInjected(Exception):
    """Base class of injected (recoverable) faults. Instances carry the
    spec's ``kind`` and the 1-based index ``n`` in that kind's own counter
    domain, so event logs match the ``DACCORD_FAULT`` grammar exactly."""

    kind = "fault"
    n = 0


class FaultHang(FaultInjected):
    """Injected hang: the supervisor treats it exactly like a watchdog
    deadline expiry (no real wall-clock is spent)."""


class FaultDispatchError(FaultInjected):
    """Injected transient dispatch failure (retry succeeds)."""


class FaultDeviceLost(FaultInjected):
    """Injected terminal device loss (probe reports dead afterwards)."""


class FaultCompileStall(FaultInjected):
    """Injected first-compile stall (exercises the COMPILING/heartbeat
    path; the op then proceeds normally)."""


class FaultDeviceOOM(FaultInjected):
    """Injected capacity fault (allocator OOM / XLA RESOURCE_EXHAUSTED).

    Deterministic — the message carries the RESOURCE_EXHAUSTED marker so the
    supervisor's classifier treats it exactly like a real XLA capacity
    abort: no transient retry ladder, straight to the governor's
    degradation ladder."""


class InjectedCrash(BaseException):
    """Test-only hard crash: BaseException so no supervisor/pipeline
    ``except Exception`` can swallow it — it must unwind like a kill."""


_KINDS = ("fetch_hang", "dispatch_error", "device_lost", "compile_stall",
          "crash", "las_bitflip", "las_truncate", "db_garbage",
          "worker_crash", "worker_hang", "lease_stall",
          "device_oom", "host_rss", "monster_pile", "worker_oom",
          "feeder_stall", "serve_crash", "serve_hang",
          "io_enospc", "io_eio", "io_fsync_fail", "io_short_write",
          "io_slow",
          "net_refused", "net_reset", "net_hang", "net_torn", "net_slow",
          "sdc")

#: storage kinds (ISSUE 17): consumed by the utils/aio.py fault hook at
#: every durable-I/O primitive, optionally scoped to one path class with
#: ``@domain``. ``io_slow`` reads N as milliseconds (duration grammar).
IO_KINDS = ("io_enospc", "io_eio", "io_fsync_fail", "io_short_write",
            "io_slow")

#: path classes a storage spec may scope to — the durable surfaces of the
#: multi-process tier: the serve job journal, shared-FS leases, shard/job
#: manifests, tenant spool uploads, telemetry sidecars, the AOT cache dir.
IO_DOMAINS = ("journal", "lease", "manifest", "spool", "sidecar", "aot")

#: network kinds (ISSUE 18): consumed by the serve/netio.py choke point at
#: every router/autoscaler/client HTTP attempt, optionally scoped to one
#: RPC class with ``@domain``. ``net_slow`` reads N as milliseconds and
#: ``net_torn`` reads N as a body byte offset (see the module doc).
NET_KINDS = ("net_refused", "net_reset", "net_hang", "net_torn", "net_slow")

#: RPC classes a net spec may scope to — the router → peer call surfaces:
#: healthz polls, job submits, result fetches, streamed result proxies,
#: abort/shutdown-drain calls.
NET_DOMAINS = ("healthz", "submit", "result", "stream", "abort")

#: fleet-orchestrator kinds: they sabotage worker spawns / lease renewal at
#: the fleet layer (parallel/fleet.py) and are stripped from the worker
#: subprocesses' environment — a worker must never fail to parse the spec
#: that describes how its own orchestrator is being tested.
FLEET_KINDS = ("worker_crash", "worker_hang", "lease_stall", "worker_oom")

#: data-corruption kinds: they corrupt the INPUT ARTIFACTS (deterministically,
#: keyed by record index N) instead of raising at a device op, exercising the
#: ingest integrity layer (formats/ingest.py) the way the device kinds
#: exercise the supervisor. Applied once per plan by apply_data_faults(),
#: which the pipeline entry points call before opening the artifacts.
DATA_KINDS = ("las_bitflip", "las_truncate", "db_garbage")


@dataclass
class FaultSpec:
    kind: str
    at: int = 1        # 1-based index in the kind's counter domain
    fired: bool = False
    device: int = -1   # mesh-member index a device_lost names (-1 = unknown)
    domain: str = ""   # path class an io_* spec scopes to ("" = any domain)


@dataclass
class FaultPlan:
    specs: list = field(default_factory=list)
    device_dead: bool = False
    # mesh-member index of the last fired device_lost (-1 = not attributed);
    # the supervisor's partial-mesh rung reads it to name the culprit chip
    dead_device: int = -1
    # virtual HBM ceiling left by a fired device_oom spec: every later
    # primary op wider than this raises (None = no ceiling). Not one-shot by
    # design — the doomed shape must keep failing until it is bisected small
    # enough, which is exactly the real allocator's behavior.
    oom_max_width: int | None = None
    # logical-operation counters (advance once per op, not per retry)
    n_dispatch: int = 0
    n_fetch: int = 0
    n_device: int = 0
    n_compile: int = 0
    # fleet counters (advance once per worker spawn / successful lease claim)
    n_spawn: int = 0
    n_claim: int = 0
    # capacity counters (advance once per watermark check / inspected pile)
    n_rss: int = 0
    n_pile: int = 0
    # serve counters (advance once per fsync'd journal append / job run)
    n_journal: int = 0
    n_jobrun: int = 0
    # storage counters (advance once per aio primitive invocation): the
    # process-wide op count plus one counter per path-class domain, so an
    # ``@domain`` spec indexes only its own class's traffic
    n_io: int = 0
    n_io_domain: dict = field(default_factory=dict)
    # network counters (advance once per HTTP attempt through serve/netio):
    # process-wide plus one counter per RPC-class domain, mirroring storage
    n_net: int = 0
    n_net_domain: dict = field(default_factory=dict)
    # silent-corruption counter (advances once per successfully fetched
    # primary result) and the persistent liar set: mesh members a fired
    # ``sdc@K`` spec named. A liar keeps lying to attribution probes — the
    # deterministic stand-in for a chip whose bad lane corrupts everything
    # it computes, which is what makes per-member culprit attribution sound
    n_result: int = 0
    liar_devices: set = field(default_factory=set)

    @classmethod
    def parse(cls, text: str) -> "FaultPlan":
        specs = []
        for part in text.split(","):
            part = part.strip()
            if not part:
                continue
            kind, _, at = part.partition(":")
            if kind not in _KINDS:
                raise ValueError(
                    f"DACCORD_FAULT: unknown kind {kind!r} (known: "
                    f"{', '.join(_KINDS)})")
            at, _, dev = at.partition("@")
            d, dom = -1, ""
            if dev:
                if kind in ("device_lost", "sdc"):
                    try:
                        d = int(dev)
                    except ValueError:
                        raise ValueError(
                            f"DACCORD_FAULT: bad device in {part!r}")
                elif kind in IO_KINDS:
                    if dev not in IO_DOMAINS:
                        raise ValueError(
                            f"DACCORD_FAULT: unknown io domain {dev!r} "
                            f"(known: {', '.join(IO_DOMAINS)})")
                    dom = dev
                elif kind in NET_KINDS:
                    if dev not in NET_DOMAINS:
                        raise ValueError(
                            f"DACCORD_FAULT: unknown net domain {dev!r} "
                            f"(known: {', '.join(NET_DOMAINS)})")
                    dom = dev
                else:
                    raise ValueError(
                        f"DACCORD_FAULT: @suffix only applies to device_lost "
                        f"and sdc (@device), io_* and net_* kinds (@domain) "
                        f"(got {part!r})")
            if kind == "sdc" and at == "*":
                # continuous storm: '*' = EVERY fetched result is perturbed
                # (never fired-out, like the duration kinds); at=0 encodes it
                n = 0
            else:
                try:
                    n = int(at) if at else 1
                except ValueError:
                    raise ValueError(f"DACCORD_FAULT: bad count in {part!r}")
                if n < 1:
                    raise ValueError(
                        f"DACCORD_FAULT: count must be >= 1 in {part!r}")
            specs.append(FaultSpec(kind, n, device=d, domain=dom))
        return cls(specs=specs)

    @classmethod
    def from_env(cls, env=None) -> "FaultPlan | None":
        """The process-wide plan, or None when ``DACCORD_FAULT`` is unset.
        Read at supervisor construction (once per shard), so a test can set
        the env var per run."""
        text = (env if env is not None else os.environ).get("DACCORD_FAULT")
        return cls.parse(text) if text else None

    def _take(self, kind: str, count: int) -> FaultSpec | None:
        for s in self.specs:
            if s.kind == kind and not s.fired and count >= s.at:
                s.fired = True
                return s
        return None

    def op(self, domain: str, compiling: bool = False,
           degraded: bool = False, width: int | None = None) -> None:
        """Advance counters for one logical ``dispatch``/``fetch`` op and
        raise the matching injected fault, if any. ``degraded`` ops (already
        failed over; no device involved) only ever raise ``crash`` — the
        device-fault kinds describe the primary engine. ``width`` is the
        op's batch width (rows), consulted by the ``device_oom`` virtual
        HBM ceiling."""
        if domain == "dispatch":
            self.n_dispatch += 1
        elif domain == "fetch":
            self.n_fetch += 1
        else:
            raise ValueError(f"unknown op domain {domain!r}")
        self.n_device += 1
        if compiling:
            self.n_compile += 1
        def _raise(exc_cls, kind: str, n: int, msg: str):
            e = exc_cls(msg)
            e.kind, e.n = kind, n
            raise e

        if self._take("crash", self.n_device) is not None:
            raise InjectedCrash(f"injected crash at {domain} #{self.n_device}")
        if degraded:
            return
        if self.device_dead:
            # a lost device stays lost for every later primary op
            _raise(FaultDeviceLost, "device_lost", self.n_device,
                   f"device dead (injected) at {domain}")
        s = self._take("device_lost", self.n_device)
        if s is not None:
            self.device_dead = True
            self.dead_device = s.device
            _raise(FaultDeviceLost, "device_lost", self.n_device,
                   f"injected device_lost at {domain} #{self.n_device}"
                   + (f" (device {s.device})" if s.device >= 0 else ""))
        if self._take("device_oom", self.n_device) is not None:
            # the triggering op sets the ceiling to half its own width, so
            # one bisect step deterministically fits; compose multiple
            # device_oom specs to force a deeper walk
            if width:
                self.oom_max_width = max(1, int(width) // 2)
            _raise(FaultDeviceOOM, "device_oom", self.n_device,
                   f"RESOURCE_EXHAUSTED: injected device_oom at {domain} "
                   f"#{self.n_device} (width {width})")
        if (self.oom_max_width is not None and width
                and int(width) > self.oom_max_width):
            _raise(FaultDeviceOOM, "device_oom", self.n_device,
                   f"RESOURCE_EXHAUSTED: width {width} exceeds injected "
                   f"capacity ceiling {self.oom_max_width} at {domain}")
        if domain == "fetch" and self._take("fetch_hang",
                                            self.n_fetch) is not None:
            _raise(FaultHang, "fetch_hang", self.n_fetch,
                   f"injected fetch_hang at fetch #{self.n_fetch}")
        if domain == "dispatch" and self._take(
                "dispatch_error", self.n_dispatch) is not None:
            _raise(FaultDispatchError, "dispatch_error", self.n_dispatch,
                   f"injected dispatch_error at dispatch #{self.n_dispatch}")
        if compiling and self._take("compile_stall",
                                    self.n_compile) is not None:
            _raise(FaultCompileStall, "compile_stall", self.n_compile,
                   f"injected compile_stall at cold-shape op "
                   f"#{self.n_compile}")

    def fleet_spawn(self) -> str | None:
        """Advance the fleet's worker-spawn counter and return the sabotage
        kind for this spawn (``worker_crash`` | ``worker_hang``), or None.
        One-shot like every device kind: a requeued attempt of the same
        shard is a NEW spawn, so it runs clean and the retry path is
        exercised, not an infinite crash loop."""
        self.n_spawn += 1
        for kind in ("worker_crash", "worker_hang", "worker_oom"):
            if self._take(kind, self.n_spawn) is not None:
                return kind
        return None

    def fleet_claim_stall(self) -> bool:
        """Advance the fleet's lease-claim counter; True when this claim's
        heartbeat renewal must stall (the host wedged right after claiming —
        the lease goes stale and any orchestrator may take the shard over)."""
        self.n_claim += 1
        return self._take("lease_stall", self.n_claim) is not None

    def host_rss_check(self) -> bool:
        """Advance the host-watermark counter (the pipeline checks once per
        pile block); True when this check must report hard memory pressure
        (``host_rss:N`` — exercises the backpressure flush without actually
        ballooning the test process)."""
        self.n_rss += 1
        return self._take("host_rss", self.n_rss) is not None

    def feeder_stall_ms(self) -> float:
        """Milliseconds of injected per-pile feeder delay (``feeder_stall:N``
        — N is a DURATION here, see the module doc), 0.0 when the spec is
        absent. Continuous, never marked fired: the profiler A/B needs the
        whole run slowed, and the pipeline books the sleep under the
        ``stall`` stage so the verdict attributes it honestly."""
        for s in self.specs:
            if s.kind == "feeder_stall":
                return float(s.at)
        return 0.0

    def serve_crash_check(self) -> bool:
        """Advance the serve journal-append counter (``serve/journal.py``
        calls this AFTER each append is fsync'd); True when the process must
        now die hard — the journal responds with an ``os._exit(137)``,
        simulating a SIGKILL landing between syscalls. The durable-first
        ordering is the point: every record the journal holds at death is a
        record replay will see, exactly the real-crash contract."""
        self.n_journal += 1
        return self._take("serve_crash", self.n_journal) is not None

    def serve_hang_check(self) -> bool:
        """Advance the serve job-run counter (``serve/jobs.run_job`` calls
        this as a job starts); True when this run must wedge forever — the
        stand-in for a group thread stuck in a solve, exercising the bounded
        drain deadline (jobs journal-marked INTERRUPTED, nonzero exit) and
        the peer takeover of a hung process's lease."""
        self.n_jobrun += 1
        return self._take("serve_hang", self.n_jobrun) is not None

    def io_check(self, domain: str = "") -> "FaultSpec | None":
        """Advance the storage-op counters for one logical aio primitive
        invocation in path class ``domain`` and return the fired ``io_*``
        spec (never ``io_slow`` — that is a duration, see
        :meth:`io_slow_ms`), or None. A domained spec matches only ops of
        its own class and indexes that class's private counter; an
        undomained spec indexes the process-wide op counter. One-shot like
        the device kinds — the retry wrapper's next attempt runs clean,
        which is exactly what makes ``io_eio`` a *transient* class."""
        self.n_io += 1
        cnt = self.n_io_domain.get(domain, 0) + 1
        self.n_io_domain[domain] = cnt
        for s in self.specs:
            if s.kind not in IO_KINDS or s.kind == "io_slow" or s.fired:
                continue
            if s.domain:
                if s.domain == domain and cnt >= s.at:
                    s.fired = True
                    return s
            elif self.n_io >= s.at:
                s.fired = True
                return s
        return None

    def io_slow_ms(self, domain: str = "") -> float:
        """Milliseconds of injected delay for ONE storage op in ``domain``
        (``io_slow:MS[@domain]`` — N is a DURATION, like ``feeder_stall``),
        0.0 when absent. Continuous, never fired-out: a degraded disk is
        slow for the whole run, and sustained slowness — not a one-shot
        blip — is what the saturation verdict and SLO burn must see."""
        for s in self.specs:
            if s.kind == "io_slow" and (not s.domain or s.domain == domain):
                return float(s.at)
        return 0.0

    def has_io_faults(self) -> bool:
        """True while any storage spec could still fire (or an ``io_slow``
        delay applies) — the aio hook's fast-path gate."""
        return any(s.kind in IO_KINDS and (s.kind == "io_slow" or not s.fired)
                   for s in self.specs)

    def net_check(self, domain: str = "") -> "FaultSpec | None":
        """Advance the network-op counters for one HTTP *attempt* in RPC
        class ``domain`` and return the fired ``net_*`` spec (never
        ``net_slow`` — that is a duration, see :meth:`net_slow_ms`), or
        None. A domained spec matches only attempts of its own class and
        indexes that class's private counter; an undomained spec indexes
        the process-wide attempt counter. ``net_torn`` is special: its N is
        a BYTE offset, not an index, so it fires on the FIRST matching
        attempt and the caller reads ``spec.at`` as the truncation point.
        One-shot like the storage kinds — a retry's next attempt runs
        clean, which is what makes reset/refused the *transient* class."""
        self.n_net += 1
        cnt = self.n_net_domain.get(domain, 0) + 1
        self.n_net_domain[domain] = cnt
        for s in self.specs:
            if s.kind not in NET_KINDS or s.kind == "net_slow" or s.fired:
                continue
            if s.domain and s.domain != domain:
                continue
            if s.kind == "net_torn" or (cnt if s.domain
                                        else self.n_net) >= s.at:
                s.fired = True
                return s
        return None

    def net_slow_ms(self, domain: str = "") -> float:
        """Milliseconds of injected delay for ONE HTTP attempt in ``domain``
        (``net_slow:MS[@domain]`` — N is a DURATION, like ``io_slow``), 0.0
        when absent. Continuous, never fired-out: a grey-slow peer is slow
        for the whole run, and sustained slowness — not a one-shot blip —
        is what the hedged-read latency budget must see."""
        for s in self.specs:
            if s.kind == "net_slow" and (not s.domain or s.domain == domain):
                return float(s.at)
        return 0.0

    def has_net_faults(self) -> bool:
        """True while any network spec could still fire (or a ``net_slow``
        delay applies) — the netio hook's fast-path gate."""
        return any(s.kind in NET_KINDS
                   and (s.kind == "net_slow" or not s.fired)
                   for s in self.specs)

    def sdc_check(self) -> "FaultSpec | None":
        """Advance the fetched-result counter and return the ``sdc`` spec
        whose silent corruption applies to THIS result, or None. A ``sdc:N``
        spec is one-shot at result N; ``sdc:*`` (at=0) is continuous —
        every result perturbs, the chaos-storm grammar. A device-pinned
        spec adds its member to :attr:`liar_devices` so attribution probes
        (:meth:`sdc_liars`) re-corrupt that member's answers forever —
        silent by contract: no event, no exception, the supervisor's shadow
        audit is the only thing that can see it."""
        self.n_result += 1
        for s in self.specs:
            if s.kind != "sdc":
                continue
            if s.at == 0 or (not s.fired and self.n_result >= s.at):
                if s.at != 0:
                    s.fired = True
                if s.device >= 0:
                    self.liar_devices.add(s.device)
                return s
        return None

    def sdc_liars(self) -> set:
        """Original mesh-member indexes every fired (or continuous)
        device-pinned ``sdc`` spec named — the members whose attribution-
        probe answers must re-corrupt. Includes continuous specs' members
        even before their first main-stream hit."""
        liars = set(self.liar_devices)
        for s in self.specs:
            if s.kind == "sdc" and s.at == 0 and s.device >= 0:
                liars.add(s.device)
        return liars

    def has_sdc_faults(self) -> bool:
        """True while any ``sdc`` spec could still perturb a result (or a
        liar member exists) — the supervisor's fast-path gate."""
        return bool(self.liar_devices) or any(
            s.kind == "sdc" and (s.at == 0 or not s.fired)
            for s in self.specs)

    def monster_check(self) -> bool:
        """Advance the inspected-pile counter (the monster guard runs once
        per pile, BEFORE the quadratic windowing spend); True when this pile
        must bust the budget (``monster_pile:N``)."""
        self.n_pile += 1
        return self._take("monster_pile", self.n_pile) is not None

    def probe_override(self) -> bool | None:
        """False once device_lost fired (probe must agree the chip is dead);
        None = no opinion, run the real probe."""
        return False if self.device_dead else None

    def has_data_faults(self) -> bool:
        return any(s.kind in DATA_KINDS and not s.fired for s in self.specs)

    def apply_data_faults(self, las_path: str | None = None,
                          db_path: str | None = None) -> list[dict]:
        """Apply every unfired data-corruption spec to the given artifacts
        (one-shot, like the device kinds). Returns one descriptor dict per
        applied corruption, for ``ingest.fault`` event logging."""
        fired: list[dict] = []
        for s in self.specs:
            if s.fired or s.kind not in DATA_KINDS:
                continue
            if s.kind == "las_bitflip" and las_path is not None:
                fired.append(corrupt_las_bitflip(las_path, s.at))
            elif s.kind == "las_truncate" and las_path is not None:
                fired.append(corrupt_las_truncate(las_path, s.at))
            elif s.kind == "db_garbage" and db_path is not None:
                fired.append(corrupt_db_garbage(db_path, s.at))
            else:
                continue
            s.fired = True
        return fired


def maybe_apply_data_faults(las_path: str | None = None,
                            db_path: str | None = None,
                            env=None) -> list[dict]:
    """Entry-point hook: parse ``DACCORD_FAULT`` and apply any data-corruption
    kinds to the run's input artifacts BEFORE they are opened. Device kinds in
    the same spec are untouched (the supervisor reads its own plan). Each
    entry invocation re-parses the env, so a resumed run must clear the var
    (tests do) or the corruption re-applies."""
    plan = FaultPlan.from_env(env)
    if plan is None or not plan.has_data_faults():
        return []
    return plan.apply_data_faults(las_path=las_path, db_path=db_path)


def non_fleet_spec(text: str | None) -> str:
    """``text`` with every fleet kind removed — the ``DACCORD_FAULT`` value a
    fleet orchestrator forwards to its worker subprocesses (device and data
    kinds pass through; the fleet kinds describe the orchestrator itself)."""
    if not text:
        return ""
    return ",".join(p.strip() for p in text.split(",") if p.strip()
                    and p.strip().partition(":")[0] not in FLEET_KINDS)


# ---------------------------------------------------------------------------
# Deterministic artifact corruption (the data-plane twin of the device kinds;
# also callable directly by tests and the tools_pounce.sh corruption-fuzz
# smoke step). All helpers speak aio URLs (mem: fixtures corrupt too).
# ---------------------------------------------------------------------------

#: byte offset of each fixed-header field inside a 40-byte LAS record
LAS_FIELD_OFF = {"tlen": 0, "diffs": 4, "abpos": 8, "bbpos": 12, "aepos": 16,
                 "bepos": 20, "flags": 24, "aread": 28, "bread": 32}


def _read_all(path: str) -> bytes:
    from ..utils import aio

    with aio.open_input(path, "rb") as fh:
        return fh.read()


def _write_all(path: str, data: bytes) -> None:
    from ..utils import aio

    with aio.open_output(path, "wb") as fh:
        fh.write(data)


def _las_record_offsets(data: bytes) -> list[int]:
    """Byte offsets of every record in a CLEAN LAS image (corruption helpers
    run on intact fixtures; a malformed tlen aborts the walk)."""
    import struct as _struct

    import numpy as np

    from ..formats.las import _HDR_FMT, _HDR_SIZE, _REC_SIZE, _trace_dtype

    _novl, tspace = _struct.unpack(_HDR_FMT, data[:_HDR_SIZE])
    tsize = np.dtype(_trace_dtype(tspace)).itemsize
    offs: list[int] = []
    pos = _HDR_SIZE
    while pos + _REC_SIZE <= len(data):
        tlen = _struct.unpack_from("<i", data, pos)[0]
        if tlen < 0:
            break
        offs.append(pos)
        pos += _REC_SIZE + tlen * tsize
    return offs


def corrupt_las_bitflip(path: str, record: int, field: str = "abpos",
                        bit: int = 31) -> dict:
    """Flip one bit in record ``record`` (1-based, clamped). The default —
    the MSB of ``abpos`` — leaves framing intact but blows the coordinate out
    of read bounds; ``field='tlen'`` corrupts the framing field instead
    (absurd trace length), ``field='bread'`` fabricates a read id."""
    data = bytearray(_read_all(path))
    offs = _las_record_offsets(bytes(data))
    if not offs:
        raise ValueError(f"{path}: no records to corrupt")
    if record < 1:
        raise ValueError(f"record index is 1-based, got {record}")
    off = offs[min(record, len(offs)) - 1] + LAS_FIELD_OFF[field]
    data[off + bit // 8] ^= 1 << (bit % 8)
    _write_all(path, bytes(data))
    from ..formats.las import invalidate_index

    invalidate_index(path)  # writer-path sidecar rule: stale offsets must die
    return {"kind": "las_bitflip", "path": path, "record": record,
            "field": field, "bit": bit, "offset": off}


def corrupt_las_truncate(path: str, record: int) -> dict:
    """Cut the file mid-record ``record`` (1-based, clamped): everything from
    that record's 18th header byte on is gone — the torn-write / torn-copy
    failure mode."""
    data = _read_all(path)
    offs = _las_record_offsets(data)
    if not offs:
        raise ValueError(f"{path}: no records to truncate at")
    if record < 1:
        raise ValueError(f"record index is 1-based, got {record}")
    cut = offs[min(record, len(offs)) - 1] + 17
    _write_all(path, data[:cut])
    from ..formats.las import invalidate_index

    invalidate_index(path)  # writer-path sidecar rule: stale offsets must die
    return {"kind": "las_truncate", "path": path, "record": record,
            "offset": cut}


def corrupt_db_garbage(db_path: str, record: int) -> dict:
    """Overwrite read record ``record`` (1-based, clamped) of the DB's .idx
    with 0xFF garbage — rlen/boff become absurd, exercising the validated DB
    decode (``read_db`` strict raise vs ``bad_reads`` quarantine marking)."""
    import os as _os

    from ..formats.dazzdb import _HDR_SIZE, _READ_SIZE, _db_stems

    d, stem = _db_stems(db_path)
    idx = _os.path.join(d, f".{stem}.idx")
    data = bytearray(_read_all(idx))
    n = (len(data) - _HDR_SIZE) // _READ_SIZE
    if n <= 0:
        raise ValueError(f"{idx}: no read records to corrupt")
    if record < 1:
        raise ValueError(f"record index is 1-based, got {record}")
    off = _HDR_SIZE + _READ_SIZE * (min(record, n) - 1)
    data[off : off + _READ_SIZE] = b"\xff" * _READ_SIZE
    _write_all(idx, bytes(data))
    return {"kind": "db_garbage", "path": idx, "record": record, "offset": off}
