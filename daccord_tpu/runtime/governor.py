"""Capacity governor: bounded, byte-identical degradation under memory
exhaustion (ISSUE 5).

At north-star scale (B=2048 x D=32 batches, M=256 quadratic rescue DP,
fleets on shared hosts) capacity faults are the *expected* failure, not the
exotic one — yet before this module a deterministic HBM OOM was classified
like a transient dispatch error: the supervisor burned its whole backoff
ladder re-dispatching the identical doomed shape, then failed over to the
CPU engine *permanently*, losing the chip for the rest of the shard. The
governor turns that into a walk down a degradation ladder whose every rung
is byte-identical by per-window independence (the same argument as the
two-stream split ladder — re-batching a window cannot change its bytes):

    capacity-classified op (XLA RESOURCE_EXHAUSTED / allocator OOM)
      └▶ BISECT    the retained WindowBatch re-dispatches as width-W chunks,
                   W walking B → B/2 → … → min_width (shape-keyed, so the
                   shrunken shapes reuse/record compile fingerprints)
           └▶ CLAMP    the esc-cap-clamped ladder program (rescue lanes at
                       ``esc_clamp`` slots instead of full width — the M=256
                       quadratic DP dominates HBM) + host-routed completion
                       of any overflowed rows (split-ladder semantics)
                └▶ NATIVE FAILOVER    demoted to last resort (the supervisor
                                      engages it only when the ladder is
                                      exhausted)

The working rung is **ratcheted** per shape fingerprint — recorded next to
the compile-fingerprint registry — so later batches of that shape dispatch
at the known-good width directly: zero full-width re-dispatches of a shape
already classified as capacity-faulted. An opt-in probation re-probe
(``probation=N``) restores full width after N clean reduced dispatches
(mirrors the supervisor's failback).

The module also hosts the two host-side capacity guards the pipeline wires
in: the RSS watermark (:func:`check_host_pressure` — backpressure that
force-flushes rescue pools + partial buckets before the OS OOM-killer gets
a vote) and the monster-pile guard (:func:`CapacityGovernor` is not
involved; the pipeline budgets pile overlap counts BEFORE the quadratic
windowing/realignment spend and routes busted piles through the PR-2
quarantine machinery).

Deterministic on CPU via ``DACCORD_FAULT=device_oom:N|host_rss:N|
monster_pile:N`` (``runtime/faults.py``); every decision emits a
``governor.*`` event (schema: ``tools/eventcheck.py``).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass

import numpy as np

from .faults import FaultDeviceOOM


class CapacityError(RuntimeError):
    """A device op failed for lack of memory. Deterministic for a given
    shape — re-dispatching the identical batch would fail identically — so
    the supervisor must NOT spend its transient retry ladder on it; the
    governor's degradation ladder is the remedy."""

    def __init__(self, msg: str, width: int = 0):
        super().__init__(msg)
        self.width = width


#: substrings that classify an exception as a capacity fault. XLA surfaces
#: HBM exhaustion as ``RESOURCE_EXHAUSTED: Out of memory while trying to
#: allocate ...``; host allocators raise MemoryError or "failed to
#: allocate" strings. Deliberately conservative — a misclassified transient
#: would skip the retry ladder, which only costs a shrink; a misclassified
#: capacity fault would burn the ladder on a doomed shape.
_CAPACITY_MARKERS = ("RESOURCE_EXHAUSTED", "RESOURCE EXHAUSTED",
                     "OUT_OF_MEMORY", "Out of memory", "out of memory",
                     "Failed to allocate", "failed to allocate",
                     "Attempting to allocate")


def is_capacity_error(exc: BaseException) -> bool:
    """True when ``exc`` is a memory-exhaustion fault (injected or real)."""
    if isinstance(exc, (CapacityError, FaultDeviceOOM, MemoryError)):
        return True
    return any(m in f"{exc}" for m in _CAPACITY_MARKERS)


from ..utils.obs import env_float as _env_num


@dataclass
class GovernorConfig:
    min_width: int = 8        # bisect floor: below this the clamp rung (or
                              # native failover) takes over — a width-1
                              # batch that still OOMs is not a batching
                              # problem
    esc_clamp: int = 256      # rescue-lane slots of the clamped ladder
                              # program (the B/8-at-B=2048 experiment row);
                              # also the effective width the clamp reports
                              # to the fault plan — the M=256 quadratic DP
                              # over the rescue lanes dominates the
                              # program's HBM, not the B tier-0 rows
    probation: int = 0        # 0 = ratchets are sticky for the run; N>0 =
                              # after N clean reduced solves of a shape,
                              # re-probe full width once (restore on
                              # success — mirrors supervisor failback)
    rss_soft_mb: float = 0.0  # host RSS watermarks (0 = off): soft force-
    rss_hard_mb: float = 0.0  # flushes pools/partial buckets, hard also
                              # drains every in-flight batch
    persist: bool = True      # record ratchets in the compile-cache
                              # registry dir so later runs on this host
                              # dispatch at the known-good width directly

    @classmethod
    def from_env(cls, **overrides) -> "GovernorConfig":
        cfg = cls(
            min_width=int(_env_num("DACCORD_GOV_MIN_WIDTH", 8)),
            esc_clamp=int(_env_num("DACCORD_GOV_ESC_CLAMP", 256)),
            probation=int(_env_num("DACCORD_GOV_PROBATION", 0)),
            rss_soft_mb=_env_num("DACCORD_GOV_RSS_SOFT_MB", 0.0),
            rss_hard_mb=_env_num("DACCORD_GOV_RSS_HARD_MB", 0.0),
        )
        for k, v in overrides.items():
            setattr(cfg, k, v)
        return cfg


# ---------------------------------------------------------------------------
# ratchet persistence (beside the compile-fingerprint registry: both answer
# "what do we already know about this shape on this host?")
# ---------------------------------------------------------------------------

def _ratchet_path() -> str | None:
    from ..utils.obs import compcache_dir

    d = compcache_dir()
    return os.path.join(d, "daccord_capacity.json") if d else None


def load_ratchets() -> dict:
    """Raw registry entries. A NEGATIVE width marks a shape whose working
    rung is the clamped program (the bisect floor still OOMed): the next
    run must re-engage the clamp directly, not re-dispatch the unclamped
    program at a width known to OOM."""
    p = _ratchet_path()
    if p is None or not os.path.exists(p):
        return {}
    try:
        with open(p) as fh:
            d = json.load(fh)
        return {str(k): int(v) for k, v in d.items()} if isinstance(d, dict) else {}
    except (OSError, json.JSONDecodeError, ValueError, TypeError):
        return {}


def _with_ratchets(mutate) -> None:
    """Cross-process-safe read-modify-write of the ratchet registry: fleet
    workers on one host share the compcache dir, and an unlocked load/store
    pair would drop each other's entries (the lost shape re-dispatches full
    width next run and must re-OOM to reclassify). flock on a sidecar
    lockfile; best-effort throughout — same doctrine as record_fingerprint,
    a read-only cache dir must never sink a run."""
    p = _ratchet_path()
    if p is None:
        return
    try:
        import fcntl

        os.makedirs(os.path.dirname(p), exist_ok=True)
        with open(p + ".lock", "w") as lk:
            fcntl.flock(lk, fcntl.LOCK_EX)
            r = load_ratchets()
            if mutate(r) is False:
                return
            tmp = f"{p}.tmp.{os.getpid()}"
            with open(tmp, "wt") as fh:
                json.dump(r, fh)
            os.replace(tmp, p)
    except OSError:
        pass


def record_ratchet(key: str, width: int) -> None:
    def _set(r: dict):
        if r.get(key) == width:
            return False
        r[key] = int(width)

    _with_ratchets(_set)


def clear_ratchet(key: str) -> None:
    def _del(r: dict):
        if key not in r:
            return False
        del r[key]

    _with_ratchets(_del)


# ---------------------------------------------------------------------------
# result merging: the one reason the bisect is byte-identical — every
# window solves independently, so concatenating chunk results in row order
# reconstructs the full-width result exactly
# ---------------------------------------------------------------------------

def merge_results(parts: list) -> dict:
    """Merge ``(live_rows, result_dict)`` chunks back into one full-width
    result. Array fields concatenate (each chunk trimmed to its live rows —
    governor pad rows are discarded); numeric scalars (``esc_overflow``)
    sum; anything else takes the first chunk's value."""
    if len(parts) == 1:
        n, out = parts[0]
        first = next((np.asarray(v) for v in out.values()
                      if isinstance(v, np.ndarray) and np.asarray(v).ndim >= 1),
                     None)
        if first is None or len(first) == n:
            return out
    merged: dict = {}
    for k, v0 in parts[0][1].items():
        try:
            a0 = np.asarray(v0)
        except Exception:
            merged[k] = v0
            continue
        if a0.ndim >= 1 and a0.shape[0] >= parts[0][0]:
            arrs = [np.asarray(o[k])[:n] for n, o in parts]
            if any(a.shape[1:] != arrs[0].shape[1:] for a in arrs):
                # engines may size trailing dims per batch (the native
                # ladder sizes cons to the batch's longest consensus): pad
                # to the widest — padded cells sit past cons_len/lens and
                # are never read
                tgt = tuple(max(a.shape[d] for a in arrs)
                            for d in range(1, arrs[0].ndim))
                arrs = [np.pad(a, [(0, 0)] + [(0, t - s) for t, s
                                              in zip(tgt, a.shape[1:])])
                        for a in arrs]
            merged[k] = np.concatenate(arrs, axis=0)
        elif a0.ndim == 0 and a0.dtype.kind in "iuf":
            merged[k] = int(sum(int(np.asarray(o[k])) for _, o in parts)) \
                if a0.dtype.kind in "iu" else \
                float(sum(float(np.asarray(o[k])) for _, o in parts))
        else:
            merged[k] = v0
    return merged




class CapacityGovernor:
    """Walks the degradation ladder for one supervisor.

    ``solve_width_fn(batch)`` runs one guarded dispatch+fetch of ``batch``
    at its own width (the supervisor provides it, so shrunk shapes get real
    compile classification, retries, and fault injection) and raises
    :class:`CapacityError` when that width does not fit. ``clamp_solve_fn``
    (optional) solves a batch on the esc-cap-clamped program — the rung
    between the bisect floor and native failover.
    """

    def __init__(self, solve_width_fn, *, log=None,
                 cfg: GovernorConfig | None = None, clamp_solve_fn=None,
                 tracer=None, quantum_fn=None):
        from ..utils.obs import NullLogger, Tracer

        self._solve = solve_width_fn
        self._clamp = clamp_solve_fn
        # mesh-aware bisect (parallel/mesh.py): ``quantum_fn() -> N`` makes
        # every rung width a multiple of the mesh width and scales the floor
        # per device (min_width rows PER DEVICE, not per batch) — the OOM is
        # a per-device-slice property, and a non-multiple width would just
        # pad back up to one inside the solver. Callable because the
        # partial-mesh rung changes N mid-run.
        self._quantum_fn = quantum_fn
        self.cfg = cfg or GovernorConfig.from_env()
        self.log = log if log is not None else NullLogger()
        # governor-rung trace spans (ISSUE 6): each ladder-rung chunk solve
        # is bracketed so daccord-trace can attribute the degraded wall
        self.tracer = tracer if tracer is not None else Tracer(None)
        self.ratchet: dict[str, int] = {}
        self._loaded = False
        self._touched: set[str] = set()       # keys ratcheted/applied THIS run
        self._clamped: set[str] = set()       # keys whose working rung is the clamp
        self._since_probe: dict[str, int] = {}
        self.counters = {"classify": 0, "shrink": 0, "clamp": 0,
                         "ratchet": 0, "restore": 0, "chunks": 0}

    # -- ratchet state ----------------------------------------------------

    def _ensure_loaded(self) -> None:
        if not self._loaded:
            self._loaded = True
            if self.cfg.persist:
                for k, w in load_ratchets().items():
                    if k in self.ratchet:
                        continue
                    # negative width = the clamp is this shape's working
                    # rung (load_ratchets docstring). Without a clamp
                    # program wired in, fall back to treating it as a
                    # plain width ratchet at the bisect floor.
                    if w < 0 and self._clamp is not None:
                        self._clamped.add(k)
                    self.ratchet[k] = abs(w)

    def planned_width(self, key: str, width: int) -> int | None:
        """The ratcheted dispatch width for ``key``, or None when the full
        ``width`` is (as far as we know) safe. A clamp-rung shape plans even
        at full width — its working program is the clamped one."""
        self._ensure_loaded()
        r = self.ratchet.get(key)
        if r is None:
            return None
        if key in self._clamped:
            return min(r, width)
        return r if r < width else None

    def active_state(self) -> dict:
        """Ratchet entries applied or recorded during THIS run — what shard
        manifests carry as the degradation state."""
        return {k: self.ratchet[k] for k in sorted(self._touched)
                if k in self.ratchet}

    def _note_ratchet(self, key: str, width: int, clamped: bool = False) -> None:
        was = (self.ratchet.get(key), key in self._clamped)
        if clamped:
            self._clamped.add(key)
        self._touched.add(key)
        if was == (width, clamped):
            return
        self.ratchet[key] = width
        self.counters["ratchet"] += 1
        self.log.log("governor.ratchet", key=key, width=int(width),
                     clamped=clamped)
        if self.cfg.persist:
            record_ratchet(key, -width if clamped else width)

    def _note_restore(self, key: str, width: int, ok: bool) -> None:
        self.counters["restore"] += 1
        self.log.log("governor.restore", key=key, width=int(width), ok=ok)
        if ok:
            self.ratchet.pop(key, None)
            self._clamped.discard(key)
            self._since_probe.pop(key, None)
            self._touched.add(key)
            if self.cfg.persist:
                clear_ratchet(key)

    # -- the ladder -------------------------------------------------------

    def solve(self, batch, key: str, reason: str | None = None) -> dict:
        """Solve ``batch`` down the degradation ladder; returns the merged
        full-width result. ``reason`` is the classified capacity error when
        the full-width op just failed (first rung is then B/2); None means
        a ratchet-planned reduced dispatch. Raises :class:`CapacityError`
        when the whole ladder is exhausted (caller demotes to native
        failover) and lets :class:`DeviceLostError` propagate (the chip
        died mid-walk — a different failure class)."""
        self._ensure_loaded()
        # Capacity bisect operates on the HOST batch: a staged mesh batch
        # (parallel/mesh.py StagedBatch) unwraps to its retained host-side
        # windows — the staged device buffers are width-committed and get
        # discarded here, then re-staged per rung by the dispatch path.
        batch = getattr(batch, "replay_batch", batch)
        B = int(batch.size)
        q = max(1, int(self._quantum_fn())) if self._quantum_fn else 1

        def _q_up(w: int) -> int:
            # round a proposed width up to a mesh multiple (never above B)
            return min(-(-w // q) * q, B)

        floor = max(1, min(self.cfg.min_width * q, B))
        clamped = key in self._clamped
        if reason is not None:
            self.counters["classify"] += 1
            self.log.log("governor.classify", key=key, width=B,
                         reason=str(reason)[:200])
            width = self.ratchet.get(key, B)
            proposed = _q_up(max(B // 2, floor))
            if proposed < B:
                width = min(width, proposed)
                if width < B:
                    self.counters["shrink"] += 1
                    # per_device = the capacity rung each mesh member now
                    # runs at (ISSUE 13: the OOM is a per-device-slice
                    # property, so the telemetry names the slice, not just
                    # the batch)
                    self.log.log("governor.shrink", key=key, width_from=B,
                                 width_to=int(width),
                                 **({"per_device": int(width) // q}
                                    if q > 1 else {}))
            elif clamped:
                # the clamp is already this shape's working rung: stay on it
                width = min(width, B)
            elif self._clamp is not None:
                # no bisect rung exists below the floor: straight to clamp
                clamped = True
                self.counters["clamp"] += 1
                self.log.log("governor.clamp", key=key, width=B,
                             esc_cap=int(self.cfg.esc_clamp))
                width = min(width, B)
            else:
                raise CapacityError(
                    f"degradation ladder exhausted for {key}: no bisect "
                    f"rung below floor {floor} and no clamp program",
                    width=B)
        else:
            width = min(self.ratchet.get(key, B), B)
            if (width < B and self.cfg.probation > 0
                    and self._since_probe.get(key, 0) >= self.cfg.probation):
                # opt-in probation re-probe: one full-width attempt; failure
                # re-ratchets (and resets the probation clock), success
                # restores full-width dispatching for this shape
                self._since_probe[key] = 0
                try:
                    out = self._solve(batch)
                except CapacityError:
                    self._note_restore(key, B, ok=False)
                else:
                    self._note_restore(key, B, ok=True)
                    return out
        from ..kernels.tensorize import pad_batch, slice_batch

        parts: list = []
        pos = 0
        while pos < B:
            take = min(width, B - pos)
            sub = slice_batch(batch, pos, pos + take)
            if sub.size < width:
                sub = pad_batch(sub, width)
            rung_sp = self.tracer.open("governor.rung", key=key,
                                       width=int(width), clamped=clamped)
            try:
                out = self._clamp(sub) if clamped else self._solve(sub)
            except CapacityError as e:
                self.tracer.close(rung_sp, status="capacity")
                if not clamped and width > floor:
                    new = _q_up(max(width // 2, floor))
                    self.counters["shrink"] += 1
                    self.log.log("governor.shrink", key=key,
                                 width_from=int(width), width_to=int(new),
                                 **({"per_device": int(new) // q}
                                    if q > 1 else {}))
                    width = new
                    continue
                if not clamped and self._clamp is not None:
                    clamped = True
                    self.counters["clamp"] += 1
                    self.log.log("governor.clamp", key=key, width=int(width),
                                 esc_cap=int(self.cfg.esc_clamp))
                    continue
                raise CapacityError(
                    f"degradation ladder exhausted for {key} at width "
                    f"{width}: {e}", width=width) from e
            except BaseException:
                # device loss (or anything else) mid-rung: close the span
                # here — the run continues after failover, so leaving it to
                # the end-of-run unwind would book the rest of the shard's
                # wall against this rung
                self.tracer.close(rung_sp, status="error")
                raise
            self.tracer.close(rung_sp)
            self.counters["chunks"] += 1
            parts.append((take, out))
            pos += take
        if width < B or clamped:
            self._note_ratchet(key, width, clamped=clamped)
            self._since_probe[key] = self._since_probe.get(key, 0) + 1
        return merge_results(parts)


# ---------------------------------------------------------------------------
# host watermarks (RSS backpressure) — pipeline-side capacity guard
# ---------------------------------------------------------------------------

def host_rss_mb() -> float:
    """Current resident set size in MB (Linux /proc; 0.0 when unreadable —
    the watermark then simply never engages, it must not sink a run)."""
    try:
        with open("/proc/self/statm") as fh:
            pages = int(fh.read().split()[1])
        return pages * (os.sysconf("SC_PAGE_SIZE") / (1024.0 * 1024.0))
    except (OSError, ValueError, IndexError):
        return 0.0


def check_host_pressure(faults, cfg: GovernorConfig) -> tuple[str | None, float, bool]:
    """One watermark check: ``(level, rss_mb, injected)`` with level in
    (None, 'soft', 'hard'). The injected ``host_rss`` fault (deterministic,
    counted per check) reports hard pressure regardless of real RSS."""
    if faults is not None and faults.host_rss_check():
        return "hard", host_rss_mb(), True
    if not (cfg.rss_soft_mb or cfg.rss_hard_mb):
        return None, 0.0, False
    rss = host_rss_mb()
    if cfg.rss_hard_mb and rss >= cfg.rss_hard_mb:
        return "hard", rss, False
    if cfg.rss_soft_mb and rss >= cfg.rss_soft_mb:
        return "soft", rss, False
    return None, rss, False
