"""End-to-end correction pipeline: LAS piles -> window batches -> device -> FASTA.

The reference's L5 orchestration (SimpleThreadPool work packages fanning reads
to handleWindow, ordered output — SURVEY.md §3.1) re-imagined as a host->device
pipeline: the host streams piles from the LAS byte range, refines trace points,
cuts windows, and accumulates them into fixed-size cross-read batches; the
device solves batches through the tier ladder; results scatter back to their
reads and each completed read is stitched and written in input order.

The profile pass (reference: error-profile estimation over sampled piles)
runs once up front on the first piles of the shard.
"""

from __future__ import annotations

import sys
import time
from dataclasses import dataclass, field

import numpy as np

from ..formats.dazzdb import DazzDB, read_db
from ..formats.fasta import FastaRecord, write_fasta
from ..formats.las import LasFile
from ..kernels.tensorize import BatchShape, pad_batch, tensorize_windows
from ..kernels.tiers import TierLadder, solve_tiered
from ..oracle.consensus import ConsensusConfig, estimate_profile_two_pass, stitch_results
from ..oracle.profile import ErrorProfile
from ..oracle.windows import WindowSegments, build_pile_windows, cut_windows, refine_overlap
from ..utils.bases import ints_to_seq


@dataclass
class PipelineConfig:
    consensus: ConsensusConfig = field(default_factory=ConsensusConfig)
    batch_size: int = 512
    depth: int = 32
    seg_len: int = 64
    profile_sample_piles: int = 4
    verbose: bool = False


@dataclass
class PipelineStats:
    n_reads: int = 0
    n_windows: int = 0
    n_solved: int = 0
    n_fragments: int = 0
    bases_in: int = 0
    bases_out: int = 0
    tier_histogram: dict = field(default_factory=dict)
    pad_waste: float = 0.0
    wall_s: float = 0.0
    device_s: float = 0.0

    def bases_per_sec(self) -> float:
        return self.bases_out / self.wall_s if self.wall_s > 0 else 0.0


class _PendingRead:
    __slots__ = ("aread", "a_bases", "n_windows", "results", "n_done")

    def __init__(self, aread: int, a_bases: np.ndarray, n_windows: int):
        self.aread = aread
        self.a_bases = a_bases
        self.n_windows = n_windows
        self.results: list = [None] * n_windows
        self.n_done = 0


def estimate_profile_for_shard(db: DazzDB, las: LasFile, cfg: PipelineConfig,
                               start: int | None = None, end: int | None = None) -> ErrorProfile:
    """Profile pass over the first piles of the shard."""
    refined_all = []
    windows_all: list[WindowSegments] = []
    for i, (aread, pile) in enumerate(las.iter_piles(start, end)):
        if i >= cfg.profile_sample_piles:
            break
        a_bases = db.read_bases(aread)
        refined = [refine_overlap(o, a_bases, db.read_bases(o.bread), las.tspace) for o in pile]
        refined_all.extend(refined)
        windows_all.extend(cut_windows(a_bases, refined, w=cfg.consensus.w, adv=cfg.consensus.adv))
    return estimate_profile_two_pass(refined_all, windows_all, cfg.consensus, sample=32)


def correct_shard(db: DazzDB, las: LasFile, cfg: PipelineConfig,
                  start: int | None = None, end: int | None = None,
                  profile: ErrorProfile | None = None,
                  solver=None):
    """Correct every pile in the byte range; yields (aread, [fragments]).

    ``solver`` maps a WindowBatch to the solve_tiered output dict; defaults to
    the local single-device ladder. The parallel backend passes a sharded one.
    """
    stats = PipelineStats()
    t_start = time.time()
    if profile is None:
        profile = estimate_profile_for_shard(db, las, cfg, start, end)
    ladder = TierLadder.from_config(profile, cfg.consensus)
    if solver is None:
        def solver(batch):
            return solve_tiered(batch, ladder)

    shape = BatchShape(depth=cfg.depth, seg_len=cfg.seg_len, wlen=cfg.consensus.w)
    queue: list[tuple[int, WindowSegments]] = []
    pending: dict[int, _PendingRead] = {}
    order: list[int] = []
    ready: dict[int, list[np.ndarray]] = {}
    emit_idx = 0
    pad_cells = pad_used = 0

    def flush_batch(final: bool):
        nonlocal queue, pad_cells, pad_used, emit_idx
        while queue and (len(queue) >= cfg.batch_size or final):
            chunk, queue = queue[: cfg.batch_size], queue[cfg.batch_size :]
            batch = pad_batch(tensorize_windows(chunk, shape), cfg.batch_size)
            t0 = time.time()
            out = solver(batch)
            stats.device_s += time.time() - t0
            pad_cells += batch.seqs.size
            pad_used += int(batch.lens.sum())
            for i, (rid, ws) in enumerate(chunk):
                pr = pending[rid]
                widx = (ws.wstart // cfg.consensus.adv)
                seq = (np.asarray(out["cons"][i][: out["cons_len"][i]], dtype=np.int8)
                       if out["solved"][i] else None)
                pr.results[widx] = (ws.wstart, ws.wlen, seq)
                pr.n_done += 1
                if out["solved"][i]:
                    stats.n_solved += 1
                    t = int(out["tier"][i])
                    stats.tier_histogram[t] = stats.tier_histogram.get(t, 0) + 1
                if pr.n_done == pr.n_windows:
                    rows = [r for r in pr.results if r is not None]
                    frags = stitch_results(pr.a_bases, rows, cfg.consensus)
                    ready[rid] = frags
                    del pending[rid]

    for aread, pile in las.iter_piles(start, end):
        a_bases = db.read_bases(aread)
        stats.bases_in += len(a_bases)
        refined = [refine_overlap(o, a_bases, db.read_bases(o.bread), las.tspace) for o in pile]
        windows = cut_windows(a_bases, refined, w=cfg.consensus.w, adv=cfg.consensus.adv)
        stats.n_reads += 1
        stats.n_windows += len(windows)
        pr = _PendingRead(aread, a_bases, len(windows))
        pending[aread] = pr
        order.append(aread)
        if not windows:
            ready[aread] = []
            del pending[aread]
        queue.extend((aread, ws) for ws in windows)
        flush_batch(final=False)
        # emit completed reads in order
        while emit_idx < len(order) and order[emit_idx] in ready:
            rid = order[emit_idx]
            frags = ready.pop(rid)
            stats.n_fragments += len(frags)
            stats.bases_out += sum(len(f) for f in frags)
            yield rid, frags, stats
            emit_idx += 1

    flush_batch(final=True)
    while emit_idx < len(order):
        rid = order[emit_idx]
        frags = ready.pop(rid, [])
        stats.n_fragments += len(frags)
        stats.bases_out += sum(len(f) for f in frags)
        yield rid, frags, stats
        emit_idx += 1
    stats.wall_s = time.time() - t_start


def correct_to_fasta(db_path: str, las_path: str, out_path, cfg: PipelineConfig | None = None,
                     start: int | None = None, end: int | None = None) -> PipelineStats:
    """Run the pipeline and write corrected fragments as FASTA (stdout with '-')."""
    cfg = cfg or PipelineConfig()
    db = read_db(db_path)
    las = LasFile(las_path)
    t0 = time.time()
    stats: PipelineStats | None = None
    recs = []
    for rid, frags, st in correct_shard(db, las, cfg, start, end):
        stats = st
        for fi, f in enumerate(frags):
            recs.append(FastaRecord(f"read{rid}/{fi}", ints_to_seq(f)))
    if out_path == "-":
        write_fasta(sys.stdout, recs)
    else:
        write_fasta(out_path, recs)
    if stats is None:
        stats = PipelineStats()
    stats.wall_s = time.time() - t0
    return stats
