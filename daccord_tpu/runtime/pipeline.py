"""End-to-end correction pipeline: LAS piles -> window batches -> device -> FASTA.

The reference's L5 orchestration (SimpleThreadPool work packages fanning reads
to handleWindow, ordered output — SURVEY.md §3.1) re-imagined as a host->device
pipeline: the host streams piles from the LAS byte range, refines trace points
and cuts windows (native C++ hot path when built, bit-identical Python
fallback), accumulates fixed-size cross-read window batches, the device solves
them through the tier ladder, and results scatter back to their reads; each
completed read is stitched and emitted in input order.

The profile pass (reference: error-profile estimation over sampled piles)
runs once up front on the first piles of the shard.
"""

from __future__ import annotations

import os
import sys
import time
from dataclasses import dataclass, field

import numpy as np

from ..formats.dazzdb import DazzDB, read_db
from ..formats.fasta import FastaRecord, write_fasta
from ..formats.las import LasFile
from ..kernels.tensorize import BatchShape, WindowBatch, pad_batch, tensorize_windows
from ..kernels.tiers import TierLadder, solve_ladder
from ..oracle.consensus import ConsensusConfig, stitch_results
from ..oracle.profile import ErrorProfile
from ..oracle.windows import WindowSegments, cut_windows, refine_overlap
from ..utils.bases import ints_to_seq


@dataclass
class PipelineConfig:
    consensus: ConsensusConfig = field(default_factory=ConsensusConfig)
    batch_size: int | None = None    # windows per device batch; None = auto:
                                 # 2048 on TPU (the tunneled chip pays a fixed
                                 # ~100 ms RTT per fetched batch, so wall-clock
                                 # ~= n_batches x RTT — bigger batches amortize
                                 # it, measured 2x in the B=1024->2048 sweep),
                                 # 512 elsewhere (CPU compile/compute cost
                                 # grows with the static batch shape)
    depth: int = 32
    seg_len: int = 64
    max_kmers: int = 64          # tier-0 compacted active-set size (top-M
                                 # k-mers per window); the cap binds on
                                 # 60-70% of windows at 24-30x depth
                                 # (topm_overflow stat) though truncations
                                 # are usually harmless — larger M trades
                                 # quadratic DP cost for fidelity
    rescue_max_kmers: int = 256  # active-set size of the min_count<=1
                                 # rescue tiers (they keep every k-mer, so
                                 # they need the headroom)
    overflow_rescue: bool = False  # re-solve top-M-capped windows at
                                 # rescue_max_kmers (reference full-graph
                                 # semantics for exactly the windows where
                                 # truncation binds; measured in the
                                 # BASELINE.md top-M table before choosing
                                 # the default)
    profile_sample_piles: int = 4
    profile_sample_offset: int = 0   # pile-index shift of the strided profile
                                 # sample; distinct offsets draw disjoint
                                 # samples (estimator-variance probe,
                                 # tools/profilevar.py)
    use_native: bool = True      # C++ host path when available
    native_solver: bool = False  # solve windows with the native C++ tier
                                 # ladder (dazz_native.cpp solve_windows)
                                 # instead of a device/JAX ladder. Same
                                 # top-M cap semantics as the device ladder
                                 # by default (max_kmers applies); -M 0
                                 # restores full-graph oracle semantics.
                                 # Measured 4-7x the JAX-CPU fallback per
                                 # core — the degraded-mode engine and the
                                 # reference-class CPU baseline in one
                                 # (tools/consensusbench.py)
    depth_rank: bool = True      # best-alignments-first before depth capping
    qv_track: str | None = "inqual"  # intrinsic-QV track consumed by the
                                 # consensus run (reference: daccord loads the
                                 # track computeintrinsicqv wrote, SURVEY.md
                                 # §3.1 "load track inqual"): B-read tile QVs
                                 # join the depth-ranking score so intrinsically
                                 # noisy B segments lose their depth slots.
                                 # Missing track = trace-diff ranking only
    skip_shallow: bool = True    # windows with fewer than min_depth segments
                                 # never solve (the kernel marks them unsolved,
                                 # window_kernel.py:389) — resolve them on host
                                 # without spending device batch slots
    max_inflight: int = 8        # device batches in flight. The deque fills
                                 # to this depth, then HALF is drained in one
                                 # grouped fetch: the tunnel charges ~100 ms
                                 # per fetch call (not per array), so the
                                 # per-batch fetch floor is RTT/(max_inflight/2)
    feeder_threads: int = 0      # host windowing threads (0 = synchronous);
                                 # the reference's -t fan-out re-imagined as a
                                 # feeder pool ahead of the device queue — the
                                 # native pile processor releases the GIL, so
                                 # piles window in parallel while the device
                                 # solves earlier batches
    native_threads: int = 0      # C++ solve_windows engine threads when
                                 # --backend native (0 = all host cores);
                                 # distinct from feeder_threads, which only
                                 # drives the host windowing pool
    depth_buckets: tuple = (8, 16)   # sub-depth buckets below `depth`; windows
                                 # route to the smallest bucket holding their
                                 # segment count, so shallow windows don't pay
                                 # the full-depth kernel cost (SURVEY.md §7.3
                                 # item 1 pad waste; () = single bucket)
    bucket_flush_reads: int = 128    # dispatch a partial bucket once its oldest
                                 # row has waited this many reads — bounds the
                                 # in-order emission lag (and therefore the
                                 # pending/ready memory) under bucket skew
    ladder_mode: str = "fused"   # "fused" = one jitted program per batch
                                 # (tier 0 + every rescue tier at esc_cap =
                                 # full batch width — the r1-r8 behavior);
                                 # "split" = the two-stream ladder: Stream A
                                 # dispatches tier0-only batches, rescue
                                 # candidates (tier-0 failures + top-M
                                 # overflow when --overflow-rescue) pool on
                                 # host and flush as DENSE full-ladder
                                 # Stream B batches — the M=256 quadratic
                                 # rescue DP then only ever runs over
                                 # saturated batches (ISSUE 4; byte-identical
                                 # to fused by per-window independence,
                                 # tests/test_split_ladder.py). Applies to
                                 # the JAX ladder paths only; the native
                                 # engine escalates per-window on host. The
                                 # mesh solver routes streams itself
                                 # (sharded tier0 + sharded full ladder), so
                                 # split and --mesh compose
    rescue_flush_reads: int = 128    # split mode: flush a partial rescue pool
                                 # once its oldest row has waited this many
                                 # reads (the bucket_flush_reads rule applied
                                 # to Stream B) — bounds the in-order
                                 # emission lag a pooled window can add
    seg_len_buckets: tuple = ()  # optional second-level routing by max segment
                                 # length (e.g. (48,)): windows whose segments
                                 # all fit go to a narrower batch — exact, like
                                 # depth buckets, but multiplies compile count;
                                 # off by default until measured on hardware.
                                 # Subsumed (with depth_buckets) by the paged
                                 # router's auto-derived shape families when
                                 # --paged is active
    mesh: int = 0                # shard window batches over the first N
                                 # local devices (parallel/mesh.py): the
                                 # full escalation ladder runs inside
                                 # shard_map, so one sharded batch costs one
                                 # dispatch + one fetch regardless of mesh
                                 # size. First-class: the sharded solver is
                                 # built in-pipeline from the run's own
                                 # TierLadder, carries real supervisor
                                 # identity (:m<N> compile keys, watchdog,
                                 # retries, partial-mesh degradation before
                                 # whole-program failover), per-device
                                 # governor capacity handling, and composes
                                 # with --paged and --ladder split. 0/1 =
                                 # single device; ignored (with a log line)
                                 # by the native engine and injected custom
                                 # solvers. Off-pod verification recipe:
                                 # JAX_PLATFORMS=cpu XLA_FLAGS=
                                 # --xla_force_host_platform_device_count=N
    paged: str = "off"           # ragged paged window batching
                                 # (kernels/paging.py, ISSUE 7): 'on' ships
                                 # batches as a page pool + page table bucketed
                                 # into corpus-derived (depth, pages) shape
                                 # families instead of dense [B, D, L]
                                 # rectangles — byte-identical output, the
                                 # dense tile is gathered device-side inside
                                 # the same jitted program; 'auto' enables it
                                 # on device (non-cpu) platforms only; 'off'
                                 # (default until the on-chip paged-vs-dense
                                 # decision row lands, BASELINE.md) keeps the
                                 # dense wire format. JAX ladder paths only —
                                 # the native engine iterates dense rows on
                                 # host. The mesh solver shards the page
                                 # table and replicates the pool, so paged
                                 # and --mesh compose
    page_len: int = 16           # paged page length in bases (must divide
                                 # seg_len); segments are page-aligned, so
                                 # rounding waste averages page_len/2 per
                                 # segment — 16 keeps it under ~20% of a
                                 # w=40 window segment
    paged_families: int = 4      # compile-count budget for the auto-derived
                                 # shape families (each family is one extra
                                 # jitted program per stream)
    hp_native: bool = True       # --backend native runs the hp rescue in
                                 # the C++ engine (hp_rescue_windows,
                                 # oracle/hp.py parity by test); False forces
                                 # the python host pass (the parity arm)
    use_pallas: bool = False     # route the heaviest-path DP through the
                                 # Pallas TPU kernel (pallas_dp); bit-identical
                                 # results (tests/test_pallas.py), TPU only —
                                 # ignored on the CPU solve_tiered path
    # (empirical-OL blending RETIRED in r4: measured <= analytic tables at
    # every sample size up to all piles — see OffsetLikely's docstring and
    # BASELINE.md r3/r4 for the record)
    end_trim: bool = True        # treat prefix/suffix runs of windows solved
                                 # only by a low-confidence rescue tier
                                 # (min_count<=1) as unsolved: read ends have
                                 # thin piles, and rescue-solved end windows
                                 # carry near-raw error rates (measured ~10x
                                 # the interior rate). Trimming them costs ~2%
                                 # of output bases and no extra fragments;
                                 # interior rescue windows keep the read
                                 # contiguous and are left alone
    log_path: str | None = None  # jsonl event log ('-' = stderr)
    ledger_path: str | None = None   # per-window outcome ledger jsonl
                                 # (ISSUE 6): one `window` row per window —
                                 # identity, length, depth, tier reached,
                                 # rescue membership, batch solve wall — the
                                 # training set the learned window router
                                 # (ROADMAP 5) needs. Buffered writer; None
                                 # = off (daccord-shard defaults it next to
                                 # the shard manifest)
    job_tag: str | None = None   # serving-plane job/tenant tag (ISSUE 10):
                                 # stamped on every dispatched batch
                                 # (WindowBatch.job) and every outcome-ledger
                                 # row, so the ROADMAP-5 router training set
                                 # segments per workload and a merged trace
                                 # attributes batches to jobs. None (batch
                                 # runs) leaves both exactly as before
    metrics_snapshot_s: float = 30.0  # cadence of periodic `metrics` events
                                 # (registry snapshot: windows/sec,
                                 # bases/sec, pad waste, rescue density,
                                 # RSS, device_peak_bytes); 0 disables —
                                 # the end-of-run rollup still lands
    supervise: bool = True       # wrap dispatch/fetch in the device
                                 # supervisor (runtime/supervisor.py):
                                 # watchdog deadlines with compiling-vs-wedged
                                 # classification, retry with backoff, and
                                 # mid-run failover to the degraded engine on
                                 # declared device loss. Off = the r5
                                 # behavior (a dead tunnel wedges the run)
    events_path: str | None = None   # supervisor/event jsonl (--events);
                                 # None = share log_path's logger
    failover_backend: str = "auto"   # degraded-mode engine on device loss:
                                 # 'native' (C++ ladder — the production
                                 # choice: oracle parity, and it cannot
                                 # depend on the dead backend), 'cpu' (the
                                 # same JAX ladder host-routed — exact bytes
                                 # vs a cpu-platform primary, but unusable
                                 # once a TPU backend wedged the process),
                                 # 'auto' = cpu on a cpu platform (exact
                                 # bytes), native on device platforms
                                 # (clear error if not built)
    failback: bool = False       # background re-probe may route dispatches
                                 # back to a revived chip (opt-in: failback
                                 # re-compiles every bucket shape)
    audit_rate: float | None = None  # sampled shadow verification (--audit-
                                 # rate): fraction of windows per fetched
                                 # batch re-solved on the trusted host ladder
                                 # and compared byte-for-byte (supervisor
                                 # ._audit, ISSUE 20). None = env
                                 # DACCORD_AUDIT_RATE (default 1/64); 0
                                 # disables. Changing the rate NEVER changes
                                 # output bytes — a detected divergence
                                 # re-solves the whole batch on the byte-
                                 # exact reference — only detection latency
    ingest_policy: str = "strict"    # validated LAS/DB decode policy
                                 # (formats/ingest.py): 'strict' aborts the
                                 # shard with a structured IngestError naming
                                 # byte offset + pile on the first integrity
                                 # violation; 'quarantine' contains each
                                 # corrupt overlap/pile — the pile is skipped,
                                 # its read emitted uncorrected, the event
                                 # recorded (sidecar + n_quarantined) — and
                                 # every unaffected pile corrects normally;
                                 # 'off' skips the validation scan (trusted
                                 # input, the pre-ISSUE-2 behavior)
    quarantine_path: str | None = None   # jsonl sidecar recording each
                                 # quarantined pile (kind, offset, detail;
                                 # created lazily, only when something
                                 # quarantines); launch.py and the CLI
                                 # default it next to the output
    max_pile_overlaps: int = 100_000     # monster-pile guard (ISSUE 5): a
                                 # pile holding more overlaps than this is
                                 # contained through the quarantine machinery
                                 # (read emitted uncorrected) BEFORE the
                                 # quadratic windowing/realignment spend can
                                 # OOM-kill the worker. Production piles run
                                 # ~2x coverage; only ultra-deep repeat piles
                                 # approach this. 0 disables the budget (the
                                 # injected monster_pile fault still fires)
    verbose: bool = False


@dataclass
class PipelineStats:
    n_reads: int = 0
    n_windows: int = 0
    n_solved: int = 0
    n_skipped_shallow: int = 0
    n_topm_overflow: int = 0     # windows whose surviving k-mer count exceeded
                                 # the kernel's top-M active set (the only
                                 # kernel-vs-oracle divergence source;
                                 # VERDICT r1 weak #4)
    qv_ranked: bool = False
    n_hp_rescued: int = 0        # windows replaced by the run-length-
                                 # compressed rescue (oracle/hp.py)
    hp_wall_s: float = 0.0       # host wall spent in the hp drain pass
                                 # (device paths only; the native engine
                                 # runs hp in-engine inside its solve call)
    n_end_trimmed: int = 0
    n_fragments: int = 0
    n_quarantined: int = 0       # piles contained by the quarantine policy
                                 # (their reads emitted uncorrected)
    n_ingest_issues: int = 0     # integrity violations the validating scan
                                 # found in this shard's byte range
    # two-stream ladder accounting (ISSUE 4). rescue_slots_executed counts
    # the rescue-lane batch slots the device program ran: in fused mode the
    # whole esc_cap (= padded batch) every time the lax.cond fired (any
    # rescue candidate in the batch); in split mode the padded width of each
    # Stream B dispatch. Host-side, so the fused-vs-split tail-cost ratio is
    # measurable with no chip.
    n_rescue_windows: int = 0    # live windows that went through a rescue lane
    rescue_slots_executed: int = 0
    n_dispatch_tier0: int = 0    # Stream A dispatches (split mode)
    n_dispatch_rescue: int = 0   # Stream B dispatches (split mode)
    rescue_dispatches: list = field(default_factory=list)
                                 # split mode: one {rows, slots, reason} per
                                 # Stream B dispatch (reason: full|lag|final)
    bases_in: int = 0
    bases_out: int = 0
    tier_histogram: dict = field(default_factory=dict)
    native_host: bool = False
    degraded: bool = False       # supervisor failed over mid-run (the shard
                                 # completed on the fallback engine)
    fallback_reason: str | None = None
    # capacity governor (ISSUE 5). Capacity degradation is degraded SPEED,
    # not degraded OUTPUT (byte-identical by per-window independence), so it
    # is deliberately NOT folded into `degraded` — the merge gate accepts
    # capacity-degraded shards without --allow-degraded.
    n_capacity_events: int = 0   # capacity-classified device ops (governor
                                 # ladder engagements)
    n_backpressure: int = 0      # host-watermark force-flushes
    n_monster_piles: int = 0     # piles contained by the monster guard
                                 # (subset of n_quarantined)
    batch_effective: int | None = None   # dispatch width the shard ran at:
                                 # the smallest ratcheted width when the
                                 # governor engaged, else the configured
                                 # batch (None = unsupervised run; compare
                                 # against governor_ratchet to tell
                                 # configured from ratcheted)
    governor_ratchet: dict = field(default_factory=dict)
                                 # shape fingerprint -> ratcheted width,
                                 # entries touched this run (manifest state)
    paged: bool = False          # the shard dispatched the paged wire format
                                 # (kernels/paging.py); pad_cells then counts
                                 # shipped pool payload cells instead of the
                                 # dense rectangle
    pad_cells: int = 0
    used_cells: int = 0
    wall_s: float = 0.0
    device_s: float = 0.0
    host_s: float = 0.0
    # saturation profiler (ISSUE 14). feeder_s = host wall blocked on the
    # feeder iterator (pipeline-visible: a threaded feeder overlaps, so this
    # is what the pile loop actually waited, not thread-summed CPU time);
    # dispatch_s = wall inside dispatch calls (the solve itself on inline
    # engines, the enqueue on async ones); stage_profile = the per-stage
    # StageProfile.summary() table; verdict/bottleneck = the automatic
    # attribution (obs.bottleneck_verdict) stamped into shard_done, every
    # metrics rollup, the prom exposition, and the bench sidecars.
    feeder_s: float = 0.0
    dispatch_s: float = 0.0
    dispatch_walls: dict | None = None
                                 # staged mesh dispatch (ISSUE 19): the
                                 # pack/stage/launch sub-wall decomposition
                                 # (+ restaged count) from
                                 # ShardedLadderSolver.dispatch_walls();
                                 # None off the mesh path
    stage_profile: dict = field(default_factory=dict)
    verdict: str = "balanced"
    bottleneck: dict = field(default_factory=dict)
    metrics: dict = field(default_factory=dict)
                                 # end-of-run MetricsRegistry rollup
                                 # (ISSUE 6); launch.run_shard commits it
                                 # durably beside the shard manifest

    @property
    def pad_waste(self) -> float:
        return 1.0 - self.used_cells / self.pad_cells if self.pad_cells else 0.0

    @property
    def rescue_density(self) -> float:
        """Live rows per executed rescue slot (1.0 = every rescue slot the
        quadratic DP paid for held a real window; fused mode at production
        failure rates sits near the failure rate itself)."""
        return (self.n_rescue_windows / self.rescue_slots_executed
                if self.rescue_slots_executed else 0.0)

    def bases_per_sec(self) -> float:
        return self.bases_out / self.wall_s if self.wall_s > 0 else 0.0


class _PendingRead:
    __slots__ = ("aread", "a_bases", "n_windows", "results", "n_done", "tiers")

    def __init__(self, aread: int, a_bases: np.ndarray, n_windows: int):
        self.aread = aread
        self.a_bases = a_bases
        self.n_windows = n_windows
        self.results: list = [None] * n_windows
        self.n_done = 0
        self.tiers = np.full(n_windows, -1, dtype=np.int32)


def _trim_rescue_ends(pr: _PendingRead, rescue_tiers: set, stats: PipelineStats) -> None:
    """Null out prefix/suffix runs of rescue-tier-solved windows (see
    PipelineConfig.end_trim). Scanning skips over already-unsolved windows
    (they are split points either way) and stops at the first window solved
    by a confident tier."""
    res = pr.results

    def sweep(idxs) -> None:
        for j in idxs:
            ws, wl, seq = res[j]
            if seq is None:
                continue
            t = int(pr.tiers[j])
            if t not in rescue_tiers:
                return
            res[j] = (ws, wl, None)
            stats.n_solved -= 1
            stats.n_end_trimmed += 1
            stats.tier_histogram[t] = stats.tier_histogram.get(t, 0) - 1

    sweep(range(pr.n_windows))
    sweep(range(pr.n_windows - 1, -1, -1))


class QvRanker:
    """Per-overlap B-read quality from an intrinsic-QV track.

    The track (written by ``compute_intrinsic_qv``) holds one QV byte per
    tspace tile per read; :meth:`rates` averages each B read's tiles under
    its aligned interval and returns error-rate units (QV / QV_SCALE), NaN
    when no covered tile has coverage. All per-read prefix sums are built
    once up front as flat arrays (one global cumsum differenced inside each
    read's tile span), so ranking a pile is pure vectorized numpy — this
    runs inside the feeder threads' windowing loop.
    """

    def __init__(self, qv_payloads: list, tspace: int, db: DazzDB):
        from ..tools.lastools import QV_NOCOV, QV_SCALE

        self.tspace = tspace
        self._scale = QV_SCALE
        nt = np.fromiter((len(p) for p in qv_payloads), np.int64,
                         len(qv_payloads))
        self.tile_base = np.zeros(len(nt) + 1, np.int64)
        np.cumsum(nt, out=self.tile_base[1:])
        flat = (np.concatenate(qv_payloads) if len(qv_payloads)
                else np.zeros(0, np.uint8))
        valid = flat != QV_NOCOV
        self.cv = np.zeros(len(flat) + 1, np.float64)
        np.cumsum(np.where(valid, flat, 0), out=self.cv[1:])
        self.cc = np.zeros(len(flat) + 1, np.int64)
        np.cumsum(valid, out=self.cc[1:])
        self.rlens = np.fromiter((db.read_length(i)
                                  for i in range(len(qv_payloads))),
                                 np.int64, len(qv_payloads))

    def rates(self, bread, bbpos, bepos, comp) -> np.ndarray:
        """Vectorized per-overlap mean QV rate; NaN = no QV information."""
        bread = np.asarray(bread, np.int64)
        bb = np.asarray(bbpos, np.int64)
        be = np.asarray(bepos, np.int64)
        comp = np.asarray(comp).astype(bool)
        inb = (bread >= 0) & (bread < len(self.rlens))
        br = np.where(inb, bread, 0)
        blen = self.rlens[br]
        # LAS B coordinates of complemented overlaps live in complement
        # space; the track indexes forward-strand tiles
        fb = np.where(comp, blen - be, bb)
        fe = np.where(comp, blen - bb, be)
        nt = self.tile_base[br + 1] - self.tile_base[br]
        g0 = np.maximum(fb // self.tspace, 0)
        g1 = np.minimum((np.maximum(fe, fb + 1) - 1) // self.tspace, nt - 1)
        ok = inb & (nt > 0) & (g1 >= g0)
        lo = np.where(ok, self.tile_base[br] + g0, 0)
        hi = np.where(ok, self.tile_base[br] + g1 + 1, 0)
        cnt = self.cc[hi] - self.cc[lo]
        sums = self.cv[hi] - self.cv[lo]
        return np.where(ok & (cnt > 0),
                        sums / np.maximum(cnt, 1) / self._scale, np.nan)

    def rate(self, bread: int, bbpos: int, bepos: int, comp: bool) -> float:
        """Scalar convenience form of :meth:`rates`."""
        return float(self.rates([bread], [bbpos], [bepos], [comp])[0])


#: weight of the B read's intrinsic QV rate in the depth-ranking score.
#: The pair trace rate already contains B's error contribution — and it is
#: the ONLY signal separating cross-repeat-copy alignments (their divergence
#: lives in the pair, not in B's intrinsic quality) — so the QV term enters
#: small: enough to sink intrinsically junk B reads (inqual aggregates B's
#: whole pile, far lower variance than one window's trace diffs), without
#: diluting the pair signal. Measured on the diverged-repeat sim: weight 1.0
#: cost -1.2 Q vs trace-only ranking.
QV_RANK_WEIGHT = 0.25


def _rank_scores(diffs: np.ndarray, spans: np.ndarray,
                 bq: np.ndarray | None) -> np.ndarray:
    """Depth-ranking score per overlap: pair trace-diff rate plus (when a QV
    track is loaded) a down-weighted intrinsic error rate of the B read.
    Overlaps whose B tiles have no QV coverage take the pile median so
    unknown quality ranks neutral, not best. One function for the native and
    oracle paths — their orderings must stay identical for the byte-parity
    tests."""
    score = diffs.astype(np.float64) / spans
    if bq is not None:
        valid = ~np.isnan(bq)
        fill = float(np.median(bq[valid])) if valid.any() else 0.0
        score = score + QV_RANK_WEIGHT * np.where(valid, bq, fill)
    return score


def load_qv_ranker(db: DazzDB, las: LasFile, cfg: PipelineConfig) -> QvRanker | None:
    """The shard's QV ranker, or None when the track is absent/disabled or
    its tile geometry doesn't match this LAS's tspace (a track written under
    a different tspace would silently map wrong tiles)."""
    if not cfg.qv_track or not cfg.depth_rank:
        return None
    from ..formats.dazzdb import read_track

    try:
        payloads = read_track(db.path, cfg.qv_track)
    except (FileNotFoundError, OSError):
        return None
    tspace = las.tspace
    for i, p in enumerate(payloads):
        if len(p) != (db.read_length(i) + tspace - 1) // tspace:
            return None
    return QvRanker(payloads, tspace, db)


def _stride_take(n_items: int, n: int, offset: int = 0) -> np.ndarray:
    """Indices of ``n`` items spread evenly across ``n_items`` (deduped,
    offset-rotated) — the profile pass's one sampling rule, shared by the
    sidecar-index stride and the ingest scan's clean-pile path."""
    if n_items == 0 or n == 0:
        return np.zeros(0, np.int64)
    return np.unique((np.linspace(0, n_items - 1,
                                  min(n, n_items)).astype(int)
                      + offset) % n_items)


def _strided_pile_ranges(las: LasFile, n: int, start: int | None,
                         end: int | None, offset: int = 0) -> list[tuple[int, int]]:
    """Byte ranges of ``n`` piles spread evenly across the shard (via the
    aread index sidecar). The reference samples across the input; round 1
    took the FIRST n piles — a start-of-file bias (VERDICT r1 weak #5)."""
    from ..formats.las import _HDR_SIZE, index_las
    from ..utils.aio import getsize

    idx = index_las(las.path)
    lo = start if start is not None else _HDR_SIZE
    hi = end if end is not None else getsize(las.path)
    if len(idx) == 0:
        return [(lo, hi)]
    sel = np.nonzero((idx[:, 1] >= lo) & (idx[:, 1] < hi))[0]
    if len(sel) == 0:
        return [(lo, hi)]
    take = _stride_take(len(sel), n, offset)
    out = []
    for t in take:
        j = int(sel[t])
        s = int(idx[j, 1])
        e = int(idx[j + 1, 1]) if j + 1 < len(idx) else hi
        out.append((s, min(e, hi)))
    return out


def estimate_profile_for_shard(db: DazzDB, las: LasFile, cfg: PipelineConfig,
                               start: int | None = None,
                               end: int | None = None,
                               pile_ranges: list | None = None,
                               return_windows: bool = False):
    """Profile pass over ``cfg.profile_sample_piles`` piles strided across the
    shard (oracle path: the sample is tiny and this doubles as a continuous
    cross-check of the native path).

    ``pile_ranges`` overrides the sidecar-index stride with an explicit list
    of (start, end) pile byte ranges — the quarantine path passes the
    validating scan's CLEAN piles so estimation never decodes corrupt bytes
    (index_las would reject the file outright). ``return_windows`` also
    returns the sampled windows: the paged router derives its shape families
    from exactly this sample, so a paged run pays the alignment-heavy
    sampling pass once, not twice."""
    from ..oracle.consensus import estimate_profile_two_pass

    refined_all, windows_all = _sample_windows(db, las, cfg, start, end,
                                               pile_ranges)
    prof = estimate_profile_two_pass(refined_all, windows_all, cfg.consensus,
                                     sample=32)
    return (prof, windows_all) if return_windows else prof


def _sample_windows(db: DazzDB, las: LasFile, cfg: PipelineConfig,
                    start, end, pile_ranges: list | None = None):
    """The shard's ONE strided pile-sampling procedure (refined overlaps +
    cut windows of ``cfg.profile_sample_piles`` piles), shared by the
    profile pass and the paged family derivation so their sampling rules —
    the quarantine clean-pile branch included — cannot drift apart."""
    if pile_ranges is not None:
        take = _stride_take(len(pile_ranges), cfg.profile_sample_piles,
                            cfg.profile_sample_offset)
        ranges = [pile_ranges[int(t)] for t in take]
    else:
        ranges = _strided_pile_ranges(las, cfg.profile_sample_piles, start,
                                      end, offset=cfg.profile_sample_offset)
    refined_all = []
    windows_all: list[WindowSegments] = []
    for s, e in ranges:
        for aread, pile in las.iter_piles(s, e):
            a_bases = db.read_bases(aread)
            refined = [refine_overlap(o, a_bases, db.read_bases(o.bread), las.tspace)
                       for o in pile]
            refined_all.extend(refined)
            windows_all.extend(cut_windows(a_bases, refined, w=cfg.consensus.w,
                                           adv=cfg.consensus.adv))
            break   # one pile per strided range
    return refined_all, windows_all


def families_from_windows(windows: list[WindowSegments],
                          cfg: PipelineConfig):
    """Shape families for the paged router (kernels/paging.py) from a
    window sample — the corpus length x depth histogram the ISSUE names.
    The sample approximates the runtime histogram (depth ranking reorders
    which segments survive the cap, not how many), which only shifts family
    budgets, never correctness: the mandatory full-coverage family routes
    any window the sample never predicted."""
    from ..kernels import paging

    shape = BatchShape(depth=cfg.depth, seg_len=cfg.seg_len,
                       wlen=cfg.consensus.w)
    if windows:
        b = tensorize_windows([(0, ws) for ws in windows], shape)
        ns = b.nsegs
        pg = paging.window_pages(b.lens, cfg.page_len)
    else:
        ns = pg = np.zeros(0, np.int64)
    return paging.derive_families(
        ns, pg, max_depth=cfg.depth,
        max_pages=-(-cfg.depth * cfg.seg_len // cfg.page_len),
        budget=cfg.paged_families, page_len=cfg.page_len)


def derive_families_for_shard(db: DazzDB, las: LasFile, cfg: PipelineConfig,
                              start: int | None = None,
                              end: int | None = None,
                              pile_ranges: list | None = None):
    """:func:`families_from_windows` over a fresh strided pile sample
    (:func:`_sample_windows` — the profile pass's exact sampling rule,
    ``pile_ranges`` = the validating scan's clean piles under the
    quarantine policy). Only for callers with no profile-pass sample to
    reuse — a precomputed-profile run; in-run estimation hands its windows
    straight to families_from_windows."""
    _, windows_all = _sample_windows(db, las, cfg, start, end, pile_ranges)
    return families_from_windows(windows_all, cfg)


def _window_one_pile(db: DazzDB, col, cfg: PipelineConfig, aread: int, s: int, e: int,
                     qvr: QvRanker | None = None, prof=None):
    """Window one pile via the native path; shared by the synchronous and
    threaded feeders so their outputs stay byte-identical by construction.
    ``prof`` (obs.StageProfile) books the per-stage walls — ``decode`` for
    the DB base decodes, ``rank`` for the depth-ranking sort, ``realign``
    for the native pile processor (which fuses realign + window cut +
    tensorize in C++, so the python-path kmer/tensorize stages read 0 on
    native runs). Runs inside the feeder threads: StageProfile.add is
    lock-guarded, and timer cost is two perf_counter calls per stage per
    pile — noise against the pile's own DP."""
    from ..native.api import process_pile_native

    w, adv = cfg.consensus.w, cfg.consensus.adv
    D, L = cfg.depth, cfg.seg_len
    t0 = time.perf_counter()
    a = db.read_bases(aread)
    t1 = time.perf_counter()
    order = None
    if cfg.depth_rank:
        # quality-ranked depth capping (SURVEY.md §7.3 item 1): best
        # alignments (lowest trace-diff rate, plus the B read's intrinsic
        # QV when the inqual track is loaded) fill the depth slots
        span = np.maximum(col.aepos[s:e] - col.abpos[s:e], 1)
        bq = None
        if qvr is not None:
            bq = qvr.rates(col.bread[s:e], col.bbpos[s:e], col.bepos[s:e],
                           col.comp[s:e])
        order = np.argsort(_rank_scores(col.diffs[s:e], span, bq), kind="stable")
    t2 = time.perf_counter()
    idxs = range(s, e) if order is None else (s + order)
    b_reads = db.read_bases_batch(int(col.bread[i]) for i in idxs)
    t3 = time.perf_counter()
    seqs, lens, nsegs = process_pile_native(a, col, s, e, b_reads, w, adv, D, L,
                                            order=order)
    if prof is not None:
        prof.add("decode", (t1 - t0) + (t3 - t2))
        prof.add("rank", t2 - t1)
        prof.add("realign", time.perf_counter() - t3)
    return aread, a, seqs, lens, nsegs


def _monster_marker(aread: int, n_overlaps: int):
    """Quarantine-style block marker for a budget-busting pile: rides the
    same byte-ordered containment path the ingest layer uses (read emitted
    UNCORRECTED, sidecar row, n_quarantined), so a monster pile degrades one
    read instead of OOM-killing the worker."""
    return ("quarantine", int(aread), -1, "monster_pile",
            f"pile busts the capacity budget ({n_overlaps} overlaps)")


def _iter_pile_blocks(db: DazzDB, las: LasFile, cfg: PipelineConfig,
                      start, end, native_ok: bool, qvr: QvRanker | None = None,
                      monster=None, prof=None):
    """Yield (aread, a_bases, seqs [nwin,D,L], lens [nwin,D], nsegs [nwin]).

    ``monster(aread, n_overlaps) -> bool`` is the capacity governor's
    monster-pile guard, consulted per pile BEFORE the quadratic windowing/
    realignment spend; a busted pile yields a quarantine marker instead.
    ``prof`` (obs.StageProfile) books the feeder sub-stage walls — on the
    python path decode/realign/kmer/tensorize are individually separable,
    so this is where the full five-way decomposition comes from."""
    w, adv = cfg.consensus.w, cfg.consensus.adv
    D, L = cfg.depth, cfg.seg_len
    if native_ok:
        from ..native.api import ColumnarLas

        t0 = time.perf_counter()
        col = ColumnarLas(las.path, start, end)
        if prof is not None:
            # the whole-range columnar LAS parse is byte decode
            prof.add("decode", time.perf_counter() - t0)
        for aread, s, e in col.piles():
            if monster is not None and monster(aread, e - s):
                yield _monster_marker(aread, e - s)
                continue
            yield _window_one_pile(db, col, cfg, aread, s, e, qvr, prof=prof)
    else:
        shape = BatchShape(depth=D, seg_len=L, wlen=w)
        it = las.iter_piles(start, end)
        while True:
            # the pile decode happens inside the generator's __next__; time
            # it explicitly so the decode stage covers the LAS byte walk
            t0 = time.perf_counter()
            try:
                aread, pile = next(it)
            except StopIteration:
                break
            if prof is not None:
                prof.add("decode", time.perf_counter() - t0)
            if monster is not None and monster(aread, len(pile)):
                yield _monster_marker(aread, len(pile))
                continue
            t0 = time.perf_counter()
            a = db.read_bases(aread)
            t1 = time.perf_counter()
            if cfg.depth_rank and pile:
                diffs = np.asarray([o.diffs for o in pile])
                span = np.maximum(
                    np.asarray([o.aepos - o.abpos for o in pile]), 1)
                bq = None
                if qvr is not None:
                    bq = qvr.rates([o.bread for o in pile],
                                   [o.bbpos for o in pile],
                                   [o.bepos for o in pile],
                                   [o.is_comp for o in pile])
                order = np.argsort(_rank_scores(diffs, span, bq), kind="stable")
                pile = [pile[i] for i in order]
            t2 = time.perf_counter()
            # B reads decode ONE AT A TIME inside the refine loop (never the
            # whole pile at once — a deep repeat pile would balloon transient
            # RSS); the decode timer follows the read into the loop
            refined = []
            b_dec_s = 0.0
            for o in pile:
                td = time.perf_counter()
                b = db.read_bases(o.bread)
                b_dec_s += time.perf_counter() - td
                refined.append(refine_overlap(o, a, b, las.tspace))
            t3 = time.perf_counter()
            windows = cut_windows(a, refined, w=w, adv=adv)
            t4 = time.perf_counter()
            if prof is not None:
                prof.add("decode", (t1 - t0) + b_dec_s)
                prof.add("rank", t2 - t1)
                prof.add("realign", (t3 - t2) - b_dec_s)
                prof.add("kmer", t4 - t3)
            if windows:
                b = tensorize_windows([(aread, ws) for ws in windows], shape,
                                      prof=prof)
                yield aread, a, b.seqs, b.lens, b.nsegs
            else:
                yield aread, a, np.zeros((0, D, L), np.int8), np.zeros((0, D), np.int32), np.zeros(0, np.int32)


class _Ready:
    """Pre-resolved stand-in for a Future (monster-pile markers interleave
    with real windowing jobs in input order)."""

    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def result(self):
        return self.v


def _iter_pile_blocks_threaded(db: DazzDB, las: LasFile, cfg: PipelineConfig,
                               start, end, nthreads: int,
                               qvr: QvRanker | None = None, monster=None,
                               prof=None):
    """Same stream as :func:`_iter_pile_blocks` (native path), but piles are
    windowed by a thread pool with bounded in-order prefetch. Output order —
    and therefore every downstream byte — is identical to the synchronous
    path; only wall-clock changes. The monster guard runs in the (ordered)
    submission loop, so its fault counter stays deterministic. ``prof``
    stage walls sum ACROSS pool threads (StageProfile records ``threads``
    so daccord-prof's reconciliation scales accordingly)."""
    from collections import deque
    from concurrent.futures import ThreadPoolExecutor

    from ..native.api import ColumnarLas

    t0 = time.perf_counter()
    col = ColumnarLas(las.path, start, end)
    piles = list(col.piles())
    if prof is not None:
        prof.add("decode", time.perf_counter() - t0)
    # QvRanker state is built fully in __init__ and only read here, so the
    # worker threads need no lock

    def job(item):
        aread, s, e = item
        return _window_one_pile(db, col, cfg, aread, s, e, qvr, prof=prof)

    with ThreadPoolExecutor(max_workers=nthreads) as ex:
        def submit(item):
            aread, s, e = item
            if monster is not None and monster(aread, e - s):
                return _Ready(_monster_marker(aread, e - s))
            return ex.submit(job, item)

        inflight: deque = deque()
        it = iter(piles)
        budget = nthreads + 2
        for item in it:
            inflight.append(submit(item))
            if len(inflight) >= budget:
                break
        while inflight:
            yield inflight.popleft().result()
            for item in it:
                inflight.append(submit(item))
                break


class _Stager:
    """Async double-buffered dispatch staging (ISSUE 19).

    One daemon thread runs the *stage* half of the split mesh dispatch
    (``parallel/mesh.py`` — host pad/pack + per-device shard slicing + H2D
    transfer) so batch N+1's host work proceeds entirely under batch N's
    device solve; the pipeline thread only ``launch``es finished stages (a
    cheap async jit call). Depth is bounded at 2 — one batch staging on the
    thread plus at most one waiting in the queue — and :meth:`submit`
    BLOCKS when the buffer is full, so the feeder cannot run ahead of the
    governor's RSS watermarks (backpressure still binds; at most two extra
    host batches are retained, same order as the in-flight window).

    A ticket retains the HOST batch alongside the staged device buffers:
    a staging error falls back to the direct dispatch path (the supervisor
    ladder takes it from there), and replay-class faults downstream never
    depend on staged state — the supervisor unwraps ``replay_batch``.
    """

    class _Ticket:
        __slots__ = ("batch", "meta", "staged", "error", "done")

        def __init__(self, batch, meta):
            import threading

            self.batch = batch
            self.meta = meta
            self.staged = None
            self.error: BaseException | None = None
            self.done = threading.Event()

    def __init__(self, stage_fn, prof=None):
        import queue
        import threading

        self._stage_fn = stage_fn
        self._prof = prof
        self._q: queue.Queue = queue.Queue(maxsize=1)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="daccord-stager")
        self._thread.start()

    def submit(self, batch, meta) -> "_Stager._Ticket":
        t = self._Ticket(batch, meta)
        self._q.put(t)   # blocks at depth 2: the double-buffer backpressure
        return t

    def _loop(self) -> None:
        # this thread NEVER logs: the events sidecar requires monotonic
        # timestamps within one file, and a second writer interleaving its
        # own clock reads breaks that lint. The staged walls ride the
        # ticket; the pipeline thread emits dispatch.stage when it consumes
        # it (StageProfile.add is lock-guarded aggregation, not an event).
        while True:
            t = self._q.get()
            if t is None:
                return
            try:
                t.staged = self._stage_fn(t.batch, prof=self._prof)
            except BaseException as e:  # noqa: BLE001 - relayed to launcher
                t.error = e
            finally:
                t.done.set()

    def stop(self) -> None:
        self._q.put(None)


def _native_wide_rescue(wide_nladder, b, out: dict, nt: int) -> None:
    """Overflow rescue on the native engine, device-ladder semantics
    (kernels/tiers.py ladder_core): windows whose top-M cap bound re-solve
    at the rescue active-set size and the wide result replaces the capped
    one wherever it solves. Widen-only guard applied at wide_nladder
    construction (same rule as TierLadder.from_config)."""
    import dataclasses

    idx = np.nonzero(out["m_ovf"])[0]
    sub = dataclasses.replace(
        b, seqs=b.seqs[idx], lens=b.lens[idx],
        nsegs=b.nsegs[idx], read_ids=b.read_ids[idx],
        wstarts=b.wstarts[idx])
    wide = wide_nladder.solve(sub, n_threads=nt)
    take = wide["solved"]
    ti = idx[take]
    for key in ("cons", "cons_len", "err", "tier"):
        out[key][ti] = wide[key][take]
    out["solved"][ti] = True
    out["m_ovf"][ti] = wide["m_ovf"][take]


def _build_native_fallback(profile: ErrorProfile, cfg: PipelineConfig):
    """Degraded-mode engine for the supervisor: the C++ tier ladder at the
    run's cap config (oracle-parity semantics; no hp pass here — the
    pipeline's host-side hp drain applies to fallback results exactly as it
    does to fetched device results). Raises when the library isn't built."""
    from ..native import available as _nat_avail
    from ..native.api import NativeLadder
    from ..oracle.consensus import make_offset_likely

    if not _nat_avail():
        raise RuntimeError("native library unavailable")
    ols = make_offset_likely(profile, cfg.consensus)
    nt = cfg.native_threads if cfg.native_threads > 0 else (
        os.cpu_count() or 1)
    # tables packed ONCE; thousands of per-batch calls share them
    nladder = NativeLadder(ols, cfg.consensus, max_kmers=cfg.max_kmers,
                           rescue_max_kmers=cfg.rescue_max_kmers)
    # widen-only guard applied here (same rule as TierLadder.from_config)
    wide = (nladder.with_caps(cfg.rescue_max_kmers, cfg.rescue_max_kmers)
            if cfg.overflow_rescue
            and 0 < cfg.max_kmers < cfg.rescue_max_kmers else None)

    def solve(b):
        # same top-M semantics as the device ladder (measured beneficial on
        # CLR, BASELINE.md r3 top-M table); -M 0 gives the full graph
        out = nladder.solve(b, n_threads=nt)
        if wide is not None and out["m_ovf"].any():
            _native_wide_rescue(wide, b, out, nt)
        return out

    solve.__name__ = "native-ladder"
    # exposed so the --backend native primary can layer its in-engine hp
    # rescue + stats on the SAME construction (one path, byte parity)
    solve.nladder, solve.nt, solve.ols = nladder, nt, ols
    return solve


def _make_clamp_solve(ladder: TierLadder, use_pallas: bool, interp: bool,
                      esc_clamp: int):
    """The governor's esc-cap-clamp rung for the JAX ladder paths: the same
    ladder program with its rescue lanes clamped to ``esc_clamp`` slots (the
    M=256 quadratic DP over the rescue lanes dominates the program's HBM),
    plus host-routed completion of any rows the clamp overflowed — the
    split-ladder argument again, so the rung stays byte-identical to the
    full program."""
    import dataclasses

    from ..kernels.tiers import fetch as _fetch
    from ..kernels.tiers import solve_ladder_async, solve_tiered

    min_depth = ladder.params[0].min_depth

    def clamp_solve(b):
        out = _fetch(solve_ladder_async(b, ladder,
                                        esc_cap=min(esc_clamp, b.size),
                                        use_pallas=use_pallas,
                                        pallas_interpret=interp))
        out = {k: (np.array(v) if isinstance(v, np.ndarray) else v)
               for k, v in out.items()}
        if int(np.asarray(out.get("esc_overflow", 0))) > 0:
            # rows past the clamp stayed unsolved on device: complete them
            # in compact host-routed sub-batches (bounded memory) so the
            # clamp degrades speed, never bytes
            need = (~np.asarray(out["solved"])
                    & (np.asarray(b.nsegs) >= min_depth))
            idx = np.nonzero(need)[0]
            if len(idx):
                # the host-routed completion iterates dense rows: unpack a
                # paged batch first (byte-identical by the round-trip
                # property, tests/test_paging.py)
                bd = b.to_dense() if hasattr(b, "to_dense") else b
                sub = dataclasses.replace(
                    bd, seqs=bd.seqs[idx], lens=bd.lens[idx],
                    nsegs=bd.nsegs[idx], read_ids=bd.read_ids[idx],
                    wstarts=bd.wstarts[idx])
                r = solve_tiered(sub, ladder)
                for kk in ("cons", "cons_len", "err", "solved", "tier",
                           "m_ovf"):
                    out[kk][idx] = r[kk]
            out["esc_overflow"] = 0
        return out

    return clamp_solve


class _Telemetry:
    """Per-shard telemetry bundle (ISSUE 6): buffered event/log writers, the
    trace-span tracer, the per-window outcome ledger, and the metrics
    registry. Created before the pipeline body and closed in
    :func:`correct_shard`'s ``finally``, so abort/failover unwind paths flush
    buffered tails and close every open span (the pairing invariant
    ``daccord-trace --check`` enforces)."""

    def __init__(self, cfg: PipelineConfig, start, end):
        from ..utils.obs import (JsonlLogger, MetricsRegistry, StageProfile,
                                 Tracer, WindowLedger)

        # file-backed streams buffer (hot-path budget); '-' streams stay
        # line-flushed — stderr exists for LIVE monitoring, and a buffered
        # tail would go silent exactly when an operator watches for a wedge
        def _mk(path):
            kw = ({"buffer_lines": 64, "flush_s": 2.0}
                  if path and path != "-" else {})
            return JsonlLogger(path, **kw)

        self.log = _mk(cfg.log_path)
        self.ev_log = _mk(cfg.events_path) if cfg.events_path else self.log
        # stream boundary FIRST: a requeued/resumed worker appends to the
        # same sidecar with a fresh relative clock — eventcheck --strict
        # resets its t/state/span tracking here
        self.ev_log.log("shard_start", start=int(start or 0),
                        end=int(-1 if end is None else end), pid=os.getpid())
        self.tracer = Tracer(self.ev_log)
        self.ledger = (WindowLedger(cfg.ledger_path) if cfg.ledger_path
                       else None)
        self.metrics = MetricsRegistry()
        # saturation profiler (ISSUE 14): always-on per-stage feeder
        # accounting — timers cost two perf_counter calls per stage per pile
        # (measured << the 2% budget), and emission rides the existing
        # snapshot cadence, so there is no profiler on/off switch to drift.
        # `threads` is corrected once the run knows whether the threaded
        # feeder actually engages (native path present).
        self.stage = StageProfile(threads=max(1, cfg.feeder_threads))
        self.run_span = self.tracer.open("run")

    def close(self) -> None:
        stop = getattr(self, "prof_stop", None)
        if stop is not None:
            # an abort mid-capture must still stop the jax.profiler trace
            # (a torn trace dir is worse than no trace)
            stop()
        self.tracer.unwind()
        if self.ledger is not None:
            self.ledger.close()
        if self.ev_log is not self.log:
            self.ev_log.close()
        self.log.close()


def correct_shard(db: DazzDB, las: LasFile, cfg: PipelineConfig,
                  start: int | None = None, end: int | None = None,
                  profile: ErrorProfile | None = None,
                  solver=None, ingest_report=None):
    """Correct every pile in the byte range; yields (aread, fragments, stats).

    ``solver`` maps a WindowBatch to a solve_tiered-style output dict; defaults
    to the local single-device ladder. The parallel backend passes the
    mesh-sharded one. ``ingest_report`` supplies a pre-computed
    :class:`~..formats.ingest.LasScanReport` covering exactly this byte range
    (the checkpointed launcher pre-scans; rescanning a damaged multi-GB file
    would double the slowest ingest step) — None runs the scan here.
    """
    tel = _Telemetry(cfg, start, end)
    try:
        yield from _correct_shard_impl(db, las, cfg, start, end, profile,
                                       solver, ingest_report, tel)
    finally:
        # one exit path for every outcome — normal exhaustion, strict-scan
        # abort, injected crash, abandoned generator: buffered telemetry
        # flushes and open spans close (status=abort when not already closed)
        tel.close()


def _correct_shard_impl(db: DazzDB, las: LasFile, cfg: PipelineConfig,
                        start, end, profile, solver, ingest_report,
                        tel: _Telemetry):
    stats = PipelineStats()
    t_start = time.time()
    log, ev_log = tel.log, tel.ev_log
    tracer, ledger, metrics = tel.tracer, tel.ledger, tel.metrics

    # ONE fault plan for the whole shard (ISSUE 5): the supervisor consumes
    # the device kinds, the capacity guards below consume host_rss /
    # monster_pile — separate counter domains, shared spec state
    from .faults import FaultPlan
    from .governor import GovernorConfig, check_host_pressure, host_rss_mb

    plan = FaultPlan.from_env()
    gov_cfg = GovernorConfig.from_env()

    # ingest integrity gate (formats/ingest.py, ISSUE 2): validate every
    # record header in the byte range BEFORE any fast decoder trusts it.
    # strict -> abort with the structured report; quarantine -> the scan's
    # segment plan below contains each corrupt pile without sinking the run
    report = None
    bad_reads = getattr(db, "bad_reads", None) or set()
    if cfg.ingest_policy != "off":
        if ingest_report is not None:
            report = ingest_report
        else:
            from ..formats.ingest import scan_with_db

            with tracer.span("scan"):
                report = scan_with_db(db, las, start, end)
        stats.n_ingest_issues = len(report.issues)
        ev_log.log("ingest.scan", path=las.path, records=report.n_records,
                   piles=report.n_piles, issues=len(report.issues),
                   policy=cfg.ingest_policy)
        for iss in report.issues[:64]:
            ev_log.log("ingest.issue", kind=iss.kind, offset=iss.offset,
                       aread=(-1 if iss.aread is None else int(iss.aread)),
                       detail=iss.detail)
        if report.issues and cfg.ingest_policy == "strict":
            # correct_shard's finally closes the telemetry bundle: a driver
            # loop retrying corrupt shards must not leak two fds per abort
            raise report.error()
    # mesh intent resolved early: a custom/injected solver brings its own
    # programs and the native engine solves on host — both ignore cfg.mesh
    # (log, not raise: an auto-resolved native backend must keep working)
    mesh_n = cfg.mesh if cfg.mesh and cfg.mesh > 1 else 0
    if mesh_n and (solver is not None or cfg.native_solver):
        log.log("info", msg=f"mesh={mesh_n} inapplicable here (native "
                            "engine or custom solver); running single-device")
        mesh_n = 0
    if mesh_n:
        # fail fast — BEFORE the alignment-heavy profile pass — with the
        # off-pod recipe when the device pool is too small
        from ..parallel.mesh import check_mesh_devices

        check_mesh_devices(mesh_n)
    if cfg.batch_size is None:
        import dataclasses

        from ..utils.obs import auto_batch_size

        if cfg.native_solver and solver is None:
            cfg = dataclasses.replace(cfg, batch_size=auto_batch_size(True))
        else:
            import jax

            # one host, N chips = one worker: the auto batch scales by mesh
            # size so each device's slice keeps the single-device width
            cfg = dataclasses.replace(cfg, batch_size=auto_batch_size(
                False, jax.default_backend(), mesh=mesh_n))
    # paged intent resolved BEFORE the profile pass so family derivation can
    # reuse the pass's window sample (one alignment-heavy sampling pass, not
    # two); the authoritative paged_on below uses identical conditions
    paged_want = (cfg.paged in ("on", "auto") and not cfg.native_solver
                  and (solver is None
                       or getattr(solver, "supports_paged", False)))
    if paged_want and cfg.paged == "auto":
        import jax

        paged_want = jax.default_backend() != "cpu"
    paged_sample = None
    if profile is None:
        with tracer.span("profile"):
            # quarantine policy: sample only validated-clean piles —
            # index_las rejects the file outright on a corrupt one
            kw = (dict(pile_ranges=report.pile_ranges)
                  if report is not None and report.issues else {})
            if paged_want:
                profile, paged_sample = estimate_profile_for_shard(
                    db, las, cfg, start, end, return_windows=True, **kw)
            else:
                profile = estimate_profile_for_shard(db, las, cfg, start,
                                                     end, **kw)
    ladder = None
    if solver is not None and hasattr(solver, "ladder"):
        # a warm-state solver (the serve batcher) already owns the ladder
        # for this run's exact solve fingerprint — rebuilding the
        # OffsetLikely tables per job would re-spend the cold start the
        # warm group exists to amortize. None (a native group) matches the
        # solo native path, which builds no device ladder either.
        ladder = solver.ladder
    elif not (solver is None and cfg.native_solver):
        # the native C++ solver builds its own OffsetLikely tables from the
        # same make_offset_likely call — constructing the (unused) device
        # ladder too would do that work twice
        with tracer.span("ladder.build"):
            ladder = TierLadder.from_config(profile, cfg.consensus,
                                            max_kmers=cfg.max_kmers,
                                            rescue_max_kmers=cfg.rescue_max_kmers,
                                            overflow_rescue=cfg.overflow_rescue)
    # mesh-native solve path (parallel/mesh.py): build the sharded solver
    # from the run's OWN TierLadder (no second OffsetLikely construction),
    # so mesh batches flow through the same supervisor/governor/paging/split
    # machinery as single-device ones — it is the default multi-chip path,
    # not a side-door solver
    mesh_solver = None
    mesh_interp = False
    if mesh_n and ladder is not None:
        from ..kernels.window_kernel import pallas_needs_interpret
        from ..parallel.mesh import make_mesh, make_sharded_solver

        mesh_interp = cfg.use_pallas and pallas_needs_interpret()
        with tracer.span("mesh.build"):
            mesh_solver = make_sharded_solver(
                ladder, make_mesh(mesh_n), use_pallas=cfg.use_pallas,
                pallas_interpret=mesh_interp, batch=cfg.batch_size)
        solver = mesh_solver
        ev_log.log("mesh.init", nd=int(mesh_solver.nd),
                   devices=mesh_solver.describe(),
                   esc_cap=int(mesh_solver._esc_cap_for(cfg.batch_size)))
    fetch_many_fn = None
    native_dispatch = solver is None and cfg.native_solver
    # both votes AND both acceptance objectives are implemented in the C++
    # engine (r5: posterior tables are built python-side and passed in;
    # likelihood walk mirrored — all byte-identical by test), so hp_native
    # routes every hp configuration
    hp_use_native = cfg.hp_native
    if native_dispatch:
        from ..native import available as _nat_avail

        if not _nat_avail():
            raise SystemExit("--backend native: native library unavailable "
                             "(g++ build failed?)")
        # one construction path shared with the supervisor's failover engine
        # (_build_native_fallback): byte parity depends on the two never
        # diverging
        base_solve = _build_native_fallback(profile, cfg)
        ols, nt = base_solve.ols, base_solve.nt

        def _native_solver(b):
            out = base_solve(b)
            if cfg.consensus.hp_rescue and hp_use_native:
                # in-engine hp rescue (C++, oracle/hp.py parity): runs after
                # the overflow rescue, matching the host pass's ordering
                stats.n_hp_rescued += base_solve.nladder.hp_rescue(
                    b, out, n_threads=nt)
            return out

        solver = _native_solver
    # two-stream ladder (ISSUE 4): the local JAX ladder paths split — the
    # native engine already escalates per-window on host, and an opaque
    # custom solver brings its own programs. A solver that declares
    # ``routes_streams`` understands the stream tags and routes each batch
    # to the right program, so the split machinery runs for it too: the
    # serving plane's cross-job batcher (ISSUE 10) pools tier0 and rescue
    # rows separately, and the mesh solver dispatches the sharded tier0 /
    # full-ladder program per tag (:t0 and :m<N> compile keys compose).
    split_ladder = (cfg.ladder_mode == "split"
                    and ((solver is None and not native_dispatch)
                         or getattr(solver, "routes_streams", False)))
    if cfg.ladder_mode == "split" and not split_ladder:
        log.log("info", msg="ladder_mode=split inapplicable here "
                            "(native engine or custom solver); running fused")
    # a partial-width-capable solver (the cross-job batcher) pads/packs its
    # own MERGED batches: padding each job's flush here would ship dead rows
    # the batcher cannot reclaim for cohabiting jobs
    partial_dispatch = (solver is not None
                        and getattr(solver, "accepts_partial", False))
    # ragged paged window batching (kernels/paging.py, ISSUE 7): JAX ladder
    # paths only — the native engine iterates dense rows on host, and a
    # custom (mesh) solver brings its own programs. 'auto' enables paging on
    # device platforms only (the pre-decision-row default posture); explicit
    # 'on' also takes the async ladder on CPU so the whole fault/capacity
    # matrix can verify the paged path with no chip.
    paged_on = False
    if cfg.paged not in ("off", "on", "auto"):
        raise SystemExit(f"--paged {cfg.paged!r}: expected on|off|auto")
    if cfg.paged != "off":
        if (solver is not None
                and not getattr(solver, "supports_paged", False)) \
                or native_dispatch:
            log.log("info", msg=f"paged={cfg.paged} inapplicable here "
                                "(native engine or custom solver); "
                                "running dense")
        else:
            paged_on = paged_want
    families = None
    if paged_on:
        from ..kernels import paging

        if cfg.seg_len % cfg.page_len:
            raise SystemExit(f"--paged: page_len {cfg.page_len} must divide "
                             f"seg-len {cfg.seg_len}")
        with tracer.span("paging.derive"):
            if paged_sample is not None:
                # in-run profile estimation: families come from the SAME
                # window sample the profile pass already cut
                families = families_from_windows(paged_sample, cfg)
            elif report is not None and report.issues:
                families = derive_families_for_shard(
                    db, las, cfg, start, end, pile_ranges=report.pile_ranges)
            else:
                families = derive_families_for_shard(db, las, cfg, start, end)
        # a batch's pool must hold at least one worst-case window of its
        # family, or the router's budget cut could never make progress
        families = [
            f if cfg.batch_size * f.budget >= f.pages else
            paging.ShapeFamily(depth=f.depth, pages=f.pages,
                               page_len=f.page_len,
                               pool_pages=-(-f.pages // cfg.batch_size))
            for f in families]
        for fi, f in enumerate(families):
            ev_log.log("paging.family", family=f.describe(), bucket=fi,
                       depth=int(f.depth), pages=int(f.pages),
                       page_len=int(f.page_len), pool_pages=int(f.budget))
    clamp_solve = None   # governor esc-cap-clamp rung (JAX async ladder only)
    # saturation accounting (ISSUE 14): a synchronous engine solves INSIDE
    # the dispatch call (native ladder, host-routed solve_tiered, plain
    # callables), so its device-busy wall is the dispatch wall and its
    # "host blocked on device" includes it; an async engine's busy window is
    # the in-flight occupancy integral and only the fetch blocks the host
    sync_engine = False
    if solver is not None:
        if hasattr(solver, "dispatch") and hasattr(solver, "fetch"):
            # async solver (e.g. the mesh-sharded ladder): pipeline batches
            # through it exactly like the local single-device path
            dispatch_fn, fetch_fn = solver.dispatch, solver.fetch
            fetch_many_fn = getattr(solver, "fetch_many", None)
        else:
            dispatch_fn, fetch_fn = solver, (lambda h: h)
            sync_engine = True
        if mesh_solver is not None:
            # the mesh gets the full governor ladder: its clamp rung is the
            # single-device clamped program + host completion — byte-
            # identical by per-window independence, and a rung narrower
            # than one mesh slice has no sharded form anyway
            clamp_solve = _make_clamp_solve(ladder, cfg.use_pallas,
                                            mesh_interp, gov_cfg.esc_clamp)
    else:
        import jax

        if jax.default_backend() == "cpu" and not split_ladder and not paged_on:
            # host-routed ladder: skips escalation tiers when nothing failed
            # (cheap syncs; right trade-off for local CPU execution). Paged
            # batches always take the async ladder below — paging IS the
            # jitted wire format
            from ..kernels.tiers import solve_tiered

            if cfg.use_pallas:
                print("daccord: --pallas has no effect on the CPU host-routed "
                      "ladder (scan path used); use the tpu backend or --mesh",
                      file=sys.stderr)
            dispatch_fn, fetch_fn = (lambda b: solve_tiered(b, ladder)), (lambda h: h)
            sync_engine = True
        else:
            # async device ladder: one dispatch per batch, fetched a batch
            # later so host windowing overlaps device compute + tunnel RTT
            # (default esc_cap sizes escalation to the full batch: overflow
            # is structurally impossible). In split mode Stream A dispatches
            # the tier0-only program and Stream B (pool flushes, routed by
            # batch.stream) the full rescue ladder — the same jitted program
            # a fused dispatch uses, now only ever fed dense batches.
            from ..kernels.tiers import fetch as _fetch, solve_ladder_async

            from ..kernels.tiers import fetch_many as _fetch_many
            from ..kernels.window_kernel import pallas_needs_interpret

            interp = cfg.use_pallas and pallas_needs_interpret()
            if split_ladder:
                # the ONE stream-routing rule, shared with the serving
                # plane's cross-job batcher (kernels.tiers.stream_dispatcher)
                from ..kernels.tiers import stream_dispatcher

                dispatch_fn = stream_dispatcher(ladder,
                                                use_pallas=cfg.use_pallas,
                                                pallas_interpret=interp)
            else:
                dispatch_fn = (lambda b: solve_ladder_async(
                    b, ladder, use_pallas=cfg.use_pallas, pallas_interpret=interp))
            fetch_fn = _fetch
            fetch_many_fn = _fetch_many
            clamp_solve = _make_clamp_solve(ladder, cfg.use_pallas, interp,
                                            gov_cfg.esc_clamp)

    # device supervisor (runtime/supervisor.py): watchdog deadlines with
    # compiling-vs-wedged classification, retry with backoff, and mid-run
    # failover to the degraded engine — the robustness layer between the
    # pipeline and whichever dispatch/fetch pair was resolved above
    sup = None
    if cfg.supervise:
        from .supervisor import DeviceSupervisor, SupervisorConfig

        rtt_s = None
        inline = False
        if native_dispatch:
            # the primary IS the degraded engine: failover to itself keeps
            # byte parity trivially while fault injection still exercises
            # the full machinery
            prim = solver
            fallback_factory = (lambda: prim)
            desc, fp_prefix = "native-ladder", "native:"
            inline = True
        else:
            if solver is not None:
                d = getattr(solver, "describe", None)
                desc = d() if callable(d) else type(solver).__name__
                # a host-local mesh (forced host platform count) cannot
                # hang the way a tunnel can: run the supervisor inline,
                # same rule as the single-device cpu ladder below
                inline = bool(getattr(solver, "host_local", False))
                if mesh_solver is not None and not inline:
                    from ..utils.obs import measure_rtt_s

                    rtt_s = measure_rtt_s()
            else:
                import jax

                is_cpu = jax.default_backend() == "cpu"
                desc = ("cpu-ladder" if is_cpu else "device-ladder")
                if split_ladder:
                    desc += "-split"
                # a host-local ladder cannot hang the way a tunnel can;
                # skip the watchdog thread (its hand-off is the only
                # measurable supervisor cost on the hot path)
                inline = is_cpu
                if not is_cpu:
                    # RTT-scaled fetch deadline (the tunnel's fixed
                    # per-device_get cost is the natural time unit here)
                    from ..utils.obs import measure_rtt_s

                    rtt_s = measure_rtt_s()
            import jax

            fp_prefix = jax.default_backend() + ":"
            _lad = ladder

            def fallback_factory():
                import jax as _jax

                kind = cfg.failover_backend
                if kind == "auto":
                    # a cpu-platform primary keeps the SAME ladder (byte-
                    # exact degraded output, and the backend is by definition
                    # still usable); any device platform needs the native
                    # engine — the dead backend cannot be swapped for cpu
                    # in-process, so without the native library there is no
                    # usable fallback (raise a clear error, not a crash)
                    if _jax.default_backend() == "cpu":
                        kind = "cpu"
                    else:
                        try:
                            from ..native import available as _na

                            nat_ok = _na()
                        except Exception:
                            nat_ok = False
                        if not nat_ok:
                            raise RuntimeError(
                                "device lost and the native library is not "
                                "built: no usable degraded engine (the dead "
                                "device backend cannot be swapped for cpu "
                                "in-process)")
                        kind = "native"
                if kind == "native":
                    return _build_native_fallback(profile, cfg)
                # exact-ladder host fallback: the same TierLadder the
                # primary used, host-routed
                from ..kernels.tiers import solve_tiered as _st

                def _cpu_fb(b):
                    return _st(b, _lad)

                _cpu_fb.__name__ = "cpu-ladder"
                return _cpu_fb

            def _audit_factory():
                # audit reference: same bytes as the failover engine, but
                # where failover would hand back the host tiered ladder,
                # audit k-row samples on the fused single-dispatch program
                # instead — one XLA call per audit, not one per rescue tier
                eng = fallback_factory()
                if getattr(eng, "__name__", "") == "cpu-ladder":
                    from ..kernels.tiers import audit_reference

                    return audit_reference(_lad)
                return eng

        sup = DeviceSupervisor(
            dispatch_fn, fetch_fn, fetch_many_fn,
            fallback_factory=fallback_factory, log=ev_log,
            # --failback forces it on; otherwise DACCORD_SUP_FAILBACK decides
            cfg=SupervisorConfig.from_env(
                **({"failback": True} if cfg.failback else {})),
            faults=plan, rtt_s=rtt_s, describe=desc,
            fingerprint_prefix=fp_prefix, inline=inline,
            clamp_solve=clamp_solve, governor_cfg=gov_cfg, tracer=tracer,
            mesh=mesh_solver,
            # sampled shadow verification (ISSUE 20): the reference shares
            # bytes with the failover rung. Only the pipeline-built
            # primaries audit here — an injected serve JobSolver is audited
            # by the batcher's OWN supervisor, and a native primary's
            # reference would be itself (tautology)
            audit_ref_factory=(_audit_factory
                               if ((solver is None or mesh_solver is not None)
                                   and not native_dispatch) else None),
            audit_rate=cfg.audit_rate)
        dispatch_fn, fetch_fn = sup.dispatch, sup.fetch
        if fetch_many_fn is not None:
            fetch_many_fn = sup.fetch_many

    # ledger mesh column (ISSUE 13 satellite): rows record the solve path's
    # mesh width — an in-run mesh (cfg.mesh), a mesh-backed serve group
    # (the injected JobSolver carries its group's width as an int), or a
    # directly-injected sharded solver (whose `mesh` is the jax Mesh object;
    # its width is `nd`) — so the ROADMAP-4 router training set can segment
    # by mesh configuration. 0 (the non-mesh case) is omitted from the row
    # entirely: non-mesh ledgers stay byte-for-byte what they were.
    def _solver_mesh_width(s) -> int:
        if s is None:
            return 0
        m = getattr(s, "mesh", 0)
        if isinstance(m, int):
            return m
        return int(getattr(s, "nd", 0) or 0)

    ledger_mesh = mesh_n or _solver_mesh_width(solver)

    # opt-in jax.profiler capture (ISSUE 13): DACCORD_PROFILE_DIR captures a
    # device trace bracketing the Nth dispatch (DACCORD_PROFILE_DISPATCH,
    # default 2 — past the cold compile) through the drain that fetches it.
    # One capture per run, never on the native engine (no jax to trace, and
    # importing it there would init a backend the native path avoids).
    from ..utils.obs import env_float as _envf

    _prof_dir = os.environ.get("DACCORD_PROFILE_DIR")
    _prof = {"n": 0, "fetched": 0, "active": False,
             "done": not _prof_dir or native_dispatch,
             "at": max(1, int(_envf("DACCORD_PROFILE_DISPATCH", 2)))}

    def _prof_on_dispatch() -> None:
        if _prof["done"] or _prof["active"]:
            return
        _prof["n"] += 1
        if _prof["n"] < _prof["at"]:
            return
        try:
            import jax

            os.makedirs(_prof_dir, exist_ok=True)
            jax.profiler.start_trace(_prof_dir)
            _prof["active"] = True
            ev_log.log("profile.capture", dir=_prof_dir,
                       dispatch=_prof["n"], state="start")
        except Exception as e:   # profiling must never sink a run
            log.log("warn", msg=f"profiler start failed: {e}")
            _prof["done"] = True

    def _prof_on_drain(n_fetched: int = 0, force: bool = False) -> None:
        # fetches pop FIFO, so the profiled dispatch (the at-th) is the
        # at-th fetched entry — stop only at the drain that fetches IT,
        # not the first drain after start (with >=2 batches in flight
        # those differ and the capture would miss the profiled fetch)
        _prof["fetched"] += n_fetched
        if not _prof["active"] or (not force
                                   and _prof["fetched"] < _prof["at"]):
            return
        _prof["active"] = False
        _prof["done"] = True
        try:
            import jax

            jax.profiler.stop_trace()
            ev_log.log("profile.capture", dir=_prof_dir,
                       dispatch=_prof["n"], state="stop")
        except Exception as e:
            log.log("warn", msg=f"profiler stop failed: {e}")

    # an aborted run must still stop an in-flight capture (the trace file
    # would otherwise be left torn); the telemetry bundle's finally runs it
    tel.prof_stop = lambda: _prof_on_drain(force=True)

    hp_ols = None
    hp_nladder = None
    hp_nt = cfg.native_threads if cfg.native_threads > 0 else (
        os.cpu_count() or 1)
    if cfg.consensus.hp_rescue:
        # homopolymer rescue (oracle/hp.py) is a host-side post-pass over any
        # engine's per-window err; the C++ engine runs it when available
        # (bit-identical by test, ~20x the python loop) — for the DEVICE
        # ladder path too, where the python loop would dominate the drain
        if native_dispatch:
            hp_ols = None if hp_use_native else ols
        else:
            # a warm-state solver (serve batcher) shares its group's
            # OffsetLikely tables across jobs (read-only) — rebuilding them
            # per job would re-spend the cold start the warm group
            # amortizes
            hp_ols = (getattr(solver, "hp_ols", None)
                      if solver is not None else None)
            if hp_ols is None:
                from ..oracle.consensus import make_offset_likely

                hp_ols = make_offset_likely(profile, cfg.consensus)
            if hp_use_native:
                try:
                    from ..native import available as _nat_avail
                    from ..native.api import NativeLadder as _NL

                    if _nat_avail():
                        hp_nladder = _NL(hp_ols, cfg.consensus,
                                         max_kmers=cfg.max_kmers,
                                         rescue_max_kmers=cfg.rescue_max_kmers)
                except Exception:
                    hp_nladder = None

    try:
        from ..native import available as native_available
        native_ok = cfg.use_native and native_available()
    except Exception:
        native_ok = False
    stats.native_host = native_ok

    D, L = cfg.depth, cfg.seg_len
    adv = cfg.consensus.adv
    w = cfg.consensus.w
    if paged_on:
        # paged mode: the corpus-derived shape families ARE the buckets —
        # they subsume the hand-tuned depth/seg-len grids (windows route by
        # (nsegs, pages); L stays global, page rounding absorbs length)
        buckets = [(f.depth, L) for f in families]
        shapes = [BatchShape(depth=f.depth, seg_len=L, wlen=w)
                  for f in families]
        d_arr = l_arr = None
        nl = 1
        # per-family pool capacity of one batch-size-wide dispatch (pages):
        # the router cuts a batch early rather than overflow it
        cap_pages = [cfg.batch_size * f.budget for f in families]
    else:
        # depth (and optional seg-len) buckets: windows route to the smallest
        # bucket holding their segment count / max segment length; each (D, L)
        # bucket is its own statically-shaped batch stream
        d_buckets = sorted({b for b in cfg.depth_buckets if 0 < b < D} | {D})
        l_buckets = sorted({b for b in cfg.seg_len_buckets if 0 < b < L} | {L})
        buckets = [(dv, lv) for dv in d_buckets for lv in l_buckets]
        shapes = [BatchShape(depth=db, seg_len=lb, wlen=w) for db, lb in buckets]
        d_arr = np.asarray(d_buckets)
        l_arr = np.asarray(l_buckets)
        nl = len(l_buckets)
        cap_pages = None

    pending: dict[int, _PendingRead] = {}
    order: list[int] = []
    ready: dict[int, list[np.ndarray]] = {}
    emit_idx = 0
    # per-bucket row buffers: parallel lists of blocks + (rid, widx) bookkeeping
    nb = len(buckets)
    blk_seqs: list[list[np.ndarray]] = [[] for _ in range(nb)]
    blk_lens: list[list[np.ndarray]] = [[] for _ in range(nb)]
    blk_nsegs: list[list[np.ndarray]] = [[] for _ in range(nb)]
    blk_rid: list[list[np.ndarray]] = [[] for _ in range(nb)]
    blk_widx: list[list[np.ndarray]] = [[] for _ in range(nb)]
    # paged mode only: per-row page counts + running totals, so the router
    # can cut a batch at the family's pool budget (and trigger a flush when
    # the buffered pages alone would fill a pool)
    blk_pages: list[list[np.ndarray]] = [[] for _ in range(nb)]
    npages = [0] * nb
    nrows = [0] * nb
    first_seen = [None] * nb     # read counter when the bucket got its oldest row

    from collections import deque

    # (handle, rid, widx, take, t_dispatch, rows_ctx, bucket, stream) —
    # rows_ctx retains the dispatched (seqs, lens, nsegs) so the hp pass can
    # reconstruct segments and the split ladder can pool rescue rows (the
    # supervisor's handles already retain the whole batch for replay, so
    # this costs nothing extra under the default supervised config)
    inflight: deque = deque()

    # device-occupancy integral + dispatch wall (saturation profiler,
    # ISSUE 14). Async engines: `t0` opens when a dispatch finds the
    # in-flight window empty and closes at the drain that empties it again —
    # busy_s integrates "the device has work". Sync engines solve inside the
    # dispatch call, so busy_s accrues the dispatch wall directly and t0
    # stays unused. All dispatch/drain happens on the pipeline thread, so no
    # lock is needed.
    dev = {"busy_s": 0.0, "t0": None, "dispatch_s": 0.0}

    def timed_dispatch(batch):
        t_d = time.time()
        if not sync_engine and dev["t0"] is None:
            dev["t0"] = t_d
        handle = dispatch_fn(batch)
        dt = time.time() - t_d
        dev["dispatch_s"] += dt
        if sync_engine:
            dev["busy_s"] += dt
        return handle

    # Async double-buffered dispatch pipeline (ISSUE 19): with a staged-
    # dispatch mesh solver, batch N+1's pad/shard/H2D transfer runs on the
    # _Stager daemon thread while batch N solves; the pipeline thread only
    # launches finished stages. DACCORD_MESH_PIPELINE=0 opts out (the
    # unpipelined path is the byte-parity control). The supervisor unwraps
    # a StagedBatch to its retained host batch for every replay path, so
    # the fault matrix is unchanged by pipelining.
    stager = None
    if (mesh_solver is not None and hasattr(mesh_solver, "stage")
            and os.environ.get("DACCORD_MESH_PIPELINE", "1") != "0"):
        stager = _Stager(mesh_solver.stage, prof=tel.stage)
        ev_log.log("dispatch.pipeline", depth=2, solver=mesh_solver.describe())
    staged_pending: deque = deque()

    def _launch_staged(block: bool = False):
        # launch staged tickets FIFO. A head still staging only blocks the
        # launcher when the device would otherwise idle (empty in-flight
        # window) or the caller needs the buffer drained (block=True) —
        # otherwise the stage keeps overlapping the in-flight solve.
        while staged_pending:
            t = staged_pending[0]
            if not t.done.is_set() and not block and inflight:
                break
            t.done.wait()
            staged_pending.popleft()
            rid, widx, take, rows_ctx, bi, stream, b_sp = t.meta
            if t.error is None and t.staged is not None:
                # emitted HERE (not on the staging thread) so the events
                # sidecar keeps one monotonic writer; the walls were
                # measured on the staging thread and ride the StagedBatch
                ev_log.log("dispatch.stage", rows=int(take),
                           pack_s=round(t.staged.pack_s, 4),
                           stage_s=round(t.staged.stage_s, 4))
            l_sp = tracer.open("dispatch.launch", parent=b_sp, attach=False,
                               rows=int(take))
            t_l = time.time()
            _prof_on_dispatch()
            if t.error is not None or t.staged is None:
                # staging failed host-side: dispatch the retained host batch
                # directly — the supervisor ladder takes it from here
                handle = timed_dispatch(t.batch)
            else:
                handle = timed_dispatch(t.staged)
            tracer.close(l_sp)
            ev_log.log("dispatch.launch", rows=int(take),
                       launch_s=round(time.time() - t_l, 4))
            metrics.counter("dispatches").inc()
            inflight.append((handle, rid, widx, take, time.time(),
                             rows_ctx, bi, stream, b_sp))
            if len(inflight) >= cfg.max_inflight:
                drain(cfg.max_inflight // 2)

    def submit_batch(batch, rid, widx, take, rows_ctx, bi, stream, b_sp):
        """The ONE dispatch seam both streams use: direct (unpipelined) or
        staged through the double buffer. Keeps the dispatch span/stage
        accounting rules in one place."""
        if stager is None:
            d_sp = tracer.open("dispatch", parent=b_sp, stream=stream)
            _prof_on_dispatch()
            handle = timed_dispatch(batch)
            tracer.close(d_sp)
            metrics.counter("dispatches").inc()
            inflight.append((handle, rid, widx, take, time.time(),
                             rows_ctx, bi, stream, b_sp))
            if len(inflight) >= cfg.max_inflight:
                drain(cfg.max_inflight // 2)
            return
        _launch_staged()
        staged_pending.append(stager.submit(
            batch, (rid, widx, take, rows_ctx, bi, stream, b_sp)))
        _launch_staged()

    # split-ladder rescue pools, one per bucket shape (Stream B inputs):
    # tier-0 failures and top-M-overflow windows accumulate here until a
    # full dense batch (or the flush deadline / final drain) dispatches them
    r_seqs: list[list[np.ndarray]] = [[] for _ in range(nb)]
    r_lens: list[list[np.ndarray]] = [[] for _ in range(nb)]
    r_nsegs: list[list[np.ndarray]] = [[] for _ in range(nb)]
    r_rid: list[list[np.ndarray]] = [[] for _ in range(nb)]
    r_widx: list[list[np.ndarray]] = [[] for _ in range(nb)]
    r_pages: list[list[np.ndarray]] = [[] for _ in range(nb)]
    r_npages = [0] * nb
    r_nrows = [0] * nb
    r_first_seen = [None] * nb   # read counter when the pool got its oldest row

    # rescue tiers = frequency filter effectively off (min_count <= 1);
    # their end-of-read solutions get trimmed (see PipelineConfig.end_trim).
    # In patch mode unsolved windows are refilled with RAW bases — strictly
    # worse than any rescue consensus — so trimming only applies to split mode
    rescue_tiers = ({i for i, t in enumerate(cfg.consensus.tiers) if t[1] <= 1}
                    if cfg.end_trim and cfg.consensus.mode != "patch" else set())

    def finalize_read(r: int, pr: _PendingRead):
        if rescue_tiers:
            _trim_rescue_ends(pr, rescue_tiers, stats)
        rows = [x for x in pr.results if x is not None]
        ready[r] = stitch_results(pr.a_bases, rows, cfg.consensus)
        del pending[r]

    def emit_ready():
        # in-order drain of finished reads — the one emission/accounting
        # path shared by the main loop and the quarantine-marker branch
        nonlocal emit_idx
        while emit_idx < len(order) and order[emit_idx] in ready:
            r = order[emit_idx]
            frags = ready.pop(r)
            stats.n_fragments += len(frags)
            stats.bases_out += sum(len(f) for f in frags)
            # keep wall_s live so mid-stream consumers (progress reporters)
            # see real bases_per_sec(), not 0 until exhaustion
            stats.wall_s = time.time() - t_start
            yield r, frags, stats
            emit_idx += 1

    def hp_pass(out, hp_ctx, take, skip=None) -> dict[int, np.ndarray]:
        """Homopolymer rescue over one fetched batch (oracle/hp.py).

        Routes windows that failed or solved with err > hp_err through the
        run-length-compressed solver; accepted candidates override the
        result row (their sequence may exceed the packed cons capacity, so
        they travel as a side dict consumed by scatter). ``skip`` masks rows
        whose ladder result is NOT final yet — split-mode Stream A rows
        headed for the rescue pool; hp runs on them when their Stream B
        result lands, exactly where the fused ladder would have run it."""
        from ..oracle.hp import HP_TIER, hp_candidate

        seqs_b, lens_b, nsegs_b = hp_ctx
        if skip is not None:
            # masked rows drop below min_depth (nseg 0), which both engines
            # treat as "no candidate" — alignment of rows to `out` indices
            # is preserved for the writeback scan
            nsegs_b = np.where(skip, 0, nsegs_b[:take])
        ccfg = cfg.consensus
        overrides: dict[int, np.ndarray] = {}
        if hp_nladder is not None:
            # C++ engine pass (bit-identical to the python loop below by
            # test). The fetched result arrays can be strided views over the
            # packed wire array OR already-contiguous solver outputs — in
            # the latter case np.array copies would alias via
            # ascontiguousarray, so force copies: rescued rows are
            # identified by tier == HP_TIER after the call (safe: the
            # ladder can never reach HP_TIER — ConsensusConfig rejects
            # that depth) and written back explicitly (their sequence
            # travels via the override dict; the row's in-array cons
            # stays the direct result)
            from types import SimpleNamespace

            shim = SimpleNamespace(seqs=seqs_b[:take], lens=lens_b[:take],
                                   nsegs=nsegs_b[:take])
            sub = {"cons": np.array(out["cons"][:take], dtype=np.int8),
                   "cons_len": np.array(out["cons_len"][:take],
                                        dtype=np.int32),
                   "err": np.array(out["err"][:take], dtype=np.float32),
                   "tier": np.array(out["tier"][:take], dtype=np.int32)}
            n = hp_nladder.hp_rescue(shim, sub, n_threads=hp_nt)
            if n:
                stats.n_hp_rescued += n
                for i in np.nonzero(sub["tier"] == HP_TIER)[0]:
                    i = int(i)
                    cl = int(sub["cons_len"][i])
                    overrides[i] = sub["cons"][i][:cl].copy()
                    out["err"][i] = sub["err"][i]
                    out["solved"][i] = True
                    out["tier"][i] = HP_TIER
            return overrides
        for i in range(take):
            nseg = int(nsegs_b[i])
            if nseg < min_depth:
                continue
            solved = bool(out["solved"][i])
            derr = float(out["err"][i]) if solved else float("inf")
            if solved and derr <= ccfg.hp_err:
                continue   # fast path; hp_candidate re-checks
            dseq = (np.asarray(out["cons"][i][: out["cons_len"][i]],
                               dtype=np.int8) if solved else None)
            segs = [np.asarray(seqs_b[i, d, : lens_b[i, d]], dtype=np.int8)
                    for d in range(nseg)]
            res = hp_candidate(segs, dseq, derr, hp_ols, ccfg)
            if res is None:
                continue
            overrides[i] = res.seq
            out["err"][i] = res.err
            out["solved"][i] = True
            out["tier"][i] = HP_TIER
            stats.n_hp_rescued += 1
        return overrides

    # tier index -> k of the solving tier (ledger rows record both; out-of-
    # range tiers — hp rescue, unsolved — map to -1)
    tier_ks = [tt[0] for tt in cfg.consensus.tiers]

    def scatter(out, rid, widx, take, hp_over=None, keep=None,
                nsegs_b=None, stream="full", wall=0.0):
        """Scatter one fetched batch's rows into their pending reads.
        ``keep`` (split mode) masks out rows whose windows went to the
        rescue pool instead — they scatter exactly once, when their Stream B
        result lands, so per-window accounting never double-counts (and the
        outcome ledger gets exactly one row per window). ``nsegs_b``/
        ``stream``/``wall`` carry the ledger row context: depth column,
        stream tag, and the batch's dispatch→scatter turnaround."""
        n_batch_solved = 0
        if "m_ovf" in out:
            mv = np.asarray(out["m_ovf"][:take])
            stats.n_topm_overflow += int(np.sum(mv if keep is None
                                                else mv[keep]))
        for i in range(take):
            if keep is not None and not keep[i]:
                continue
            r = int(rid[i])
            pr = pending[r]
            if hp_over is not None and i in hp_over:
                seq = hp_over[i]
            else:
                seq = (np.asarray(out["cons"][i][: out["cons_len"][i]],
                                  dtype=np.int8)
                       if out["solved"][i] else None)
            wj = int(widx[i])
            pr.results[wj] = (wj * adv, w, seq)
            pr.n_done += 1
            solved_i = bool(out["solved"][i])
            t = int(out["tier"][i]) if solved_i else -1
            if solved_i:
                stats.n_solved += 1
                n_batch_solved += 1
                pr.tiers[wj] = t
                stats.tier_histogram[t] = stats.tier_histogram.get(t, 0) + 1
            if ledger is not None:
                ledger.record(
                    r, wj, w,
                    int(nsegs_b[i]) if nsegs_b is not None else -1,
                    t, tier_ks[t] if 0 <= t < len(tier_ks) else -1,
                    solved_i, stream,
                    # rescue membership: the window rode a rescue lane —
                    # a Stream B dispatch in split mode, or (fused) any
                    # escalation-tier solve
                    rescued=(stream == "rescue" or t >= 1), wall_s=wall,
                    job=cfg.job_tag, mesh=ledger_mesh)
            if pr.n_done == pr.n_windows:
                finalize_read(r, pr)
        return n_batch_solved

    def _pop_rows(pools, counts, seen, bi: int, take: int):
        """Concatenate bucket ``bi``'s buffered row arrays, requeue the
        remainder past ``take``, and maintain the count + oldest-row stamp —
        the ONE buffer-pop shared by the window buckets (run_batches) and
        the rescue pools (flush_rescues), so their leftover/stale rules
        cannot drift apart. Leftover rows keep the pre-pop stamp
        (conservative: may flush early, never lets a row wait past its
        deadline)."""
        arrs = []
        for lst in pools:
            a = np.concatenate(lst[bi]) if len(lst[bi]) > 1 else lst[bi][0]
            lst[bi].clear()
            arrs.append(a)
        n = len(arrs[2])     # nsegs column carries the row count
        if n > take:
            for lst, a in zip(pools, arrs):
                lst[bi].append(a[take:])
        counts[bi] = n - take
        if not counts[bi]:
            seen[bi] = None
        return arrs

    def _pool_rescue(bi: int, rows_ctx, sel, rid, widx) -> None:
        """Append the selected rows of a fetched Stream A batch to bucket
        ``bi``'s rescue pool (Stream B input)."""
        seqs_b, lens_b, nsegs_b = rows_ctx
        r_seqs[bi].append(seqs_b[sel])
        r_lens[bi].append(lens_b[sel])
        r_nsegs[bi].append(nsegs_b[sel])
        r_rid[bi].append(rid[sel])
        r_widx[bi].append(widx[sel])
        if paged_on:
            from ..kernels import paging

            pgs = paging.window_pages(lens_b[sel], cfg.page_len)
            r_pages[bi].append(pgs)
            r_npages[bi] += int(pgs.sum())
        r_nrows[bi] += len(sel)
        if r_first_seen[bi] is None:
            r_first_seen[bi] = stats.n_reads

    def _paged_take(pages_lists, bi: int, take: int) -> int:
        """Rows of bucket ``bi``'s buffer that fit one pool budget: the
        largest prefix (never zero) whose page total stays within the
        family's per-dispatch capacity — the router-side guarantee behind
        pack_paged's overflow assertion."""
        cat = (np.concatenate(pages_lists[bi]) if len(pages_lists[bi]) > 1
               else pages_lists[bi][0])
        fit = int(np.searchsorted(np.cumsum(cat[:take]), cap_pages[bi],
                                  side="right"))
        return max(min(take, fit), 1)

    def _finish_batch(batch: WindowBatch, bi: int, pages_popped: int):
        """Shared tail of batch assembly: pad (dense) or pack (paged) to the
        dispatch width, account pad-waste cells, and return the dispatchable
        batch plus its rows_ctx (dense host-side arrays the hp pass and the
        rescue pool reconstruct segments from)."""
        if paged_on:
            from ..kernels import paging

            dense_seqs = batch.seqs
            pb = paging.pack_paged(batch, families[bi],
                                   target_rows=cfg.batch_size,
                                   prof=tel.stage)
            # payload-cell accounting, symmetric with the dense metric
            # (which counts seqs only — never lens/nsegs metadata); the
            # table's byte cost is reported on the batch.paged event
            stats.pad_cells += int(pb.pool.size)
            stats.used_cells += int(pb.lens.sum())
            ev_log.log("batch.paged", windows=int(batch.size), bucket=bi,
                       family=families[bi].describe(),
                       pages=int(pages_popped),
                       pool_pages=int(pb.pool.shape[0] - 1),
                       table_cells=int(pb.table.size) * 4,
                       occupancy=round(pages_popped
                                       / max(pb.pool.shape[0] - 1, 1), 4))
            return pb, (dense_seqs, pb.lens, pb.nsegs)
        if not native_dispatch and not partial_dispatch:
            # padding exists only for jit static shapes; the native engine
            # iterates real rows and would just walk PAD, and a
            # partial-capable solver (serve batcher) pads its own merged
            # batches after pooling rows across jobs
            batch = pad_batch(batch, cfg.batch_size, prof=tel.stage)
        stats.pad_cells += batch.seqs.size
        stats.used_cells += int(batch.lens.sum())
        return batch, (batch.seqs, batch.lens, batch.nsegs)

    def drain(to_depth: int):
        # drain in ONE grouped fetch: the tunnel charges its ~100 ms RTT per
        # device_get CALL, not per array, so fetching k batches together
        # divides the per-batch fetch floor by k (see kernels.tiers.fetch_many)
        n_pop = len(inflight) - to_depth
        if n_pop <= 0:
            return
        entries = [inflight.popleft() for _ in range(n_pop)]
        t_f = time.time()
        # the device.fetch span wraps EXACTLY the region the device_s timer
        # measures, so daccord-trace's device-stage sum reconciles with
        # stats.device_s by construction
        f_sp = tracer.open("device.fetch", n=len(entries))
        if fetch_many_fn is not None and len(entries) > 1:
            outs = fetch_many_fn([e[0] for e in entries])
        else:
            outs = [fetch_fn(e[0]) for e in entries]
        now = time.time()
        tracer.close(f_sp)
        _prof_on_drain(len(entries))
        # device_s = time the host actually BLOCKED on the device/tunnel
        # (in-flight batches overlap, so summing dispatch->fetch spans
        # would double-count and can exceed wall time)
        stats.device_s += now - t_f
        if not inflight and dev["t0"] is not None:
            # the in-flight window just emptied: close the device-busy
            # occupancy interval (saturation gauges)
            dev["busy_s"] += now - dev["t0"]
            dev["t0"] = None
        metrics.counter("fetch_calls").inc()
        for (handle, rid, widx, take, t0, rows_ctx, bi, stream, b_sp), out \
                in zip(entries, outs):
            metrics.histogram("batch_turnaround_s").observe(now - t0)
            keep = pool_mask = None
            if split_ladder and stream == "tier0":
                # pool-membership rule shared with the kernel-level unit
                # (kernels.tiers.rescue_candidates): rows the fused ladder
                # would have rescued defer to Stream B; the rest are final.
                # A supervisor-degraded entry carries FULL results here —
                # still correct: its pooled rows re-solve to the same bytes
                from ..kernels.tiers import rescue_candidates

                # out arrays carry the PADDED batch length; pad rows have
                # nsegs 0 so they can never be candidates — slice to live
                need = rescue_candidates(out, rows_ctx[2], ladder)[:take]
                if need.any():
                    _pool_rescue(bi, rows_ctx, np.nonzero(need)[0], rid, widx)
                    keep, pool_mask = ~need, need
            elif not split_ladder and ladder is not None and "m_ovf" in out:
                # fused-mode comparator for the split decision row: ANY
                # rescue candidate means the lax.cond ran the rescue lanes
                # at full esc_cap (= padded batch) width. Candidates are
                # reconstructed post-hoc from FINAL results — escalation-
                # solved windows show tier >= 1, still-failed deep windows
                # show unsolved — so only a tier-0 failure the wide rescue
                # solved is missed: a slight undercount, never an overcount
                deep = rows_ctx[2][:take] >= min_depth
                tierv = np.asarray(out["tier"][:take])
                need_f = (tierv >= 1) | (~np.asarray(out["solved"][:take])
                                         & deep)
                if ladder.wide_p0 is not None:
                    need_f |= np.asarray(out["m_ovf"][:take]) & deep
                n_need = int(np.sum(need_f))
                if n_need:
                    stats.n_rescue_windows += n_need
                    stats.rescue_slots_executed += len(rows_ctx[2])
            if hp_ols is not None:
                t_hp = time.time()
                hp_sp = tracer.open("hp", parent=b_sp, attach=False)
                hp_over = hp_pass(out, rows_ctx, take, skip=pool_mask)
                tracer.close(hp_sp)
                stats.hp_wall_s += time.time() - t_hp
            else:
                hp_over = None
            n_s = scatter(out, rid, widx, take, hp_over, keep,
                          nsegs_b=rows_ctx[2], stream=stream, wall=now - t0)
            tracer.close(b_sp, windows=take, solved=n_s)
            log.log("batch", windows=take, solved=n_s, stream=stream,
                    overflow=int(out.get("esc_overflow", 0)),
                    # live rescue-pool gauge: lets a log reader (and the
                    # checkpoint/resume test) see pooled rows pending at any
                    # point in the run
                    pool=int(sum(r_nrows)) if split_ladder else 0,
                    inflight=len(inflight), t_turnaround=round(now - t0, 4))

    def flush_rescues(final: bool, pressure: bool = False):
        """Dispatch Stream B: drain each bucket's rescue pool as DENSE
        full-ladder batches. A pool flushes when it holds a full batch, when
        its oldest row has waited ``rescue_flush_reads`` reads (the
        bucket_flush_reads rule applied to Stream B — bounds the in-order
        emission lag a pooled window can add), at final drain, or under a
        host-watermark force-flush (``pressure`` — its own reason, so flush
        analyses keyed on 'final' see only the real end-of-shard drain)."""
        if not split_ladder:
            return
        for bi in range(nb):
            stale = (r_first_seen[bi] is not None
                     and stats.n_reads - r_first_seen[bi] >= cfg.rescue_flush_reads)
            while (r_nrows[bi] >= cfg.batch_size
                   or (paged_on and r_npages[bi] >= cap_pages[bi])
                   or ((final or stale) and r_nrows[bi] > 0)):
                full = (r_nrows[bi] >= cfg.batch_size
                        or (paged_on and r_npages[bi] >= cap_pages[bi]))
                reason = ("full" if full
                          else ("pressure" if pressure
                                else ("final" if final else "lag")))
                stale = False
                take = min(cfg.batch_size, r_nrows[bi])
                if paged_on:
                    take = _paged_take(r_pages, bi, take)
                fl_sp = tracer.open("flush", reason=reason, rows=take,
                                    bucket=bi)
                pools = (r_seqs, r_lens, r_nsegs, r_rid, r_widx) + (
                    (r_pages,) if paged_on else ())
                arrs = _pop_rows(pools, r_nrows, r_first_seen, bi, take)
                seqs, lens, nsg, rid, widx = arrs[:5]
                pages_popped = 0
                if paged_on:
                    pages_popped = int(arrs[5][:take].sum())
                    r_npages[bi] -= pages_popped
                batch = WindowBatch(seqs=seqs[:take], lens=lens[:take],
                                    nsegs=nsg[:take], shape=shapes[bi],
                                    read_ids=rid[:take],
                                    wstarts=widx[:take].astype(np.int64) * adv,
                                    stream="rescue", job=cfg.job_tag or "")
                batch, rows_ctx = _finish_batch(batch, bi, pages_popped)
                # the flush span covers the pool pop + pad/pack only: the
                # dispatch below books under the dispatch stage, and the
                # two stages must stay disjoint or daccord-trace's stage
                # table double-counts the (synchronous, on inline engines)
                # solve wall
                tracer.close(fl_sp)
                b_sp = tracer.open("batch", attach=False, stream="rescue",
                                   rows=take, bucket=bi)
                metrics.histogram("flush_rows").observe(take)
                stats.n_dispatch_rescue += 1
                stats.n_rescue_windows += take
                stats.rescue_slots_executed += batch.size
                stats.rescue_dispatches.append(
                    {"rows": take, "slots": int(batch.size), "reason": reason})
                ev_log.log("ladder.flush", rows=take, slots=int(batch.size),
                           reason=reason, bucket=bi)
                submit_batch(batch, rid, widx, take, rows_ctx, bi, "rescue",
                             b_sp)

    def run_batches(final: bool, drain_inflight: bool | None = None,
                    pressure: bool = False):
        # drain_inflight=False is the soft-watermark flush: partial buckets
        # and rescue pools force through the device, but the in-flight
        # window keeps pipelining (hard pressure drains it too)
        if drain_inflight is None:
            drain_inflight = final
        for bi in range(nb):
            # partial flush once the bucket's oldest row has waited too long:
            # bounds the in-order emission lag under bucket skew
            stale = (first_seen[bi] is not None
                     and stats.n_reads - first_seen[bi] >= cfg.bucket_flush_reads)
            while (nrows[bi] >= cfg.batch_size
                   or (paged_on and npages[bi] >= cap_pages[bi])
                   or ((final or stale) and nrows[bi] > 0)):
                stale = False
                take = min(cfg.batch_size, nrows[bi])
                if paged_on:
                    take = _paged_take(blk_pages, bi, take)
                pools = (blk_seqs, blk_lens, blk_nsegs, blk_rid, blk_widx) + (
                    (blk_pages,) if paged_on else ())
                arrs = _pop_rows(pools, nrows, first_seen, bi, take)
                seqs, lens, nsg, rid, widx = arrs[:5]
                pages_popped = 0
                if paged_on:
                    pages_popped = int(arrs[5][:take].sum())
                    npages[bi] -= pages_popped
                batch = WindowBatch(seqs=seqs[:take], lens=lens[:take], nsegs=nsg[:take],
                                    shape=shapes[bi], read_ids=rid[:take],
                                    wstarts=widx[:take].astype(np.int64) * adv,
                                    stream="tier0" if split_ladder else "full",
                                    job=cfg.job_tag or "")
                batch, rows_ctx = _finish_batch(batch, bi, pages_popped)
                b_sp = tracer.open("batch", attach=False, stream=batch.stream,
                                   rows=take, bucket=bi)
                if split_ladder:
                    stats.n_dispatch_tier0 += 1
                # hp rescue reconstructs segments, and the split ladder pools
                # rescue rows, from the dispatched rows_ctx arrays — kept
                # alive until the fetch (the supervisor's replay handles
                # retain the whole batch anyway). submit_batch lets the
                # in-flight window FILL, then drains half of it in one
                # grouped fetch — steady state pays one tunnel RTT per
                # max_inflight/2 batches instead of one per batch
                submit_batch(batch, rid, widx, take, rows_ctx, bi,
                             batch.stream, b_sp)
        flush_rescues(final, pressure)
        if drain_inflight:
            _launch_staged(block=True)
            drain(0)
            # draining Stream A pools fresh rescue rows; alternate flush and
            # drain until both are empty (Stream B results never pool, so
            # this terminates after at most one extra round)
            while inflight or staged_pending or (split_ladder and any(r_nrows)):
                flush_rescues(True, pressure)
                _launch_staged(block=True)
                drain(0)

    stats.paged = paged_on
    qvr = load_qv_ranker(db, las, cfg)
    stats.qv_ranked = qvr is not None
    if cfg.qv_track and qvr is None:
        log.log("info", msg=f"qv track '{cfg.qv_track}' absent: "
                            "trace-diff depth ranking only")
    min_depth = cfg.consensus.dbg.min_depth

    t_host0 = time.time()
    if cfg.feeder_threads > 0 and not native_ok:
        print("daccord-tpu: feeder_threads ignored (native host path "
              "unavailable or disabled)", file=sys.stderr)
        log.log("warn", msg="feeder_threads ignored: no native host path")
    # the stage profile records the ACTUAL feeder pool width (prof --check
    # scales its reconciliation by it: thread-summed stage walls legitimately
    # exceed the pipeline-visible feeder wall under a pool)
    tel.stage.threads = (cfg.feeder_threads
                         if native_ok and cfg.feeder_threads > 0 else 1)

    def monster_guard(aread, n_overlaps) -> bool:
        """Capacity governor's monster-pile budget, consulted once per pile
        BEFORE the quadratic windowing/realignment spend (the memory that
        actually kills a worker on an ultra-deep repeat pile). True = bust:
        the pile is contained through the quarantine machinery instead."""
        injected = plan is not None and plan.monster_check()
        budget = cfg.max_pile_overlaps
        if not injected and not (budget and n_overlaps > budget):
            return False
        stats.n_monster_piles += 1
        ev_log.log("governor.monster", aread=int(aread),
                   overlaps=int(n_overlaps), budget=int(budget or 0),
                   injected=injected)
        return True

    def _block_iter(s, e):
        if native_ok and cfg.feeder_threads > 0:
            return _iter_pile_blocks_threaded(db, las, cfg, s, e,
                                              cfg.feeder_threads, qvr,
                                              monster=monster_guard,
                                              prof=tel.stage)
        return _iter_pile_blocks(db, las, cfg, s, e, native_ok, qvr,
                                 monster=monster_guard, prof=tel.stage)

    qfh = None

    def _q_record(**rec):
        # quarantine sidecar: one jsonl row per contained pile, created
        # lazily so clean runs never leave an empty sidecar behind
        nonlocal qfh
        if cfg.quarantine_path is None:
            return
        import json as _json

        if qfh is None:
            qfh = open(cfg.quarantine_path, "at")
        qfh.write(_json.dumps(rec) + "\n")
        qfh.flush()

    if report is not None and report.issues:
        # quarantine plan: clean byte segments stream through the fast
        # decoders exactly as before; each contained pile rides along as a
        # marker in byte order so emission order is preserved. Known trade:
        # the threaded feeder pool restarts per clean segment — scattered
        # corruption costs feeder pipelining, but only on damaged inputs
        # (clean runs take the single-segment path below)
        def _segmented():
            for seg in report.segments:
                if seg[0] == "clean":
                    yield from _block_iter(seg[1], seg[2])
                else:
                    yield seg

        blocks = _segmented()
    else:
        blocks = _block_iter(start, end)

    # pipeline-visible feeder wall (saturation profiler): what the pile loop
    # actually BLOCKED on the feeder iterator — under a threaded feeder this
    # is smaller than the thread-summed stage walls, and it is the anchor
    # daccord-prof reconciles the sub-stage table against
    feeder_wall = [0.0]
    # injected feeder slowdown (DACCORD_FAULT=feeder_stall:MS, ISSUE 14):
    # the A/B lever that flips the verdict to host_feeder — booked under
    # the profiler's `stall` stage so the attribution names it honestly
    stall_s = (plan.feeder_stall_ms() if plan is not None else 0.0) / 1e3
    if stall_s:
        ev_log.log("sup_fault", kind="feeder_stall", op="feeder",
                   n=int(stall_s * 1e3))

    def _timed_blocks():
        # feeder spans bracket the host windowing wall per pile block (the
        # block generator's __next__ — decode, k-mer extraction,
        # tensorization); the previous pile span is closed by then, so
        # these parent under the run span
        it = iter(blocks)
        while True:
            f_sp = tracer.open("feeder")
            t_f0 = time.perf_counter()
            try:
                blk = next(it)
            except StopIteration:
                tracer.close(f_sp, status="end")
                return
            if stall_s:
                time.sleep(stall_s)
                tel.stage.add("stall", stall_s)
            feeder_wall[0] += time.perf_counter() - t_f0
            tracer.close(f_sp)
            yield blk

    def _mesh_telemetry() -> dict | None:
        # per-device mesh flight recorder (ISSUE 13): the health map rides
        # the metrics snapshot and each member gets a mesh.device state row
        # — dispatch wall, rows, HBM peak, fault state, and the capacity
        # rung its slice currently runs at, keyed by device index
        if mesh_solver is None:
            return None
        hm = mesh_solver.health_map()
        rung_rows = None
        if sup is not None:
            rat = sup.governor.active_state()
            if rat:
                rung_rows = min(rat.values()) // max(mesh_solver.nd, 1)
        if rung_rows is not None:
            hm["rung_rows_per_device"] = int(rung_rows)
        lost = sum(1 for r in hm["devices"].values() if r["state"] != "ok")
        g = metrics.gauge
        g("mesh_nd").set(float(hm["nd"]))
        g("mesh_devices_lost").set(float(lost))
        for i, row in sorted(hm["devices"].items()):
            ev_log.log("mesh.device", device=int(i), state=row["state"],
                       platform=row["platform"],
                       dispatches=int(row["dispatches"]),
                       dispatch_wall_s=round(row["dispatch_wall_s"], 4),
                       rows=int(row["rows"]),
                       hbm_peak_bytes=row["hbm_peak_bytes"],
                       # per-member starvation gauge (ISSUE 14)
                       idle_frac=row.get("idle_frac"),
                       # per-member stage/solve overlap gauge (ISSUE 19):
                       # fraction of staging wall that ran under an
                       # in-flight solve — the pipeline acceptance gauge
                       overlap_frac=row.get("overlap_frac"),
                       **({"rung_rows": int(rung_rows)}
                          if rung_rows is not None else {}))
        return hm

    def _saturation():
        """Live (gauges, stage summary, verdict) triple — the saturation
        profiler's one computation, shared by the periodic snapshot and the
        end-of-run stamp so they can never disagree on the rules."""
        from ..utils.obs import bottleneck_verdict, saturation_gauges

        now = time.time()
        el = max(now - t_start, 1e-9)
        busy = dev["busy_s"]
        if dev["t0"] is not None:
            busy += now - dev["t0"]   # open occupancy interval
        blocked = stats.device_s
        if sync_engine:
            # the solve happens inside dispatch: the host is blocked there,
            # and that same wall is the engine's busy time
            blocked += dev["dispatch_s"]
            busy += stats.device_s
        gs = saturation_gauges(el, blocked, busy)
        summ = tel.stage.summary()
        return gs, summ, bottleneck_verdict(gs, summ["stages"])

    def _metrics_snap(final: bool = False):
        # registry update + periodic snapshot event: derived rates from the
        # live stats plus the two samplers (host RSS; device peak memory on
        # device-ladder paths only — memory_stats would needlessly init a
        # backend under the native engine)
        el = max(time.time() - t_start, 1e-9)
        g = metrics.gauge
        g("windows_per_sec").set(stats.n_windows / el)
        g("bases_per_sec").set(stats.bases_out / el)
        g("pad_waste").set(stats.pad_waste)
        g("rescue_density").set(stats.rescue_density)
        g("rss_mb").set(host_rss_mb())
        g("pool_rows").set(float(sum(r_nrows)) if split_ladder else 0.0)
        g("inflight").set(float(len(inflight)))
        g("n_reads").set(float(stats.n_reads))
        g("n_windows").set(float(stats.n_windows))
        g("n_solved").set(float(stats.n_solved))
        # saturation profiler (ISSUE 14): starvation/overlap gauges, the
        # blocked-on-feeder wall, and one stage_<name>_s gauge per feeder
        # sub-stage ride every snapshot AND the durable rollup/prom — plus
        # a stage.profile event carrying the full table + live verdict
        gs, summ, bver = _saturation()
        for k, v in gs.items():
            g(k).set(v)
        g("feeder_s").set(feeder_wall[0])
        g("dispatch_s").set(dev["dispatch_s"])
        # the pool width rides the rollup so a committed *.metrics.json is
        # self-describing for daccord-prof's reconciliation (thread-summed
        # stage walls only reconcile serially when threads == 1)
        g("stage_threads").set(float(summ["threads"]))
        for name, row in summ["stages"].items():
            g(f"stage_{name}_s").set(row["wall_s"])
        ev_log.log("stage.profile", stages=summ["stages"],
                   threads=summ["threads"],
                   feeder_s=round(feeder_wall[0], 4),
                   dispatch_s=round(dev["dispatch_s"], 4),
                   verdict=bver["verdict"],
                   stage=bver["stage"] or "",
                   device_idle_frac=bver["device_idle_frac"],
                   host_blocked_frac=bver["host_blocked_frac"],
                   overlap_frac=bver["overlap_frac"], final=final)
        if ladder is not None and not native_dispatch:
            from ..utils.obs import device_peak_bytes

            dpb = device_peak_bytes()
            if dpb is not None:
                g("device_peak_bytes").set(float(dpb))
        hm = _mesh_telemetry()
        if not final:
            metrics.snapshot(ev_log, **({"mesh": hm} if hm else {}))
        return hm

    bp_latched = None
    last_snap = time.time()
    for blk in _timed_blocks():
        if (cfg.metrics_snapshot_s
                and time.time() - last_snap >= cfg.metrics_snapshot_s):
            last_snap = time.time()
            _metrics_snap()
        pa = blk[1] if blk[0] == "quarantine" else blk[0]
        pile_sp = tracer.open("pile", aread=int(-1 if pa is None else pa))
        # host watermark (capacity governor, one check per pile block): under
        # memory pressure the feeder pauses here while the buffered rows —
        # partial buckets and split-ladder rescue pools (soft), plus the
        # in-flight window (hard) — force-flush through the device, and
        # finished reads emit. Frees the pending/ready/pool memory without
        # changing any window's bytes (flush cadence is not part of the
        # output contract). Real pressure LATCHES per level: allocators
        # rarely return freed heap to the OS, so RSS can sit above the
        # watermark long after a drain — re-arm only once it drops below
        # rather than collapsing batching on every subsequent pile block.
        level, rss_mb, injected = check_host_pressure(plan, gov_cfg)
        if not injected:
            if level is None:
                bp_latched = None
            elif level == "soft" and bp_latched == "hard":
                # RSS fell back below the hard watermark: renewed growth past
                # it is new pressure, not retained heap — keep suppressing
                # soft, but re-arm the hard level so a second crossing flushes
                bp_latched = "soft"
                level = None
            elif bp_latched == level:
                level = None
        if level is not None:
            stats.n_backpressure += 1
            ev_log.log("governor.backpressure", level=level,
                       rss_mb=round(rss_mb, 1), injected=injected,
                       pool=int(sum(r_nrows)) if split_ladder else 0,
                       inflight=len(inflight))
            run_batches(final=True, drain_inflight=level == "hard",
                        pressure=True)
            yield from emit_ready()
            if not injected:
                bp_latched = level
        if blk[0] == "quarantine":
            _, q_aread, q_off, q_kind, q_detail = blk
            stats.n_quarantined += 1
            ev_log.log("ingest.quarantine", kind=q_kind, offset=int(q_off),
                       aread=(-1 if q_aread is None else int(q_aread)))
            _q_record(path=las.path, aread=q_aread, offset=int(q_off),
                      kind=q_kind, detail=q_detail)
            if (q_aread is not None and 0 <= q_aread < len(db.reads)
                    and q_aread not in bad_reads):
                # bound by len(db.reads) (= ureads), matching the scan's
                # read-id validation — on a trimmed DB len(db) is nreads,
                # which would silently drop a quarantined tail read
                # containment contract: the corrupt pile's read is emitted
                # UNCORRECTED so downstream coverage accounting stays whole
                a = db.read_bases(int(q_aread))
                stats.n_reads += 1
                stats.bases_in += len(a)
                order.append(int(q_aread))
                ready[int(q_aread)] = [a]
            yield from emit_ready()
            tracer.close(pile_sp, quarantined=True)
            continue
        aread, a_bases, seqs, lens, nsegs = blk
        stats.n_reads += 1
        stats.bases_in += len(a_bases)
        nwin = len(nsegs)
        stats.n_windows += nwin
        order.append(aread)
        if nwin == 0:
            ready[aread] = []
        else:
            pr = _PendingRead(aread, a_bases, nwin)
            pending[aread] = pr
            rid_arr = np.full(nwin, aread, dtype=np.int64)
            widx_arr = np.arange(nwin, dtype=np.int64)
            if cfg.skip_shallow:
                # exact: the kernel marks nsegs < min_depth unsolved
                # (window_kernel.py:389, every tier shares min_depth), so
                # these windows skip the device entirely. Subsumes the
                # all-NOCOV-tile case: no QV coverage means no segments
                shallow = nsegs < min_depth
                ns = int(shallow.sum())
                if ns:
                    for wj in np.nonzero(shallow)[0]:
                        pr.results[int(wj)] = (int(wj) * adv, w, None)
                        if ledger is not None:
                            # skipped-shallow windows never dispatch but ARE
                            # counted windows: the ledger's row count must
                            # equal stats.n_windows
                            ledger.record(aread, int(wj), w, int(nsegs[wj]),
                                          -1, -1, False, "skip",
                                          rescued=False, wall_s=0.0,
                                          job=cfg.job_tag, mesh=ledger_mesh)
                    pr.n_done += ns
                    stats.n_skipped_shallow += ns
                    keep = ~shallow
                    seqs, lens, nsegs = seqs[keep], lens[keep], nsegs[keep]
                    rid_arr, widx_arr = rid_arr[keep], widx_arr[keep]
                    nwin -= ns
                    if nwin == 0:
                        finalize_read(aread, pr)
        if nwin and aread in pending:
            if paged_on:
                # family router: smallest (depth, pages) family fitting each
                # window — the corpus-derived replacement for the depth/
                # seg-len bucket grid
                from ..kernels import paging

                pgs = paging.window_pages(lens, cfg.page_len)
                assign = np.asarray(paging.assign_family(families, nsegs,
                                                         pgs))
                for bi in range(nb):
                    sel = np.nonzero(assign == bi)[0]
                    if len(sel) == 0:
                        continue
                    Df = families[bi].depth
                    blk_seqs[bi].append(seqs[sel, :Df])
                    blk_lens[bi].append(lens[sel, :Df])
                    blk_nsegs[bi].append(nsegs[sel])
                    blk_rid[bi].append(rid_arr[sel])
                    blk_widx[bi].append(widx_arr[sel])
                    blk_pages[bi].append(pgs[sel])
                    npages[bi] += int(pgs[sel].sum())
                    nrows[bi] += len(sel)
                    if first_seen[bi] is None:
                        first_seen[bi] = stats.n_reads
            elif nb == 1:
                # single bucket: append the pile block as-is, zero copies
                blk_seqs[0].append(seqs); blk_lens[0].append(lens)
                blk_nsegs[0].append(nsegs); blk_rid[0].append(rid_arr)
                blk_widx[0].append(widx_arr)
                nrows[0] += nwin
                if first_seen[0] is None:
                    first_seen[0] = stats.n_reads
            else:
                d_assign = np.searchsorted(d_arr, nsegs, side="left")
                if nl > 1:
                    maxlen = lens.max(axis=1)
                    assign = d_assign * nl + np.searchsorted(l_arr, maxlen, side="left")
                else:
                    assign = d_assign
                for bi in range(nb):
                    sel = np.nonzero(assign == bi)[0]
                    if len(sel) == 0:
                        continue
                    Db, Lb = buckets[bi]
                    blk_seqs[bi].append(seqs[sel, :Db, :Lb])
                    blk_lens[bi].append(lens[sel, :Db])
                    blk_nsegs[bi].append(nsegs[sel])
                    blk_rid[bi].append(rid_arr[sel])
                    blk_widx[bi].append(widx_arr[sel])
                    nrows[bi] += len(sel)
                    if first_seen[bi] is None:
                        first_seen[bi] = stats.n_reads
        run_batches(final=False)
        yield from emit_ready()
        tracer.close(pile_sp)

    run_batches(final=True)
    if stager is not None:
        stager.stop()
    while emit_idx < len(order):
        r = order[emit_idx]
        frags = ready.pop(r, [])
        stats.n_fragments += len(frags)
        stats.bases_out += sum(len(f) for f in frags)
        stats.wall_s = time.time() - t_start
        yield r, frags, stats
        emit_idx += 1
    stats.wall_s = time.time() - t_start
    if sup is not None:
        # governor ladder solves block the host at dispatch time, outside
        # the drain loop's fetch timer — device time, not feeder time
        stats.device_s += sup.gov_device_s
    stats.host_s = stats.wall_s - stats.device_s
    if sup is not None:
        stats.degraded = sup.failed_over
        stats.fallback_reason = sup.fail_reason
        gov = sup.governor
        stats.n_capacity_events = gov.counters["classify"]
        stats.governor_ratchet = gov.active_state()
        stats.batch_effective = (min(stats.governor_ratchet.values())
                                 if stats.governor_ratchet
                                 else cfg.batch_size)
        ev_log.log("sup_done", state=sup.state, degraded=sup.failed_over,
                   audit_s=round(sup.audit_s, 4),
                   **sup.counters,
                   **{f"gov_{k}": v for k, v in gov.counters.items()})
    # saturation profiler final stamp (ISSUE 14): gauges + stage table +
    # verdict computed ONCE from the finalized walls, then surfaced through
    # every channel — stats fields (bench/serve read them), shard_done,
    # the metrics rollup (launch.py renders it into the .prom exposition),
    # and the stage.profile event the final snapshot emits
    sat_g, sat_summ, sat_verdict = _saturation()
    stats.feeder_s = round(feeder_wall[0], 4)
    stats.dispatch_s = round(dev["dispatch_s"], 4)
    # Staged mesh dispatch (ISSUE 19): the dispatch wall decomposes into
    # pack/stage/launch sub-walls, and — satellite 2 — means HOST work only
    # on every backend: the solver's own perf_counter brackets around the
    # pad/slice/transfer/jit-call stages can never swallow a synchronous
    # solve the way a wall around a blocking dispatch call did
    # (MULTICHIP_r06's 40.2 s mesh-8 "dispatch" was partly compute).
    dispatch_walls = None
    if mesh_solver is not None and hasattr(mesh_solver, "dispatch_walls"):
        dispatch_walls = mesh_solver.dispatch_walls()
        stats.dispatch_s = round(dispatch_walls["dispatch_s"], 4)
        stats.dispatch_walls = dispatch_walls
    stats.stage_profile = sat_summ
    stats.verdict = sat_verdict["verdict"]
    stats.bottleneck = sat_verdict
    # end-of-run metrics rollup: final gauge refresh, one last snapshot
    # event, and the registry dict on stats — run_shard commits it durably
    # beside the shard manifest
    hm_final = _metrics_snap(final=True)
    metrics.snapshot(ev_log, final=True,
                     **({"mesh": hm_final} if hm_final else {}))
    stats.metrics = metrics.rollup()
    # the verdict string rides the rollup so render_prom (the durable
    # *.metrics.prom and the serve scrape) exposes it as a labeled gauge
    stats.metrics["verdict"] = stats.verdict
    done = dict(
        reads=stats.n_reads, windows=stats.n_windows,
        solved=stats.n_solved, skipped_shallow=stats.n_skipped_shallow,
        topm_overflow=stats.n_topm_overflow,
        hp_rescued=stats.n_hp_rescued,
        qv_ranked=stats.qv_ranked, bases_out=stats.bases_out,
        quarantined=stats.n_quarantined,
        ingest_issues=stats.n_ingest_issues,
        pad_waste=round(stats.pad_waste, 4), paged=stats.paged,
        wall_s=round(stats.wall_s, 3),
        # wall decomposition anchors (ISSUE 6): daccord-trace reconciles
        # its device/host stage sums against these
        device_s=round(stats.device_s, 4), host_s=round(stats.host_s, 4),
        # saturation profiler (ISSUE 14): the per-stage feeder table, the
        # blocked-on-feeder/dispatch anchors, the starvation gauges, and
        # the committed bottleneck verdict — daccord-prof's primary source.
        # `mesh` rides along so the sentinel's host_feeder-on-mesh>=4
        # advisory reads off the one record
        stages={k: v["wall_s"] for k, v in sat_summ["stages"].items()},
        stage_threads=sat_summ["threads"],
        feeder_s=stats.feeder_s, dispatch_s=stats.dispatch_s,
        verdict=stats.verdict, bottleneck=sat_verdict,
        mesh=int(ledger_mesh),
        # staged-dispatch sub-walls (ISSUE 19): host-only pack/stage/launch
        # decomposition of the dispatch wall, plus the stale-staged-buffer
        # re-stage count (shrink/failover landed while a batch was staged)
        **({"pack_s": round(dispatch_walls["pack_s"], 4),
            "stage_s": round(dispatch_walls["stage_s"], 4),
            "launch_s": round(dispatch_walls["launch_s"], 4),
            "restaged": int(dispatch_walls["restaged"])}
           if dispatch_walls is not None else {}),
        tiers=stats.tier_histogram, native=stats.native_host,
        # two-stream ladder decision counters (ISSUE 4): fused-vs-split
        # rescue tail cost is measurable from these with no chip
        ladder=cfg.ladder_mode,
        rescue_slots=stats.rescue_slots_executed,
        rescue_windows=stats.n_rescue_windows,
        rescue_density=round(stats.rescue_density, 4),
        # capacity governor (ISSUE 5): degraded speed, never bytes
        capacity_events=stats.n_capacity_events,
        backpressure=stats.n_backpressure,
        monster_piles=stats.n_monster_piles,
        batch_effective=stats.batch_effective,
        # north-star counters (BASELINE.json metric; SURVEY.md §5 metrics)
        bases_per_sec=round(stats.bases_per_sec(), 1),
        degraded=stats.degraded,
        windows_per_sec=round(stats.n_windows / stats.wall_s, 1)
        if stats.wall_s else 0.0)
    log.log("shard_done", **done)
    if ev_log is not log:
        # the events sidecar is what daccord-trace merges — the terminal
        # record (and its device_s/host_s anchors) must land there too
        ev_log.log("shard_done", **done)
    # clean completion: the run span closes HERE (not in the unwind) so a
    # trace can tell a finished shard from an aborted one
    tracer.close(tel.run_span, reads=stats.n_reads, status="done")
    if qfh is not None:
        qfh.close()


def correct_to_fasta(db_path: str, las_path: str, out_path, cfg: PipelineConfig | None = None,
                     start: int | None = None, end: int | None = None,
                     profile: ErrorProfile | None = None,
                     solver=None) -> PipelineStats:
    """Run the pipeline and write corrected fragments as FASTA (stdout with '-').

    ``profile`` skips the estimation pass (reference: cached error profile);
    ``solver`` overrides the window solver (e.g. the mesh-sharded ladder)."""
    cfg = cfg or PipelineConfig()
    from .faults import maybe_apply_data_faults

    # data-corruption fault injection lands BEFORE the artifacts are opened
    # (DACCORD_FAULT=las_bitflip:N|las_truncate:N|db_garbage:N)
    fired = maybe_apply_data_faults(las_path=las_path, db_path=db_path)
    if fired and cfg.events_path:
        from ..utils.obs import JsonlLogger

        with JsonlLogger(cfg.events_path) as _fl:
            for f in fired:
                _fl.log("ingest.fault", kind=f["kind"], path=f["path"],
                        record=f["record"], offset=f.get("offset", -1))
    if (cfg.ingest_policy == "quarantine" and cfg.quarantine_path is None
            and isinstance(out_path, str) and out_path != "-"
            and not out_path.startswith("mem:")):
        import dataclasses

        cfg = dataclasses.replace(cfg,
                                  quarantine_path=out_path + ".quarantine.jsonl")
    if (cfg.ingest_policy == "quarantine" and cfg.quarantine_path
            and os.path.exists(cfg.quarantine_path)):
        # a whole-range quarantine run always starts a fresh sidecar; stale
        # rows would double-count against n_quarantined (mid-shard RESUMES
        # go through launch.py, which appends deliberately). Other policies
        # never write the sidecar, so a prior run's record is left alone
        os.remove(cfg.quarantine_path)
    if cfg.ledger_path and os.path.exists(cfg.ledger_path):
        # same rule for the outcome ledger: a whole-range run starts fresh
        # (row count must equal the run's window count; only checkpointed
        # resumes, via launch.py, append deliberately)
        os.remove(cfg.ledger_path)
    # only the strict policy aborts on DB validation failures: quarantine
    # contains them via bad_reads, and 'off' trusts the input (no raise —
    # the pre-ISSUE-2 behavior an operator opts back into)
    db = read_db(db_path, strict=cfg.ingest_policy == "strict")
    las = LasFile(las_path)
    t0 = time.time()
    stats: PipelineStats | None = None
    recs = []
    for rid, frags, st in correct_shard(db, las, cfg, start, end, profile=profile,
                                        solver=solver):
        stats = st
        for fi, f in enumerate(frags):
            recs.append(FastaRecord(f"read{rid}/{fi}", ints_to_seq(f)))
    if out_path == "-":
        write_fasta(sys.stdout, recs)
    else:
        write_fasta(out_path, recs)
    if stats is None:
        stats = PipelineStats()
    stats.wall_s = time.time() - t0
    return stats
