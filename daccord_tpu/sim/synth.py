"""Synthetic long-read dataset generator: genome -> noisy reads -> true LAS.

The reference pipeline consumes DALIGNER output on real sequencing data; it has
no simulator. This module is the framework's test/bench fixture factory
(SURVEY.md §4 item 2): it fabricates a genome, samples strand-aware noisy reads
with PacBio-like error profiles, and emits

  - a Dazzler DB of the reads,
  - a .las of all true pairwise overlaps (both (A,B) and (B,A) records, sorted
    by aread, with exact per-tile trace points derived from the generative
    alignment — no aligner needed),
  - per-read truth (genome interval, strand, clean sequence) for Q-score
    evaluation.

Coordinate conventions follow DALIGNER: the A read is used as stored; when the
B read's orientation differs, the overlap carries OVL_COMP and bbpos/bepos are
coordinates in the *complemented* B read.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field, asdict

import numpy as np

from ..formats.dazzdb import write_db, DazzDB
from ..formats.las import Overlap, write_las, OVL_COMP
from ..utils.bases import revcomp_ints


@dataclass
class SimConfig:
    genome_len: int = 20_000
    coverage: float = 25.0
    read_len_mean: float = 2_000.0
    read_len_sigma: float = 0.3       # lognormal sigma on length
    p_ins: float = 0.08
    p_del: float = 0.04
    p_sub: float = 0.015
    min_overlap: int = 500
    tspace: int = 100
    repeat_fraction: float = 0.0      # fraction of genome covered by a planted repeat
    repeat_divergence: float = 0.0    # substitution rate between the two repeat
                                      # copies (0 = exact copies). Diverged
                                      # copies are what make repeat-induced
                                      # piles damaging: cross-copy B segments
                                      # pull window consensus toward the OTHER
                                      # copy, the failure mode the paper's
                                      # local-consistency filtering targets
    seed: int = 0
    # --- model-mismatch stress knobs (all default OFF; BASELINE.md round-3
    # mismatch table). The base model above is the iid ins/del/sub family the
    # error-profile estimator and OffsetLikely assume; these knobs generate
    # error processes the estimator does NOT model, as the sealed-environment
    # substitute for real sequencer data. All extra errors flow through the
    # same err/dels bookkeeping, so trace-point diffs stay truthful.
    hp_indel_slope: float = 0.0   # indel prob scaled by 1+slope*(runlen-1) in
                                  # homopolymer runs; insertions duplicate the
                                  # run base instead of being uniform random
    hp_run_cap: int = 8           # runlen-1 capped here (prob clip at 0.45)
    burst_rate: float = 0.0       # expected error bursts per base (e.g. 2e-4)
    burst_len_mean: float = 30.0  # geometric mean burst length (bases)
    burst_mult: float = 6.0       # ins/del/sub multiplier inside a burst
    read_rate_sigma: float = 0.0  # lognormal sigma of a per-read error-rate
                                  # multiplier (mean 1): rate dispersion
    p_chimera: float = 0.0        # per-read prob of a foreign insert replacing
                                  # an interior span (bridged chimera junction)
    chimera_frac: float = 0.2     # replaced span, as a fraction of read length
    dropout_frac: float = 0.0     # genome fraction with thinned coverage
    dropout_factor: float = 4.0   # coverage divisor inside the dropout region

    @classmethod
    def pacbio_clr(cls, **kw) -> "SimConfig":
        """PacBio CLR-like: ~13.5% error, insertion-heavy (the defaults)."""
        return cls(**kw)

    @classmethod
    def ont_r10(cls, **kw) -> "SimConfig":
        """ONT R10-like: much longer reads at a few percent error,
        deletion-leaning (BASELINE.md ladder config 5's regime). Read length
        stresses windowing/stitching; window count per read grows ~25x over
        the PacBio preset while the per-window kernel stays identical."""
        kw.setdefault("read_len_mean", 20_000.0)
        kw.setdefault("read_len_sigma", 0.5)
        kw.setdefault("p_ins", 0.008)
        kw.setdefault("p_del", 0.018)
        kw.setdefault("p_sub", 0.01)
        kw.setdefault("coverage", 30.0)
        kw.setdefault("min_overlap", 2_000)
        return cls(**kw)

    @classmethod
    def pacbio_mismatch(cls, **kw) -> "SimConfig":
        """PacBio CLR shape with every mismatch process switched on — the
        'everything the estimator does not model at once' stress preset."""
        kw.setdefault("hp_indel_slope", 0.5)
        kw.setdefault("burst_rate", 2e-4)
        kw.setdefault("read_rate_sigma", 0.4)
        kw.setdefault("p_chimera", 0.03)
        kw.setdefault("dropout_frac", 0.15)
        return cls(**kw)

    @classmethod
    def ont_r10_mismatch(cls, **kw) -> "SimConfig":
        """ONT R10 shape + homopolymer-dominated indels and rate dispersion —
        the characteristic ONT failure modes."""
        kw.setdefault("hp_indel_slope", 1.0)
        kw.setdefault("read_rate_sigma", 0.5)
        kw.setdefault("burst_rate", 1e-4)
        return cls.ont_r10(**kw)


@dataclass
class SimRead:
    """One sampled read plus its generative alignment to the genome.

    ``g_of_r`` maps stored-read position -> genome position (non-strictly
    monotone; inserted bases repeat the previous base's genome position).
    Direction is increasing for strand 0, decreasing for strand 1.
    ``err`` marks stored-read positions that are insertions or substitutions.
    ``dels`` lists genome positions deleted from this read (sorted ascending).
    """

    start: int
    end: int
    strand: int
    seq: np.ndarray
    g_of_r: np.ndarray
    err: np.ndarray
    dels: np.ndarray
    # lazy per-orientation cache for the overlap-construction hot path (r5):
    # {comp: (gB, err_cum, neg_gB)} — recomputing cumsums/negations per
    # overlap PAIR was the sim's top cost at scale. Values only, never
    # semantics; built on first use by _omaps().
    _oc: dict | None = None

    def omaps(self, comp: bool) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        if self._oc is None:
            self._oc = {}
        hit = self._oc.get(comp)
        if hit is None:
            gB, errB = _oriented_maps(self, comp)
            gB = np.ascontiguousarray(gB)
            hit = (gB, np.concatenate(([0], np.cumsum(errB, dtype=np.int64))),
                   -gB)
            self._oc[comp] = hit
        return hit


@dataclass
class SimResult:
    genome: np.ndarray
    reads: list[SimRead]
    overlaps: list[Overlap]
    config: SimConfig


def _sample_noisy(genome: np.ndarray, start: int, end: int, cfg: SimConfig,
                  rng: np.random.Generator, rmult: float = 1.0
                  ) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Apply sub/ins/del noise to genome[start:end] (forward orientation).

    Returns (read_fwd, g_of_r_fwd, err_fwd, dels) where g_of_r is monotone
    non-decreasing over genome positions start..end-1. ``rmult`` is the
    per-read rate multiplier (rate dispersion); the mismatch knobs
    (homopolymer slope, bursts) modulate the per-position probabilities.
    The knobs-off scalar path is kept verbatim so existing seeds reproduce
    their datasets bit-for-bit (cached fixtures, parity tests).
    """
    seg = genome[start:end]
    n = len(seg)
    mismatch = (cfg.hp_indel_slope > 0 or cfg.burst_rate > 0 or rmult != 1.0)
    in_run = None
    if not mismatch:
        u = rng.random(n)
        is_del = u < cfg.p_del
        is_sub = (~is_del) & (u < cfg.p_del + cfg.p_sub)
        n_ins = rng.geometric(1.0 - cfg.p_ins, size=n) - 1  # insertions after each base
    else:
        m = np.full(n, float(rmult))
        if cfg.burst_rate > 0 and n:
            # error bursts: Poisson-placed starts, geometric lengths, all
            # three channels multiplied inside — the polymerase-stall /
            # signal-dropout process the iid estimator does not model
            nb = int(rng.poisson(cfg.burst_rate * n))
            if nb:
                bs = rng.integers(0, n, size=nb)
                bl = rng.geometric(1.0 / max(cfg.burst_len_mean, 1.0), size=nb)
                for s, ln_ in zip(bs, bl):
                    m[s:s + ln_] *= cfg.burst_mult
        hp = np.ones(n)
        if cfg.hp_indel_slope > 0 and n:
            change = np.nonzero(np.diff(seg))[0] + 1
            bounds = np.concatenate([[0], change, [n]])
            rl = np.diff(bounds)
            runlen = np.repeat(rl, rl)
            hp = 1.0 + cfg.hp_indel_slope * np.minimum(runlen - 1,
                                                       cfg.hp_run_cap)
            in_run = runlen > 1
        pd = np.clip(cfg.p_del * m * hp, 0.0, 0.45)
        ps = np.clip(cfg.p_sub * m, 0.0, 0.45)
        pi = np.clip(cfg.p_ins * m * hp, 0.0, 0.45)
        u = rng.random(n)
        is_del = u < pd
        is_sub = (~is_del) & (u < pd + ps)
        n_ins = rng.geometric(1.0 - pi) - 1 if n else np.zeros(0, np.int64)

    # Assembly is vectorized (r5: the per-base python loop was ~40% of sim
    # wall at scale), but the rng draws MUST keep the original per-position
    # call sequence — sub draw, then that position's insertion draw — so
    # every existing seed reproduces its dataset bit-for-bit (cached
    # fixtures, parity tests). The event loop below touches only positions
    # that actually draw (~10% at typical rates); in-run insertions draw
    # nothing (np.full in the original).
    keep = ~is_del
    sub_vals = np.zeros(0, dtype=np.int8)
    ins_vals_parts: list[np.ndarray] = []
    if n:
        draw_sub = is_sub
        draw_ins = n_ins > 0
        if in_run is not None:
            rand_ins = draw_ins & ~in_run
        else:
            rand_ins = draw_ins
        sub_list = []
        ev = np.nonzero(draw_sub | draw_ins)[0]
        for i in ev:
            if draw_sub[i]:
                sub_list.append(rng.integers(1, 4))
            k = int(n_ins[i])
            if k:
                if in_run is not None and in_run[i]:
                    ins_vals_parts.append(np.full(k, seg[i], dtype=np.int8))
                else:
                    ins_vals_parts.append(rng.integers(0, 4, size=k,
                                                       dtype=np.int8))
        sub_vals = np.asarray(sub_list, dtype=np.int8)
        del rand_ins
    counts = keep.astype(np.int64) + n_ins
    total = int(counts.sum()) if n else 0
    read = np.empty(total, dtype=np.int8)
    err = np.empty(total, dtype=np.int8)
    g_of_r = np.repeat(start + np.arange(n, dtype=np.int64), counts)
    if n:
        offs = np.zeros(n, dtype=np.int64)
        np.cumsum(counts[:-1], out=offs[1:])
        base_pos = offs[keep]
        bases = seg.copy()
        if len(sub_vals):
            si = np.nonzero(is_sub)[0]
            bases[si] = (bases[si] + sub_vals) % 4
        read[base_pos] = bases[keep]
        err[base_pos] = is_sub[keep].astype(np.int8)
        # insertion slots: for position i they follow its surviving base
        ins_idx = np.nonzero(n_ins > 0)[0]
        if len(ins_idx):
            k_arr = n_ins[ins_idx]
            starts_i = offs[ins_idx] + keep[ins_idx]
            K = int(k_arr.sum())
            flat = (np.repeat(starts_i, k_arr)
                    + np.arange(K, dtype=np.int64)
                    - np.repeat(np.concatenate(([0], np.cumsum(k_arr[:-1]))),
                                k_arr))
            read[flat] = (np.concatenate(ins_vals_parts)
                          if ins_vals_parts else np.zeros(0, np.int8))
            err[flat] = 1
    dels = (start + np.nonzero(is_del)[0]).astype(np.int64)
    return read, g_of_r, err, dels


def _chimerize(fwd: np.ndarray, g_of_r: np.ndarray, err: np.ndarray,
               dels: np.ndarray, cfg: SimConfig, rng: np.random.Generator
               ) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Replace an interior span of a (forward-orientation) read with foreign
    sequence — a bridged chimera junction. The replaced genome positions
    become deletions and the foreign bases insertion-like errors pinned at
    the junction, so per-tile trace diffs remain truthful: an overlap tile
    crossing the junction really carries that much divergence."""
    n = len(fwd)
    lf = max(50, int(n * cfg.chimera_frac))
    lf = min(lf, n - n // 4 - 2)
    if lf <= 0:
        return fwd, g_of_r, err, dels
    j = int(rng.integers(n // 4, n - lf - 1))
    g_prev = int(g_of_r[j - 1]) if j else int(g_of_r[0])
    g_next = int(g_of_r[j + lf])
    span = np.arange(g_prev + 1, g_next, dtype=np.int64)
    if len(span):
        span = span[~np.isin(span, dels)]
        dels = np.sort(np.concatenate([dels, span]))
    fwd = fwd.copy()
    fwd[j:j + lf] = rng.integers(0, 4, size=lf, dtype=np.int8)
    g_of_r = g_of_r.copy()
    g_of_r[j:j + lf] = g_prev
    err = err.copy()
    err[j:j + lf] = 1
    return fwd, g_of_r, err, dels


def _make_genome(cfg: SimConfig, rng: np.random.Generator) -> tuple[np.ndarray, tuple | None]:
    """Returns (genome, repeat) where repeat = (src, dst, rep_len, div_off)
    or None; ``div_off`` holds the copy-local offsets where the two copies
    differ (empty for an exact repeat)."""
    g = rng.integers(0, 4, size=cfg.genome_len, dtype=np.int8)
    rep = None
    if cfg.repeat_fraction > 0:
        # plant a two-copy repeat: copy one segment to another location,
        # then diverge the second copy by repeat_divergence substitutions
        rep_len = int(cfg.genome_len * cfg.repeat_fraction / 2)
        if rep_len > 100:
            src = int(rng.integers(0, cfg.genome_len // 2 - rep_len))
            dst = int(rng.integers(cfg.genome_len // 2, cfg.genome_len - rep_len))
            g[dst : dst + rep_len] = g[src : src + rep_len]
            ndiv = int(round(rep_len * cfg.repeat_divergence))
            div_off = np.sort(rng.choice(rep_len, size=ndiv, replace=False)) \
                if ndiv else np.zeros(0, np.int64)
            if ndiv:
                g[dst + div_off] = (g[dst + div_off]
                                    + rng.integers(1, 4, ndiv, dtype=np.int8)) % 4
            rep = (src, dst, rep_len, div_off.astype(np.int64))
    return g, rep


def _oriented_maps(r: SimRead, comp: bool) -> tuple[np.ndarray, np.ndarray]:
    """(g_of_r, err) in the requested orientation of the stored read."""
    if not comp:
        return r.g_of_r, r.err
    return r.g_of_r[::-1], r.err[::-1]


def _positions_in(g_of_r: np.ndarray, neg_g: np.ndarray, glo: int, ghi: int,
                  ascending: bool) -> tuple[int, int]:
    """Half-open index range of read positions whose genome pos is in [glo, ghi)."""
    if ascending:
        lo = int(np.searchsorted(g_of_r, glo, side="left"))
        hi = int(np.searchsorted(g_of_r, ghi, side="left"))
    else:
        # descending: search the (cached) negation
        lo = int(np.searchsorted(neg_g, -(ghi - 1), side="left"))
        hi = int(np.searchsorted(neg_g, -(glo - 1), side="left"))
    return lo, hi


def _true_overlap(a: SimRead, b: SimRead, ai: int, bi: int, cfg: SimConfig,
                  shift: int = 0, clamp: tuple[int, int] | None = None,
                  div_sites: np.ndarray | None = None) -> Overlap | None:
    """Construct the true overlap record (A as stored; B possibly complemented).

    ``shift`` maps B's genome coordinates into A's frame (used for overlaps
    induced by a planted repeat copy: B positions g map to A positions
    g - shift). ``clamp`` restricts the overlap to an A-frame interval (the
    repeat body — flanks beyond the copy do not match). ``div_sites`` are
    A-frame genome positions where the two copies differ; each one inside a
    tile adds a pair diff (cross-copy alignments really see that mismatch).
    """
    glo = max(a.start, b.start - shift)
    ghi = min(a.end, b.end - shift)
    if clamp is not None:
        glo = max(glo, clamp[0])
        ghi = min(ghi, clamp[1])
    if ghi - glo < cfg.min_overlap:
        return None
    comp = a.strand != b.strand
    # orientation chosen so B traverses the genome in the same direction as A
    gA, a_err_cum, negA = a.omaps(False)
    gB, b_err_cum, negB = b.omaps(comp)
    a_asc = a.strand == 0
    abpos, aepos = _positions_in(gA, negA, glo, ghi, a_asc)
    bbpos, bepos = _positions_in(gB, negB, glo + shift, ghi + shift, a_asc)
    if aepos - abpos < cfg.min_overlap // 2 or bepos - bbpos < cfg.min_overlap // 2:
        return None

    # trace points: cut A range at multiples of tspace, map each boundary to B
    ovl = Overlap(aread=ai, bread=bi, abpos=abpos, aepos=aepos,
                  bbpos=bbpos, bepos=bepos, flags=OVL_COMP if comp else 0)
    bounds = ovl.tile_bounds(cfg.tspace)
    # genome coordinate of each A boundary position
    gb = np.empty(len(bounds), dtype=np.int64)
    gb[:-1] = a.g_of_r[bounds[:-1]]
    gb[-1] = ghi  # end boundary maps to overlap end
    # map genome coords to B positions (vectorized r5: this function is the
    # sim's hot spot at scale; identical arithmetic to the scalar loops)
    if a_asc:
        bpos = np.searchsorted(gB, gb + shift, side="left").astype(np.int64)
    else:
        bpos = np.searchsorted(negB, -(gb + shift), side="left").astype(np.int64)
    bpos[0] = bbpos
    bpos[-1] = bepos
    bpos = np.maximum.accumulate(np.clip(bpos, bbpos, bepos))

    # per-tile diffs (approximation: A-edits + B-edits vs genome in the tile;
    # exact pair diffs are not needed — consumers use these only for error-rate
    # estimation, mirroring the trace-point diff semantics)
    ntiles = len(bounds) - 1
    trace = np.zeros((ntiles, 2), dtype=np.int32)
    a_ed = a_err_cum[bounds[1:]] - a_err_cum[bounds[:-1]]
    b_ed = b_err_cum[bpos[1:]] - b_err_cum[bpos[:-1]]
    gmin = np.minimum(gb[:-1], gb[1:])
    gmax = np.maximum(gb[:-1], gb[1:])
    a_dl = np.searchsorted(a.dels, gmax) - np.searchsorted(a.dels, gmin)
    b_dl = (np.searchsorted(b.dels, gmax + shift)
            - np.searchsorted(b.dels, gmin + shift))
    tot = a_ed + a_dl + b_ed + b_dl
    if div_sites is not None:
        tot += np.searchsorted(div_sites, gmax) - np.searchsorted(div_sites, gmin)
    trace[:, 0] = np.minimum(tot, 255 if cfg.tspace <= 125 else 65535)
    trace[:, 1] = bpos[1:] - bpos[:-1]
    ovl.trace = trace
    ovl.diffs = int(trace[:, 0].sum())
    return ovl


def simulate(cfg: SimConfig) -> SimResult:
    rng = np.random.default_rng(cfg.seed)
    genome, rep = _make_genome(cfg, rng)

    nbases_target = cfg.genome_len * cfg.coverage
    reads: list[SimRead] = []
    total = 0
    drop = None
    if cfg.dropout_frac > 0:
        dlen = int(cfg.genome_len * cfg.dropout_frac)
        if dlen:
            d0 = int(rng.integers(0, cfg.genome_len - dlen + 1))
            drop = (d0, d0 + dlen)
    while total < nbases_target:
        ln = int(rng.lognormal(np.log(cfg.read_len_mean), cfg.read_len_sigma))
        ln = max(300, min(ln, cfg.genome_len))
        start = int(rng.integers(0, cfg.genome_len - ln + 1))
        if drop is not None:
            # thin reads proportionally to their overlap with the dropout
            # region: coverage inside tends to depth/dropout_factor
            ov = min(start + ln, drop[1]) - max(start, drop[0])
            if ov > 0 and rng.random() < (ov / ln) * (1.0 - 1.0 / cfg.dropout_factor):
                continue
        strand = int(rng.integers(0, 2))
        rmult = 1.0
        if cfg.read_rate_sigma > 0:
            # mean-1 lognormal: a fat right tail of junk reads, the per-read
            # dispersion real instruments show
            s = cfg.read_rate_sigma
            rmult = float(rng.lognormal(-0.5 * s * s, s))
        fwd, g_of_r, err, dels = _sample_noisy(genome, start, start + ln, cfg,
                                               rng, rmult)
        if len(fwd) < 100:
            continue
        if cfg.p_chimera > 0 and len(fwd) > 600 and rng.random() < cfg.p_chimera:
            fwd, g_of_r, err, dels = _chimerize(fwd, g_of_r, err, dels, cfg, rng)
        if strand == 1:
            seq = revcomp_ints(fwd)
            g_of_r = g_of_r[::-1].copy()
            err = err[::-1].copy()
        else:
            seq = fwd
        reads.append(SimRead(start=start, end=start + ln, strand=strand,
                             seq=seq, g_of_r=g_of_r, err=err, dels=dels))
        total += len(fwd)

    # all true pairwise overlaps, both directions, sorted by aread
    overlaps: list[Overlap] = []
    order = np.argsort([r.start for r in reads], kind="stable")
    starts = np.array([r.start for r in reads])[order]
    for ai in range(len(reads)):
        a = reads[ai]
        # candidate B reads: start before a.end (and end after a.start)
        hi = int(np.searchsorted(starts, a.end))
        for oj in range(hi):
            bi = int(order[oj])
            if bi == ai:
                continue
            b = reads[bi]
            if b.end <= a.start:
                continue
            ovl = _true_overlap(a, b, ai, bi, cfg)
            if ovl is not None:
                overlaps.append(ovl)

    # repeat-induced overlaps: reads over the two copies align to each other
    # within the copy body (what daligner would report on a repeat); with
    # repeat_divergence > 0 every divergent site inside the overlap adds a
    # real pair diff
    if rep is not None:
        src, dst, rep_len, div_off = rep
        shift = dst - src
        in_src = [i for i, r in enumerate(reads) if r.start < src + rep_len and r.end > src]
        in_dst = [i for i, r in enumerate(reads) if r.start < dst + rep_len and r.end > dst]
        for ai in range(len(reads)):
            a = reads[ai]
            if a.start < src + rep_len and a.end > src:
                # A over copy 1, B over copy 2: B coords map down by shift
                for bi in in_dst:
                    if bi == ai:
                        continue
                    ovl = _true_overlap(a, reads[bi], ai, bi, cfg, shift=shift,
                                        clamp=(src, src + rep_len),
                                        div_sites=src + div_off)
                    if ovl is not None:
                        overlaps.append(ovl)
            if a.start < dst + rep_len and a.end > dst:
                # A over copy 2, B over copy 1: B coords map up by -shift
                for bi in in_src:
                    if bi == ai:
                        continue
                    ovl = _true_overlap(a, reads[bi], ai, bi, cfg, shift=-shift,
                                        clamp=(dst, dst + rep_len),
                                        div_sites=dst + div_off)
                    if ovl is not None:
                        overlaps.append(ovl)

    overlaps.sort(key=lambda o: (o.aread, o.bread))
    return SimResult(genome=genome, reads=reads, overlaps=overlaps, config=cfg)


def make_dataset(outdir: str, cfg: SimConfig, name: str = "sim") -> dict:
    """Materialize a SimResult as DB + LAS + truth files; returns paths."""
    os.makedirs(outdir, exist_ok=True)
    res = simulate(cfg)
    db_path = os.path.join(outdir, f"{name}.db")
    las_path = os.path.join(outdir, f"{name}.las")
    truth_path = os.path.join(outdir, f"{name}.truth.npz")

    write_db(db_path, [r.seq for r in res.reads])
    write_las(las_path, cfg.tspace, res.overlaps)
    np.savez_compressed(
        truth_path,
        genome=res.genome,
        starts=np.array([r.start for r in res.reads], dtype=np.int64),
        ends=np.array([r.end for r in res.reads], dtype=np.int64),
        strands=np.array([r.strand for r in res.reads], dtype=np.int8),
    )
    with open(os.path.join(outdir, f"{name}.config.json"), "wt") as fh:
        json.dump(asdict(cfg), fh, indent=2)
    return {"db": db_path, "las": las_path, "truth": truth_path, "result": res}
