from .synth import SimConfig, SimResult, simulate, make_dataset

__all__ = ["SimConfig", "SimResult", "simulate", "make_dataset"]
